"""EM (external-memory) host Sort benchmark: spill + k-way merge.

The round-3 verdict flagged the Python tournament merge as the EM
sort's bottleneck (ROADMAP item 6; reference hot loop:
api/sort.hpp:216-271, core/multiway_merge.hpp:132). This benchmark
drives the FULL host Sort path — string items, forced small runs so
the spill/merge machinery does the work — and prints phase timings.

Usage: python benchmarks/em_sort_bench.py [n_items] [run_size]
"""

import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=2")


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    run_size = int(sys.argv[2]) if len(sys.argv) > 2 else max(
        n // 40, 1024)
    os.environ["THRILL_TPU_HOST_SORT_RUN"] = str(run_size)

    import thrill_tpu  # noqa: F401
    from thrill_tpu.common.platform import force_cpu_platform
    force_cpu_platform()
    from thrill_tpu.api import Context
    from thrill_tpu.parallel.mesh import MeshExec

    import numpy as np
    rng = np.random.default_rng(5)
    ids = rng.integers(0, 1 << 48, size=n)
    items = [f"key-{v:014d}" for v in ids.tolist()]

    mex = MeshExec(num_workers=2)
    ctx = Context(mex)
    d = ctx.Distribute(items, storage="host")
    t0 = time.perf_counter()
    out = d.Sort()
    hs = out.node.materialize()
    dt = time.perf_counter() - t0
    got = [it for l in hs.lists for it in l]
    assert len(got) == n
    assert got == sorted(items), "EM sort output is WRONG"
    print(f"em_sort n={n} run_size={run_size} "
          f"runs~{-(-n // run_size)}: {dt:.2f} s "
          f"({n / dt / 1e6:.3f} Mitems/s)")
    ctx.close()


if __name__ == "__main__":
    main()
