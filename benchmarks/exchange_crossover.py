"""Measure the dense-vs-1-factor exchange crossover on the ACTUAL mesh.

The dense all_to_all pads every (src, dst) cell to the global maximum —
cheap padding, one launch. The 1-factor schedule pads each round to its
pair maximum — minimal padding, W-1 serialized launches. The crossover
is a latency/bandwidth tradeoff, so the constants must be measured, not
guessed (VERDICT r2, weak #8):

  * round_overhead_s: wall-clock of one near-empty exchange launch
    (program dispatch + collective setup), measured as the slope of
    1-factor total time over its round count at tiny payload.
  * exchange_bw: bytes/s through the padded dense exchange at large
    uniform payload.

  bytes_eq = round_overhead_s * exchange_bw   — the padded-byte volume
  whose transfer costs as much as one extra round launch. The runtime
  model (exchange._prefer_onefactor) picks 1-factor iff the padding it
  saves exceeds bytes_eq per extra launch.

Prints RESULT lines; run on the virtual 8-device CPU mesh (this image)
or any real TPU mesh unchanged.
"""

from __future__ import annotations

import os
import sys
import time

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import thrill_tpu  # noqa: F401,E402
from thrill_tpu.common.platform import force_cpu_unless_accelerator  # noqa: E402

force_cpu_unless_accelerator()

import jax  # noqa: E402

from thrill_tpu.data import exchange  # noqa: E402
from thrill_tpu.data.shards import DeviceShards  # noqa: E402
from thrill_tpu.parallel.mesh import MeshExec  # noqa: E402


def _mk_shards(mex, rows_per_worker: int, row_u64: int) -> DeviceShards:
    W = mex.num_workers
    rng = np.random.default_rng(0)
    tree = {"x": rng.integers(0, 1 << 30,
                              size=(W, rows_per_worker, row_u64)
                              ).astype(np.uint64)}
    counts = np.full(W, rows_per_worker, dtype=np.int64)
    return DeviceShards(mex, jax.tree.map(mex.put, tree), counts)


def _run_exchange(mex, shards, mode: str, iters: int, ident) -> float:
    os.environ["THRILL_TPU_EXCHANGE"] = mode
    # calibration must time the REQUESTED plan: pin the crossover so the
    # cost model under calibration cannot reroute the dense measurement
    os.environ["THRILL_TPU_XCHG_BYTES_EQ"] = str(1 << 62)
    mex.exchange_mode = mode
    W = mex.num_workers

    def dest(tree, mask, widx):
        import jax.numpy as jnp
        # uniform round-robin destinations: every cell equal
        n = tree["x"].shape[0]
        return (jnp.arange(n, dtype=jnp.int32) % W)

    def once():
        out = exchange.exchange(shards, dest, ident + (mode,))
        jax.block_until_ready(jax.tree.leaves(out.tree))
        np.asarray(jax.tree.leaves(out.tree)[0])[:1]

    once()                                  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        once()
    return (time.perf_counter() - t0) / iters


def main():
    mex = MeshExec()
    W = mex.num_workers
    if W < 2:
        print(f"RESULT bench=exchange_crossover error=single_worker W={W}")
        return

    # 1) round overhead: tiny payload, dense (1 launch) vs 1-factor
    #    (W-1 launches); slope over launch count = per-round overhead
    tiny = _mk_shards(mex, 64, 1)
    t_dense_tiny = _run_exchange(mex, tiny, "dense", 20, ("xco_tiny",))
    t_of_tiny = _run_exchange(mex, tiny, "onefactor", 20, ("xco_tiny",))
    round_overhead = max(t_of_tiny - t_dense_tiny, 1e-9) / max(W - 2, 1)

    # 2) effective exchange bandwidth: large uniform payload, dense
    rows, row_u64 = 1 << 14, 16                 # 2 MiB/worker
    big = _mk_shards(mex, rows, row_u64)
    t_dense_big = _run_exchange(mex, big, "dense", 5, ("xco_big",))
    # fabric bytes only (exclude each worker's 1/W self-share) — the
    # same units the runtime cost model compares
    bytes_moved = (W - 1) * rows * row_u64 * 8
    bw = bytes_moved / t_dense_big

    bytes_eq = round_overhead * bw
    print(f"RESULT bench=exchange_crossover platform={jax.default_backend()} "
          f"W={W} round_overhead_us={round_overhead * 1e6:.1f} "
          f"exchange_bw_mb_s={bw / 1e6:.0f} "
          f"bytes_eq_per_round={int(bytes_eq)}", flush=True)


if __name__ == "__main__":
    main()
