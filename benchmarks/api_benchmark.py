"""API-level benchmarks: Sort / ReduceByKey / Generate throughput.

Equivalent of the reference's benchmarks/api/{sort,groupby,...}.cpp.
Runs on whatever devices are available (virtual CPU mesh with
--xla_force_host_platform_device_count, or the real chip).
Prints RESULT lines like the reference (benchmarks/api/sort.cpp:49-58).
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo root on sys.path for CLI runs)


import time

import numpy as np


def _ctx():
    from thrill_tpu.api import Context
    return Context()


def _sort_key(r):
    return r["key"]


def _dict_key(t):
    return t["k"]


def _dict_reduce(a, b):
    return {"k": a["k"], "v": a["v"] + b["v"]}


def bench_sort(n=1 << 16, iterations=3):
    import jax
    ctx = _ctx()
    rng = np.random.default_rng(0)
    recs = {"key": rng.integers(0, 256, size=(n, 10)).astype(np.uint8),
            "value": rng.integers(0, 256, size=(n, 90)).astype(np.uint8)}

    def once():
        out = ctx.Distribute(recs).Sort(key_fn=_sort_key)
        sh = out.node.materialize()
        jax.block_until_ready(jax.tree.leaves(sh.tree))

    once()
    for _ in range(iterations):
        t0 = time.perf_counter()
        once()
        dt = time.perf_counter() - t0
        print(f"RESULT bench=api_sort workers={ctx.num_workers} items={n} "
              f"time_ms={dt * 1e3:.1f} items_per_s={n / dt:.0f}")
    ctx.close()


def bench_reduce(n=1 << 18, keys=1 << 10, iterations=3):
    import jax
    ctx = _ctx()
    rng = np.random.default_rng(0)
    vals = (rng.integers(0, keys, n).astype(np.int64),
            np.ones(n, dtype=np.int64))

    def once():
        d = ctx.Distribute({"k": vals[0], "v": vals[1]})
        out = d.ReduceByKey(_dict_key, _dict_reduce)
        sh = out.node.materialize()
        jax.block_until_ready(jax.tree.leaves(sh.tree))

    once()
    for _ in range(iterations):
        t0 = time.perf_counter()
        once()
        dt = time.perf_counter() - t0
        print(f"RESULT bench=api_reduce workers={ctx.num_workers} items={n} "
              f"keys={keys} time_ms={dt * 1e3:.1f} items_per_s={n / dt:.0f}")
    ctx.close()


def bench_generate(n=1 << 20, iterations=3):
    import jax
    ctx = _ctx()

    def once():
        sh = ctx.Generate(n).node.materialize()
        jax.block_until_ready(jax.tree.leaves(sh.tree))

    once()
    for _ in range(iterations):
        t0 = time.perf_counter()
        once()
        dt = time.perf_counter() - t0
        print(f"RESULT bench=api_generate workers={ctx.num_workers} "
              f"items={n} time_ms={dt * 1e3:.1f}")
    ctx.close()


if __name__ == "__main__":
    bench_generate()
    bench_sort()
    bench_reduce()
