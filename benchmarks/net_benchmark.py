"""Net microbenchmarks: ping-pong latency, pairwise bandwidth, collectives.

Equivalent of the reference's benchmarks/net/net_benchmark.cpp (ping-pong
latency, 1-factor bandwidth matrix, FCC Broadcast/PrefixSum), run over
the TCP backend on localhost. Prints reference-style RESULT lines.
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo root on sys.path for CLI runs)


import socket
import threading
import time

import numpy as np


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def run_group_threads(p, job):
    from thrill_tpu.net.tcp import construct_tcp_group
    hosts = [("127.0.0.1", pt) for pt in _free_ports(p)]
    res = [None] * p

    def tgt(r):
        g = construct_tcp_group(r, hosts, timeout=20)
        try:
            res[r] = job(g)
        finally:
            g.close()

    ts = [threading.Thread(target=tgt, args=(r,), daemon=True)
          for r in range(p)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
    return res


def bench_ping_pong(iterations=200):
    def job(g):
        if g.num_hosts < 2:
            return None
        t0 = time.perf_counter()
        for _ in range(iterations):
            if g.my_rank == 0:
                g.send_to(1, b"x")
                g.recv_from(1)
            elif g.my_rank == 1:
                g.send_to(0, g.recv_from(0))
        return (time.perf_counter() - t0) / iterations

    res = run_group_threads(2, job)
    rtt = res[0]
    print(f"RESULT bench=ping_pong hosts=2 iterations={iterations} "
          f"rtt_us={rtt * 1e6:.1f}")


def bench_bandwidth(mb=64):
    blob = np.random.default_rng(0).bytes(1 << 20)

    def job(g):
        if g.my_rank == 0:
            t0 = time.perf_counter()
            for _ in range(mb):
                g.send_to(1, blob)
            g.recv_from(1)
            return mb / (time.perf_counter() - t0)
        for _ in range(mb):
            g.recv_from(0)
        g.send_to(0, b"done")
        return None

    res = run_group_threads(2, job)
    print(f"RESULT bench=bandwidth hosts=2 volume_mb={mb} "
          f"throughput_mb_s={res[0]:.1f}")


def bench_collectives(p=4, iterations=50):
    from thrill_tpu.net import FlowControlChannel

    def job(g):
        fcc = FlowControlChannel(g)
        t0 = time.perf_counter()
        for i in range(iterations):
            fcc.prefix_sum(g.my_rank + i)
        prefix = (time.perf_counter() - t0) / iterations
        t0 = time.perf_counter()
        for i in range(iterations):
            fcc.broadcast(i if g.my_rank == 0 else None)
        bcast = (time.perf_counter() - t0) / iterations
        return prefix, bcast

    res = run_group_threads(p, job)
    prefix = max(r[0] for r in res)
    bcast = max(r[1] for r in res)
    print(f"RESULT bench=fcc_prefix_sum hosts={p} time_us={prefix * 1e6:.1f}")
    print(f"RESULT bench=fcc_broadcast hosts={p} time_us={bcast * 1e6:.1f}")


def bench_fanout(p=4, mb_each=16):
    """Fan-out: rank 0 sends a large buffer to every peer, then waits
    for acks. With the async dispatcher (default) the sends progress
    concurrently; THRILL_TPU_ASYNC_NET=0 serializes on sendall —
    measuring exactly what the reference's DispatcherThread buys."""
    import os
    blob = b"x" * (mb_each << 20)

    def job(g):
        if g.my_rank == 0:
            t0 = time.perf_counter()
            for peer in range(1, g.num_hosts):
                g.send_to(peer, blob)
            t_enqueue = time.perf_counter() - t0
            for peer in range(1, g.num_hosts):
                g.recv_from(peer)
            return t_enqueue, time.perf_counter() - t0
        assert len(g.recv_from(0)) == len(blob)
        g.send_to(0, b"ack")
        return None

    for mode, env in (("async", "1"), ("blocking", "0")):
        os.environ["THRILL_TPU_ASYNC_NET"] = env
        try:
            t_enq, dt = run_group_threads(p, job)[0]
        finally:
            os.environ.pop("THRILL_TPU_ASYNC_NET", None)
        vol = mb_each * (p - 1)
        # enqueue_ms is what the WORKER thread pays before it may
        # compute again — the overlap the dispatcher buys; blocking
        # sends hold the worker for the full transfer
        print(f"RESULT bench=fanout mode={mode} hosts={p} "
              f"volume_mb={vol} enqueue_ms={t_enq * 1000:.1f} "
              f"time_ms={dt * 1000:.1f} throughput_mb_s={vol / dt:.0f}")


if __name__ == "__main__":
    bench_ping_pong()
    bench_bandwidth()
    bench_collectives()
    bench_fanout()
