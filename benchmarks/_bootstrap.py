"""Repo-root sys.path + platform forcing for direct CLI runs.

Makes `JAX_PLATFORMS=cpu python examples/x.py` work on this image (the
axon plugin otherwise ignores the env var / can hang; see
thrill_tpu.common.platform).
"""

import os
import sys

_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _root not in sys.path:
    sys.path.insert(0, _root)

from thrill_tpu.common.platform import maybe_force_cpu_from_env

maybe_force_cpu_from_env()
