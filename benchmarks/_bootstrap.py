"""Put the repo root on sys.path for direct `python examples/x.py` runs."""

import os
import sys

_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _root not in sys.path:
    sys.path.insert(0, _root)
