"""Repo-root sys.path + platform forcing for direct CLI runs.

Also makes the standard JAX_PLATFORMS env var effective: some device
plugins (axon) ignore the env var unless the config is set before
first jax use, so `JAX_PLATFORMS=cpu python examples/x.py` works.
"""

import os
import sys

_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _root not in sys.path:
    sys.path.insert(0, _root)

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # only intervene for an explicit CPU request: this image exports
    # JAX_PLATFORMS=axon globally, and re-applying that here would
    # clobber a harness (conftest) that already forced CPU
    import jax

    jax.config.update("jax_platforms", "cpu")
    # unregister accelerator plugins entirely: on this image the axon
    # plugin can hang PJRT client init even when the platform list
    # excludes it, and plugin discovery at first backends() would
    # re-register and re-force jax_platforms
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    _xb.discover_pjrt_plugins = lambda: None
