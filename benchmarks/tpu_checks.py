"""Real-accelerator validation checklist — run when the chip is healthy.

The CI suite (tests/) pins everything to a virtual CPU mesh; the paths
that only matter on real hardware (chunked sort engine above the 64K
compile cliff, pallas kernels outside interpret mode, ragged
all-to-all) are claims until they execute on the device. This script
runs them one by one and prints one RESULT line each, never letting a
single failure hide the rest.

Usage (healthy chip):   python benchmarks/tpu_checks.py
The axon plugin can hang at init — probe with a subprocess timeout
before running this (bench.py does that automatically).
"""

from __future__ import annotations

import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

RESULTS = []


def check(name):
    def deco(fn):
        RESULTS.append((name, fn))
        return fn
    return deco


@check("platform")
def _platform():
    import jax
    d = jax.devices()[0]
    return f"platform={d.platform} kind={getattr(d, 'device_kind', '?')}"


@check("chunked_sort_1m")
def _chunked_sort():
    import jax
    import jax.numpy as jnp
    from thrill_tpu.core.device_sort import _chunked_argsort

    n = 1 << 20
    rng = np.random.default_rng(0)
    ws = [jnp.asarray(rng.integers(0, 1 << 63, n, dtype=np.uint64))
          for _ in range(2)]
    f = jax.jit(lambda *w: _chunked_argsort(list(w)))
    t0 = time.perf_counter()
    perm = f(*ws)
    perm.block_until_ready()
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    perm = f(*ws)
    perm.block_until_ready()
    run_s = time.perf_counter() - t0
    # the axon backend is experimental: cross-check that
    # block_until_ready actually blocked by timing a readback of the
    # result right after it (a large gap means block lied and run_s
    # undercounts — trust fetch_s - one RTT instead)
    t0 = time.perf_counter()
    perm2 = f(*ws)
    perm2.block_until_ready()
    _ = np.asarray(perm2[:4])
    fetch_s = time.perf_counter() - t0
    a, b = np.asarray(ws[0]), np.asarray(ws[1])
    got = np.asarray(perm)
    want = np.lexsort((b, a))
    assert np.array_equal(a[got], a[want]) and np.array_equal(
        b[got], b[want]), "chunked sort wrong"
    return (f"compile={compile_s:.1f}s run={run_s * 1000:.0f}ms "
            f"run_with_fetch={fetch_s * 1000:.0f}ms "
            f"({n / max(run_s, 1e-9) / 1e6:.1f} Mrows/s)")


@check("terasort_pipeline_1m")
def _terasort():
    import jax
    from thrill_tpu.api import Context
    from thrill_tpu.parallel.mesh import MeshExec

    n = 1 << 20
    rng = np.random.default_rng(0)
    recs = {"key": rng.integers(0, 256, size=(n, 10)).astype(np.uint8),
            "value": rng.integers(0, 256, size=(n, 90)).astype(np.uint8)}
    ctx = Context(MeshExec())
    try:
        def key_fn(r):
            return r["key"]

        # ingest ONCE (bench.py methodology): the timed loop measures
        # the Sort pipeline, not re-uploading the same 100 MB per run
        inp = ctx.Distribute(recs)
        jax.block_until_ready(jax.tree.leaves(
            inp.node.materialize(consume=False).tree))

        def once():
            inp.Keep()
            sh = inp.Sort(key_fn=key_fn).node.materialize()
            leaves = jax.tree.leaves(sh.tree)
            jax.block_until_ready(leaves)
            np.asarray(leaves[0][0, :1])     # completion readback
            return sh

        once()
        t0 = time.perf_counter()
        once()
        dt = time.perf_counter() - t0
    finally:
        ctx.close()
    return f"{n / dt / 1e6:.2f} Mrec/s ({dt * 1000:.0f} ms)"


@check("pallas_histogram_device")
def _pallas():
    import jax
    import jax.numpy as jnp
    from thrill_tpu.core.pallas_kernels import partition_histogram

    dest = jnp.asarray(
        np.random.default_rng(1).integers(0, 8, 1 << 16).astype(np.int32))
    hist = jax.jit(lambda d: partition_histogram(d, 8))(dest)
    got = np.asarray(hist)
    want = np.bincount(np.asarray(dest), minlength=8)[:8]
    assert np.array_equal(got, want), (got, want)
    return "device histogram matches bincount"


@check("pallas_radix_partition")
def _pallas_radix():
    """Round-3 Pallas stable-partition kernel + radix argsort engine on
    real hardware (CPU validates via interpret mode; here the compiled
    kernel runs)."""
    import jax
    import jax.numpy as jnp

    from thrill_tpu.core import pallas_sort as ps

    rng = np.random.default_rng(5)
    n = 1 << 17
    dest = rng.integers(0, 256, size=n).astype(np.int32)
    prev = os.environ.get("THRILL_TPU_PALLAS")
    os.environ["THRILL_TPU_PALLAS"] = "1"
    try:
        offs = np.asarray(jax.jit(
            lambda d: ps.stable_partition_offsets(d, 256))(
            jnp.asarray(dest)))
        perm = np.zeros(n, np.int64)
        perm[offs] = np.arange(n)
        assert np.array_equal(perm, np.argsort(dest, kind="stable")), \
            "partition offsets wrong"
        # full radix argsort through the pallas kernel
        w = rng.integers(0, 1 << 63, size=1 << 16).astype(np.uint64)
        t0 = time.perf_counter()
        p = np.asarray(ps.radix_argsort_device([jnp.asarray(w)]))
        dt = time.perf_counter() - t0
        assert np.array_equal(p, np.argsort(w, kind="stable")), \
            "radix argsort wrong"
    finally:
        if prev is None:
            os.environ.pop("THRILL_TPU_PALLAS", None)
        else:
            os.environ["THRILL_TPU_PALLAS"] = prev
    return (f"pallas partition+radix correct on device "
            f"(64K argsort incl. compile: {dt * 1000:.0f} ms)")


@check("text_wordcount_device")
def _text_wordcount():
    """Round-3 device text pipeline on real hardware: vectorized
    tokenization -> packed byte keys -> jitted ReduceByKey (the CPU
    host-radix fast path is ineligible on TPU, so this exercises the
    jitted sort + segmented-scan engines end to end)."""
    import collections
    import tempfile

    import jax

    from thrill_tpu.api import Context
    from thrill_tpu.parallel.mesh import MeshExec

    rng = np.random.default_rng(7)
    vocab = ["w%03d" % i for i in range(500)]
    words = [vocab[i] for i in rng.integers(0, 500, size=200_000)]
    text = " ".join(words)
    ctx = None
    with tempfile.NamedTemporaryFile("w", suffix=".txt") as f:
        f.write(text)
        f.flush()
        try:
            import sys
            sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))), "examples"))
            import word_count as wc

            ctx = Context(MeshExec())
            t0 = time.perf_counter()
            out = wc.word_count_text_device(ctx, f.name)
            sh = out.node.materialize()
            jax.block_until_ready(jax.tree.leaves(sh.tree))
            np.asarray(jax.tree.leaves(sh.tree)[0])[:1]
            dt = time.perf_counter() - t0
            hs = sh.to_host_shards("tpu-check")
            got = {bytes(np.asarray(it["w"])).rstrip(b"\x00").decode():
                   int(it["c"]) for l in hs.lists for it in l}
            assert got == dict(collections.Counter(words)), "counts wrong"
        finally:
            if ctx is not None:
                ctx.close()
    return (f"{len(words) / dt / 1e6:.2f} M words/s "
            f"({dt * 1000:.0f} ms, {len(got)} keys, golden)")


@check("fieldreduce_segment_engine")
def _fieldreduce_segment_engine():
    """Round-4 engine A/B on real hardware: the declarative FieldReduce
    segment-op fold (core/segmented.py segmented_reduce_fields — one
    scatter pass per field) vs the generic associative scan (O(log n)
    HBM combine rounds), identical results asserted, speedup reported."""
    import jax

    from thrill_tpu.api import Context, FieldReduce
    from thrill_tpu.parallel.mesh import MeshExec

    n = 1 << 19
    rng = np.random.default_rng(11)
    data = {"k": rng.integers(0, 4096, size=n).astype(np.int64),
            "v": rng.integers(0, 1000, size=n).astype(np.int64)}
    ctx = Context(MeshExec())
    try:
        d = ctx.Distribute(data)
        d.Keep()
        d.Keep()

        def key_fn(t):          # ONE key_fn object: the executable
            return t["k"]       # cache token is (key_fn, reduce_fn)

        def run(red):
            d.Keep()
            sh = d.ReduceByKey(key_fn, red).node.materialize()
            jax.block_until_ready(jax.tree.leaves(sh.tree))
            np.asarray(jax.tree.leaves(sh.tree)[0])[:1]
            return sh

        def timed(red):
            run(red)                        # warmup/compile
            t0 = time.perf_counter()
            sh = run(red)
            return time.perf_counter() - t0, sh

        dt_seg, sh_seg = timed(FieldReduce({"k": "first", "v": "sum"}))
        dt_scan, sh_scan = timed(
            lambda a, b: {"k": a["k"], "v": a["v"] + b["v"]})

        def pairs(sh):
            hs = sh.to_host_shards("tpu-check")
            return sorted((int(it["k"]), int(it["v"]))
                          for l in hs.lists for it in l)

        assert pairs(sh_seg) == pairs(sh_scan), "engines disagree"
    finally:
        ctx.close()
    return (f"segment={dt_seg*1e3:.0f}ms scan={dt_scan*1e3:.0f}ms "
            f"speedup={dt_scan/dt_seg:.2f}x (parity)")


@check("ragged_all_to_all")
def _ragged():
    import jax

    if len(jax.devices()) < 2:
        return "SKIP (single device; needs a multi-chip mesh)"
    prev = os.environ.get("THRILL_TPU_EXCHANGE")
    os.environ["THRILL_TPU_EXCHANGE"] = "ragged"
    ctx = None
    try:
        from thrill_tpu.api import Context
        from thrill_tpu.parallel.mesh import MeshExec
        ctx = Context(MeshExec())
        vals = np.arange(4096, dtype=np.int64)
        out = ctx.Distribute(vals).Map(lambda x: (x % 7, 1)).ReducePair(
            lambda a, b: a + b)
        assert sum(int(v) for _, v in out.AllGather()) == 4096
        return "ragged exchange pipeline correct"
    finally:
        if ctx is not None:
            ctx.close()
        if prev is None:
            os.environ.pop("THRILL_TPU_EXCHANGE", None)
        else:
            os.environ["THRILL_TPU_EXCHANGE"] = prev


def main():
    from thrill_tpu.common.platform import maybe_force_cpu_from_env
    maybe_force_cpu_from_env()

    if os.environ.get("JAX_PLATFORMS") != "cpu":
        # the axon plugin can HANG (not raise) at PJRT init — probe in
        # a throwaway subprocess first, exactly like bench.py
        from bench import _probe_accelerator
        if _probe_accelerator(float(os.environ.get(
                "THRILL_TPU_BENCH_PROBE_TIMEOUT_S", "150"))) is None:
            print("RESULT check=platform status=FAIL accelerator probe "
                  "failed/timed out; run with JAX_PLATFORMS=cpu for a "
                  "CPU smoke", flush=True)
            raise SystemExit(1)

    import jax
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.expanduser("~/.cache/thrill_tpu_xla"))
    except Exception:
        pass
    import thrill_tpu  # noqa: F401

    failures = 0
    for name, fn in RESULTS:
        try:
            msg = fn()
            print(f"RESULT check={name} status=ok {msg}", flush=True)
        except Exception:
            failures += 1
            print(f"RESULT check={name} status=FAIL", flush=True)
            traceback.print_exc()
    raise SystemExit(1 if failures else 0)


if __name__ == "__main__":
    main()
