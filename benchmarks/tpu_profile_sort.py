"""Decompose the on-chip TeraSort cost: upload vs sort vs gather.

Prints one RESULT line per component so the perf pass can target the
dominant one instead of guessing. Run on a healthy chip.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def timeit(fn, iters=3, warmup=1):
    """Time fn with block_until_ready AND a per-iteration readback of a
    few result bytes. The axon backend is experimental; if block lies,
    the fetch-inclusive number (minus one tunnel RTT, measured by the
    dispatch_tiny/fetch_tiny steps) is the trustworthy one. Returns the
    fetch-inclusive mean; prints nothing itself."""
    import jax
    import numpy as _np

    def _force(out):
        out = jax.block_until_ready(out)
        leaf = jax.tree.leaves(out)[0]
        _np.asarray(leaf[:1])        # readback forces real completion
        return out

    for _ in range(warmup):
        _force(fn())
    t0 = time.perf_counter()
    for _ in range(iters):
        _force(fn())
    return (time.perf_counter() - t0) / iters


def main():
    import thrill_tpu  # noqa: F401
    from thrill_tpu.common.platform import force_cpu_unless_accelerator

    # wedged-tunnel guard: probe the accelerator in a subprocess and
    # force CPU if it hangs (the watcher normally runs this only on a
    # healthy chip; direct CPU validation runs hit the hang otherwise)
    force_cpu_unless_accelerator()

    import jax
    import jax.numpy as jnp

    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.expanduser("~/.cache/thrill_tpu_xla"))
    except Exception:
        pass
    from thrill_tpu.core import keys as keymod
    from thrill_tpu.core.device_sort import argsort_words

    n = 1 << 20
    rng = np.random.default_rng(0)
    keys_h = rng.integers(0, 256, size=(n, 10)).astype(np.uint8)
    vals_h = rng.integers(0, 256, size=(n, 90)).astype(np.uint8)

    print(f"RESULT platform={jax.default_backend()} n={n}", flush=True)

    # 1. upload cost (host -> device through the tunnel)
    t0 = time.perf_counter()
    keys_d = jax.device_put(keys_h)
    vals_d = jax.device_put(vals_h)
    jax.block_until_ready((keys_d, vals_d))
    up = time.perf_counter() - t0
    print(f"RESULT step=upload_100mb time_ms={up*1000:.0f} "
          f"mb_s={100/up:.0f}", flush=True)

    # 2. encode key words only
    f_enc = jax.jit(lambda k: keymod.encode_key_words(k))
    dt = timeit(lambda: f_enc(keys_d))
    print(f"RESULT step=encode_words time_ms={dt*1000:.1f}", flush=True)

    # 3. argsort words only — A/B every device engine at this size
    #    (auto = chunked above 64K; radix = the Pallas stable-partition
    #    LSD engine, with and without the compiled kernel)
    def sort_only(k):
        words = keymod.encode_key_words(k)
        return argsort_words(list(words))

    prev_impl = os.environ.get("THRILL_TPU_SORT_IMPL")
    prev_pallas = os.environ.get("THRILL_TPU_PALLAS")
    for impl, pallas in (("auto", "0"), ("radix", "0"), ("radix", "1")):
        os.environ["THRILL_TPU_SORT_IMPL"] = impl
        os.environ["THRILL_TPU_PALLAS"] = pallas
        f_sort = jax.jit(sort_only)             # fresh trace per engine
        try:
            dt = timeit(lambda: f_sort(keys_d))
            print(f"RESULT step=argsort_words impl={impl} "
                  f"pallas={pallas} time_ms={dt*1000:.1f}", flush=True)
        except Exception as e:                  # engine fails: keep going
            print(f"RESULT step=argsort_words impl={impl} "
                  f"pallas={pallas} error={type(e).__name__}", flush=True)
    for var, prev in (("THRILL_TPU_SORT_IMPL", prev_impl),
                      ("THRILL_TPU_PALLAS", prev_pallas)):
        if prev is None:
            os.environ.pop(var, None)
        else:
            os.environ[var] = prev

    f_sort = jax.jit(sort_only)
    perm_d = jax.block_until_ready(f_sort(keys_d))

    # 4. payload gather only: [n, 90] u8 take along axis 0
    f_gather = jax.jit(lambda v, p: jnp.take(v, p, axis=0))
    dt_gather = timeit(lambda: f_gather(vals_d, perm_d))
    print(f"RESULT step=gather_90b_u8 time_ms={dt_gather*1000:.1f}",
          flush=True)

    # 4b. payload gather with payload packed as u32 words
    vals_u32 = jax.jit(
        lambda v: jax.lax.bitcast_convert_type(
            jnp.pad(v, ((0, 0), (0, 2))).reshape(n, 23, 4),
            jnp.uint32))(vals_d)
    vals_u32 = jax.block_until_ready(vals_u32)
    dt = timeit(lambda: f_gather(vals_u32, perm_d))
    print(f"RESULT step=gather_23w_u32 time_ms={dt*1000:.1f}", flush=True)

    # 4c. gather keys [n, 10] u8
    dt = timeit(lambda: f_gather(keys_d, perm_d))
    print(f"RESULT step=gather_10b_u8 time_ms={dt*1000:.1f}", flush=True)

    # 4d. HBM-bandwidth utilization (the roofline check the BASELINE.md
    # analysis needs a measured point for): the payload gather's
    # traffic model is exact — 90 B random-read + 90 B stream-write
    # per row — so measured GB/s = 180n/t, derived from step 4's
    # timing (no re-run: healthy-chip windows are scarce minutes).
    # Utilization is quoted against v5e-class peak (~820 GB/s).
    gbs = 180 * n / dt_gather / 1e9
    print(f"RESULT step=hbm_bandwidth_gather gb_s={gbs:.1f} "
          f"util_vs_820={gbs / 820:.3f}", flush=True)

    # 5. fused whole program (encode + sort + both gathers), like the
    #    W=1 Sort program — A/B over the packed-movement flag
    from thrill_tpu.core.rowmove import take_rows

    def fused(k, v):
        words = keymod.encode_key_words(k)
        perm = argsort_words(list(words))
        return take_rows(k, perm), take_rows(v, perm)

    best_fused = None
    for mode in ("1", "0"):
        os.environ["THRILL_TPU_PACK_MOVE"] = mode
        f_all = jax.jit(lambda k, v: fused(k, v))  # fresh trace per mode
        dt = timeit(lambda: f_all(keys_d, vals_d))
        best_fused = dt if best_fused is None else min(best_fused, dt)
        print(f"RESULT step=fused_sort_gather pack={mode} "
              f"time_ms={dt*1000:.1f} mrec_s={n/dt/1e6:.2f}", flush=True)
    os.environ.pop("THRILL_TPU_PACK_MOVE", None)
    # modeled traffic for the fused W=1 program (BASELINE.md roofline
    # rows: ~480 B argsort state + 20 B key gather + 180 B payload
    # gather ≈ 680 B/row) — softer than the gather-only figure but the
    # one comparable to the 0.25 s/100 GB floor analysis
    gbs_f = 680 * n / best_fused / 1e9
    print(f"RESULT step=hbm_bandwidth_fused_model gb_s={gbs_f:.1f} "
          f"util_vs_820={gbs_f / 820:.3f}", flush=True)

    # 6. per-dispatch overhead through the tunnel (tiny program)
    f_tiny = jax.jit(lambda x: x + 1)
    x1 = jax.device_put(np.zeros(8, np.float32))
    dt = timeit(lambda: f_tiny(x1), iters=20)
    print(f"RESULT step=dispatch_tiny time_ms={dt*1000:.2f}", flush=True)

    # 7. device->host fetch of the [W,W] counts analog (tiny fetch)
    t_small = jax.device_put(np.zeros((1, 1), np.int32))
    dt = timeit(lambda: np.asarray(t_small), iters=20)
    print(f"RESULT step=fetch_tiny time_ms={dt*1000:.2f}", flush=True)


if __name__ == "__main__":
    main()
