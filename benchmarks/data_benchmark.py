"""Data-layer microbenchmarks: File/BlockPool/serializer throughput.

Equivalent of the reference's benchmarks/data/data_benchmark.cpp.
Prints RESULT lines.
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo root on sys.path for CLI runs)


import tempfile
import time

import numpy as np

from thrill_tpu.data.block_pool import BlockPool
from thrill_tpu.data.file import File
from thrill_tpu.data.serializer import deserialize_batch, serialize_batch


def bench_blockpool(n_blocks=2000, block_kb=64):
    payload = np.random.default_rng(0).bytes(block_kb * 1024)
    pool = BlockPool()
    t0 = time.perf_counter()
    ids = [pool.put(payload) for _ in range(n_blocks)]
    put_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    for bid in ids:
        pool.get(bid)
    get_dt = time.perf_counter() - t0
    vol = n_blocks * block_kb / 1024
    print(f"RESULT bench=blockpool_put native={pool.native} "
          f"volume_mb={vol:.0f} throughput_mb_s={vol / put_dt:.1f}")
    print(f"RESULT bench=blockpool_get native={pool.native} "
          f"volume_mb={vol:.0f} throughput_mb_s={vol / get_dt:.1f}")
    pool.close()


def bench_blockpool_spill(n_blocks=500, block_kb=64):
    with tempfile.TemporaryDirectory() as d:
        pool = BlockPool(spill_dir=d, soft_limit=4 << 20)
        payload = np.random.default_rng(0).bytes(block_kb * 1024)
        t0 = time.perf_counter()
        ids = [pool.put(payload) for _ in range(n_blocks)]
        for bid in ids:
            pool.get(bid)
        dt = time.perf_counter() - t0
        vol = n_blocks * block_kb / 1024
        print(f"RESULT bench=blockpool_spill_roundtrip volume_mb={vol:.0f} "
              f"resident_mb={pool.mem_usage / 1e6:.1f} "
              f"throughput_mb_s={2 * vol / dt:.1f}")
        pool.close()


def bench_file_items(n=200_000):
    f = File(block_items=8192)
    t0 = time.perf_counter()
    with f.writer() as w:
        for i in range(n):
            w.put(i)
    wr = time.perf_counter() - t0
    t0 = time.perf_counter()
    cnt = sum(1 for _ in f.keep_reader())
    rd = time.perf_counter() - t0
    assert cnt == n
    print(f"RESULT bench=file_write items={n} items_per_s={n / wr:.0f}")
    print(f"RESULT bench=file_read items={n} items_per_s={n / rd:.0f}")
    f.close()


def bench_serializer(n=100, batch=10_000):
    arrs = [np.arange(batch, dtype=np.int64) for _ in range(8)]
    t0 = time.perf_counter()
    for _ in range(n):
        deserialize_batch(serialize_batch(arrs))
    dt = time.perf_counter() - t0
    vol = n * 8 * batch * 8 / 1e6
    print(f"RESULT bench=serializer_raw_roundtrip volume_mb={vol:.0f} "
          f"throughput_mb_s={vol / dt:.1f}")


if __name__ == "__main__":
    bench_blockpool()
    bench_blockpool_spill()
    bench_file_items()
    bench_serializer()
