"""Data-layer microbenchmarks: File/BlockPool/serializer throughput.

Equivalent of the reference's benchmarks/data/data_benchmark.cpp.
Prints RESULT lines.
"""

from __future__ import annotations

import _bootstrap  # noqa: F401  (repo root on sys.path for CLI runs)


import tempfile
import time

import numpy as np

from thrill_tpu.data.block_pool import BlockPool
from thrill_tpu.data.file import File
from thrill_tpu.data.serializer import deserialize_batch, serialize_batch


def bench_blockpool(n_blocks=2000, block_kb=64):
    payload = np.random.default_rng(0).bytes(block_kb * 1024)
    pool = BlockPool()
    t0 = time.perf_counter()
    ids = [pool.put(payload) for _ in range(n_blocks)]
    put_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    for bid in ids:
        pool.get(bid)
    get_dt = time.perf_counter() - t0
    vol = n_blocks * block_kb / 1024
    print(f"RESULT bench=blockpool_put native={pool.native} "
          f"volume_mb={vol:.0f} throughput_mb_s={vol / put_dt:.1f}")
    print(f"RESULT bench=blockpool_get native={pool.native} "
          f"volume_mb={vol:.0f} throughput_mb_s={vol / get_dt:.1f}")
    pool.close()


def bench_blockpool_spill(n_blocks=500, block_kb=64):
    with tempfile.TemporaryDirectory() as d:
        pool = BlockPool(spill_dir=d, soft_limit=4 << 20)
        payload = np.random.default_rng(0).bytes(block_kb * 1024)
        t0 = time.perf_counter()
        ids = [pool.put(payload) for _ in range(n_blocks)]
        for bid in ids:
            pool.get(bid)
        dt = time.perf_counter() - t0
        vol = n_blocks * block_kb / 1024
        print(f"RESULT bench=blockpool_spill_roundtrip volume_mb={vol:.0f} "
              f"resident_mb={pool.mem_usage / 1e6:.1f} "
              f"throughput_mb_s={2 * vol / dt:.1f}")
        pool.close()


def bench_file_items(n=200_000):
    f = File(block_items=8192)
    t0 = time.perf_counter()
    with f.writer() as w:
        for i in range(n):
            w.put(i)
    wr = time.perf_counter() - t0
    t0 = time.perf_counter()
    cnt = sum(1 for _ in f.keep_reader())
    rd = time.perf_counter() - t0
    assert cnt == n
    print(f"RESULT bench=file_write items={n} items_per_s={n / wr:.0f}")
    print(f"RESULT bench=file_read items={n} items_per_s={n / rd:.0f}")
    f.close()


def bench_serializer(n=100, batch=10_000):
    arrs = [np.arange(batch, dtype=np.int64) for _ in range(8)]
    t0 = time.perf_counter()
    for _ in range(n):
        deserialize_batch(serialize_batch(arrs))
    dt = time.perf_counter() - t0
    vol = n * 8 * batch * 8 / 1e6
    print(f"RESULT bench=serializer_raw_roundtrip volume_mb={vol:.0f} "
          f"throughput_mb_s={vol / dt:.1f}")


def bench_file_scatter(n=1_000_000, parts=64):
    """Zero-copy scatter vs item-level re-partitioning (the reference's
    Stream::Scatter block re-slicing win, thrill/data/stream.hpp:77-210)."""
    import numpy as np
    from thrill_tpu.data.file import File

    f = File(block_items=4096)
    with f.writer() as w:
        for i in range(0, n, 4096):
            for row in np.arange(i, i + 4096, dtype=np.int64
                                 ).reshape(-1, 1):
                w.put(row)
    offsets = [(p * n) // parts for p in range(parts + 1)]
    t0 = time.perf_counter()
    files = f.scatter(offsets)
    dt_scatter = time.perf_counter() - t0
    t0 = time.perf_counter()
    items = list(f.keep_reader())
    lists = [items[offsets[p]:offsets[p + 1]] for p in range(parts)]
    dt_items = time.perf_counter() - t0
    assert sum(x.num_items for x in files) == sum(len(l) for l in lists)
    print(f"RESULT bench=file_scatter items={n} parts={parts} "
          f"scatter_ms={dt_scatter * 1000:.2f} "
          f"item_repartition_ms={dt_items * 1000:.1f}")
    for x in files:
        x.close()
    f.close()


if __name__ == "__main__":
    bench_blockpool()
    bench_blockpool_spill()
    bench_file_items()
    bench_serializer()
    bench_file_scatter()
