"""A/B: bulk vs streamed (MixStream-analog) ReduceByKey post-phase.

The reference defaults ReduceByKey to MixStream delivery with an
overlapped post-phase thread (api/reduce_by_key.hpp:142-168,
core/reduce_table.hpp:40 DefaultReduceConfig). Our analog is
THRILL_TPU_REDUCE_STREAM: per-round exchange programs whose folds
overlap later rounds' collectives via jax async dispatch.

Prints RESULT lines for both modes over a sweep of key cardinalities;
run on the virtual 8-device CPU mesh by default (the only mesh this
image can host) and on a real multi-chip mesh unchanged.
"""

from __future__ import annotations

import os
import sys
import time

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import thrill_tpu  # noqa: F401,E402
from thrill_tpu.common.platform import force_cpu_unless_accelerator  # noqa: E402

force_cpu_unless_accelerator()

import jax  # noqa: E402

from thrill_tpu.api import Context  # noqa: E402
from thrill_tpu.parallel.mesh import MeshExec  # noqa: E402


def _key(t):
    return t["k"]


def _red(a, b):
    return {"k": a["k"], "v": a["v"] + b["v"]}


def run_mode(stream: bool, n: int, nkeys: int, iters: int = 5) -> float:
    os.environ["THRILL_TPU_REDUCE_STREAM"] = "1" if stream else "0"
    mex = MeshExec()
    ctx = Context(mex)
    rng = np.random.default_rng(42)
    data = {
        "k": rng.integers(0, nkeys, size=n).astype(np.int64),
        "v": rng.standard_normal(n),
    }
    inp = ctx.Distribute(data)
    jax.block_until_ready(jax.tree.leaves(
        inp.node.materialize(consume=False).tree))

    def once():
        inp.Keep()
        out = inp.ReduceByKey(_key, _red)
        shards = out.node.materialize()
        leaves = jax.tree.leaves(shards.tree)
        jax.block_until_ready(leaves)
        np.asarray(leaves[0])[:1]
        return shards

    once()  # warmup/compile
    t0 = time.perf_counter()
    for _ in range(iters):
        once()
    dt = (time.perf_counter() - t0) / iters
    ctx.close()
    return dt


def main():
    n = int(os.environ.get("AB_N", 1 << 19))
    for nkeys in (64, 4096, 1 << 16, 1 << 19):
        bulk = run_mode(False, n, nkeys)
        strm = run_mode(True, n, nkeys)
        print(f"RESULT bench=reduce_post n={n} keys={nkeys} "
              f"bulk_ms={bulk * 1e3:.1f} stream_ms={strm * 1e3:.1f} "
              f"stream_speedup={bulk / strm:.3f}",
              flush=True)


if __name__ == "__main__":
    main()
