// Native async I/O engine: epoll event loop on a dedicated thread.
//
// Equivalent of the reference's net::Dispatcher / DispatcherThread
// (reference: thrill/net/dispatcher.hpp:510 — AsyncRead/AsyncWrite of
// buffers queued per connection, callbacks run on the dispatcher
// thread; dispatcher_thread.hpp:60 — the dedicated thread driving the
// loop). TPU-native role: the host control plane (TCP group) hands
// byte buffers to this engine so sends to many peers progress
// CONCURRENTLY while the worker thread computes — the overlap the
// reference gets for its Multiplexer block streams. Completions are
// polled/awaited from Python (ids), not delivered as C callbacks:
// Python owns scheduling, C++ owns bytes and the event loop, the same
// split as the native block store.
//
// Request lifecycle: async_write copies the buffer in, async_read
// records a want-length; the loop moves bytes whenever epoll reports
// readiness, retiring requests FIFO per fd per direction (matching the
// reference's per-connection queues). disp_wait blocks on a condvar;
// fetch copies a completed read's bytes out and frees the slot.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 dispatcher.cpp -o libdispatcher.so

#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>
#include <fcntl.h>
#include <errno.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct WriteReq {
  int64_t id = 0;
  // borrowed buffer: the caller guarantees it stays valid until the
  // request completes (the Python wrapper pins the immutable bytes
  // object until fetch) — enqueue is zero-copy even for huge frames
  const char* data = nullptr;
  size_t len = 0;
  size_t off = 0;
};

struct ReadReq {
  int64_t id = 0;
  std::vector<char> data;   // filled up to got
  size_t want = 0;
  size_t got = 0;
};

struct FdState {
  int fd = -1;
  std::deque<WriteReq> writes;
  std::deque<ReadReq> reads;
  uint32_t events = 0;      // current epoll interest set
  bool error = false;
  // fd removed from the epoll set: a bare EPOLLHUP/EPOLLERR is
  // level-triggered and reported regardless of the interest mask, so
  // an idle hung-up fd must leave the set or the loop busy-spins. A
  // later request re-adds it (buffered bytes are still readable).
  bool parked = false;
};

// completed request: status >0 ok (bytes), <0 error (-errno or -1 eof)
struct Done {
  int64_t status = 0;
  std::vector<char> data;   // read payload (empty for writes)
};

class Dispatcher {
 public:
  Dispatcher() {
    epfd_ = epoll_create1(EPOLL_CLOEXEC);
    if (pipe2(wake_, O_NONBLOCK | O_CLOEXEC) != 0) {
      wake_[0] = wake_[1] = -1;
    }
    if (epfd_ >= 0 && wake_[0] >= 0) {
      epoll_event ev{};
      ev.events = EPOLLIN;
      ev.data.fd = wake_[0];
      epoll_ctl(epfd_, EPOLL_CTL_ADD, wake_[0], &ev);
      loop_ = std::thread([this] { Run(); });
      running_ = true;
    }
  }

  ~Dispatcher() {
    if (running_) {
      stop_.store(true);
      Wake();
      loop_.join();
    }
    if (epfd_ >= 0) close(epfd_);
    if (wake_[0] >= 0) { close(wake_[0]); close(wake_[1]); }
  }

  bool ok() const { return running_; }

  int Register(int fd) {
    std::lock_guard<std::mutex> g(mu_);
    if (fds_.count(fd)) return -1;       // before any fd-mode change
    int flags = fcntl(fd, F_GETFL, 0);
    if (flags < 0 || fcntl(fd, F_SETFL, flags | O_NONBLOCK) < 0) return -1;
    FdState st;
    st.fd = fd;
    fds_.emplace(fd, std::move(st));
    epoll_event ev{};
    ev.events = 0;
    ev.data.fd = fd;
    if (epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &ev) != 0) {
      fds_.erase(fd);
      fcntl(fd, F_SETFL, flags);         // restore blocking mode
      return -1;
    }
    return 0;
  }

  // Drop the fd from the engine. Pending requests complete with error;
  // the fd is restored to blocking mode for the caller's further use.
  int Unregister(int fd) {
    std::unique_lock<std::mutex> lk(mu_);
    auto it = fds_.find(fd);
    if (it == fds_.end()) return -1;
    for (auto& w : it->second.writes) Retire(w.id, -EPIPE, {});
    for (auto& r : it->second.reads) Retire(r.id, -EPIPE, {});
    epoll_ctl(epfd_, EPOLL_CTL_DEL, fd, nullptr);
    fds_.erase(it);
    lk.unlock();
    int flags = fcntl(fd, F_GETFL, 0);
    if (flags >= 0) fcntl(fd, F_SETFL, flags & ~O_NONBLOCK);
    cv_.notify_all();
    return 0;
  }

  int64_t AsyncWrite(int fd, const char* buf, int64_t len) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = fds_.find(fd);
    if (it == fds_.end() || it->second.error) return -1;
    FdState& st = it->second;
    int64_t id = next_id_++;
    size_t off = 0;
    if (st.writes.empty()) {
      // opportunistic inline send while the queue is empty (FIFO-safe):
      // a few attempts fill the socket buffer at caller speed — small
      // frames usually complete here — but the attempt cap keeps the
      // caller's enqueue latency bounded so a continuously-draining
      // receiver cannot turn the async send into a full blocking one
      for (int attempts = 0;
           off < static_cast<size_t>(len) && attempts < 4; attempts++) {
        ssize_t n = send(fd, buf + off, len - off, MSG_NOSIGNAL);
        if (n < 0) {
          if (errno == EAGAIN || errno == EWOULDBLOCK) break;
          if (errno == EINTR) continue;
          Retire(id, -errno, {});
          FailAll(st, -errno);
          return id;
        }
        off += static_cast<size_t>(n);
      }
      if (off == static_cast<size_t>(len)) {
        Retire(id, std::max<int64_t>(len, 1), {});
        cv_.notify_all();
        return id;
      }
    }
    WriteReq req;
    req.id = id;
    req.data = buf;
    req.len = static_cast<size_t>(len);
    req.off = off;
    st.writes.push_back(req);
    UpdateInterest(st);
    Wake();
    return id;
  }

  int64_t AsyncRead(int fd, int64_t len) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = fds_.find(fd);
    if (it == fds_.end() || it->second.error) return -1;
    int64_t id = next_id_++;
    if (len == 0 && it->second.reads.empty()) {
      // zero-byte read with nothing queued ahead completes right away
      // (epoll never fires for it; matches blocking recv_exact(0))
      Retire(id, 1, {});
      cv_.notify_all();
      return id;
    }
    ReadReq req;
    req.id = id;
    req.want = static_cast<size_t>(len);
    req.data.resize(req.want);
    it->second.reads.push_back(std::move(req));
    UpdateInterest(it->second);
    Wake();
    return id;
  }

  // 0 = pending, 1 = done ok, negative = error status
  int64_t Poll(int64_t id) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = done_.find(id);
    if (it == done_.end()) return 0;
    return it->second.status > 0 ? 1 : it->second.status;
  }

  int64_t Wait(int64_t id, double timeout_s) {
    std::unique_lock<std::mutex> lk(mu_);
    auto pred = [&] { return done_.count(id) > 0; };
    if (timeout_s < 0) {
      cv_.wait(lk, pred);
    } else {
      // wait_until(system_clock), not wait_for: wait_for waits on the
      // steady clock, which libstdc++ lowers to pthread_cond_clockwait
      // — a call this toolchain's libtsan does not intercept, so TSan
      // loses track of the condvar's internal unlock/relock and
      // reports a bogus "double lock of a mutex" on the next acquire.
      // pthread_cond_timedwait (what system_clock waits use) is
      // intercepted. A wall-clock step can stretch/shrink the timeout;
      // completion wakeups are condvar-signaled either way.
      auto deadline = std::chrono::system_clock::now() +
                      std::chrono::microseconds(
                          static_cast<int64_t>(timeout_s * 1e6));
      if (!cv_.wait_until(lk, deadline, pred))
        return 0;  // timeout, still pending
    }
    auto& d = done_[id];
    return d.status > 0 ? 1 : d.status;
  }

  // copy a completed request's read bytes out and free the slot;
  // returns bytes copied (0 for writes), negative error status, or
  // kNotDone for an id with no completion yet (distinct from the -1
  // EOF status so callers can tell "still pending" from "failed")
  static constexpr int64_t kNotDone = -(int64_t(1) << 62);

  int64_t Fetch(int64_t id, char* out, int64_t cap) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = done_.find(id);
    if (it == done_.end()) return kNotDone;
    Done d = std::move(it->second);
    done_.erase(it);
    if (d.status < 0) return d.status;
    int64_t n = static_cast<int64_t>(d.data.size());
    if (n > 0 && out != nullptr && cap >= n)
      std::memcpy(out, d.data.data(), static_cast<size_t>(n));
    else if (n > cap)
      return -EMSGSIZE;
    return n;
  }

  int64_t PendingCount() {
    std::lock_guard<std::mutex> g(mu_);
    int64_t n = 0;
    for (auto& kv : fds_)
      n += static_cast<int64_t>(kv.second.writes.size() +
                                kv.second.reads.size());
    return n;
  }

 private:
  void Wake() {
    char b = 1;
    if (wake_[1] >= 0) { ssize_t r = write(wake_[1], &b, 1); (void)r; }
  }

  // caller holds mu_
  void Retire(int64_t id, int64_t status, std::vector<char>&& data) {
    Done d;
    d.status = status;
    d.data = std::move(data);
    done_.emplace(id, std::move(d));
  }

  // caller holds mu_
  void UpdateInterest(FdState& st) {
    uint32_t want = 0;
    if (!st.reads.empty()) want |= EPOLLIN;
    if (!st.writes.empty()) want |= EPOLLOUT;
    if (st.parked) {
      if (want == 0) return;
      epoll_event ev{};
      ev.events = want;
      ev.data.fd = st.fd;
      if (epoll_ctl(epfd_, EPOLL_CTL_ADD, st.fd, &ev) == 0) {
        st.parked = false;
        st.events = want;
      }
      return;
    }
    if (want == st.events) return;
    epoll_event ev{};
    ev.events = want;
    ev.data.fd = st.fd;
    epoll_ctl(epfd_, EPOLL_CTL_MOD, st.fd, &ev);
    st.events = want;
  }

  // caller holds mu_: drop the fd from the epoll set (see FdState)
  void Park(FdState& st) {
    if (st.parked) return;
    epoll_ctl(epfd_, EPOLL_CTL_DEL, st.fd, nullptr);
    st.parked = true;
    st.events = 0;
  }

  void HandleWritable(FdState& st) {
    while (!st.writes.empty()) {
      WriteReq& w = st.writes.front();
      ssize_t n = send(st.fd, w.data + w.off, w.len - w.off,
                       MSG_NOSIGNAL);
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) return;
        if (errno == EINTR) continue;
        FailAll(st, -errno);
        return;
      }
      w.off += static_cast<size_t>(n);
      if (w.off < w.len) return;
      // zero-length writes still report success (status must be > 0)
      Retire(w.id, std::max<int64_t>(static_cast<int64_t>(w.len), 1), {});
      st.writes.pop_front();
      cv_.notify_all();
    }
  }

  void HandleReadable(FdState& st) {
    while (!st.reads.empty()) {
      ReadReq& r = st.reads.front();
      if (r.want == 0) {
        Retire(r.id, 1, {});
        st.reads.pop_front();
        cv_.notify_all();
        continue;
      }
      ssize_t n = recv(st.fd, r.data.data() + r.got, r.want - r.got, 0);
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK) return;
        if (errno == EINTR) continue;
        FailAll(st, -errno);
        return;
      }
      if (n == 0) {  // peer closed mid-request
        FailAll(st, -1);
        return;
      }
      r.got += static_cast<size_t>(n);
      if (r.got < r.want) return;
      Retire(r.id, static_cast<int64_t>(r.want), std::move(r.data));
      st.reads.pop_front();
      cv_.notify_all();
    }
  }

  // caller holds mu_
  void FailAll(FdState& st, int64_t status) {
    st.error = true;
    for (auto& w : st.writes) Retire(w.id, status, {});
    for (auto& r : st.reads) Retire(r.id, status, {});
    st.writes.clear();
    st.reads.clear();
    Park(st);  // errored fds keep reporting HUP/ERR — leave the set
    cv_.notify_all();
  }

  void Run() {
    std::vector<epoll_event> evs(64);
    while (!stop_.load()) {
      int n = epoll_wait(epfd_, evs.data(), static_cast<int>(evs.size()),
                         200 /*ms: bounded stop latency*/);
      if (n < 0) {
        if (errno == EINTR) continue;
        break;
      }
      std::lock_guard<std::mutex> g(mu_);
      for (int i = 0; i < n; i++) {
        int fd = evs[i].data.fd;
        if (fd == wake_[0]) {
          char buf[256];
          while (read(wake_[0], buf, sizeof buf) > 0) {}
          continue;
        }
        auto it = fds_.find(fd);
        if (it == fds_.end()) continue;
        FdState& st = it->second;
        if (evs[i].events & (EPOLLERR | EPOLLHUP)) {
          // a hangup is NOT an error for this fd's buffered data:
          // drain pending reads (recv returns the peer's final bytes,
          // then 0 -> EOF fails only reads that cannot complete) and
          // let pending writes fail through send() itself. An idle fd
          // is parked so the level-triggered HUP stops firing; a later
          // async_read re-adds it and still sees the kernel buffer.
          HandleReadable(st);
          HandleWritable(st);
          if (!st.error && st.reads.empty() && st.writes.empty())
            Park(st);
          continue;
        }
        if (evs[i].events & EPOLLOUT) HandleWritable(st);
        if (evs[i].events & EPOLLIN) HandleReadable(st);
        UpdateInterest(st);
      }
    }
  }

  int epfd_ = -1;
  int wake_[2] = {-1, -1};
  std::thread loop_;
  bool running_ = false;
  std::atomic<bool> stop_{false};
  std::mutex mu_;
  std::condition_variable cv_;
  std::unordered_map<int, FdState> fds_;
  std::unordered_map<int64_t, Done> done_;
  int64_t next_id_ = 1;
};

}  // namespace

extern "C" {

void* disp_create() {
  auto* d = new Dispatcher();
  if (!d->ok()) {
    delete d;
    return nullptr;
  }
  return d;
}

void disp_destroy(void* h) { delete static_cast<Dispatcher*>(h); }

int disp_register(void* h, int fd) {
  return static_cast<Dispatcher*>(h)->Register(fd);
}

int disp_unregister(void* h, int fd) {
  return static_cast<Dispatcher*>(h)->Unregister(fd);
}

int64_t disp_async_write(void* h, int fd, const char* buf, int64_t len) {
  return static_cast<Dispatcher*>(h)->AsyncWrite(fd, buf, len);
}

int64_t disp_async_read(void* h, int fd, int64_t len) {
  return static_cast<Dispatcher*>(h)->AsyncRead(fd, len);
}

int64_t disp_poll(void* h, int64_t id) {
  return static_cast<Dispatcher*>(h)->Poll(id);
}

int64_t disp_wait(void* h, int64_t id, double timeout_s) {
  return static_cast<Dispatcher*>(h)->Wait(id, timeout_s);
}

int64_t disp_fetch(void* h, int64_t id, char* out, int64_t cap) {
  return static_cast<Dispatcher*>(h)->Fetch(id, out, cap);
}

int64_t disp_pending(void* h) {
  return static_cast<Dispatcher*>(h)->PendingCount();
}

}  // extern "C"
