// ThreadSanitizer stress harness for the genuinely multithreaded
// native components: the epoll dispatcher (its event-loop thread vs
// API callers) and the block store (its async spill-writer thread vs
// put/get/pin/drop callers).
//
// The reference wires TSan through its CI for exactly this class of
// code (/root/reference/thrill/CMakeLists.txt:129-131 and the
// tsan-annotated busy-wait paths, net/flow_control_channel.hpp:108-139);
// Python-driven tests cannot give the native threads that coverage, so
// this is a STANDALONE binary: tests/native/test_tsan.py compiles it
// together with dispatcher.cpp + blockstore.cpp under
// -fsanitize=thread and asserts a clean run (TSan exits non-zero on a
// detected race via halt_on_error, and reports go to stderr).
//
// Build (the test does this):
//   g++ -O1 -g -fsanitize=thread -pthread -std=c++17 \
//       native/tsan_stress.cpp -o tsan_stress
// (dispatcher.cpp / blockstore.cpp are #included so their internal
// classes are compiled into the instrumented binary directly.)

#include <atomic>
#include <cstdio>
#include <cstring>
#include <string>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <vector>

#include "dispatcher.cpp"
#include "blockstore.cpp"

namespace {

int stress_dispatcher() {
  void* d = disp_create();
  if (!d) {
    std::fprintf(stderr, "disp_create failed\n");
    return 1;
  }
  constexpr int kPairs = 4;
  constexpr int kRounds = 60;
  int fds[kPairs][2];
  for (auto& p : fds) {
    if (socketpair(AF_UNIX, SOCK_STREAM, 0, p) != 0) return 1;
    disp_register(d, p[0]);
    disp_register(d, p[1]);
  }
  std::atomic<int> failures{0};
  std::vector<std::thread> threads;
  // per pair: one thread writes bursts into side 0, one reads from
  // side 1 — API calls race against the epoll loop thread's handling
  for (int pi = 0; pi < kPairs; ++pi) {
    threads.emplace_back([&, pi] {
      std::string blob(1 << 15, static_cast<char>('a' + pi));
      std::vector<int64_t> ids;
      for (int r = 0; r < kRounds; ++r) {
        int64_t id = disp_async_write(d, fds[pi][0], blob.data(),
                                      static_cast<int64_t>(blob.size()));
        if (id < 0) failures.fetch_add(1);
        else ids.push_back(id);
      }
      // BORROW CONTRACT: the buffer must outlive its sends (the
      // Python side pins borrowed buffers until flush() for the same
      // reason) — the first version of this harness dropped blob at
      // thread exit with writes still queued, and TSan correctly
      // flagged the recycled-memory read in the loop thread
      for (int64_t id : ids) {
        if (disp_wait(d, id, 30.0) < 0) failures.fetch_add(1);
      }
    });
    threads.emplace_back([&, pi] {
      std::vector<char> buf(1 << 15);
      for (int r = 0; r < kRounds; ++r) {
        int64_t id = disp_async_read(d, fds[pi][1],
                                     static_cast<int64_t>(buf.size()));
        if (id < 0 || disp_wait(d, id, 30.0) < 0 ||
            disp_fetch(d, id, buf.data(),
                       static_cast<int64_t>(buf.size())) !=
                static_cast<int64_t>(buf.size())) {
          failures.fetch_add(1);
          continue;
        }
        for (char c : buf) {
          if (c != 'a' + pi) {
            failures.fetch_add(1);
            break;
          }
        }
      }
    });
  }
  // a churn thread registers/unregisters an unrelated pair while the
  // loop thread is busy — the registration path races the event loop
  threads.emplace_back([&] {
    for (int r = 0; r < 40; ++r) {
      int p[2];
      if (socketpair(AF_UNIX, SOCK_STREAM, 0, p) != 0) continue;
      disp_register(d, p[0]);
      disp_register(d, p[1]);
      const char one = 'x';
      disp_async_write(d, p[0], &one, 1);
      int64_t rid = disp_async_read(d, p[1], 1);
      char c;
      disp_wait(d, rid, 30.0);
      disp_fetch(d, rid, &c, 1);
      disp_unregister(d, p[0]);
      disp_unregister(d, p[1]);
      close(p[0]);
      close(p[1]);
    }
  });
  for (auto& t : threads) t.join();
  for (auto& p : fds) {
    disp_unregister(d, p[0]);
    disp_unregister(d, p[1]);
    close(p[0]);
    close(p[1]);
  }
  disp_destroy(d);
  if (failures.load()) {
    std::fprintf(stderr, "dispatcher stress: %d logical failures\n",
                 failures.load());
    return 1;
  }
  return 0;
}

int stress_blockstore(const char* dir) {
  // tiny soft limit forces the async spill thread to run constantly
  void* s = bs_create(dir, 1 << 16, /*async_io=*/1);
  if (!s) return 1;
  constexpr int kThreads = 4;
  constexpr int kOps = 250;
  std::atomic<int> failures{0};
  std::vector<std::thread> threads;
  for (int ti = 0; ti < kThreads; ++ti) {
    threads.emplace_back([&, ti] {
      std::vector<int64_t> mine;
      std::string payload(4096, static_cast<char>('A' + ti));
      std::vector<char> out(payload.size());
      for (int i = 0; i < kOps; ++i) {
        int64_t id = bs_put(s, payload.data(),
                            static_cast<int64_t>(payload.size()));
        if (id < 0) {
          failures.fetch_add(1);
          continue;
        }
        mine.push_back(id);
        // read back an older block (may already be spilled by the
        // writer thread -> exercises the reload path under pin)
        int64_t victim = mine[mine.size() / 2];
        if (bs_pin(s, victim) == 0) {
          if (bs_size(s, victim) !=
                  static_cast<int64_t>(payload.size()) ||
              bs_get(s, victim, out.data()) != 0 ||
              std::memcmp(out.data(), payload.data(),
                          payload.size()) != 0) {
            failures.fetch_add(1);
          }
          bs_unpin(s, victim);
        }
        if (i % 7 == 0 && mine.size() > 4) {
          bs_drop(s, mine.front());
          mine.erase(mine.begin());
        }
      }
      for (int64_t id : mine) bs_drop(s, id);
    });
  }
  for (auto& t : threads) t.join();
  bs_flush(s);
  bs_destroy(s);
  if (failures.load()) {
    std::fprintf(stderr, "blockstore stress: %d logical failures\n",
                 failures.load());
    return 1;
  }
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  const char* dir = argc > 1 ? argv[1] : "/tmp";
  int rc = stress_dispatcher();
  rc |= stress_blockstore(dir);
  if (rc == 0) std::printf("TSAN_STRESS_OK\n");
  return rc;
}
