// Chunk-fed k-way merge over byte-encoded sort keys.
//
// The host EM sort (thrill_tpu/api/ops/sort.py:_em_sort) spills sorted
// runs to block-store Files and merges them; the reference's
// equivalent is its tightest loop (thrill/api/sort.hpp:216-271 partial
// multiway merge over core/multiway_merge.hpp:132 tournament trees).
// Python heapq with per-item key calls was the round-3 bottleneck;
// this engine replaces ONLY the comparison/selection loop:
//
// * Python feeds each run's key bytes in CHUNKS (offsets + blob read
//   from the spilled key file), so memory stays bounded by
//   k * chunk_size keys regardless of total run length (the
//   external-memory property is preserved — item payloads never enter
//   this engine at all).
// * mwm_next emits the merged order as run indices; the caller pulls
//   each emitted item from that run's item reader (O(1), no key
//   calls). Optionally it also copies out the winners' key bytes,
//   which the caller needs for splitter partitioning and for writing
//   intermediate merged runs when the merge degree is capped.
// * Comparison is memcmp order over the encoded keys
//   (core/order_key.py guarantees that equals the Python key order),
//   ties broken by run index, so the merge is stable in run order.
//
// A binary heap keyed by (key bytes, run) does the selection; with
// k <= max merge degree (64 by default) that is ~log2(64) = 6 memcmp
// levels per emitted item, all in native code.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct Chunk {
  const int64_t* offs = nullptr;   // n + 1 exclusive offsets
  const uint8_t* blob = nullptr;
  int64_t n = 0;
  int64_t idx = 0;                 // next unconsumed key
  bool final_chunk = false;        // no refill will follow
};

struct Merger {
  explicit Merger(int32_t k) : runs(k), heap() { heap.reserve(k); }
  std::vector<Chunk> runs;
  std::vector<int32_t> heap;       // run indices, heap-ordered
  bool started = false;

  inline const uint8_t* key_ptr(int32_t r, int64_t* len) const {
    const Chunk& c = runs[r];
    *len = c.offs[c.idx + 1] - c.offs[c.idx];
    return c.blob + c.offs[c.idx];
  }

  // (key, run) strict-weak-order: memcmp lexicographic, run id tiebreak
  inline bool less(int32_t a, int32_t b) const {
    int64_t la, lb;
    const uint8_t* pa = key_ptr(a, &la);
    const uint8_t* pb = key_ptr(b, &lb);
    const int64_t m = la < lb ? la : lb;
    const int cmp = m ? std::memcmp(pa, pb, static_cast<size_t>(m)) : 0;
    if (cmp != 0) return cmp < 0;
    if (la != lb) return la < lb;
    return a < b;
  }

  void sift_up(size_t i) {
    while (i > 0) {
      size_t p = (i - 1) / 2;
      if (!less(heap[i], heap[p])) break;
      std::swap(heap[i], heap[p]);
      i = p;
    }
  }

  void sift_down(size_t i) {
    const size_t n = heap.size();
    for (;;) {
      size_t l = 2 * i + 1, r = l + 1, best = i;
      if (l < n && less(heap[l], heap[best])) best = l;
      if (r < n && less(heap[r], heap[best])) best = r;
      if (best == i) return;
      std::swap(heap[i], heap[best]);
      i = best;
    }
  }

  void push(int32_t r) {
    heap.push_back(r);
    sift_up(heap.size() - 1);
  }

  int32_t pop() {
    const int32_t top = heap[0];
    heap[0] = heap.back();
    heap.pop_back();
    if (!heap.empty()) sift_down(0);
    return top;
  }
};

}  // namespace

extern "C" {

void* mwm_create(int32_t k) {
  if (k <= 0) return nullptr;
  return new Merger(k);
}

void mwm_destroy(void* h) { delete static_cast<Merger*>(h); }

// 1 when every run is final and fully consumed (the merge emitted
// everything). Distinguishes "finished" from "out key-blob buffer too
// small for the next key" — both return early from mwm_next.
int32_t mwm_done(void* h) {
  Merger* m = static_cast<Merger*>(h);
  if (!m || !m->started || !m->heap.empty()) return 0;
  for (const Chunk& c : m->runs) {
    if (!c.final_chunk || c.idx != c.n) return 0;
  }
  return 1;
}

// Install run r's next chunk. Only legal before the first mwm_next or
// when mwm_next reported r via *need_refill (i.e. the previous chunk
// is fully consumed). The buffers must stay alive until the next
// set_chunk for r or mwm_destroy. Returns 0, or -1 on bad arguments.
int32_t mwm_set_chunk(void* h, int32_t r, int64_t n, const int64_t* offs,
                      const uint8_t* blob, int32_t final_chunk) {
  Merger* m = static_cast<Merger*>(h);
  if (!m || r < 0 || r >= static_cast<int32_t>(m->runs.size()) || n < 0) {
    return -1;
  }
  Chunk& c = m->runs[r];
  if (c.idx != c.n) return -1;       // previous chunk not consumed
  c.offs = offs;
  c.blob = blob;
  c.n = n;
  c.idx = 0;
  c.final_chunk = final_chunk != 0;
  if (m->started && n > 0) m->push(r);
  return 0;
}

// Emit up to out_cap merged run indices. If out_offs/out_blob are
// non-null, the winners' key bytes are appended there (out_offs gets
// count+1 exclusive offsets; emission stops early if blob_cap would
// overflow). On return *need_refill is the run whose chunk ran dry
// (its next key is unknown — the merge cannot proceed past it), or -1.
// The merge is COMPLETE when the returned count < out_cap and
// *need_refill == -1.
int64_t mwm_next(void* h, uint32_t* out_runs, int64_t out_cap,
                 int32_t* need_refill, int64_t* out_offs,
                 uint8_t* out_blob, int64_t blob_cap) {
  Merger* m = static_cast<Merger*>(h);
  *need_refill = -1;
  if (!m) return -1;
  if (!m->started) {
    m->started = true;
    // Re-entry after an aborted start would re-push runs already in
    // the heap and duplicate rows; start from an empty heap always.
    m->heap.clear();
    for (int32_t r = 0;
         r < static_cast<int32_t>(m->runs.size()); ++r) {
      Chunk& c = m->runs[r];
      if (c.n > 0) {
        m->push(r);
      } else if (!c.final_chunk) {
        *need_refill = r;            // caller must feed every run once
        m->started = false;
        return 0;
      }
    }
  }
  int64_t emitted = 0;
  int64_t blob_used = 0;
  if (out_offs) out_offs[0] = 0;
  while (emitted < out_cap && !m->heap.empty()) {
    const int32_t r = m->heap[0];
    if (out_blob) {
      int64_t klen;
      const uint8_t* kp = m->key_ptr(r, &klen);
      if (blob_used + klen > blob_cap) break;   // caller grows buffer
      std::memcpy(out_blob + blob_used, kp, static_cast<size_t>(klen));
      blob_used += klen;
      out_offs[emitted + 1] = blob_used;
    }
    m->pop();
    out_runs[emitted++] = static_cast<uint32_t>(r);
    Chunk& c = m->runs[r];
    ++c.idx;
    if (c.idx < c.n) {
      m->push(r);
    } else if (!c.final_chunk) {
      *need_refill = r;
      break;
    }
    // final + exhausted: run is done, nothing re-enters the heap
  }
  return emitted;
}
}  // extern "C"