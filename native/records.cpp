// Native columnar spill records: the GIL-free encode/decode engine of
// the out-of-core hot path.
//
// The reference's data plane serializes fixed-size items with a plain
// memcpy and spills them through foxxll's async writer threads — the
// encode itself never contends with compute (thrill/data/
// serialization.hpp:34 POD path, block_writer.hpp:53). The Python
// port's write-behind spill (data/writeback.py) overlapped the disk
// I/O but NOT the encode: the per-run pickle/tuple work in em_sort run
// spilling holds the GIL, so the writer thread and the main thread
// time-slice one interpreter (ROADMAP "Out-of-core tier, remaining
// edges (a)"; PR 13 measured the wall-clock ceiling at ~1.0-1.05x).
//
// This engine is the missing piece: the columnar run state em_sort
// already maintains (a fixed-width key-byte matrix plus fixed-dtype
// payload columns, data/records.py) sorts and encodes HERE, through
// two ctypes entry points that release the GIL for their whole
// duration (ctypes releases it around every foreign call):
//
// * rec_argsort — lexicographic (memcmp) argsort of n fixed-width
//   rows. Rows carry a big-endian position suffix (core/order_key.py),
//   so they are globally unique and any comparison sort yields THE
//   total order; memcmp order equals numpy's S-dtype order (trailing
//   \0 padding is the minimum byte), so the native and numpy engines
//   are interchangeable row for row.
// * rec_gather — gather rows [i0, i1) of a permutation from ncols
//   fixed-width columns into one contiguous column-major output
//   buffer: the payload bytes of one spill block, written straight
//   into the caller-allocated buffer that already holds the block
//   header (data/serializer.py columnar container kind). One pointer
//   handoff per block instead of per-item tuple+pickle work.
//
// Python (data/records.py) owns schemas, headers and block slicing;
// this file owns only bytes — the same split as blockstore.cpp.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 records.cpp -o librecords.so

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>

extern "C" {

// Lexicographic argsort of n rows of w bytes each (memcmp order, row
// index tiebreak). out must hold n int64 slots. Returns 0, -1 on bad
// arguments.
int32_t rec_argsort(const uint8_t* rows, int64_t w, int64_t n,
                    int64_t* out) {
  if (!rows || !out || w <= 0 || n < 0) return -1;
  std::iota(out, out + n, static_cast<int64_t>(0));
  const size_t width = static_cast<size_t>(w);
  std::sort(out, out + n, [rows, width](int64_t a, int64_t b) {
    const int c = std::memcmp(rows + static_cast<size_t>(a) * width,
                              rows + static_cast<size_t>(b) * width,
                              width);
    if (c != 0) return c < 0;
    return a < b;  // rows are unique (pos suffix); keep it total anyway
  });
  return 0;
}

// Gather rows order[i0:i1] from ncols columns (widths[c] bytes per
// row, each column C-contiguous) into out, column-major: col 0's
// gathered rows, then col 1's, ... Returns total bytes written, or -1
// on bad arguments. The caller guarantees order values index every
// column validly.
int64_t rec_gather(int32_t ncols, const uint8_t* const* cols,
                   const int64_t* widths, const int64_t* order,
                   int64_t i0, int64_t i1, uint8_t* out) {
  if (ncols < 0 || !out || i0 < 0 || i1 < i0 ||
      (ncols > 0 && (!cols || !widths || !order))) {
    return -1;
  }
  uint8_t* dst = out;
  for (int32_t c = 0; c < ncols; ++c) {
    const uint8_t* src = cols[c];
    const size_t w = static_cast<size_t>(widths[c]);
    if (!src || widths[c] <= 0) return -1;
    switch (w) {
      case 8:  // the dominant case: int64/float64 scalar columns
        for (int64_t j = i0; j < i1; ++j) {
          std::memcpy(dst, src + static_cast<size_t>(order[j]) * 8, 8);
          dst += 8;
        }
        break;
      default:
        for (int64_t j = i0; j < i1; ++j) {
          std::memcpy(dst, src + static_cast<size_t>(order[j]) * w, w);
          dst += w;
        }
    }
  }
  return static_cast<int64_t>(dst - out);
}

}  // extern "C"
