// Native host-side block store with LRU spill-to-disk.
//
// Equivalent of the reference's data-plane core: ByteBlock/Block
// ref-counted buffers (reference: thrill/data/byte_block.hpp:51,
// block.hpp:52) managed by a BlockPool with soft/hard RAM limits and
// LRU eviction to disk (reference: thrill/data/block_pool.hpp:42, which
// spills through foxxll async I/O). Here the store backs the Python
// data layer through a ctypes interface: Python owns scheduling, C++
// owns bytes — copies, pinning, spill files, and newline scanning for
// the ReadLines byte-range splitter (reference: api/read_lines.hpp:181).
//
// Spills are ASYNCHRONOUS by default: eviction moves the bytes into an
// immutable write request processed by a dedicated writer thread (the
// analog of foxxll's async disk queue / the reference's Dispatcher
// thread, net/dispatcher.hpp:510) — Put/Unpin never block on disk.
// Pin/Get of an in-flight block are served from the request buffer;
// pinning cancels the spill (the writer removes the file post-write).
//
// Build: g++ -O3 -shared -fPIC -std=c++17 blockstore.cpp -o libblockstore.so

#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cctype>
#include <cstring>
#include <deque>
#include <list>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct SpillRequest {
  int64_t id = 0;
  std::vector<char> data;       // owned; IMMUTABLE once enqueued
  std::string path;
  bool cancelled = false;       // guarded by the store mutex
};

struct Block {
  std::vector<char> data;       // empty when spilled or spilling
  std::string spill_path;       // non-empty when on disk
  std::shared_ptr<SpillRequest> req;  // non-null while write in flight
  int64_t size = 0;
  int64_t pin_count = 0;
  std::list<int64_t>::iterator lru_it;
  bool in_lru = false;
};

class BlockStore {
 public:
  BlockStore(std::string spill_dir, int64_t soft_limit, bool async_io)
      : spill_dir_(std::move(spill_dir)), soft_limit_(soft_limit),
        async_(async_io) {
    if (async_) writer_ = std::thread([this] { WriterLoop(); });
  }

  ~BlockStore() {
    if (async_) {
      {
        std::lock_guard<std::mutex> lk(mu_);
        stop_ = true;
      }
      cv_work_.notify_all();
      writer_.join();
    }
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& kv : blocks_) {
      if (!kv.second.spill_path.empty())
        std::remove(kv.second.spill_path.c_str());
    }
  }

  int64_t Put(const void* data, int64_t size) {
    std::lock_guard<std::mutex> lk(mu_);
    int64_t id = next_id_++;
    Block& b = blocks_[id];
    b.size = size;
    b.data.assign(static_cast<const char*>(data),
                  static_cast<const char*>(data) + size);
    mem_usage_ += size;
    Touch(id, b);
    MaybeSpill();
    return id;
  }

  int64_t Size(int64_t id) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = blocks_.find(id);
    return it == blocks_.end() ? -1 : it->second.size;
  }

  // Copy block contents into out (caller allocates Size(id) bytes).
  // Returns 0 on success, -1 unknown id, -2 I/O error.
  int Get(int64_t id, void* out) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = blocks_.find(id);
    if (it == blocks_.end()) return -1;
    Block& b = it->second;
    if (!b.data.empty() || b.size == 0) {
      std::memcpy(out, b.data.data(), b.size);
      Touch(id, b);
      return 0;
    }
    if (b.req) {  // write in flight: serve from the request buffer
      std::memcpy(out, b.req->data.data(), b.size);
      return 0;
    }
    // fault in from disk (stays spilled; read-through)
    FILE* f = std::fopen(b.spill_path.c_str(), "rb");
    if (!f) return -2;
    size_t got = std::fread(out, 1, b.size, f);
    std::fclose(f);
    return got == static_cast<size_t>(b.size) ? 0 : -2;
  }

  // Bring a spilled block back to RAM and keep it there while pinned.
  int Pin(int64_t id) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = blocks_.find(id);
    if (it == blocks_.end()) return -1;
    Block& b = it->second;
    if (b.data.empty() && b.size > 0 && b.req) {
      // cancel the in-flight spill: copy back (the writer may be
      // mid-fwrite from the request buffer, so it cannot be moved)
      b.data = b.req->data;
      b.req->cancelled = true;
      b.req.reset();
      mem_usage_ += b.size;
    } else if (b.data.empty() && b.size > 0) {
      FILE* f = std::fopen(b.spill_path.c_str(), "rb");
      if (!f) return -2;
      b.data.resize(b.size);
      size_t got = std::fread(b.data.data(), 1, b.size, f);
      std::fclose(f);
      if (got != static_cast<size_t>(b.size)) return -2;
      std::remove(b.spill_path.c_str());
      b.spill_path.clear();
      mem_usage_ += b.size;
    }
    b.pin_count++;
    if (b.in_lru) {
      lru_.erase(b.lru_it);
      b.in_lru = false;
    }
    return 0;
  }

  int Unpin(int64_t id) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = blocks_.find(id);
    if (it == blocks_.end()) return -1;
    Block& b = it->second;
    if (b.pin_count > 0) b.pin_count--;
    if (b.pin_count == 0 && !b.data.empty()) Touch(id, b);
    MaybeSpill();
    return 0;
  }

  void Drop(int64_t id) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = blocks_.find(id);
    if (it == blocks_.end()) return;
    Block& b = it->second;
    if (!b.data.empty()) mem_usage_ -= b.size;
    if (b.in_lru) lru_.erase(b.lru_it);
    if (b.req) b.req->cancelled = true;  // writer removes its file
    if (!b.spill_path.empty()) std::remove(b.spill_path.c_str());
    blocks_.erase(it);
  }

  // Is the block servable from RAM (resident, or its spill write is
  // still in flight with the request buffer alive)? 1 = RAM, 0 = a
  // Get would fault in from disk, -1 = unknown id. Drives the
  // surgical merge readahead (data/file.py prefetch_reader): only
  // disk-resident blocks are worth a background fetch.
  int Resident(int64_t id) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = blocks_.find(id);
    if (it == blocks_.end()) return -1;
    Block& b = it->second;
    return (!b.data.empty() || b.size == 0 || b.req) ? 1 : 0;
  }

  int64_t MemUsage() {
    std::lock_guard<std::mutex> lk(mu_);
    return mem_usage_;
  }

  int64_t NumBlocks() {
    std::lock_guard<std::mutex> lk(mu_);
    return static_cast<int64_t>(blocks_.size());
  }

  // Block until every queued/in-flight spill write has completed.
  void Flush() {
    std::unique_lock<std::mutex> lk(mu_);
    cv_idle_.wait(lk, [this] { return queue_.empty() && inflight_ == 0; });
  }

  int64_t Pending() {
    std::lock_guard<std::mutex> lk(mu_);
    return static_cast<int64_t>(queue_.size()) + inflight_;
  }

 private:
  void WriterLoop() {
    for (;;) {
      std::shared_ptr<SpillRequest> req;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_work_.wait(lk, [this] { return stop_ || !queue_.empty(); });
        if (queue_.empty()) return;  // stop_ and drained
        req = queue_.front();
        queue_.pop_front();
        if (req->cancelled) {        // Pin/Drop got there first:
          spilling_bytes_ -= static_cast<int64_t>(req->data.size());
          cv_idle_.notify_all();     // skip the disk write entirely
          continue;
        }
        inflight_++;
      }
      // file write OUTSIDE the lock: the request buffer is immutable
      bool ok = false;
      FILE* f = std::fopen(req->path.c_str(), "wb");
      if (f) {
        size_t put = std::fwrite(req->data.data(), 1, req->data.size(), f);
        std::fclose(f);
        ok = put == req->data.size();
        if (!ok) std::remove(req->path.c_str());
      }
      {
        std::lock_guard<std::mutex> lk(mu_);
        inflight_--;
        spilling_bytes_ -= static_cast<int64_t>(req->data.size());
        auto it = blocks_.find(req->id);
        if (req->cancelled || it == blocks_.end()) {
          if (ok) std::remove(req->path.c_str());
        } else if (ok) {
          it->second.spill_path = req->path;
          it->second.req.reset();
        } else {
          // write failed: restore the bytes to RAM (cannot move — the
          // request may still be aliased; copy like Pin does)
          Block& b = it->second;
          b.data = req->data;
          b.req.reset();
          mem_usage_ += b.size;
          Touch(req->id, b);
        }
        cv_idle_.notify_all();
      }
    }
  }
  void Touch(int64_t id, Block& b) {
    if (b.in_lru) lru_.erase(b.lru_it);
    lru_.push_front(id);
    b.lru_it = lru_.begin();
    b.in_lru = true;
  }

  std::string SpillPath(int64_t victim) {
    // the owning host+pid ride in the name so an external sweeper
    // (data/block_pool.py purge_stale_spills) can reclaim files whose
    // process died without running the destructor (kill -9, abort) —
    // and, on a spill dir shared across hosts, never judge a REMOTE
    // process's file by local pid liveness. Hostname sanitized to
    // [A-Za-z0-9_] so the dash-delimited name stays parseable.
    static const std::string host = [] {
      char h[128] = "unknown";
      gethostname(h, sizeof(h) - 1);
      std::string s(h);
      for (char& c : s)
        if (!std::isalnum(static_cast<unsigned char>(c))) c = '_';
      return s.empty() ? std::string("unknown") : s;
    }();
    char path[512];
    std::snprintf(path, sizeof(path),
                  "%s/ttpu-blk-%lld-%p-%lld-%s.spill",
                  spill_dir_.c_str(),
                  static_cast<long long>(getpid()),
                  static_cast<void*>(this),
                  static_cast<long long>(victim), host.c_str());
    return path;
  }

  void MaybeSpill() {
    bool queued = false;
    while (soft_limit_ > 0 && mem_usage_ > soft_limit_ && !lru_.empty()) {
      int64_t victim = lru_.back();
      lru_.pop_back();
      Block& b = blocks_[victim];
      b.in_lru = false;
      if (b.data.empty() || b.pin_count > 0) continue;
      // bounded write pool (foxxll semantics): async only while the
      // in-flight bytes stay under the budget; past it, spill
      // synchronously — Put/Unpin then block on disk, which is the
      // backpressure that keeps real RSS bounded at ~2x soft_limit
      if (async_ && spilling_bytes_ < soft_limit_) {
        auto req = std::make_shared<SpillRequest>();
        req->id = victim;
        req->data = std::move(b.data);
        req->path = SpillPath(victim);
        b.data.clear();
        b.data.shrink_to_fit();
        b.req = req;
        mem_usage_ -= b.size;
        spilling_bytes_ += b.size;
        queue_.push_back(std::move(req));
        queued = true;
        continue;
      }
      std::string path = SpillPath(victim);
      FILE* f = std::fopen(path.c_str(), "wb");
      if (!f) return;  // cannot spill; keep in RAM
      size_t put = std::fwrite(b.data.data(), 1, b.size, f);
      std::fclose(f);
      if (put != static_cast<size_t>(b.size)) {
        std::remove(path.c_str());
        return;
      }
      b.spill_path = path;
      b.data.clear();
      b.data.shrink_to_fit();
      mem_usage_ -= b.size;
    }
    if (queued) cv_work_.notify_one();
  }

  std::mutex mu_;
  std::condition_variable cv_work_;
  std::condition_variable cv_idle_;
  std::deque<std::shared_ptr<SpillRequest>> queue_;
  std::thread writer_;
  int64_t inflight_ = 0;
  int64_t spilling_bytes_ = 0;  // bytes owned by queued/in-flight writes
  bool stop_ = false;
  std::string spill_dir_;
  int64_t soft_limit_;
  bool async_;
  int64_t next_id_ = 1;
  int64_t mem_usage_ = 0;
  std::unordered_map<int64_t, Block> blocks_;
  std::list<int64_t> lru_;  // front = most recent; only unpinned in-RAM
};

}  // namespace

extern "C" {

void* bs_create(const char* spill_dir, int64_t soft_limit,
                int async_io) {
  return new BlockStore(spill_dir ? spill_dir : "/tmp", soft_limit,
                        async_io != 0);
}

void bs_flush(void* s) { static_cast<BlockStore*>(s)->Flush(); }

int64_t bs_pending(void* s) {
  return static_cast<BlockStore*>(s)->Pending();
}

void bs_destroy(void* s) { delete static_cast<BlockStore*>(s); }

int64_t bs_put(void* s, const void* data, int64_t size) {
  return static_cast<BlockStore*>(s)->Put(data, size);
}

int64_t bs_size(void* s, int64_t id) {
  return static_cast<BlockStore*>(s)->Size(id);
}

int bs_get(void* s, int64_t id, void* out) {
  return static_cast<BlockStore*>(s)->Get(id, out);
}

int bs_pin(void* s, int64_t id) {
  return static_cast<BlockStore*>(s)->Pin(id);
}

int bs_unpin(void* s, int64_t id) {
  return static_cast<BlockStore*>(s)->Unpin(id);
}

void bs_drop(void* s, int64_t id) {
  static_cast<BlockStore*>(s)->Drop(id);
}

int bs_resident(void* s, int64_t id) {
  return static_cast<BlockStore*>(s)->Resident(id);
}

int64_t bs_mem_usage(void* s) {
  return static_cast<BlockStore*>(s)->MemUsage();
}

int64_t bs_num_blocks(void* s) {
  return static_cast<BlockStore*>(s)->NumBlocks();
}

// Scan buf for line-start offsets (byte after each '\n', plus 0).
// Writes up to max_out offsets; returns the number found (clamped).
// Used by the ReadLines range splitter (reference: read_lines.hpp:181).
int64_t bs_scan_lines(const char* buf, int64_t size, int64_t* out,
                      int64_t max_out) {
  int64_t n = 0;
  if (size <= 0) return 0;
  if (max_out > 0) out[n++] = 0;
  const char* p = buf;
  const char* end = buf + size;
  while (p < end && n < max_out) {
    const char* nl = static_cast<const char*>(
        std::memchr(p, '\n', end - p));
    if (!nl) break;
    int64_t off = (nl - buf) + 1;
    if (off < size) out[n++] = off;
    p = nl + 1;
  }
  return n;
}

}  // extern "C"
