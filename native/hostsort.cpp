// Host-side sort kernels for the CPU backend.
//
// The reference framework's local-sort phase runs a tuned host sort
// (sort_algorithm_ = std::sort / tlx radix variants, selected per key
// type). On the CPU backend our "device" buffers are host memory, so
// the same engine choice applies: a stable LSD radix argsort over the
// already-encoded lexicographic uint64 key words, plus a row gather
// for the single payload permutation. On TPU the device engines in
// thrill_tpu/core/device_sort.py run instead; this file is never used
// there.
//
// Layout notes:
// * 16-bit digits: 65536-entry u32 histogram (256 KiB) per pass.
// * Uniform-digit passes are detected from the histogram and skipped
//   (zero-padded packed byte keys make most high/low passes uniform).
// * Stability comes from the counting scatter being order-preserving;
//   the caller needs no tie-break iota word.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int kDigitBits = 16;
constexpr uint32_t kBuckets = 1u << kDigitBits;
constexpr uint64_t kDigitMask = kBuckets - 1;

}  // namespace

extern "C" {

// Stable argsort of n items keyed lexicographically by nwords uint64
// words (words[w][i]; w = 0 is the MOST significant word). On return
// perm_out[r] = original index of the r-th smallest item. Returns the
// number of counting passes actually performed (>= 0), or -1 on bad
// arguments.
int radix_argsort_u64(int64_t n, int32_t nwords, const uint64_t** words,
                      uint32_t* perm_out) {
  if (n < 0 || nwords <= 0 || n > static_cast<int64_t>(UINT32_MAX)) {
    return -1;
  }
  std::vector<uint32_t> tmp(static_cast<size_t>(n));
  std::vector<uint32_t> hist(kBuckets);
  uint32_t* cur = perm_out;
  uint32_t* alt = tmp.data();
  for (int64_t i = 0; i < n; ++i) cur[i] = static_cast<uint32_t>(i);

  int passes = 0;
  // least-significant word first, least-significant digit first
  for (int w = nwords - 1; w >= 0; --w) {
    const uint64_t* col = words[w];
    for (int shift = 0; shift < 64; shift += kDigitBits) {
      std::memset(hist.data(), 0, kBuckets * sizeof(uint32_t));
      for (int64_t i = 0; i < n; ++i) {
        ++hist[(col[cur[i]] >> shift) & kDigitMask];
      }
      // skip uniform passes (common: zero-padded key bytes)
      if (n > 0 && hist[(col[cur[0]] >> shift) & kDigitMask] ==
                       static_cast<uint32_t>(n)) {
        continue;
      }
      uint32_t sum = 0;
      for (uint32_t b = 0; b < kBuckets; ++b) {
        uint32_t c = hist[b];
        hist[b] = sum;
        sum += c;
      }
      for (int64_t i = 0; i < n; ++i) {
        uint32_t idx = cur[i];
        alt[hist[(col[idx] >> shift) & kDigitMask]++] = idx;
      }
      std::swap(cur, alt);
      ++passes;
    }
  }
  if (cur != perm_out) {
    std::memcpy(perm_out, cur, static_cast<size_t>(n) * sizeof(uint32_t));
  }
  return passes;
}

// dst row r = src row perm[r]; rows are row_bytes wide.
void gather_rows_u8(int64_t n, int64_t row_bytes, const uint8_t* src,
                    const uint32_t* perm, uint8_t* dst) {
  switch (row_bytes) {
    case 1: {
      for (int64_t r = 0; r < n; ++r) dst[r] = src[perm[r]];
      return;
    }
    case 2: {
      const uint16_t* s = reinterpret_cast<const uint16_t*>(src);
      uint16_t* d = reinterpret_cast<uint16_t*>(dst);
      for (int64_t r = 0; r < n; ++r) d[r] = s[perm[r]];
      return;
    }
    case 4: {
      const uint32_t* s = reinterpret_cast<const uint32_t*>(src);
      uint32_t* d = reinterpret_cast<uint32_t*>(dst);
      for (int64_t r = 0; r < n; ++r) d[r] = s[perm[r]];
      return;
    }
    case 8: {
      const uint64_t* s = reinterpret_cast<const uint64_t*>(src);
      uint64_t* d = reinterpret_cast<uint64_t*>(dst);
      for (int64_t r = 0; r < n; ++r) d[r] = s[perm[r]];
      return;
    }
    default: {
      for (int64_t r = 0; r < n; ++r) {
        std::memcpy(dst + r * row_bytes,
                    src + static_cast<int64_t>(perm[r]) * row_bytes,
                    static_cast<size_t>(row_bytes));
      }
    }
  }
}
}  // extern "C"
