// Host-side sort kernels for the CPU backend.
//
// The reference framework's local-sort phase runs a tuned host sort
// (sort_algorithm_ = std::sort / tlx radix variants, selected per key
// type). On the CPU backend our "device" buffers are host memory, so
// the same engine choice applies: a stable LSD radix argsort over the
// already-encoded lexicographic uint64 key words, plus a row gather
// for the single payload permutation. On TPU the device engines in
// thrill_tpu/core/device_sort.py run instead; this file is never used
// there.
//
// Layout notes:
// * 16-bit digits: 65536-entry u32 histogram (256 KiB) per pass.
// * Uniform-digit passes are detected from the histogram and skipped
//   (zero-padded packed byte keys make most high/low passes uniform).
// * Stability comes from the counting scatter being order-preserving;
//   the caller needs no tie-break iota word.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int kDigitBits = 16;
constexpr uint32_t kBuckets = 1u << kDigitBits;
constexpr uint64_t kDigitMask = kBuckets - 1;

}  // namespace

extern "C" {

// Stable argsort of n items keyed lexicographically by nwords uint64
// words (words[w][i]; w = 0 is the MOST significant word). On return
// perm_out[r] = original index of the r-th smallest item. Returns the
// number of counting passes actually performed (>= 0), or -1 on bad
// arguments.
int radix_argsort_u64(int64_t n, int32_t nwords, const uint64_t** words,
                      uint32_t* perm_out) {
  if (n < 0 || nwords <= 0 || n > static_cast<int64_t>(UINT32_MAX)) {
    return -1;
  }
  std::vector<uint32_t> tmp(static_cast<size_t>(n));
  std::vector<uint32_t> hist(kBuckets);
  uint32_t* cur = perm_out;
  uint32_t* alt = tmp.data();
  for (int64_t i = 0; i < n; ++i) cur[i] = static_cast<uint32_t>(i);

  int passes = 0;
  // least-significant word first, least-significant digit first
  for (int w = nwords - 1; w >= 0; --w) {
    const uint64_t* col = words[w];
    for (int shift = 0; shift < 64; shift += kDigitBits) {
      std::memset(hist.data(), 0, kBuckets * sizeof(uint32_t));
      for (int64_t i = 0; i < n; ++i) {
        ++hist[(col[cur[i]] >> shift) & kDigitMask];
      }
      // skip uniform passes (common: zero-padded key bytes)
      if (n > 0 && hist[(col[cur[0]] >> shift) & kDigitMask] ==
                       static_cast<uint32_t>(n)) {
        continue;
      }
      uint32_t sum = 0;
      for (uint32_t b = 0; b < kBuckets; ++b) {
        uint32_t c = hist[b];
        hist[b] = sum;
        sum += c;
      }
      for (int64_t i = 0; i < n; ++i) {
        uint32_t idx = cur[i];
        alt[hist[(col[idx] >> shift) & kDigitMask]++] = idx;
      }
      std::swap(cur, alt);
      ++passes;
    }
  }
  if (cur != perm_out) {
    std::memcpy(perm_out, cur, static_cast<size_t>(n) * sizeof(uint32_t));
  }
  return passes;
}

// dst row r = src row perm[r]; rows are row_bytes wide. Fixed-size
// cases use memcpy loads/stores (compilers emit the single mov either
// way) so contiguous-but-misaligned buffers are not UB.
void gather_rows_u8(int64_t n, int64_t row_bytes, const uint8_t* src,
                    const uint32_t* perm, uint8_t* dst) {
  switch (row_bytes) {
    case 1: {
      for (int64_t r = 0; r < n; ++r) dst[r] = src[perm[r]];
      return;
    }
    case 2: {
      for (int64_t r = 0; r < n; ++r) {
        uint16_t v;
        std::memcpy(&v, src + static_cast<int64_t>(perm[r]) * 2, 2);
        std::memcpy(dst + r * 2, &v, 2);
      }
      return;
    }
    case 4: {
      for (int64_t r = 0; r < n; ++r) {
        uint32_t v;
        std::memcpy(&v, src + static_cast<int64_t>(perm[r]) * 4, 4);
        std::memcpy(dst + r * 4, &v, 4);
      }
      return;
    }
    case 8: {
      for (int64_t r = 0; r < n; ++r) {
        uint64_t v;
        std::memcpy(&v, src + static_cast<int64_t>(perm[r]) * 8, 8);
        std::memcpy(dst + r * 8, &v, 8);
      }
      return;
    }
    default: {
      for (int64_t r = 0; r < n; ++r) {
        std::memcpy(dst + r * row_bytes,
                    src + static_cast<int64_t>(perm[r]) * row_bytes,
                    static_cast<size_t>(row_bytes));
      }
    }
  }
}

// dst row idx[r] = src row r (inverse of gather_rows_u8). Same memcpy
// discipline for the fixed-size fast paths.
void scatter_rows_u8(int64_t n, int64_t row_bytes, const uint8_t* src,
                     const uint32_t* idx, uint8_t* dst) {
  switch (row_bytes) {
    case 1: {
      for (int64_t r = 0; r < n; ++r) dst[idx[r]] = src[r];
      return;
    }
    case 2: {
      for (int64_t r = 0; r < n; ++r) {
        uint16_t v;
        std::memcpy(&v, src + r * 2, 2);
        std::memcpy(dst + static_cast<int64_t>(idx[r]) * 2, &v, 2);
      }
      return;
    }
    case 4: {
      for (int64_t r = 0; r < n; ++r) {
        uint32_t v;
        std::memcpy(&v, src + r * 4, 4);
        std::memcpy(dst + static_cast<int64_t>(idx[r]) * 4, &v, 4);
      }
      return;
    }
    case 8: {
      for (int64_t r = 0; r < n; ++r) {
        uint64_t v;
        std::memcpy(&v, src + r * 8, 8);
        std::memcpy(dst + static_cast<int64_t>(idx[r]) * 8, &v, 8);
      }
      return;
    }
    default: {
      for (int64_t r = 0; r < n; ++r) {
        std::memcpy(dst + static_cast<int64_t>(idx[r]) * row_bytes,
                    src + r * row_bytes,
                    static_cast<size_t>(row_bytes));
      }
    }
  }
}

namespace {

// splitmix64 finalizer: the per-word mixer for the grouping table.
inline uint64_t mix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

// Open-addressing (linear probe) find-or-insert keyed by exact
// equality of nwords uint64 key words, shared by hash_group_u64 and
// hash_group_acc_u64 so the probing scheme cannot diverge between the
// grouped and fused engines. Sized once for a known max row count
// (load factor <= 0.5, so the linear probe terminates).
struct GroupTable {
  std::vector<uint32_t> head_plus1;  // original row index + 1; 0 empty
  std::vector<uint32_t> slot_gid;
  const uint64_t** words;
  int32_t nwords;
  size_t mask;
  uint32_t ngroups = 0;

  GroupTable(int64_t n, int32_t nw, const uint64_t** w)
      : words(w), nwords(nw) {
    size_t tsize = 16;
    while (tsize < static_cast<size_t>(n) * 2) tsize <<= 1;
    mask = tsize - 1;
    head_plus1.assign(tsize, 0);
    slot_gid.resize(tsize);
  }

  // Returns the row's group id; *is_new reports whether row i opened
  // the group (i becomes its head row).
  inline uint32_t find_or_insert(int64_t i, bool* is_new) {
    uint64_t h = 0;
    for (int32_t w = 0; w < nwords; ++w) h = mix64(h ^ words[w][i]);
    size_t s = static_cast<size_t>(h) & mask;
    for (;;) {
      const uint32_t hp = head_plus1[s];
      if (hp == 0) {
        head_plus1[s] = static_cast<uint32_t>(i) + 1;
        slot_gid[s] = ngroups;
        *is_new = true;
        return ngroups++;
      }
      const uint32_t head = hp - 1;
      bool eq = true;
      for (int32_t w = 0; w < nwords; ++w) {
        if (words[w][head] != words[w][i]) {
          eq = false;
          break;
        }
      }
      if (eq) {
        *is_new = false;
        return slot_gid[s];
      }
      s = (s + 1) & mask;
    }
  }
};

}  // namespace

// Group n rows by EXACT equality of their nwords uint64 key words via
// an open-addressing (linear probe) hash table with full-key compare —
// the host-native analog of the reference's ReducePrePhase probing
// tables (thrill/core/reduce_pre_phase.hpp:94). Collisions are
// resolved by comparing every key word, so the grouping is exact for
// any key distribution.
//
// Outputs:
//   perm_out[n]   — row indices clustered group-by-group (groups in
//                   first-appearance order; original order kept WITHIN
//                   a group, so non-commutative folds stay correct)
//   lens_out[<=n] — rows per group
// Returns the number of groups, or -1 on bad arguments.
//
// Cost model vs the radix argsort above: one pass with ~1 probe per
// row. Live table entries (one per DISTINCT key) cluster in cache, so
// skewed key sets (the WordCount case) probe mostly L1/L2 instead of
// paying 4+ full counting passes.
int64_t hash_group_u64(int64_t n, int32_t nwords, const uint64_t** words,
                       uint32_t* perm_out, uint32_t* lens_out) {
  if (n < 0 || nwords <= 0 || n > static_cast<int64_t>(UINT32_MAX)) {
    return -1;
  }
  if (n == 0) return 0;
  GroupTable table(n, nwords, words);
  std::vector<uint32_t> gids(static_cast<size_t>(n));
  std::vector<uint32_t> counts;
  counts.reserve(1024);
  for (int64_t i = 0; i < n; ++i) {
    bool is_new;
    const uint32_t g = table.find_or_insert(i, &is_new);
    gids[i] = g;
    if (is_new) {
      counts.push_back(1);
    } else {
      ++counts[g];
    }
  }
  const int64_t ngroups = static_cast<int64_t>(counts.size());
  std::vector<uint32_t> off(counts.size());
  uint32_t sum = 0;
  for (int64_t g = 0; g < ngroups; ++g) {
    off[g] = sum;
    sum += counts[g];
    lens_out[g] = counts[g];
  }
  for (int64_t i = 0; i < n; ++i) {
    perm_out[off[gids[i]]++] = static_cast<uint32_t>(i);
  }
  return ngroups;
}

// Fused variant of hash_group_u64 for DECLARATIVE reduce functors
// (api/functors.py FieldReduce): the value columns are accumulated
// into the table during the single probe pass, which is the runtime
// analog of the reference's C++ templates inlining the reduce functor
// into the probing-table insert (thrill/core/reduce_pre_phase.hpp:94,
// reduce_functional.hpp). No permutation, gather, or fold pass exists
// afterwards — the output is one row per group.
//
// col_ops[c] selects the accumulator for value column c (all columns
// are 8-byte scalars, pre-converted by the caller):
//   0 sum_i64 (two's-complement: also exact mod-2^64 for uint64)
//   1 min_i64   2 max_i64
//   3 sum_f64   4 min_f64 (NaN propagates, numpy-parity)
//   5 max_f64 (NaN propagates)
//   6 min_u64   7 max_u64
// acc_out[c] (capacity n rows) receives ngroups accumulated values;
// heads_out[g] = original row index of group g's FIRST row (for
// "first" columns the caller gathers those rows). Returns ngroups or
// -1 on bad arguments.
int64_t hash_group_acc_u64(int64_t n, int32_t nwords,
                           const uint64_t** words, int32_t ncols,
                           const int32_t* col_ops, const void** cols,
                           void** acc_out, uint32_t* heads_out) {
  if (n < 0 || nwords <= 0 || ncols < 0 ||
      n > static_cast<int64_t>(UINT32_MAX)) {
    return -1;
  }
  for (int32_t c = 0; c < ncols; ++c) {
    if (col_ops[c] < 0 || col_ops[c] > 7) return -1;
  }
  if (n == 0) return 0;
  GroupTable table(n, nwords, words);
  for (int64_t i = 0; i < n; ++i) {
    bool is_new;
    const int64_t g = table.find_or_insert(i, &is_new);
    if (is_new) {
      heads_out[g] = static_cast<uint32_t>(i);
      for (int32_t c = 0; c < ncols; ++c) {
        std::memcpy(static_cast<uint8_t*>(acc_out[c]) + g * 8,
                    static_cast<const uint8_t*>(cols[c]) + i * 8, 8);
      }
      continue;
    }
    for (int32_t c = 0; c < ncols; ++c) {
      uint8_t* ap = static_cast<uint8_t*>(acc_out[c]) + g * 8;
      const uint8_t* vp = static_cast<const uint8_t*>(cols[c]) + i * 8;
      switch (col_ops[c]) {
        case 0: {  // sum_i64
          int64_t a, v;
          std::memcpy(&a, ap, 8);
          std::memcpy(&v, vp, 8);
          a = static_cast<int64_t>(static_cast<uint64_t>(a) +
                                   static_cast<uint64_t>(v));
          std::memcpy(ap, &a, 8);
          break;
        }
        case 1: case 2: {  // min_i64 / max_i64
          int64_t a, v;
          std::memcpy(&a, ap, 8);
          std::memcpy(&v, vp, 8);
          if (col_ops[c] == 1 ? (v < a) : (v > a)) std::memcpy(ap, &v, 8);
          break;
        }
        case 3: {  // sum_f64
          double a, v;
          std::memcpy(&a, ap, 8);
          std::memcpy(&v, vp, 8);
          a += v;
          std::memcpy(ap, &a, 8);
          break;
        }
        case 4: case 5: {  // min_f64 / max_f64, NaN propagates
          double a, v;
          std::memcpy(&a, ap, 8);
          std::memcpy(&v, vp, 8);
          if (a != a) break;           // acc already NaN
          if (v != v || (col_ops[c] == 4 ? (v < a) : (v > a))) {
            std::memcpy(ap, &v, 8);
          }
          break;
        }
        case 6: case 7: {  // min_u64 / max_u64
          uint64_t a, v;
          std::memcpy(&a, ap, 8);
          std::memcpy(&v, vp, 8);
          if (col_ops[c] == 6 ? (v < a) : (v > a)) std::memcpy(ap, &v, 8);
          break;
        }
      }
    }
  }
  return static_cast<int64_t>(table.ngroups);
}

// Plan for the strided in-place run fold over group-contiguous rows
// (see thrill_tpu/api/ops/reduce.py:_strided_run_fold). Row at in-run
// position p > 0 is absorbed exactly once, at step s = p & -p, into
// the row s slots to its left; this emits the absorbed (right-operand)
// GLOBAL row indices bucketed by level l = ctz(p), ascending within a
// level. level_counts_out must hold 32 slots. Returns the total number
// of emitted indices (== sum(lens) - ngroups).
int64_t fold_plan_u32(int64_t ngroups, const uint32_t* lens,
                      uint32_t* ri_out, int64_t* level_counts_out) {
  for (int l = 0; l < 32; ++l) level_counts_out[l] = 0;
  for (int64_t g = 0; g < ngroups; ++g) {
    for (uint32_t p = 1; p < lens[g]; ++p) {
      ++level_counts_out[__builtin_ctz(p)];
    }
  }
  int64_t off[32];
  int64_t sum = 0;
  for (int l = 0; l < 32; ++l) {
    off[l] = sum;
    sum += level_counts_out[l];
  }
  uint32_t start = 0;
  for (int64_t g = 0; g < ngroups; ++g) {
    const uint32_t len = lens[g];
    for (uint32_t p = 1; p < len; ++p) {
      ri_out[off[__builtin_ctz(p)]++] = start + p;
    }
    start += len;
  }
  return sum;
}
}  // extern "C"
