"""Distributed duplicate detection for reduce shuffles.

Equivalent of the reference's DuplicateDetection
(reference: thrill/core/duplicate_detection.hpp:46): workers exchange
Golomb-coded sorted hash lists of their keys; hashes seen by exactly
one worker are *globally unique* — their items cannot combine with
anything remote, so ReduceByKey can skip shuffling them (a large win
when most keys are unique, e.g. WordCount over natural text).
"""

from __future__ import annotations

from typing import Iterable, List, Set

import numpy as np

from .location_detection import (decode_fingerprint, encode_fingerprint,
                                 fingerprint, _MASK)


def find_non_unique_hashes(per_worker_hashes: List[Iterable[int]]
                           ) -> Set[int]:
    """Hashes appearing on >= 2 workers (these must be shuffled)."""
    seen: dict = {}
    for w, hashes in enumerate(per_worker_hashes):
        msg = encode_fingerprint(fingerprint(hashes))
        for h in decode_fingerprint(msg):
            h = int(h)
            seen[h] = seen.get(h, 0) + 1
    return {h for h, c in seen.items() if c >= 2}


def is_unique(h: int, non_unique: Set[int]) -> bool:
    return (h & _MASK) not in non_unique
