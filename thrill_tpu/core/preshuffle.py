"""Plan-time pre-shuffle reduction decisions.

The reference enables LocationDetection / DuplicateDetection by opt-in
template tags (reference: api/inner_join.hpp:161-190 LocationDetectionTag,
api/reduce_by_key.hpp DuplicateDetectionTag) — the caller must know the
workload. Here both become COST-MODEL decisions made at plan time, on by
default whenever the model says the fingerprint traffic is cheaper than
the rows it is expected to prune:

    est_pruned_row_bytes  >  margin * est_fingerprint_bytes

* est_pruned_row_bytes: global row estimate x item bytes x the expected
  prune fraction x the off-diagonal share (W-1)/W. The row estimate
  prefers exact counts (host-known), then the LEARNED per-site padded
  capacities the capacity-plan cache recorded for this site's exchanges
  (data/exchange.py _sticky_caps — the PR 6 machinery), then the padded
  capacity upper bound. The prune fraction starts at a neutral default
  and is refined per site from observed pre/post counts when a pipeline
  happens to expose them (no syncs are ever added to learn it).
* est_fingerprint_bytes: the presence registers crossing the fabric —
  sides x M bytes (u8 registers; core register width adapts to the row
  estimate, clamped so small joins pay kilobytes and large joins stop
  growing at the point false positives are already rare).

Decisions are STICKY per (mesh, site): flipping mid-run would recompile
the destination programs for nothing. Env overrides force either way:
THRILL_TPU_LOCATION_DETECT=0/1 and THRILL_TPU_DUP_DETECT=0/1 (unset =
auto). Multi-controller runs AGREE the decision inputs over the host
control plane (local counts all-reduce to the global sum, learned
fractions to their mean) before deciding, so every controller computes
the same verdict; only meshes WITHOUT a spanning host control plane
still resolve auto to OFF (a per-process flip would desync the
collective schedule). With the adaptive planner attached
(api/planner.py) the verdict is the planner's — the same inequality,
owned by the one cost model — and an audited prune fraction that
contradicts the prediction re-evaluates the verdict immediately
instead of waiting out the periodic resync window.

Register fingerprints are PLAN traffic, like the send-count all_gather:
they are deliberately not counted in ``bytes_on_wire`` (which measures
the exchange data plane), but the cost model weighs them all the same.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

from ..common.config import round_up_pow2

# register-width clamps: below the floor the pmax/psum launch overhead
# dominates anyway; above the ceiling false positives are already rare
# (M >= 8x rows -> <~12% spurious keeps) and the register cost would
# keep growing linearly for no pruning gain
_REG_MIN = 1 << 12
_REG_MAX = 1 << 17

# expected prune fraction before a site has taught us anything: half
# the rows neither match (join) nor collide remotely (reduce) — the
# neutral prior between WordCount-like (mostly unique) and dense-join
# workloads
_DEFAULT_PRUNE_FRAC = 0.5

# enable only when the expected pruned bytes clear the fingerprint
# cost by this factor (the filter also costs a dispatch; narrow wins
# are not worth the program-cache entry)
_MARGIN = 2.0


def _env_mode(name: str) -> Optional[bool]:
    v = os.environ.get(name)
    if v in (None, "", "auto"):
        return None
    return v not in ("0", "off", "false")


def _planner_of(mex):
    """The mesh's adaptive planner (api/planner.py) when live, else
    None — attribute reads only, the ledger_of pattern."""
    pl = getattr(mex, "planner", None)
    if pl is not None and pl.enabled:
        return pl
    return None


def _agree_net(mex):
    """The host control plane when it actually spans this mesh's
    controllers (ctx.net, wired as ``mex.host_net``), else None — the
    gate for cross-controller agreement of decision inputs."""
    net = getattr(mex, "host_net", None)
    if net is None:
        return None
    if getattr(net, "num_workers", 1) != getattr(mex, "num_processes",
                                                 1):
        return None
    return net


def _agreed_rows(mex, rows: int, local_rows: bool) -> Optional[int]:
    """Cross-controller agreement of the cost model's row estimate:
    LOCAL counts (host-storage paths hold only their own workers'
    items) all-reduce by SUM into the global count; nominally-global
    estimates all-reduce by MAX (defensive: every rank then provably
    decides from one number). None = no host control plane — the
    caller must resolve OFF, a per-process flip would desync the
    collective schedule. This is a COLLECTIVE: it runs only inside
    the sticky decision's (lockstep) compute/resync."""
    net = _agree_net(mex)
    if net is None:
        return None
    op = (lambda a, b: a + b) if local_rows else max
    return int(net.all_reduce(int(rows), op))


def _agreed_fraction(mex, frac: float) -> float:
    """Cross-controller mean of the learned prune fraction (fractions
    are learned rank-locally; the mean is deterministic and identical
    on every rank). Callers hold a live ``_agree_net``."""
    net = _agree_net(mex)
    if net is None:
        return frac
    vals = [float(v) for v in net.all_gather(float(frac))]
    return sum(vals) / len(vals)


def location_mode() -> Optional[bool]:
    """THRILL_TPU_LOCATION_DETECT: 1 forces the join location filter
    on, 0 off, unset/auto defers to the cost model."""
    return _env_mode("THRILL_TPU_LOCATION_DETECT")


def dup_mode() -> Optional[bool]:
    """THRILL_TPU_DUP_DETECT: 1 forces ReduceByKey duplicate detection
    on, 0 off, unset/auto defers to the cost model."""
    return _env_mode("THRILL_TPU_DUP_DETECT")


def register_width(est_rows: int) -> int:
    """Presence-register count adapted to the global row estimate."""
    return max(_REG_MIN, min(_REG_MAX,
                             round_up_pow2(8 * max(int(est_rows), 1))))


def record_prune(mex, token, pre_rows: int, post_rows: int) -> None:
    """Teach the site its observed prune fraction (called only where
    both counts are already host-known — learning never adds a sync).
    This is also the prune decision's audit-join point: the fraction
    the cost model predicted meets the fraction the filter actually
    removed (common/decisions.py)."""
    if pre_rows <= 0:
        return
    hist = getattr(mex, "_prune_history", None)
    if hist is None:
        hist = mex._prune_history = {}
    frac = max(0.0, min(1.0, 1.0 - post_rows / pre_rows))
    from ..common import decisions as _decisions
    led = _decisions.ledger_of(mex)
    if led is not None:
        led.resolve_site("prune", _prune_site(token), max(frac, 1e-6))
    prev = hist.get(token)
    hist[token] = frac if prev is None else 0.5 * (prev + frac)


def prune_fraction(mex, token) -> float:
    hist = getattr(mex, "_prune_history", None)
    if hist is None:
        hist = mex._prune_history = {}
    frac = hist.get(token)
    if frac is None:
        # warm restart: the plan store remembers what fraction this
        # site's filter pruned in past runs (service/plan_store.py)
        from ..data.exchange import plan_seed
        v = plan_seed(mex, "prune_history", token)
        if v is not None:
            try:
                frac = hist[token] = max(0.0, min(1.0, float(v)))
            except (TypeError, ValueError):
                frac = None
    return _DEFAULT_PRUNE_FRAC if frac is None else frac


def learned_site_rows(mex, xchg_ident) -> Optional[int]:
    """Best learned output capacity of the exchange site ``xchg_ident``
    (the capacity-plan cache's sticky caps, data/exchange.py): what PR 6
    already knows about this site's steady-state row volume."""
    caps = getattr(mex, "_sticky_caps", None)
    if not caps:
        return None
    best = None
    for key, v in caps.items():
        if (isinstance(key, tuple) and len(key) >= 2
                and key[0] == "xchg_caps" and key[1] == xchg_ident
                and len(v) == 2):
            best = max(best or 0, int(v[1]))
    return best


# every Nth use of a site's remembered verdict re-runs the cost model,
# so the prune fraction LEARNED after the first decision (record_prune)
# actually gets a vote — the same periodic-resync pattern the exchange
# capacity cache uses. A flip costs one extra program compile, bounded
# by the re-evaluation period.
_DECIDE_RESYNC_EVERY = 16


def _decay_fraction(mex, token) -> None:
    """Pull a site's learned prune fraction halfway back toward the
    neutral prior. Observations only arrive while the filter RUNS
    (record_prune reads counts the filter path exposes) — without
    decay, a site whose verdict flipped OFF would re-evaluate forever
    on its frozen last fraction and never probe pruning again even if
    the workload turned prunable."""
    hist = getattr(mex, "_prune_history", None)
    if hist and token in hist:
        hist[token] = 0.5 * (hist[token] + _DEFAULT_PRUNE_FRAC)


def _sticky_decision(mex, kind: str, token, compute) -> bool:
    from ..data.exchange import count_plan_build, plan_seed
    store = getattr(mex, "_prune_decisions", None)
    if store is None:
        store = mex._prune_decisions = {}
    key = (kind, token)
    entry = store.get(key)
    if entry is None:
        seeded = plan_seed(mex, "prune_decisions", key)
        if seeded is not None:
            # warm restart: the remembered verdict, no cost-model run.
            # Correctness-neutral either way — pruning filters are
            # exact; a stale verdict costs performance until the
            # periodic resync below re-evaluates it.
            entry = (bool(seeded), 1)
        else:
            count_plan_build(mex)
            entry = (bool(compute()), 1)
    else:
        verdict, uses = entry
        # replan marks are RANK-LOCAL (an audit's observed fraction
        # derives from per-rank counts on the host paths), so honoring
        # one on a multi-controller mesh could send a single rank into
        # the agreement collectives inside compute() while its peers
        # return the cached verdict — the exact desync the lockstep
        # periodic resync below avoids (every rank re-evaluates at the
        # same use count). Multi-controller lies wait for the resync.
        pl = _planner_of(mex) \
            if getattr(mex, "num_processes", 1) == 1 else None
        why = pl.take_replan(_prune_site(token)) if pl is not None \
            else None
        if why is not None:
            # audit-driven re-optimization (api/planner.py): the
            # observed prune fraction contradicted the prediction by
            # more than the threshold — re-evaluate NOW from the
            # freshly observed fraction (record_prune already folded
            # it in; no decay, this is a correction not a probe)
            # instead of riding the stale verdict out to the periodic
            # resync window
            count_plan_build(mex)
            new = bool(compute())
            if new != verdict:
                pl.note_switch()
                from ..common import decisions as _decisions
                pl.record_replan(
                    _decisions.ledger_of(mex), _prune_site(token),
                    f"{kind}:{'on' if new else 'off'}",
                    predicted=None,
                    rejected=[(f"{kind}:{'on' if verdict else 'off'}",
                               None)],
                    reason=why)
            verdict = new
        elif uses % _DECIDE_RESYNC_EVERY == 0:
            _decay_fraction(mex, token)
            count_plan_build(mex)
            verdict = bool(compute())
        entry = (verdict, uses + 1)
    store[key] = entry
    return entry[0]


# -- plan-state persistence (service/plan_store.py) --------------------

def export_plan_state(mex) -> dict:
    """Pre-shuffle verdicts and learned prune fractions as digest maps
    (the plan store's on-disk form; keys digest like the exchange
    plan state — data/exchange.py _ident_digest)."""
    from ..data.exchange import _ident_digest, merge_unconsumed_seeds
    return merge_unconsumed_seeds(mex, {
        "prune_decisions": {
            _ident_digest(k): bool(v[0])
            for k, v in getattr(mex, "_prune_decisions", {}).items()},
        "prune_history": {
            _ident_digest(k): float(v)
            for k, v in getattr(mex, "_prune_history", {}).items()},
    })


def import_plan_state(mex, state: dict, *,
                      symmetric: bool = False) -> int:
    """Install pre-shuffle seeds into the shared ``mex._plan_seed``
    table (consumed lazily by the lookup helpers above)."""
    from ..data.exchange import install_plan_seeds
    return install_plan_seeds(
        mex, state, ("prune_decisions", "prune_history"),
        symmetric=symmetric)


def _pays(rows: int, item_bytes: int, W: int, sides: int, M: int,
          frac: float) -> bool:
    pruned, fingerprint = _pays_est(rows, item_bytes, W, sides, M,
                                    frac)
    if W <= 1 or rows <= 0:
        return False
    return pruned > _MARGIN * fingerprint


def _pays_est(rows: int, item_bytes: int, W: int, sides: int, M: int,
              frac: float) -> Tuple[float, float]:
    """(est_pruned_row_bytes, est_fingerprint_bytes): the two sides of
    the pre-shuffle cost inequality — what the decision ledger records
    as the verdict's inputs."""
    pruned = max(rows, 0) * item_bytes * frac * max(W - 1, 0) / max(W, 1)
    fingerprint = sides * M                     # u8 registers
    return pruned, fingerprint


def _prune_site(token) -> str:
    from ..data.exchange import _ident_digest
    return "prune:" + _ident_digest(token)[:10]


def _record_verdict(mex, which: str, token, verdict: bool,
                    rows: int, item_bytes: int, sides: int,
                    frac: Optional[float],
                    reason: str) -> bool:
    """Ledger entry for one prune verdict (location/dup): the chosen
    alternative, the rejected one's estimated cost, and the predicted
    prune fraction — kept open for record_prune's audit join."""
    from ..common import decisions as _decisions
    led = _decisions.ledger_of(mex)
    if led is not None:
        W = getattr(mex, "num_workers", 1)
        M = register_width(rows)
        pruned, fp = _pays_est(rows, item_bytes, W, sides, M,
                               frac if frac is not None else 0.0)
        chosen = f"{which}:on" if verdict else f"{which}:off"
        other = f"{which}:off" if verdict else f"{which}:on"
        led.record("prune", _prune_site(token), chosen,
                   predicted=frac, join=frac is not None,
                   rejected=[(other, fp if verdict else pruned)],
                   reason=reason, rows=int(rows), unit="frac",
                   est_pruned_bytes=int(pruned),
                   est_fingerprint_bytes=int(fp))
    return verdict


def _auto_verdict(mex, which: str, kind: str, token, rows_global: int,
                  item_bytes: int, sides: int,
                  local_rows: bool) -> bool:
    """Shared sticky cost-model verdict for both pre-shuffle filters.

    Multi-controller runs AGREE the decision inputs over the host
    control plane before deciding (ROADMAP "globally-agreed pruning
    inputs"): local row counts all-reduce to the global count, learned
    fractions to their mean — every controller then provably computes
    the same verdict from the same numbers, so ``auto`` no longer has
    to resolve OFF. The OFF fallback remains ONLY for meshes without a
    spanning host control plane (a per-process flip would desync the
    collective schedule). The agreement collective runs only inside
    the sticky decision's lockstep compute/resync, never per call."""
    def compute():
        W = mex.num_workers
        rows = rows_global
        why = "cost model"
        if getattr(mex, "num_processes", 1) > 1:
            agreed = _agreed_rows(mex, rows, local_rows)
            if agreed is None:
                return _record_verdict(
                    mex, which, token, False, rows, item_bytes, sides,
                    None, "multi-controller: no host control plane to "
                          "agree decision inputs")
            rows = agreed
            frac = _agreed_fraction(mex, prune_fraction(mex, token))
            why = "cost model (inputs agreed across controllers)"
        else:
            frac = prune_fraction(mex, token)
        M = register_width(rows)
        pl = _planner_of(mex)
        verdict = (pl.prune_verdict(rows, item_bytes, W, sides, M,
                                    frac)
                   if pl is not None
                   else _pays(rows, item_bytes, W, sides, M, frac))
        return _record_verdict(mex, which, token, verdict, rows,
                               item_bytes, sides, frac, why)
    return _sticky_decision(mex, kind, token, compute)


def auto_location_detect(mex, rows_global: int, item_bytes: int,
                         token, local_rows: bool = False) -> bool:
    """Cost-model verdict for the join location filter (device path).
    ``rows_global`` is the caller's best row estimate (exact counts >
    learned site caps > padded upper bound); ``local_rows=True`` marks
    a per-process partial count (host-storage paths) that must
    all-reduce by sum before a multi-controller decision."""
    forced = location_mode()
    if forced is not None:
        return _record_verdict(
            mex, "location", token, forced, rows_global, item_bytes,
            2, None, "THRILL_TPU_LOCATION_DETECT forced")
    return _auto_verdict(mex, "location", "ld", token, rows_global,
                         item_bytes, 2, local_rows)


def auto_dup_detect(mex, rows_global: int, item_bytes: int,
                    token, local_rows: bool = False) -> bool:
    """Cost-model verdict for ReduceByKey duplicate detection: keep
    globally-unique keys local instead of shuffling them."""
    forced = dup_mode()
    if forced is not None:
        return _record_verdict(
            mex, "dup", token, forced, rows_global, item_bytes, 1,
            None, "THRILL_TPU_DUP_DETECT forced")
    return _auto_verdict(mex, "dup", "dup", token, rows_global,
                         item_bytes, 1, local_rows)


def join_rows_estimate(mex, left, right, token_l, token_r) -> Tuple[int,
                                                                    int]:
    """(rows_global, item_bytes) for a device join's decision: exact
    host-known counts when present, else the learned exchange-site
    capacities, else the padded capacity bound."""
    import numpy as np

    def side_rows(shards, ident):
        counts = getattr(shards, "_counts_host", None)
        if counts is not None:
            return int(np.asarray(counts).sum())
        learned = learned_site_rows(mex, ident)
        if learned is not None:
            return learned * mex.num_workers
        return shards.cap * mex.num_workers

    rows = side_rows(left, token_l) + side_rows(right, token_r)
    from ..data.exchange import leaf_item_bytes
    import jax
    bytes_l = leaf_item_bytes(jax.tree.leaves(left.tree))
    bytes_r = leaf_item_bytes(jax.tree.leaves(right.tree))
    return rows, max((bytes_l + bytes_r) // 2, 1)
