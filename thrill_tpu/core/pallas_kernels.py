"""Pallas TPU kernels for data-plane hot loops.

The exchange planner's per-destination histogram and the reduce phases'
segment sums are the innermost device loops of every shuffle (reference
analog: the per-partition counters of ReducePrePhase,
core/reduce_pre_phase.hpp:94). These kernels keep the accumulator in
VMEM across a sequential grid over row blocks, and express the one-hot
accumulation as a matmul so the MXU does the counting.

Usage is gated: ``partition_histogram`` dispatches to the Pallas kernel
when THRILL_TPU_PALLAS=1 and the platform is a TPU, else to the jnp
fallback (identical semantics; CPU tests run the kernel in interpret
mode to pin equivalence).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

BLOCK = 512          # rows per grid step (multiple of the 128 lane width)
LANES = 128


def pallas_enabled() -> bool:
    return os.environ.get("THRILL_TPU_PALLAS", "0") == "1" and \
        jax.default_backend() == "tpu"


def _round_up(n: int, g: int) -> int:
    return ((n + g - 1) // g) * g


def _hist_kernel(dest_ref, out_ref, *, num_bins_padded: int):
    from jax.experimental import pallas as pl

    pi = pl.program_id(0)

    @pl.when(pi == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    d = dest_ref[:]                                   # [1, BLOCK] int32
    bins = jax.lax.broadcasted_iota(
        jnp.int32, (BLOCK, num_bins_padded), 1)       # [BLOCK, B]
    onehot = (d.reshape(BLOCK, 1) == bins).astype(jnp.float32)
    # MXU-friendly: per-block count = ones[1,BLOCK] @ onehot[BLOCK,B].
    # Block partials are <= BLOCK (exact in f32); the cross-block
    # accumulator is int32 so totals never lose precision past 2^24.
    ones = jnp.ones((1, BLOCK), jnp.float32)
    partial = jnp.dot(ones, onehot, preferred_element_type=jnp.float32)
    out_ref[:] += partial.astype(jnp.int32)


def partition_histogram_pallas(dest: jnp.ndarray, num_bins: int,
                               interpret: bool = False) -> jnp.ndarray:
    """Count occurrences of each bin value in ``dest`` (int32 [n]).

    Values outside [0, num_bins) are ignored (padding sentinel W).
    """
    from jax.experimental import pallas as pl

    n = dest.shape[0]
    n_pad = _round_up(max(n, 1), BLOCK)
    bpad = _round_up(max(num_bins, 1), LANES)
    d = jnp.full(n_pad, -1, jnp.int32).at[:n].set(dest.astype(jnp.int32))
    d2 = d.reshape(n_pad // BLOCK, BLOCK)

    kernel = functools.partial(_hist_kernel, num_bins_padded=bpad)
    out = pl.pallas_call(
        kernel,
        grid=(n_pad // BLOCK,),
        in_specs=[pl.BlockSpec((1, BLOCK), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1, bpad), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, bpad), jnp.int32),
        interpret=interpret,
    )(d2)
    return out[0, :num_bins]


def partition_histogram(dest: jnp.ndarray, num_bins: int) -> jnp.ndarray:
    """Dispatch: Pallas on TPU when enabled, else jnp.bincount.

    Both paths ignore values outside [0, num_bins) — negative or
    too-large ids are padding sentinels, never counted.
    """
    if pallas_enabled():
        return partition_histogram_pallas(dest, num_bins)
    sanitized = jnp.where((dest >= 0) & (dest < num_bins), dest, num_bins)
    return jnp.bincount(sanitized,
                        length=num_bins + 1)[:num_bins].astype(jnp.int32)


def _segsum_kernel(seg_ref, val_ref, out_ref, *, num_segs_padded: int):
    from jax.experimental import pallas as pl

    pi = pl.program_id(0)

    @pl.when(pi == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    s = seg_ref[:]                                    # [1, BLOCK] int32
    v = val_ref[:]                                    # [1, BLOCK] f32
    segs = jax.lax.broadcasted_iota(
        jnp.int32, (BLOCK, num_segs_padded), 1)
    onehot = (s.reshape(BLOCK, 1) == segs).astype(jnp.float32)
    out_ref[:] += jnp.dot(v.reshape(1, BLOCK), onehot,
                          preferred_element_type=jnp.float32)


def segment_sum(seg_ids: jnp.ndarray, values: jnp.ndarray,
                num_segments: int) -> jnp.ndarray:
    """Dispatch: Pallas on TPU when enabled, else jax segment_sum."""
    if pallas_enabled():
        return segment_sum_pallas(seg_ids, values, num_segments)
    import jax.ops
    safe = jnp.where((seg_ids >= 0) & (seg_ids < num_segments),
                     seg_ids, num_segments)
    return jax.ops.segment_sum(values.astype(jnp.float32), safe,
                               num_segments=num_segments + 1)[:num_segments]


def segment_sum_pallas(seg_ids: jnp.ndarray, values: jnp.ndarray,
                       num_segments: int,
                       interpret: bool = False) -> jnp.ndarray:
    """Sum float32 ``values`` into ``num_segments`` buckets by seg id.

    The one-hot matmul runs the accumulation on the MXU. This is the
    specialized fast path for additive float reductions (dense
    ReduceToIndex-style sums); the generic reduce pipeline keeps the
    segmented associative scan, which supports arbitrary reduce
    functions.
    """
    from jax.experimental import pallas as pl

    n = values.shape[0]
    n_pad = _round_up(max(n, 1), BLOCK)
    spad = _round_up(max(num_segments, 1), LANES)
    s = jnp.full(n_pad, -1, jnp.int32).at[:n].set(seg_ids.astype(jnp.int32))
    v = jnp.zeros(n_pad, jnp.float32).at[:n].set(values.astype(jnp.float32))

    kernel = functools.partial(_segsum_kernel, num_segs_padded=spad)
    out = pl.pallas_call(
        kernel,
        grid=(n_pad // BLOCK,),
        in_specs=[pl.BlockSpec((1, BLOCK), lambda i: (i, 0)),
                  pl.BlockSpec((1, BLOCK), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((1, spad), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, spad), jnp.float32),
        interpret=interpret,
    )(s.reshape(-1, BLOCK), v.reshape(-1, BLOCK))
    return out[0, :num_segments]
