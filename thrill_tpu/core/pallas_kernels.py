"""Pallas TPU kernels for data-plane hot loops.

The exchange planner's per-destination histogram and the reduce phases'
segment sums are the innermost device loops of every shuffle (reference
analog: the per-partition counters of ReducePrePhase,
core/reduce_pre_phase.hpp:94). These kernels keep the accumulator in
VMEM across a sequential grid over row blocks, and express the one-hot
accumulation as lane-parallel VPU compares and reductions (the
stable-partition kernel in pallas_sort.py additionally rides the MXU
for its within-row triangular prefix).

Layout (settled by an on-chip round-5 lowering session — the original
(1, BLOCK) row blocks violated Mosaic's (8, 128) trailing-dims rule,
and the ``d.reshape(BLOCK, 1)`` one-hot pivot is a lane->sublane
transpose Mosaic won't lower):

* data tiles are ``(SUBLANES, COLS)`` = (8, 64) — 512 elements per
  sequential grid step, elements ALWAYS on the lane axis;
* bin/segment counters are ``(bins, 1)`` columns — bins on the
  SUBLANE axis — so one-hot compares are pure broadcasts
  ``iota(bins, COLS) == d_row(1, COLS)`` with no transposes anywhere.

Usage is gated: ``partition_histogram`` dispatches to the Pallas kernel
when THRILL_TPU_PALLAS=1 and the platform is a TPU, else to the jnp
fallback (identical semantics; CPU tests run the kernel in interpret
mode to pin equivalence).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

BLOCK = 512          # elements per sequential grid step
LANES = 128
SUBLANES = 8         # Mosaic block rule: trailing dims divisible by
                     # (8, 128) or equal to the array's dims
COLS = BLOCK // SUBLANES   # 64 lanes per tile row


# f32 one-hot partials stay exact below this row count (same bound as
# pallas_sort._F32_EXACT); every dispatcher refuses larger inputs
MAX_ROWS = 1 << 24
# one-hot register fill / segment sum are O(bins*n) lane-compares: a
# clear win only while the bin column stays small (the preshuffle
# _REG_MIN clamp's home turf); above these XLA's native scatter wins
PRESFILL_MAX_REGS = 1 << 13
SEGSUM_MAX_SEGS = 1 << 12

_MISSING = object()


def pallas_enabled(mex=None) -> bool:
    """True when the Pallas kernel tier should drive eligible hot loops
    (THRILL_TPU_PALLAS=1 on a real TPU backend).

    The knob is resolved ONCE at MeshExec construction (mirroring the
    THRILL_TPU_EXCHANGE contract): inside a dispatch or trace the
    owning mesh's cached value wins — flipping the env var after the
    mesh exists deliberately does nothing. Outside any dispatch (bare
    kernel calls, unit tests) fall back to the live env read.
    """
    if mex is None:
        from ..parallel.mesh import current_mex
        mex = current_mex()
    env = getattr(mex, "_env_pallas", _MISSING) if mex is not None \
        else _MISSING
    if env is _MISSING:
        env = os.environ.get("THRILL_TPU_PALLAS", "0")
    return env == "1" and jax.default_backend() == "tpu"


def rows_ok(n: int) -> bool:
    """Row-count refusal gate shared by every kernel dispatcher."""
    return n < MAX_ROWS


def presence_fill_ok(num_regs: int, n: int) -> bool:
    return num_regs <= PRESFILL_MAX_REGS and rows_ok(n)


def segment_sum_ok(num_segments: int, n: int) -> bool:
    return num_segments <= SEGSUM_MAX_SEGS and rows_ok(n)


def _round_up(n: int, g: int) -> int:
    return ((n + g - 1) // g) * g


def _hist_kernel(dest_ref, out_ref, *, num_bins_padded: int):
    from jax.experimental import pallas as pl

    pi = pl.program_id(0)

    @pl.when(pi == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    bins = jax.lax.broadcasted_iota(
        jnp.int32, (num_bins_padded, COLS), 0)        # [B, COLS]
    acc = jnp.zeros((num_bins_padded, 1), jnp.float32)
    for r in range(SUBLANES):                          # static unroll
        d_r = dest_ref[r:r + 1, :]                     # [1, COLS] int32
        onehot = (bins == d_r).astype(jnp.float32)     # [B, COLS]
        # per-row count = lane reduce; partials <= BLOCK (exact in f32),
        # the cross-block accumulator is int32 so totals never lose
        # precision past 2^24
        acc += jnp.sum(onehot, axis=1, keepdims=True)
    out_ref[:] += acc.astype(jnp.int32)


def partition_histogram_pallas(dest: jnp.ndarray, num_bins: int,
                               interpret: bool = False) -> jnp.ndarray:
    """Count occurrences of each bin value in ``dest`` (int32 [n]).

    Values outside [0, num_bins) are ignored (padding sentinel W).
    """
    from jax.experimental import pallas as pl

    n = dest.shape[0]
    n_pad = _round_up(max(n, 1), BLOCK)
    bpad = _round_up(max(num_bins, 1), LANES)
    d = jnp.full(n_pad, -1, jnp.int32).at[:n].set(dest.astype(jnp.int32))
    d2 = d.reshape(n_pad // COLS, COLS)

    kernel = functools.partial(_hist_kernel, num_bins_padded=bpad)
    out = pl.pallas_call(
        kernel,
        grid=(n_pad // BLOCK,),
        in_specs=[pl.BlockSpec((SUBLANES, COLS), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bpad, 1), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((bpad, 1), jnp.int32),
        interpret=interpret,
    )(d2)
    return out[:num_bins, 0]


def partition_histogram(dest: jnp.ndarray, num_bins: int) -> jnp.ndarray:
    """Dispatch: Pallas on TPU when enabled, else jnp.bincount.

    Both paths ignore values outside [0, num_bins) — negative or
    too-large ids are padding sentinels, never counted.
    """
    if pallas_enabled() and rows_ok(dest.shape[0]):
        return partition_histogram_pallas(dest, num_bins)
    sanitized = jnp.where((dest >= 0) & (dest < num_bins), dest, num_bins)
    return jnp.bincount(sanitized,
                        length=num_bins + 1)[:num_bins].astype(jnp.int32)


def _segsum_kernel(seg_ref, val_ref, out_ref, *, num_segs_padded: int):
    from jax.experimental import pallas as pl

    pi = pl.program_id(0)

    @pl.when(pi == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    segs = jax.lax.broadcasted_iota(
        jnp.int32, (num_segs_padded, COLS), 0)        # [S, COLS]
    acc = jnp.zeros((num_segs_padded, 1), jnp.float32)
    for r in range(SUBLANES):                          # static unroll
        s_r = seg_ref[r:r + 1, :]                      # [1, COLS]
        v_r = val_ref[r:r + 1, :]                      # [1, COLS] f32
        onehot = (segs == s_r).astype(jnp.float32)     # [S, COLS]
        acc += jnp.sum(onehot * v_r, axis=1, keepdims=True)
    out_ref[:] += acc


def segment_sum(seg_ids: jnp.ndarray, values: jnp.ndarray,
                num_segments: int) -> jnp.ndarray:
    """Dispatch: Pallas on TPU when enabled, else jax segment_sum."""
    if pallas_enabled() and segment_sum_ok(num_segments, values.shape[0]):
        return segment_sum_pallas(seg_ids, values, num_segments)
    import jax.ops
    safe = jnp.where((seg_ids >= 0) & (seg_ids < num_segments),
                     seg_ids, num_segments)
    return jax.ops.segment_sum(values.astype(jnp.float32), safe,
                               num_segments=num_segments + 1)[:num_segments]


def segment_sum_pallas(seg_ids: jnp.ndarray, values: jnp.ndarray,
                       num_segments: int,
                       interpret: bool = False) -> jnp.ndarray:
    """Sum float32 ``values`` into ``num_segments`` buckets by seg id.

    This is the specialized fast path for additive float reductions
    (dense ReduceToIndex-style sums); the generic reduce pipeline keeps
    the segmented associative scan, which supports arbitrary reduce
    functions.
    """
    from jax.experimental import pallas as pl

    n = values.shape[0]
    n_pad = _round_up(max(n, 1), BLOCK)
    spad = _round_up(max(num_segments, 1), LANES)
    s = jnp.full(n_pad, -1, jnp.int32).at[:n].set(seg_ids.astype(jnp.int32))
    v = jnp.zeros(n_pad, jnp.float32).at[:n].set(values.astype(jnp.float32))

    kernel = functools.partial(_segsum_kernel, num_segs_padded=spad)
    out = pl.pallas_call(
        kernel,
        grid=(n_pad // BLOCK,),
        in_specs=[pl.BlockSpec((SUBLANES, COLS), lambda i: (i, 0)),
                  pl.BlockSpec((SUBLANES, COLS), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((spad, 1), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((spad, 1), jnp.float32),
        interpret=interpret,
    )(s.reshape(-1, COLS), v.reshape(-1, COLS))
    return out[:num_segments, 0]


def _presfill_kernel(h_ref, v_ref, out_ref, *, num_regs_padded: int):
    from jax.experimental import pallas as pl

    pi = pl.program_id(0)

    @pl.when(pi == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    regs = jax.lax.broadcasted_iota(
        jnp.int32, (num_regs_padded, COLS), 0)         # [M, COLS]
    acc = jnp.zeros((num_regs_padded, 1), jnp.float32)
    for r in range(SUBLANES):                          # static unroll
        h_r = h_ref[r:r + 1, :]                        # [1, COLS] int32
        v_r = v_ref[r:r + 1, :]                        # [1, COLS] f32
        onehot = (regs == h_r).astype(jnp.float32)     # [M, COLS]
        acc = jnp.maximum(
            acc, jnp.max(onehot * v_r, axis=1, keepdims=True))
    out_ref[:] = jnp.maximum(out_ref[:], acc.astype(jnp.int32))


def presence_fill_pallas(h: jnp.ndarray, valid: jnp.ndarray,
                         num_regs: int,
                         interpret: bool = False) -> jnp.ndarray:
    """u8 presence registers: out[m] = 1 iff some i has ``h[i] == m``
    and ``valid[i]`` truthy. Values of ``h`` outside [0, num_regs) are
    ignored (padding sentinel -1)."""
    from jax.experimental import pallas as pl

    n = h.shape[0]
    n_pad = _round_up(max(n, 1), BLOCK)
    mpad = _round_up(max(num_regs, 1), LANES)
    hp = jnp.full(n_pad, -1, jnp.int32).at[:n].set(h.astype(jnp.int32))
    vp = jnp.zeros(n_pad, jnp.float32).at[:n].set(
        valid.astype(jnp.float32))

    kernel = functools.partial(_presfill_kernel, num_regs_padded=mpad)
    out = pl.pallas_call(
        kernel,
        grid=(n_pad // BLOCK,),
        in_specs=[pl.BlockSpec((SUBLANES, COLS), lambda i: (i, 0)),
                  pl.BlockSpec((SUBLANES, COLS), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((mpad, 1), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((mpad, 1), jnp.int32),
        interpret=interpret,
    )(hp.reshape(-1, COLS), vp.reshape(-1, COLS))
    return (out[:num_regs, 0] > 0).astype(jnp.uint8)


def presence_fill(h: jnp.ndarray, valid: jnp.ndarray,
                  num_regs: int) -> jnp.ndarray:
    """Dispatch: Pallas on TPU when enabled and the register column is
    small enough that one-hot compares beat XLA's scatter, else the
    scatter-max fallback (bit-identical — presence is 0/1, no float
    reassociation). This is the device analog of the reference's
    Golomb-coded fingerprint columns (duplicate detection,
    arXiv:1608.05634): the pre-shuffle presence registers that
    location-detect and dup-detect fill before any data ships.
    """
    if pallas_enabled() and presence_fill_ok(num_regs, h.shape[0]):
        return presence_fill_pallas(h, valid, num_regs)
    safe = jnp.where((h >= 0) & (h < num_regs), h, num_regs)
    return jnp.zeros(num_regs + 1, jnp.uint8).at[safe].max(
        valid.astype(jnp.uint8))[:num_regs]
