"""Sort-based segmented aggregation: the device reduce engine.

The reference aggregates with linear-probing hash tables
(reference: thrill/core/reduce_pre_phase.hpp:94,
reduce_by_hash_post_phase.hpp:44, reduce_probing_hash_table.hpp:77).
Hash tables are a pointer-chasing CPU idiom; the TPU-native equivalent
is sort + segmented reduction: XLA's bitonic sort groups equal keys into
runs, a segmented associative scan combines each run with the user's
reduce function, and run representatives are compacted out. Everything
is static-shaped, branch-free and VPU/MXU friendly.
"""

from __future__ import annotations

from typing import Any, Callable, List, Tuple

import jax
import jax.numpy as jnp


def sort_by_key_words(words: List[jnp.ndarray], tree: Any, valid: jnp.ndarray,
                      extra_words: List[jnp.ndarray] = ()):
    """Stable sort of (words, tree, valid) with invalid items last.

    Returns (sorted_words, sorted_tree, sorted_valid). ``extra_words``
    sort after the key words (e.g. global index for stability).
    """
    invalid_first_word = (~valid).astype(jnp.uint32)  # valid(0) < invalid(1)
    sort_keys = [invalid_first_word] + list(words) + list(extra_words)
    perm = _argsort_multi(sort_keys)
    take = lambda x: jnp.take(x, perm, axis=0)
    return ([take(w) for w in words],
            jax.tree.map(take, tree),
            take(valid),
            [take(w) for w in extra_words])


def _argsort_multi(keys: List[jnp.ndarray]) -> jnp.ndarray:
    """Stable argsort by multiple uint64 key arrays (lexicographic)."""
    from .device_sort import argsort_words
    return argsort_words(keys)


def segment_boundaries(words: List[jnp.ndarray], valid: jnp.ndarray
                       ) -> jnp.ndarray:
    """starts[i] = True iff item i begins a new key run (valid items,
    assumed key-sorted with invalid last)."""
    n = valid.shape[0]
    idx = jnp.arange(n)
    diff = jnp.zeros(n, dtype=bool).at[0].set(True)
    neq = jnp.zeros(n, dtype=bool)
    for w in words:
        neq = neq | (w != jnp.roll(w, 1))
    diff = diff | neq
    return diff & valid


def segmented_reduce(words: List[jnp.ndarray], tree: Any,
                     valid: jnp.ndarray, reduce_fn: Callable
                     ) -> Tuple[List[jnp.ndarray], Any, jnp.ndarray]:
    """Combine each equal-key run into one item.

    Inputs must be key-sorted with invalid items last. Returns
    (words, tree, rep_mask): ``rep_mask`` marks one surviving item per
    run, whose tree value is the fold of the whole run. The fold uses a
    segmented inclusive scan, so ``reduce_fn`` must be associative
    (same contract as the reference's reduce function).
    """
    n = valid.shape[0]
    starts = segment_boundaries(words, valid)

    def combine(a, b):
        tree_a, flag_a = a
        tree_b, flag_b = b
        merged = reduce_fn(tree_a, tree_b)
        keep_b = jax.tree.map(
            lambda m, vb: jnp.where(_bshape(flag_b, m), vb, m),
            merged, tree_b)
        return keep_b, flag_a | flag_b

    scanned, _ = jax.lax.associative_scan(combine, (tree, starts), axis=0)
    # representative = last item of each run = position before next start,
    # or the last valid item overall
    next_start = jnp.roll(starts, -1).at[-1].set(True)
    count = jnp.sum(valid.astype(jnp.int32))
    is_last_valid = jnp.arange(n) == count - 1
    rep = valid & (next_start | is_last_valid)
    return words, scanned, rep


def _bshape(flag, leaf):
    """Broadcast [n] flag against leaf [n, ...]."""
    return flag.reshape(flag.shape + (1,) * (leaf.ndim - 1))
