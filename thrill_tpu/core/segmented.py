"""Sort-based segmented aggregation: the device reduce engine.

The reference aggregates with linear-probing hash tables
(reference: thrill/core/reduce_pre_phase.hpp:94,
reduce_by_hash_post_phase.hpp:44, reduce_probing_hash_table.hpp:77).
Hash tables are a pointer-chasing CPU idiom; the TPU-native equivalent
is sort + segmented reduction: XLA's bitonic sort groups equal keys into
runs, a segmented associative scan combines each run with the user's
reduce function, and run representatives are compacted out. Everything
is static-shaped, branch-free and VPU/MXU friendly.
"""

from __future__ import annotations

from typing import Any, Callable, List, Tuple

import jax
import jax.numpy as jnp


def sort_by_key_words(words: List[jnp.ndarray], tree: Any, valid: jnp.ndarray,
                      extra_words: List[jnp.ndarray] = ()):
    """Stable sort of (words, tree, valid) with invalid items last.

    Returns (sorted_words, sorted_tree, sorted_valid). ``extra_words``
    sort after the key words (e.g. global index for stability).
    """
    invalid_first_word = (~valid).astype(jnp.uint32)  # valid(0) < invalid(1)
    sort_keys = [invalid_first_word] + list(words) + list(extra_words)
    perm = _argsort_multi(sort_keys)
    take = lambda x: jnp.take(x, perm, axis=0)
    return ([take(w) for w in words],
            jax.tree.map(take, tree),
            take(valid),
            [take(w) for w in extra_words])


def _argsort_multi(keys: List[jnp.ndarray]) -> jnp.ndarray:
    """Stable argsort by multiple uint64 key arrays (lexicographic)."""
    from .device_sort import argsort_words
    return argsort_words(keys)


def segment_boundaries(words: List[jnp.ndarray], valid: jnp.ndarray
                       ) -> jnp.ndarray:
    """starts[i] = True iff item i begins a new key run (valid items,
    assumed key-sorted with invalid last)."""
    n = valid.shape[0]
    idx = jnp.arange(n)
    diff = jnp.zeros(n, dtype=bool).at[0].set(True)
    neq = jnp.zeros(n, dtype=bool)
    for w in words:
        neq = neq | (w != jnp.roll(w, 1))
    diff = diff | neq
    return diff & valid


def segmented_reduce(words: List[jnp.ndarray], tree: Any,
                     valid: jnp.ndarray, reduce_fn: Callable
                     ) -> Tuple[List[jnp.ndarray], Any, jnp.ndarray]:
    """Combine each equal-key run into one item.

    Inputs must be key-sorted with invalid items last. Returns
    (words, tree, rep_mask): ``rep_mask`` marks one surviving item per
    run, whose tree value is the fold of the whole run. The fold uses a
    segmented inclusive scan, so ``reduce_fn`` must be associative
    (same contract as the reference's reduce function).
    """
    starts = segment_boundaries(words, valid)

    def combine(a, b):
        tree_a, flag_a = a
        tree_b, flag_b = b
        merged = reduce_fn(tree_a, tree_b)
        keep_b = jax.tree.map(
            lambda m, vb: jnp.where(_bshape(flag_b, m), vb, m),
            merged, tree_b)
        return keep_b, flag_a | flag_b

    scanned, _ = jax.lax.associative_scan(combine, (tree, starts), axis=0)
    return words, scanned, _rep_mask(starts, valid)


def _rep_mask(starts: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """Representative = last item of each run (position before the next
    start), or the last valid item overall. Shared by both segmented
    reduce engines so the contract cannot diverge."""
    n = valid.shape[0]
    next_start = jnp.roll(starts, -1).at[-1].set(True)
    count = jnp.sum(valid.astype(jnp.int32))
    is_last_valid = jnp.arange(n) == count - 1
    return valid & (next_start | is_last_valid)


def _bshape(flag, leaf):
    """Broadcast [n] flag against leaf [n, ...]."""
    return flag.reshape(flag.shape + (1,) * (leaf.ndim - 1))


def reduce_runs(words, tree, valid, reduce_fn, specs):
    """One dispatch point for every device reduce program: the
    segment-op engine when ``specs`` (from FieldReduce, pre-gated by
    :func:`fields_specializable`) is available, else the generic
    associative scan. Same (words, tree, rep) contract either way."""
    if specs is not None:
        return segmented_reduce_fields(words, tree, valid, specs)
    return segmented_reduce(words, tree, valid, reduce_fn)


def fields_specializable(flat_specs, leaf_dtypes) -> bool:
    """Can :func:`segmented_reduce_fields` handle this FieldReduce
    spec? "first" takes any dtype; "sum" needs numeric (bool addition
    differs between numpy and the scan's `+`); "min"/"max" need
    INTEGER dtypes — float segment-min/max via scatter does not
    guarantee the NaN-propagation order jnp.minimum gives the generic
    scan, so floats keep the scan."""
    import numpy as np
    for s, dt in zip(flat_specs, leaf_dtypes):
        if s == "first":
            # bool/int/uint/float all route through an exact integer
            # segment_sum (floats via bitcast); complex has no clean
            # bitcast target — keep the scan for it
            if np.issubdtype(dt, np.complexfloating):
                return False
            continue
        if s == "sum":
            if not (np.issubdtype(dt, np.integer)
                    or np.issubdtype(dt, np.floating)):
                return False
        elif s in ("min", "max"):
            if not np.issubdtype(dt, np.integer):
                return False
        else:
            return False
    return True


def segmented_reduce_fields(words: List[jnp.ndarray], tree: Any,
                            valid: jnp.ndarray, flat_specs
                            ) -> Tuple[List[jnp.ndarray], Any,
                                       jnp.ndarray]:
    """FieldReduce specialization of :func:`segmented_reduce` — same
    inputs and (words, tree, rep_mask) contract, different engine: each
    field folds with ONE sorted segment reduction plus one gather
    instead of the O(log n)-round associative scan over the whole tree.
    On TPU that is a single scatter pass per field through HBM rather
    than log2(n) combine rounds; the reference reaches the same shape
    by accumulating std::plus directly in its probing table.

    "first" is computed as segment_sum of a start-row-masked
    contribution (each segment receives exactly one addend — its first
    row — so the sum IS the first value, exactly). Caller gates with
    :func:`fields_specializable`.
    """
    import jax.ops as jops

    n = valid.shape[0]
    starts = segment_boundaries(words, valid)
    seg = jnp.clip(jnp.cumsum(starts.astype(jnp.int32)) - 1, 0, n - 1)
    leaves, td = jax.tree.flatten(tree)
    out_leaves = []
    for s, leaf in zip(flat_specs, leaves):
        v = _bshape(valid, leaf)
        if s == "first":
            st = _bshape(starts, leaf)
            # exactly one addend lands in each segment, so segment_sum
            # IS a select — but only over INTEGERS: bools cast through
            # int32, and floats BITCAST to same-width uints (a float
            # sum would canonicalize -0.0 + 0.0 to +0.0, silently
            # diverging from the scan engine on sign-bit-sensitive
            # consumers) and bitcast back
            fdt = leaf.dtype
            if fdt == jnp.bool_:
                src = leaf.astype(jnp.int32)
            elif jnp.issubdtype(fdt, jnp.floating):
                src = jax.lax.bitcast_convert_type(
                    leaf, jnp.dtype(f"uint{fdt.itemsize * 8}"))
            else:
                src = leaf
            contrib = jnp.where(st, src, jnp.zeros_like(src))
            res = jops.segment_sum(contrib, seg, num_segments=n,
                                   indices_are_sorted=True)
            if fdt == jnp.bool_:
                res = res.astype(jnp.bool_)
            elif jnp.issubdtype(fdt, jnp.floating):
                res = jax.lax.bitcast_convert_type(res, fdt)
        elif s == "sum":
            # Float sums mask invalid rows to +0.0, which IEEE adds
            # as identity EXCEPT for the sign of zero: a group whose
            # true sum is -0.0 comes back +0.0 here (the scan engine,
            # folding only real rows, preserves -0.0). Accepted
            # divergence — the unordered-reduce contract never
            # promised sign-of-zero, and excluding float sums would
            # forfeit the specialization for the dominant use case.
            contrib = jnp.where(v, leaf, jnp.zeros_like(leaf))
            res = jops.segment_sum(contrib, seg, num_segments=n,
                                   indices_are_sorted=True)
        elif s == "min":
            fill = jnp.array(jnp.iinfo(leaf.dtype).max, leaf.dtype)
            contrib = jnp.where(v, leaf, fill)
            res = jops.segment_min(contrib, seg, num_segments=n,
                                   indices_are_sorted=True)
        else:  # "max"
            fill = jnp.array(jnp.iinfo(leaf.dtype).min, leaf.dtype)
            contrib = jnp.where(v, leaf, fill)
            res = jops.segment_max(contrib, seg, num_segments=n,
                                   indices_are_sorted=True)
        out_leaves.append(jnp.take(res, seg, axis=0))
    return (words, jax.tree.unflatten(td, out_leaves),
            _rep_mask(starts, valid))
