"""Device sort engine: XLA sort, chunked sort+merge, or bitonic network.

The DIA operators sort through one entry point, ``argsort_words``
(stable argsort by a list of uint64 key words). Three interchangeable
implementations:

* ``xla``     — ``lax.sort`` multi-operand (fastest where the XLA sort
                lowering is healthy; always used on CPU).
* ``chunked`` — batched ``lax.sort`` over 64K-row tiles (each tile
                stays below the TPU sort-lowering compile cliff), then
                a bitonic *merge* tree over the sorted tiles. Every
                merge substage is a reshape-based compare-exchange —
                pure slicing/selects at static strides, NO random
                gathers — so it is both MXU/VPU friendly and cheap to
                compile: O(log C · log n) elementwise substages versus
                the full network's O(log² n) gather substages.
* ``bitonic`` — the explicit full bitonic network driven by
                ``lax.fori_loop`` (kept as a fallback: tiny program
                regardless of n, but O(n log² n) gathers at runtime).

Selection: THRILL_TPU_SORT_IMPL = auto (default) | xla | chunked |
bitonic. ``auto`` uses xla on CPU backends and for small n, chunked on
accelerators above the threshold (observed on the axon single-chip
backend: plain sort compiles stall beyond ~64K rows; batched 64K tiles
compile fine).
"""

from __future__ import annotations

import math
import os
from typing import List

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# above this row count, accelerator backends switch engines in auto
XLA_SORT_MAX_N = 1 << 16

# coarse per-platform element-op throughput, converting modeled engine
# costs into µs — the unit the dispatch-latency audit joins back in
# (parallel/mesh.py resolves sort_engine records with the program's
# measured post-compile dispatch wall time). Deliberately order-of-
# magnitude: the audit checks magnitude, not percent.
_OPS_PER_US = {"cpu": 2e2, "tpu": 2e4}


def _impl(n: int) -> str:
    mode = os.environ.get("THRILL_TPU_SORT_IMPL", "auto")
    if mode in ("xla", "bitonic", "chunked", "radix"):
        return mode
    if jax.default_backend() == "cpu" or n <= XLA_SORT_MAX_N:
        return "xla"
    return "chunked"


def sort_engine_policy(n: int, total_bits: int, radix_ok: bool):
    """THE cost model for the device sort engine choice (ROADMAP
    planner edge (e)) — shared verbatim by the auto path here and by
    ``Planner.sort_engine`` so both always agree.

    Returns ``(engine, costs_us, reason)`` where ``costs_us`` maps each
    candidate engine to its modeled cost in µs:

    * xla     — one ``lax.sort``: ~n·log n work, but only where the
                lowering is healthy (CPU, or n below the TPU compile
                cliff at ``XLA_SORT_MAX_N``);
    * chunked — batched 64K-tile sorts + bitonic merge tree:
                n·(log²(64K)/2 + log C·log n) compare-exchanges;
    * radix   — LSD 8-bit passes over the key words (pallas_sort):
                ~3n per pass (histogram + offsets + scatter),
                ``total_bits/8`` passes, eligible only when the Pallas
                stable-partition kernel engages (``radix_ok``).
    """
    plat = jax.default_backend()
    ops = _OPS_PER_US.get(plat, 2e3)
    lg = max(1.0, math.log2(max(n, 2)))
    if plat == "cpu" or n <= XLA_SORT_MAX_N:
        return ("xla", {"xla": n * lg / ops},
                "xla sort lowering healthy at this size")
    costs = {}
    lgc = math.log2(XLA_SORT_MAX_N)
    c_tiles = max(1.0, n / XLA_SORT_MAX_N)
    costs["chunked"] = n * (lgc * lgc / 2.0
                            + math.log2(c_tiles) * lg) / ops
    if radix_ok:
        passes = max(1, (total_bits + 7) // 8)
        costs["radix"] = 3.0 * n * passes / ops
        reason = "past the xla compile cliff; radix eligible"
    else:
        reason = ("past the xla compile cliff; radix ineligible "
                  "(Pallas off or too many rows)")
    engine = min(costs, key=costs.get)
    return engine, costs, reason


def _auto_engine(words: List[jnp.ndarray], n: int) -> str:
    """Resolve auto mode to an engine, routing through the planner's
    cost model when one is attached and recording the choice in the
    decision ledger (audited later with the program's measured dispatch
    latency — see _CountedJit._dispatch)."""
    from ..parallel import mesh as _mesh
    from .pallas_kernels import MAX_ROWS, pallas_enabled

    mex = _mesh.current_mex()
    radix_ok = pallas_enabled(mex) and n < MAX_ROWS
    total_bits = sum(32 if w.dtype == jnp.uint32 else 64 for w in words)
    site = f"sort:n{n}:w{len(words)}"
    pl = getattr(mex, "planner", None) if mex is not None else None
    if pl is not None and pl.enabled:
        engine, costs, reason = pl.sort_engine(n, total_bits, radix_ok,
                                               site=site)
    else:
        engine, costs, reason = sort_engine_policy(n, total_bits,
                                                   radix_ok)
    if mex is not None:
        led = getattr(mex, "decisions", None)
        if led is not None and led.enabled:
            rec = led.record(
                "sort_engine", site=site,
                chosen=engine, predicted=costs.get(engine),
                rejected=[(e, c) for e, c in sorted(costs.items())
                          if e != engine],
                reason=reason, n=n, total_bits=total_bits)
            prog = _mesh.current_program()
            if prog is not None and not prog._engine_armed:
                prog._engine_recs.append(rec)
    return engine


def _use_u32() -> bool:
    """Split uint64 key words into native uint32 (hi, lo) pairs?

    TPU VPU lanes are 32-bit; XLA emulates every 64-bit integer compare
    and select as u32 pairs with carry fixups. Splitting explicitly
    yields the same lexicographic order ((hi, lo) big-endian) out of
    native ops and lets the carried iota be a single u32 word. Default
    on for accelerator backends, off on CPU (native 64-bit ALU); env
    THRILL_TPU_SORT_U32 = 0|1 overrides.
    """
    mode = os.environ.get("THRILL_TPU_SORT_U32")
    if mode is not None:
        return mode not in ("0", "false", "")
    return jax.default_backend() != "cpu"


def _split_words_u32(words: List[jnp.ndarray]) -> List[jnp.ndarray]:
    """uint64 word list -> equivalent uint32 (hi, lo) word list.

    Words already narrower than 33 bits keep one (lo) word."""
    out: List[jnp.ndarray] = []
    for w in words:
        if w.dtype != jnp.uint64:
            out.append(w.astype(jnp.uint32))
            continue
        out.append((w >> jnp.uint64(32)).astype(jnp.uint32))
        out.append(w.astype(jnp.uint32))
    return out


def prepare_sort_words(words: List[jnp.ndarray], n: int):
    """Shared key prep for every sort entry point: apply the u32 word
    split when enabled and pick the index/iota dtype for ``n`` rows.
    Returns (words, index_dtype). Callers that build their own sort
    or merge network (Sort's fused run-merge) MUST go through this so
    their key layout never diverges from ``argsort_words``."""
    if _use_u32():
        words = _split_words_u32(words)
        idt = jnp.uint32 if n <= (1 << 31) else jnp.uint64
    else:
        idt = jnp.uint64
    return words, idt


def argsort_words(words: List[jnp.ndarray]) -> jnp.ndarray:
    """Stable argsort by uint64 key words (lexicographic). [n] int32."""
    n = words[0].shape[0]
    mode = os.environ.get("THRILL_TPU_SORT_IMPL", "auto")
    impl = mode if mode in ("xla", "bitonic", "chunked", "radix") \
        else _auto_engine(words, n)
    if impl == "radix":
        # LSD radix over 8-bit digits (O(n * passes), no comparison
        # network, no XLA-sort compile cliff): Pallas stable-partition
        # kernel on TPU, lax.scan fallback elsewhere. u32 split is
        # irrelevant — digits are extracted by shifts either way.
        from .pallas_sort import radix_argsort_device
        bits = [32 if w.dtype == jnp.uint32 else 64 for w in words]
        return radix_argsort_device(
            [w.astype(jnp.uint64) for w in words],
            word_bits=bits).astype(jnp.int32)
    words, idt = prepare_sort_words(words, n)
    if impl == "xla":
        iota = jnp.arange(n, dtype=idt)
        res = lax.sort(tuple(words) + (iota,), dimension=0,
                       num_keys=len(words), is_stable=True)
        return res[-1].astype(jnp.int32)
    if impl == "chunked":
        return _chunked_argsort(words, index_dtype=idt)
    return _bitonic_argsort(words, index_dtype=idt)


def _lex_gt(a_words, b_words):
    """Elementwise lexicographic a > b over parallel word lists."""
    gt = jnp.zeros(a_words[0].shape, bool)
    eq = jnp.ones(a_words[0].shape, bool)
    for a, b in zip(a_words, b_words):
        gt = gt | (eq & (a > b))
        eq = eq & (a == b)
    return gt


def _compare_exchange(arrs, d: int):
    """Min-first compare-exchange at distance ``d`` on [C, L] arrays.

    Within each 2d-block, position i is compared with i+d and the smaller
    tuple kept first — expressed as reshape + slice + select (static
    strides), never as a gather.
    """
    C, L = arrs[0].shape
    resh = [a.reshape(C, L // (2 * d), 2, d) for a in arrs]
    a_side = [r[:, :, 0, :] for r in resh]
    b_side = [r[:, :, 1, :] for r in resh]
    gt = _lex_gt(a_side, b_side)
    out = []
    for x, y in zip(a_side, b_side):
        lo = jnp.where(gt, y, x)
        hi = jnp.where(gt, x, y)
        out.append(jnp.stack([lo, hi], axis=2).reshape(C, L))
    return out


def _chunked_argsort(words: List[jnp.ndarray],
                     chunk: int = XLA_SORT_MAX_N,
                     index_dtype=jnp.uint64) -> jnp.ndarray:
    """Sorted 64K tiles + bitonic merge tree; [n] int32 permutation.

    Stability comes from carrying the original index as the final key
    word (total order), not from the network itself. Pads (max words,
    index >= n) sort last within their tile and stay last through every
    merge, so perm[:n] is exactly the sorted real items.
    """
    n_real = words[0].shape[0]
    if n_real == 1:
        return jnp.zeros(1, jnp.int32)
    n = 1 << (n_real - 1).bit_length()
    c = min(chunk, n)
    pad = n - n_real
    iota = jnp.arange(n, dtype=index_dtype)
    arrs = [jnp.concatenate([w, jnp.full(pad, jnp.iinfo(w.dtype).max,
                                         w.dtype)])
            if pad else w for w in words] + [iota]

    C = n // c
    arrs = [a.reshape(C, c) for a in arrs]
    # base case: batched sort of every tile (compiles like one 64K sort)
    arrs = list(lax.sort(tuple(arrs), dimension=1, num_keys=len(arrs),
                         is_stable=False))
    arrs = merge_sorted_runs(arrs)
    return arrs[-1].reshape(-1)[:n_real].astype(jnp.int32)


def merge_sorted_runs(arrs: List[jnp.ndarray]) -> List[jnp.ndarray]:
    """Bitonic merge tree over C sorted runs; [C, L] arrays -> [1, C*L].

    Each input row must be sorted ascending by the word tuple (ties
    allowed); C and L must be powers of two. log C merge levels, each a
    reshape-based compare-exchange cascade — no gathers. This is the
    back half of the chunked sort, exposed for callers whose runs are
    already sorted (Sort phase 3 merges the W received rank-ordered
    runs this way instead of re-sorting from scratch)."""
    C, L = arrs[0].shape
    while C > 1:
        # pair neighbouring runs: ascending ++ descending is bitonic
        paired = [a.reshape(C // 2, 2, L) for a in arrs]
        arrs = [jnp.concatenate(
                    [p[:, 0, :], jnp.flip(p[:, 1, :], axis=1)], axis=1)
                for p in paired]
        C //= 2
        L *= 2
        d = L // 2
        while d >= 1:
            arrs = _compare_exchange(arrs, d)
            d //= 2
    return arrs


def _bitonic_argsort(words: List[jnp.ndarray],
                     index_dtype=jnp.uint64) -> jnp.ndarray:
    n_real = words[0].shape[0]
    if n_real == 1:
        return jnp.zeros(1, jnp.int32)
    # pad to a power of two with max-words; pads carry the largest iota
    # so they sort strictly last and perm[:n_real] is exactly the sorted
    # real items (handles non-pow2 caps, e.g. after local concat)
    n = 1 << (n_real - 1).bit_length()
    pad = n - n_real
    k = n.bit_length() - 1
    # original index as the final key word: total order -> stability
    iota = jnp.arange(n, dtype=index_dtype)
    arrs = tuple(jnp.concatenate([w, jnp.full(pad, jnp.iinfo(w.dtype).max,
                                              w.dtype)])
                 if pad else w for w in words) + (iota,)

    stages = [(s, ss) for s in range(k) for ss in range(s, -1, -1)]
    stage_of = jnp.array([s for s, _ in stages], jnp.int32)
    dist_of = jnp.array([1 << ss for _, ss in stages], jnp.int32)
    i = jnp.arange(n, dtype=jnp.int32)

    def body(t, arrs):
        d = dist_of[t]
        s = stage_of[t]
        p = i ^ d
        partner = tuple(jnp.take(a, p) for a in arrs)
        up = ((i >> (s + 1)) & 1) == 0
        want_min = up == (i < p)
        gt = jnp.zeros(n, bool)
        eq = jnp.ones(n, bool)
        for a, b in zip(arrs, partner):
            gt = gt | (eq & (a > b))
            eq = eq & (a == b)
        take_partner = jnp.where(want_min, gt, ~gt)   # eq impossible
        return tuple(jnp.where(take_partner, b, a)
                     for a, b in zip(arrs, partner))

    arrs = lax.fori_loop(0, len(stages), body, arrs)
    return arrs[-1].astype(jnp.int32)[:n_real]
