"""Device sort engine: XLA sort or explicit bitonic network.

The DIA operators sort through one entry point, ``argsort_words``
(stable argsort by a list of uint64 key words). Two interchangeable
implementations:

* ``xla``     — ``lax.sort`` multi-operand (fastest where the XLA sort
                lowering is healthy; always used on CPU).
* ``bitonic`` — an explicit bitonic network driven by ``lax.fori_loop``:
                k(k+1)/2 compare-exchange substages of pure elementwise
                gathers/selects. Compiles to a tiny program regardless
                of n, which matters on TPU toolchains whose sort
                lowering degrades at large row counts (observed: the
                axon single-chip backend stalls compiling sorts beyond
                ~64K rows). Requires n to be a power of two — DIA shard
                capacities already are.

Selection: THRILL_TPU_SORT_IMPL = auto (default) | xla | bitonic.
``auto`` uses xla on CPU backends and for small n, bitonic on
accelerators above the threshold.
"""

from __future__ import annotations

import os
from typing import List

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# above this row count, accelerator backends switch to bitonic in auto
XLA_SORT_MAX_N = 1 << 16


def _impl(n: int) -> str:
    mode = os.environ.get("THRILL_TPU_SORT_IMPL", "auto")
    if mode in ("xla", "bitonic"):
        return mode
    if jax.default_backend() == "cpu" or n <= XLA_SORT_MAX_N:
        return "xla"
    return "bitonic"


def argsort_words(words: List[jnp.ndarray]) -> jnp.ndarray:
    """Stable argsort by uint64 key words (lexicographic). [n] int32."""
    n = words[0].shape[0]
    if _impl(n) == "xla":
        iota = jnp.arange(n, dtype=jnp.uint64)
        res = lax.sort(tuple(words) + (iota,), dimension=0,
                       num_keys=len(words), is_stable=True)
        return res[-1].astype(jnp.int32)
    return _bitonic_argsort(words)


def _bitonic_argsort(words: List[jnp.ndarray]) -> jnp.ndarray:
    n_real = words[0].shape[0]
    if n_real == 1:
        return jnp.zeros(1, jnp.int32)
    # pad to a power of two with max-words; pads carry the largest iota
    # so they sort strictly last and perm[:n_real] is exactly the sorted
    # real items (handles non-pow2 caps, e.g. after local concat)
    n = 1 << (n_real - 1).bit_length()
    pad = n - n_real
    maxw = jnp.uint64(0xFFFFFFFFFFFFFFFF)
    k = n.bit_length() - 1
    # original index as the final key word: total order -> stability
    iota = jnp.arange(n, dtype=jnp.uint64)
    arrs = tuple(jnp.concatenate([w.astype(jnp.uint64),
                                  jnp.full(pad, maxw, jnp.uint64)])
                 if pad else w.astype(jnp.uint64) for w in words) + (iota,)

    stages = [(s, ss) for s in range(k) for ss in range(s, -1, -1)]
    stage_of = jnp.array([s for s, _ in stages], jnp.int32)
    dist_of = jnp.array([1 << ss for _, ss in stages], jnp.int32)
    i = jnp.arange(n, dtype=jnp.int32)

    def body(t, arrs):
        d = dist_of[t]
        s = stage_of[t]
        p = i ^ d
        partner = tuple(jnp.take(a, p) for a in arrs)
        up = ((i >> (s + 1)) & 1) == 0
        want_min = up == (i < p)
        gt = jnp.zeros(n, bool)
        eq = jnp.ones(n, bool)
        for a, b in zip(arrs, partner):
            gt = gt | (eq & (a > b))
            eq = eq & (a == b)
        take_partner = jnp.where(want_min, gt, ~gt)   # eq impossible
        return tuple(jnp.where(take_partner, b, a)
                     for a, b in zip(arrs, partner))

    arrs = lax.fori_loop(0, len(stages), body, arrs)
    return arrs[-1].astype(jnp.int32)[:n_real]
