"""External-memory host tables for ReduceByKey / GroupByKey.

Reference: thrill/core/reduce_by_hash_post_phase.hpp:44-120 — the post
table splits into partitions, spills the fullest partition's items to a
data::File when over the memory budget, and on PushData re-reduces each
spilled partition RECURSIVELY (deeper hash bits, smaller slices) until
a slice fits in RAM. GroupByKey's analog (api/group_by_key.hpp:188-216)
spills (key-)sorted runs and multiway-merges them so each group streams.

TPU-native framing: these are the HOST-storage backstops. The device
engines bound memory by construction (fixed-cap shards, segment ops);
host Python dicts do not — so the host reduce/group phases get the same
spill ladder Sort already has (api/ops/sort.py _em_sort): a negotiated
RAM grant (api/context.py negotiate_mem) sizes a deterministic entry
cap from one pickled sample, with /proc RSS growth (mem/manager.py
RssBudget) as ground-truth backstop, and the block store
(data/block_pool.py) absorbs spills RAM-first, disk beyond its soft
limit.

Hash-partition recursion uses DISJOINT 4-bit slices of the 64-bit
stable host hash per depth (top bits first), so a re-reduced partition
re-splits 16 ways on fresh bits; at MAX_DEPTH (48 consumed bits) a
slice holds only hash-colliding distinct keys — vanishing for 64-bit
hashes — and stays in RAM unconditionally.
"""

from __future__ import annotations

import heapq
import os
import pickle
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from ..common import hashing
from ..data.file import File
from ..mem.manager import RssBudget

PARTITION_BITS = 4
NUM_PARTITIONS = 1 << PARTITION_BITS
MAX_DEPTH = 12

#: test hook — forces the deterministic in-RAM entry cap regardless of
#: the negotiated grant (the analog of THRILL_TPU_HOST_SORT_RUN)
_CAP_ENV = "THRILL_TPU_HOST_TABLE_CAP"


def entry_cap(mem_limit: int, sample: Any, floor: int = 64) -> int:
    """In-RAM entry budget for one host table: the negotiated grant
    over one pickled sample's size (the reference sizes its table from
    the DIAMemUse grant over sizeof(KeyValuePair) the same way,
    reduce_by_hash_post_phase.hpp:44). Estimates, not truth — RssBudget
    backstops the difference."""
    env = os.environ.get(_CAP_ENV)
    if env:
        return max(int(env), 8)
    if not mem_limit:
        return 1 << 22
    try:
        est = len(pickle.dumps(
            sample, protocol=pickle.HIGHEST_PROTOCOL)) + 96
    except Exception:
        est = 256
    return max(floor, min(mem_limit // est, 1 << 26))


def _new_stats() -> Dict[str, int]:
    return {"spills": 0, "spilled_entries": 0, "max_depth": 0,
            "peak_entries": 0}


class EMReduceTable:
    """Memory-bounded reducing hash table with recursive re-reduce.

    ``insert`` folds (key, value) under ``reduce_fn``; when the in-RAM
    entry count passes the cap (or RSS passes the grant), the fullest
    partitions spill to block-store Files. ``emit`` yields the reduced
    values partition by partition, re-reducing spilled partitions
    through child tables keyed on deeper hash bits — working memory
    stays one table slice regardless of total distinct keys.

    Values inserted may themselves be partial aggregates (the post
    phase receives pre-reduced rows); associativity of ``reduce_fn``
    makes re-reducing spilled partials exact.
    """

    def __init__(self, reduce_fn: Callable[[Any, Any], Any], pool,
                 mem_limit: Optional[int], depth: int = 0,
                 stats: Optional[Dict[str, int]] = None) -> None:
        self.reduce_fn = reduce_fn
        self.pool = pool
        self.mem_limit = int(mem_limit or 0)
        self.depth = depth
        self.tables: List[dict] = [dict() for _ in range(NUM_PARTITIONS)]
        self.files: List[Optional[File]] = [None] * NUM_PARTITIONS
        self.stats = _new_stats() if stats is None else stats
        if depth > self.stats["max_depth"]:
            self.stats["max_depth"] = depth
        self.budget = RssBudget(self.mem_limit)
        self.cap: Optional[int] = None
        self.n = 0

    def _pidx(self, h: int) -> int:
        shift = 64 - PARTITION_BITS * (self.depth + 1)
        return (h >> shift) & (NUM_PARTITIONS - 1)

    def insert(self, key, value, h: Optional[int] = None) -> None:
        if h is None:
            h = hashing.stable_host_hash(key)
        t = self.tables[self._pidx(h)]
        cur = t.get(key)
        if cur is not None:
            t[key] = (h, self.reduce_fn(cur[1], value))
            # the combine path must ALSO watch real memory: aggregates
            # that grow (list/str concatenation, set union) blow the
            # grant with a constant entry count (round-5 reviewer)
            if self.depth < MAX_DEPTH and self.n >= 16 \
                    and self.budget.exceeded():
                self._spill_over_budget()
            return
        if self.cap is None:
            self.cap = entry_cap(self.mem_limit, (key, value))
        t[key] = (h, value)
        self.n += 1
        if self.n > self.stats["peak_entries"]:
            self.stats["peak_entries"] = self.n
        if self.depth < MAX_DEPTH and self.n >= 16 and (
                self.n >= self.cap or self.budget.exceeded()):
            self._spill_over_budget()

    def _spill_over_budget(self) -> None:
        """Spill fullest partitions until under half the cap — fewer,
        larger writes than the reference's one-partition-per-overflow,
        same invariant (reference: SpillAnyPartition,
        reduce_by_hash_post_phase.hpp:92). ALWAYS spills at least the
        fullest partition: an RSS-triggered call may arrive with few
        entries whose aggregates grew huge — the entry-count target
        alone would make it a no-op and the grant would keep blowing."""
        target = max((self.cap or 64) // 2, 8)
        order = sorted(range(NUM_PARTITIONS),
                       key=lambda p: -len(self.tables[p]))
        spilled_any = False
        for p in order:
            if spilled_any and self.n <= target:
                break
            t = self.tables[p]
            if not t:
                break
            f = self.files[p]
            if f is None:
                f = self.files[p] = File(pool=self.pool)
            with f.writer() as w:
                for k, (h, v) in t.items():
                    w.put((h, k, v))
            self.stats["spills"] += 1
            self.stats["spilled_entries"] += len(t)
            self.n -= len(t)
            t.clear()
            spilled_any = True
        self.budget.reset()

    def emit(self) -> Iterator[Any]:
        """Yield every reduced value exactly once. RAM-only partitions
        stream straight out; spilled ones flush their RAM remainder and
        re-reduce through a depth+1 child table."""
        for p in range(NUM_PARTITIONS):
            t = self.tables[p]
            f = self.files[p]
            if f is None:
                for (_h, v) in t.values():
                    yield v
                self.n -= len(t)
                t.clear()
                continue
            if t:
                with f.writer() as w:
                    for k, (h, v) in t.items():
                        w.put((h, k, v))
                self.n -= len(t)
                t.clear()
            child = EMReduceTable(self.reduce_fn, self.pool,
                                  self.mem_limit, self.depth + 1,
                                  self.stats)
            for h, k, v in f.consume_reader():
                child.insert(k, v, h)
            f.clear()
            self.files[p] = None
            yield from child.emit()
            child.close()

    def close(self) -> None:
        for t in self.tables:
            t.clear()
        for f in self.files:
            if f is not None:
                f.clear()
        self.files = [None] * NUM_PARTITIONS
        self.n = 0


def _run_order(row: Tuple[int, int, Any, Any]) -> Tuple[int, int]:
    return (row[0], row[1])


class EMGroupBuffer:
    """Arrival-order-preserving grouping with sorted-run spill.

    ``add`` buffers (hash, seq, key, item) rows; over budget, the
    buffer spills as a (hash, seq)-sorted run. ``groups`` yields
    ``(key, [items])`` per distinct key: with no spills, straight from
    an insertion-ordered dict (identical to the historical in-RAM
    path); with spills, a k-way merge of the runs on (hash, seq) makes
    all rows of one hash adjacent — one hash bucket (almost always one
    group) is materialized at a time, and the seq tiebreak keeps each
    group's items in ARRIVAL order across runs. The analog of the
    reference's sorted-run spill + multiway merge
    (api/group_by_key.hpp:188-216); working memory is one run buffer
    plus the largest single group.
    """

    def __init__(self, pool, mem_limit: Optional[int],
                 stats: Optional[Dict[str, int]] = None) -> None:
        self.pool = pool
        self.mem_limit = int(mem_limit or 0)
        self.rows: List[Tuple[int, int, Any, Any]] = []
        self.runs: List[File] = []
        self.seq = 0
        self.cap: Optional[int] = None
        self.budget = RssBudget(self.mem_limit)
        self.stats = _new_stats() if stats is None else stats

    def add(self, key, item, h: Optional[int] = None) -> None:
        if h is None:
            h = hashing.stable_host_hash(key)
        if self.cap is None:
            self.cap = entry_cap(self.mem_limit, (h, 0, key, item))
        self.rows.append((h, self.seq, key, item))
        self.seq += 1
        if len(self.rows) > self.stats["peak_entries"]:
            self.stats["peak_entries"] = len(self.rows)
        if len(self.rows) >= 16 and (len(self.rows) >= self.cap
                                     or self.budget.exceeded()):
            self._spill()

    def _spill(self) -> None:
        # (hash, seq) sort: pure int compares, items never touched
        self.rows.sort(key=_run_order)
        f = File(pool=self.pool)
        with f.writer() as w:
            for r in self.rows:
                w.put(r)
        self.runs.append(f)
        self.stats["spills"] += 1
        self.stats["spilled_entries"] += len(self.rows)
        self.rows = []
        self.budget.reset()

    def groups(self) -> Iterator[Tuple[Any, List[Any]]]:
        if not self.runs:
            g: dict = {}
            for _h, _s, k, v in self.rows:
                g.setdefault(k, []).append(v)
            self.rows = []
            yield from g.items()
            return
        if self.rows:
            self._spill()
        stream = heapq.merge(*[f.consume_reader() for f in self.runs],
                             key=_run_order)
        bucket_h: Optional[int] = None
        bucket: dict = {}
        for h, _s, k, v in stream:
            if h != bucket_h and bucket:
                yield from bucket.items()
                bucket = {}
            bucket_h = h
            bucket.setdefault(k, []).append(v)
        if bucket:
            yield from bucket.items()

    def close(self) -> None:
        for f in self.runs:
            f.clear()
        self.runs = []
        self.rows = []
