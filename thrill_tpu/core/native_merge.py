"""Native k-way merge of spilled runs over byte-encoded keys.

Driver for native/mwmerge.cpp (see its header): runs are pairs of
block-store Files — an ITEM file holding the run's (pos, item) records
in key order, and a KEY file holding the matching order-encoded key
bytes (core/order_key.py) as (offsets, blob) chunks. The native engine
consumes key chunks and emits the merged order as run indices plus the
winners' key bytes; items never leave Python, and only one key chunk
per run is resident, so the merge stays external-memory-friendly
(reference: the partial multiway merge bound, thrill/api/sort.hpp:229-
260, core/multiway_merge.hpp:132).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Iterator, List, Optional, Tuple

import numpy as np

from ..data.file import File

#: keys per spilled chunk item (a few hundred KB of key bytes for
#: typical keys — one chunk per run resident during the merge)
KEY_CHUNK = 8192

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        from ..common.native_build import build_and_load
        lib = build_and_load("mwmerge.cpp")
        if lib is not None:
            lib.mwm_create.restype = ctypes.c_void_p
            lib.mwm_create.argtypes = [ctypes.c_int32]
            lib.mwm_destroy.restype = None
            lib.mwm_destroy.argtypes = [ctypes.c_void_p]
            lib.mwm_done.restype = ctypes.c_int32
            lib.mwm_done.argtypes = [ctypes.c_void_p]
            lib.mwm_set_chunk.restype = ctypes.c_int32
            lib.mwm_set_chunk.argtypes = [
                ctypes.c_void_p, ctypes.c_int32, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32]
            lib.mwm_next.restype = ctypes.c_int64
            lib.mwm_next.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int32), ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int64]
        _LIB = lib
        return _LIB


def available() -> bool:
    if os.environ.get("THRILL_TPU_EM_MERGE", "native") == "py":
        return False
    return _load() is not None


def write_key_chunks(keys_file: File, key_bytes: List[bytes]) -> None:
    """Spill a sorted run's key bytes as (offsets, blob) chunk items."""
    with keys_file.writer() as w:
        for i in range(0, len(key_bytes), KEY_CHUNK):
            chunk = key_bytes[i:i + KEY_CHUNK]
            offs = np.zeros(len(chunk) + 1, dtype=np.int64)
            np.cumsum([len(b) for b in chunk], out=offs[1:])
            w.put((offs, b"".join(chunk)))


def write_key_chunks_fixed(keys_file: File, arr: np.ndarray) -> None:
    """Fixed-width variant of :func:`write_key_chunks`: ``arr`` is a
    key-sorted ``S{w}`` array; offsets are an arange and the blob is
    one raw-memory copy — no per-key Python objects at all.

    With native records on, each chunk spills as ONE raw ndarray item
    (the serializer RAW kind: header + memcpy, no pickle) and the feed
    side points the native merge straight into the decoded array —
    zero-copy both ways. ``THRILL_TPU_NATIVE_RECORDS=0`` restores the
    pickled ``(offs, blob)`` chunk items bit-identically."""
    from ..data import records
    w_ = arr.dtype.itemsize
    raw = records.enabled()
    with keys_file.writer() as wtr:
        for i in range(0, len(arr), KEY_CHUNK):
            chunk = arr[i:i + KEY_CHUNK]
            if raw:
                wtr.put(np.ascontiguousarray(chunk))
                wtr.flush()        # one RAW block per chunk item
            else:
                offs = np.arange(len(chunk) + 1, dtype=np.int64) * w_
                wtr.put((offs, chunk.tobytes()))


class _RunFeed:
    """One run's key-chunk stream; owns the live buffers the native
    engine points into (they must outlive the chunk's consumption)."""

    def __init__(self, reader) -> None:
        self.reader = reader
        self.offs: Optional[np.ndarray] = None
        self.blob: Optional[bytes] = None

    def feed(self, lib, handle, r: int) -> None:
        nxt = next(self.reader, None)
        if nxt is None:
            self.offs = np.zeros(1, dtype=np.int64)
            self.blob = b""
            rc = lib.mwm_set_chunk(
                handle, r, 0, self.offs.ctypes.data_as(ctypes.c_void_p),
                None, 1)
        elif isinstance(nxt, np.ndarray):
            # raw fixed-width chunk (write_key_chunks_fixed, native
            # records): synthesize arange offsets and point the engine
            # straight into the decoded array — no per-chunk bytes copy
            w = nxt.dtype.itemsize
            arr = np.ascontiguousarray(nxt)
            self.offs = np.arange(len(arr) + 1, dtype=np.int64) * w
            self.blob = arr               # owns the live buffer
            rc = lib.mwm_set_chunk(
                handle, r, len(arr),
                self.offs.ctypes.data_as(ctypes.c_void_p),
                arr.ctypes.data_as(ctypes.c_void_p), 0)
        else:
            offs, blob = nxt
            self.offs = np.ascontiguousarray(offs, dtype=np.int64)
            self.blob = bytes(blob)
            rc = lib.mwm_set_chunk(
                handle, r, len(self.offs) - 1,
                self.offs.ctypes.data_as(ctypes.c_void_p),
                ctypes.cast(ctypes.c_char_p(self.blob), ctypes.c_void_p),
                0)
        if rc != 0:
            raise RuntimeError(f"mwm_set_chunk failed for run {r}")


def _merge_group(item_files: List[File], key_files: List[File],
                 consume: bool,
                 submit=None) -> Iterator[Tuple[bytes, object]]:
    """Stream the native merge of one group: yields (key_bytes, item)
    in merged order. ``submit`` (readahead executor, data/writeback.py)
    gives each run's key/item streams one block of readahead."""
    lib = _load()
    assert lib is not None
    k = len(item_files)
    handle = lib.mwm_create(k)
    if not handle:
        raise RuntimeError("mwm_create failed")
    out_cap = 8192
    out_runs = np.empty(out_cap, dtype=np.uint32)
    out_offs = np.empty(out_cap + 1, dtype=np.int64)
    blob_cap = 1 << 20
    need = ctypes.c_int32(-1)
    try:
        feeds = [_RunFeed(kf.prefetch_reader(consume=consume,
                                             submit=submit))
                 for kf in key_files]
        item_readers = [f.prefetch_reader(consume=consume, submit=submit)
                        for f in item_files]
        for r, feed in enumerate(feeds):
            feed.feed(lib, handle, r)
        out_blob = ctypes.create_string_buffer(blob_cap)
        while True:
            cnt = lib.mwm_next(
                handle, out_runs.ctypes.data_as(ctypes.c_void_p),
                out_cap, ctypes.byref(need),
                out_offs.ctypes.data_as(ctypes.c_void_p),
                out_blob, blob_cap)
            if cnt < 0:
                raise RuntimeError("mwm_next failed")
            if cnt:
                # copy only the used prefix (blob_cap can be MBs after
                # a growth; .raw would copy all of it every round)
                blob = ctypes.string_at(out_blob, int(out_offs[cnt]))
                offs = out_offs
                runs = out_runs
                for i in range(cnt):
                    kb = blob[offs[i]:offs[i + 1]]
                    yield kb, next(item_readers[runs[i]])
            if need.value >= 0:
                feeds[need.value].feed(lib, handle, need.value)
                continue
            if lib.mwm_done(handle):
                return
            if cnt == 0:
                # next key alone exceeds the blob buffer: grow it
                blob_cap *= 4
                out_blob = ctypes.create_string_buffer(blob_cap)
    finally:
        lib.mwm_destroy(handle)


def _resolve_degree(max_merge_degree: int) -> int:
    if max_merge_degree <= 0:
        max_merge_degree = int(
            os.environ.get("THRILL_TPU_MAX_MERGE_DEGREE", "64") or 64)
    return max(max_merge_degree, 2)


def _reduce_degree(pairs: List[Tuple[File, File]], max_merge_degree: int,
                   consume: bool, made: List[File],
                   submit=None) -> List[Tuple[File, File]]:
    """Partially merge the smallest (item, key) file pairs into
    intermediate pairs until at most ``max_merge_degree`` remain
    (reference: the partial multiway merge bound, api/sort.hpp:229-260).
    Intermediates are appended to ``made`` (caller clears them);
    ``consume=False`` reads input runs with keep semantics so the
    caller's Files survive."""
    while len(pairs) > max_merge_degree:
        pairs.sort(key=lambda p: p[0].num_items)
        group, pairs = pairs[:max_merge_degree], pairs[max_merge_degree:]
        pool = group[0][0].pool
        mi, mk = File(pool=pool), File(pool=pool)
        kb_buf: List[bytes] = []
        with mi.writer() as wi, mk.writer() as wk:
            for kb, item in _merge_group(
                    [p[0] for p in group], [p[1] for p in group],
                    consume=consume, submit=submit):
                wi.put(item)
                kb_buf.append(kb)
                if len(kb_buf) >= KEY_CHUNK:
                    _put_chunk(wk, kb_buf)
                    kb_buf = []
            if kb_buf:
                _put_chunk(wk, kb_buf)
        if consume:
            for fi, fk in group:
                fi.clear()
                fk.clear()
        made.extend([mi, mk])
        # intermediates are always consumable (they are ours)
        pairs.append((mi, mk))
    return pairs


def merge_partitioned(item_files: List[File], key_files: List[File],
                      splitters_kb: List[bytes], out_lists: List[list],
                      consume: bool = True,
                      max_merge_degree: int = 0,
                      submit=None) -> None:
    """Merge + splitter-partition in one pass, appending items into
    ``out_lists`` directly (the EM sort's final phase).

    The splitters ride as ONE EXTRA RUN of the native merge: when the
    engine emits the splitter run, the partition index advances — so
    partitioning costs zero key comparisons in Python and the final
    merge never copies key bytes out of the engine at all. Tie
    semantics match the generic path exactly: the splitter run has the
    HIGHEST run index, so items whose key equals a splitter pop first
    (run-id tiebreak) and land in the current partition, like the
    generic ``k > split_keys[w]`` advance."""
    max_merge_degree = _resolve_degree(max_merge_degree)
    pairs = list(zip(item_files, key_files))
    made: List[File] = []
    lib = _load()
    assert lib is not None
    try:
        pairs = _reduce_degree(pairs, max_merge_degree, consume, made,
                               submit=submit)
        k = len(pairs)
        handle = lib.mwm_create(k + 1)      # +1: the splitter run
        if not handle:
            raise RuntimeError("mwm_create failed")
        out_cap = 8192
        out_runs = np.empty(out_cap, dtype=np.uint32)
        need = ctypes.c_int32(-1)
        try:
            feeds = [_RunFeed(p[1].prefetch_reader(consume=consume,
                                                   submit=submit))
                     for p in pairs]
            # project=1: only the item half of each (pos, item) record
            # is consumed here — columnar run blocks never decode
            # their pos columns at all (lazy decode, ISSUE 15)
            item_readers = [p[0].prefetch_reader(consume=consume,
                                                 submit=submit,
                                                 project=1)
                            for p in pairs]
            for r, feed in enumerate(feeds):
                feed.feed(lib, handle, r)
            sp_offs = np.zeros(len(splitters_kb) + 1, dtype=np.int64)
            np.cumsum([len(b) for b in splitters_kb], out=sp_offs[1:])
            sp_blob = b"".join(splitters_kb)
            rc = lib.mwm_set_chunk(
                handle, k, len(splitters_kb),
                sp_offs.ctypes.data_as(ctypes.c_void_p),
                ctypes.cast(ctypes.c_char_p(sp_blob), ctypes.c_void_p)
                if sp_blob else None, 1)
            if rc != 0:
                raise RuntimeError("mwm_set_chunk(splitters) failed")
            w = 0
            while True:
                cnt = lib.mwm_next(
                    handle, out_runs.ctypes.data_as(ctypes.c_void_p),
                    out_cap, ctypes.byref(need), None, None, 0)
                if cnt < 0:
                    raise RuntimeError("mwm_next failed")
                if cnt:
                    cur = out_lists[w]
                    for r in out_runs[:cnt].tolist():
                        if r == k:
                            w += 1
                            cur = out_lists[w]
                        else:
                            cur.append(next(item_readers[r]))
                if need.value >= 0:
                    feeds[need.value].feed(lib, handle, need.value)
                    continue
                if lib.mwm_done(handle):
                    return
        finally:
            lib.mwm_destroy(handle)
    finally:
        for f in made:
            f.clear()


def merge_key_files(item_files: List[File], key_files: List[File],
                    consume: bool = True,
                    max_merge_degree: int = 0, submit=None
                    ) -> Iterator[Tuple[bytes, object]]:
    """Merge sorted (item, key) file pairs; yields (key_bytes, item).

    Mirrors multiway_merge_files' bounded-degree strategy: when there
    are more runs than ``max_merge_degree``, the smallest runs are
    partially merged into intermediate item+key Files first, so at most
    max_merge_degree key chunks are resident at once."""
    max_merge_degree = _resolve_degree(max_merge_degree)
    pairs = list(zip(item_files, key_files))
    made: List[File] = []
    try:
        pairs = _reduce_degree(pairs, max_merge_degree, consume, made,
                               submit=submit)
        yield from _merge_group([p[0] for p in pairs],
                                [p[1] for p in pairs], consume=consume,
                                submit=submit)
    finally:
        for f in made:
            f.clear()


def _put_chunk(writer, kb_buf: List[bytes]) -> None:
    offs = np.zeros(len(kb_buf) + 1, dtype=np.int64)
    np.cumsum([len(b) for b in kb_buf], out=offs[1:])
    writer.put((offs, b"".join(kb_buf)))
