"""Key encoding: map item keys to lexicographic uint64 word vectors.

XLA's sort (and our Pallas kernels) compare fixed numbers of scalar
words, not arbitrary C++ comparators. Any key pytree whose leaves are
ints, floats, bools or fixed-width byte vectors is encoded into k uint64
"key words" whose lexicographic order equals the natural order of the
key (tuple order = left-to-right significance, matching the reference's
operator< on std::tuple / struct keys used by api/sort.hpp).

Encodings (all order-preserving):
* unsigned ints  -> zero-extended
* signed ints    -> bias by 2^63 (flip sign bit)
* floats         -> IEEE trick: if sign bit set, flip all bits, else flip
                    sign bit (total order incl. -0 < +0; NaN sorts last)
* uint8[L] bytes -> big-endian packed into ceil(L/8) words (shorter-is-
                    smaller padding with zeros — matches memcmp on
                    zero-padded fixed-width fields, e.g. TeraSort keys)
"""

from __future__ import annotations

from typing import Any, List

import jax
import jax.numpy as jnp
import numpy as np


def num_key_words(example_key_tree: Any) -> int:
    """Number of uint64 words the encoder will produce per item."""
    total = 0
    for leaf in jax.tree.leaves(example_key_tree):
        leaf = np.asarray(leaf) if not hasattr(leaf, "dtype") else leaf
        if leaf.dtype == np.uint8 and leaf.ndim >= 1:
            total += -(-leaf.shape[-1] // 8)
        else:
            total += 1
    return total


def encode_key_words(key_tree: Any) -> List[jnp.ndarray]:
    """Encode a batched key pytree (leaves [n] or [n, L]) to uint64 [n] words."""
    words: List[jnp.ndarray] = []
    for leaf in jax.tree.leaves(key_tree):
        dt = leaf.dtype
        if dt == jnp.uint8 and leaf.ndim >= 2:
            words.extend(_pack_bytes(leaf))
        elif jnp.issubdtype(dt, jnp.unsignedinteger):
            words.append(leaf.astype(jnp.uint64))
        elif jnp.issubdtype(dt, jnp.signedinteger) or dt == jnp.bool_:
            w = leaf.astype(jnp.int64).astype(jnp.uint64)
            words.append(w ^ jnp.uint64(1 << 63))
        elif jnp.issubdtype(dt, jnp.floating):
            bits = jax.lax.bitcast_convert_type(
                leaf.astype(jnp.float64), jnp.uint64)
            sign = bits >> jnp.uint64(63)
            flipped = jnp.where(sign == 1, ~bits, bits | jnp.uint64(1 << 63))
            words.append(flipped)
        else:
            raise TypeError(f"unsupported key leaf dtype {dt}")
    if not words:
        raise ValueError("key function produced an empty pytree")
    return words


def encode_key_words_np(key_tree: Any) -> List[np.ndarray]:
    """Host mirror of :func:`encode_key_words` over numpy leaves —
    identical word values, no XLA dispatch (used by the CPU backend's
    native radix sort path, where eager jnp op overhead would dominate
    the sort itself)."""
    words: List[np.ndarray] = []
    for leaf in jax.tree.leaves(key_tree):
        leaf = np.asarray(leaf)
        dt = leaf.dtype
        if dt == np.uint8 and leaf.ndim == 2:
            n, L = leaf.shape
            nwords = -(-L // 8)
            padded = np.zeros((n, nwords * 8), dtype=np.uint8)
            padded[:, :L] = leaf
            packed = padded.view(np.dtype(">u8")).astype(np.uint64)
            words.extend(packed[:, i] for i in range(nwords))
        elif dt == np.uint8 and leaf.ndim > 2:
            # >2-D byte keys produce non-flat words in the traced
            # encoder; no host mirror — let callers fall back to it
            raise TypeError("encode_key_words_np: >2-D uint8 key leaf")
        elif np.issubdtype(dt, np.unsignedinteger):
            words.append(leaf.astype(np.uint64))
        elif np.issubdtype(dt, np.signedinteger) or dt == np.bool_:
            words.append(leaf.astype(np.int64).astype(np.uint64)
                         ^ np.uint64(1 << 63))
        elif np.issubdtype(dt, np.floating):
            bits = leaf.astype(np.float64).view(np.uint64)
            sign = bits >> np.uint64(63)
            words.append(np.where(sign == 1, ~bits,
                                  bits | np.uint64(1 << 63)))
        else:
            raise TypeError(f"unsupported key leaf dtype {dt}")
    if not words:
        raise ValueError("key function produced an empty pytree")
    return words


def _pack_bytes(leaf: jnp.ndarray) -> List[jnp.ndarray]:
    """[n, L] uint8 -> ceil(L/8) big-endian uint64 [n] words."""
    n, L = leaf.shape[0], leaf.shape[-1]
    nwords = -(-L // 8)
    padded = jnp.pad(leaf, [(0, 0)] * (leaf.ndim - 1) + [(0, nwords * 8 - L)])
    grouped = padded.reshape(*leaf.shape[:-1], nwords, 8).astype(jnp.uint64)
    shifts = jnp.uint64(8) * jnp.arange(7, -1, -1, dtype=jnp.uint64)
    packed = jnp.sum(grouped << shifts, axis=-1, dtype=jnp.uint64)
    # -> [n, nwords]; split into word list
    return [packed[..., i] for i in range(nwords)]


def sort_by_words(words: List[jnp.ndarray], operands: List[jnp.ndarray]):
    """Stable multi-word sort along axis 0: returns (words, operands)
    permuted by lexicographic key order."""
    from .device_sort import argsort_words
    perm = argsort_words(list(words))
    take = lambda x: jnp.take(x, perm, axis=0)
    return [take(w) for w in words], [take(o) for o in operands]
