"""Golomb-Rice coded bit streams over delta-coded sorted integers.

Equivalent of the reference's bit/Golomb/delta streams
(reference: thrill/core/bit_stream.hpp:29, golomb_bit_stream.hpp:29,145,
delta_stream.hpp) used by LocationDetection and DuplicateDetection to
exchange sorted hash lists compactly: sorted values are delta-coded and
each delta is Golomb-Rice encoded with parameter b (quotient unary,
remainder in floor(log2 b) or ceil bits — we use the Rice special case
b = 2^k for branch-free codecs).
"""

from __future__ import annotations

from typing import Iterable, Iterator, List

import numpy as np


class BitWriter:
    def __init__(self) -> None:
        self._bits: List[int] = []

    def put_bits(self, value: int, nbits: int) -> None:
        for i in range(nbits - 1, -1, -1):
            self._bits.append((value >> i) & 1)

    def put_unary(self, q: int) -> None:
        self._bits.extend([1] * q)
        self._bits.append(0)

    def to_bytes(self) -> bytes:
        bits = self._bits
        out = bytearray((len(bits) + 7) // 8)
        for i, b in enumerate(bits):
            if b:
                out[i >> 3] |= 1 << (7 - (i & 7))
        return bytes(out)

    def __len__(self) -> int:
        return len(self._bits)


class BitReader:
    def __init__(self, data: bytes, nbits: int) -> None:
        self.data = data
        self.nbits = nbits
        self.pos = 0

    def get_bit(self) -> int:
        b = (self.data[self.pos >> 3] >> (7 - (self.pos & 7))) & 1
        self.pos += 1
        return b

    def get_bits(self, n: int) -> int:
        v = 0
        for _ in range(n):
            v = (v << 1) | self.get_bit()
        return v

    def get_unary(self) -> int:
        q = 0
        while self.get_bit():
            q += 1
        return q

    @property
    def exhausted(self) -> bool:
        return self.pos >= self.nbits


def rice_parameter(mean_delta: float) -> int:
    """Rice k ~ log2(mean delta) (reference picks b from the expected
    gap n_total/space like GolombKeyCounterPair setups)."""
    k = 0
    while (1 << (k + 1)) < mean_delta:
        k += 1
    return k


def encode_sorted(values: Iterable[int], k: int) -> tuple:
    """Delta + Rice(2^k) encode a sorted non-negative sequence.
    Returns (payload bytes, nbits, count)."""
    w = BitWriter()
    prev = -1
    count = 0
    for v in values:
        delta = v - prev - 1        # strictly increasing -> delta >= 0
        assert delta >= 0, "encode_sorted requires strictly increasing"
        w.put_unary(delta >> k)
        if k:
            w.put_bits(delta & ((1 << k) - 1), k)
        prev = v
        count += 1
    return w.to_bytes(), len(w), count


def decode_sorted(payload: bytes, nbits: int, count: int, k: int
                  ) -> Iterator[int]:
    r = BitReader(payload, nbits)
    prev = -1
    for _ in range(count):
        q = r.get_unary()
        rem = r.get_bits(k) if k else 0
        delta = (q << k) | rem
        prev = prev + delta + 1
        yield prev


def encode_sorted_np(values: np.ndarray, k: int) -> tuple:
    return encode_sorted([int(v) for v in values], k)
