"""Golomb-Rice coded bit streams over delta-coded sorted integers.

Equivalent of the reference's bit/Golomb/delta streams
(reference: thrill/core/bit_stream.hpp:29, golomb_bit_stream.hpp:29,145,
delta_stream.hpp) used by LocationDetection and DuplicateDetection to
exchange sorted hash lists compactly: sorted values are delta-coded and
each delta is Golomb-Rice encoded with parameter b (quotient unary,
remainder in floor(log2 b) or ceil bits — we use the Rice special case
b = 2^k for branch-free codecs).
"""

from __future__ import annotations

from typing import Iterable, Iterator, List

import numpy as np


class BitWriter:
    def __init__(self) -> None:
        self._bits: List[int] = []

    def put_bits(self, value: int, nbits: int) -> None:
        for i in range(nbits - 1, -1, -1):
            self._bits.append((value >> i) & 1)

    def put_unary(self, q: int) -> None:
        self._bits.extend([1] * q)
        self._bits.append(0)

    def to_bytes(self) -> bytes:
        bits = self._bits
        out = bytearray((len(bits) + 7) // 8)
        for i, b in enumerate(bits):
            if b:
                out[i >> 3] |= 1 << (7 - (i & 7))
        return bytes(out)

    def __len__(self) -> int:
        return len(self._bits)


class BitReader:
    def __init__(self, data: bytes, nbits: int) -> None:
        self.data = data
        self.nbits = nbits
        self.pos = 0

    def get_bit(self) -> int:
        b = (self.data[self.pos >> 3] >> (7 - (self.pos & 7))) & 1
        self.pos += 1
        return b

    def get_bits(self, n: int) -> int:
        v = 0
        for _ in range(n):
            v = (v << 1) | self.get_bit()
        return v

    def get_unary(self) -> int:
        q = 0
        while self.get_bit():
            q += 1
        return q

    @property
    def exhausted(self) -> bool:
        return self.pos >= self.nbits


def rice_parameter(mean_delta: float) -> int:
    """Rice k ~ log2(mean delta) (reference picks b from the expected
    gap n_total/space like GolombKeyCounterPair setups)."""
    k = 0
    while (1 << (k + 1)) < mean_delta:
        k += 1
    return k


def encode_sorted(values: Iterable[int], k: int) -> tuple:
    """Delta + Rice(2^k) encode a sorted non-negative sequence.
    Returns (payload bytes, nbits, count)."""
    w = BitWriter()
    prev = -1
    count = 0
    for v in values:
        delta = v - prev - 1        # strictly increasing -> delta >= 0
        assert delta >= 0, "encode_sorted requires strictly increasing"
        w.put_unary(delta >> k)
        if k:
            w.put_bits(delta & ((1 << k) - 1), k)
        prev = v
        count += 1
    return w.to_bytes(), len(w), count


def decode_sorted(payload: bytes, nbits: int, count: int, k: int
                  ) -> Iterator[int]:
    r = BitReader(payload, nbits)
    prev = -1
    for _ in range(count):
        q = r.get_unary()
        rem = r.get_bits(k) if k else 0
        delta = (q << k) | rem
        prev = prev + delta + 1
        yield prev


def encode_sorted_np(values: np.ndarray, k: int) -> tuple:
    """Vectorized twin of :func:`encode_sorted` — bit-identical output
    (same MSB-first layout ``np.packbits`` produces), numpy-speed.

    The per-bit Python writer above costs ~1 us/bit; the wire codec
    (net/wire.py) ships whole hash/fingerprint columns through Rice
    streams, where that is the difference between a codec and a stall.
    Layout per value: ``delta >> k`` one-bits, a zero terminator, then
    the low ``k`` delta bits MSB-first.
    """
    v = np.asarray(values, dtype=np.int64)
    if v.size == 0:
        return b"", 0, 0
    if int(v[0]) < 0:
        raise AssertionError("encode_sorted requires strictly increasing")
    gaps = np.diff(v)
    if v.size > 1 and int(gaps.min()) <= 0:
        raise AssertionError("encode_sorted requires strictly increasing")
    deltas = np.empty(v.size, dtype=np.uint64)
    deltas[0] = np.uint64(int(v[0]))              # delta = v0 - (-1) - 1
    deltas[1:] = (gaps - 1).astype(np.uint64)
    q = deltas >> np.uint64(k)
    widths = q + np.uint64(1 + k)                 # bits per value
    ends = np.cumsum(widths)                      # bit offset AFTER value i
    total = int(ends[-1])
    bits = np.ones(total, dtype=np.uint8)         # unary runs default to 1
    # zero terminator of value i sits k+1 bits before its end
    bits[(ends - np.uint64(1 + k)).astype(np.int64)] = 0
    if k:
        rem = deltas & np.uint64((1 << k) - 1)
        for j in range(k):                        # MSB-first remainder
            bits[(ends - np.uint64(k - j)).astype(np.int64)] = \
                ((rem >> np.uint64(k - 1 - j)) & np.uint64(1)).astype(
                    np.uint8)
    return np.packbits(bits).tobytes(), total, int(v.size)


def decode_sorted_np(payload: bytes, nbits: int, count: int,
                     k: int) -> np.ndarray:
    """Vectorized twin of :func:`decode_sorted` (returns int64 array).

    Unary terminators interleave with fixed-width remainders, so the
    stream is walked value by value — but each step is O(log z) over
    the precomputed zero-bit positions (searchsorted), not a per-bit
    Python loop, and the remainder bits extract vectorized at the end.
    """
    if count == 0:
        return np.zeros(0, dtype=np.int64)
    bits = np.unpackbits(np.frombuffer(payload, dtype=np.uint8),
                         count=nbits)
    zeros_at = np.flatnonzero(bits == 0)
    starts = np.empty(count, dtype=np.int64)      # unary-run starts
    terms = np.empty(count, dtype=np.int64)       # zero-terminator pos
    pos = 0
    zi = 0
    for i in range(count):
        zi = np.searchsorted(zeros_at, pos, side="left")
        if zi >= len(zeros_at):
            raise ValueError("golomb: truncated Rice stream")
        z = int(zeros_at[zi])
        starts[i] = pos
        terms[i] = z
        pos = z + 1 + k
    if pos > nbits:
        raise ValueError("golomb: truncated Rice stream")
    q = (terms - starts).astype(np.uint64)
    deltas = q << np.uint64(k)
    if k:
        rem = np.zeros(count, dtype=np.uint64)
        for j in range(k):                        # MSB-first remainder
            rem = (rem << np.uint64(1)) | bits[terms + 1 + j].astype(
                np.uint64)
        deltas |= rem
    return (np.cumsum(deltas.astype(np.int64) + 1) - 1).astype(np.int64)
