"""Resumable external-memory runs: the spilled run IS a checkpoint.

The EM sort (api/ops/sort.py) forms sorted runs and spills them through
the write-behind writer; until now a crash between run formation and
the merge threw ALL of that work away — the relaunch re-sorted the
world. This module makes each spilled run durable and reusable: the
spill job serializes the run's blocks to
``<ckpt_dir>/em_runs/<signature>/run_<slot>.bin`` and, only after those
bytes are durably on storage, commits a CRC'd JSON manifest beside
them via ``write_file_atomic`` — the same publish-then-commit protocol
the epoch checkpoints use (api/checkpoint.py), so a SIGKILL at ANY
point leaves either a committed, verifiable run or nothing visible.

On relaunch with ``Config(resume=True)``, the sort re-streams its
input (the scan and the reservoir sampler must see identical items for
bit-identical splitters) but each run's expensive tail — argsort,
serialize, disk write — is skipped when a committed run matches the
identity check: same slot, same position range, same first-item
fingerprint. Matches count ``runs_reused`` (common/iostats.py) and
``resume_skipped_runs`` (the checkpoint manager's resume ledger);
a missing manifest silently re-forms the run (normal — the crash beat
the commit), while a CORRUPT or mismatching one is reported LOUDLY via
``faults.note("recovery", ...)`` and the run re-forms from scratch —
never wrong data, never a silent fallback.

The run signature pins (node id, label, W, run_size, input size, host
rank): node ids are deterministic per-Context counters, so a relaunch
of the same program maps each Sort to the same store directory, and two
different Sorts (different key functions) can never alias. Run
BOUNDARIES must also line up — they do whenever ``run_size`` governs
the cut; an RSS-pressure early spill (mem/manager.py) that fired in one
launch but not the other shifts ``pos0`` and fails the identity check,
degrading to a re-sort of that run (documented in ARCHITECTURE.md).

All storage goes through the vfs seam (vfs/file_io.py), so run stores
work unchanged over ``file://`` and remote object stores.
``THRILL_TPU_EM_RESUME=0`` disables the store entirely (no writes, no
reuse). Every public entry point is exception-safe: a store failure
degrades to the non-resumable behavior, it never poisons the sort.
"""

from __future__ import annotations

import json
import os
import pickle
import struct
import threading
import zlib
from typing import Optional, Tuple

from ..common import faults

# fault site: armed at manifest commit AND manifest load, so the chaos
# matrix can prove both "crash before commit re-forms the run" and
# "corrupt manifest re-forms LOUDLY" (tests/common/test_faults.py)
_F_MANIFEST = faults.declare("em.run.manifest")

_MAGIC = 0x454D5231  # "EMR1"

# orphan-run adoption (elastic mesh): a rank that JOINS an elastic
# group (net.tcp.join_tcp_group) as the replacement for a departed
# rank scans the run store for its rank id's committed runs and adopts
# them instead of re-forming them. OWNER.json records which process
# owns a signature dir (liveness-checked before adoption — a store
# whose owner still runs is NOT an orphan); ADOPTED.json marks a
# claimed store so its RunStore loads runs even when the joiner's own
# Context is not in global resume mode. Adoption is deliberately
# scoped to the SAME rank id: the host rank in the run signature pins
# the input partition that rank processed, so another rank's runs
# could never pass the (slot, pos0, n, fp) identity check anyway.
_OWNER = "OWNER.json"
_ADOPTED = "ADOPTED.json"
_adopt_lock = threading.Lock()
_adopted = 0


def adopted_total() -> int:
    """Process-wide count of runs adopted from departed owners —
    surfaced as ``runs_adopted`` in ``Context.overall_stats`` and
    pinned EXACTLY zero on non-elastic workloads by the perf
    sentinel."""
    with _adopt_lock:
        return _adopted


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(int(pid), 0)
        return True
    except (OSError, ValueError, TypeError):
        return False


def adopt_orphan_runs(ckpt_dir: str, my_rank: int) -> int:
    """Adopt the committed EM runs a DEPARTED rank left behind.

    Called by a rank joining an elastic group (and by the relaunch
    path when a resize marker is consumed): scan
    ``<ckpt_dir>/em_runs/*_h<my_rank>`` for signature dirs whose
    recorded owner process is gone, verify each committed run
    (manifest JSON validity + bin present at the manifested byte
    size — the full CRC still runs at ``try_load`` before any byte is
    reused), claim ownership, and mark the store ADOPTED so its runs
    load without global resume mode. Returns the number of runs
    adopted; exception-safe and silent on a missing store (a joiner
    into a group that never spilled adopts nothing). Remote object
    stores are skipped — there is no cheap liveness/listing seam, and
    the joiner's normal resume path covers them."""
    global _adopted
    if not _enabled() or not ckpt_dir or _is_remote(ckpt_dir):
        return 0
    base = os.path.join(ckpt_dir.rstrip("/"), "em_runs")
    suffix = f"_h{int(my_rank)}"
    total = 0
    try:
        sigs = sorted(os.listdir(base))
    except OSError:
        return 0                       # no store: nothing ever spilled
    for sig in sigs:
        if not sig.endswith(suffix):
            continue
        sdir = os.path.join(base, sig)
        if not os.path.isdir(sdir) \
                or os.path.isfile(os.path.join(sdir, _ADOPTED)):
            continue                   # already claimed
        try:
            owner = None
            opath = os.path.join(sdir, _OWNER)
            try:
                with open(opath, "rb") as fh:
                    owner = json.loads(fh.read().decode("ascii"))
            except (OSError, ValueError):
                owner = None           # ownerless pre-adoption store
            if owner is not None:
                pid = owner.get("pid")
                if pid == os.getpid():
                    continue           # my own store, nothing to adopt
                if _pid_alive(pid):
                    continue           # owner still runs: NOT an orphan
            verified = 0
            for name in sorted(os.listdir(sdir)):
                if not (name.startswith("run_")
                        and name.endswith(".json")):
                    continue
                try:
                    with open(os.path.join(sdir, name), "rb") as fh:
                        man = json.loads(fh.read().decode("ascii"))
                    bin_path = os.path.join(
                        sdir, name[:-len(".json")] + ".bin")
                    if not all(k in man for k in
                               ("slot", "pos0", "n", "fp",
                                "crc", "bin_bytes")):
                        raise ValueError("manifest missing keys")
                    if os.path.getsize(bin_path) != man["bin_bytes"]:
                        raise ValueError("bin size mismatch")
                    verified += 1
                except (OSError, ValueError) as e:
                    faults.note("recovery",
                                what="em_runs.adopt_skipped_run",
                                sig=sig, run=name,
                                error=repr(e)[:200])
            if not verified:
                continue               # nothing committed to claim
            from ..vfs.file_io import write_file_atomic
            write_file_atomic(
                os.path.join(sdir, _ADOPTED),
                json.dumps({"runs": verified, "by_pid": os.getpid(),
                            "from_pid": (owner or {}).get("pid")}
                           ).encode("ascii"))
            write_file_atomic(opath, json.dumps(
                {"pid": os.getpid(),
                 "rank": int(my_rank)}).encode("ascii"))
            total += verified
            faults.note("recovery", what="em_runs.adopted",
                        sig=sig, runs=verified, _quiet=True)
        except Exception as e:
            faults.note("recovery", what="em_runs.adopt_failed",
                        sig=sig, error=repr(e)[:200])
    if total:
        with _adopt_lock:
            _adopted += total
    return total


def _enabled() -> bool:
    return os.environ.get("THRILL_TPU_EM_RESUME", "1") != "0"


def _is_remote(path: str) -> bool:
    return "://" in path and not path.startswith("file://")


def fingerprint(item) -> int:
    """Cheap identity of a run: CRC of the FIRST item in arrival
    order. Combined with (slot, pos0, n) this pins the run to its exact
    position range of the exact input stream — a changed input or a
    shifted run boundary cannot silently reuse stale bytes."""
    try:
        return zlib.crc32(pickle.dumps(item, protocol=4)) & 0xFFFFFFFF
    except Exception:
        return 0


def store_for(ctx, node_id: int, label: str, W: int, run_size: int,
              total: int) -> Optional["RunStore"]:
    """The run store of one EM sort, or None when checkpointing is off
    (``ctx.checkpoint is None``) or ``THRILL_TPU_EM_RESUME=0``."""
    ckpt = getattr(ctx, "checkpoint", None)
    if ckpt is None or not _enabled():
        return None
    try:
        sig = (f"n{node_id}_{label.lower()}_w{W}_r{run_size}"
               f"_t{total}_h{getattr(ctx, 'host_rank', 0)}")
        base = os.path.join(ckpt.dir.rstrip("/"), "em_runs", sig)
        return RunStore(base, mgr=ckpt)
    except Exception as e:
        faults.note("recovery", what="em_runs.store_unavailable",
                    error=repr(e)[:200])
        return None


class RunStore:
    """Commit/reload of one sort's spilled runs under one signature
    directory. ``commit`` runs inside the write-behind spill job (the
    run's blocks are resident right after the job wrote them);
    ``try_load`` runs on the main thread inside ``spill()`` before the
    job would be submitted."""

    def __init__(self, base: str, mgr=None) -> None:
        self.base = base
        self.mgr = mgr          # CheckpointManager (resume ledger)
        self.resume = bool(getattr(mgr, "resume", False))
        # an ADOPTED store (orphan runs claimed by this process after
        # an elastic join) loads its runs even without global resume
        # mode. Probed only when adoption actually happened in this
        # process — non-elastic workloads never pay the stat.
        if not self.resume and adopted_total() > 0 \
                and not _is_remote(base) \
                and os.path.isfile(os.path.join(base, _ADOPTED)):
            self.resume = True
        # commit concurrency: commits of DIFFERENT runs are
        # independent (only bin-before-manifest within one run is
        # ordered), and against remote storage each one is
        # latency-bound — serializing them behind the single
        # write-behind thread would put 2 round trips per run on the
        # spill critical path. A small pool overlaps them; the sync
        # ladder (THRILL_TPU_WRITEBACK=0) keeps commits inline on the
        # caller so the bench A/B measures exactly this machinery.
        self._pool = None
        self._pending: list = []
        # resume-side warm state (one Glob + concurrent manifest
        # fetches on first try_load; bins ride a bounded readahead
        # window) — against remote storage the old 2-serial-GETs-per-
        # run on the foreground thread cost MORE than re-forming runs
        self._manfut: dict = {}             # manifest path -> Future
        self._committed: Optional[set] = None   # slots seen in Glob
        self._binfut: dict = {}             # bin path -> Future
        self._warm_evt: Optional[threading.Event] = None
        if not _is_remote(base):
            os.makedirs(base, exist_ok=True)
            # ownership record for the elastic orphan-adoption scan:
            # which process currently owns this signature dir. Local
            # stores only (adoption itself is local-only) and best-
            # effort — a failed write just makes the store ownerless,
            # which adoption treats as adoptable-after-verification.
            try:
                from ..vfs.file_io import write_file_atomic
                write_file_atomic(
                    os.path.join(base, _OWNER),
                    json.dumps({"pid": os.getpid()}).encode("ascii"))
            except Exception:
                pass
        if self.resume:
            # warm from CONSTRUCTION, not first try_load: the sort
            # re-streams its whole input before it cuts the first run,
            # so the LIST + manifest GETs (and the first bin window)
            # finish behind that scan instead of on the reuse path
            self._warm_evt = threading.Event()
            threading.Thread(target=self._warm_bg, daemon=True,
                             name="thrill-tpu-em-warm").start()

    def _commit_async(self) -> bool:
        from ..data.writeback import writeback_enabled
        return writeback_enabled()

    def _conc(self) -> int:
        try:
            return max(1, int(os.environ.get(
                "THRILL_TPU_EM_COMMIT_CONC", "4") or 4))
        except ValueError:
            return 4

    def _ensure_pool(self):
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=self._conc(),
                thread_name_prefix="thrill-tpu-em-commit")
        return self._pool

    @staticmethod
    def _read_path(path: str) -> bytes:
        from ..vfs.file_io import OpenReadStream
        with OpenReadStream(path) as r:
            return r.read()

    def _paths(self, slot: int) -> Tuple[str, str]:
        return (os.path.join(self.base, f"run_{slot:06d}.bin"),
                os.path.join(self.base, f"run_{slot:06d}.json"))

    # -- serialization ---------------------------------------------------
    @staticmethod
    def _pack_file(f) -> bytes:
        """Blocks of one File as length-prefixed payload records.
        Layout: [u32 nblocks] then per block [u32 lo][u32 hi]
        [u64 len][payload] — lo/hi preserved so sliced views (never
        produced by the spill jobs today, but cheap to carry) rebuild
        exactly."""
        parts = [struct.pack("<I", len(f.blocks))]
        for b in f.blocks:
            payload = f.pool.get(b.bid)
            parts.append(struct.pack("<IIQ", b.lo, b.hi, len(payload)))
            parts.append(payload)
        return b"".join(parts)

    @staticmethod
    def _unpack_file(body: bytes, off: int, pool, block_items: int):
        from ..data.file import File
        from ..data.block import Block
        (nblocks,) = struct.unpack_from("<I", body, off)
        off += 4
        f = File(pool=pool, block_items=block_items)
        for _ in range(nblocks):
            lo, hi, plen = struct.unpack_from("<IIQ", body, off)
            off += 16
            payload = body[off:off + plen]
            if len(payload) != plen:
                raise ValueError("truncated run payload")
            off += plen
            bid = pool.put(payload)
            f.blocks.append(Block(pool, bid, lo, hi))
        return f, off

    # -- commit ----------------------------------------------------------
    def commit(self, slot: int, pos0: int, n: int, fp: int,
               f, kf=None) -> bool:
        """Persist one spilled run. Called from the spill job AFTER
        ``files[slot]``/``key_files[slot]`` are set (blocks durable in
        the pool). Exception-safe: a failed commit is noted and the run
        simply stays non-resumable."""
        from ..vfs.file_io import write_file_atomic
        bin_path, man_path = self._paths(slot)
        try:
            body = struct.pack("<I", _MAGIC) + self._pack_file(f)
            has_keys = kf is not None and kf.blocks
            body += self._pack_file(kf) if has_keys \
                else struct.pack("<I", 0)
            # bin first, manifest only after the bytes are durable —
            # the manifest's existence IS the commit record
            write_file_atomic(bin_path, body)
            faults.check(_F_MANIFEST, path=man_path, op="commit")
            manifest = {"slot": slot, "pos0": pos0, "n": n, "fp": fp,
                        "crc": zlib.crc32(body) & 0xFFFFFFFF,
                        "bin_bytes": len(body),
                        "has_keys": bool(has_keys)}
            write_file_atomic(
                man_path, json.dumps(manifest).encode("ascii"))
            if self._committed is not None:
                self._committed.add(slot)   # keep the warm listing's
                                            # negative cache truthful
            return True
        except Exception as e:
            faults.note("recovery", what="em_runs.commit_failed",
                        slot=slot, error=repr(e)[:200])
            return False

    def submit_commit(self, slot: int, pos0: int, n: int, fp: int,
                      f, kf=None) -> None:
        """Commit, concurrently when the overlap tier is on. The spill
        job calls this after ``files[slot]`` is set; the blocks are
        immutable from then on, so packing them on a pool thread races
        nothing. ``drain()`` joins every pending commit at the sort's
        pre-merge barrier. Exception-safe like ``commit``."""
        if not self._commit_async():
            self.commit(slot, pos0, n, fp, f, kf)
            return
        try:
            self._pending.append(self._ensure_pool().submit(
                self.commit, slot, pos0, n, fp, f, kf))
        except Exception as e:
            faults.note("recovery", what="em_runs.commit_failed",
                        slot=slot, error=repr(e)[:200])

    def drain(self) -> None:
        """Join every in-flight commit (the sort's pre-merge barrier —
        after this, what is committed is committed and the merge may
        consume the pool blocks). Never raises: ``commit`` degrades
        internally."""
        pending, self._pending = self._pending, []
        for fut in pending:
            try:
                fut.result()
            except Exception:
                pass

    def close(self) -> None:
        self.drain()
        self._binfut.clear()
        self._manfut.clear()
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    # -- reuse -----------------------------------------------------------
    def _warm_bg(self) -> None:
        try:
            self._warm()
            if self._committed:
                self._prefetch_bins(min(self._committed))
        except Exception as e:
            faults.note("recovery", what="em_runs.warm_failed",
                        error=repr(e)[:200])
        finally:
            self._warm_evt.set()

    def _warm(self) -> None:
        """One Glob, then a manifest fetch IN FLIGHT for every
        committed run. Against remote storage the per-slot probe
        pattern (manifest GET, then bin GET, serial, on the foreground
        thread) costs two round trips per run — at 20 ms each that
        made resume SLOWER than re-forming the runs. Only futures are
        installed here (the warm event sets right after), so the first
        ``try_load`` blocks on ITS slot's manifest alone, not on the
        whole gather; a slot absent from the listing returns None with
        zero requests."""
        try:
            from ..vfs.file_io import Glob
            fl = Glob(os.path.join(self.base, "run_*.json"))
            listed = [fi.path for fi in fl.files]
        except Exception as e:
            faults.note("recovery", what="em_runs.warm_failed",
                        error=repr(e)[:200])
            return                # fall back to per-slot direct reads
        committed = set()
        for p in listed:
            stem = os.path.basename(p)
            try:
                committed.add(int(stem[len("run_"):-len(".json")]))
            except ValueError:
                pass
        ex = self._ensure_pool()
        self._manfut = {p: ex.submit(self._read_path, p)
                        for p in listed}
        self._committed = committed

    def _prefetch_bins(self, slot: int) -> None:
        """Keep the bins of the next few committed slots in flight —
        the merge consumes runs in slot order, so by the time
        ``try_load(slot)`` validates its manifest the bin bytes are
        usually already here. Window = pool width, so at most that
        many bins are buffered (popped as consumed)."""
        if self._committed is None:
            return
        ex = self._ensure_pool()
        for s in range(slot, slot + self._conc()):
            if s in self._committed:
                bp = self._paths(s)[0]
                if bp not in self._binfut:
                    self._binfut[bp] = ex.submit(self._read_path, bp)

    def try_load(self, slot: int, pos0: int, n: int, fp: int, pool,
                 block_items: int):
        """(item_file, key_file_or_None) of a committed matching run,
        or None. A missing manifest is silent (the run was never
        committed); a corrupt/mismatching one is LOUD — the caller
        re-forms the run either way, so the only cost of corruption is
        the re-sort, never wrong data."""
        if not self.resume:
            return None
        if self._warm_evt is not None:
            self._warm_evt.wait()
        bin_path, man_path = self._paths(slot)
        try:
            raw = None
            if self._committed is not None:
                if slot not in self._committed:
                    return None       # never committed: zero requests
                fut = self._manfut.pop(man_path, None)
                if fut is not None:
                    try:
                        raw = fut.result()
                    except Exception:
                        raw = None    # direct read decides loud/silent
            if raw is None:
                try:
                    raw = self._read_path(man_path)
                except FileNotFoundError:
                    return None           # never committed: normal
            faults.check(_F_MANIFEST, path=man_path, op="load")
            man = json.loads(raw.decode("ascii"))
            if (man.get("slot") != slot or man.get("pos0") != pos0
                    or man.get("n") != n or man.get("fp") != fp):
                raise ValueError(
                    f"run identity mismatch: manifest "
                    f"{({k: man.get(k) for k in ('slot', 'pos0', 'n', 'fp')})} "
                    f"!= live (slot={slot}, pos0={pos0}, n={n}, fp={fp})")
            self._prefetch_bins(slot)
            fut = self._binfut.pop(bin_path, None)
            body = fut.result() if fut is not None \
                else self._read_path(bin_path)
            if len(body) != man["bin_bytes"] or \
                    (zlib.crc32(body) & 0xFFFFFFFF) != man["crc"]:
                raise ValueError("run bin CRC/length mismatch")
            (magic,) = struct.unpack_from("<I", body, 0)
            if magic != _MAGIC:
                raise ValueError(f"bad run magic {magic:#x}")
            f, off = self._unpack_file(body, 4, pool, block_items)
            kf = None
            if man["has_keys"]:
                kf, off = self._unpack_file(body, off, pool,
                                            block_items)
            if self.mgr is not None:
                self.mgr.resume_skipped_runs += 1
            return f, kf
        except FileNotFoundError:
            return None
        except Exception as e:
            # LOUD: corruption/mismatch re-forms the run from scratch
            faults.note("recovery", what="em_runs.manifest_invalid",
                        slot=slot, path=man_path,
                        error=repr(e)[:200])
            return None
