"""Vectorized text tokenization: raw bytes -> fixed-width word matrix.

The device-side string story (SURVEY §7 "hard parts"): XLA programs
need static shapes, so variable-length words become [n, max_word]
zero-padded uint8 rows — the byte-key encoding keys.encode_key_words
already sorts/hashes lexicographically. This module turns a text chunk
into that packed matrix with numpy array ops only — no per-word Python
loop (the reference tokenizes per-item inside its FlatMap lambda,
examples/word_count/word_count.hpp:35-44; a Python-level equivalent
would dominate the whole pipeline).
"""

from __future__ import annotations

import numpy as np

#: ASCII whitespace ONLY. This is narrower than str.split(): Unicode
#: whitespace (U+00A0, U+2028, ...) does NOT separate words here, so
#: UTF-8 text using such separators tokenizes differently from the
#: host path's line.split(). The byte-level contract is deliberate —
#: it is what a static-shape device scan can evaluate per byte.
SEPARATORS = b" \t\n\r\x0b\x0c"

_SEP = np.zeros(256, dtype=bool)
_SEP[list(SEPARATORS)] = True


def sep_mask(data: np.ndarray) -> np.ndarray:
    """bool[n]: which bytes are word separators."""
    return _SEP[data]


def find_first_sep(data: bytes) -> int:
    """Offset of the first separator byte, or -1."""
    hits = np.flatnonzero(_SEP[np.frombuffer(data, dtype=np.uint8)])
    return int(hits[0]) if len(hits) else -1


def tokenize_packed(data, max_word: int = 16) -> np.ndarray:
    """Pack every whitespace-delimited word of ``data`` into a
    [n_words, max_word] uint8 matrix (zero padded, clipped at
    ``max_word`` bytes — matching the device WordCount contract).

    Contract (byte-level, see SEPARATORS): words split on ASCII
    whitespace only, and clipping at ``max_word`` BYTES may cut a
    multi-byte UTF-8 sequence mid-character (unpack_words decodes with
    errors='replace'). ASCII and single-byte-encoded text round-trips
    exactly; general Unicode text gets byte-truncation semantics."""
    if isinstance(data, (bytes, bytearray, memoryview)):
        a = np.frombuffer(data, dtype=np.uint8)
    else:
        a = np.asarray(data, dtype=np.uint8)
    if a.size == 0:
        return np.zeros((0, max_word), dtype=np.uint8)
    sep = _SEP[a]
    nonsep = ~sep
    # word starts: non-sep preceded by sep (or stream start)
    starts = np.flatnonzero(nonsep & np.concatenate(([True], sep[:-1])))
    if len(starts) == 0:
        return np.zeros((0, max_word), dtype=np.uint8)
    # word ends (exclusive): non-sep followed by sep (or stream end)
    ends = np.flatnonzero(nonsep & np.concatenate((sep[1:], [True]))) + 1
    lens = np.minimum(ends - starts, max_word)
    gather = starts[:, None] + np.arange(max_word)[None, :]
    valid = np.arange(max_word)[None, :] < lens[:, None]
    packed = np.where(valid, a[np.where(valid, gather, 0)], 0)
    return packed.astype(np.uint8)


def unpack_words(packed: np.ndarray) -> list:
    """[n, L] uint8 -> list of str (zero padding stripped)."""
    return [bytes(row).rstrip(b"\x00").decode("utf-8", "replace")
            for row in np.asarray(packed)]
