"""Packed row movement: u32 word views for sub-word payload columns.

TPU VPU lanes are 32-bit; a gather/scatter of a [n, 90] uint8 payload
column moves 90 sub-word elements per row where 23 u32 words would do.
Every bulk row movement (sort payload gathers, exchange scatters +
all_to_all) can therefore run on a bitcast u32 view: pad the trailing
axis to a 4-byte multiple, bitcast to uint32, move, bitcast back,
slice. Pack and unpack live INSIDE the same jitted program as the
movement, so the layout is never observable outside and endianness is
self-consistent by construction.

Gate: THRILL_TPU_PACK_MOVE = auto (default: on for accelerator
backends, off on CPU) | 1 | 0. The helpers are no-ops for leaves where
packing cannot help (4-byte+ dtypes, tiny rows, 1-D sub-word columns).

Reference analog: the block layer moves opaque byte ranges, not typed
items (thrill/data/block.hpp:52) — this is the columnar, static-shape
translation of that idea.
"""

from __future__ import annotations

import os
from typing import List

import jax
import jax.numpy as jnp
from jax import lax


def enabled() -> bool:
    mode = os.environ.get("THRILL_TPU_PACK_MOVE", "auto")
    if mode in ("0", "false"):
        return False
    if mode == "auto":
        return jax.default_backend() != "cpu"
    return True


def _packable(x) -> bool:
    dt = jnp.dtype(x.dtype)
    isz = dt.itemsize
    # bitcast_convert_type rejects bool (and complex never benefits)
    if dt == jnp.bool_ or dt.kind == "c" or isz >= 4 or x.ndim < 2:
        return False
    row_elems = 1
    for d in x.shape[1:]:
        row_elems *= d
    return row_elems * isz >= 8      # tiny rows: packing buys nothing


def pack_rows(x):
    """[n, ...] sub-word leaf -> ([n, w] uint32 view, meta). Leaves that
    cannot profit pass through with meta=None."""
    if not _packable(x):
        return x, None
    n = x.shape[0]
    isz = jnp.dtype(x.dtype).itemsize
    flat = x.reshape(n, -1)
    k = flat.shape[1]
    per = 4 // isz                   # elements per u32 word
    pad = (-k) % per
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    words = lax.bitcast_convert_type(
        flat.reshape(n, (k + pad) // per, per), jnp.uint32)
    return words, (x.dtype, x.shape[1:], k, per)


def unpack_rows(words, meta):
    """Inverse of pack_rows on the moved words."""
    if meta is None:
        return words
    dtype, trail_shape, k, per = meta
    n = words.shape[0]
    flat = lax.bitcast_convert_type(words, dtype)   # [n, w, per]
    flat = flat.reshape(n, -1)[:, :k]
    return flat.reshape((n,) + tuple(trail_shape))


def pack_leaves(leaves: List):
    """Pack every leaf; returns (packed_leaves, metas)."""
    packed, metas = [], []
    for l in leaves:
        p, m = pack_rows(l)
        packed.append(p)
        metas.append(m)
    return packed, metas


def unpack_leaves(packed: List, metas: List):
    return [unpack_rows(p, m) for p, m in zip(packed, metas)]


def take_rows(x, perm):
    """jnp.take(x, perm, axis=0) through the packed view when enabled
    and profitable — the drop-in gather for payload columns."""
    if not enabled():
        return jnp.take(x, perm, axis=0)
    words, meta = pack_rows(x)
    return unpack_rows(jnp.take(words, perm, axis=0), meta)
