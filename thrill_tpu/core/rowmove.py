"""Packed row movement: u32 word views for sub-word payload columns.

TPU VPU lanes are 32-bit; a gather/scatter of a [n, 90] uint8 payload
column moves 90 sub-word elements per row where 23 u32 words would do.
Every bulk row movement (sort payload gathers, exchange scatters +
all_to_all) can therefore run on a bitcast u32 view: pad the trailing
axis to a 4-byte multiple, bitcast to uint32, move, bitcast back,
slice. Pack and unpack live INSIDE the same jitted program as the
movement, so the layout is never observable outside and endianness is
self-consistent by construction.

Gate: THRILL_TPU_PACK_MOVE = auto (default: on for accelerator
backends, off on CPU) | 1 | 0. The helpers are no-ops for leaves where
packing cannot help (4-byte+ dtypes, tiny rows, 1-D sub-word columns).

Reference analog: the block layer moves opaque byte ranges, not typed
items (thrill/data/block.hpp:52) — this is the columnar, static-shape
translation of that idea.
"""

from __future__ import annotations

import os
from typing import List

import jax
import jax.numpy as jnp
from jax import lax


def enabled() -> bool:
    mode = os.environ.get("THRILL_TPU_PACK_MOVE", "auto")
    if mode in ("0", "false"):
        return False
    if mode == "auto":
        return jax.default_backend() != "cpu"
    return True


def _packable(x) -> bool:
    dt = jnp.dtype(x.dtype)
    isz = dt.itemsize
    # bitcast_convert_type rejects bool (and complex never benefits)
    if dt == jnp.bool_ or dt.kind == "c" or isz >= 4 or x.ndim < 2:
        return False
    row_elems = 1
    for d in x.shape[1:]:
        row_elems *= d
    return row_elems * isz >= 8      # tiny rows: packing buys nothing


def pack_rows(x):
    """[n, ...] sub-word leaf -> ([n, w] uint32 view, meta). Leaves that
    cannot profit pass through with meta=None."""
    if not _packable(x):
        return x, None
    n = x.shape[0]
    isz = jnp.dtype(x.dtype).itemsize
    flat = x.reshape(n, -1)
    k = flat.shape[1]
    per = 4 // isz                   # elements per u32 word
    pad = (-k) % per
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    words = lax.bitcast_convert_type(
        flat.reshape(n, (k + pad) // per, per), jnp.uint32)
    return words, (x.dtype, x.shape[1:], k, per)


def unpack_rows(words, meta):
    """Inverse of pack_rows on the moved words."""
    if meta is None:
        return words
    dtype, trail_shape, k, per = meta
    n = words.shape[0]
    flat = lax.bitcast_convert_type(words, dtype)   # [n, w, per]
    flat = flat.reshape(n, -1)[:, :k]
    return flat.reshape((n,) + tuple(trail_shape))


def pack_leaves(leaves: List):
    """Pack every leaf; returns (packed_leaves, metas)."""
    packed, metas = [], []
    for l in leaves:
        p, m = pack_rows(l)
        packed.append(p)
        metas.append(m)
    return packed, metas


def unpack_leaves(packed: List, metas: List):
    return [unpack_rows(p, m) for p, m in zip(packed, metas)]


def take_rows(x, perm):
    """jnp.take(x, perm, axis=0) through the packed view when enabled
    and profitable — the drop-in gather for payload columns."""
    if not enabled():
        return jnp.take(x, perm, axis=0)
    words, meta = pack_rows(x)
    return unpack_rows(jnp.take(words, perm, axis=0), meta)


# ----------------------------------------------------------------------
# widened + batched gathers: ONE u32 word matrix for a whole leaf set
# ----------------------------------------------------------------------
# A permutation gather of a typical sorted payload moves each leaf in
# its own gather — sub-word leaves as packed words, but every >=4-byte
# scalar column ([n] int64 keys, [n] float64 ranks) as SCALAR rows: one
# element per gathered row, 1.6% of the HBM roofline measured (13 GB/s,
# BENCH r5). ``pack_rows_wide`` widens packing to those leaves too
# (any non-bool/complex dtype bitcasts to u32 words, 1-D columns
# included), and ``take_rows_multi`` batches every widenable leaf into
# ONE [n, total_words] matrix so a single gather moves all their words
# per lane instead of k scalar gathers.


def pack_rows_wide(x):
    """[n, ...] leaf of ANY non-bool/complex dtype -> ([n, w] uint32
    words, meta). Unlike :func:`pack_rows` this also packs 1-D columns
    and >=4-byte dtypes (each element bitcast to itemsize/4 words), so
    a whole payload tree can ride one word matrix. Returns (x, None)
    for leaves that cannot be packed.

    The narrow branch mirrors :func:`pack_rows` (different word layout:
    flattened 2-D here vs [n, w, per] there, matching each consumer's
    concat/ship shape) — a pad/bitcast change to one must be mirrored
    in the other."""
    dt = jnp.dtype(x.dtype)
    isz = dt.itemsize
    if dt == jnp.bool_ or dt.kind == "c":
        return x, None
    n = x.shape[0]
    flat = x.reshape(n, -1)
    k = flat.shape[1]
    if isz >= 4:
        words = lax.bitcast_convert_type(flat, jnp.uint32)
        if isz > 4:                    # [n, k, isz//4] -> [n, k*isz//4]
            words = words.reshape(n, -1)
        return words, ("wide", x.dtype, x.shape[1:], k, isz)
    per = 4 // isz                     # elements per u32 word
    pad = (-k) % per
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    words = lax.bitcast_convert_type(
        flat.reshape(n, (k + pad) // per, per), jnp.uint32
    ).reshape(n, -1)
    return words, ("narrow", x.dtype, x.shape[1:], k, per)


def unpack_rows_wide(words, meta):
    """Inverse of :func:`pack_rows_wide` on the moved words."""
    if meta is None:
        return words
    kind, dtype, trail_shape, k, arg = meta
    n = words.shape[0]
    if kind == "wide":
        isz = arg
        if isz > 4:                    # [n, k*m] -> [n, k, m] -> [n, k]
            flat = lax.bitcast_convert_type(
                words.reshape(n, k, isz // 4), dtype)
        else:
            flat = lax.bitcast_convert_type(words, dtype)
        return flat.reshape((n,) + tuple(trail_shape))
    # narrow: [n, w] u32 -> [n, w, per] elems, trim the pad
    flat = lax.bitcast_convert_type(words, dtype)
    flat = flat.reshape(n, -1)[:, :k]
    return flat.reshape((n,) + tuple(trail_shape))


def take_rows_multi(leaves, perm):
    """Gather MANY leaves by one shared row permutation through a
    single concatenated u32 word matrix.

    All widenable leaves bitcast+concatenate into one [n, W_total]
    uint32 matrix, ONE ``jnp.take`` moves it, and the slices bitcast
    back — the gather engine sees wide rows instead of k scalar/narrow
    gathers (the 13 GB/s -> multi-word-per-lane fix). Leaves that
    cannot pack (bool, complex) gather individually; with packing
    disabled this degrades to plain per-leaf takes."""
    leaves = list(leaves)
    if not enabled() or len(leaves) == 0:
        return [jnp.take(l, perm, axis=0) for l in leaves]
    packed = [pack_rows_wide(l) for l in leaves]
    batch = [(i, w, m) for i, (w, m) in enumerate(packed)
             if m is not None]
    out: list = [None] * len(leaves)
    for i, (w, m) in enumerate(packed):
        if m is None:
            out[i] = jnp.take(leaves[i], perm, axis=0)
    if batch:
        if len(batch) == 1:
            i, w, m = batch[0]
            out[i] = unpack_rows_wide(jnp.take(w, perm, axis=0), m)
        else:
            widths = [w.shape[1] for _, w, _ in batch]
            mat = jnp.concatenate([w for _, w, _ in batch], axis=1)
            moved = jnp.take(mat, perm, axis=0)
            off = 0
            for (i, _w, m), width in zip(batch, widths):
                out[i] = unpack_rows_wide(moved[:, off:off + width], m)
                off += width
    return out
