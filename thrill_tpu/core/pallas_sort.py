"""Device radix sort: Pallas stable-partition kernel + LSD driver.

The chunked/bitonic engines (core/device_sort.py) exist because XLA's
sort lowering hits a compile cliff above ~64K rows on the axon TPU
(BASELINE.md round 1). Both are comparison networks — O(n log^2 n)
compare-exchanges. A radix sort is O(n * passes): each pass is a
STABLE PARTITION by an 8-bit digit, and stable partition is exactly
the primitive a sequential-grid Pallas kernel expresses naturally:

  offsets[i] = base[d_i] + #{j < i : d_j == d_i}

* ``base``    — exclusive scan of the global digit histogram
  (partition_histogram, already MXU-counted).
* the running per-digit counters live in VMEM scratch across the
  sequential row-tile grid (TPU grids execute in order), and the
  within-tile exclusive prefix-by-digit is a strict-lower-triangular
  matmul of the one-hot matrix — the MXU does the counting, there is
  no per-item loop anywhere.

``stable_partition_offsets`` dispatches to the Pallas kernel on TPU
(THRILL_TPU_PALLAS=1) with a lax.scan fallback of identical semantics
on every platform; CPU tests run the kernel in interpret mode to pin
equivalence. ``radix_argsort_device`` drives LSD passes over uint
words (most-significant word last), honoring per-word used-bit hints
so zero-padded byte keys skip dead passes at TRACE time (the host
engine skips them at runtime; static shapes demand a static pass
list here).

Precision note: tile partials ride the MXU in f32, exact up to 2^24 —
the Pallas path therefore applies to n < 16M rows per shard (well
above any per-shard capacity this framework produces; the fallback has
no such limit).
"""

from __future__ import annotations

import functools
import os
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from .pallas_kernels import (BLOCK, COLS, LANES, SUBLANES, _round_up,
                             pallas_enabled, partition_histogram)

_F32_EXACT = 1 << 24


def _part_kernel(base_ref, dest_ref, out_ref, run_ref, *,
                 num_bins_padded: int):
    # Layout contract (see pallas_kernels module docstring): elements on
    # the LANE axis in (SUBLANES, COLS) tiles, bins on the SUBLANE axis
    # as (B, 1) columns — no transposes anywhere. The tile's sublane
    # rows are processed in order (row-major element order) so the
    # running per-digit counters stay sequentially consistent.
    from jax.experimental import pallas as pl

    pi = pl.program_id(0)

    @pl.when(pi == 0)
    def _init():
        run_ref[:] = base_ref[:].astype(jnp.float32)

    bins = jax.lax.broadcasted_iota(
        jnp.int32, (num_bins_padded, COLS), 0)         # [B, COLS]
    # upper-triangular matmul = exclusive within-row prefix along lanes:
    # prefix[b, j] = #{k < j : d_k == b}
    rows = jax.lax.broadcasted_iota(jnp.float32, (COLS, COLS), 0)
    cols = jax.lax.broadcasted_iota(jnp.float32, (COLS, COLS), 1)
    tri_u = (rows < cols).astype(jnp.float32)
    for r in range(SUBLANES):                          # static unroll
        d_r = dest_ref[r:r + 1, :]                     # [1, COLS]
        onehot = (bins == d_r).astype(jnp.float32)     # [B, COLS]
        prefix = jnp.dot(onehot, tri_u,
                         preferred_element_type=jnp.float32)
        within = jnp.sum(prefix * onehot, axis=0,
                         keepdims=True)                # [1, COLS]
        start = jnp.sum(onehot * run_ref[:], axis=0,
                        keepdims=True)                 # gather by digit
        out_ref[r:r + 1, :] = (start + within).astype(jnp.int32)
        run_ref[:] += jnp.sum(onehot, axis=1, keepdims=True)


def stable_partition_offsets_pallas(dest: jnp.ndarray, num_bins: int,
                                    interpret: bool = False
                                    ) -> jnp.ndarray:
    """Pallas path of :func:`stable_partition_offsets`."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = dest.shape[0]
    n_pad = _round_up(max(n, 1), BLOCK)
    # out-of-range and padding rows partition into sentinel bin
    # num_bins (kept stable after every real row) so they never
    # collide with real offsets
    bpad = _round_up(num_bins + 1, LANES)
    dest = jnp.where((dest >= 0) & (dest < num_bins),
                     dest.astype(jnp.int32), num_bins)
    d = jnp.full(n_pad, num_bins, jnp.int32).at[:n].set(dest)
    hist = partition_histogram(d, num_bins)            # real bins only
    base = jnp.concatenate([
        jnp.zeros(1, jnp.int32),
        jnp.cumsum(hist.astype(jnp.int32))])           # [num_bins + 1]
    base = jnp.pad(base, (0, bpad - num_bins - 1))
    d2 = d.reshape(n_pad // COLS, COLS)

    kernel = functools.partial(_part_kernel, num_bins_padded=bpad)
    out = pl.pallas_call(
        kernel,
        grid=(n_pad // BLOCK,),
        in_specs=[pl.BlockSpec((bpad, 1), lambda i: (0, 0)),
                  pl.BlockSpec((SUBLANES, COLS), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((SUBLANES, COLS), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad // COLS, COLS),
                                       jnp.int32),
        scratch_shapes=[pltpu.VMEM((bpad, 1), jnp.float32)],
        interpret=interpret,
    )(base.reshape(bpad, 1), d2)
    return out.reshape(-1)[:n]


def _offsets_scan(dest: jnp.ndarray, num_bins: int) -> jnp.ndarray:
    """lax.scan fallback: same carried-counter math, any platform."""
    n = dest.shape[0]
    n_pad = _round_up(max(n, 1), BLOCK)
    B = num_bins + 1                                   # + pad sentinel
    dest = jnp.where((dest >= 0) & (dest < num_bins),
                     dest.astype(jnp.int32), num_bins)
    d = jnp.full(n_pad, num_bins, jnp.int32).at[:n].set(dest)
    hist = jnp.bincount(d[:n], length=B)
    base = jnp.concatenate([jnp.zeros(1, jnp.int32),
                            jnp.cumsum(hist[:num_bins])
                            .astype(jnp.int32)])       # [B]
    d2 = d.reshape(n_pad // BLOCK, BLOCK)

    def step(carry, dt):
        onehot = (dt[:, None] == jnp.arange(B)[None, :]).astype(
            jnp.int32)                                 # [BLOCK, B]
        prefix = jnp.cumsum(onehot, axis=0) - onehot   # exclusive
        within = jnp.sum(prefix * onehot, axis=1)
        start = jnp.take(carry, dt)
        return (carry + jnp.sum(onehot, axis=0).astype(jnp.int32),
                start + within.astype(jnp.int32))

    _, offs = jax.lax.scan(step, base, d2)
    return offs.reshape(-1)[:n]


def stable_partition_offsets(dest: jnp.ndarray,
                             num_bins: int) -> jnp.ndarray:
    """offsets[i] = stable-partition target of row i under dest[i].
    Values outside [0, num_bins) are SANITIZED into the trailing pad
    bin (both engines) and land after every real row, still stably —
    the result is always a permutation of [0, n)."""
    if pallas_enabled() and dest.shape[0] < _F32_EXACT:
        return stable_partition_offsets_pallas(dest, num_bins)
    return _offsets_scan(dest, num_bins)


def radix_argsort_device(words: Sequence[jnp.ndarray],
                         word_bits: Optional[Sequence[int]] = None,
                         digit_bits: int = 8) -> jnp.ndarray:
    """LSD radix argsort by lexicographic uint words (words[0] most
    significant) — O(n * passes), no comparison network, no XLA sort.

    ``word_bits[k]`` bounds the USED high bits of words[k] counting
    from bit 0 (e.g. a 2-byte zero-padded field packed high uses 64 —
    pass the real span; dead all-zero passes are skipped statically).
    """
    n = words[0].shape[0]
    nbins = 1 << digit_bits
    perm = jnp.arange(n, dtype=jnp.int32)

    def run_pass(digit, p):
        offs = stable_partition_offsets(digit, nbins)
        return jnp.zeros_like(p).at[offs].set(p)

    for k in range(len(words) - 1, -1, -1):
        w = words[k]
        bits = 64 if word_bits is None else int(word_bits[k])
        w = w.astype(jnp.uint64)
        for shift in range(0, bits, digit_bits):
            digit = ((jnp.take(w, perm) >> jnp.uint64(shift))
                     & jnp.uint64(nbins - 1)).astype(jnp.int32)
            # runtime dead-pass skip (the host engine's histogram skip,
            # expressed as lax.cond): a uniform digit — zero-padded key
            # bytes, narrow fields — costs one O(n) check instead of a
            # full partition + scatter
            uniform = jnp.all(digit == digit[0])
            perm = jax.lax.cond(uniform, lambda d, p: p, run_pass,
                                digit, perm)
    return perm
