"""Order-preserving byte encoding of sort keys.

The host EM sort spills sorted runs and k-way merges them; comparing
Python keys per item in that merge is the round-3 bottleneck. This
module maps common key schemas — str, bytes, int64-range ints, floats,
and (nested) tuples of those — to byte strings whose memcmp order
equals the Python comparison order, so the merge can run in native
code over raw bytes (native/mwmerge.cpp) and run sorting can compare
plain bytes objects (C memcmp) instead of calling key functions.

Encodings (each self-delimiting, so tuple concatenation compares
element-wise, and a shorter tuple that is a prefix compares smaller —
matching Python):

* bytes/str: 0x00 bytes escaped as 0x00 0xFF, terminated by 0x00
  (the FoundationDB tuple-layer scheme); str encodes as UTF-8 first,
  whose byte order equals code-point order.
* int in [-2**63, 2**63): 8 bytes big-endian of value + 2**63.
* float: 8 bytes big-endian of the monotone IEEE-754 transform (the
  same mapping core/keys.py uses for device sort words).
* tuple: concatenation of element encodings.

A schema is derived from ONE sample key; the returned encoder raises
:class:`OrderKeyError` on any later key that deviates (different type,
int overflow, tuple arity change), and the caller falls back to the
generic Python-comparison path. Mixed int/float at one position is
supported via the float encoding with an exactness check (an int that
float() cannot represent exactly raises, because numeric comparison
order could differ).

Reference analog: the C++ framework compares typed keys inline in its
tournament tree (core/multiway_merge.hpp:132); byte-encoding them is
how a dynamic language buys back those typed comparisons.
"""

from __future__ import annotations

import struct
from typing import Any, Callable, Optional

import numpy as np


class OrderKeyError(TypeError):
    """Key does not fit the schema probed from the first item."""


def _enc_bytes(b: bytes) -> bytes:
    return b.replace(b"\x00", b"\x00\xff") + b"\x00"


def _enc_int(v: int) -> bytes:
    if not -(1 << 63) <= v < (1 << 63):
        raise OrderKeyError(f"int out of int64 range: {v}")
    return struct.pack(">Q", v + (1 << 63))


_F64 = struct.Struct(">d")
_Q = struct.Struct(">Q")


def _enc_float(v: float) -> bytes:
    if v == 0:
        v = 0.0        # -0.0 == 0.0 in Python: one encoding for both
    (bits,) = _Q.unpack(_F64.pack(v))
    if bits >> 63:
        bits = ~bits & 0xFFFFFFFFFFFFFFFF
    else:
        bits |= 1 << 63
    return _Q.pack(bits)


def _enc_numeric(v) -> bytes:
    """Mixed int/float position: compare as floats, exactly or not at
    all."""
    if isinstance(v, float):
        return _enc_float(v)
    if isinstance(v, int):
        f = float(v)
        if int(f) != v:
            raise OrderKeyError(f"int {v} not exactly representable "
                                f"as float in mixed numeric key")
        return _enc_float(f)
    raise OrderKeyError(f"non-numeric {type(v).__name__} in numeric key")


def _schema_of(key: Any):
    if isinstance(key, (np.generic,)):
        key = key.item()
    if isinstance(key, bytes):
        return "bytes"
    if isinstance(key, str):
        return "str"
    if isinstance(key, bool):
        return "int"                   # bool is an int in comparisons
    if isinstance(key, int):
        return "int"
    if isinstance(key, float):
        return "float"
    if isinstance(key, tuple):
        return ("tuple", tuple(_schema_of(e) for e in key))
    raise OrderKeyError(f"unsupported key type {type(key).__name__}")


def _encoder_for(schema) -> Callable[[Any], bytes]:
    if schema == "bytes":
        def enc(k):
            if isinstance(k, np.generic):
                k = k.item()
            if not isinstance(k, bytes):
                raise OrderKeyError(f"expected bytes, got "
                                    f"{type(k).__name__}")
            return _enc_bytes(k)
        return enc
    if schema == "str":
        def enc(k):
            if isinstance(k, np.generic):
                k = k.item()
            if not isinstance(k, str):
                raise OrderKeyError(f"expected str, got "
                                    f"{type(k).__name__}")
            return _enc_bytes(k.encode("utf-8"))
        return enc
    if schema == "int":
        def enc(k):
            if isinstance(k, np.generic):
                k = k.item()
            if isinstance(k, int):          # bool included
                return _enc_int(k)
            # an int-schema position meeting a float: re-route both
            # sides through the numeric encoding
            if isinstance(k, float):
                raise _MixedNumeric()
            raise OrderKeyError(f"expected int, got {type(k).__name__}")
        return enc
    if schema == "float":
        def enc(k):
            if isinstance(k, np.generic):
                k = k.item()
            return _enc_numeric(k)
        return enc
    if isinstance(schema, tuple) and schema[0] == "tuple":
        subs = [_encoder_for(s) for s in schema[1]]

        def enc(k):
            if not isinstance(k, tuple) or len(k) != len(subs):
                raise OrderKeyError(
                    f"expected {len(subs)}-tuple, got {k!r:.60}")
            return b"".join(e(v) for e, v in zip(subs, k))
        return enc
    raise OrderKeyError(f"no encoder for schema {schema!r}")


class _MixedNumeric(Exception):
    """Signal: int-schema met a float; retry with the float schema."""


def make_encoder(sample_key: Any) -> Optional[Callable[[Any], bytes]]:
    """Encoder for ``sample_key``'s schema, or None if unsupported.

    The returned callable raises :class:`OrderKeyError` for keys that
    do not fit the schema. An int-schema position that later meets a
    float widens to the numeric (float) schema transparently — but the
    WIDENING invalidates earlier encodings, so it raises
    ``OrderKeyError`` too; callers treat it as a schema mismatch."""
    try:
        schema = _schema_of(sample_key)
        enc = _encoder_for(schema)
        enc(sample_key)                    # self-check on the sample
        return enc
    except (OrderKeyError, _MixedNumeric, UnicodeError):
        return None


def encode_or_raise(enc: Callable[[Any], bytes], key: Any) -> bytes:
    try:
        return enc(key)
    except _MixedNumeric:
        raise OrderKeyError("int key position met a float key")


#: everything an encoder call can raise on a schema deviation — batch
#: callers catch this tuple around a whole-run listcomp instead of
#: paying a wrapper call per item
ENCODE_ERRORS = (OrderKeyError, _MixedNumeric, UnicodeError)

#: the batch encoders below additionally surface deviations as the
#: underlying C-level errors (struct.error is a Exception subclass)
BATCH_ENCODE_ERRORS = ENCODE_ERRORS + (AttributeError, TypeError,
                                       struct.error, OverflowError)

_PK = _Q.pack
_BIAS = 1 << 63


def _pos_rows(pos0: int, n: int) -> "np.ndarray":
    """[n, 8] uint8 big-endian rows of positions pos0..pos0+n-1."""
    import numpy as np
    # astype to an EXPLICIT big-endian dtype is endian-correct on any
    # host (native dtypes report byteorder '=', so a != '>' test would
    # byteswap wrongly on big-endian machines)
    p = np.arange(pos0, pos0 + n, dtype=np.uint64).astype(">u8")
    return p.view(np.uint8).reshape(n, 8)


def make_array_batch_encoder(sample_key: Any):
    """Vectorized sibling of :func:`make_batch_encoder`:
    ``g(keys_list, pos0) -> np.ndarray(S{w}) | None``, producing the
    IDENTICAL bytes per key as the listcomp encoder but as rows of one
    fixed-width numpy array — zero per-item Python objects, so the EM
    sort's run formation (encode + order) stays in C (np.argsort over
    the S view is pure memcmp). Returns a callable for int and str
    schemas, else None. The callable returns None for a batch it cannot
    vectorize exactly (non-ASCII, unequal lengths, embedded NULs —
    where escaping/termination make widths vary); the caller then uses
    the listcomp encoder for that batch. Schema DEVIATIONS raise
    ``BATCH_ENCODE_ERRORS`` exactly like the listcomp encoder."""
    import numpy as np
    try:
        schema = _schema_of(sample_key)
    except OrderKeyError:
        return None
    if schema == "int" and type(sample_key) in (int, bool):
        def g(keys, pos0):
            if set(map(type, keys)) - {int, bool}:
                raise OrderKeyError("non-int key in int batch")
            n = len(keys)
            # OverflowError (in BATCH_ENCODE_ERRORS) on > int64 range
            a = np.fromiter(keys, dtype=np.int64, count=n)
            biased = (a.view(np.uint64)
                      + np.uint64(_BIAS)).astype(">u8")  # wraps: k+BIAS
            out = np.empty((n, 16), dtype=np.uint8)
            out[:, :8] = biased.view(np.uint8).reshape(n, 8)
            out[:, 8:] = _pos_rows(pos0, n)
            return out.reshape(-1).view("S16")   # zero-copy rows view
        return g
    if schema == "str" and type(sample_key) is str:
        # Variable-length batches emit NUL-PADDED rows: row i is
        # content + \x00 terminator + 8-byte pos + zero padding to the
        # batch max. Padding is ORDER-SAFE against both padded rows of
        # any width and the exact variable-length kbs (mixed runs /
        # splitters): content bytes are NUL-free (the exact encoder
        # escapes \x00, and batches containing NULs fall back), so the
        # first memcmp mismatch always lands in content, terminator, or
        # the globally-unique pos field — never in padding — and there
        # it agrees with the variable-length comparison byte for byte.
        # Data rows carry globally-unique positions, so no data-data
        # comparison ever reaches the pads with everything equal; the
        # one same-(key, pos) pairing that exists — a splitter kb
        # against its own sampled twin row — ties toward the exact
        # (shorter, prefix) form, which only shifts that one item
        # across a partition boundary, never breaking sortedness.
        def g(keys, pos0):
            if set(map(type, keys)) - {str}:
                raise OrderKeyError("non-str key in str batch")
            n = len(keys)
            u = np.array(keys)
            try:
                s = u.astype(f"S{max(u.dtype.itemsize // 4, 1)}")
            except (UnicodeEncodeError, UnicodeError):
                return None                  # non-ASCII: listcomp batch
            w = s.dtype.itemsize
            view = s.view(np.uint8).reshape(n, w)
            nz = view != 0
            # content NULs (the exact encoder escapes them, changing
            # widths) make padding ambiguous — detect and fall back:
            # an interior zero followed by a nonzero byte, or a key
            # ENDING in U+0000 (its padding-like suffix would encode
            # differently), cannot take this path
            if (~nz[:, :-1] & nz[:, 1:]).any():
                return None
            lens = np.count_nonzero(nz, axis=1)
            # numpy's U dtype itself drops trailing NULs at np.array(),
            # so compare against the PYTHON lengths: any key whose true
            # length disagrees (trailing U+0000) must fall back
            if (lens != np.fromiter(map(len, keys), dtype=np.int64,
                                    count=n)).any():
                return None
            out = np.zeros((n, w + 9), dtype=np.uint8)
            out[:, :w] = view                # content, zero-padded
            rows = np.arange(n)
            # terminator is the zero already at out[rows, lens]; the
            # pos field lands right after it, pads stay zero
            out[rows[:, None],
                lens[:, None] + 1 + np.arange(8)] = _pos_rows(pos0, n)
            return out.reshape(-1).view(f"S{w + 9}")  # zero-copy view
        return g
    return None


def make_batch_encoder(sample_key: Any):
    """Batch encoder ``fn(keys_list, positions) -> list[bytes]`` where
    each output is the order encoding of the key plus an 8-byte
    big-endian position suffix (the EM sort's stability/splitter
    tiebreak). Flat str/bytes/int schemas run as ONE type-checked
    listcomp with zero per-item Python dispatch — the per-item closure
    of :func:`make_encoder` was a profiled hotspot of the spill loop.
    Other schemas wrap the per-item encoder in a single comp. Returns
    None when the schema is unsupported; raises a member of
    ``BATCH_ENCODE_ERRORS`` on any later schema deviation (the caller
    demotes to the generic merge)."""
    try:
        schema = _schema_of(sample_key)
    except OrderKeyError:
        return None
    # exact-type specializations only (a numpy-scalar sample routes to
    # the per-item branch, which unboxes it); the up-front set(map(type))
    # pass is one C-level scan that keeps look-alike custom key types
    # (anything with .encode/.replace) out of the fast comp
    if schema == "str" and type(sample_key) is str:
        def f(keys, poss):
            if set(map(type, keys)) - {str}:
                raise OrderKeyError("non-str key in str batch")
            return [k.encode("utf-8").replace(b"\x00", b"\x00\xff")
                    + b"\x00" + _PK(p)
                    for k, p in zip(keys, poss)]
    elif schema == "bytes" and type(sample_key) is bytes:
        def f(keys, poss):
            if set(map(type, keys)) - {bytes}:
                raise OrderKeyError("non-bytes key in bytes batch")
            return [k.replace(b"\x00", b"\x00\xff") + b"\x00" + _PK(p)
                    for k, p in zip(keys, poss)]
    elif schema == "int" and type(sample_key) in (int, bool):
        def f(keys, poss):
            if set(map(type, keys)) - {int, bool}:
                raise OrderKeyError("non-int key in int batch")
            # struct.error surfaces out-of-int64-range values
            return [_PK(k + _BIAS) + _PK(p)
                    for k, p in zip(keys, poss)]
    else:
        enc = _encoder_for(schema)

        def f(keys, poss):
            return [enc(k) + _PK(p) for k, p in zip(keys, poss)]
    try:
        got = f([sample_key], [0])          # self-check on the sample
        per_item = make_encoder(sample_key)
        if per_item is None or got[0] != per_item(sample_key) + _PK(0):
            return None
    except BATCH_ENCODE_ERRORS:
        return None
    return f
