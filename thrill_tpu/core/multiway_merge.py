"""K-way merge of sorted runs.

Equivalent of the reference's tournament-tree multiway merge
(reference: thrill/core/multiway_merge.hpp:132 make_multiway_merge_tree,
buffered_multiway_merge.hpp — there used by Sort/GroupByKey to merge
spilled sorted runs from data::Files). Here it is the standalone merge
primitive for spilled File runs; the DIA device Sort instead merges via
one bitonic pass on-device. File readers are merged lazily — only one
block per run is resident, so merging stays external-memory-friendly;
heapq plays the role of the tournament tree.
"""

from __future__ import annotations

import heapq
from typing import Any, Callable, Iterable, Iterator, List, Optional

from ..data.file import File


def multiway_merge(runs: List[Iterable[Any]],
                   key: Optional[Callable] = None) -> Iterator[Any]:
    """Stable k-way merge: ties resolve by run index (run order wins)."""
    key = key or (lambda x: x)
    heap = []
    iters = [iter(r) for r in runs]
    for i, it in enumerate(iters):
        for first in it:
            heap.append((key(first), i, first))
            break
    heapq.heapify(heap)
    while heap:
        k, i, item = heapq.heappop(heap)
        yield item
        for nxt in iters[i]:
            heapq.heappush(heap, (key(nxt), i, nxt))
            break


def multiway_merge_files(files: List[File], key: Optional[Callable] = None,
                         consume: bool = False) -> Iterator[Any]:
    """Merge sorted Files block-lazily (reference merges File readers
    with prefetch degree control, data/block_pool.hpp:177)."""
    readers = [f.consume_reader() if consume else f.keep_reader()
               for f in files]
    return multiway_merge(readers, key)
