"""K-way merge of sorted runs.

Equivalent of the reference's tournament-tree multiway merge
(reference: thrill/core/multiway_merge.hpp:132 make_multiway_merge_tree,
buffered_multiway_merge.hpp — there used by Sort/GroupByKey to merge
spilled sorted runs from data::Files). Here it is the standalone merge
primitive for spilled File runs; the DIA device Sort instead merges via
one bitonic pass on-device. File readers are merged lazily — only one
block per run is resident, and a block's decode is deferred to its
consumption (columnar native-record batches decode zero-copy column
views with no pickle parse, data/file.py readers) — so merging stays
external-memory-friendly; heapq plays the role of the tournament tree.
"""

from __future__ import annotations

import heapq
from typing import Any, Callable, Iterable, Iterator, List, Optional

from ..data.file import File


def multiway_merge(runs: List[Iterable[Any]],
                   key: Optional[Callable] = None) -> Iterator[Any]:
    """Stable k-way merge: ties resolve by run index (run order wins)."""
    key = key or (lambda x: x)
    heap = []
    iters = [iter(r) for r in runs]
    for i, it in enumerate(iters):
        for first in it:
            heap.append((key(first), i, first))
            break
    heapq.heapify(heap)
    while heap:
        k, i, item = heapq.heappop(heap)
        yield item
        for nxt in iters[i]:
            heapq.heappush(heap, (key(nxt), i, nxt))
            break


def multiway_merge_files(files: List[File], key: Optional[Callable] = None,
                         consume: bool = False,
                         max_merge_degree: int = 0,
                         submit=None) -> Iterator[Any]:
    """Merge sorted Files block-lazily with bounded merge degree.

    At most ``max_merge_degree`` run readers are open at once
    (reference: MaxMergeDegreePrefetch, thrill/data/block_pool.hpp:177,
    and Sort's partial-merge loop, api/sort.hpp:229-260): when there
    are more runs, groups are partially merged into intermediate Files
    first, so memory stays bounded even for thousands of spilled runs.
    0 = default (64, the reference's prefetch-less fallback ballpark).

    ``submit`` (a readahead executor's submit, data/writeback.py) gives
    every run reader one block of readahead — the winner's next block
    is already resident when the tournament pops it; None keeps the
    demand readers exactly.
    """
    import os
    if max_merge_degree <= 0:
        max_merge_degree = int(
            os.environ.get("THRILL_TPU_MAX_MERGE_DEGREE", "64") or 64)
    max_merge_degree = max(max_merge_degree, 2)

    files = list(files)
    made_intermediates = []
    try:
        while len(files) > max_merge_degree:
            # partially merge the SMALLEST runs first (fewest re-copies)
            files.sort(key=lambda f: f.num_items)
            group, files = files[:max_merge_degree], \
                files[max_merge_degree:]
            pool = group[0].pool
            merged = File(pool=pool)
            with merged.writer() as w:
                readers = [f.prefetch_reader(consume=consume,
                                             submit=submit)
                           for f in group]
                for item in multiway_merge(readers, key):
                    w.put(item)
            if consume:
                for f in group:
                    f.clear()
            made_intermediates.append(merged)
            files.append(merged)

        readers = [f.prefetch_reader(
                       consume=(consume or f in made_intermediates),
                       submit=submit) for f in files]
        yield from multiway_merge(readers, key)
    finally:
        for f in made_intermediates:
            f.clear()
