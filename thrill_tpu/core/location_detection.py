"""Distributed key-location discovery.

Equivalent of the reference's LocationDetection
(reference: thrill/core/location_detection.hpp:70, used by InnerJoin
api/inner_join.hpp:161-190 and GroupByKey with LocationDetectionTag):
before shuffling full items, workers exchange *compressed hash
fingerprints* of their keys (delta + Golomb-Rice coded sorted hashes);
each worker then knows, per hash, which workers hold matching items and
can target exactly one of them — or skip sending items whose key exists
on no other side (join pruning).

Single-controller flavor: the fingerprint exchange is simulated through
the same codec (so wire cost is measurable and the codec is exercised),
and the result maps hash -> target worker.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Set, Tuple

import numpy as np

from .golomb import decode_sorted, encode_sorted, rice_parameter

HASH_SPACE_BITS = 32          # fingerprints truncated to 32-bit space
_MASK = (1 << HASH_SPACE_BITS) - 1


def fingerprint(hashes: Iterable[int]) -> np.ndarray:
    """Sorted unique truncated hashes of one worker's keys."""
    arr = np.unique(np.asarray([h & _MASK for h in hashes],
                               dtype=np.int64))
    return arr


def encode_fingerprint(fp: np.ndarray) -> Tuple[bytes, int, int, int]:
    """Returns (payload, nbits, count, k) — the wire message."""
    if len(fp) == 0:
        return b"", 0, 0, 0
    mean_delta = (1 << HASH_SPACE_BITS) / max(len(fp), 1)
    k = rice_parameter(mean_delta)
    payload, nbits, count = encode_sorted([int(v) for v in fp], k)
    return payload, nbits, count, k


def decode_fingerprint(msg: Tuple[bytes, int, int, int]) -> np.ndarray:
    payload, nbits, count, k = msg
    if count == 0:
        return np.zeros(0, dtype=np.int64)
    return np.fromiter(decode_sorted(payload, nbits, count, k),
                       dtype=np.int64, count=count)


class LocationDetection:
    """Aggregates per-worker fingerprints into a location map."""

    def __init__(self, num_workers: int) -> None:
        self.num_workers = num_workers
        self._present: Dict[int, List[int]] = {}

    def add_worker(self, worker: int, hashes: Iterable[int]) -> int:
        """Register worker's keys; returns the encoded wire size in bytes
        (what the reference would ship over the Golomb CatStream)."""
        msg = encode_fingerprint(fingerprint(hashes))
        for h in decode_fingerprint(msg):     # round-trip the codec
            self._present.setdefault(int(h), []).append(worker)
        return len(msg[0])

    def workers_of(self, h: int) -> List[int]:
        return self._present.get(h & _MASK, [])

    def target_of(self, h: int) -> int:
        """Deterministic home worker for a hash: the first holder
        (reference sends all matching items to one discovered location)."""
        ws = self.workers_of(h)
        return ws[0] if ws else (h & _MASK) % self.num_workers

    def common_hashes(self, other: "LocationDetection") -> Set[int]:
        """Hashes present in both sides (join candidate pruning)."""
        return set(self._present) & set(other._present)
