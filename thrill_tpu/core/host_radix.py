"""Native stable radix argsort for the CPU backend's local-sort phase.

On the CPU backend, "device" buffers live in host memory, so the local
sort engine can be the same kind the reference uses for its in-RAM run
sorts (sort_algorithm_ = std::sort / tlx radix variants, selected per
key type in thrill/api/sort.hpp): a C++ stable LSD radix sort over the
encoded lexicographic uint64 key words (native/hostsort.cpp), plus one
native row gather for the payload permutation. On TPU the device
engines in core/device_sort.py run; this module is never used there.

Stability makes the global-index tie-break implicit: equal keys keep
their input order, which at W == 1 is exactly global-index order.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import List, Optional

import numpy as np

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        from ..common.native_build import build_and_load
        lib = build_and_load("hostsort.cpp")
        if lib is not None:
            lib.radix_argsort_u64.restype = ctypes.c_int
            lib.radix_argsort_u64.argtypes = [
                ctypes.c_int64, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_void_p), ctypes.c_void_p]
            lib.gather_rows_u8.restype = None
            lib.gather_rows_u8.argtypes = [
                ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p]
        _LIB = lib
        return _LIB


def available() -> bool:
    if os.environ.get("THRILL_TPU_HOST_RADIX", "1") == "0":
        return False
    return _load() is not None


def eligible(mex) -> bool:
    """Shared CPU-backend gate for the native-radix fast paths (Sort,
    ReduceByKey, GroupByKey): device buffers must BE host memory
    (CPU platform, CPU default backend, single controller) and the
    native library must load."""
    import jax
    return (bool(mex.devices)
            and mex.devices[0].platform == "cpu"
            and jax.default_backend() == "cpu"
            and getattr(mex, "num_processes", 1) <= 1
            and available())


def sorted_runs(words: List[np.ndarray]):
    """Stable radix argsort + equal-key run detection. Returns
    (perm, same_next) where same_next[i] == True iff sorted rows i and
    i+1 share all key words."""
    perm = radix_argsort(words)
    n = int(perm.shape[0])
    same_next = np.ones(max(n - 1, 0), dtype=bool)
    for kw in words:
        kws = kw[perm]
        same_next &= kws[1:] == kws[:-1]
    return perm, same_next


def radix_argsort(words: List[np.ndarray]) -> np.ndarray:
    """Stable argsort by lexicographic uint64 words (words[0] most
    significant). Returns uint32 permutation (sorted -> original)."""
    lib = _load()
    assert lib is not None
    n = int(words[0].shape[0])
    cols = [np.ascontiguousarray(w, dtype=np.uint64) for w in words]
    ptrs = (ctypes.c_void_p * len(cols))(
        *[c.ctypes.data_as(ctypes.c_void_p).value for c in cols])
    perm = np.empty(n, dtype=np.uint32)
    rc = lib.radix_argsort_u64(
        n, len(cols), ctypes.cast(ptrs, ctypes.POINTER(ctypes.c_void_p)),
        perm.ctypes.data_as(ctypes.c_void_p))
    if rc < 0:
        raise ValueError(f"radix_argsort_u64 failed (rc={rc}, n={n})")
    return perm


def gather_rows(arr: np.ndarray, perm: np.ndarray) -> np.ndarray:
    """arr[perm] along axis 0 via the native row gather (falls back to
    numpy take for non-contiguous inputs)."""
    lib = _load()
    if lib is None or not arr.flags.c_contiguous:
        return np.take(arr, perm, axis=0)
    n = int(perm.shape[0])
    row_bytes = int(arr.dtype.itemsize * int(np.prod(arr.shape[1:], dtype=np.int64)))
    if row_bytes == 0 or n == 0:
        return np.take(arr, perm, axis=0)
    out = np.empty((n,) + arr.shape[1:], dtype=arr.dtype)
    lib.gather_rows_u8(
        n, row_bytes, arr.ctypes.data_as(ctypes.c_void_p),
        np.ascontiguousarray(perm, dtype=np.uint32).ctypes.data_as(
            ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p))
    return out
