"""Native stable radix argsort for the CPU backend's local-sort phase.

On the CPU backend, "device" buffers live in host memory, so the local
sort engine can be the same kind the reference uses for its in-RAM run
sorts (sort_algorithm_ = std::sort / tlx radix variants, selected per
key type in thrill/api/sort.hpp): a C++ stable LSD radix sort over the
encoded lexicographic uint64 key words (native/hostsort.cpp), plus one
native row gather for the payload permutation. On TPU the device
engines in core/device_sort.py run; this module is never used there.

Stability makes the global-index tie-break implicit: equal keys keep
their input order, which at W == 1 is exactly global-index order.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import List, Optional

import numpy as np

_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


class NativeEngineError(RuntimeError):
    """A native engine call itself failed (bad return code, plan size
    mismatch). Callers that fall back to a slower engine on arbitrary
    exceptions must NOT swallow this silently — it means the fast path
    is broken, not inapplicable."""


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        from ..common.native_build import build_and_load
        lib = build_and_load("hostsort.cpp")
        if lib is not None:
            lib.radix_argsort_u64.restype = ctypes.c_int
            lib.radix_argsort_u64.argtypes = [
                ctypes.c_int64, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_void_p), ctypes.c_void_p]
            lib.gather_rows_u8.restype = None
            lib.gather_rows_u8.argtypes = [
                ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p]
            lib.scatter_rows_u8.restype = None
            lib.scatter_rows_u8.argtypes = [
                ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p]
            lib.hash_group_u64.restype = ctypes.c_int64
            lib.hash_group_u64.argtypes = [
                ctypes.c_int64, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_void_p), ctypes.c_void_p,
                ctypes.c_void_p]
            lib.fold_plan_u32.restype = ctypes.c_int64
            lib.fold_plan_u32.argtypes = [
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p]
            lib.hash_group_acc_u64.restype = ctypes.c_int64
            lib.hash_group_acc_u64.argtypes = [
                ctypes.c_int64, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_void_p), ctypes.c_int32,
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
                ctypes.POINTER(ctypes.c_void_p), ctypes.c_void_p]
        _LIB = lib
        return _LIB


def available() -> bool:
    if os.environ.get("THRILL_TPU_HOST_RADIX", "1") == "0":
        return False
    return _load() is not None


def eligible(mex) -> bool:
    """Shared CPU-backend gate for the native-radix fast paths (Sort,
    ReduceByKey, GroupByKey): device buffers must BE host memory
    (CPU platform, CPU default backend, single controller) and the
    native library must load."""
    import jax
    return (bool(mex.devices)
            and mex.devices[0].platform == "cpu"
            and jax.default_backend() == "cpu"
            and getattr(mex, "num_processes", 1) <= 1
            and available())


def sorted_runs(words: List[np.ndarray]):
    """Stable radix argsort + equal-key run detection. Returns
    (perm, same_next) where same_next[i] == True iff sorted rows i and
    i+1 share all key words."""
    perm = radix_argsort(words)
    n = int(perm.shape[0])
    same_next = np.ones(max(n - 1, 0), dtype=bool)
    for kw in words:
        kws = kw[perm]
        same_next &= kws[1:] == kws[:-1]
    return perm, same_next


def radix_argsort(words: List[np.ndarray]) -> np.ndarray:
    """Stable argsort by lexicographic uint64 words (words[0] most
    significant). Returns uint32 permutation (sorted -> original)."""
    lib = _load()
    assert lib is not None
    n = int(words[0].shape[0])
    cols = [np.ascontiguousarray(w, dtype=np.uint64) for w in words]
    ptrs = (ctypes.c_void_p * len(cols))(
        *[c.ctypes.data_as(ctypes.c_void_p).value for c in cols])
    perm = np.empty(n, dtype=np.uint32)
    rc = lib.radix_argsort_u64(
        n, len(cols), ctypes.cast(ptrs, ctypes.POINTER(ctypes.c_void_p)),
        perm.ctypes.data_as(ctypes.c_void_p))
    if rc < 0:
        raise ValueError(f"radix_argsort_u64 failed (rc={rc}, n={n})")
    return perm


def hash_group(words: List[np.ndarray]):
    """Group rows by exact key-word equality via the native
    open-addressing table (the reference ReducePrePhase's engine class,
    thrill/core/reduce_pre_phase.hpp:94). Returns ``(perm, lens)``:
    ``perm`` (uint32) clusters rows group-contiguously in
    first-appearance order, stable within each group; ``lens`` (uint32)
    is rows per group. Unlike :func:`sorted_runs` the output group
    order is NOT key-sorted — callers that only need equal keys
    adjacent (ReduceByKey, GroupByKey) get a one-pass engine instead of
    4+ counting passes."""
    lib = _load()
    assert lib is not None
    n = int(words[0].shape[0])
    cols = [np.ascontiguousarray(w, dtype=np.uint64) for w in words]
    ptrs = (ctypes.c_void_p * len(cols))(
        *[c.ctypes.data_as(ctypes.c_void_p).value for c in cols])
    perm = np.empty(n, dtype=np.uint32)
    lens = np.empty(max(n, 1), dtype=np.uint32)
    ng = lib.hash_group_u64(
        n, len(cols), ctypes.cast(ptrs, ctypes.POINTER(ctypes.c_void_p)),
        perm.ctypes.data_as(ctypes.c_void_p),
        lens.ctypes.data_as(ctypes.c_void_p))
    if ng < 0:
        raise NativeEngineError(f"hash_group_u64 failed (rc={ng}, n={n})")
    return perm, lens[:ng].copy()


def hash_group_acc(words: List[np.ndarray], cols: List[np.ndarray],
                   ops: List[int]):
    """Fused grouping + per-column accumulation in ONE native pass (the
    FieldReduce fast path; see api/functors.py). ``cols`` are 1-D
    arrays with 8-byte items (pre-converted by the caller), ``ops`` the
    matching ``hash_group_acc_u64`` opcodes. Returns
    ``(heads, acc_list)``: ``heads`` (uint32, one per group) is the
    original row index of each group's first row; ``acc_list[c]`` the
    accumulated values per group, in the same (first-appearance) group
    order."""
    lib = _load()
    assert lib is not None
    n = int(words[0].shape[0])
    kcols = [np.ascontiguousarray(w, dtype=np.uint64) for w in words]
    kptrs = (ctypes.c_void_p * len(kcols))(
        *[c.ctypes.data_as(ctypes.c_void_p).value for c in kcols])
    vcols = [np.ascontiguousarray(c) for c in cols]
    for c in vcols:
        if c.ndim != 1 or c.dtype.itemsize != 8:
            # the native pass reads/writes fixed 8-byte strides; a
            # narrower or multi-dim column would read out of bounds
            raise ValueError(
                f"hash_group_acc: columns must be 1-D 8-byte scalars, "
                f"got ndim={c.ndim} dtype={c.dtype}")
    vptrs = (ctypes.c_void_p * max(len(vcols), 1))(
        *([c.ctypes.data_as(ctypes.c_void_p).value for c in vcols] or [0]))
    ops_arr = np.ascontiguousarray(ops, dtype=np.int32)
    accs = [np.empty(max(n, 1), dtype=c.dtype) for c in vcols]
    aptrs = (ctypes.c_void_p * max(len(accs), 1))(
        *([a.ctypes.data_as(ctypes.c_void_p).value for a in accs] or [0]))
    heads = np.empty(max(n, 1), dtype=np.uint32)
    ng = lib.hash_group_acc_u64(
        n, len(kcols), ctypes.cast(kptrs, ctypes.POINTER(ctypes.c_void_p)),
        len(vcols), ops_arr.ctypes.data_as(ctypes.c_void_p),
        ctypes.cast(vptrs, ctypes.POINTER(ctypes.c_void_p)),
        ctypes.cast(aptrs, ctypes.POINTER(ctypes.c_void_p)),
        heads.ctypes.data_as(ctypes.c_void_p))
    if ng < 0:
        raise NativeEngineError(
            f"hash_group_acc_u64 failed (rc={ng}, n={n})")
    return heads[:ng].copy(), [a[:ng].copy() for a in accs]


def fold_plan(lens: np.ndarray):
    """Native plan for the strided run fold: returns
    ``(ri, level_counts)`` where ``ri`` (uint32) holds the absorbed
    right-operand global row indices concatenated level by level
    (level l = rows at in-run position p with p & -p == 1 << l,
    ascending within a level) and ``level_counts`` (int64[32]) the
    per-level slice sizes. ``sum(level_counts) == sum(lens) - len(lens)``."""
    lib = _load()
    assert lib is not None
    lens_c = np.ascontiguousarray(lens, dtype=np.uint32)
    total = int(lens_c.sum(dtype=np.int64)) - len(lens_c)
    ri = np.empty(max(total, 1), dtype=np.uint32)
    level_counts = np.empty(32, dtype=np.int64)
    got = lib.fold_plan_u32(
        len(lens_c), lens_c.ctypes.data_as(ctypes.c_void_p),
        ri.ctypes.data_as(ctypes.c_void_p),
        level_counts.ctypes.data_as(ctypes.c_void_p))
    if got != total:
        raise NativeEngineError(
            f"fold_plan_u32 size mismatch (got={got}, expected={total})")
    return ri[:total], level_counts


def scatter_rows(dst: np.ndarray, idx: np.ndarray, src: np.ndarray) -> None:
    """dst[idx[r]] = src[r] along axis 0 (in place). Native when both
    sides are C-contiguous; numpy fancy assignment otherwise."""
    lib = _load()
    n = int(idx.shape[0])
    if (lib is None or not dst.flags.c_contiguous
            or not src.flags.c_contiguous or dst.dtype != src.dtype
            or src.shape != (n,) + dst.shape[1:]):
        dst[idx] = src          # numpy handles broadcasts / casts
        return
    row_bytes = int(dst.dtype.itemsize
                    * int(np.prod(dst.shape[1:], dtype=np.int64)))
    if n == 0 or row_bytes == 0:
        return
    lib.scatter_rows_u8(
        n, row_bytes, src.ctypes.data_as(ctypes.c_void_p),
        np.ascontiguousarray(idx, dtype=np.uint32).ctypes.data_as(
            ctypes.c_void_p),
        dst.ctypes.data_as(ctypes.c_void_p))


def gather_rows(arr: np.ndarray, perm: np.ndarray) -> np.ndarray:
    """arr[perm] along axis 0 via the native row gather (falls back to
    numpy take for non-contiguous inputs)."""
    lib = _load()
    if lib is None or not arr.flags.c_contiguous:
        return np.take(arr, perm, axis=0)
    n = int(perm.shape[0])
    row_bytes = int(arr.dtype.itemsize * int(np.prod(arr.shape[1:], dtype=np.int64)))
    if row_bytes == 0 or n == 0:
        return np.take(arr, perm, axis=0)
    out = np.empty((n,) + arr.shape[1:], dtype=arr.dtype)
    lib.gather_rows_u8(
        n, row_bytes, arr.ctypes.data_as(ctypes.c_void_p),
        np.ascontiguousarray(perm, dtype=np.uint32).ctypes.data_as(
            ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p))
    return out
