"""Lazily-fused local operation (LOp) stacks.

The reference fuses chained Map/Filter/FlatMap lambdas into the consuming
distributed op at *compile time* via template function stacks
(reference: thrill/api/dia.hpp:358-387 stack push, tlx::FunctionStack),
so no per-item virtual call happens. The TPU-native equivalent: a DIA
handle carries a tuple of StackOps which are *traced* into the consuming
operator's jitted program — XLA fusion replaces template fusion, and the
whole chain becomes one device kernel between materialization points.

Semantics of user functions:
* host storage  — ``fn`` is applied per item (Thrill-style).
* device storage — ``fn`` is applied to **batched columns**: each leaf of
  the item pytree carries a leading item axis. For elementwise lambdas
  (``lambda x: x * 2``, ``lambda kv: (kv[0], kv[1] + 1)``) this is
  identical to per-item semantics; scalar outputs are broadcast to the
  item axis automatically. Items whose leaves are themselves arrays
  (fixed-width byte strings) must index with an explicit trailing axis
  (``x[:, 3]``), the one divergence from per-item code.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp


class Bind:
    """A map/filter/flat_map function with runtime-bound array operands.

    ``Bind(fn, *operands)`` behaves like ``lambda t: fn(t, *operands)``
    but the compiled program caches on ``(fn, operand shapes/dtypes)``
    and the CURRENT operand values enter the jitted program as real
    (replicated) arguments on every execution. This is the idiomatic
    spelling for iterative algorithms whose per-iteration state is a
    small array (k-means centroids, PageRank teleport vectors): a
    closure over the array would be traced as a CONSTANT — one fresh
    executable per iteration, 20-40s each on TPU — where Bind compiles
    once and re-binds values. (The reference's C++ lambdas capture by
    reference and re-run natively, so it never faces this; under XLA's
    trace-once model the operand/constant distinction is load-bearing.)

    ``fn`` must be identity-stable across iterations (module-level) for
    the cache to hit, like every other stacked function. Operands may
    be pytrees of arrays; on the host path they are passed through
    as-is.
    """

    __slots__ = ("fn", "operands")

    def __init__(self, fn: Callable, *operands: Any) -> None:
        self.fn = fn
        self.operands = operands

    def __call__(self, tree):
        return self.fn(tree, *self.operands)

    def cache_token(self) -> Tuple:
        import numpy as np
        leaves, td = jax.tree.flatten(self.operands)
        # metadata from attributes — no host<->device copies (this runs
        # on every stack execution, the iterative hot path Bind serves);
        # only scalar leaves pay an np.asarray
        metas = []
        for l in leaves:
            if hasattr(l, "dtype") and hasattr(l, "shape"):
                metas.append((np.dtype(l.dtype), tuple(l.shape)))
            else:
                a = np.asarray(l)
                metas.append((a.dtype, a.shape))
        return (self.fn, td, tuple(metas))


@dataclasses.dataclass(frozen=True)
class StackOp:
    kind: str                      # 'map' | 'filter' | 'flat_map'
    fn: Callable                   # see module docstring for semantics
    # device flat_map only: static expansion factor k; fn returns
    # (tree [n, k, ...], valid [n, k]) in batched form.
    factor: int = 1

    def cache_token(self) -> Tuple:
        # the function object itself (hashable by identity) keys the
        # compiled-program cache; holding it in the key pins it alive so
        # a freed lambda's id can never alias onto a stale executable.
        # Bind tokens swap operand identity for operand shape so
        # iterative re-binds reuse the executable.
        if isinstance(self.fn, Bind):
            return (self.kind, self.fn.cache_token(), self.factor)
        return (self.kind, self.fn, self.factor)


Stack = Tuple[StackOp, ...]


def stack_cache_token(stack: Stack) -> Tuple:
    return tuple(op.cache_token() for op in stack)


def _broadcast_outputs(tree: Any, n: int) -> Any:
    """Broadcast scalar leaves to the item axis after a map fn."""
    def fix(leaf):
        arr = jnp.asarray(leaf)
        if arr.ndim == 0 or arr.shape[0] != n:
            arr = jnp.broadcast_to(arr, (n,) + arr.shape)
        return arr
    return jax.tree.map(fix, tree)


def stack_bound_operands(stack: Stack):
    """Current bound-operand pytrees of every Bind op in the stack, in
    stack order (device programs take them as replicated arguments)."""
    return [op.fn.operands for op in stack if isinstance(op.fn, Bind)]


def apply_stack_traced(tree: Any, mask: jnp.ndarray, stack: Stack,
                       bound=None):
    """Apply a stack inside a traced program. Returns (tree, mask).

    The item count may grow only through flat_map (factor-k static
    expansion); mask tracks validity, compaction happens once at the
    consumer's boundary. ``bound``, when given, supplies the TRACED
    operand pytrees for the stack's Bind ops (in stack order) so bound
    values are program arguments, not baked constants.
    """
    bound_iter = iter(bound) if bound is not None else None
    for op in stack:
        fn = op.fn
        if isinstance(fn, Bind) and bound_iter is not None:
            inner, ops_ = fn.fn, next(bound_iter)
            fn = (lambda _in, _ops: lambda t: _in(t, *_ops))(inner, ops_)
        n = mask.shape[0]
        if op.kind == "map":
            tree = _broadcast_outputs(fn(tree), n)
        elif op.kind == "filter":
            keep = jnp.asarray(fn(tree))
            mask = mask & keep.astype(bool)
        elif op.kind == "flat_map":
            out_tree, out_valid = fn(tree)
            k = op.factor
            out_valid = jnp.asarray(out_valid)
            assert out_valid.shape[:2] == (n, k), (
                f"flat_map valid mask must be [n, {k}], got {out_valid.shape}")
            tree = jax.tree.map(
                lambda leaf: jnp.reshape(leaf, (n * k,) + leaf.shape[2:]),
                out_tree)
            mask = (mask[:, None] & out_valid.astype(bool)).reshape(n * k)
        else:  # pragma: no cover
            raise ValueError(op.kind)
    return tree, mask


def apply_stack_host_item(item: Any, stack: Stack, emit: Callable) -> None:
    """Apply a stack to one host item, calling ``emit`` per output item."""
    if not stack:
        emit(item)
        return
    op, rest = stack[0], stack[1:]
    if op.kind == "map":
        apply_stack_host_item(op.fn(item), rest, emit)
    elif op.kind == "filter":
        if op.fn(item):
            apply_stack_host_item(item, rest, emit)
    elif op.kind == "flat_map":
        for out in op.fn(item):
            apply_stack_host_item(out, rest, emit)
    else:  # pragma: no cover
        raise ValueError(op.kind)


def apply_stack_host_list(items, stack: Stack) -> list:
    out: list = []
    append = out.append
    for it in items:
        apply_stack_host_item(it, stack, append)
    return out
