"""Lazily-fused local operation (LOp) stacks.

The reference fuses chained Map/Filter/FlatMap lambdas into the consuming
distributed op at *compile time* via template function stacks
(reference: thrill/api/dia.hpp:358-387 stack push, tlx::FunctionStack),
so no per-item virtual call happens. The TPU-native equivalent: a DIA
handle carries a tuple of StackOps which are *traced* into the consuming
operator's jitted program — XLA fusion replaces template fusion, and the
whole chain becomes one device kernel between materialization points.

Semantics of user functions:
* host storage  — ``fn`` is applied per item (Thrill-style).
* device storage — ``fn`` is applied to **batched columns**: each leaf of
  the item pytree carries a leading item axis. For elementwise lambdas
  (``lambda x: x * 2``, ``lambda kv: (kv[0], kv[1] + 1)``) this is
  identical to per-item semantics; scalar outputs are broadcast to the
  item axis automatically. Items whose leaves are themselves arrays
  (fixed-width byte strings) must index with an explicit trailing axis
  (``x[:, 3]``), the one divergence from per-item code.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class StackOp:
    kind: str                      # 'map' | 'filter' | 'flat_map'
    fn: Callable                   # see module docstring for semantics
    # device flat_map only: static expansion factor k; fn returns
    # (tree [n, k, ...], valid [n, k]) in batched form.
    factor: int = 1

    def cache_token(self) -> Tuple:
        # the function object itself (hashable by identity) keys the
        # compiled-program cache; holding it in the key pins it alive so
        # a freed lambda's id can never alias onto a stale executable
        return (self.kind, self.fn, self.factor)


Stack = Tuple[StackOp, ...]


def stack_cache_token(stack: Stack) -> Tuple:
    return tuple(op.cache_token() for op in stack)


def _broadcast_outputs(tree: Any, n: int) -> Any:
    """Broadcast scalar leaves to the item axis after a map fn."""
    def fix(leaf):
        arr = jnp.asarray(leaf)
        if arr.ndim == 0 or arr.shape[0] != n:
            arr = jnp.broadcast_to(arr, (n,) + arr.shape)
        return arr
    return jax.tree.map(fix, tree)


def apply_stack_traced(tree: Any, mask: jnp.ndarray, stack: Stack):
    """Apply a stack inside a traced program. Returns (tree, mask).

    The item count may grow only through flat_map (factor-k static
    expansion); mask tracks validity, compaction happens once at the
    consumer's boundary.
    """
    for op in stack:
        n = mask.shape[0]
        if op.kind == "map":
            tree = _broadcast_outputs(op.fn(tree), n)
        elif op.kind == "filter":
            keep = jnp.asarray(op.fn(tree))
            mask = mask & keep.astype(bool)
        elif op.kind == "flat_map":
            out_tree, out_valid = op.fn(tree)
            k = op.factor
            out_valid = jnp.asarray(out_valid)
            assert out_valid.shape[:2] == (n, k), (
                f"flat_map valid mask must be [n, {k}], got {out_valid.shape}")
            tree = jax.tree.map(
                lambda leaf: jnp.reshape(leaf, (n * k,) + leaf.shape[2:]),
                out_tree)
            mask = (mask[:, None] & out_valid.astype(bool)).reshape(n * k)
        else:  # pragma: no cover
            raise ValueError(op.kind)
    return tree, mask


def apply_stack_host_item(item: Any, stack: Stack, emit: Callable) -> None:
    """Apply a stack to one host item, calling ``emit`` per output item."""
    if not stack:
        emit(item)
        return
    op, rest = stack[0], stack[1:]
    if op.kind == "map":
        apply_stack_host_item(op.fn(item), rest, emit)
    elif op.kind == "filter":
        if op.fn(item):
            apply_stack_host_item(item, rest, emit)
    elif op.kind == "flat_map":
        for out in op.fn(item):
            apply_stack_host_item(out, rest, emit)
    else:  # pragma: no cover
        raise ValueError(op.kind)


def apply_stack_host_list(items, stack: Stack) -> list:
    out: list = []
    append = out.append
    for it in items:
        apply_stack_host_item(it, stack, append)
    return out
