"""ReduceByKey / ReducePair / ReduceToIndex.

Reference: thrill/api/reduce_by_key.hpp:64 (two-phase hash aggregation:
pre-phase table partitioned by worker, stream shuffle, post-phase table)
and reduce_to_index.hpp:60 (range-partitioned dense variant).

TPU-native design: both phases are sort+segmented-reduce device programs
(see core/segmented.py) around a hash- or range-partitioned all-to-all
exchange — pre-reduction cuts shuffle volume exactly like the reference's
pre-phase table, and the whole pipeline is three jitted SPMD programs.
Host storage falls back to dict-based aggregation per worker (the same
algorithm the reference runs, in Python).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np

from ...common import hashing
from ...common.partition import dense_range_bounds
from ...core import keys as keymod
from ...core import segmented
from ...data import exchange
from ...data.shards import DeviceShards, HostShards, compact_valid
from ..dia import DIA
from ..dia_base import DIABase
from ...parallel.mesh import AXIS


# device DuplicateDetection registers are sized per site by
# core/preshuffle.register_width (collisions only cause unnecessary
# shuffling, never wrong results)


def _device_fold_specs(reduce_fn, treedef, leaves):
    """Flat FieldReduce specs when the DEVICE segment-op specialization
    applies (core/segmented.py segmented_reduce_fields), else None."""
    from ..functors import FieldReduce
    if not isinstance(reduce_fn, FieldReduce):
        return None
    specs = reduce_fn.flat_spec(treedef)
    if specs is None or not segmented.fields_specializable(
            specs, [l.dtype for l in leaves]):
        return None
    return specs


def _local_reduce_device(shards: DeviceShards, key_fn: Callable,
                         reduce_fn: Callable, phase: str,
                         token) -> DeviceShards:
    """One jitted program: encode keys, sort, segmented-reduce, compact."""
    mex = shards.mesh_exec
    # an optimistic post-exchange input may owe its capacity check —
    # heal before reading the columns (data/exchange.py)
    shards.validate_pending()
    out = _host_reduce_shards(shards, key_fn, reduce_fn)
    if out is not None:
        return out
    cap = shards.cap
    leaves, treedef = jax.tree.flatten(shards.tree)
    specs = _device_fold_specs(reduce_fn, treedef, leaves)
    key = ("reduce_local", phase, token, cap, treedef,
           tuple((l.dtype, l.shape[2:]) for l in leaves))

    def build():
        def f(counts_dev, *ls):
            valid = jnp.arange(cap) < counts_dev[0, 0]
            tree = jax.tree.unflatten(treedef, [l[0] for l in ls])
            words = keymod.encode_key_words(key_fn(tree))
            words, tree, valid, _ = segmented.sort_by_key_words(
                words, tree, valid)
            words, tree, rep = segmented.reduce_runs(
                words, tree, valid, reduce_fn, specs)
            tree, new_count = compact_valid(tree, rep)
            out_leaves = jax.tree.leaves(tree)
            return (new_count[None, None].astype(jnp.int32),
                    *[l[None] for l in out_leaves])

        return mex.smap(f, 1 + len(leaves))

    fn = mex.cached(key, build)
    out = fn(shards.counts_device(), *leaves)
    tree = jax.tree.unflatten(treedef, list(out[1:]))
    # counts stay on device: pre-phase -> exchange phase A dispatches
    # back-to-back with no host sync in between
    return DeviceShards(mex, tree, out[0])


def _host_reduce_shards(shards: DeviceShards, key_fn: Callable,
                        reduce_fn: Callable) -> Optional[DeviceShards]:
    """CPU-backend mirror of :func:`_local_reduce_device`: native
    hash-grouping (core/host_radix.py) + a strided in-place run fold.

    On the CPU backend device buffers are host memory and XLA's
    single-core sort + associative_scan are the wrong engines (a 1.2M
    row WordCount reduce spent ~17s there). Grouping uses the native
    open-addressing table (ONE pass; the engine class of the
    reference's ReducePrePhase, thrill/core/reduce_pre_phase.hpp:94)
    rather than the radix argsort — ReduceByKey only needs equal keys
    adjacent, not sorted. The fold then combines each group to its head
    row in log2(longest run) vectorized ``reduce_fn`` calls (same
    associativity contract as the device segmented scan) with a total
    gathered-row volume of ~1n (see :func:`_strided_run_fold`).

    Returns None when inapplicable (non-CPU, multi-controller, trace-
    only key_fn) so the caller falls through to the jitted engine."""
    from ...core import host_radix

    mex = shards.mesh_exec
    if not host_radix.eligible(mex):
        return None
    leaves, treedef = jax.tree.flatten(shards.tree)
    leaves_np = [np.asarray(l) for l in leaves]          # [W, cap, ...]
    W = mex.num_workers
    out_counts = np.zeros(W, dtype=np.int64)
    per_worker = []
    # any failure (trace-only key_fn, a reduce_fn using jax-array-only
    # APIs like .at[] on the numpy trees, ...) falls back to the jitted
    # engine, which either handles it or raises the real error
    try:
        for w in range(W):
            cnt = int(shards.counts[w])
            tree = jax.tree.unflatten(treedef,
                                      [l[w][:cnt] for l in leaves_np])
            if cnt == 0:
                per_worker.append(tree)
                continue
            words = keymod.encode_key_words_np(key_fn(tree))
            fused = _fused_field_reduce(tree, treedef, words, reduce_fn)
            if fused is not None:
                tree, ngroups = fused
            else:
                perm, lens = host_radix.hash_group(words)
                tree = jax.tree.map(
                    lambda a: host_radix.gather_rows(
                        np.ascontiguousarray(a), perm), tree)
                # identity write-back skip is only sound for functors
                # known pure; a black-box reduce_fn may mutate its
                # left argument in place and return it
                from ..functors import FieldReduce
                tree = _strided_run_fold(
                    tree, lens, reduce_fn,
                    allow_identity_skip=isinstance(reduce_fn, FieldReduce))
                ngroups = len(lens)
            per_worker.append(tree)
            out_counts[w] = ngroups
    except host_radix.NativeEngineError:
        # the native engine itself is broken (bad rc / plan mismatch) —
        # not an inapplicable-input case. Warn loudly before falling
        # back so a real bug doesn't masquerade as slowness.
        import warnings
        import traceback
        warnings.warn("native reduce engine failed; falling back to the "
                      "jitted engine:\n" + traceback.format_exc(),
                      RuntimeWarning)
        return None
    except Exception:
        return None
    return DeviceShards.from_worker_arrays(mex, per_worker,
                                           counts=out_counts)


def _fused_field_reduce(tree, treedef, words, reduce_fn):
    """FieldReduce fast path: when the reduce functor is declarative
    (api/functors.py) and every accumulated leaf is a supported scalar
    column, the ENTIRE local reduction runs as one native hash-probe
    pass (hash_group_acc_u64) — grouping and accumulation fused, no
    permutation/gather/fold afterwards. This is the runtime analog of
    the reference's templates inlining the functor into the probing
    table (thrill/core/reduce_pre_phase.hpp:94). Returns
    ``(out_tree, ngroups)`` or None to fall back to the generic fold."""
    from ..functors import FieldReduce, acc_plan
    from ...core import host_radix

    if not isinstance(reduce_fn, FieldReduce):
        return None
    specs = reduce_fn.flat_spec(treedef)
    if specs is None:
        return None
    leaves = jax.tree.leaves(tree)
    plans = []
    for s, a in zip(specs, leaves):
        p = acc_plan(s, a.dtype, a.ndim)
        if p is None:
            return None
        plans.append(p)
    cols, ops = [], []
    for (opcode, conv), a in zip(plans, leaves):
        if opcode < 0:
            continue                       # "first": gathered below
        ops.append(opcode)
        cols.append(a.astype(conv, copy=False))
    heads, accs = host_radix.hash_group_acc(words, cols, ops)
    out_leaves, ai = [], 0
    for (opcode, conv), a in zip(plans, leaves):
        if opcode < 0:
            out_leaves.append(
                host_radix.gather_rows(np.ascontiguousarray(a), heads))
        else:
            acc = accs[ai]
            ai += 1
            out_leaves.append(acc if acc.dtype == a.dtype
                              else acc.astype(a.dtype))
    return jax.tree.unflatten(treedef, out_leaves), len(heads)


def _strided_run_fold(tree, lens: np.ndarray, reduce_fn: Callable,
                      allow_identity_skip: bool = False):
    """Fold each contiguous run of group-clustered rows into its head
    row, in place, then gather the heads.

    Classic power-of-two strided up-sweep over stable row indices: the
    row at in-run position p > 0 is absorbed exactly once, at step
    s = p & -p, into the row s slots left of it (which by then holds
    the fold of positions [p-s, p)), so after all steps each run head
    holds the left-to-right fold of its whole run. Compared to a
    compact-every-level scheme this needs NO per-level position
    recomputation (the native ``fold_plan`` emits all per-level index
    lists in one O(n) pass) and no whole-tree compaction per level:
    total gathered+scattered rows across all levels is exactly
    3*(n - num_runs) plus one final head gather. ``reduce_fn`` sees
    (left_rows, right_rows) with left rows earlier in the run, so
    non-commutative (associative) functions are safe.

    MUTATES the leaves of ``tree`` (callers pass freshly gathered
    arrays). Returns the head-compacted tree (len(lens) rows)."""
    from ...core import host_radix

    leaves, td = jax.tree.flatten(tree)
    leaves = [np.ascontiguousarray(a) for a in leaves]
    ri_all, level_counts = host_radix.fold_plan(lens)
    off = 0
    for lvl in range(32):
        lc = int(level_counts[lvl])
        if lc == 0:
            continue
        ri = ri_all[off:off + lc]
        off += lc
        li = (ri - np.uint32(1 << lvl)).astype(np.uint32, copy=False)
        left = jax.tree.unflatten(
            td, [host_radix.gather_rows(a, li) for a in leaves])
        right = jax.tree.unflatten(
            td, [host_radix.gather_rows(a, ri) for a in leaves])
        left_leaves = jax.tree.leaves(left)
        merged = reduce_fn(left, right)
        if jax.tree.structure(merged) != td:
            # positional zip below would silently scatter mispaired
            # leaves; a malformed reduce_fn must be a hard error (the
            # jitted engine's tree.map raises on this too)
            raise ValueError(
                f"reduce_fn returned tree structure "
                f"{jax.tree.structure(merged)} != item structure {td}")
        for a, m, ll in zip(leaves, jax.tree.leaves(merged), left_leaves):
            if allow_identity_skip and m is ll:
                # a PURE functor (FieldReduce "first") passed the left
                # rows through unchanged: scattering a[li] back to
                # a[li] is a no-op. Gated on provable purity — a
                # black-box reduce_fn returning `m is ll` may have
                # MUTATED the gathered left leaf in place, and its
                # merged values must still be written back.
                continue
            host_radix.scatter_rows(
                a, li, np.ascontiguousarray(np.asarray(m), dtype=a.dtype))
    starts = np.zeros(len(lens), dtype=np.uint32)
    np.cumsum(lens[:-1], dtype=np.uint32, out=starts[1:])
    return jax.tree.unflatten(
        td, [host_radix.gather_rows(a, starts) for a in leaves])


def _fold_reduce_device(acc: DeviceShards, block: DeviceShards,
                        key_fn: Callable, reduce_fn: Callable,
                        token) -> DeviceShards:
    """One jitted program folding two reduced shards into one: concat
    both valid prefixes, sort by key words, segmented-reduce, compact.
    Counts stay device-resident end to end — the whole streamed post
    phase runs with zero host syncs.

    The output capacity is round_up_pow2(capA + capB). Callers must NOT
    fold a long stream linearly through one accumulator — feeding the
    rounded cap back makes the accumulator double every fold
    (exponential padding). The streamed post phase folds blocks as a
    binary counter instead (see ``_compute_device_stream``): caps stay
    on a power-of-two ladder, only O(log W) distinct shapes compile,
    and worst-case padded rows stay within ~2x the bulk path."""
    from ...common.config import round_up_pow2
    mex = acc.mesh_exec
    leaves_a, td = jax.tree.flatten(acc.tree)
    leaves_b, td_b = jax.tree.flatten(block.tree)
    assert td == td_b, "fold requires matching schemas"
    capA, capB = acc.cap, block.cap
    out_cap = round_up_pow2(capA + capB)
    nA = len(leaves_a)
    specs = _device_fold_specs(reduce_fn, td, leaves_a)
    key = ("reduce_fold", token, capA, capB, out_cap, td,
           tuple((l.dtype, l.shape[2:]) for l in leaves_a))

    def build():
        def f(ca, cb, *ls):
            validA = jnp.arange(capA) < ca[0, 0]
            validB = jnp.arange(capB) < cb[0, 0]
            treeA = jax.tree.unflatten(td, [l[0] for l in ls[:nA]])
            treeB = jax.tree.unflatten(td, [l[0] for l in ls[nA:]])
            tree = jax.tree.map(
                lambda a, b: jnp.concatenate([a, b], axis=0),
                treeA, treeB)
            valid = jnp.concatenate([validA, validB])
            words = keymod.encode_key_words(key_fn(tree))
            words, tree, valid, _ = segmented.sort_by_key_words(
                words, tree, valid)
            words, tree, rep = segmented.reduce_runs(
                words, tree, valid, reduce_fn, specs)
            tree, new_count = compact_valid(tree, rep)
            pad = out_cap - (capA + capB)
            tree = jax.tree.map(
                lambda l: jnp.pad(l, [(0, pad)] + [(0, 0)] * (l.ndim - 1))
                if pad else l, tree)
            out_leaves = jax.tree.leaves(tree)
            return (new_count[None, None].astype(jnp.int32),
                    *[l[None] for l in out_leaves])

        return mex.smap(f, 2 + 2 * nA)

    fn = mex.cached(key, build)
    out = fn(acc.counts_device(), block.counts_device(),
             *leaves_a, *leaves_b)
    tree = jax.tree.unflatten(td, list(out[1:]))
    return DeviceShards(mex, tree, out[0])


class ReduceNode(DIABase):
    # both phase tables want workspace (reference: ReduceByKey registers
    # DIAMemUse::Max for its pre/post tables, api/reduce_by_key.hpp);
    # the host path sizes its EM tables from the grant, the device path
    # bounds memory by construction and leaves the grant unused
    MEM_USE = "max"

    def __init__(self, ctx, link, key_fn: Callable, reduce_fn: Callable,
                 label: str = "ReduceByKey",
                 dup_detection=None, token=None) -> None:
        super().__init__(ctx, label, [link])
        self.key_fn = key_fn
        self.reduce_fn = reduce_fn
        # executable-cache token. When a wrapper (ReducePair) mints
        # fresh closures per call, it must pass a token derived from
        # the USER's stable functions, or loops recompile every
        # iteration.
        self.token = token if token is not None else (key_fn, reduce_fn)
        # reference: DuplicateDetectionTag, api/reduce_by_key.hpp — skip
        # shuffling keys whose hash is globally unique. None = decided
        # by the plan-time cost model (core/preshuffle.py)
        self.dup_detection = dup_detection

    def _fuse_segment(self, phase: str):
        """This node's local combine phase as a fused segment
        (api/fusion.py): the same encode + sort + segmented-reduce
        trace as :func:`_local_reduce_device`, stitched into a larger
        program instead of paying its own dispatch. The FieldReduce
        specs are derived at trace time from the actual traced tree
        (the composite plan key pins treedef/dtypes, so the choice is
        deterministic per executable)."""
        from ...core import host_radix
        from .. import fusion
        if host_radix.eligible(self.context.mesh_exec):
            return None      # the native CPU engine beats the jitted one
        key_fn, reduce_fn = self.key_fn, self.reduce_fn

        def trace(fctx, tree, mask, _bound):
            leaves, td = jax.tree.flatten(tree)
            specs = _device_fold_specs(reduce_fn, td, leaves)
            words = keymod.encode_key_words(key_fn(tree))
            words, tree_s, valid, _ = segmented.sort_by_key_words(
                words, tree, mask)
            words, tree_s, rep = segmented.reduce_runs(
                words, tree_s, valid, reduce_fn, specs)
            return tree_s, rep

        return fusion.Segment(label="ReduceLocal",
                              token=("reduce_local", phase, self.token),
                              trace=trace, dia_id=self.id)

    def compute_plan(self):
        from .. import fusion
        plan = fusion.pull_plan(self.parents[0])
        seg = self._fuse_segment("pre") if plan.stitchable else None
        if seg is None:
            return fusion.wrap(self._compute_on(plan.finish()))
        plan.append(seg)
        if self.context.num_workers == 1:
            # the pre-phase IS the whole reduce at W == 1: hand the
            # plan on so downstream ops stitch onto it
            return plan
        # finish(), not execute(): the exchange below is a fusion
        # barrier consuming the columns — pending checks drain first
        pre = plan.finish()
        return self._post_exchange(pre)

    def compute(self):
        plan = self.compute_plan()
        return plan.finish()

    def _compute_on(self, shards):
        """Pre-fusion compute body over pulled shards (the
        THRILL_TPU_FUSE=0 path, and the host/native fallbacks)."""
        if isinstance(shards, HostShards):
            return self._compute_host(shards)
        key_fn, reduce_fn = self.key_fn, self.reduce_fn
        token = self.token
        W = self.context.num_workers
        # pre-phase: local combine (reference: ReducePrePhase)
        pre = _local_reduce_device(shards, key_fn, reduce_fn, "pre", token)
        if W == 1:
            # the pre-phase already combined every key; with no
            # exchange there is nothing for a post phase to merge
            return pre
        return self._post_exchange(pre).finish()

    def _post_exchange(self, pre: "DeviceShards"):
        """Shuffle the pre-reduced shards and run the post combine.
        Returns a FusionPlan (post phase pending when fusible, so
        downstream ops can stitch onto it)."""
        from .. import fusion
        key_fn, reduce_fn = self.key_fn, self.reduce_fn
        token = self.token
        W = self.context.num_workers
        mex = self.context.mesh_exec
        dup = self.dup_detection
        if dup is None and W > 1:
            # plan-time cost model (core/preshuffle.py): presence-
            # register psum bytes vs the pre-reduced rows expected to
            # stay local. The pre-phase cap is globally agreed, so the
            # verdict is deterministic across controllers.
            from ...core import preshuffle
            import jax as _jax
            item_bytes = exchange.leaf_item_bytes(
                _jax.tree.leaves(pre.tree))
            dup = preshuffle.auto_dup_detect(
                mex, pre.cap * W, item_bytes, ("reduce_dup", token))
        dup = bool(dup)
        # shuffle by key hash (reference: Mix/CatStream exchange).
        # With DuplicateDetection, globally-unique key hashes skip the
        # shuffle: a register psum inside the destination program finds
        # hashes held by exactly one worker and keeps those items local
        # (reference: core/duplicate_detection.hpp:46 — the Golomb-coded
        # register exchange becomes one psum over a [M] register array).
        if W > 1:
            if dup:
                from ...core import preshuffle
                M = preshuffle.register_width(pre.cap * W)
            else:
                M = 0

            def dest(tree, mask, widx):
                words = keymod.encode_key_words(key_fn(tree))
                h = hashing.hash_key_words(words)
                hash_dest = (h % jnp.uint64(W)).astype(jnp.int32)
                if not dup:
                    return hash_dest
                reg = (h % jnp.uint64(M)).astype(jnp.int32)
                # presence (not item counts): a worker contributes 0/1
                # per register, so the psum'd holder count fits u8 for
                # W < 256 — a quarter of the i32 registers' fabric
                # bytes, same verdict ("exactly one worker holds this
                # hash, and it is me"). Wider meshes keep i32: a u8
                # psum would WRAP (257 holders reads as 1) and silently
                # keep colliding keys local — wrong results, not just
                # extra traffic.
                reg_dt = jnp.uint8 if W < 256 else jnp.int32
                if reg_dt == jnp.uint8:
                    # register fill through the Pallas presence kernel
                    # where it engages (bit-identical: presence is 0/1)
                    from ...core.pallas_kernels import presence_fill
                    local = presence_fill(reg, mask, M)
                else:
                    local = jnp.zeros(M, reg_dt).at[reg].max(
                        mask.astype(reg_dt))
                holders = lax.psum(local, AXIS)
                mine_only = (jnp.take(holders, reg) == 1) & \
                    (jnp.take(local, reg) == 1)
                return jnp.where(mine_only, widx.astype(jnp.int32),
                                 hash_dest)

            import os
            if os.environ.get("THRILL_TPU_REDUCE_STREAM") == "1":
                # MixStream-analog post phase: fold each received round
                # into the accumulator while later rounds' collectives
                # are still in flight (reference: use_post_thread_
                # overlap, api/reduce_by_key.hpp:142-168, over
                # MixStream's arbitrary-order delivery)
                return fusion.wrap(
                    self._compute_device_stream(pre, dest, token, dup))
            pre = exchange.exchange(pre, dest,
                                    ("reduce_dest", token, W, dup))
        # post-phase: final combine (reference: ReduceByHashPostPhase);
        # fusible, so the chain continues across the exchange barrier
        if fusion.enabled():
            seg = self._fuse_segment("post")
            if seg is not None:
                plan = fusion.FusionPlan(pre.mesh_exec, [pre])
                plan.append(seg)
                return plan
        return fusion.wrap(
            _local_reduce_device(pre, key_fn, reduce_fn, "post", token))

    def _compute_device_stream(self, pre: DeviceShards, dest, token,
                               dup: bool = False):
        """Streamed post-phase: per-round receive + incremental fold.

        Every yielded round block is folded by ONE jitted program
        (concat + sort + segmented reduce, counts staying
        device-resident throughout — a host counts sync per round would
        serialize the rounds); jax async dispatch overlaps round r's
        fold with round r+1's ppermute.

        Blocks combine as a BINARY COUNTER (bottom-up merge-sort
        shape): ``levels[i]`` holds the reduction of 2^i round blocks;
        a new block folds up through full levels. A single linear
        accumulator would double its padded cap on every fold (the fold
        rounds capA+capB up to a power of two and feeds it back —
        exponential growth); the counter keeps every fold between
        same-magnitude shards, so caps walk a pow2 ladder with O(log W)
        distinct compiled shapes and ~2x the bulk path's padded rows.
        """
        key_fn, reduce_fn = self.key_fn, self.reduce_fn
        W = self.context.num_workers
        levels: List[Optional[DeviceShards]] = []
        for block in exchange.exchange_stream(
                pre, dest, ("reduce_dest", token, W, dup)):
            # round blocks carry pre-reduced (unique-key) rows, so any
            # block IS a valid partial accumulator
            cur = block
            i = 0
            while i < len(levels) and levels[i] is not None:
                cur = _fold_reduce_device(levels[i], cur, key_fn,
                                          reduce_fn, token)
                levels[i] = None
                i += 1
            if i == len(levels):
                levels.append(cur)
            else:
                levels[i] = cur
        acc: Optional[DeviceShards] = None
        for lv in levels:                  # fold up the leftovers
            if lv is None:
                continue
            acc = lv if acc is None else _fold_reduce_device(
                lv, acc, key_fn, reduce_fn, token)
        return acc

    def _compute_host(self, shards: HostShards):
        W = shards.num_workers
        mex = self.context.mesh_exec
        key_fn, reduce_fn = self.key_fn, self.reduce_fn
        from ...core.em_table import EMReduceTable
        from ...data import multiplexer
        from ...data.block_pool import spill_pool
        owns_input = self.parents[0].node.state == "DISPOSED"
        # pre-phase per worker (local combine cuts shuffle volume, the
        # reference's ReducePrePhase table). Deliberately NOT
        # grant-flushed: the input it folds is already RAM-resident, so
        # the table's footprint is bounded by the input itself (at most
        # one folded aggregate per distinct key), while flushing
        # partials to the outgoing list — the in-RAM analog of the
        # reference's flush-to-NETWORK (core/reduce_pre_phase.hpp) —
        # would regress high-duplication workloads from O(distinct) to
        # O(items) decorated tuples in RAM and on the wire (round-5
        # review). The grant-bounded EM machinery lives in the POST
        # phase below, where spills leave RAM for the block store.
        pre_entries: List[list] = []      # per worker: [(k, v), ...]
        for lst in shards.lists:
            table: dict = {}
            for it in lst:
                k = key_fn(it)
                table[k] = reduce_fn(table[k], it) if k in table else it
            pre_entries.append(list(table.items()))
            if owns_input:
                lst.clear()       # spill-free analog of Sort's release
        # one hash per entry, computed once and carried with the item
        # through detection, keep-check and the shuffle dest
        pre_hashes = [[hashing.stable_host_hash(k) for k, _ in entries]
                      for entries in pre_entries]
        non_unique = None
        dup = self.dup_detection
        if dup is None:
            # host path: exact local entry counts feed the cost model
            # (local_rows: multi-controller runs all-reduce them to
            # the global count before deciding, core/preshuffle.py)
            from ...core import preshuffle
            rows = sum(len(h) for h in pre_hashes)
            dup = preshuffle.auto_dup_detect(
                mex, rows, 32, ("reduce_dup_host", self.token),
                local_rows=True)
        if dup and W > 1:
            from ...core import duplicate_detection as dd
            hash_lists = pre_hashes
            if multiplexer.multiprocess(mex):
                # fingerprint exchange over the control plane: ship the
                # hashes (not the items) so every process agrees on the
                # globally-unique set (reference:
                # core/duplicate_detection.hpp:46)
                local = {w: hash_lists[w] for w in mex.local_workers}
                merged = [[] for _ in range(W)]
                for msg in mex.host_net.all_gather(local):
                    for w, hs in msg.items():
                        merged[int(w)] = hs
                hash_lists = merged
            non_unique = dd.find_non_unique_hashes(hash_lists)
        # shuffle + post-phase; globally-unique keys stay local. Items
        # travel as (src_worker_kept, hash, key, value) so the
        # PRE-PHASE key stays authoritative (reduce_fn need not
        # preserve key_fn — the reference's tables likewise carry the
        # extracted key) and the precomputed hash rides along instead
        # of being recomputed per routing decision.
        def dest(kv):
            keep, h, _, _ = kv
            if keep is not None:
                return keep
            return h % W

        pre_lists = []
        for w, entries in enumerate(pre_entries):
            hs = pre_hashes[w]
            lst = []
            for (k, v), h in zip(entries, hs):
                keep = None
                if non_unique is not None and dd.is_unique(h, non_unique):
                    keep = w              # globally unique: stays local
                lst.append((keep, h, k, v))
            entries.clear()
            pre_lists.append(lst)
        del pre_entries, pre_hashes
        # hash-partition target: the post-phase reduce table is keyed,
        # so batch ARRIVAL order is semantically free — under
        # THRILL_TPU_HOST_MIX=1 delivery is MixStream (arrival order;
        # note a non-commutative float reduce_fn then folds in that
        # order — the documented contract for opting in)
        ex = multiplexer.host_exchange(mex, HostShards(W, pre_lists),
                                       dest, reason="reduce",
                                       rank_order=False)
        # post-phase: EM reduce tables sized by the grant — spilled
        # partitions re-reduce recursively, so distinct keys beyond the
        # grant stream through bounded RAM (reference:
        # core/reduce_by_hash_post_phase.hpp:44-120)
        pool = spill_pool(self.context.config.spill_dir,
                          self.mem_limit)
        stats: dict = {}
        post_lists = []
        try:
            for items in ex.lists:
                t = EMReduceTable(reduce_fn, pool, self.mem_limit,
                                  stats=stats or None)
                stats = t.stats
                for _, h, k, v in items:
                    t.insert(k, v, h)
                items.clear()    # exchange output is ours: free as we go
                post_lists.append(list(t.emit()))
                t.close()
        finally:
            pool.close()
        self._em_stats = stats
        if stats.get("spills") and self.context.logger.enabled:
            self.context.logger.line(event="reduce_post_spill",
                                     node=self.label, dia_id=self.id,
                                     **stats)
        return HostShards(W, post_lists)


def ReduceByKey(dia: DIA, key_fn: Callable, reduce_fn: Callable,
                dup_detection=None) -> DIA:
    return DIA(ReduceNode(dia.context, dia._link(), key_fn, reduce_fn,
                          dup_detection=dup_detection))


def ReducePair(dia: DIA, value_reduce_fn) -> DIA:
    """Items are (key, value) pairs; combine values of equal keys.
    Reference: ReducePair, api/reduce_by_key.hpp.

    ``value_reduce_fn`` may be a callable, or a declarative op string
    ("sum"/"min"/"max") — the spelling of the reference's common
    functors (std::plus, common::minimum) that unlocks the fused
    native aggregation path (api/functors.py FieldReduce)."""
    def key_fn(kv):
        return kv[0]

    if isinstance(value_reduce_fn, str):
        from ..functors import FieldReduce
        red = FieldReduce(("first", value_reduce_fn))
        # token carries the content-hashed functor, NOT the per-call
        # key_fn closure — identical specs share compiled executables
        return DIA(ReduceNode(dia.context, dia._link(), key_fn, red,
                              label="ReducePair",
                              token=("ReducePair", red)))

    def reduce_fn(a, b):
        return (a[0], value_reduce_fn(a[1], b[1]))

    return DIA(ReduceNode(dia.context, dia._link(), key_fn, reduce_fn,
                          label="ReducePair",
                          token=("ReducePair", value_reduce_fn)))


def _host_reduce_to_index(shards: DeviceShards, index_fn, reduce_fn,
                          bounds: np.ndarray, neutral):
    """CPU-backend mirror of ReduceToIndex's dense scatter-reduce (the
    same engine-selection argument as :func:`_host_reduce_shards`).

    FieldReduce specs run as numpy ufunc.at scatter-accumulations per
    column (no grouping pass at all); generic reduce functions group
    via the native hash table + strided fold, then scatter group heads
    by index. Unset indices fill with ``neutral`` (zeros when None,
    matching the device program's zero base). Returns None when
    inapplicable."""
    from ...core import host_radix
    from ..functors import FieldReduce, acc_plan

    mex = shards.mesh_exec
    if not host_radix.eligible(mex):
        return None
    leaves, treedef = jax.tree.flatten(shards.tree)
    leaves_np = [np.asarray(l) for l in leaves]
    W = mex.num_workers
    local_sizes = (bounds[1:] - bounds[:-1]).astype(np.int64)
    neutral_leaves = None
    if neutral is not None:
        if jax.tree.structure(neutral) != treedef:
            # positional pairing below would silently mismatch fields;
            # the jitted engine raises loudly on this — let it
            return None
        neutral_leaves = jax.tree.leaves(neutral)
    specs = None
    if isinstance(reduce_fn, FieldReduce):
        specs = reduce_fn.flat_spec(treedef)
        if specs is not None:
            for s, a in zip(specs, leaves_np):
                if s == "first":
                    continue         # any shape scatters fine
                # ufunc.at path needs 1-D numeric columns for the
                # accumulated fields (per-worker ndim = a.ndim - 1)
                if acc_plan(s, a.dtype, a.ndim - 1) is None:
                    specs = None
                    break
    per_worker = []
    try:
        for w in range(W):
            cnt = int(shards.counts[w])
            lo = int(bounds[w])
            size = int(local_sizes[w])
            tree = jax.tree.unflatten(treedef,
                                      [l[w][:cnt] for l in leaves_np])
            cols = jax.tree.leaves(tree)
            idx = (np.asarray(index_fn(tree)).astype(np.int64) - lo
                   if cnt else np.zeros(0, np.int64))
            if cnt and (idx.min() < 0 or idx.max() >= size):
                return None          # out-of-range: let the jitted
                                     # engine's clip semantics apply
            present = np.zeros(size, dtype=bool)
            present[idx] = True
            out_leaves = []
            if specs is not None:
                for s, col in zip(specs, cols):
                    out_leaves.append(
                        _scatter_field(s, col, idx, size))
            else:
                if cnt:
                    perm, lens = host_radix.hash_group(
                        [idx.astype(np.uint64)])
                    gtree = jax.tree.map(
                        lambda a: host_radix.gather_rows(
                            np.ascontiguousarray(a), perm), tree)
                    gtree = _strided_run_fold(
                        gtree, lens, reduce_fn,
                        allow_identity_skip=isinstance(reduce_fn,
                                                       FieldReduce))
                    starts = np.zeros(len(lens), dtype=np.uint32)
                    np.cumsum(lens[:-1], dtype=np.uint32,
                              out=starts[1:])
                    gidx = idx[perm[starts]]
                    for col in jax.tree.leaves(gtree):
                        base = np.zeros((size,) + col.shape[1:],
                                        col.dtype)
                        base[gidx] = col
                        out_leaves.append(base)
                else:
                    out_leaves = [np.zeros((size,) + a.shape[2:],
                                           a.dtype) for a in leaves_np]
            # fill indices no item mapped to: the neutral value, or 0
            # (the device program's zero scatter base) — ALWAYS applied
            # so min/max sentinel fills never leak into the output
            for i, ol in enumerate(out_leaves):
                nv = (neutral_leaves[i] if neutral_leaves is not None
                      else 0)
                ol[~present] = nv
            per_worker.append(jax.tree.unflatten(treedef, out_leaves))
    except host_radix.NativeEngineError:
        # same loud-fallback policy as _host_reduce_shards: a broken
        # native engine must not masquerade as slowness
        import traceback
        import warnings
        warnings.warn("native ReduceToIndex engine failed; falling "
                      "back to the jitted engine:\n"
                      + traceback.format_exc(), RuntimeWarning)
        return None
    except Exception:
        return None
    return DeviceShards.from_worker_arrays(mex, per_worker,
                                           counts=local_sizes)


def _scatter_field(op: str, col: np.ndarray, idx: np.ndarray,
                   size: int) -> np.ndarray:
    """One FieldReduce column as a dense scatter-accumulate."""
    if op == "first":
        out = np.zeros((size,) + col.shape[1:], col.dtype)
        # reversed assignment: the FIRST occurrence wins
        out[idx[::-1]] = col[::-1]
        return out
    out = np.zeros(size, col.dtype)
    if op == "sum":
        np.add.at(out, idx, col)
        return out
    if op == "min":
        out.fill(_type_max(col.dtype))
        np.minimum.at(out, idx, col)
    else:
        out.fill(_type_min(col.dtype))
        np.maximum.at(out, idx, col)
    # untouched slots hold sentinels; the caller's neutral fill (or the
    # zero default) overwrites them via the presence mask
    return out


def _type_max(dt):
    return (np.inf if np.issubdtype(dt, np.floating)
            else np.iinfo(dt).max)


def _type_min(dt):
    return (-np.inf if np.issubdtype(dt, np.floating)
            else np.iinfo(dt).min)


def _scatter_fold_specs(reduce_fn, treedef, leaves):
    """Flat FieldReduce specs when the SORT-FREE dense scatter engine
    applies to every leaf (ReduceToIndex only): "sum"/"min"/"max" need
    numeric non-bool leaves, "first" works for any dtype (scatter-min
    arbitration over arrival order + one gather). Returns None when any
    leaf must go through the sorted segmented engine instead."""
    from ..functors import FieldReduce
    if not isinstance(reduce_fn, FieldReduce):
        return None
    specs = reduce_fn.flat_spec(treedef)
    if specs is None:
        return None
    for s, l in zip(specs, leaves):
        if s != "first" and (l.dtype == jnp.bool_
                             or not (jnp.issubdtype(l.dtype, jnp.number))):
            return None
    return specs


def _scatter_reduce_apply(tree, valid, local_idx, range_size, out_cap,
                          specs, neutral):
    """The dense ReduceToIndex phase as pure scatters — NO sort.

    The sorted engine pays an XLA argsort (~43 ms at 64 k rows on
    XLA:CPU — the dominant cost of iterative PageRank/k-means bodies);
    with declarative FieldReduce specs the same result is a direct
    ``.at[idx].add/min/max`` (deterministic: XLA applies duplicate
    updates in operand order) plus, for "first" fields, a scatter-min
    over arrival positions and one gather. Out-of-range indices are
    DROPPED (routed to the dump slot) rather than clamped like the
    sorted engine's clip — they cannot occur through the public op
    (the exchange routes every item into its worker's range).

    ``local_idx``: range-start-relative indices [cap]; ``valid``: item
    mask [cap]; ``range_size``: traced scalar (this worker's dense
    rows); ``out_cap``: static padded output rows. Returns the dense
    output tree ([out_cap, ...] leaves, neutral at untouched rows).
    """
    leaves, td = jax.tree.flatten(tree)
    cap = valid.shape[0]
    ok = valid & (local_idx >= 0) & (local_idx < range_size)
    pos = jnp.where(ok, local_idx, out_cap).astype(jnp.int32)
    win = None          # first-arrival winner per bin, computed lazily

    def winners():
        nonlocal win
        if win is None:
            arrival = jnp.where(ok, jnp.arange(cap, dtype=jnp.int32),
                                cap)
            win = jnp.full(out_cap + 1, cap,
                           jnp.int32).at[pos].min(arrival)[:out_cap]
        return win

    nleaves = (jax.tree.leaves(neutral) if neutral is not None
               else [None] * len(leaves))
    outs = []
    for s, leaf, nv in zip(specs, leaves, nleaves):
        trail = leaf.shape[1:]
        if s == "first":
            w = winners()
            col = jnp.take(leaf, jnp.clip(w, 0, cap - 1), axis=0)
            present = w < cap
        elif s == "sum":
            from ...core import pallas_kernels as _pk
            if (leaf.dtype == jnp.float32 and not trail
                    and _pk.pallas_enabled()
                    and _pk.segment_sum_ok(out_cap, cap)):
                # additive f32 fold through the Pallas segment-sum
                # kernel (the PageRank/k-means hot shape). Sum order
                # differs from the scatter (per-block partials), which
                # the unordered-reduce contract permits; the scatter
                # below stays THE path whenever the knob is off, so
                # THRILL_TPU_PALLAS=0 is bit-identical by construction.
                col = _pk.segment_sum_pallas(pos, leaf, out_cap)
            else:
                col = jnp.zeros((out_cap + 1,) + trail,
                                leaf.dtype).at[pos].add(leaf)[:out_cap]
            if nv is None or not np.any(np.asarray(nv)):
                # zero neutral == the scatter base: skip the presence
                # arbitration entirely (the PageRank/k-means hot shape)
                outs.append(col)
                continue
            present = winners() < cap
        else:
            big = jnp.asarray(_type_max(np.dtype(leaf.dtype))
                              if s == "min"
                              else _type_min(np.dtype(leaf.dtype)),
                              leaf.dtype)
            base = jnp.full((out_cap + 1,) + trail, big, leaf.dtype)
            col = (base.at[pos].min(leaf) if s == "min"
                   else base.at[pos].max(leaf))[:out_cap]
            present = winners() < cap
        fill = (jnp.zeros((), leaf.dtype) if nv is None
                else jnp.asarray(nv, leaf.dtype))
        pb = present.reshape(present.shape + (1,) * len(trail))
        outs.append(jnp.where(pb, col, fill))
    return jax.tree.unflatten(td, outs)


class ReduceToIndexNode(DIABase):
    """Key = dense index in [0, size); output is the dense array with
    ``neutral`` at unused indices (reference: api/reduce_to_index.hpp:60)."""

    def __init__(self, ctx, link, index_fn, reduce_fn, size, neutral) -> None:
        super().__init__(ctx, "ReduceToIndex", [link])
        self.index_fn = index_fn
        self.reduce_fn = reduce_fn
        self.size = int(size)
        self.neutral = neutral

    def _bounds(self):
        return dense_range_bounds(self.size, self.context.num_workers)

    def _exchange_by_index(self, shards, bounds, token):
        W = self.context.num_workers
        index_fn = self.index_fn
        bounds_dev = jnp.asarray(bounds)

        def dest(tree, mask, widx):
            idx = jnp.asarray(index_fn(tree)).astype(jnp.int64)
            return (jnp.searchsorted(bounds_dev[1:], idx, side="right")
                    ).astype(jnp.int32)

        return exchange.exchange(shards, dest, ("r2i_dest", token, W))

    def _fuse_segment(self, bounds: np.ndarray):
        """The dense scatter-reduce (post-exchange local phase) as a
        fused segment: sort by index, segmented-reduce, scatter into
        this worker's dense [range_size] rows."""
        from .. import fusion
        from ...common.config import round_up_pow2
        index_fn, reduce_fn = self.index_fn, self.reduce_fn
        neutral = self.neutral
        W = self.context.num_workers
        local_sizes = (bounds[1:] - bounds[:-1]).astype(np.int64)
        # pow2 cap like every other DeviceShards producer: a dense
        # result then has the SAME padded shape as a Generate'd table
        # of the same size, so loop carries (api/loop.py) are shape-
        # stable from iteration 0 and capture on the first pass
        out_cap = max(1, round_up_pow2(int(local_sizes.max())))
        ntok = None
        if neutral is not None:
            ntok = (str(jax.tree.structure(neutral)),
                    tuple(np.asarray(l).tobytes()
                          for l in jax.tree.leaves(neutral)))
        bound = (bounds[:W].astype(np.int64),
                 local_sizes.astype(np.int64))

        def trace(fctx, tree, mask, bound_t):
            starts, sizes = bound_t            # replicated [W] plans
            widx = lax.axis_index(AXIS)
            range_start = starts[widx]
            range_size = sizes[widx]
            leaves, td = jax.tree.flatten(tree)
            idx = jnp.asarray(index_fn(tree)).astype(jnp.int64)
            sc = _scatter_fold_specs(reduce_fn, td, leaves)
            if sc is not None:
                # declarative specs: sort-free scatter engine (the
                # iterative hot path — no XLA argsort per iteration)
                out_tree = _scatter_reduce_apply(
                    tree, mask, idx - range_start, range_size, out_cap,
                    sc, neutral)
                return out_tree, jnp.arange(out_cap) < range_size
            specs = _device_fold_specs(reduce_fn, td, leaves)
            words = [idx.astype(jnp.uint64)]
            words, tree_s, valid, _ = segmented.sort_by_key_words(
                words, tree, mask)
            words, tree_s, rep = segmented.reduce_runs(
                words, tree_s, valid, reduce_fn, specs)
            local_idx = words[0].astype(jnp.int64) - range_start
            pos = jnp.where(rep, local_idx, out_cap)
            pos = jnp.clip(pos, 0, out_cap)

            def scatter(leaf):
                base = jnp.zeros((out_cap + 1,) + leaf.shape[1:],
                                 leaf.dtype)
                return base.at[pos].set(leaf)[:out_cap]

            if neutral is None:
                out_tree = jax.tree.map(scatter, tree_s)
            else:
                def scatter_n(leaf, nval):
                    base = jnp.full((out_cap + 1,) + leaf.shape[1:],
                                    nval, leaf.dtype)
                    return base.at[pos].set(leaf)[:out_cap]
                out_tree = jax.tree.map(scatter_n, tree_s, neutral)
            return out_tree, jnp.arange(out_cap) < range_size

        return fusion.Segment(
            label="ReduceToIndex",
            token=("r2i_post_fused", (index_fn, reduce_fn, self.size),
                   out_cap, ntok),
            trace=trace, bound=bound, already_compact=True,
            sets_counts=local_sizes, dia_id=self.id)

    def compute_plan(self):
        from .. import fusion
        from ..functors import FieldReduce
        from ...core import host_radix
        plan = fusion.pull_plan(self.parents[0])
        bounds = self._bounds()
        # declarative FieldReduce specs unlock the sort-free scatter
        # engine, which beats the native host engine EVEN on the CPU
        # backend (no device->host demotion, no blocking column fetch,
        # stays in jax's async dispatch stream — load-bearing for
        # iterative loop replay, api/loop.py); everything else keeps
        # the host-radix preference on CPU (XLA's single-core sort is
        # the wrong engine there). Leaf dtypes are unknown until the
        # plan materializes, so this gate trusts the FieldReduce shape
        # alone: a spec the scatter engine later rejects (bool or
        # non-numeric sum/min/max leaf) still runs correctly through the
        # fused segment's sorted fallback, just on the slower engine
        if not plan.stitchable or (
                host_radix.eligible(self.context.mesh_exec)
                and not isinstance(self.reduce_fn, FieldReduce)):
            return fusion.wrap(self._compute_on(plan.finish(), bounds))
        W = self.context.num_workers
        token = (self.index_fn, self.reduce_fn, self.size)
        if W > 1:
            # exchange barrier: finish the upstream chain, shuffle,
            # start a fresh chain with the local scatter phase pending
            shards = self._exchange_by_index(plan.finish(), bounds,
                                             token)
            plan = fusion.FusionPlan(shards.mesh_exec, [shards])
        plan.append(self._fuse_segment(bounds))
        return plan

    def compute(self):
        plan = self.compute_plan()
        return plan.finish()

    def _compute_on(self, shards, bounds):
        """Pre-fusion compute body over pulled shards."""
        W = self.context.num_workers
        n = self.size
        if isinstance(shards, HostShards):
            return self._compute_host(shards, bounds)

        mex = shards.mesh_exec
        index_fn, reduce_fn = self.index_fn, self.reduce_fn
        token = (index_fn, reduce_fn, n)

        if W > 1:
            shards = self._exchange_by_index(shards, bounds, token)
            shards.validate_pending()    # optimistic-exchange heal point

        cap = shards.cap
        leaves, treedef = jax.tree.flatten(shards.tree)
        sc = _scatter_fold_specs(reduce_fn, treedef, leaves)
        if sc is None:
            # the sort-free scatter engine only takes declarative specs
            # over numeric leaves (and "first" anywhere); everything it
            # rejects — generic reduce functions AND FieldReduce specs
            # with unsupported leaf dtypes — still prefers the native
            # host engine on the CPU backend over XLA's single-core
            # sorted path
            host = _host_reduce_to_index(shards, index_fn, reduce_fn,
                                         bounds, self.neutral)
            if host is not None:
                return host

        # dense scatter-reduce into the local index range (pow2 cap —
        # shape-stable loop carries, see _fuse_segment)
        from ...common.config import round_up_pow2
        local_sizes = (bounds[1:] - bounds[:-1]).astype(np.int64)
        out_cap = max(1, round_up_pow2(int(local_sizes.max())))
        neutral = self.neutral
        specs = _device_fold_specs(reduce_fn, treedef, leaves)
        key = ("r2i_post", token, cap, out_cap, treedef,
               tuple((l.dtype, l.shape[2:]) for l in leaves))

        def build():
            def f(counts_dev, range_start, range_size, *ls):
                valid = jnp.arange(cap) < counts_dev[0, 0]
                tree = jax.tree.unflatten(treedef, [l[0] for l in ls])
                idx = jnp.asarray(index_fn(tree)).astype(jnp.int64)
                if sc is not None:
                    # sort-free scatter engine (same math as the fused
                    # segment — FUSE=0 runs produce identical results)
                    out_tree = _scatter_reduce_apply(
                        tree, valid, idx - range_start[0, 0],
                        range_size[0, 0], out_cap, sc, neutral)
                    out_leaves = jax.tree.leaves(out_tree)
                    return (range_size[0].astype(jnp.int32)[None],
                            *[l[None] for l in out_leaves])
                words = [idx.astype(jnp.uint64)]
                words, tree, valid, _ = segmented.sort_by_key_words(
                    words, tree, valid)
                words, tree, rep = segmented.reduce_runs(
                    words, tree, valid, reduce_fn, specs)
                local_idx = (words[0].astype(jnp.int64) - range_start[0, 0])
                pos = jnp.where(rep, local_idx, out_cap)
                pos = jnp.clip(pos, 0, out_cap)

                def scatter(leaf):
                    base = jnp.zeros((out_cap + 1,) + leaf.shape[1:],
                                     leaf.dtype)
                    return base.at[pos].set(leaf)[:out_cap]

                if neutral is None:
                    out_tree = jax.tree.map(scatter, tree)
                else:
                    def scatter_n(leaf, nval):
                        base = jnp.full((out_cap + 1,) + leaf.shape[1:],
                                        nval, leaf.dtype)
                        return base.at[pos].set(leaf)[:out_cap]
                    out_tree = jax.tree.map(scatter_n, tree, neutral)
                out_leaves = jax.tree.leaves(out_tree)
                return (range_size[0].astype(jnp.int32)[None],
                        *[l[None] for l in out_leaves])

            return mex.smap(f, 3 + len(leaves))

        fn = mex.cached(key, build)
        rs = mex.put_small(bounds[:W].astype(np.int64)[:, None])
        rsz = mex.put_small(local_sizes[:, None])
        out = fn(shards.counts_device(), rs, rsz, *leaves)
        tree = jax.tree.unflatten(treedef, list(out[1:]))
        return DeviceShards(mex, tree, local_sizes)

    def _compute_host(self, shards: HostShards, bounds):
        W = shards.num_workers
        index_fn, reduce_fn = self.index_fn, self.reduce_fn
        tables = [dict() for _ in range(W)]
        for items in shards.lists:
            for it in items:
                i = int(index_fn(it))
                w = int(np.searchsorted(bounds[1:], i, side="right"))
                t = tables[w]
                t[i] = reduce_fn(t[i], it) if i in t else it
        out = []
        for w in range(W):
            lo, hi = int(bounds[w]), int(bounds[w + 1])
            out.append([tables[w].get(i, self.neutral)
                        for i in range(lo, hi)])
        return HostShards(W, out)


def ReduceToIndex(dia: DIA, index_fn, reduce_fn, size, neutral=None) -> DIA:
    return DIA(ReduceToIndexNode(dia.context, dia._link(), index_fn,
                                 reduce_fn, size, neutral))
