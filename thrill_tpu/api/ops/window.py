"""Window / FlatWindow / DisjointWindow.

Reference: thrill/api/window.hpp:32 — overlapping k-windows fetch the
k-1 predecessor items from the previous worker via
FlowControlChannel::Predecessor (net/flow_control_channel.hpp:653).

Device path: the predecessor fetch is a **ppermute halo exchange** over
the mesh axis — each worker passes its last k-1 items to its successor,
the 1-D sharded-sequence pattern that generalizes to ring-style
sequence parallelism (this is where the long-context halo primitive
lives in this framework). Window functions are applied batched over
[n_windows, k] stacks. Workers with fewer than k-1 items (rare,
tiny inputs) fall back to the host path.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ...data.shards import DeviceShards, HostShards, compact_valid
from ...parallel.mesh import AXIS
from ..dia import DIA
from ..dia_base import DIABase


class WindowNode(DIABase):
    def __init__(self, ctx, link, k: int, fn: Optional[Callable],
                 device_fn: Optional[Callable], disjoint: bool) -> None:
        super().__init__(ctx, "DisjointWindow" if disjoint else "Window",
                         [link])
        self.k = int(k)
        self.fn = fn
        self.device_fn = device_fn
        self.disjoint = disjoint

    def compute(self):
        shards = self.parents[0].pull()
        k = self.k
        if isinstance(shards, DeviceShards) and self.device_fn is not None \
                and not self.disjoint \
                and bool(np.all(shards.counts[:-1] >= k - 1)):
            return self._compute_device(shards)
        if isinstance(shards, DeviceShards):
            shards = shards.to_host_shards("window-host-fn")
        return self._compute_host(shards)

    def _compute_host(self, shards: HostShards):
        k = self.k
        fn = self.fn
        flat = [it for l in shards.lists for it in l]
        if self.disjoint:
            wins = [flat[i:i + k] for i in range(0, len(flat) - k + 1, k)]
        else:
            wins = [flat[i:i + k] for i in range(len(flat) - k + 1)]
        out = [fn(i * (k if self.disjoint else 1), w)
               for i, w in enumerate(wins)]
        W = shards.num_workers
        bounds = [(w * len(out)) // W for w in range(W + 1)]
        return HostShards(W, [out[bounds[w]:bounds[w + 1]]
                              for w in range(W)])

    def _compute_device(self, shards: DeviceShards):
        mex = shards.mesh_exec
        W = mex.num_workers
        k = self.k
        cap = shards.cap
        offsets = np.concatenate([[0], np.cumsum(shards.counts)])[:-1]
        leaves, treedef = jax.tree.flatten(shards.tree)
        fn = self.device_fn
        key = ("window_dev", k, fn, cap, treedef,
               tuple((l.dtype, l.shape[2:]) for l in leaves))
        holder = {}

        def build():
            def f(counts_dev, off_dev, *ls):
                count = counts_dev[0, 0]
                off = off_dev[0, 0]
                tree = jax.tree.unflatten(treedef, [l[0] for l in ls])

                # halo: my last k-1 items -> successor (ppermute ring step)
                def halo_of(leaf):
                    idx = jnp.clip(count - (k - 1) + jnp.arange(k - 1), 0,
                                   cap - 1)
                    h = jnp.take(leaf, idx, axis=0)
                    perm = [(i, i + 1) for i in range(W - 1)]
                    return lax.ppermute(h, AXIS, perm) if W > 1 else \
                        jnp.zeros_like(h)

                halo = jax.tree.map(halo_of, tree)
                ext = jax.tree.map(
                    lambda h, x: jnp.concatenate([h, x], axis=0), halo, tree)
                # window ending at local item j = ext[j : j + k]
                widx_mat = jnp.arange(cap)[:, None] + jnp.arange(k)[None, :]
                windows = jax.tree.map(
                    lambda e: jnp.take(e, widx_mat, axis=0), ext)
                out = fn(windows)            # batched [cap, ...]
                g_end = off + jnp.arange(cap, dtype=jnp.int64)
                valid = (jnp.arange(cap) < count) & (g_end >= k - 1)
                out, cnt = compact_valid(out, valid)
                out_leaves, out_td = jax.tree.flatten(out)
                holder["treedef"] = out_td
                return (cnt[None, None].astype(jnp.int32),
                        *[l[None] for l in out_leaves])

            return mex.smap(f, 2 + len(leaves)), holder

        f, h = mex.cached(key, build)
        out = f(shards.counts_device(),
                mex.put(offsets.astype(np.int64)[:, None]), *leaves)
        tree = jax.tree.unflatten(h["treedef"], list(out[1:]))
        return DeviceShards(mex, tree, out[0])


class FlatWindowNode(DIABase):
    """fn(index, window) -> iterable of outputs (host path)."""

    def __init__(self, ctx, link, k: int, fn: Callable) -> None:
        super().__init__(ctx, "FlatWindow", [link])
        self.k = int(k)
        self.fn = fn

    def compute(self):
        shards = self.parents[0].pull()
        if isinstance(shards, DeviceShards):
            shards = shards.to_host_shards("flatwindow")
        flat = [it for l in shards.lists for it in l]
        out = []
        for i in range(len(flat) - self.k + 1):
            out.extend(self.fn(i, flat[i:i + self.k]))
        W = shards.num_workers
        bounds = [(w * len(out)) // W for w in range(W + 1)]
        return HostShards(W, [out[bounds[w]:bounds[w + 1]]
                              for w in range(W)])


def Window(dia: DIA, k: int, fn, device_fn=None, disjoint=False) -> DIA:
    return DIA(WindowNode(dia.context, dia._link(), k, fn, device_fn,
                          disjoint))


def FlatWindow(dia: DIA, k: int, fn) -> DIA:
    return DIA(FlatWindowNode(dia.context, dia._link(), k, fn))
