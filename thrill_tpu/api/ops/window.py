"""Window / FlatWindow / DisjointWindow.

Reference: thrill/api/window.hpp:32 — overlapping k-windows fetch the
k-1 predecessor items from the previous worker via
FlowControlChannel::Predecessor (net/flow_control_channel.hpp:653).

Device path: the predecessor fetch is a **ppermute halo exchange** over
the mesh axis — each worker passes its last k-1 items to its successor,
the 1-D sharded-sequence pattern that generalizes to ring-style
sequence parallelism (this is where the long-context halo primitive
lives in this framework). Window functions are applied batched over
[n_windows, k] stacks; DisjointWindow is the same machinery with a
start-alignment mask, and FlatWindow uses the FlatMap contract (a
static output factor + validity mask). Workers with fewer than k-1
items (rare, tiny inputs) fall back to the host path.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ...data.shards import DeviceShards, HostShards, compact_valid
from ...parallel.mesh import AXIS
from ..dia import DIA
from ..dia_base import DIABase
from ...common.partition import dense_range_bounds


def _device_windows(tree, cap, count, off, k, W):
    """Traced helper: halo exchange + batched [cap, k, ...] windows.

    Window j ends at local item j (covers global positions
    off+j-(k-1) .. off+j); the k-1 halo items come from the predecessor
    worker via a ppermute ring step. Returns (windows_tree, ends_valid,
    g_start) where ends_valid marks windows whose full extent exists.
    """
    def halo_of(leaf):
        idx = jnp.clip(count - (k - 1) + jnp.arange(k - 1), 0, cap - 1)
        h = jnp.take(leaf, idx, axis=0)
        perm = [(i, i + 1) for i in range(W - 1)]
        return lax.ppermute(h, AXIS, perm) if W > 1 else \
            jnp.zeros_like(h)

    halo = jax.tree.map(halo_of, tree)
    ext = jax.tree.map(lambda h, x: jnp.concatenate([h, x], axis=0),
                       halo, tree)
    widx_mat = jnp.arange(cap)[:, None] + jnp.arange(k)[None, :]
    windows = jax.tree.map(lambda e: jnp.take(e, widx_mat, axis=0), ext)
    g_end = off + jnp.arange(cap, dtype=jnp.int64)
    ends_valid = (jnp.arange(cap) < count) & (g_end >= k - 1)
    g_start = g_end - (k - 1)
    return windows, ends_valid, g_start



def _windowed_device_program(shards: DeviceShards, k: int, cache_tag,
                             make_output):
    """Shared driver for all windowed device ops: one jitted program
    building halo windows, applying ``make_output(windows, ends_valid,
    g_start) -> (out_tree, keep_mask)`` and compacting the kept rows."""
    mex = shards.mesh_exec
    W = mex.num_workers
    cap = shards.cap
    offsets = np.concatenate([[0], np.cumsum(shards.counts)])[:-1]
    leaves, treedef = jax.tree.flatten(shards.tree)
    key = ("windowed",) + tuple(cache_tag) + (
        k, cap, treedef, tuple((l.dtype, l.shape[2:]) for l in leaves))
    holder = {}

    def build():
        def f(counts_dev, off_dev, *ls):
            count = counts_dev[0, 0]
            off = off_dev[0, 0]
            tree = jax.tree.unflatten(treedef, [l[0] for l in ls])
            windows, valid, g_start = _device_windows(
                tree, cap, count, off, k, W)
            out_tree, keep = make_output(windows, valid, g_start)
            out, cnt = compact_valid(out_tree, keep)
            out_leaves, out_td = jax.tree.flatten(out)
            holder["treedef"] = out_td
            return (cnt[None, None].astype(jnp.int32),
                    *[l[None] for l in out_leaves])

        return mex.smap(f, 2 + len(leaves)), holder

    f, h = mex.cached(key, build)
    out = f(shards.counts_device(),
            mex.put_small(offsets.astype(np.int64)[:, None]), *leaves)
    tree = jax.tree.unflatten(h["treedef"], list(out[1:]))
    return DeviceShards(mex, tree, out[0])


def _fused_window_plan(node):
    """Shared Window/FlatWindow fusion gate: the halo eligibility check
    (every non-last worker holds at least k-1 items) needs host counts,
    so the op fuses only when the pending chain provably preserves the
    source's KNOWN counts; anything else finishes the chain and takes
    the per-op path."""
    from .. import fusion
    plan = fusion.pull_plan(node.parents[0])
    if plan.stitchable and plan.counts_preserved() \
            and plan.known_counts is not None \
            and bool(np.all(plan.known_counts[:-1] >= node.k - 1)):
        plan.append(node._fuse_segment())
        return plan
    return fusion.wrap(node._compute_on(plan.finish()))


class WindowNode(DIABase):
    def __init__(self, ctx, link, k: int, fn: Optional[Callable],
                 device_fn: Optional[Callable], disjoint: bool,
                 partial_fn: Optional[Callable] = None) -> None:
        super().__init__(ctx, "DisjointWindow" if disjoint else "Window",
                         [link])
        self.k = int(k)
        self.fn = fn
        self.device_fn = device_fn
        self.disjoint = disjoint
        # reference: DisjointWindow delivers the trailing (< k) block
        # to a separate partial_window_function (api/window.hpp:389);
        # its dynamic length keeps it on the host path
        if partial_fn is not None and not disjoint:
            raise ValueError(
                "partial_fn only applies to DisjointWindow (the sliding "
                "Window has no trailing partial block)")
        self.partial_fn = partial_fn

    def _fuse_segment(self):
        from .. import fusion
        k = self.k
        disjoint = self.disjoint
        fn = self.device_fn
        W = self.context.num_workers

        def trace(fctx, tree, mask, _bound):
            cap = mask.shape[0]
            count = jnp.sum(mask.astype(jnp.int32))
            off = fctx.exclusive_offset(mask)
            windows, valid, g_start = _device_windows(
                tree, cap, count, off, k, W)
            if disjoint:
                valid = valid & (g_start % k == 0)
            return fn(windows), valid

        return fusion.Segment(label=self.label,
                              token=("window_fused", fn, disjoint, k),
                              trace=trace, dia_id=self.id)

    def compute_plan(self):
        if self.device_fn is None or self.partial_fn is not None:
            return None
        return _fused_window_plan(self)

    def compute(self):
        plan = self.compute_plan()
        if plan is not None:
            return plan.finish()
        return self._compute_on(self.parents[0].pull())

    def _compute_on(self, shards):
        k = self.k
        if isinstance(shards, DeviceShards) and self.device_fn is not None \
                and self.partial_fn is None \
                and bool(np.all(shards.counts[:-1] >= k - 1)):
            return self._compute_device(shards)
        if self.fn is None:
            raise ValueError(
                f"{self.label} fell back to the host path (host storage, "
                f"a worker with fewer than k-1 items, or partial_fn — "
                f"which is host-only) but no host fn was given — pass fn "
                f"alongside device_fn")
        if isinstance(shards, DeviceShards):
            shards = shards.to_host_shards("window-host-fn")
        return self._compute_host(shards)

    def _compute_host(self, shards: HostShards):
        k = self.k
        fn = self.fn
        from ...data import multiplexer
        mex = self.context.mesh_exec
        shards = multiplexer.ensure_replicated(mex, shards, "window-host")
        flat = [it for l in shards.lists for it in l]
        if self.disjoint:
            wins = [flat[i:i + k] for i in range(0, len(flat) - k + 1, k)]
        else:
            wins = [flat[i:i + k] for i in range(len(flat) - k + 1)]
        out = [fn(i * (k if self.disjoint else 1), w)
               for i, w in enumerate(wins)]
        if self.disjoint and self.partial_fn is not None \
                and len(flat) % k:
            rest = flat[len(flat) - len(flat) % k:]
            out.append(self.partial_fn(len(flat) - len(rest), rest))
        W = shards.num_workers
        bounds = dense_range_bounds(len(out), W).tolist()
        return multiplexer.localize(
            mex, HostShards(W, [out[bounds[w]:bounds[w + 1]]
                                for w in range(W)]))

    def _compute_device(self, shards: DeviceShards):
        k = self.k
        disjoint = self.disjoint
        fn = self.device_fn

        def make_output(windows, valid, g_start):
            if disjoint:
                # keep only windows aligned to a k boundary
                valid = valid & (g_start % k == 0)
            return fn(windows), valid        # batched [cap, ...]

        return _windowed_device_program(
            shards, k, ("window_dev", fn, disjoint), make_output)


class FlatWindowNode(DIABase):
    """fn(index, window) -> iterable of outputs.

    Device path (``device_fn`` + ``factor``): like FlatMap's device
    contract — ``device_fn(windows)`` receives the batched
    [cap, k, ...] window tree and returns ``(outputs, mask)`` where
    outputs' leaves are [cap, factor, ...] and mask is [cap, factor]
    bool (which of each window's factor slots are real). Windows whose
    extent is incomplete are masked automatically.
    """

    def __init__(self, ctx, link, k: int, fn: Callable,
                 device_fn: Optional[Callable] = None,
                 factor: int = 0) -> None:
        super().__init__(ctx, "FlatWindow", [link])
        self.k = int(k)
        self.fn = fn
        self.device_fn = device_fn
        self.factor = int(factor)
        if device_fn is not None and self.factor <= 0:
            raise ValueError(
                "FlatWindow device_fn requires factor > 0 (static "
                "outputs per window)")
        if fn is None and device_fn is None:
            raise ValueError("FlatWindow needs fn and/or device_fn")

    def _fuse_segment(self):
        from .. import fusion
        k = self.k
        factor = self.factor
        fn = self.device_fn
        W = self.context.num_workers

        def trace(fctx, tree, mask, _bound):
            cap = mask.shape[0]
            count = jnp.sum(mask.astype(jnp.int32))
            off = fctx.exclusive_offset(mask)
            windows, valid, g_start = _device_windows(
                tree, cap, count, off, k, W)
            out, fmask = fn(windows)         # [cap, factor, ...]
            flat_tree = jax.tree.map(
                lambda l: l.reshape((cap * factor,) + l.shape[2:]), out)
            return flat_tree, (valid[:, None] & fmask).reshape(-1)

        return fusion.Segment(label="FlatWindow",
                              token=("flatwindow_fused", fn, factor, k),
                              trace=trace, dia_id=self.id)

    def compute_plan(self):
        if self.device_fn is None or self.factor <= 0:
            return None
        return _fused_window_plan(self)

    def compute(self):
        plan = self.compute_plan()
        if plan is not None:
            return plan.finish()
        return self._compute_on(self.parents[0].pull())

    def _compute_on(self, shards):
        k = self.k
        if isinstance(shards, DeviceShards) and self.device_fn is not None \
                and self.factor > 0 \
                and bool(np.all(shards.counts[:-1] >= k - 1)):
            return self._compute_device(shards)
        if self.fn is None:
            raise ValueError(
                "FlatWindow fell back to the host path (host storage "
                "or a worker with fewer than k-1 items) but no host "
                "fn was given — pass fn alongside device_fn")
        if isinstance(shards, DeviceShards):
            shards = shards.to_host_shards("flatwindow")
        from ...data import multiplexer
        mex = self.context.mesh_exec
        shards = multiplexer.ensure_replicated(mex, shards,
                                               "flatwindow-host")
        flat = [it for l in shards.lists for it in l]
        out = []
        for i in range(len(flat) - self.k + 1):
            out.extend(self.fn(i, flat[i:i + self.k]))
        W = shards.num_workers
        bounds = dense_range_bounds(len(out), W).tolist()
        return multiplexer.localize(
            mex, HostShards(W, [out[bounds[w]:bounds[w + 1]]
                                for w in range(W)]))

    def _compute_device(self, shards: DeviceShards):
        k = self.k
        factor = self.factor
        fn = self.device_fn

        def make_output(windows, valid, g_start):
            out, mask = fn(windows)          # [cap, factor, ...], mask
            cap = valid.shape[0]
            flat_tree = jax.tree.map(
                lambda l: l.reshape((cap * factor,) + l.shape[2:]), out)
            return flat_tree, (valid[:, None] & mask).reshape(-1)

        return _windowed_device_program(
            shards, k, ("flatwindow_dev", fn, factor), make_output)


def Window(dia: DIA, k: int, fn, device_fn=None, disjoint=False,
           partial_fn=None) -> DIA:
    return DIA(WindowNode(dia.context, dia._link(), k, fn, device_fn,
                          disjoint, partial_fn=partial_fn))


def FlatWindow(dia: DIA, k: int, fn, device_fn=None, factor: int = 0
               ) -> DIA:
    return DIA(FlatWindowNode(dia.context, dia._link(), k, fn,
                              device_fn=device_fn, factor=factor))
