"""HyperLogLog distinct counting.

Reference: thrill/api/hyperloglog.hpp:27 + core/hyperloglog.{hpp,cpp}
(register arrays, sparse/dense encodings, AllReduce merge). Device
path: hash to uint64, scatter-max into 2^p registers per worker, pmax
across the mesh, classic HLL estimate with linear-counting small-range
correction on the host.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ...common import hashing
from ...core import keys as keymod
from ...data.shards import DeviceShards, HostShards
from ...parallel.mesh import AXIS
from ..dia import DIA


def _alpha(m: int) -> float:
    if m == 16:
        return 0.673
    if m == 32:
        return 0.697
    if m == 64:
        return 0.709
    return 0.7213 / (1 + 1.079 / m)


def _estimate(registers: np.ndarray, p: int) -> float:
    m = 1 << p
    inv = np.sum(np.exp2(-registers.astype(np.float64)))
    raw = _alpha(m) * m * m / inv
    if raw <= 2.5 * m:
        zeros = int(np.sum(registers == 0))
        if zeros:
            return m * np.log(m / zeros)
    two32 = float(1 << 32)
    if raw > two32 / 30.0:
        return -two32 * np.log(1.0 - raw / two32)
    return raw


def HyperLogLog(dia: DIA, precision: int = 14) -> float:
    p = int(precision)
    m = 1 << p
    shards = dia._link().pull()
    if isinstance(shards, HostShards):
        regs = np.zeros(m, dtype=np.int32)
        for items in shards.lists:
            for it in items:
                h = hashing.stable_host_hash(_hashable(it))
                idx = h >> (64 - p)
                rest = (h << p) & 0xFFFFFFFFFFFFFFFF
                # standard register range is [1, 64-p+1]: an all-zero
                # suffix yields rho = 64-p+1 (ADVICE r1)
                rho = 64 - p + 1 if rest == 0 else _clz64(rest) + 1
                regs[idx] = max(regs[idx], min(rho, 64 - p + 1))
        from ...data import multiplexer
        mex = dia.context.mesh_exec
        if multiplexer.multiprocess(mex):
            # the register sketch merges by elementwise max — ship the
            # m-register array, not the items (reference:
            # core/hyperloglog.hpp merge)
            regs = multiplexer.net_fold(mex, regs, np.maximum)
        return _estimate(regs, p)

    mex = shards.mesh_exec
    cap = shards.cap
    leaves, treedef = jax.tree.flatten(shards.tree)
    key = ("hll", p, cap, treedef,
           tuple((l.dtype, l.shape[2:]) for l in leaves))

    def build():
        def f(counts_dev, *ls):
            valid = jnp.arange(cap) < counts_dev[0, 0]
            tree = jax.tree.unflatten(treedef, [l[0] for l in ls])
            words = keymod.encode_key_words(tree)
            h = hashing.hash_key_words(words)
            idx = (h >> jnp.uint64(64 - p)).astype(jnp.int32)
            rest = h << jnp.uint64(p)
            # register range [1, 64-p+1]; rest==0 -> 64-p+1 (ADVICE r1)
            rho = jnp.where(rest == 0, 64 - p + 1, _clz_device(rest) + 1)
            rho = jnp.minimum(rho, 64 - p + 1).astype(jnp.int32)
            rho = jnp.where(valid, rho, 0)
            regs = jnp.zeros(m, jnp.int32).at[idx].max(rho)
            return lax.pmax(regs, AXIS)

        from jax.sharding import PartitionSpec as P
        return mex.smap(f, 1 + len(leaves), out_specs=P())

    fn = mex.cached(key, build)
    regs = mex.fetch(fn(shards.counts_device(), *leaves))
    return _estimate(regs, p)


def _clz_device(x: jnp.ndarray) -> jnp.ndarray:
    """Count leading zeros of nonzero uint64 (branch-free doubling)."""
    n = jnp.zeros(x.shape, jnp.int32)
    for shift in (32, 16, 8, 4, 2, 1):
        hi = x >> jnp.uint64(64 - shift)
        move = hi == 0
        n = n + jnp.where(move, shift, 0)
        x = jnp.where(move, x << jnp.uint64(shift), x)
    return n


def _clz64(v: int) -> int:
    n = 0
    for shift in (32, 16, 8, 4, 2, 1):
        if (v >> (64 - shift)) == 0:
            n += shift
            v = (v << shift) & 0xFFFFFFFFFFFFFFFF
    return n


def _hashable(it):
    if isinstance(it, np.ndarray):
        return tuple(it.tolist())
    if isinstance(it, np.generic):
        return it.item()
    return it
