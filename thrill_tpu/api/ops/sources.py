"""Source operations: Generate, Distribute/EqualToDIA, ConcatToDIA.

Reference: thrill/api/generate.hpp:37 (index range -> item lambda, local
range split), equal_to_dia.hpp:30, concat_to_dia.hpp:30,
distribute.hpp:33.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...common.partition import dense_range_bounds
from ...data.shards import DeviceShards, HostShards
from ..dia import DIA
from ..dia_base import DIABase
from ..stack import _broadcast_outputs


class GenerateNode(DIABase):
    """size indices [0, size) split evenly; fn maps index -> item."""

    def __init__(self, ctx, size: int, fn: Optional[Callable],
                 storage: str) -> None:
        super().__init__(ctx, "Generate")
        self.size = int(size)
        self.fn = fn
        self.storage = storage

    def compute(self):
        W = self.context.num_workers
        n = self.size
        bounds = dense_range_bounds(n, W).tolist()
        if self.storage == "host":
            fn = self.fn or (lambda i: i)
            # multi-controller: materialize only this process's workers
            # (the host-storage invariant, data/multiplexer.py)
            from ...data.multiplexer import local_worker_set
            local = local_worker_set(self.context.mesh_exec)
            return HostShards(
                W, [[fn(i) for i in range(bounds[w], bounds[w + 1])]
                    if w in local else [] for w in range(W)])
        mex = self.context.mesh_exec
        counts = np.array([bounds[w + 1] - bounds[w] for w in range(W)],
                          dtype=np.int64)
        cap = max(1, 1 << (int(counts.max()) - 1).bit_length()) \
            if counts.max() > 0 else 1
        starts = mex.put_small(np.array(bounds[:W], dtype=np.int64)[:, None])
        fn = self.fn
        holder = {}
        key = ("generate", n, cap, fn)

        def build():
            def f(start):
                idx = start[0, 0] + jnp.arange(cap, dtype=jnp.int64)
                tree = idx if fn is None else _broadcast_outputs(fn(idx), cap)
                leaves, treedef = jax.tree.flatten(tree)
                holder["treedef"] = treedef
                return tuple(l[None] for l in leaves)
            return mex.smap(f, 1), holder

        f, h = mex.cached(key, build)
        out = f(starts)
        tree = jax.tree.unflatten(h["treedef"], list(out))
        return DeviceShards(mex, tree, counts)


class DistributeNode(DIABase):
    """Global collection split evenly across workers, order preserved."""

    def __init__(self, ctx, items, storage: Optional[str]) -> None:
        super().__init__(ctx, "Distribute")
        # materialize iterators/generators up front: storage inference
        # probes the first element, which would otherwise be consumed
        if not _is_columnar(items) and not isinstance(items, (list, tuple)):
            items = list(items)
        self.items = items
        self.storage = storage or _infer_storage(ctx, items)

    def compute(self):
        W = self.context.num_workers
        if self.storage == "host":
            items = list(self.items) if not isinstance(self.items, list) \
                else self.items
            n = len(items)
            bounds = dense_range_bounds(n, W).tolist()
            # Distribute expects identical input on every controller
            # (see RunDistributed docstring); each keeps its own slice
            from ...data.multiplexer import local_worker_set
            local = local_worker_set(self.context.mesh_exec)
            return HostShards(W, [items[bounds[w]:bounds[w + 1]]
                                  if w in local else []
                                  for w in range(W)])
        tree = _columnarize(self.items)
        return DeviceShards.from_global_numpy(self.context.mesh_exec, tree)


class ConcatToDIANode(DIABase):
    """Per-worker lists placed exactly on their worker."""

    def __init__(self, ctx, per_worker, storage: Optional[str]) -> None:
        super().__init__(ctx, "ConcatToDIA")
        self.per_worker = per_worker
        self.storage = storage or "host"

    def compute(self):
        W = self.context.num_workers
        lists = [list(l) for l in self.per_worker]
        if len(lists) < W:
            lists += [[] for _ in range(W - len(lists))]
        elif len(lists) > W:
            # fold extras into the last worker, preserving order
            extra = [it for l in lists[W:] for it in l]
            lists = lists[:W - 1] + [lists[W - 1] + extra] if W > 0 else lists
            lists = lists[:W]
        from ...data import multiplexer
        shards = multiplexer.localize(self.context.mesh_exec,
                                      HostShards(W, lists))
        if self.storage == "device":
            return shards.to_device(self.context.mesh_exec)
        return shards


def _is_columnar(items) -> bool:
    """Columnar input: a global array, or a dict pytree of equal-length
    arrays (struct-of-arrays). Lists/tuples are item *sequences*."""
    if isinstance(items, np.ndarray) or hasattr(items, "dtype"):
        return True
    if isinstance(items, dict):
        leaves = jax.tree.leaves(items)
        return bool(leaves) and all(
            isinstance(l, np.ndarray) or hasattr(l, "dtype")
            for l in leaves)
    return False


def _infer_storage(ctx, items) -> str:
    if _is_columnar(items):
        return "device"
    probe = None
    for it in items:
        probe = it
        break
    if probe is None:
        return ctx.config.default_storage
    leaves = jax.tree.leaves(probe)
    if all(isinstance(l, (int, float, bool, np.generic, np.ndarray))
           for l in leaves) and leaves:
        return "device"
    return "host"


def _columnarize(items):
    """Columnar pytree passthrough, or list of item pytrees -> columns."""
    if _is_columnar(items):
        # device arrays pass through UNFETCHED — from_global_numpy
        # splits them on device (np.asarray here would be a blocking
        # device->host round trip per leaf)
        return jax.tree.map(
            lambda l: l if isinstance(l, jax.Array) else np.asarray(l),
            items)
    items = list(items)
    if not items:
        raise ValueError("cannot infer schema of empty device DIA; "
                         "use storage='host'")
    treedef = jax.tree.structure(items[0])
    nleaves = treedef.num_leaves
    cols = [np.asarray([jax.tree.leaves(it)[i] for it in items])
            for i in range(nleaves)]
    return jax.tree.unflatten(treedef, cols)


def Generate(ctx, size, fn=None, storage=None) -> DIA:
    storage = storage or "device"
    return DIA(GenerateNode(ctx, size, fn, storage))


def Distribute(ctx, items, storage=None) -> DIA:
    return DIA(DistributeNode(ctx, items, storage))


def ConcatToDIA(ctx, per_worker, storage=None) -> DIA:
    return DIA(ConcatToDIANode(ctx, per_worker, storage))
