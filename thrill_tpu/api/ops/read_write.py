"""File sources and sinks: ReadLines, ReadBinary, WriteLines*, WriteBinary.

Reference: thrill/api/read_lines.hpp:41 (byte-range split via size
prefix sums, scan to next newline :181-199, whole-file granularity for
compressed inputs), read_binary.hpp:45 (fixed-size records mapped to
blocks), write_lines.hpp:33 / write_lines_one.hpp:31 / write_binary.hpp:36
(per-worker chunked files with pattern substitution, or one file).
"""

from __future__ import annotations

import os
from typing import List

import numpy as np

from ...data.shards import DeviceShards, HostShards
from ...vfs import file_io
from ..dia import DIA
from ..dia_base import DIABase


class ReadLinesNode(DIABase):
    def __init__(self, ctx, path_or_glob: str) -> None:
        super().__init__(ctx, "ReadLines")
        self.pattern = path_or_glob

    def compute(self):
        W = self.context.num_workers
        fl = file_io.Glob(self.pattern)
        if len(fl) == 0:
            raise FileNotFoundError(f"ReadLines: no files match "
                                    f"{self.pattern!r}")
        if fl.contains_compressed:
            return self._compute_whole_files(fl)
        return self._compute_ranges(fl)

    def _compute_whole_files(self, fl: file_io.FileList):
        """Compressed: whole-file granularity round-robin by size psum."""
        W = self.context.num_workers
        total = fl.total_size
        from ...data.multiplexer import local_worker_set
        local = local_worker_set(self.context.mesh_exec)
        lists: List[List[str]] = [[] for _ in range(W)]
        for fi in fl.files:
            # assign file to the worker owning its start offset
            w = min(W - 1, (fi.size_ex_psum * W) // max(total, 1))
            if w not in local:
                continue          # another controller reads this file
            with file_io.OpenReadStream(fi.path) as f:
                data = f.read()
            lists[w].extend(data.decode("utf-8").splitlines())
        return HostShards(W, lists)

    def _compute_ranges(self, fl: file_io.FileList):
        """Uncompressed: split the global byte range evenly; each worker
        starts after the first newline past its range start (the item
        owned by the worker containing its START). Multi-controller:
        each process reads ONLY its own workers' byte ranges — the I/O
        scales out with processes (reference: read_lines.hpp:41 splits
        by worker the same way)."""
        W = self.context.num_workers
        total = fl.total_size
        from ...data.multiplexer import local_worker_set
        local = local_worker_set(self.context.mesh_exec)
        bounds = [(w * total) // W for w in range(W + 1)]
        lists: List[List[str]] = []
        for w in range(W):
            if w not in local:
                lists.append([])
                continue
            lo, hi = bounds[w], bounds[w + 1]
            lists.append(_read_lines_range(fl, lo, hi))
        return HostShards(W, lists)


def _read_lines_range(fl: file_io.FileList, lo: int, hi: int) -> List[str]:
    """All lines whose first byte lies in [lo, hi) of the global stream."""
    out: List[str] = []
    if lo >= hi:
        return out
    for fi in fl.files:
        f_lo, f_hi = fi.size_ex_psum, fi.size_ex_psum + fi.size
        if f_hi <= lo or f_lo >= hi:
            continue
        start = max(lo, f_lo) - f_lo
        end = min(hi, f_hi) - f_lo
        with file_io.OpenReadStream(fi.path) as f:
            if start > 0:
                f.seek(start - 1)
                prev = f.read(1)
                if prev == b"\n":
                    chunk_start = start
                else:
                    # mid-line: scan forward to the next newline
                    chunk_start = None
                    pos = start
                    while True:
                        b = f.read(1 << 16)
                        if not b:
                            chunk_start = f_hi - f_lo
                            break
                        nl = b.find(b"\n")
                        if nl >= 0:
                            chunk_start = pos + nl + 1
                            break
                        pos += len(b)
            else:
                chunk_start = 0
            if chunk_start >= end:
                continue
            f.seek(chunk_start)
            data = f.read(end - chunk_start)
            # extend to finish the last line (it starts in-range)
            if not data.endswith(b"\n"):
                while True:
                    b = f.read(1 << 16)
                    if not b:
                        break
                    nl = b.find(b"\n")
                    if nl >= 0:
                        data += b[:nl + 1]
                        break
                    data += b
            # str.splitlines is already a C-level loop and handles CRLF
            # etc.; the native scanner (data/block_pool.scan_line_offsets)
            # is reserved for the raw-bytes -> device packing path where
            # no Python string objects are materialized
            out.extend(data.decode("utf-8").splitlines())
    return out


class ReadBinaryNode(DIABase):
    """Fixed-size records -> device columnar storage directly."""

    def __init__(self, ctx, path_or_glob: str, dtype, record_shape) -> None:
        super().__init__(ctx, "ReadBinary")
        self.pattern = path_or_glob
        self.dtype = np.dtype(dtype)
        self.record_shape = tuple(record_shape)

    def compute(self):
        W = self.context.num_workers
        fl = file_io.Glob(self.pattern)
        rec_items = int(np.prod(self.record_shape)) if self.record_shape \
            else 1
        rec_bytes = rec_items * self.dtype.itemsize
        total_recs = fl.total_size // rec_bytes
        bounds = [(w * total_recs) // W for w in range(W + 1)]
        # multi-controller: read only this process's workers' ranges;
        # counts derive from bounds, so no agreement round is needed
        from ...data.multiplexer import local_worker_set
        local = local_worker_set(self.context.mesh_exec)
        empty = np.empty((0,) + self.record_shape, dtype=self.dtype)
        per_worker = []
        for w in range(W):
            if w not in local:
                per_worker.append(empty)
                continue
            lo, hi = bounds[w], bounds[w + 1]
            arr = _read_records(fl, lo, hi, rec_bytes, self.dtype)
            per_worker.append(arr.reshape((-1,) + self.record_shape))
        counts = np.array([bounds[w + 1] - bounds[w] for w in range(W)],
                          dtype=np.int64)
        return DeviceShards.from_worker_arrays(
            self.context.mesh_exec, per_worker, counts=counts)


def _read_records(fl, lo_rec, hi_rec, rec_bytes, dtype) -> np.ndarray:
    lo, hi = lo_rec * rec_bytes, hi_rec * rec_bytes
    chunks = []
    for fi in fl.files:
        f_lo, f_hi = fi.size_ex_psum, fi.size_ex_psum + fi.size
        if f_hi <= lo or f_lo >= hi:
            continue
        start = max(lo, f_lo) - f_lo
        end = min(hi, f_hi) - f_lo
        with file_io.OpenReadStream(fi.path, offset=start) as f:
            chunks.append(f.read(end - start))
    buf = b"".join(chunks)
    return np.frombuffer(buf, dtype=dtype)


# ----------------------------------------------------------------------
# sinks
# ----------------------------------------------------------------------

def _worker_path(pattern: str, w: int) -> str:
    if "$$$$$" in pattern:        # reference's wildcard (api/dia.hpp:813)
        return pattern.replace("$$$$$", f"{w:05d}")
    if "{}" in pattern:
        return pattern.format(w)
    base, ext = os.path.splitext(pattern)
    return f"{base}-{w:05d}{ext}"


def _host_lists(dia) -> HostShards:
    shards = dia._link().pull()
    if isinstance(shards, DeviceShards):
        shards = shards.to_host_shards("writelines")
    return shards


def _local_worker_ids(dia):
    mex = dia.context.mesh_exec
    from ...data import multiplexer
    if multiplexer.multiprocess(mex):
        return set(mex.local_workers)
    return set(range(mex.num_workers))


def WriteLines(dia, path_pattern: str) -> None:
    """One text file per worker (reference: api/write_lines.hpp:33).
    Multi-controller: each process writes only its own workers' files."""
    shards = _host_lists(dia)
    owned = _local_worker_ids(dia)
    for w, items in enumerate(shards.lists):
        if w not in owned:
            continue
        with file_io.OpenWriteStream(_worker_path(path_pattern, w)) as f:
            for it in items:
                f.write(str(it).encode("utf-8"))
                f.write(b"\n")


def WriteLinesOne(dia, path: str) -> None:
    """Single coordinated output file (reference: write_lines_one.hpp:31).
    Multi-controller: items gather to process 0, which writes the file
    alone (worker-rank order is preserved)."""
    shards = _host_lists(dia)
    mex = dia.context.mesh_exec
    from ...data import multiplexer
    if multiplexer.multiprocess(mex):
        items = multiplexer.all_items(mex, shards)
        if mex.process_index != 0:
            return
        with file_io.OpenWriteStream(path) as f:
            for it in items:
                f.write(str(it).encode("utf-8"))
                f.write(b"\n")
        return
    with file_io.OpenWriteStream(path) as f:
        for items in shards.lists:
            for it in items:
                f.write(str(it).encode("utf-8"))
                f.write(b"\n")


def WriteBinary(dia, path_pattern: str) -> None:
    """Raw fixed-size records, one file per worker
    (reference: api/write_binary.hpp:36)."""
    shards = dia._link().pull()
    owned = _local_worker_ids(dia)
    if isinstance(shards, DeviceShards):
        per_worker = shards.to_worker_arrays(local_only=True)
        import jax
        for w, tree in enumerate(per_worker):
            if tree is None or w not in owned:
                continue
            leaves = jax.tree.leaves(tree)
            with file_io.OpenWriteStream(_worker_path(path_pattern, w)) as f:
                for leaf in leaves:
                    f.write(np.ascontiguousarray(leaf).tobytes())
        return
    for w, items in enumerate(shards.lists):
        if w not in owned:
            continue
        with file_io.OpenWriteStream(_worker_path(path_pattern, w)) as f:
            for it in items:
                f.write(np.asarray(it).tobytes())


def ReadLines(ctx, path_or_glob: str) -> DIA:
    return DIA(ReadLinesNode(ctx, path_or_glob))


def ReadBinary(ctx, path_or_glob: str, dtype, record_shape=()) -> DIA:
    return DIA(ReadBinaryNode(ctx, path_or_glob, dtype, record_shape))
