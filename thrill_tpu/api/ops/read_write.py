"""File sources and sinks: ReadLines, ReadBinary, WriteLines*, WriteBinary.

Reference: thrill/api/read_lines.hpp:41 (byte-range split via size
prefix sums, scan to next newline :181-199, whole-file granularity for
compressed inputs), read_binary.hpp:45 (fixed-size records mapped to
blocks), write_lines.hpp:33 / write_lines_one.hpp:31 / write_binary.hpp:36
(per-worker chunked files with pattern substitution, or one file).
"""

from __future__ import annotations

import os
from typing import List

import numpy as np

from ...data.shards import DeviceShards, HostShards
from ...vfs import file_io
from ..dia import DIA
from ..dia_base import DIABase
from ...common.partition import dense_range_bounds


class ReadLinesNode(DIABase):
    def __init__(self, ctx, path_or_glob: str) -> None:
        super().__init__(ctx, "ReadLines")
        self.pattern = path_or_glob

    def compute(self):
        W = self.context.num_workers
        fl = file_io.Glob(self.pattern)
        if len(fl) == 0:
            raise FileNotFoundError(f"ReadLines: no files match "
                                    f"{self.pattern!r}")
        if fl.contains_compressed:
            return self._compute_whole_files(fl)
        return self._compute_ranges(fl)

    def _compute_whole_files(self, fl: file_io.FileList):
        """Compressed: whole-file granularity round-robin by size psum."""
        W = self.context.num_workers
        total = fl.total_size
        from ...data.multiplexer import local_worker_set
        local = local_worker_set(self.context.mesh_exec)
        lists: List[List[str]] = [[] for _ in range(W)]
        for fi in fl.files:
            # assign file to the worker owning its start offset
            w = min(W - 1, (fi.size_ex_psum * W) // max(total, 1))
            if w not in local:
                continue          # another controller reads this file
            with file_io.OpenReadStream(fi.path) as f:
                data = f.read()
            lists[w].extend(data.decode("utf-8").splitlines())
        return HostShards(W, lists)

    def _compute_ranges(self, fl: file_io.FileList):
        """Uncompressed: split the global byte range evenly; each worker
        starts after the first newline past its range start (the item
        owned by the worker containing its START). Multi-controller:
        each process reads ONLY its own workers' byte ranges — the I/O
        scales out with processes (reference: read_lines.hpp:41 splits
        by worker the same way)."""
        W = self.context.num_workers
        total = fl.total_size
        from ...data.multiplexer import local_worker_set
        local = local_worker_set(self.context.mesh_exec)
        bounds = dense_range_bounds(total, W).tolist()
        lists: List[List[str]] = []
        for w in range(W):
            if w not in local:
                lists.append([])
                continue
            lo, hi = bounds[w], bounds[w + 1]
            lists.append(_read_lines_range(fl, lo, hi))
        return HostShards(W, lists)


def _read_delimited_range(fl: file_io.FileList, lo: int, hi: int,
                          is_delim, find_delim,
                          include_delim: bool) -> List[bytes]:
    """Byte chunks covering every delimited item whose FIRST byte lies
    in [lo, hi) of the global stream (one chunk per overlapping file;
    file boundaries always terminate an item).

    The one boundary scanner behind both ReadLines (delimiter = '\\n',
    kept in the chunk) and ReadWordsPacked (delimiter = any whitespace,
    dropped): ``is_delim(byte) -> bool`` probes the byte before the
    range, ``find_delim(bytes) -> offset|-1`` scans forward, and
    ``include_delim`` controls whether the final delimiter is part of
    the last item."""
    out: List[bytes] = []
    if lo >= hi:
        return out
    for fi in fl.files:
        f_lo, f_hi = fi.size_ex_psum, fi.size_ex_psum + fi.size
        if f_hi <= lo or f_lo >= hi:
            continue
        start = max(lo, f_lo) - f_lo
        end = min(hi, f_hi) - f_lo
        # readahead horizon = the range end: the background reader must
        # not stream blocks past the bytes this worker will consume
        # (the tail extension past ``end`` legitimately continues on
        # demand reads — a horizon is a hint, not EOF)
        with file_io.OpenReadStream(fi.path, readahead_to=end) as f:
            if start > 0:
                f.seek(start - 1)
                if is_delim(f.read(1)):
                    chunk_start = start
                else:
                    # mid-item: the item containing byte ``start``
                    # began earlier and belongs to the previous range
                    chunk_start = None
                    pos = start
                    while True:
                        b = f.read(1 << 16)
                        if not b:
                            chunk_start = f_hi - f_lo
                            break
                        d = find_delim(b)
                        if d >= 0:
                            chunk_start = pos + d + 1
                            break
                        pos += len(b)
            else:
                chunk_start = 0
            if chunk_start >= end:
                continue
            f.seek(chunk_start)
            data = f.read(end - chunk_start)
            # extend to finish the last item (it starts in-range)
            if data and not is_delim(data[-1:]):
                while True:
                    b = f.read(1 << 16)
                    if not b:
                        break
                    d = find_delim(b)
                    if d >= 0:
                        data += b[:d + 1] if include_delim else b[:d]
                        break
                    data += b
            out.append(data)
    return out


def _read_lines_range(fl: file_io.FileList, lo: int, hi: int) -> List[str]:
    """All lines whose first byte lies in [lo, hi) of the global stream."""
    out: List[str] = []
    for data in _read_delimited_range(
            fl, lo, hi, lambda b: b == b"\n",
            lambda b: b.find(b"\n"), include_delim=True):
        # str.splitlines is already a C-level loop and handles CRLF
        # etc.; the native scanner (data/block_pool.scan_line_offsets)
        # is reserved for the raw-bytes -> device packing path where
        # no Python string objects are materialized
        out.extend(data.decode("utf-8").splitlines())
    return out


class ReadWordsPackedNode(DIABase):
    """Text -> device DIA of fixed-width packed words.

    The device-native text source (reference text pipelines start from
    ReadLines + a per-item FlatMap split, read_lines.hpp:41 +
    word_count.hpp:35-44; here tokenization is one vectorized pass and
    the words land directly in device columns as {"w": [max_word] u8}
    rows, ready for byte-key ReduceByKey/Sort). A word is owned by the
    worker whose byte range contains its FIRST byte — the same
    ownership rule ReadLines uses for lines."""

    def __init__(self, ctx, path_or_glob: str, max_word: int) -> None:
        super().__init__(ctx, "ReadWordsPacked")
        self.pattern = path_or_glob
        self.max_word = int(max_word)

    def compute(self):
        from ...core import text as textmod
        from ...data import multiplexer

        W = self.context.num_workers
        mex = self.context.mesh_exec
        fl = file_io.Glob(self.pattern)
        if len(fl) == 0:
            raise FileNotFoundError(f"ReadWordsPacked: no files match "
                                    f"{self.pattern!r}")
        local = multiplexer.local_worker_set(mex)
        total = fl.total_size
        empty = np.zeros((0, self.max_word), dtype=np.uint8)
        per_worker = []
        if fl.contains_compressed:
            # whole-file granularity (same placement rule as ReadLines)
            chunks: List[List[bytes]] = [[] for _ in range(W)]
            for fi in fl.files:
                w = min(W - 1, (fi.size_ex_psum * W) // max(total, 1))
                if w not in local:
                    continue
                with file_io.OpenReadStream(fi.path) as f:
                    chunks[w].append(f.read())
            for w in range(W):
                per_worker.append(np.concatenate(
                    [textmod.tokenize_packed(c, self.max_word)
                     for c in chunks[w]], axis=0)
                    if chunks[w] else empty)
        else:
            bounds = dense_range_bounds(total, W).tolist()
            for w in range(W):
                if w not in local:
                    per_worker.append(empty)
                    continue
                parts = [textmod.tokenize_packed(c, self.max_word)
                         for c in _read_word_bytes_range(
                             fl, bounds[w], bounds[w + 1])]
                per_worker.append(np.concatenate(parts, axis=0)
                                  if parts else empty)

        counts = np.array([len(a) for a in per_worker], dtype=np.int64)
        if multiplexer.multiprocess(mex):
            # counts are data-dependent: agree on the global vector
            mine = {w: int(counts[w]) for w in mex.local_workers}
            for msg in multiplexer._net(mex).all_gather(mine):
                for w, c in msg.items():
                    counts[int(w)] = c
        return DeviceShards.from_worker_arrays(
            mex, [{"w": a} for a in per_worker], counts=counts)


def _read_word_bytes_range(fl: file_io.FileList, lo: int,
                           hi: int) -> List[bytes]:
    """Byte chunks covering every word whose first byte lies in
    [lo, hi) of the global stream (file boundaries count as
    separators, like ReadLines treats them as line breaks)."""
    from ...core import text as textmod
    return _read_delimited_range(
        fl, lo, hi,
        lambda b: bool(textmod.sep_mask(np.frombuffer(b, np.uint8))[0]),
        textmod.find_first_sep, include_delim=False)


class ReadBinaryNode(DIABase):
    """Fixed-size records -> device columnar storage directly."""

    def __init__(self, ctx, path_or_glob: str, dtype, record_shape) -> None:
        super().__init__(ctx, "ReadBinary")
        self.pattern = path_or_glob
        self.dtype = np.dtype(dtype)
        self.record_shape = tuple(record_shape)

    def compute(self):
        W = self.context.num_workers
        fl = file_io.Glob(self.pattern)
        rec_items = int(np.prod(self.record_shape)) if self.record_shape \
            else 1
        rec_bytes = rec_items * self.dtype.itemsize
        total_recs = fl.total_size // rec_bytes
        bounds = dense_range_bounds(total_recs, W).tolist()
        # multi-controller: read only this process's workers' ranges;
        # counts derive from bounds, so no agreement round is needed
        from ...data.multiplexer import local_worker_set
        local = local_worker_set(self.context.mesh_exec)
        empty = np.empty((0,) + self.record_shape, dtype=self.dtype)
        per_worker = []
        for w in range(W):
            if w not in local:
                per_worker.append(empty)
                continue
            lo, hi = bounds[w], bounds[w + 1]
            arr = _read_records(fl, lo, hi, rec_bytes, self.dtype)
            per_worker.append(arr.reshape((-1,) + self.record_shape))
        counts = np.array([bounds[w + 1] - bounds[w] for w in range(W)],
                          dtype=np.int64)
        return DeviceShards.from_worker_arrays(
            self.context.mesh_exec, per_worker, counts=counts)


def _read_records(fl, lo_rec, hi_rec, rec_bytes, dtype) -> np.ndarray:
    lo, hi = lo_rec * rec_bytes, hi_rec * rec_bytes
    chunks = []
    for fi in fl.files:
        f_lo, f_hi = fi.size_ex_psum, fi.size_ex_psum + fi.size
        if f_hi <= lo or f_lo >= hi:
            continue
        start = max(lo, f_lo) - f_lo
        end = min(hi, f_hi) - f_lo
        with file_io.OpenReadStream(fi.path, offset=start,
                                    readahead_to=end) as f:
            chunks.append(f.read(end - start))
    buf = b"".join(chunks)
    return np.frombuffer(buf, dtype=dtype)


# ----------------------------------------------------------------------
# sinks
# ----------------------------------------------------------------------

def _worker_path(pattern: str, w: int) -> str:
    if "$$$$$" in pattern:        # reference's wildcard (api/dia.hpp:813)
        return pattern.replace("$$$$$", f"{w:05d}")
    if "{}" in pattern:
        return pattern.format(w)
    base, ext = os.path.splitext(pattern)
    return f"{base}-{w:05d}{ext}"


def _host_lists(dia) -> HostShards:
    shards = dia._link().pull()
    if isinstance(shards, DeviceShards):
        shards = shards.to_host_shards("writelines")
    return shards


def _local_worker_ids(dia):
    mex = dia.context.mesh_exec
    from ...data import multiplexer
    if multiplexer.multiprocess(mex):
        return set(mex.local_workers)
    return set(range(mex.num_workers))


def WriteLines(dia, path_pattern: str) -> None:
    """One text file per worker (reference: api/write_lines.hpp:33).
    Multi-controller: each process writes only its own workers' files."""
    shards = _host_lists(dia)
    owned = _local_worker_ids(dia)
    for w, items in enumerate(shards.lists):
        if w not in owned:
            continue
        with file_io.OpenWriteStream(_worker_path(path_pattern, w)) as f:
            for it in items:
                f.write(str(it).encode("utf-8"))
                f.write(b"\n")


def WriteLinesOne(dia, path: str) -> None:
    """Single coordinated output file (reference: write_lines_one.hpp:31).
    Multi-controller: items gather to process 0, which writes the file
    alone (worker-rank order is preserved)."""
    shards = _host_lists(dia)
    mex = dia.context.mesh_exec
    from ...data import multiplexer
    if multiplexer.multiprocess(mex):
        items = multiplexer.all_items(mex, shards)
        if mex.process_index != 0:
            return
        with file_io.OpenWriteStream(path) as f:
            for it in items:
                f.write(str(it).encode("utf-8"))
                f.write(b"\n")
        return
    with file_io.OpenWriteStream(path) as f:
        for items in shards.lists:
            for it in items:
                f.write(str(it).encode("utf-8"))
                f.write(b"\n")


def WriteBinary(dia, path_pattern: str) -> None:
    """Raw fixed-size records, one file per worker
    (reference: api/write_binary.hpp:36)."""
    shards = dia._link().pull()
    owned = _local_worker_ids(dia)
    if isinstance(shards, DeviceShards):
        per_worker = shards.to_worker_arrays(local_only=True)
        import jax
        for w, tree in enumerate(per_worker):
            if tree is None or w not in owned:
                continue
            leaves = jax.tree.leaves(tree)
            with file_io.OpenWriteStream(_worker_path(path_pattern, w)) as f:
                for leaf in leaves:
                    f.write(np.ascontiguousarray(leaf).tobytes())
        return
    for w, items in enumerate(shards.lists):
        if w not in owned:
            continue
        with file_io.OpenWriteStream(_worker_path(path_pattern, w)) as f:
            for it in items:
                f.write(np.asarray(it).tobytes())


def ReadLines(ctx, path_or_glob: str) -> DIA:
    return DIA(ReadLinesNode(ctx, path_or_glob))


def ReadWordsPacked(ctx, path_or_glob: str, max_word: int = 16) -> DIA:
    return DIA(ReadWordsPackedNode(ctx, path_or_glob, max_word))


def ReadBinary(ctx, path_or_glob: str, dtype, record_shape=()) -> DIA:
    return DIA(ReadBinaryNode(ctx, path_or_glob, dtype, record_shape))
