"""Helper nodes for local ops that must leave the device path."""

from __future__ import annotations

from typing import Callable

from ...data.shards import DeviceShards, HostShards
from ..dia import DIA
from ..dia_base import DIABase, ParentLink


class HostFlatMapNode(DIABase):
    """Generic (variable-arity) FlatMap: falls back to host item lists.

    The device path only supports fixed-factor flat_map (static shapes);
    the reference's fully general FlatMap semantics
    (api/dia.hpp:458) live here.
    """

    def __init__(self, ctx, link: ParentLink, fn: Callable) -> None:
        super().__init__(ctx, "FlatMapHost", [link])
        self.fn = fn

    def compute(self):
        shards = self.parents[0].pull()
        if isinstance(shards, DeviceShards):
            shards = shards.to_host_shards("explicit-tohost")
        out = []
        for items in shards.lists:
            lst = []
            for it in items:
                lst.extend(self.fn(it))
            out.append(lst)
        return HostShards(shards.num_workers, out)


def flat_map_host(dia: DIA, fn: Callable) -> DIA:
    return DIA(HostFlatMapNode(dia.context, dia._link(), fn))


class ToHostNode(DIABase):
    def __init__(self, ctx, link: ParentLink) -> None:
        super().__init__(ctx, "ToHost", [link])

    def compute(self):
        shards = self.parents[0].pull()
        if isinstance(shards, DeviceShards):
            return shards.to_host_shards("explicit-tohost")
        return shards


class ToDeviceNode(DIABase):
    def __init__(self, ctx, link: ParentLink) -> None:
        super().__init__(ctx, "ToDevice", [link])

    def compute(self):
        shards = self.parents[0].pull()
        if isinstance(shards, HostShards):
            return shards.to_device(self.context.mesh_exec)
        return shards


def to_host(dia: DIA) -> DIA:
    return DIA(ToHostNode(dia.context, dia._link()))


def to_device(dia: DIA) -> DIA:
    return DIA(ToDeviceNode(dia.context, dia._link()))
