"""Union: combine DIAs without order guarantees.

Reference: thrill/api/union.hpp:53 — concatenates local pieces, no
communication. Device path: per-worker compacting concatenation only.
"""

from __future__ import annotations

from typing import List

from ...data.shards import DeviceShards, HostShards
from ..dia import DIA
from ..dia_base import DIABase
from .concat import _local_concat


class UnionNode(DIABase):
    def __init__(self, ctx, links) -> None:
        super().__init__(ctx, "Union", links)

    def compute(self):
        pulls = [l.pull() for l in self.parents]
        if any(isinstance(p, HostShards) for p in pulls):
            pulls = [p.to_host_shards("union-mixed-storage") if isinstance(p, DeviceShards)
                     else p for p in pulls]
            W = pulls[0].num_workers
            return HostShards(W, [[it for p in pulls for it in p.lists[w]]
                                  for w in range(W)])
        if len(pulls) == 1:
            return pulls[0]
        return _local_concat(pulls)


def Union(a: DIA, *others: DIA) -> DIA:
    return DIA(UnionNode(a.context, [a._link()] +
                         [o._link() for o in others]))


def UnionMany(dias: List[DIA]) -> DIA:
    assert dias
    return DIA(UnionNode(dias[0].context, [d._link() for d in dias]))
