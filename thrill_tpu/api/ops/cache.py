"""Cache and Collapse nodes.

Reference: thrill/api/cache.hpp:32 (materialize items for reuse) and
collapse.hpp:29 (fold a non-empty LOp stack into a plain DIA<T>, e.g.
for loop variables whose type must not depend on the stack).
"""

from __future__ import annotations

from ..dia import DIA
from ..dia_base import DIABase


class CacheNode(DIABase):
    def __init__(self, ctx, link) -> None:
        super().__init__(ctx, "Cache", [link])

    def compute_plan(self):
        # pure pass-through: the folded stack (and any deferred parent
        # chain) rides into the consumer's stitched dispatch
        from .. import fusion
        return fusion.pull_plan(self.parents[0])

    def compute(self):
        return self.parents[0].pull()


class CollapseNode(DIABase):
    """Same materialization behavior; semantically folds the stack so
    the handle is a plain DIA (loop-variable pattern)."""

    def __init__(self, ctx, link) -> None:
        super().__init__(ctx, "Collapse", [link])

    def compute_plan(self):
        from .. import fusion
        return fusion.pull_plan(self.parents[0])

    def compute(self):
        return self.parents[0].pull()


def Cache(dia: DIA) -> DIA:
    return DIA(CacheNode(dia.context, dia._link()))


def Collapse(dia: DIA) -> DIA:
    return DIA(CollapseNode(dia.context, dia._link()))
