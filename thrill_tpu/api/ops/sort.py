"""Distributed sample sort.

Reference: thrill/api/sort.hpp:64 — PreOp reservoir-samples while
spilling; MainOp gathers samples on worker 0, picks p-1 splitters,
classifies every item down a branchless splitter tree into per-worker
stream writers (tie-break by global index for balance on equal keys,
api/sort.hpp:487-502); receivers sort runs and multiway-merge.

TPU-native design, bulk-synchronous device programs in which the
payload is gathered exactly ONCE per phase and only (validity, key
words, global index) flow through sort networks:
 1. keys:     local argsort of the key words + quantile sampling —
              outputs the permutation, sorted words and samples, with
              NO payload movement (the worker-0 splitter step collapses
              to the single controller). W == 1 finishes here with a
              single payload gather.
 2. classify: destination = lexicographic rank among splitters
              ((words, index) compare, so duplicate keys spread evenly
              across workers exactly like the reference's tie-break).
              Items are already key-sorted, so destinations are
              MONOTONE — destination grouping needs no second sort; the
              same program gathers the payload once (by the phase-1
              permutation) and the planned all-to-all ships it.
 3. merge:    one local sort of the received (words, index) pairs +
              one payload gather — the analog of sort-runs + multiway
              merge (received runs are rank-ordered and internally
              sorted; the chunked engine exploits tile sortedness).

The result is globally sorted across worker ranks and stable: equal
keys keep their original global order, making Sort and SortStable one
code path (the reference needs a separate CatStream variant).
"""

from __future__ import annotations

from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ...core import keys as keymod
from ...data import exchange
from ...data.shards import DeviceShards, HostShards
from ...parallel.mesh import AXIS
from ..dia import DIA
from ..dia_base import DIABase
from ...common.partition import dense_range_bounds

OVERSAMPLE = 32  # samples per worker; splitter error ~ 1/OVERSAMPLE


def quantile_positions(count, cap: int):
    """Traced helper: OVERSAMPLE quantile positions over the valid
    prefix [0, count) of a sorted column (clipped to [0, cap))."""
    count_f = jnp.maximum(count, 1)
    qpos = ((jnp.arange(OVERSAMPLE, dtype=jnp.int64) * 2 + 1)
            * count_f // (2 * OVERSAMPLE))
    return jnp.clip(qpos, 0, cap - 1)


def choose_splitters(samples, W: int, ncols: int) -> np.ndarray:
    """Host helper: W-1 equidistant splitters from SORTED sample tuples
    (each a flat tuple of ints, ncols wide) -> uint64 matrix
    [max(W-1,1), ncols]. The worker-0 splitter step collapsed to the
    single controller (reference: FindAndSendSplitters,
    api/sort.hpp:337-378)."""
    splitters = np.zeros((max(W - 1, 1), ncols), dtype=np.uint64)
    if samples and W > 1:
        for j in range(1, W):
            s = samples[min(len(samples) - 1, (j * len(samples)) // W)]
            splitters[j - 1] = np.array(s, dtype=np.uint64)
    return splitters


class SortNode(DIABase):
    # EM operator: asks the stage negotiation for as much worker RAM as
    # available (reference: SortNode uses DIAMemUse::Max for its
    # ReceiveItems capacity, api/sort.hpp MainOp + dia_base.cpp:121-270)
    MEM_USE = "max"

    def __init__(self, ctx, link, key_fn: Optional[Callable],
                 compare_fn: Optional[Callable], stable: bool) -> None:
        super().__init__(ctx, "Sort", [link])
        self.key_fn = key_fn or (lambda x: x)
        self.compare_fn = compare_fn
        self.stable = stable

    def _fuse_segment(self):
        """W == 1 local sort (key-only argsort + one payload gather) as
        a fused segment. The W > 1 sample sort needs its splitter
        agreement and all-to-all — a fusion barrier — and stays on the
        phased path."""
        from .. import fusion
        from ...core import host_radix
        if self.context.num_workers != 1 or self.compare_fn is not None \
                or host_radix.eligible(self.context.mesh_exec):
            return None
        key_fn = self.key_fn

        def trace(fctx, tree, mask, _bound):
            cap = mask.shape[0]
            words = keymod.encode_key_words(key_fn(tree))
            iota = jnp.arange(cap, dtype=jnp.uint64)
            from ...core.device_sort import argsort_words
            sort_words = ([(~mask).astype(jnp.uint32)] + list(words)
                          + [iota])
            perm = argsort_words(sort_words)
            from ...core import rowmove
            leaves, td = jax.tree.flatten(tree)
            out = rowmove.take_rows_multi(leaves, perm)
            count = jnp.sum(mask.astype(jnp.int32))
            return (jax.tree.unflatten(td, out),
                    jnp.arange(cap) < count)

        return fusion.Segment(label="Sort",
                              token=("sort_w1_fused", self.key_fn),
                              trace=trace, preserves_counts=True,
                              already_compact=True, dia_id=self.id)

    def compute_plan(self):
        from .. import fusion
        seg = self._fuse_segment()
        if seg is None:
            return None
        plan = fusion.pull_plan(self.parents[0])
        if not plan.stitchable:
            return fusion.wrap(self._compute_on(plan.finish()))
        plan.append(seg)
        return plan

    def compute(self):
        plan = self.compute_plan()
        if plan is not None:
            return plan.finish()
        return self._compute_on(self.parents[0].pull())

    def _compute_on(self, shards):
        if isinstance(shards, HostShards):
            return self._compute_host(shards)
        if self.compare_fn is not None:
            return self._compute_host(shards.to_host_shards("sort-compare-fn"))
        return _device_sample_sort(shards, self.key_fn,
                                   (self.key_fn,))

    # above this many items the host path sorts external-memory style:
    # sorted runs spilled to Files, k-way merged (reference:
    # SortAndWriteToFile + PartialMultiwayMerge, api/sort.hpp:665-699,
    # 216-271). Overridable for tests via THRILL_TPU_HOST_SORT_RUN.
    HOST_RUN_SIZE = 1 << 20

    def _compute_host(self, shards: HostShards):
        # multi-controller: the EM/in-memory host sort needs the global
        # item stream; replicate, compute identically, keep local lists
        from ...data import multiplexer
        mex = self.context.mesh_exec
        if multiplexer.multiprocess(mex):
            rep = multiplexer.ensure_replicated(mex, shards, "sort-host")
            return multiplexer.localize(mex, self._compute_host_impl(rep))
        return self._compute_host_impl(shards)

    def _compute_host_impl(self, shards: HostShards):
        import functools
        import os
        W = shards.num_workers
        if self.compare_fn is not None:
            sort_key = functools.cmp_to_key(
                lambda a, b: -1 if self.compare_fn(a, b)
                else (1 if self.compare_fn(b, a) else 0))
        else:
            sort_key = self.key_fn

        run_size = int(os.environ.get("THRILL_TPU_HOST_SORT_RUN") or
                       self._granted_run_size(shards))
        run_size = max(run_size, 16)
        self._granted_run_size_last = run_size
        n = shards.total
        if n <= run_size:
            items = [it for l in shards.lists for it in l]
            items.sort(key=sort_key)
            bounds = dense_range_bounds(n, W).tolist()
            return HostShards(W, [items[bounds[w]:bounds[w + 1]]
                                  for w in range(W)])
        try:
            return HostShards(W, self._em_sort(shards, sort_key,
                                               run_size, W))
        except (TypeError, ValueError, AttributeError):
            # unpicklable items cannot spill; fall back in-memory
            items = [it for l in shards.lists for it in l]
            items.sort(key=sort_key)
            bounds = dense_range_bounds(n, W).tolist()
            return HostShards(W, [items[bounds[w]:bounds[w + 1]]
                                  for w in range(W)])

    def _granted_run_size(self, shards: HostShards) -> int:
        """In-RAM run capacity in items from the negotiated grant.

        The reference sizes its ReceiveItems capacity from the granted
        RAM over the item size (api/sort.hpp:665-699); host items here
        are Python objects spilled pickled, so the estimate probes the
        first item's pickled size (plus interpreter overhead)."""
        if not self.mem_limit:
            return self.HOST_RUN_SIZE
        first = next((it for l in shards.lists for it in l), None)
        if first is None:
            return self.HOST_RUN_SIZE
        try:
            import pickle
            est = len(pickle.dumps(
                first, protocol=pickle.HIGHEST_PROTOCOL)) + 64
        except Exception:
            est = 256
        return max(16, min(self.mem_limit // est, 1 << 26))

    def _em_sort(self, shards: HostShards, sort_key, run_size: int,
                 W: int):
        """External-memory sort: spill sorted runs, k-way merge them.

        A growing reservoir samples the stream while it spills
        (reference: ReservoirSamplingGrow in the Sort PreOp,
        api/sort.hpp:303) and yields W-1 splitters; the k-way merge then
        streams STRAIGHT into splitter-partitioned per-worker output
        lists — the merged sequence is never materialized twice.

        The phases run as an OVERLAPPED pipeline, not a blocking
        ladder (the foxxll analog this repo's out-of-core tier is
        built on): each completed run's sort+serialize+flush rides the
        bounded write-behind writer (data/writeback.py) so run k+1
        encodes while run k flushes — a writer failure re-raises on
        this thread at the next spill or the pre-merge barrier, never
        silent loss — and the k-way merge gives every run one block of
        readahead so the winner's next block is resident before the
        tournament needs it. ``THRILL_TPU_WRITEBACK=0`` /
        ``THRILL_TPU_PREFETCH=0`` restore the synchronous ladder
        byte-identically (same results, same spill-file naming).

        When this node owns the input exclusively (the consuming pull
        disposed the parent), shard lists are released as they spill so
        the spilled copy replaces — not duplicates — the resident items.
        """
        from ...common import faults
        from ...common.decisions import record_of, resolve_io_prefetch
        from ...common.iostats import IO as _IOSTATS, hit_rate, \
            overlap_frac
        from ...common.sampling import ReservoirSamplingGrow
        from ...data import records as native_records
        from ...data.block_pool import spill_pool
        from ...data.writeback import AsyncWriter, make_readahead
        from ...core import native_merge, order_key
        from ...core.multiway_merge import multiway_merge_files
        from ...vfs.file_io import prefetch_depth

        owns_input = self.parents[0].node.state == "DISPOSED"
        mex = self.context.mesh_exec
        io_base = _IOSTATS.snapshot()
        # spilled-run store keeps a quarter of the grant resident
        # before evicting runs to disk
        pool = spill_pool(self.context.config.spill_dir,
                          self.mem_limit)
        # resumable runs (core/em_runs.py): with checkpointing on, each
        # spilled run commits a CRC'd manifest under the checkpoint
        # dir; a relaunch with resume reloads committed runs instead of
        # re-sorting them (identity-checked — slot, position range,
        # first-item fingerprint). None when ctx.checkpoint is None or
        # THRILL_TPU_EM_RESUME=0: zero overhead on the default path.
        from ...core import em_runs
        run_store = em_runs.store_for(
            self.context, node_id=self.id, label=self.label, W=W,
            run_size=run_size, total=shards.total)
        sampler = ReservoirSamplingGrow(np.random.default_rng(17))
        # items carry their stream position: the (key, position)
        # tiebreak makes the EM sort stable AND lets splitters cut
        # inside equal-key runs, so low-cardinality keys cannot pile
        # every duplicate onto one worker (the reference breaks splitter
        # ties by global index the same way, api/sort.hpp:487-502)
        pair_key = lambda t: (sort_key(t[1]), t[0])  # noqa: E731
        # native merge path: when the key schema byte-encodes
        # (core/order_key.py), runs sort by raw key bytes and the merge
        # selection loop runs in C++ (native/mwmerge.cpp) instead of
        # heapq + per-item Python key calls. ``enc`` is probed from the
        # first item and demoted to None on ANY schema deviation —
        # item files always hold plain (pos, item) records in key
        # order, so runs spilled before a demotion merge fine on the
        # generic path.
        enc = None
        enc_state = "probe" if native_merge.available() else "off"
        enc_arr = None      # vectorized S-array encoder (int/str)
        files = []          # item Files, (pos, item) records
        key_files = []      # parallel key-byte Files (native path)
        run = []            # native: (kb, pos, item); generic: (pos, item)
        # columnar run state (native fast path): kb rows live in S-w
        # numpy arrays, items in a parallel list, positions implicit
        # (col_pos0 + index) — zero per-item Python objects until the
        # vectorized spill. Any batch the array encoder can't handle
        # exactly folds the columnar state into `run` tuples and
        # continues on the listcomp path; a full schema deviation
        # demotes to the generic engine as before.
        col_arrs: list = []
        col_items: list = []
        col_pos0 = 0
        # native-record spiller: when the ITEMS themselves vectorize
        # into fixed-dtype columns (data/records.py schema probe), a
        # fully-columnar run spills through _records_job — the payload
        # encode, memcmp argsort, pos+payload gather and block handoff
        # ALL run inside the write-behind job, off the main thread's
        # critical path, and the native calls release the GIL so the
        # writer genuinely overlaps the next run's encode. A run the
        # encoder cannot represent exactly degrades to the per-item
        # path inside the job (never wrong data); the key-columnar
        # state is unaffected.
        rec_probe = "probe"
        rec_enc = None
        pos = 0
        # real-memory feedback: run_size is an ESTIMATE from one
        # pickled item; the RSS budget is ground truth and spills the
        # run early when actual interpreter growth passes the grant
        # (reference: ReceiveItems spills on mem::memory_exceeded,
        # api/sort.hpp:679)
        from ...data.file import DEFAULT_BLOCK_ITEMS, File
        from ...mem.manager import RssBudget
        budget = RssBudget(self.mem_limit or 0)

        def run_len():
            return len(run) + len(col_items)

        def decolumnize():
            """Fold columnar batches into (kb, pos, item) tuples so the
            mixed-width tuple path can continue the run."""
            nonlocal col_arrs, col_items, col_pos0
            p = col_pos0
            for arr in col_arrs:
                w_ = arr.dtype.itemsize
                raw = arr.tobytes()     # raw memory: no NUL stripping
                n_ = len(arr)
                run.extend(zip(
                    (raw[i * w_:(i + 1) * w_] for i in range(n_)),
                    range(p, p + n_), col_items[p - col_pos0:
                                                p - col_pos0 + n_]))
                p += n_
            col_arrs, col_items, col_pos0 = [], [], 0

        # write-behind spill: each completed run's sort+serialize+write
        # is ONE FIFO job on the bounded writer — run k+1's encode (the
        # main thread) overlaps run k's argsort/disk-write (GIL-
        # releasing; the job's pickle fraction is not, and bounds the
        # wall-clock win — ARCHITECTURE "Out-of-core storage tier").
        # Slots are reserved at submit so run order in ``files`` is
        # the arrival order regardless of who executes.
        writer = AsyncWriter("em_sort.spill",
                             tracer=getattr(mex, "tracer", None))

        def _widen_concat(arrs):
            """One S-W key array from per-batch arrays of possibly
            different widths (str batches pad to their own max): widen
            with zero pads — order-safe by the padding argument in
            order_key make_array_batch_encoder."""
            W_ = max(a.dtype.itemsize for a in arrs)
            for j, a in enumerate(arrs):
                w_ = a.dtype.itemsize
                if w_ != W_:
                    buf = np.zeros((len(a), W_), np.uint8)
                    buf[:, :w_] = a.view(np.uint8).reshape(
                        len(a), w_)               # zero-copy source
                    arrs[j] = buf.reshape(-1).view(f"S{W_}")
            return arrs[0] if len(arrs) == 1 else np.concatenate(arrs)

        def _columnar_job(arrs, items_, p0, slot, meta=None):
            def job():
                b0 = pool.bytes_put
                arr = _widen_concat(arrs)
                order = np.argsort(arr)
                f = File(pool=pool)
                with f.writer() as w:
                    for i in order.tolist():
                        w.put((p0 + i, items_[i]))
                kf = File(pool=pool)
                native_merge.write_key_chunks_fixed(kf, arr[order])
                files[slot] = f
                key_files[slot] = kf
                if meta is not None:
                    run_store.submit_commit(slot, *meta, f, kf)
                return pool.bytes_put - b0
            return job

        def _records_job(arrs, items_, p0, slot, meta=None):
            """Native-records spill: the whole encode — vectorized
            payload columns, memcmp argsort, pos/payload gather, block
            handoff — runs INSIDE the write-behind job, so the main
            thread pays nothing beyond handing over the item list it
            already held, and the native calls (native/records.cpp)
            release the GIL for the job's heavy part. Any encode
            failure (schema deviation inside the run, injected
            ``data.records.encode``, or real) DEGRADES to the per-item
            pickle path on the same data — slower, never wrong, never
            poisons."""
            def job():
                b0 = pool.bytes_put
                arr = _widen_concat(arrs)
                order = native_records.argsort_rows(arr)
                f = File(pool=pool)
                enc = None
                try:
                    enc = rec_enc(items_)
                    if enc is not None:
                        native_records.write_run_blocks(
                            f, order, p0, enc[1], enc[0],
                            f.block_items)
                except Exception as e:
                    faults.note("recovery",
                                what="records.encode_degraded",
                                error=repr(e)[:200])
                    f.clear()
                    f = File(pool=pool)
                    enc = None
                if enc is None:
                    with f.writer() as w:
                        for i in order.tolist():
                            w.put((p0 + i, items_[i]))
                kf = File(pool=pool)
                native_merge.write_key_chunks_fixed(
                    kf, native_records.gather_rows(arr, order))
                files[slot] = f
                key_files[slot] = kf
                if meta is not None:
                    run_store.submit_commit(slot, *meta, f, kf)
                return pool.bytes_put - b0
            return job

        def _encoded_job(this_run, slot, meta=None):
            def job():
                b0 = pool.bytes_put
                this_run.sort()          # kb unique (pos suffix): pure
                f = File(pool=pool)      # memcmp, items never compared
                with f.writer() as w:
                    for kb, p, it in this_run:
                        w.put((p, it))
                kf = File(pool=pool)
                native_merge.write_key_chunks(kf, [t[0] for t in this_run])
                files[slot] = f
                key_files[slot] = kf
                if meta is not None:
                    run_store.submit_commit(slot, *meta, f, kf)
                return pool.bytes_put - b0
            return job

        def _generic_job(this_run, slot, meta=None):
            def job():
                b0 = pool.bytes_put
                f = _spill_run(pool, this_run, pair_key)
                files[slot] = f
                if meta is not None:
                    run_store.submit_commit(slot, *meta, f, None)
                return pool.bytes_put - b0
            return job

        def spill():
            nonlocal run
            if col_items and run:
                decolumnize()           # mixed run: one representation
            slot = len(files)
            files.append(None)
            key_files.append(None)
            meta = None
            if run_store is not None:
                # run identity in arrival order: (pos0, n, first-item
                # fingerprint) — computed BEFORE the job sorts anything
                if col_items:
                    p0, n_, first = col_pos0, len(col_items), \
                        col_items[0]
                elif enc is not None:
                    p0, n_, first = run[0][1], len(run), run[0][2]
                else:
                    p0, n_, first = run[0][0], len(run), run[0][1]
                meta = (p0, n_, em_runs.fingerprint(first))
                got = run_store.try_load(slot, *meta, pool,
                                         DEFAULT_BLOCK_ITEMS)
                if got is not None:
                    # committed run from the previous launch: adopt its
                    # blocks, skip the sort+serialize+write entirely.
                    # runs_reused counts here; spill_runs does NOT —
                    # the perf sentinel separates formed from reloaded.
                    files[slot], key_files[slot] = got
                    _IOSTATS.add(runs_reused=1)
                    col_arrs.clear()
                    col_items.clear()
                    run = []
                    return
            _IOSTATS.add(spill_runs=1)
            if col_items:
                # fully-columnar run: ordering is ONE argsort over the
                # S-w rows (C memcmp — no Python compares, no per-key
                # objects); the key file writes vectorized slices of
                # the sorted array. The pos suffix makes every row
                # distinct, so argsort stability is immaterial. With a
                # records-encodable item schema the whole job (payload
                # columns + sort + gather + handoff) runs natively in
                # the writer.
                if rec_enc is not None:
                    writer.submit(_records_job(list(col_arrs),
                                               list(col_items),
                                               col_pos0, slot, meta),
                                  tag=slot)
                else:
                    writer.submit(_columnar_job(list(col_arrs),
                                                list(col_items),
                                                col_pos0, slot, meta),
                                  tag=slot)
                col_arrs.clear()
                col_items.clear()
            elif enc is not None:
                writer.submit(_encoded_job(run, slot, meta), tag=slot)
            else:
                writer.submit(_generic_job(run, slot, meta), tag=slot)
            run = []

        def demote():
            """Schema deviation: strip key decoration from the live run
            and stop encoding; spilled runs stay valid as-is."""
            nonlocal enc, enc_state, enc_arr, run
            enc, enc_state, enc_arr = None, "off", None
            if col_items:
                run.extend(zip(range(col_pos0,
                                     col_pos0 + len(col_items)),
                               col_items))
                col_arrs.clear()
                col_items.clear()
            else:
                run = [(p, it) for _kb, p, it in run]

        def append_batch(batch):
            """Batch-at-a-time spill-side processing: ONE vectorized
            encode (or one listcomp) and ONE vectorized reservoir call
            per slice — per-item Python bookkeeping was the profiled
            bottleneck of the whole EM sort, bigger than the merge it
            feeds."""
            nonlocal enc, enc_state, enc_arr, pos, col_pos0
            nonlocal rec_probe, rec_enc
            if enc_state == "probe" and batch:
                enc = order_key.make_batch_encoder(sort_key(batch[0]))
                enc_state = "on" if enc is not None else "off"
                if enc is not None:
                    enc_arr = order_key.make_array_batch_encoder(
                        sort_key(batch[0]))
            if rec_probe == "probe" and batch:
                rec_probe = "done"
                rec_enc = native_records.make_run_encoder(batch[0])
            if enc is not None:
                keys = list(map(sort_key, batch))
                try:
                    arr = None
                    if enc_arr is not None and not run:
                        # batches of different widths coexist; spill
                        # widens them with order-safe zero pads
                        arr = enc_arr(keys, pos)
                    if arr is not None:
                        if not col_items:
                            col_pos0 = pos
                        col_arrs.append(arr)
                        col_items.extend(batch)
                    else:
                        if col_items:
                            decolumnize()
                        # kbs built fully BEFORE touching run: a
                        # mid-batch schema deviation leaves no partial
                        # decoration
                        kbs = enc(keys, range(pos, pos + len(batch)))
                        run.extend(zip(kbs,
                                       range(pos, pos + len(batch)),
                                       batch))
                except order_key.BATCH_ENCODE_ERRORS:
                    demote()
                    run.extend(zip(range(pos, pos + len(batch)), batch))
            else:
                run.extend(zip(range(pos, pos + len(batch)), batch))
            sampler.add_batch_indexed(pos, batch)
            pos += len(batch)

        # batch bound: one real RSS check per batch keeps the grant
        # feedback responsive even when run_size is huge, and caps the
        # transient key-bytes list a single encode pass builds
        MAX_BATCH = 1 << 16
        # phase decomposition for perf evidence: the run-formation
        # (encode+sort+spill) phase is engine-independent machinery;
        # the merge phase is where the native k-way engine replaces
        # heapq + per-item Python key calls (ref hot loop:
        # api/sort.hpp:216-271) — bench.py reports the phase times so
        # the engine win is pinned, not inferred from noisy totals
        import time as _time
        t_phase0 = _time.perf_counter()
        ra = None
        try:
            for lst in shards.lists:
                idx = 0
                while idx < len(lst):
                    take = min(run_size - run_len(), len(lst) - idx,
                               MAX_BATCH)
                    append_batch(lst[idx:idx + take])
                    idx += take
                    if run_len() >= run_size or \
                            (budget.exceeded_now() and run_len() >= 16):
                        spill()
                        budget.reset()
                if owns_input:
                    lst.clear()
            if run_len():
                spill()
            # pre-merge barrier: every run durably spilled (a writer
            # error re-raises HERE with its root cause — the merge
            # never reads a half-flushed run), THEN the block store's
            # own eviction queue drained — the merge's surgical
            # readahead consults resident(), and a settled store makes
            # that policy (and the perf sentinel's prefetch counters) a
            # pure function of the program, not of writer-thread timing
            writer.flush()
            if run_store is not None:
                # every in-flight run commit joined too: after this
                # barrier what is committed is committed, and the
                # consuming merge below may release the pool blocks
                run_store.drain()
            pool.flush()
            t_phase1 = _time.perf_counter()

            # merge readahead: one prefetch slot per run (planner-
            # recorded so explain()/the audit loop cover the choice)
            from ..planner import planner_of
            depth = prefetch_depth()
            pl = planner_of(mex)
            if pl is not None:
                depth = pl.io_prefetch_depth("em_sort.merge", depth)
            rec = record_of(mex, "io_prefetch", "em_sort.merge",
                            f"depth={depth}", predicted=1.0,
                            reason="readahead hit-rate target",
                            runs=len(files), depth=depth)
            ra = make_readahead(depth)
            submit = ra.submit if ra is not None else None
            io_merge0 = _IOSTATS.snapshot()

            samples = sorted(sampler.samples, key=pair_key)
            sample_at = [min(len(samples) - 1, (j * len(samples)) // W)
                         for j in range(1, W)] if samples else []
            out = [[] for _ in range(W)]
            w = 0
            if enc is not None and all(kf is not None
                                       for kf in key_files):
                # byte splitters fed as an extra merge run: partition
                # advances when a splitter pops — no per-item key
                # comparison or key-byte copy in Python at all
                split_kb = [enc([sort_key(samples[i][1])],
                                [samples[i][0]])[0]
                            for i in sample_at]
                native_merge.merge_partitioned(files, key_files,
                                               split_kb, out,
                                               consume=True,
                                               submit=submit)
            else:
                # W-1 (key, position) splitters from the reservoir
                split_keys = [pair_key(samples[i]) for i in sample_at]
                for t in multiway_merge_files(files, key=pair_key,
                                              consume=True,
                                              submit=submit):
                    k = pair_key(t)
                    while w < len(split_keys) and k > split_keys[w]:
                        w += 1
                    out[w].append(t[1])

            io_all = _IOSTATS.delta(_IOSTATS.snapshot(), io_base)
            io_merge = _IOSTATS.delta(_IOSTATS.snapshot(), io_merge0)
            hr = hit_rate(io_merge)
            # shared audit-join formula (common/decisions.py): the
            # planner's learned depth feeds off exactly this signal at
            # every readahead site
            resolve_io_prefetch(mex, rec, io_merge)
            self._em_stats = {
                "runs": len(files), "engine":
                    "native" if enc is not None else "py",
                # columnar blocks the native record format encoded (0 =
                # every run spilled through the per-item pickle path)
                "records_blocks": io_all.get("records_blocks", 0),
                # committed runs reloaded from the run store instead of
                # re-formed (core/em_runs.py; 0 without resume)
                "runs_reused": io_all.get("runs_reused", 0),
                "spill_s": round(t_phase1 - t_phase0, 3),
                "merge_s": round(_time.perf_counter() - t_phase1, 3),
                "overlap_frac": round(overlap_frac(io_all), 3),
                "io_wait_s": io_all["io_wait_s"],
                "io_busy_s": io_all["io_busy_s"],
                "prefetch_hit_rate": round(hr, 3),
                "writeback_bytes": writer.bytes_written,
                "writeback_sync": writer.sync}
            log = self.context.logger
            if log.enabled:
                log.line(event="writeback", what="em_sort.spill",
                         bytes=writer.bytes_written,
                         jobs=writer.jobs_run, sync=writer.sync)
                log.line(event="prefetch", what="em_sort.merge",
                         hits=io_merge["prefetch_hits"],
                         misses=io_merge["prefetch_misses"],
                         wait_s=io_merge["io_wait_s"], depth=depth)
        finally:
            writer.close(drain=False)
            if run_store is not None:
                run_store.close()
            if ra is not None:
                ra.shutdown(wait=True, cancel_futures=True)
            for f in files + key_files:
                if f is not None:
                    f.clear()
            pool.close()
        return out


def _spill_run(pool, run, sort_key):
    from ...data.file import File
    run.sort(key=sort_key)
    f = File(pool=pool)
    with f.writer() as w:
        for it in run:
            w.put(it)
    return f


def _device_sample_sort(shards: DeviceShards, key_fn: Callable,
                        token) -> DeviceShards:
    mex = shards.mesh_exec
    W = mex.num_workers
    cap = shards.cap
    leaves, treedef = jax.tree.flatten(shards.tree)
    total = shards.total
    if total == 0:
        return shards

    # global index offsets (host-known counts -> exclusive prefix)
    offsets = np.concatenate([[0], np.cumsum(shards.counts)])[:-1]

    # all shards full -> the validity sort word is statically dropped
    # (one fewer sort operand; the common case after Distribute/Generate)
    full = bool(np.all(shards.counts == cap))

    if W == 1:
        # CPU backend: device buffers are host memory, so the local
        # sort engine is the native stable radix sort — the same engine
        # class the reference picks for its in-RAM run sorts
        # (sort_algorithm_, api/sort.hpp). On TPU the jitted path below
        # runs instead.
        out = _host_radix_w1(mex, shards, key_fn, leaves, treedef, full)
        if out is not None:
            return out
        # single worker: one fused program — key-only argsort, then the
        # single payload gather. No samples, no splitters, no exchange.
        key1 = ("sort_w1", token, cap, full, treedef,
                tuple((l.dtype, l.shape[2:]) for l in leaves))

        def build_w1():
            def f(counts_dev, *ls):
                count = counts_dev[0, 0]
                tree = jax.tree.unflatten(treedef, [l[0] for l in ls])
                words = keymod.encode_key_words(key_fn(tree))
                iota = jnp.arange(cap, dtype=jnp.uint64)
                from ...core.device_sort import argsort_words
                if full:
                    sort_words = list(words) + [iota]
                else:
                    valid = jnp.arange(cap) < count
                    sort_words = ([(~valid).astype(jnp.uint32)]
                                  + list(words) + [iota])
                perm = argsort_words(sort_words)
                from ...core.rowmove import take_rows_multi
                return tuple(
                    o[None] for o in take_rows_multi([l[0] for l in ls],
                                                     perm))

            return mex.smap(f, 1 + len(leaves))

        f1 = mex.cached(key1, build_w1)
        out1 = f1(shards.counts_device(), *leaves)
        tree = jax.tree.unflatten(treedef, list(out1))
        return DeviceShards(mex, tree, shards.counts.copy())

    # ---- phase 1: key-only local argsort + quantile samples ----------
    # No payload touches the sort network: only (validity, key words,
    # global index) are sorted; the permutation is carried forward and
    # the payload is gathered once, later, per phase.
    key1 = ("sort_keys", token, cap, full, treedef,
            tuple((l.dtype, l.shape[2:]) for l in leaves))
    holder = {}

    def build1():
        def f(counts_dev, offset_dev, *ls):
            count = counts_dev[0, 0]
            tree = jax.tree.unflatten(treedef, [l[0] for l in ls])
            gidx = offset_dev[0, 0] + jnp.arange(cap, dtype=jnp.int64)
            words = keymod.encode_key_words(key_fn(tree))
            holder["nwords"] = len(words)
            from ...core.device_sort import argsort_words
            if full:
                sort_words = list(words) + [gidx.astype(jnp.uint64)]
            else:
                valid = jnp.arange(cap) < count
                sort_words = ([(~valid).astype(jnp.uint32)]
                              + list(words) + [gidx.astype(jnp.uint64)])
            perm = argsort_words(sort_words)
            words_s = [jnp.take(w, perm) for w in words]
            gidx_s = jnp.take(gidx, perm)
            # quantile positions over the valid prefix (sorted: valid
            # items occupy [0, count))
            qpos = quantile_positions(count, cap)
            sample_words = jnp.stack(
                [jnp.take(w, qpos) for w in words_s], axis=1)  # [S, nw]
            sample_idx = jnp.take(gidx_s, qpos)                # [S]
            sample_valid = qpos < count
            return (jnp.stack(words_s, 1)[None], gidx_s[None],
                    perm[None], sample_words[None], sample_idx[None],
                    sample_valid[None])

        return mex.smap(f, 2 + len(leaves)), holder

    f1, h1 = mex.cached(key1, build1)
    out1 = f1(shards.counts_device(),
              mex.put_small(offsets.astype(np.int64)[:, None]), *leaves)
    words_mat, gidx_s, perm_dev, s_words, s_idx, s_valid = out1
    nwords = h1["nwords"]

    # ---- host: choose splitters (the "worker 0" step) ----------------
    sw = mex.fetch(s_words).reshape(W * OVERSAMPLE, nwords)
    si = mex.fetch(s_idx).reshape(W * OVERSAMPLE)
    sv = mex.fetch(s_valid).reshape(W * OVERSAMPLE)
    samples = sorted(tuple(int(x) for x in sw[i]) + (int(si[i]),)
                     for i in range(len(sv)) if sv[i])
    splitters = choose_splitters(samples, W, nwords + 1)

    # ---- phase 2: classify on sorted keys + single payload gather ----
    # Items are key-sorted, so destinations (rank among splitters) are
    # monotone: no destination sort is needed — this replaces the
    # generic exchange's phase-A argsort entirely. Splitters are a
    # RUNTIME operand (replicated like the send-count matrix), never
    # baked into the cached executable.
    # the eventual carrier is {__gidx, __words, tree}: build matching
    # leaf templates up front so the phase-B narrowing's range analysis
    # (exchange.leaf_ranges_traced) can ride this classify program —
    # the data is already resident here, no extra pass
    carrier_templates, _ = jax.tree.flatten({
        "__words": words_mat, "__gidx": gidx_s,
        "tree": jax.tree.unflatten(treedef, list(leaves))})
    nidx3 = exchange.presorted_range_leaves(mex, cap, carrier_templates)
    key2 = ("sort_classify", token, W, cap, nwords, treedef, nidx3,
            tuple((l.dtype, l.shape[2:]) for l in leaves))

    def build2():
        def f(spl_a, words_a, gidx_a, perm_a, counts_dev, *ls):
            spl = spl_a[0]                        # [W-1, nwords+1]
            wm = words_a[0]                       # [cap, nwords] sorted
            gi = gidx_a[0]
            p = perm_a[0]
            count = counts_dev[0, 0]
            valid = jnp.arange(cap) < count       # sorted: valid first
            d = jnp.zeros(cap, dtype=jnp.int32)
            for j in range(W - 1):
                gt = _lex_greater(wm, gi.astype(jnp.uint64), spl[j])
                d = d + gt.astype(jnp.int32)
            dest = jnp.where(valid, d, W)
            all_send = exchange.send_counts(dest, W)
            # the ONE payload gather of this phase
            from ...core.rowmove import take_rows_multi
            sorted_ls = take_rows_multi([l[0] for l in ls], p)
            outs = (dest[None], all_send,
                    *[sl[None] for sl in sorted_ls])
            if nidx3:
                carrier = [gi, wm] + list(sorted_ls)
                outs = outs + (exchange.leaf_ranges_traced(
                    [carrier[li] for li in nidx3], valid),)
            return outs

        from jax.sharding import PartitionSpec as P
        out_specs = (P(AXIS), P()) + (P(AXIS),) * len(leaves)
        if nidx3:
            out_specs = out_specs + (P(),)
        return mex.smap(f, 5 + len(leaves), out_specs=out_specs)

    f2 = mex.cached(key2, build2)
    spl_dev = mex.put_small(np.broadcast_to(
        splitters, (W,) + splitters.shape).copy())
    out2 = f2(spl_dev, words_mat, gidx_s, perm_dev,
              shards.counts_device(), *leaves)
    sorted_dest, send_mat = out2[0], out2[1]
    if nidx3:
        sorted_payload = list(out2[2:-1])
        range_mat = out2[-1]
    else:
        sorted_payload = list(out2[2:])
        range_mat = None
    S = mex.fetch(send_mat)

    # fused dense path: ship + MERGE the received rank-ordered runs in
    # one program (no compaction scatter, no phase-3 re-sort).
    # THRILL_TPU_SORT_FUSED=0 forces the generic exchange + full
    # re-sort fallback (perf A/B diagnostics).
    import os
    fused_ok = os.environ.get("THRILL_TPU_SORT_FUSED", "1") != "0"
    if fused_ok and exchange.dense_all_to_all_applies(
            mex, S, exchange.leaf_item_bytes(sorted_payload)
            + 8 * (nwords + 1)):
        return _fused_exchange_merge(mex, sorted_dest, words_mat, gidx_s,
                                     sorted_payload, treedef, S, nwords,
                                     token)

    # carrier = words + gidx (already sorted, no gather needed) + payload
    carrier_tree = {
        "__words": words_mat, "__gidx": gidx_s,
        "tree": jax.tree.unflatten(treedef, sorted_payload),
    }
    carrier_leaves, treedef3 = jax.tree.flatten(carrier_tree)
    ranges = None if range_mat is None else mex._fetch_raw(range_mat)
    carrier = exchange.exchange_presorted(mex, treedef3, sorted_dest,
                                          carrier_leaves, S,
                                          ident=("sort_x", token),
                                          ranges=ranges)

    # ---- phase 3: merge received runs (keys-only sort + one gather) --
    cap3 = carrier.cap
    leaves3, _ = jax.tree.flatten(carrier.tree)
    key3 = ("sort_final", token, cap3, treedef3,
            tuple((l.dtype, l.shape[2:]) for l in leaves3))

    def build3():
        def f(counts_dev, *ls):
            count = counts_dev[0, 0]
            valid = jnp.arange(cap3) < count
            tree = jax.tree.unflatten(treedef3, [l[0] for l in ls])
            wm = tree["__words"]
            gi = tree["__gidx"]
            words = [wm[:, i] for i in range(nwords)]
            from ...core.device_sort import argsort_words
            invalid_word = (~valid).astype(jnp.uint32)
            perm = argsort_words([invalid_word] + words
                                 + [gi.astype(jnp.uint64)])
            # the ONE payload gather of this phase — all leaves batched
            # through one packed word matrix (core/rowmove.py)
            from ...core.rowmove import take_rows_multi
            out_leaves = take_rows_multi(
                jax.tree.leaves(tree["tree"]), perm)
            return tuple(l[None] for l in out_leaves)

        return mex.smap(f, 1 + len(leaves3))

    f3 = mex.cached(key3, build3)
    out3 = f3(carrier.counts_device(), *leaves3)
    tree = jax.tree.unflatten(treedef, list(out3))
    return DeviceShards(mex, tree, carrier.counts.copy())


def _host_radix_w1(mex, shards: DeviceShards, key_fn, leaves, treedef,
                   full: bool) -> Optional[DeviceShards]:
    """Single-worker sort on the CPU backend via the native stable LSD
    radix engine (core/host_radix.py). Returns None when inapplicable
    (non-CPU platform, native toolchain missing, or a key_fn that only
    works under tracing) so the caller falls through to the jitted
    engine."""
    from ...core import host_radix

    if not host_radix.eligible(mex):
        return None
    cap = shards.cap
    count = int(shards.counts[0])
    leaves_np = [np.asarray(l)[0] for l in leaves]       # [cap, ...]
    tree = jax.tree.unflatten(treedef, leaves_np)
    try:
        sort_words = keymod.encode_key_words_np(key_fn(tree))
    except Exception:
        return None                                      # trace-only key_fn
    if not full:
        # validity as the most significant word: invalid rows sort last;
        # radix stability keeps equal keys in global-index order, so no
        # iota tie-break word is needed
        sort_words = [(np.arange(cap) >= count).astype(np.uint64)] \
            + sort_words
    perm = host_radix.radix_argsort(sort_words)
    out_leaves = [
        host_radix.gather_rows(np.ascontiguousarray(l), perm)[None]
        for l in leaves_np]
    tree_out = jax.tree.unflatten(treedef,
                                  [mex.put(l) for l in out_leaves])
    return DeviceShards(mex, tree_out, shards.counts.copy())


def _fused_exchange_merge(mex, sorted_dest, words_mat, gidx_s,
                          sorted_payload, treedef, S: np.ndarray,
                          nwords: int, token) -> DeviceShards:
    """Phase 2.5+3 fused: scatter sends, all_to_all, then MERGE the W
    received runs — one jitted program, one payload gather.

    The received blocks land rank-ordered at static ``M_pad`` run
    boundaries, each run internally sorted by (key words, global index)
    — the sender classified over key-sorted items. Re-sorting them from
    scratch (the reference receivers sort run-by-run then multiway-merge,
    api/sort.hpp:665-699, 216-271) wastes the sortedness; here a bitonic
    merge tree over the run boundaries replaces both the phase-B
    compaction scatter and the phase-3 full sort. Falls back to the
    generic exchange + full sort for ragged/one-factor modes (those
    compact receives at dynamic boundaries).
    """
    from ...core.device_sort import (_impl, merge_sorted_runs,
                                     prepare_sort_words)
    W = mex.num_workers
    cap = sorted_dest.shape[1]
    R = S.sum(axis=0)
    new_counts = R.astype(np.int64)

    # capacity agreement — sticky like the generic dense exchange.
    # Sort's fused path always plans from the synced host S (splitter
    # agreement needs it anyway), so it is a plan build every time —
    # the plan store cannot elide it, only ratchet its capacities
    exchange.count_plan_build(mex)
    cap_ident = ("sort_fused_caps", token, cap, nwords, treedef,
                 tuple((l.dtype, l.shape[2:]) for l in sorted_payload))
    M_pad, out_cap = exchange._sticky_caps(
        mex, cap_ident, (max(int(S.max()), 1), max(int(R.max()), 1)))
    mex.stats_padded_rows += W * M_pad

    # carrier = payload + words matrix + gidx (the shipped columns);
    # the site tag keeps each Sort call site its own doctor skew
    # bucket (same convention as the generic exchange paths)
    exchange.account_traffic(
        mex, S, exchange.leaf_item_bytes(sorted_payload) + 8 * (nwords + 1),
        site="xchg:" + exchange._ident_digest(cap_ident)[:10])

    Wp = 1 << (W - 1).bit_length()                # runs padded to pow2
    Np = Wp * M_pad
    key = ("sort_fused", token, W, cap, M_pad, out_cap, nwords, treedef,
           tuple((l.dtype, l.shape[2:]) for l in sorted_payload))

    def build():
        def f(sdest, srow, scol, wm_a, gi_a, *ls):
            from ...core import rowmove
            d = sdest[0]
            S_row = srow[0]
            S_col = scol[0]
            send_idx = exchange.send_slot_index(d, S_row, W, M_pad, cap)

            def ship(x):
                return exchange.ship_blocks(x, send_idx, W, M_pad)

            wm_r = ship(wm_a[0])                  # [W*M_pad, nwords]
            gi_r = ship(gi_a[0])                  # [W*M_pad]
            # payload rides the exchange AND the final gather as packed
            # u32 words; unpacked only at the very end
            if rowmove.enabled():
                payload_p, pmetas = rowmove.pack_leaves(
                    [l[0] for l in ls])
            else:
                payload_p, pmetas = [l[0] for l in ls], [None] * len(ls)
            payload_r = [ship(p) for p in payload_p]

            j = jnp.arange(M_pad)[None, :]
            valid = (j < S_col[:, None]).reshape(-1)   # [W*M_pad]

            words = [wm_r[:, k] for k in range(nwords)]
            # validity as a native u32 word: _split_words_u32 keeps
            # non-u64 words single, so no dead zero hi-word rides along
            sort_words = ([(~valid).astype(jnp.uint32)] + words
                          + [gi_r.astype(jnp.uint64)])
            sort_words, idt = prepare_sort_words(sort_words, Np)
            iota = jnp.arange(Np, dtype=idt)

            # pad runs W -> Wp: invalid word 1 + max key words sorts the
            # synthetic runs after every real row (real invalid rows
            # carry zero key words from the recv buffer)
            def pad_rows(a):
                if Wp == W:
                    return a
                return jnp.concatenate(
                    [a, jnp.full(Np - W * M_pad, jnp.iinfo(a.dtype).max,
                                 a.dtype)])

            arrs = [pad_rows(w) for w in sort_words] + [iota]
            if _impl(Np) == "xla":
                res = lax.sort(tuple(arrs), dimension=0,
                               num_keys=len(arrs), is_stable=False)
                perm = res[-1][:out_cap].astype(jnp.int32)
            else:
                arrs = [a.reshape(Wp, M_pad) for a in arrs]
                merged = merge_sorted_runs(arrs)
                perm = merged[-1].reshape(-1)[:out_cap].astype(jnp.int32)

            # the ONE payload gather of this phase (clip: slots past the
            # valid total may point at synthetic pad rows)
            perm = jnp.minimum(perm, W * M_pad - 1)
            return tuple(
                rowmove.unpack_rows(jnp.take(p, perm, axis=0), m)[None]
                for p, m in zip(payload_r, pmetas))

        return mex.smap(f, 5 + len(sorted_payload))

    fb = mex.cached(key, build)
    srow = mex.put_small(S.astype(np.int32))
    scol = mex.put_small(S.T.copy().astype(np.int32))
    from ...common import trace as _trace
    with _trace.span_of(getattr(mex, "tracer", None), "exchange",
                        "sort_fused", m_pad=M_pad, out_cap=out_cap):
        out = fb(sorted_dest, srow, scol, words_mat, gidx_s,
                 *sorted_payload)
    tree = jax.tree.unflatten(treedef, list(out))
    return DeviceShards(mex, tree, new_counts)


def _lex_greater(words_mat: jnp.ndarray, gidx: jnp.ndarray,
                 splitter: jnp.ndarray) -> jnp.ndarray:
    """(words, gidx) > splitter lexicographically; [cap] bool."""
    nw = words_mat.shape[1]
    gt = jnp.zeros(words_mat.shape[0], dtype=bool)
    eq = jnp.ones(words_mat.shape[0], dtype=bool)
    for i in range(nw):
        w = words_mat[:, i]
        gt = gt | (eq & (w > splitter[i]))
        eq = eq & (w == splitter[i])
    gt = gt | (eq & (gidx.astype(jnp.uint64) > splitter[nw]))
    return gt


def Sort(dia: DIA, key_fn=None, compare_fn=None, stable=False) -> DIA:
    return DIA(SortNode(dia.context, dia._link(), key_fn, compare_fn,
                        stable))
