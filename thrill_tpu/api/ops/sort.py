"""Distributed sample sort.

Reference: thrill/api/sort.hpp:64 — PreOp reservoir-samples while
spilling; MainOp gathers samples on worker 0, picks p-1 splitters,
classifies every item down a branchless splitter tree into per-worker
stream writers (tie-break by global index for balance on equal keys,
api/sort.hpp:487-502); receivers sort runs and multiway-merge.

TPU-native design, three bulk-synchronous device programs:
 1. sample:   local XLA sort + quantile sampling of (key words, global
              index) pairs -> tiny host gather (the worker-0 splitter
              step collapses to the single controller).
 2. exchange: destination = lexicographic rank among splitters
              ((words, index) compare, so duplicate keys spread evenly
              across workers exactly like the reference's tie-break),
              then the padded all-to-all shuffle.
 3. merge:    one local XLA sort of the received items (stable by
              original index) — the analog of sort-runs + multiway
              merge, executed as a single bitonic sort on-device.

The result is globally sorted across worker ranks and stable: equal
keys keep their original global order, making Sort and SortStable one
code path (the reference needs a separate CatStream variant).
"""

from __future__ import annotations

from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...core import keys as keymod
from ...core import segmented
from ...data import exchange
from ...data.shards import DeviceShards, HostShards
from ..dia import DIA
from ..dia_base import DIABase

OVERSAMPLE = 32  # samples per worker; splitter error ~ 1/OVERSAMPLE


class SortNode(DIABase):
    def __init__(self, ctx, link, key_fn: Optional[Callable],
                 compare_fn: Optional[Callable], stable: bool) -> None:
        super().__init__(ctx, "Sort", [link])
        self.key_fn = key_fn or (lambda x: x)
        self.compare_fn = compare_fn
        self.stable = stable

    def compute(self):
        shards = self.parents[0].pull()
        if isinstance(shards, HostShards):
            return self._compute_host(shards)
        if self.compare_fn is not None:
            return self._compute_host(shards.to_host_shards())
        return _device_sample_sort(shards, self.key_fn,
                                   (self.key_fn,))

    # above this many items the host path sorts external-memory style:
    # sorted runs spilled to Files, k-way merged (reference:
    # SortAndWriteToFile + PartialMultiwayMerge, api/sort.hpp:665-699,
    # 216-271). Overridable for tests via THRILL_TPU_HOST_SORT_RUN.
    HOST_RUN_SIZE = 1 << 20

    def _compute_host(self, shards: HostShards):
        import functools
        import os
        W = shards.num_workers
        if self.compare_fn is not None:
            sort_key = functools.cmp_to_key(
                lambda a, b: -1 if self.compare_fn(a, b)
                else (1 if self.compare_fn(b, a) else 0))
        else:
            sort_key = self.key_fn

        run_size = int(os.environ.get("THRILL_TPU_HOST_SORT_RUN") or
                       self.HOST_RUN_SIZE)
        run_size = max(run_size, 16)
        n = shards.total
        if n <= run_size:
            items = [it for l in shards.lists for it in l]
            items.sort(key=sort_key)
        else:
            try:
                items = self._em_sort(shards, sort_key, run_size)
            except (TypeError, ValueError, AttributeError):
                # unpicklable items cannot spill; fall back in-memory
                items = [it for l in shards.lists for it in l]
                items.sort(key=sort_key)
        bounds = [(w * n) // W for w in range(W + 1)]
        return HostShards(W, [items[bounds[w]:bounds[w + 1]]
                              for w in range(W)])

    def _em_sort(self, shards: HostShards, sort_key, run_size: int):
        """External-memory sort: spill sorted runs, k-way merge them.

        When this node owns the input exclusively (the consuming pull
        disposed the parent), shard lists are released as they spill so
        the spilled copy replaces — not duplicates — the resident items.
        """
        from ...data.block_pool import BlockPool
        from ...core.multiway_merge import multiway_merge_files

        owns_input = self.parents[0].node.state == "DISPOSED"
        pool = BlockPool(spill_dir=self.context.config.spill_dir,
                         soft_limit=64 << 20)
        files = []
        run = []
        try:
            for lst in shards.lists:
                for it in lst:
                    run.append(it)
                    if len(run) >= run_size:
                        files.append(_spill_run(pool, run, sort_key))
                        run = []
                if owns_input:
                    lst.clear()
            if run:
                files.append(_spill_run(pool, run, sort_key))
            merged = list(multiway_merge_files(files, key=sort_key,
                                               consume=True))
        finally:
            for f in files:
                f.clear()
            pool.close()
        return merged


def _spill_run(pool, run, sort_key):
    from ...data.file import File
    run.sort(key=sort_key)
    f = File(pool=pool)
    with f.writer() as w:
        for it in run:
            w.put(it)
    return f


def _device_sample_sort(shards: DeviceShards, key_fn: Callable,
                        token) -> DeviceShards:
    mex = shards.mesh_exec
    W = mex.num_workers
    cap = shards.cap
    leaves, treedef = jax.tree.flatten(shards.tree)
    total = shards.total
    if total == 0:
        return shards

    # global index offsets (host-known counts -> exclusive prefix)
    offsets = np.concatenate([[0], np.cumsum(shards.counts)])[:-1]

    # ---- phase 1: local sort + quantile samples ----------------------
    key1 = ("sort_sample", token, cap, treedef,
            tuple((l.dtype, l.shape[2:]) for l in leaves))
    holder = {}

    def build1():
        def f(counts_dev, offset_dev, *ls):
            count = counts_dev[0, 0]
            valid = jnp.arange(cap) < count
            tree = jax.tree.unflatten(treedef, [l[0] for l in ls])
            gidx = offset_dev[0, 0] + jnp.arange(cap, dtype=jnp.int64)
            words = keymod.encode_key_words(key_fn(tree))
            holder["nwords"] = len(words)
            words, tree, valid, extra = segmented.sort_by_key_words(
                words, tree, valid, [gidx.astype(jnp.uint64)])
            gidx_sorted = extra[0]
            # quantile positions over the valid prefix
            count_f = jnp.maximum(count, 1)
            qpos = ((jnp.arange(OVERSAMPLE, dtype=jnp.int64) * 2 + 1)
                    * count_f // (2 * OVERSAMPLE))
            qpos = jnp.clip(qpos, 0, cap - 1)
            sample_words = jnp.stack(
                [jnp.take(w, qpos) for w in words], axis=1)  # [S, nw]
            sample_idx = jnp.take(gidx_sorted, qpos)         # [S]
            sample_valid = qpos < count
            out_leaves = jax.tree.leaves(tree)
            return (jnp.stack(words, 1)[None],
                    gidx_sorted[None],
                    sample_words[None], sample_idx[None], sample_valid[None],
                    *[l[None] for l in out_leaves])

        return mex.smap(f, 2 + len(leaves)), holder

    f1, h1 = mex.cached(key1, build1)
    out1 = f1(shards.counts_device(),
              mex.put(offsets.astype(np.int64)[:, None]), *leaves)
    words_mat, gidx_s, s_words, s_idx, s_valid = out1[:5]
    sorted_leaves = list(out1[5:])
    nwords = h1["nwords"]

    # ---- host: choose splitters (the "worker 0" step) ----------------
    sw = np.asarray(s_words).reshape(W * OVERSAMPLE, nwords)
    si = np.asarray(s_idx).reshape(W * OVERSAMPLE)
    sv = np.asarray(s_valid).reshape(W * OVERSAMPLE)
    samples = [(tuple(int(x) for x in sw[i]), int(si[i]))
               for i in range(len(sv)) if sv[i]]
    samples.sort()
    splitters = np.zeros((max(W - 1, 1), nwords + 1), dtype=np.uint64)
    if samples and W > 1:
        for j in range(1, W):
            s = samples[min(len(samples) - 1, (j * len(samples)) // W)]
            splitters[j - 1, :nwords] = np.array(s[0], dtype=np.uint64)
            splitters[j - 1, nwords] = np.uint64(s[1])

    if W == 1:
        tree = jax.tree.unflatten(treedef, sorted_leaves)
        return DeviceShards(mex, tree, shards.counts.copy())

    # ---- phase 2: classify + exchange --------------------------------
    # destination = number of splitters strictly below (words, gidx)
    spl = jnp.asarray(splitters)  # [W-1, nwords+1]

    sorted_tree_full = {
        "__words": words_mat, "__gidx": gidx_s,
        "tree": jax.tree.unflatten(treedef, sorted_leaves),
    }
    carrier = DeviceShards(mex, sorted_tree_full, shards.counts.copy())

    def dest(tree, mask, widx):
        wm = tree["__words"]            # [cap, nwords]
        gi = tree["__gidx"].astype(jnp.uint64)
        d = jnp.zeros(wm.shape[0], dtype=jnp.int32)
        for j in range(W - 1):
            gt = _lex_greater(wm, gi, spl[j])
            d = d + gt.astype(jnp.int32)
        return d

    carrier = exchange.exchange(carrier, dest,
                                ("sort_dest", token, W, cap))

    # ---- phase 3: final local merge (stable by global index) ---------
    cap3 = carrier.cap
    leaves3, treedef3 = jax.tree.flatten(carrier.tree)
    key3 = ("sort_final", token, cap3, treedef3,
            tuple((l.dtype, l.shape[2:]) for l in leaves3))

    def build3():
        def f(counts_dev, *ls):
            count = counts_dev[0, 0]
            valid = jnp.arange(cap3) < count
            tree = jax.tree.unflatten(treedef3, [l[0] for l in ls])
            wm = tree["__words"]
            gi = tree["__gidx"]
            words = [wm[:, i] for i in range(nwords)]
            words, t_sorted, valid, extra = segmented.sort_by_key_words(
                words, tree["tree"], valid, [gi.astype(jnp.uint64)])
            out_leaves = jax.tree.leaves(t_sorted)
            return tuple(l[None] for l in out_leaves)

        return mex.smap(f, 1 + len(leaves3))

    f3 = mex.cached(key3, build3)
    out3 = f3(carrier.counts_device(), *leaves3)
    tree = jax.tree.unflatten(treedef, list(out3))
    return DeviceShards(mex, tree, carrier.counts.copy())


def _lex_greater(words_mat: jnp.ndarray, gidx: jnp.ndarray,
                 splitter: jnp.ndarray) -> jnp.ndarray:
    """(words, gidx) > splitter lexicographically; [cap] bool."""
    nw = words_mat.shape[1]
    gt = jnp.zeros(words_mat.shape[0], dtype=bool)
    eq = jnp.ones(words_mat.shape[0], dtype=bool)
    for i in range(nw):
        w = words_mat[:, i]
        gt = gt | (eq & (w > splitter[i]))
        eq = eq & (w == splitter[i])
    gt = gt | (eq & (gidx.astype(jnp.uint64) > splitter[nw]))
    return gt


def Sort(dia: DIA, key_fn=None, compare_fn=None, stable=False) -> DIA:
    return DIA(SortNode(dia.context, dia._link(), key_fn, compare_fn,
                        stable))
