"""Actions: DAG sinks that trigger execution and return results.

Reference: thrill/api/size.hpp:28 (local count + AllReduce),
all_gather.hpp:28, gather.hpp:28, all_reduce.hpp:28, sum.hpp, min.hpp,
max.hpp, print.hpp. On the device path reductions run as one jitted
SPMD program (masked local fold + psum/pmax/pmin over the mesh axis) —
the analog of local fold + FlowControlChannel::AllReduce.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ...data import multiplexer
from ...data.shards import DeviceShards, HostShards
from ...parallel.mesh import AXIS


def _pull(dia, consume: bool = True):
    return dia._link().pull(consume)


def Size(dia) -> int:
    shards = _pull(dia)
    if isinstance(shards, HostShards):
        return multiplexer.global_total(dia.context.mesh_exec, shards)
    return int(shards.counts.sum())


def AllGather(dia) -> list:
    shards = _pull(dia)
    if isinstance(shards, DeviceShards):
        shards = shards.to_host_shards("allgather-action")
    return multiplexer.all_items(dia.context.mesh_exec, shards)


def AllGatherArrays(dia):
    """Columnar egress: the DIA's items as ONE pytree of stacked
    arrays, leaves ``[total, ...]``. On the device path the leaves are
    DEVICE arrays assembled by async slicing — no host fetch, no
    per-item boxing — so an iterative driver (the k-means centroid
    update) can compute on the result and feed it straight back into
    the next ``Bind`` without ever leaving jax's dispatch stream.
    TPU-native extension: the reference's AllGather materializes a
    std::vector of items host-side (api/all_gather.hpp:28), which on a
    tunneled chip costs a link round trip per iteration.

    Host-storage DIAs return numpy-stacked leaves (same tree shape);
    an EMPTY host-storage DIA returns ``[]`` (item structure is
    unknowable without items — the device path, whose columns carry
    their structure, returns zero-length leaves instead). Scalar items
    come back as a single stacked array."""
    shards = _pull(dia)
    mex = dia.context.mesh_exec
    # device-native egress never goes through mex.fetch on a single
    # controller: drain deferred validations here so a hinted-join
    # overflow can never ride out through columnar results
    mex.drain_checks()
    if isinstance(shards, HostShards):
        items = multiplexer.all_items(mex, shards)
        if not items:
            return items
        return jax.tree.map(lambda *ls: np.stack(ls), *items)
    counts = shards.counts               # host plan values (often known)
    W = len(counts)
    tree = shards.tree
    if multiplexer.multiprocess(mex):
        # leaves span non-addressable devices: realize on every
        # controller (numpy result — the zero-sync device contract
        # only holds single-controller, where the tunnel RTT lives)
        tree = jax.tree.map(mex.fetch, tree)

    leaves, treedef = jax.tree.flatten(tree)
    if mex.loop_recorder is not None and leaves \
            and all(isinstance(l, jax.Array) for l in leaves):
        # under an armed LoopPlan recorder (api/loop.py capture), run
        # the egress as ONE cached program (slice valid prefixes,
        # all_gather, concatenate): the whole action is then a
        # RECORDABLE dispatch, so iterative drivers that close their
        # loop through AllGatherArrays (k-means centroids) replay
        # device-resident. Outside a capture the eager slicing below
        # is equivalent (and compiles nothing), so dispatch budgets
        # are untouched. Keyed on the counts vector — static shapes;
        # loop-invariant counts compile once.
        from jax.sharding import PartitionSpec as P
        cap = shards.cap
        cnt = tuple(int(c) for c in counts)
        key = ("allgather_arrays", cap, cnt, treedef,
               tuple((l.dtype, l.shape[2:]) for l in leaves))

        def build():
            def f(*ls):
                outs = []
                for l in ls:
                    g = lax.all_gather(l[0], AXIS)      # [W, cap, ...]
                    parts = [g[w, :cnt[w]] for w in range(W) if cnt[w]]
                    outs.append(jnp.concatenate(parts, axis=0)
                                if parts else g[0, :0])
                return tuple(outs)

            return mex.smap(f, len(leaves), out_specs=P())

        fn = mex.cached(key, build)
        return jax.tree.unflatten(treedef, list(fn(*leaves)))

    def cat(leaf):
        parts = [leaf[w, :int(counts[w])] for w in range(W)
                 if int(counts[w])]
        if not parts:
            return leaf[0, :0]
        if len(parts) == 1:
            return parts[0]
        xp = np if isinstance(leaf, np.ndarray) else jnp
        return xp.concatenate(parts, axis=0)

    return jax.tree.map(cat, tree)


def Gather(dia, root: int = 0) -> list:
    """Items of the whole DIA, delivered to worker ``root`` only
    (reference: api/gather.hpp:28). Single-controller runs ARE every
    worker, so they receive the list; in multi-controller runs only the
    process hosting worker ``root`` gets the items — the others get []
    (the reference's non-root workers likewise emit nothing)."""
    shards = _pull(dia)
    mex = dia.context.mesh_exec
    mex.drain_checks()                   # egress: no unrun validations
    root = root % max(mex.num_workers, 1)
    if isinstance(shards, DeviceShards):
        shards = shards.to_host_shards("gather-action")
    if multiplexer.multiprocess(mex):
        owner = int(mex.worker_process[root])
        items = multiplexer.all_items(mex, shards)
        return items if owner == mex.process_index else []
    return [it for l in shards.lists for it in l]


def Print(dia, label: str = "", limit: int = 100) -> None:
    items = AllGather(dia)
    head = items[:limit]
    suffix = f" ... (+{len(items) - limit} more)" if len(items) > limit else ""
    print(f"[{label or 'DIA'}] n={len(items)}: {head}{suffix}")


def _device_reduce(shards: DeviceShards, mode: str,
                   keep_device: bool = False):
    """One SPMD program: masked local fold + cross-worker collective.

    ``keep_device``: return the reduced leaves as (replicated) DEVICE
    arrays with no host fetch — iterative drivers feed them straight
    back into a Bind (the SGD/logistic-regression update pattern)."""
    mex = shards.mesh_exec
    cap = shards.cap
    leaves, treedef = jax.tree.flatten(shards.tree)
    key = ("reduce_action", mode, cap, treedef,
           tuple((l.dtype, l.shape[2:]) for l in leaves))

    def build():
        def f(counts_dev, *ls):
            mask = jnp.arange(cap) < counts_dev[0, 0]
            outs = []
            for l in ls:
                x = l[0]
                m = mask.reshape(mask.shape + (1,) * (x.ndim - 1))
                if mode == "sum":
                    local = jnp.sum(jnp.where(m, x, 0), axis=0)
                    outs.append(lax.psum(local, AXIS))
                elif mode == "min":
                    big = _dtype_max(x.dtype)
                    local = jnp.min(jnp.where(m, x, big), axis=0)
                    outs.append(lax.pmin(local, AXIS))
                else:
                    small = _dtype_min(x.dtype)
                    local = jnp.max(jnp.where(m, x, small), axis=0)
                    outs.append(lax.pmax(local, AXIS))
            return tuple(outs)

        from jax.sharding import PartitionSpec as P
        return mex.smap(f, 1 + len(leaves), out_specs=P())

    fn = mex.cached(key, build)
    out = fn(shards.counts_device(), *leaves)
    if keep_device:
        return jax.tree.unflatten(treedef, list(out))
    vals = [mex.fetch(o) for o in out]
    vals = [v.item() if v.ndim == 0 else v for v in vals]
    return jax.tree.unflatten(treedef, vals)


def _dtype_max(dt):
    return jnp.inf if jnp.issubdtype(dt, jnp.floating) else jnp.iinfo(dt).max


def _dtype_min(dt):
    return -jnp.inf if jnp.issubdtype(dt, jnp.floating) else jnp.iinfo(dt).min


def Sum(dia, initial: Any = 0, device: bool = False) -> Any:
    """``device=True`` (device-storage DIAs): return the summed pytree
    as replicated DEVICE arrays, no host fetch — feed it straight back
    into a ``Bind`` (zero-sync iterative loops). Single-controller
    only by contract: on a multi-process mesh the request falls back
    to the fetched path (the device result would span non-addressable
    devices and fail confusingly under eager math / np.asarray)."""
    shards = _pull(dia)
    if device and multiplexer.multiprocess(dia.context.mesh_exec):
        device = False
    if device:
        # device-array egress bypasses mex.fetch: run deferred
        # validations before handing columns back to the caller
        dia.context.mesh_exec.drain_checks()
    if isinstance(shards, DeviceShards):
        # Single-controller with device-resident counts: SKIP the
        # empty-guard — forcing a counts sync here would stall
        # iterative loops (SGD's per-round sampled batch), and the
        # masked device reduce returns exact zeros for empty shards
        # anyway. Multi-controller keeps the eager guard: there the
        # counts fetch is a cheap collective the group performs in
        # lock-step, while skipping it costs far more (per-shape
        # reduce compiles + a process_allgather of the result for
        # sums that used to early-return — measured 7x on the
        # 2-process fuzz suite).
        lazy = shards._counts_host is None and \
            not multiplexer.multiprocess(dia.context.mesh_exec)
        if not lazy and shards.total == 0:
            return initial
        reduced = _device_reduce(shards, "sum", keep_device=device)
        if initial is None or (np.isscalar(initial) and initial == 0):
            return reduced
        # fold the initial value like the host path does; accept either
        # a matching pytree or a scalar broadcast over all leaves
        try:
            return jax.tree.map(lambda r, i: r + i, reduced, initial)
        except ValueError:
            return jax.tree.map(lambda r: r + initial, reduced)
    mex = dia.context.mesh_exec
    items = [it for l in shards.lists for it in l]
    if multiplexer.multiprocess(mex):
        local = functools.reduce(lambda a, b: a + b, items) if items \
            else None
        try:
            merged = multiplexer.net_fold(mex, local,
                                          lambda a, b: a + b,
                                          empty=not items)
        except ValueError:
            return initial
        return merged if initial is None else initial + merged
    return functools.reduce(lambda a, b: a + b, items, initial)


def MinMax(dia, is_min: bool) -> Any:
    shards = _pull(dia)
    if isinstance(shards, DeviceShards):
        if shards.total == 0:
            raise ValueError("Min/Max of empty DIA")
        return _device_reduce(shards, "min" if is_min else "max")
    mex = dia.context.mesh_exec
    items = [it for l in shards.lists for it in l]
    if multiplexer.multiprocess(mex):
        local = (min(items) if is_min else max(items)) if items else None
        try:
            return multiplexer.net_fold(
                mex, local, (lambda a, b: min(a, b)) if is_min
                else (lambda a, b: max(a, b)), empty=not items)
        except ValueError:
            raise ValueError("Min/Max of empty DIA")
    if not items:
        raise ValueError("Min/Max of empty DIA")
    return min(items) if is_min else max(items)


def AllReduce(dia, fn: Callable, initial: Any = None) -> Any:
    """Generic associative fold over all items (any storage)."""
    items = AllGather(dia)
    if not items:
        if initial is None:
            raise ValueError("AllReduce of empty DIA without initial")
        return initial
    acc = items[0] if initial is None else fn(initial, items[0])
    for it in items[1:]:
        acc = fn(acc, it)
    return acc
