"""Concat and Rebalance: even redistribution preserving global order.

Reference: thrill/api/concat.hpp:35 (globally rebalanced concatenation)
and rebalance.hpp:30 (even redistribution after skew, e.g. Filter).

Device path: items carry their target global index; the exchange routes
them to the worker owning that index under an even split, and a local
sort by carried index restores order (the analog of the reference's
CatStream rank-ordered concatenation). This is the same halo-free
"sequence re-sharding" primitive that long-sequence pipelines use to
re-balance 1-D sharded token streams.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from ...data import exchange
from ...data.shards import DeviceShards, HostShards
from ..dia import DIA
from ..dia_base import DIABase
from ...common.partition import dense_range_bounds


def rebalance_to_even(mex, parts: List[DeviceShards], token) -> DeviceShards:
    """Concatenate device shard groups in order, evenly re-split.

    Each part keeps its internal worker-major order; parts concatenate in
    list order. One carrier exchange + one order-restoring local sort.
    """
    W = mex.num_workers
    # global index base for each (part, worker)
    n_total = 0
    carriers = []
    for pi, p in enumerate(parts):
        offs = np.concatenate([[0], np.cumsum(p.counts)])[:-1] + n_total
        n_total += p.total
        cap = p.cap
        leaves, treedef = jax.tree.flatten(p.tree)
        key = ("concat_tag", token, pi, cap, treedef,
               tuple((l.dtype, l.shape[2:]) for l in leaves))
        holder = {}

        def build(cap=cap, treedef=treedef, holder=holder, nleaves=len(leaves)):
            def f(off, *ls):
                g = off[0, 0] + jnp.arange(cap, dtype=jnp.int64)
                tree = jax.tree.unflatten(treedef, [l[0] for l in ls])
                out = {"__gidx": g, "tree": tree}
                out_leaves, out_td = jax.tree.flatten(out)
                holder["treedef"] = out_td
                return tuple(l[None] for l in out_leaves)
            return mex.smap(f, 1 + nleaves), holder

        fn, h = mex.cached(key, build)
        out = fn(mex.put_small(offs.astype(np.int64)[:, None]), *leaves)
        tree = jax.tree.unflatten(h["treedef"], list(out))
        carriers.append(DeviceShards(mex, tree, p.counts.copy()))

    merged = _local_concat(carriers) if len(carriers) > 1 else carriers[0]

    bounds = dense_range_bounds(n_total, W)
    bdev = jnp.asarray(bounds[1:])

    def dest(tree, mask, widx):
        g = tree["__gidx"]
        return jnp.searchsorted(bdev, g, side="right").astype(jnp.int32)

    merged = exchange.exchange(merged, dest, ("concat_dest", token, W))
    merged.validate_pending()       # optimistic-exchange heal point

    # restore order by global index, then drop the index column
    cap = merged.cap
    leaves, treedef = jax.tree.flatten(merged.tree)
    key = ("concat_order", token, cap, treedef,
           tuple((l.dtype, l.shape[2:]) for l in leaves))
    holder2 = {}

    def build2():
        def f(counts_dev, *ls):
            count = counts_dev[0, 0]
            valid = jnp.arange(cap) < count
            tree = jax.tree.unflatten(treedef, [l[0] for l in ls])
            g = tree["__gidx"].astype(jnp.uint64)
            g = jnp.where(valid, g, jnp.uint64(2 ** 63))
            from ...core.device_sort import argsort_words
            order = argsort_words([g])
            out_tree = jax.tree.map(lambda l: jnp.take(l, order, axis=0),
                                    tree["tree"])
            out_leaves, out_td = jax.tree.flatten(out_tree)
            holder2["treedef"] = out_td
            return tuple(l[None] for l in out_leaves)

        return mex.smap(f, 1 + len(leaves)), holder2

    fn2, h2 = mex.cached(key, build2)
    out = fn2(merged.counts_device(), *leaves)
    tree = jax.tree.unflatten(h2["treedef"], list(out))
    return DeviceShards(mex, tree, merged.counts.copy())


def _local_concat(parts: List[DeviceShards]) -> DeviceShards:
    """Per-worker concatenation (valid items compacted to the front)."""
    mex = parts[0].mesh_exec
    caps = [p.cap for p in parts]
    treedefs = [jax.tree.structure(p.tree) for p in parts]
    assert all(td == treedefs[0] for td in treedefs), \
        "Concat/Union requires matching schemas"
    total_cap = sum(caps)
    all_leaves = [jax.tree.flatten(p.tree)[0] for p in parts]
    key = ("local_concat", tuple(caps),
           tuple((l.dtype, l.shape[2:]) for l in all_leaves[0]))

    def build():
        def f(*flat):
            k = len(all_leaves[0])
            counts = flat[:len(parts)]
            trees = []
            i = len(parts)
            for caps_i in caps:
                trees.append([x[0] for x in flat[i:i + k]])
                i += k
            outs = []
            for li in range(k):
                segs = []
                pos = []
                offset = jnp.int64(0)
                for pi, cap_i in enumerate(caps):
                    c = counts[pi][0, 0]
                    idx = jnp.arange(cap_i, dtype=jnp.int64)
                    valid = idx < c
                    p_ = jnp.where(valid, offset + idx, total_cap)
                    segs.append(trees[pi][li])
                    pos.append(p_)
                    offset = offset + c
                leaf0 = segs[0]
                buf = jnp.zeros((total_cap + 1,) + leaf0.shape[1:],
                                leaf0.dtype)
                for s, p_ in zip(segs, pos):
                    buf = buf.at[p_].set(s)
                outs.append(buf[:total_cap][None])
            return tuple(outs)

        return mex.smap(f, len(parts) * (1 + len(all_leaves[0])))

    # args: counts for each part, then leaves of each part
    fn = mex.cached(key, build)
    args = [p.counts_device() for p in parts]
    for ls in all_leaves:
        args.extend(ls)
    out = fn(*args)
    tree = jax.tree.unflatten(treedefs[0], list(out))
    counts = np.sum([p.counts for p in parts], axis=0).astype(np.int64)
    return DeviceShards(mex, tree, counts)


class ConcatNode(DIABase):
    def __init__(self, ctx, links) -> None:
        super().__init__(ctx, "Concat", links)

    def compute(self):
        pulls = [l.pull() for l in self.parents]
        if any(isinstance(p, HostShards) for p in pulls):
            pulls = [p.to_host_shards("concat-mixed-storage") if isinstance(p, DeviceShards)
                     else p for p in pulls]
            from ...data import multiplexer
            mex = self.context.mesh_exec
            pulls = [multiplexer.ensure_replicated(mex, p, "concat-host")
                     for p in pulls]
            W = pulls[0].num_workers
            flat = [it for p in pulls for l in p.lists for it in l]
            bounds = dense_range_bounds(len(flat), W).tolist()
            return multiplexer.localize(
                mex, HostShards(W, [flat[bounds[w]:bounds[w + 1]]
                                    for w in range(W)]))
        return rebalance_to_even(self.context.mesh_exec, pulls, (self.id,))


class RebalanceNode(DIABase):
    def __init__(self, ctx, link) -> None:
        super().__init__(ctx, "Rebalance", [link])

    def compute(self):
        shards = self.parents[0].pull()
        if isinstance(shards, HostShards):
            from ...data import multiplexer
            mex = self.context.mesh_exec
            shards = multiplexer.ensure_replicated(mex, shards,
                                                   "rebalance-host")
            W = shards.num_workers
            flat = [it for l in shards.lists for it in l]
            bounds = dense_range_bounds(len(flat), W).tolist()
            return multiplexer.localize(
                mex, HostShards(W, [flat[bounds[w]:bounds[w + 1]]
                                    for w in range(W)]))
        return rebalance_to_even(self.context.mesh_exec, [shards],
                                 (self.id,))


def Concat(a: DIA, b: DIA) -> DIA:
    return DIA(ConcatNode(a.context, [a._link(), b._link()]))


def ConcatMany(dias: List[DIA]) -> DIA:
    assert dias
    return DIA(ConcatNode(dias[0].context, [d._link() for d in dias]))
