"""Rebalance op wrapper (node lives in concat.py)."""

from __future__ import annotations

from ..dia import DIA
from .concat import RebalanceNode


def Rebalance(dia: DIA) -> DIA:
    return DIA(RebalanceNode(dia.context, dia._link()))
