"""InnerJoin.

Reference: thrill/api/inner_join.hpp:61 — hash-partition both sides,
local merge-join after sorting spilled files (optional LocationDetection
to skip shipping unmatched keys).

Device path: both sides exchange by the same key hash, then one jitted
local sort-merge-join per worker: sort left and right by key words,
count per-right-item match runs, a host capacity agreement sizes the
pair expansion, and a second jitted program gathers the (left, right)
pairs and applies ``join_fn`` batched. The expansion indices come from
searchsorted over the pair-offset cumsum — branch-free, static shapes.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ...common import hashing
from ...common.partition import dense_range_bounds
from ...parallel.mesh import AXIS
from ...core import keys as keymod
from ...core import segmented
from ...data import exchange
from ...data.shards import DeviceShards, HostShards
from ...common.config import round_up_pow2
from ..dia import DIA
from ..dia_base import DIABase


class InnerJoinNode(DIABase):
    def __init__(self, ctx, llink, rlink, lkey, rkey, join_fn,
                 location_detection=None,
                 out_size_hint=None, dense_right_index=None) -> None:
        super().__init__(ctx, "InnerJoin", [llink, rlink])
        if dense_right_index is not None and rkey is not None:
            # the dense contract DEFINES the right key as the row's
            # global position; a caller-supplied right key would be
            # honored by the host path but ignored by the device
            # gather — storage-dependent results, so refuse it
            raise ValueError(
                "InnerJoin: dense_right_index defines the right key as "
                "the row's dense position; right_key_fn must be None")
        self.lkey = lkey
        self.rkey = rkey
        self.join_fn = join_fn
        # DENSE INDEX JOIN contract: the right side is a dense table of
        # exactly ``dense_right_index`` rows whose key at global
        # position g is g (a ZipWithIndex over a ReduceToIndex/Generate
        # table — the PageRank rank/degree tables). The join is then a
        # pure GATHER: no sort, no hash, no exchange — the device
        # program all_gathers the (small) right table and indexes it by
        # the left keys. O(n) like the numpy proxy's fancy-indexing,
        # where the generic sort-merge join pays two XLA argsorts per
        # call (~43 ms each at 64 k rows on XLA:CPU). Out-of-range left
        # keys simply produce no pair (inner-join semantics); there is
        # no overflow to detect, so no deferred check and no size sync
        # at ANY worker count.
        self.dense_right_index = (None if dense_right_index is None
                                  else int(dense_right_index))
        # reference: LocationDetectionTag, api/inner_join.hpp:161-190 —
        # prune items whose key hash exists on only one side before the
        # shuffle. None (the default) = decided by the plan-time cost
        # model (core/preshuffle.py: estimated fingerprint bytes vs
        # estimated pruned row bytes, fed by the learned per-site
        # exchange capacities); True/False force it like the
        # reference's explicit tag
        self.location_detection = location_detection
        # PER-WORKER output capacity hint: when the caller knows an
        # upper bound on each worker's match count (index joins with
        # known multiplicity — PageRank's edges-by-src join emits
        # exactly one pair per edge), the device path skips its
        # blocking device->host size sync and keeps the whole join in
        # jax's async-dispatch stream. On a tunneled chip that sync is
        # a full link RTT per join per iteration (BASELINE.md r5).
        # Overflow is detected before any consumer reads the columns
        # and recovers by re-running the expansion un-hinted (or raises
        # with THRILL_TPU_JOIN_RECOVER=0 — never silently truncates).
        # TPU-native extension:
        # the reference sizes from its spilled files host-side
        # (api/inner_join.hpp:208) and has no such sync to skip.
        self.out_size_hint = out_size_hint

    def compute(self):
        left = self.parents[0].pull()
        right = self.parents[1].pull()
        if isinstance(left, HostShards) or isinstance(right, HostShards):
            return self._compute_host(left, right)
        return self._compute_device(left, right)

    # -- host path ------------------------------------------------------
    def _compute_host(self, left, right):
        if isinstance(left, DeviceShards):
            left = left.to_host_shards("join-host-path")
        if isinstance(right, DeviceShards):
            right = right.to_host_shards("join-host-path")
        W = left.num_workers
        mex = self.context.mesh_exec
        from ...data import multiplexer
        lkey, rkey, jfn = self.lkey, self.rkey, self.join_fn
        if self.dense_right_index is not None and rkey is None:
            # dense-index contract on the host path: the right key IS
            # the row's global position in the dense table (the device
            # gather's addressing), so enumerate and join on that.
            # Worker w's first row sits at dense_range_bounds[w] BY THE
            # CONTRACT — never at the cumulative length of the
            # preceding lists, which is wrong multi-controller (the
            # host-storage invariant keeps non-local workers' lists
            # empty, so cumulative offsets would collapse toward 0)
            bounds = dense_range_bounds(self.dense_right_index,
                                        W).tolist()
            enum_lists = []
            for w, items in enumerate(right.lists):
                enum_lists.append([(bounds[w] + i, it)
                                   for i, it in enumerate(items)])
            right = HostShards(W, enum_lists)
            inner = jfn
            rkey = _enum_key
            jfn = lambda l, r: inner(l, r[1])  # noqa: E731
        # hash each item once; reuse for detection, pruning and shuffle
        lh = [[hashing.stable_host_hash(_h(lkey(it))) for it in l]
              for l in left.lists]
        rh = [[hashing.stable_host_hash(_h(rkey(it))) for it in l]
              for l in right.lists]
        ld = self.location_detection
        if ld is None:
            # host path: exact local row counts feed the cost model
            # (local_rows: multi-controller runs all-reduce them to
            # the global count before deciding, core/preshuffle.py)
            from ...core import preshuffle
            rows = (sum(len(l) for l in left.lists)
                    + sum(len(l) for l in right.lists))
            ld = preshuffle.auto_location_detect(
                mex, rows, 32, ("join_host", self.lkey, self.rkey),
                local_rows=True)
        if ld and W > 1:
            from ...core.location_detection import (LocationDetection,
                                                    _MASK)
            lh_all, rh_all = lh, rh
            if multiplexer.multiprocess(mex):
                # exchange the FINGERPRINTS (not the items) so every
                # controller agrees on the common-hash set (reference:
                # core/location_detection.hpp:70 ships Golomb-coded
                # hashes the same way)
                def _gather(hs):
                    local = {w: hs[w] for w in mex.local_workers}
                    out = [[] for _ in range(W)]
                    for msg in mex.host_net.all_gather(local):
                        for w, v in msg.items():
                            out[int(w)] = v
                    return out
                lh_all, rh_all = _gather(lh), _gather(rh)
            ld_l = LocationDetection(W)
            ld_r = LocationDetection(W)
            for w in range(W):
                ld_l.add_worker(w, lh_all[w])
                ld_r.add_worker(w, rh_all[w])
            common = ld_l.common_hashes(ld_r)

            def prune(shards, hs):
                kept_items, kept_hashes = [], []
                for items, hlist in zip(shards.lists, hs):
                    ki, kh = [], []
                    for it, h in zip(items, hlist):
                        if h & _MASK in common:
                            ki.append(it)
                            kh.append(h)
                    kept_items.append(ki)
                    kept_hashes.append(kh)
                return HostShards(W, kept_items), kept_hashes

            left, lh = prune(left, lh)
            right, rh = prune(right, rh)

        def shuffle(shards, hs):
            # items travel tagged with their precomputed hash (computed
            # once at line 62, survives pruning in lock-step)
            tagged = HostShards(W, [[(h, it) for it, h in zip(items, hl)]
                                    for items, hl in zip(shards.lists, hs)])
            # hash-partition target (MixStream-eligible): the join
            # matches by key, so batch arrival order only permutes the
            # output row order under THRILL_TPU_HOST_MIX=1
            ex = multiplexer.host_exchange(mex, tagged,
                                           lambda p: p[0] % W,
                                           reason="join",
                                           rank_order=False)
            return HostShards(W, [[it for _, it in l] for l in ex.lists])

        lx = shuffle(left, lh)
        rx = shuffle(right, rh)
        out = []
        for litems, ritems in zip(lx.lists, rx.lists):
            table = {}
            for it in litems:
                table.setdefault(_h(lkey(it)), []).append(it)
            pairs = []
            for rt in ritems:
                for lt in table.get(_h(rkey(rt)), ()):
                    pairs.append(jfn(lt, rt))
            out.append(pairs)
        return HostShards(W, out)

    # -- device path ----------------------------------------------------
    def _prep_device(self, left: DeviceShards, right: DeviceShards,
                     token):
        """Location filter + hash-partition exchange (fusion barriers
        shared by the phased and the stitched join paths)."""
        mex = left.mesh_exec
        W = mex.num_workers
        lkey, rkey = self.lkey, self.rkey

        ld = self.location_detection
        if ld is None and W > 1:
            # plan-time cost model: fingerprint register bytes vs the
            # rows pruning is expected to save, fed by exact counts
            # where host-known and the learned per-site exchange
            # capacities otherwise (core/preshuffle.py)
            from ...core import preshuffle
            rows, item_bytes = preshuffle.join_rows_estimate(
                mex, left, right, ("join_l", token, W),
                ("join_r", token, W))
            ld = preshuffle.auto_location_detect(mex, rows, item_bytes,
                                                 ("join_dev", token))
        if ld and W > 1:
            pre_rows = _host_rows(left), _host_rows(right)
            left, right = _location_filter(left, right, lkey, rkey,
                                           token)
        else:
            pre_rows = None

        if W > 1:
            def mk_dest(key_fn):
                def dest(tree, mask, widx):
                    words = keymod.encode_key_words(key_fn(tree))
                    h = hashing.hash_key_words(words)
                    return (h % jnp.uint64(W)).astype(jnp.int32)
                return dest

            left = exchange.exchange(left, mk_dest(lkey),
                                     ("join_l", token, W))
            right = exchange.exchange(right, mk_dest(rkey),
                                      ("join_r", token, W))
            # optimistic (capacity-cached) exchanges owe a deferred
            # overflow check; the join phases read the columns directly
            left.validate_pending()
            right.validate_pending()
            if pre_rows is not None:
                # teach the site its prune fraction where both counts
                # happen to be host-known already (never adds a sync)
                post = _host_rows(left), _host_rows(right)
                if None not in pre_rows and None not in post:
                    from ...core import preshuffle
                    preshuffle.record_prune(
                        mex, ("join_dev", token),
                        pre_rows[0] + pre_rows[1], post[0] + post[1])
        return left, right

    def compute_plan(self):
        """Hinted joins stitch (api/fusion.py): both phases trace into
        ONE program, and the plan defers so downstream device ops ride
        in the same dispatch. Un-hinted joins need their host size
        agreement — a fusion barrier — and stay on the phased path.
        Dense-index joins stitch unconditionally (gather, no sync)."""
        from .. import fusion
        if not fusion.enabled() or (self.out_size_hint is None
                                    and self.dense_right_index is None):
            return None
        left = self.parents[0].pull()
        right = self.parents[1].pull()
        if isinstance(left, HostShards) or isinstance(right, HostShards):
            return fusion.wrap(self._compute_host(left, right))
        token = (self.lkey, self.rkey, self.join_fn)
        if self.dense_right_index is not None:
            self._check_dense(right)
            return fusion.FusionPlan(
                left.mesh_exec, [left, right],
                head=self._dense_head(right.cap, token))
        left, right = self._prep_device(left, right, token)
        return self._fused_plan(left, right, token)

    # -- dense-index join ----------------------------------------------
    def _dense_bounds(self) -> np.ndarray:
        return dense_range_bounds(self.dense_right_index,
                                  self.context.num_workers)

    def _check_dense(self, right: DeviceShards) -> None:
        """Validate the dense contract where it is free: host-known
        right counts must match the dense range split (ReduceToIndex /
        Generate layouts). Device-resident counts are trusted — forcing
        a sync here would defeat the point of the gather join."""
        counts = right._counts_host
        if counts is None:
            return
        expect = np.diff(self._dense_bounds())
        if not np.array_equal(np.asarray(counts), expect):
            raise ValueError(
                f"InnerJoin dense_right_index={self.dense_right_index}: "
                f"right side counts {np.asarray(counts).tolist()} do not "
                f"form the dense range split {expect.tolist()}")

    def _dense_head(self, rcap: int, token):
        from .. import fusion
        n = self.dense_right_index
        W = self.context.num_workers
        bounds = self._dense_bounds()
        lkey, jfn = self.lkey, self.join_fn

        def trace(fctx, states, _bound):
            (ltree, lmask), (rtree, _rmask) = states
            key = jnp.asarray(lkey(ltree)).astype(jnp.int64)
            if W == 1:
                rall = rtree
                gidx = jnp.clip(key, 0, rcap - 1)
            else:
                b = jnp.asarray(bounds)
                w = jnp.clip(jnp.searchsorted(b[1:], key, side="right"),
                             0, W - 1)
                gidx = jnp.clip(w * rcap + (key - b[w]),
                                0, W * rcap - 1)
                rall = jax.tree.map(
                    lambda x: lax.all_gather(x, AXIS).reshape(
                        (W * rcap,) + x.shape[1:]), rtree)
            rsel = jax.tree.map(lambda x: jnp.take(x, gidx, axis=0),
                                rall)
            out = jfn(ltree, rsel)
            return out, lmask & (key >= 0) & (key < n)

        return fusion.Segment(label="InnerJoin",
                              token=("join_dense", token, n),
                              trace=trace, dia_id=self.id)

    def _compute_dense(self, left: DeviceShards,
                       right: DeviceShards) -> DeviceShards:
        """Unfused twin of the dense-index gather join (THRILL_TPU_FUSE=0
        parity path): one program, same gather math, compacted output."""
        from ...data.shards import compact_valid
        mex = left.mesh_exec
        self._check_dense(right)
        head = self._dense_head(right.cap,
                                (self.lkey, self.rkey, self.join_fn))
        lcap, rcap = left.cap, right.cap
        lleaves, ltd = jax.tree.flatten(left.tree)
        rleaves, rtd = jax.tree.flatten(right.tree)
        nl = len(lleaves)
        key = ("join_dense_solo", (self.lkey, self.rkey, self.join_fn),
               self.dense_right_index, lcap, rcap, ltd, rtd,
               tuple((l.dtype, l.shape[2:]) for l in lleaves),
               tuple((l.dtype, l.shape[2:]) for l in rleaves))
        holder = {}

        def build():
            def f(lc, rc, *ls):
                ltree = jax.tree.unflatten(ltd, [x[0] for x in ls[:nl]])
                rtree = jax.tree.unflatten(rtd, [x[0] for x in ls[nl:]])
                lmask = jnp.arange(lcap) < lc[0, 0]
                rmask = jnp.arange(rcap) < rc[0, 0]
                tree, mask = head.trace(None, [(ltree, lmask),
                                               (rtree, rmask)], None)
                tree, count = compact_valid(tree, mask)
                out_leaves, out_td = jax.tree.flatten(tree)
                holder["treedef"] = out_td
                return (count[None, None].astype(jnp.int32),
                        *[x[None] for x in out_leaves])

            return mex.smap(f, 2 + nl + len(rleaves)), holder

        fn, h = mex.cached(key, build)
        out = fn(left.counts_device(), right.counts_device(),
                 *lleaves, *rleaves)
        tree = jax.tree.unflatten(h["treedef"], list(out[1:]))
        return DeviceShards(mex, tree, out[0])

    def _fused_plan(self, left: DeviceShards, right: DeviceShards,
                    token):
        """One-dispatch hinted join: sort both sides, count match runs,
        expand pairs — phase 1 + phase 2 of the phased path as a single
        head segment. The true per-worker totals ride out as an aux
        output feeding the deferred overflow check; recovery
        re-dispatches the plan (sources are immutable device buffers —
        the lineage) at the true capacity."""
        from .. import fusion
        mex = left.mesh_exec
        lkey, rkey, jfn = self.lkey, self.rkey, self.join_fn
        out_cap = round_up_pow2(max(int(self.out_size_hint), 1))
        node = self

        def make_head(cap_):
            def trace(fctx, states, _bound):
                (ltree, lmask), (rtree, rmask) = states
                lcap = lmask.shape[0]
                rcap = rmask.shape[0]
                lw = keymod.encode_key_words(lkey(ltree))
                rw = keymod.encode_key_words(rkey(rtree))
                lw, ltree_s, lvalid, _ = segmented.sort_by_key_words(
                    lw, ltree, lmask)
                rw, rtree_s, rvalid, _ = segmented.sort_by_key_words(
                    rw, rtree, rmask)
                lo, hi = _run_bounds(lw, lvalid, rw, rvalid)
                matches = jnp.where(rvalid, hi - lo, 0)      # [rcap]
                total = jnp.sum(matches)
                fctx.emit_aux("join_totals", total)
                ends = jnp.cumsum(matches)
                p = jnp.arange(cap_, dtype=jnp.int64)
                ridx = jnp.searchsorted(ends, p, side="right")
                ridx = jnp.clip(ridx, 0, rcap - 1)
                starts = ends - matches
                lidx = lo[ridx] + (p - starts[ridx])
                lidx = jnp.clip(lidx, 0, lcap - 1)
                lsel = jax.tree.map(
                    lambda x: jnp.take(x, lidx, axis=0), ltree_s)
                rsel = jax.tree.map(
                    lambda x: jnp.take(x, ridx, axis=0), rtree_s)
                return jfn(lsel, rsel), jnp.arange(cap_) < total

            def finalize(plan, out):
                node._attach_fused_check(mex, plan, out, cap_)

            return fusion.Segment(label="InnerJoin",
                                  token=("join_fused", token, cap_),
                                  trace=trace, already_compact=True,
                                  refit=make_head, finalize=finalize,
                                  dia_id=node.id)

        return fusion.FusionPlan(mex, [left, right],
                                 head=make_head(out_cap))

    def _attach_fused_check(self, mex, plan, out: DeviceShards,
                            cap: int) -> None:
        """PR-1 recovery semantics for the stitched join: deferred
        overflow check draining at the fused boundary, sticky error
        state, in-place heal by re-dispatching the plan at the true
        capacity (counts replaced too — a fused tail's output counts
        depend on the healed pairs).

        TWIN of the phased path's check in ``_compute_device`` below
        (same sticky/resolve/re-entrancy discipline, different heal:
        plan re-dispatch vs expand-closure re-run) — a change to
        either must be mirrored in the other."""
        totals_dev = plan.aux.get("join_totals")
        try:
            totals_dev.copy_to_host_async()
        except Exception:
            pass                   # overlap is best-effort, not needed
        hint = self.out_size_hint
        label, dia_id = self.label, self.id
        hbm = self.context.hbm
        state = {"ok": False, "err": None, "plan": plan, "out": out,
                 "totals": totals_dev}

        def _resolve() -> None:
            state["ok"] = state["err"] is None
            state["plan"] = None
            state["out"] = None
            state["totals"] = None

        def validate(_counts):
            if state["err"] is not None:
                raise state["err"]
            if state["ok"]:
                return None
            totals = mex._fetch_raw(
                state["totals"]).reshape(-1).astype(np.int64)
            if int(totals.max(initial=0)) <= cap:
                _resolve()
                return None
            worst = int(totals.max(initial=0))
            import os
            if os.environ.get("THRILL_TPU_JOIN_RECOVER", "1") != "0":
                true_cap = round_up_pow2(max(worst, 1))
                o, plan_ = state["out"], state["plan"]
                # resolve FIRST: the re-dispatch below realizes counts,
                # and a drain fired from inside it must see a resolved
                # check, never start a second recovery
                _resolve()
                healed = plan_.reexecute(true_cap)
                o.tree = healed.tree
                o._counts_dev = healed._counts_dev
                # _fetch_raw: no drain (re-entrancy) and no counted
                # mid-pipeline sync in the dispatch budget
                new_counts = mex._fetch_raw(
                    healed._counts_dev).reshape(-1).astype(np.int64)
                mex.stats_join_overflow_retries += 1
                # resync the governor if some node tracks these shards
                # (the consumer of a deferred chain cached them)
                for n in list(hbm._lru.values()):
                    if n._shards is o and getattr(n, "_hbm_bytes", 0):
                        nb = hbm._device_bytes(o)
                        hbm.mem.subtract(n._hbm_bytes)
                        n._hbm_bytes = nb
                        hbm.mem.add(nb)
                        break
                from ...common import faults
                faults.note("recovery", what="join_out_size_hint",
                            node=label, dia_id=dia_id, hint=int(hint),
                            true_max=worst, new_cap=true_cap,
                            fused=True)
                return new_counts
            state["err"] = ValueError(
                f"InnerJoin out_size_hint={hint} (cap {cap}) "
                f"overflowed: a worker produced {worst} pairs; "
                f"results were truncated — raise the hint or drop it")
            _resolve()
            raise state["err"]

        out._counts_check = validate

        def pending_check() -> None:
            if state["err"] is not None:
                raise state["err"]       # sticky: a drain surfaces it
            if state["ok"]:
                return
            validate(None)

        mex._pending_checks.append(pending_check)

    def _compute_device(self, left: DeviceShards, right: DeviceShards):
        mex = left.mesh_exec
        W = mex.num_workers
        lkey, rkey, jfn = self.lkey, self.rkey, self.join_fn
        token = (lkey, rkey, jfn)

        if self.dense_right_index is not None:
            # gather join: no partition exchange, no size agreement
            return self._compute_dense(left, right)

        left, right = self._prep_device(left, right, token)

        if self.out_size_hint is not None:
            from .. import fusion
            if fusion.enabled():
                return self._fused_plan(left, right, token).execute()

        lcap, rcap = left.cap, right.cap
        lleaves, ltd = jax.tree.flatten(left.tree)
        rleaves, rtd = jax.tree.flatten(right.tree)

        # phase 1: sort both sides, count pairs per right item
        key1 = ("join_count", token, lcap, rcap, ltd, rtd,
                tuple((l.dtype, l.shape[2:]) for l in lleaves),
                tuple((l.dtype, l.shape[2:]) for l in rleaves))
        nl = len(lleaves)

        def build1():
            def f(lc, rc, *ls):
                ltree = jax.tree.unflatten(ltd, [x[0] for x in ls[:nl]])
                rtree = jax.tree.unflatten(rtd, [x[0] for x in ls[nl:]])
                lvalid = jnp.arange(lcap) < lc[0, 0]
                rvalid = jnp.arange(rcap) < rc[0, 0]
                lw = keymod.encode_key_words(lkey(ltree))
                rw = keymod.encode_key_words(rkey(rtree))
                lw, ltree_s, lvalid, _ = segmented.sort_by_key_words(
                    lw, ltree, lvalid)
                rw, rtree_s, rvalid, _ = segmented.sort_by_key_words(
                    rw, rtree, rvalid)
                lo, hi = _run_bounds(lw, lvalid, rw, rvalid)
                matches = jnp.where(rvalid, hi - lo, 0)  # [rcap]
                total = jnp.sum(matches)
                return (total[None, None].astype(jnp.int64),
                        matches[None], lo[None],
                        *[x[None] for x in jax.tree.leaves(ltree_s)],
                        *[x[None] for x in jax.tree.leaves(rtree_s)])

            return mex.smap(f, 2 + nl + len(rleaves))

        f1 = mex.cached(key1, build1)
        out1 = f1(left.counts_device(), right.counts_device(),
                  *lleaves, *rleaves)
        matches_dev, lo_dev = out1[1], out1[2]
        lsorted = list(out1[3:3 + nl])
        rsorted = list(out1[3 + nl:])

        totals = None
        if self.out_size_hint is not None:
            out_cap = round_up_pow2(max(int(self.out_size_hint), 1))
        else:
            totals = mex.fetch(out1[0]).reshape(-1).astype(np.int64)
            out_cap = round_up_pow2(max(int(totals.max()), 1))

        # phase 2: expand pairs and apply join_fn. ``expand`` is the
        # re-runnable half of the join's lineage: phase-1 outputs
        # (sorted sides + per-item match runs) plus a capacity fully
        # determine the result, so the overflow recovery below can
        # re-execute it at the TRUE capacity without touching parents.
        def expand(cap_: int):
            key2 = ("join_expand", token, lcap, rcap, cap_, ltd, rtd,
                    tuple((l.dtype, l.shape[2:]) for l in lleaves),
                    tuple((l.dtype, l.shape[2:]) for l in rleaves))
            holder = {}

            def build2():
                def f(matches, lo, *ls):
                    m = matches[0]                   # [rcap] pair counts
                    lo_ = lo[0]                      # [rcap] left run start
                    ltree = jax.tree.unflatten(ltd,
                                               [x[0] for x in ls[:nl]])
                    rtree = jax.tree.unflatten(rtd,
                                               [x[0] for x in ls[nl:]])
                    ends = jnp.cumsum(m)             # [rcap]
                    p = jnp.arange(cap_, dtype=jnp.int64)
                    ridx = jnp.searchsorted(ends, p, side="right")
                    ridx = jnp.clip(ridx, 0, rcap - 1)
                    starts = ends - m
                    lidx = lo_[ridx] + (p - starts[ridx])
                    lidx = jnp.clip(lidx, 0, lcap - 1)
                    lsel = jax.tree.map(
                        lambda x: jnp.take(x, lidx, axis=0), ltree)
                    rsel = jax.tree.map(
                        lambda x: jnp.take(x, ridx, axis=0), rtree)
                    out = jfn(lsel, rsel)
                    out_leaves, out_td = jax.tree.flatten(out)
                    holder["treedef"] = out_td
                    return tuple(x[None] for x in out_leaves)

                # (fn, holder) pair is what gets cached: a cache HIT
                # must read the FIRST build's holder (filled at trace
                # time) — a fresh local dict would be empty (the Merge
                # regression, test_merge_executable_cache_hit)
                return mex.smap(f, 2 + nl + len(rleaves)), holder

            f2, h2 = mex.cached(key2, build2)
            out2 = f2(matches_dev, lo_dev, *lsorted, *rsorted)
            return jax.tree.unflatten(h2["treedef"], list(out2))

        tree = expand(out_cap)
        if totals is not None:
            return DeviceShards(mex, tree, totals)
        # hint path: counts stay on device (no host sync; the eager
        # astype is one more async device op in the stream). Kick the
        # totals' device->host copy off NOW so the deferred validation
        # at the consumer's pull confirms an already-landed value
        # instead of stalling the dispatch stream.
        out = DeviceShards(mex, tree, out1[0].astype(jnp.int32))
        cap, hint, totals_dev = out_cap, self.out_size_hint, out1[0]
        try:
            totals_dev.copy_to_host_async()
        except Exception:
            pass                   # overlap is best-effort, not needed
        # state is STICKY on failure: once an overflow is detected with
        # recovery disabled, every later validation re-raises — a
        # caller that swallows the first error (bench metric wrappers
        # catch Exception) can never silently read truncated data.
        # COST, accepted deliberately: until the first consumer
        # validates (normally the very next pull), the ``expand``
        # closure pins the phase-1 outputs (sorted copies of both
        # sides + match runs, ~the join's input size) in HBM as the
        # recovery lineage, and that validation blocks the host on
        # phase-1 completion (overlapped with phase-2's already-
        # dispatched execution; the D2H copy itself was started async
        # above). ALL device refs live in ``state`` and are nulled the
        # moment the check resolves, so the entry that may linger in
        # mex._pending_checks until the next drain pins nothing — a
        # spilled node's HBM really frees.
        state = {"ok": False, "err": None, "expand": expand,
                 "out": out, "totals": totals_dev}
        label, dia_id = self.label, self.id
        node, hbm = self, self.context.hbm

        def _resolve() -> None:
            state["ok"] = state["err"] is None
            state["expand"] = None
            state["out"] = None
            state["totals"] = None

        def validate(counts: np.ndarray) -> None:
            if state["err"] is not None:
                raise state["err"]
            if state["ok"]:
                return
            worst = int(counts.max(initial=0))
            if worst > cap:
                import os
                if os.environ.get("THRILL_TPU_JOIN_RECOVER",
                                  "1") != "0":
                    # lineage retry: re-run the expansion at the true
                    # capacity and heal the shards IN PLACE — every
                    # consumer validates before reading the columns
                    # (ParentLink.pull / counts / egress drains), so
                    # the truncated tree was never observable
                    true_cap = round_up_pow2(max(worst, 1))
                    o = state["out"]
                    o.tree = state["expand"](true_cap)
                    mex.stats_join_overflow_retries += 1
                    if (node._shards is o
                            and getattr(node, "_hbm_bytes", 0)):
                        # the healed tree is larger than what on_cache
                        # accounted: resync the governor or the budget
                        # drifts under-counted forever. ACCOUNTING
                        # ONLY — no maybe_spill from in here:
                        # validation runs inside arbitrary frames
                        # (another node's spill, a parent pull
                        # mid-materialize), and evicting from this
                        # depth can re-enter an unresolved sibling's
                        # recovery or spill shards an ancestor frame
                        # is actively returning. The next natural
                        # pressure event (on_cache/touch) evicts.
                        nb = hbm._device_bytes(o)
                        hbm.mem.subtract(node._hbm_bytes)
                        node._hbm_bytes = nb
                        hbm.mem.add(nb)
                    # resolve before the note so a re-entrant
                    # validation is a no-op, never a second recovery
                    _resolve()
                    # ONE emission: note() counts the recovery and
                    # forwards to the Context's JSON logger (attached
                    # in Context.__init__)
                    from ...common import faults
                    faults.note("recovery", what="join_out_size_hint",
                                node=label, dia_id=dia_id,
                                hint=int(hint), true_max=worst,
                                new_cap=true_cap)
                    return
                state["err"] = ValueError(
                    f"InnerJoin out_size_hint={hint} (cap {cap}) "
                    f"overflowed: a worker produced {worst} pairs; "
                    f"results were truncated — raise the hint or "
                    f"drop it")
                _resolve()
                raise state["err"]
            _resolve()

        out._counts_check = validate

        def pending_check() -> None:
            # fetch drains catch chains that never realize THIS
            # shards' counts. Skip the totals transfer once resolved;
            # the transfer uses _fetch_raw (multi-controller safe, no
            # stats, and the drain already swapped the queue out so
            # re-entrancy cannot loop)
            if state["err"] is not None:
                raise state["err"]      # sticky: a drain surfaces it
            if state["ok"]:
                return
            validate(mex._fetch_raw(state["totals"]).reshape(-1))

        mex._pending_checks.append(pending_check)
        return out


def _host_rows(shards) -> "int | None":
    """Global row count when already host-known (no sync), else None."""
    counts = getattr(shards, "_counts_host", None)
    return None if counts is None else int(np.asarray(counts).sum())


def _location_filter(left: DeviceShards, right: DeviceShards,
                     lkey, rkey, token):
    """Device LocationDetection: drop items whose key hash has no
    presence on the OTHER side anywhere in the cluster, before paying
    for the exchange (reference: LocationDetectionTag,
    api/inner_join.hpp:161-190, core/location_detection.hpp:70 — the
    Golomb-coded per-key location exchange becomes one pmax over
    presence registers). Registers are u8 presence bits sized to the
    padded row bound (core/preshuffle.py register_width) — false
    positives only cost shuffle traffic, never correctness."""
    import jax
    from jax import lax

    from ...core import preshuffle
    from ...data.shards import compact_valid
    from ...parallel.mesh import AXIS

    mex = left.mesh_exec
    lcap, rcap = left.cap, right.cap
    M = preshuffle.register_width((lcap + rcap) * mex.num_workers)
    lleaves, ltd = jax.tree.flatten(left.tree)
    rleaves, rtd = jax.tree.flatten(right.tree)
    nl = len(lleaves)
    key = ("join_ld", token, M, lcap, rcap, ltd, rtd,
           tuple((l.dtype, l.shape[2:]) for l in lleaves),
           tuple((l.dtype, l.shape[2:]) for l in rleaves))

    def build():
        def f(lc, rc, *ls):
            ltree = jax.tree.unflatten(ltd, [x[0] for x in ls[:nl]])
            rtree = jax.tree.unflatten(rtd, [x[0] for x in ls[nl:]])
            lvalid = jnp.arange(lcap) < lc[0, 0]
            rvalid = jnp.arange(rcap) < rc[0, 0]
            hl = (hashing.hash_key_words(
                keymod.encode_key_words(lkey(ltree)))
                % jnp.uint64(M)).astype(jnp.int32)
            hr = (hashing.hash_key_words(
                keymod.encode_key_words(rkey(rtree)))
                % jnp.uint64(M)).astype(jnp.int32)
            # u8 presence registers: a quarter of the i32 form's
            # fabric bytes, same verdict. Filled by the Pallas
            # presence kernel where it engages (bit-identical —
            # presence is 0/1, no float reassociation).
            from ...core.pallas_kernels import presence_fill
            pres_l = presence_fill(hl, lvalid, M)
            pres_r = presence_fill(hr, rvalid, M)
            pres_l = lax.pmax(pres_l, AXIS)
            pres_r = lax.pmax(pres_r, AXIS)
            keep_l = lvalid & (jnp.take(pres_r, hl) > 0)
            keep_r = rvalid & (jnp.take(pres_l, hr) > 0)
            ltree_c, lcount = compact_valid(ltree, keep_l)
            rtree_c, rcount = compact_valid(rtree, keep_r)
            return (lcount[None, None].astype(jnp.int32),
                    rcount[None, None].astype(jnp.int32),
                    *[x[None] for x in jax.tree.leaves(ltree_c)],
                    *[x[None] for x in jax.tree.leaves(rtree_c)])

        return mex.smap(f, 2 + nl + len(rleaves))

    fn = mex.cached(key, build)
    out = fn(left.counts_device(), right.counts_device(),
             *lleaves, *rleaves)
    new_left = DeviceShards(mex, jax.tree.unflatten(
        ltd, list(out[2:2 + nl])), out[0])
    new_right = DeviceShards(mex, jax.tree.unflatten(
        rtd, list(out[2 + nl:])), out[1])
    return new_left, new_right


def _run_bounds(lw, lvalid, rw, rvalid):
    """For each right item: [lo, hi) bounds of its equal-key run among
    the sorted valid left items.

    O((L+R) log(L+R)): both sides' key words are sorted together with a
    side flag. With right sorting *after* equal left keys, a right item
    at combined position p has (p - #rights before) = #lefts with key
    <= its key = ``hi``; flipping the flag gives #lefts with key < its
    key = ``lo``. Invalid items sort last via a prepended validity word
    (not a key-word sentinel) and are excluded from the left counts, so
    they never perturb valid bounds even for all-ones keys.
    """
    lcap = lw[0].shape[0]
    rcap = rw[0].shape[0]
    # Validity is a *prepended sort word* (0 = valid, 1 = invalid), never
    # an overwrite of the key words: the all-ones sentinel would collide
    # with legitimate keys that encode to all-ones (uint64.max, all-0xFF
    # byte keys) and produce phantom pairs against padding garbage.
    valid_all = jnp.concatenate([lvalid, rvalid])
    invalid_word = (~valid_all).astype(jnp.uint32)

    from ...core.device_sort import argsort_words

    def counts_below(right_after: bool):
        side_l = jnp.zeros(lcap, jnp.uint64) if right_after else \
            jnp.ones(lcap, jnp.uint64)
        side_r = jnp.ones(rcap, jnp.uint64) if right_after else \
            jnp.zeros(rcap, jnp.uint64)
        words = [jnp.concatenate([a, b]) for a, b in zip(lw, rw)]
        side = jnp.concatenate([side_l, side_r])
        ridx = jnp.concatenate([jnp.full(lcap, rcap, jnp.uint64),
                                jnp.arange(rcap, dtype=jnp.uint64)])
        perm = argsort_words([invalid_word] + words + [side])
        side_s = jnp.take(side, perm)
        ridx_s = jnp.take(ridx, perm)
        valid_s = jnp.take(valid_all, perm)
        is_right = side_s == (1 if right_after else 0)
        is_left = ~is_right
        # valid lefts at positions <= p == valid lefts before a right item
        lefts_before = jnp.cumsum((is_left & valid_s).astype(jnp.int64))
        # scatter back to right-item order
        out = jnp.zeros(rcap + 1, jnp.int64)
        tgt = jnp.where(is_right, ridx_s.astype(jnp.int64), rcap)
        out = out.at[tgt].set(jnp.where(is_right, lefts_before, 0))
        return out[:rcap]

    hi = counts_below(right_after=True)
    lo = counts_below(right_after=False)
    return lo, hi


def _h(k):
    if isinstance(k, np.ndarray):
        return tuple(k.tolist())
    if isinstance(k, np.generic):
        return k.item()
    return k


def _enum_key(t):
    """Key of a position-enumerated (g, item) pair (dense host path)."""
    return t[0]


def InnerJoin(left: DIA, right: DIA, left_key_fn, right_key_fn,
              join_fn, location_detection=None,
              out_size_hint=None, dense_right_index=None) -> DIA:
    """``location_detection``: None (default) lets the plan-time cost
    model decide whether to pre-filter both sides by cross-side key
    presence before the shuffle (core/preshuffle.py; forced by
    THRILL_TPU_LOCATION_DETECT=0/1); True/False force it per call like
    the reference's LocationDetectionTag.

    ``out_size_hint``: optional per-worker upper bound on match
    count; lets the device path skip its blocking size sync. A wrong
    hint is SAFE: overflow is detected before any consumer reads the
    columns and the join phase transparently re-runs without the hint
    (lineage retry; ``event=recovery`` logged, counted in
    ``ctx.overall_stats()['join_overflow_retries']``). Set
    THRILL_TPU_JOIN_RECOVER=0 to raise instead of recovering — either
    way it never silently truncates.

    ``dense_right_index=n``: declares the right side a dense index
    table — exactly n rows globally, the row at global position g has
    key g (``table.ZipWithIndex(...)`` over a ReduceToIndex/Generate
    result). The join then runs as a pure device GATHER: no sort, no
    hash partition, no exchange, no size sync, at any worker count.
    Host-known right counts are validated against the dense layout;
    out-of-range left keys yield no pair (inner-join semantics)."""
    return DIA(InnerJoinNode(left.context, left._link(), right._link(),
                             left_key_fn, right_key_fn, join_fn,
                             location_detection=location_detection,
                             out_size_hint=out_size_hint,
                             dense_right_index=dense_right_index))
