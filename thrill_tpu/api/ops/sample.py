"""BernoulliSample and Sample(k).

Reference: thrill/api/bernoulli_sample.hpp:27 (per-item coin flips; the
reference uses geometric skips, on device a vectorized uniform draw is
the natural equivalent) and api/sample.hpp:50 (distributed uniform
sample of fixed size k: the global budget is split over workers by the
multivariate hypergeometric distribution, then each worker samples
locally without replacement — exactly the reference's scheme).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...common.sampling import hypergeometric_split
from ...data.shards import DeviceShards, HostShards, compact_valid
from ..dia import DIA
from ..dia_base import DIABase


class BernoulliSampleNode(DIABase):
    def __init__(self, ctx, link, p: float, seed: int) -> None:
        super().__init__(ctx, f"BernoulliSample({p})", [link])
        self.p = float(p)
        self.seed = seed

    def compute(self):
        shards = self.parents[0].pull()
        if isinstance(shards, HostShards):
            rng = np.random.default_rng(self.seed)
            return HostShards(shards.num_workers,
                              [[it for it in items
                                if rng.random() < self.p]
                               for items in shards.lists])
        mex = shards.mesh_exec
        cap = shards.cap
        p = self.p
        seed = self.seed
        leaves, treedef = jax.tree.flatten(shards.tree)
        key = ("bernoulli", p, seed, cap, treedef,
               tuple((l.dtype, l.shape[2:]) for l in leaves))

        def build():
            def f(counts_dev, *ls):
                widx = jax.lax.axis_index("w")
                k = jax.random.fold_in(jax.random.PRNGKey(seed), widx)
                mask = jnp.arange(cap) < counts_dev[0, 0]
                keep = jax.random.uniform(k, (cap,)) < p
                tree = jax.tree.unflatten(treedef, [l[0] for l in ls])
                tree, cnt = compact_valid(tree, mask & keep)
                return (cnt[None, None].astype(jnp.int32),
                        *[l[None] for l in jax.tree.leaves(tree)])

            return mex.smap(f, 1 + len(leaves))

        fn = mex.cached(key, build)
        out = fn(shards.counts_device(), *leaves)
        tree = jax.tree.unflatten(treedef, list(out[1:]))
        return DeviceShards(mex, tree, out[0])


class SampleNode(DIABase):
    def __init__(self, ctx, link, k: int, seed: int) -> None:
        super().__init__(ctx, f"Sample({k})", [link])
        self.k = int(k)
        self.seed = seed

    def compute(self):
        shards = self.parents[0].pull()
        rng = np.random.default_rng(self.seed)
        if isinstance(shards, HostShards):
            from ...data import multiplexer
            counts = multiplexer.global_counts(
                self.context.mesh_exec, shards)
        else:
            counts = shards.counts
        takes = hypergeometric_split(rng, self.k, counts)
        if isinstance(shards, HostShards):
            out = []
            for items, t in zip(shards.lists, takes):
                idx = rng.choice(len(items), size=int(t), replace=False) \
                    if len(items) else np.array([], dtype=np.int64)
                idx.sort()
                out.append([items[i] for i in idx])
            return HostShards(shards.num_workers, out)

        mex = shards.mesh_exec
        cap = shards.cap
        seed = self.seed
        leaves, treedef = jax.tree.flatten(shards.tree)
        key = ("sample_k", seed, cap, treedef,
               tuple((l.dtype, l.shape[2:]) for l in leaves))

        def build():
            def f(counts_dev, takes_dev, *ls):
                widx = jax.lax.axis_index("w")
                kk = jax.random.fold_in(jax.random.PRNGKey(seed ^ 0x5A), widx)
                count = counts_dev[0, 0]
                t = takes_dev[0, 0]
                mask = jnp.arange(cap) < count
                # random scores; invalid items pushed last, take first t
                scores = jax.random.uniform(kk, (cap,))
                scores = jnp.where(mask, scores, 2.0)
                from ...core import keys as keymod
                from ...core.device_sort import argsort_words
                order = argsort_words(keymod.encode_key_words(scores))
                keep_sorted = jnp.arange(cap) < t
                keep = jnp.zeros(cap, bool).at[order].set(keep_sorted)
                tree = jax.tree.unflatten(treedef, [l[0] for l in ls])
                tree, cnt = compact_valid(tree, keep & mask)
                return (cnt[None, None].astype(jnp.int32),
                        *[l[None] for l in jax.tree.leaves(tree)])

            return mex.smap(f, 2 + len(leaves))

        fn = mex.cached(key, build)
        out = fn(shards.counts_device(),
                 mex.put_small(takes.astype(np.int64)[:, None]), *leaves)
        tree = jax.tree.unflatten(treedef, list(out[1:]))
        return DeviceShards(mex, tree, out[0])


def BernoulliSample(dia: DIA, p: float, seed: int = 0) -> DIA:
    return DIA(BernoulliSampleNode(dia.context, dia._link(), p, seed))


def Sample(dia: DIA, k: int, seed: int = 0) -> DIA:
    return DIA(SampleNode(dia.context, dia._link(), k, seed))
