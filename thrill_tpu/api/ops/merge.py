"""Merge of sorted DIAs.

Reference: thrill/api/merge.hpp:76 — distributed multi-sequence
selection (iterative pivot search over the sorted inputs,
SelectPivots/GetGlobalRanks/SearchStep at merge.hpp:325-429) to find
balanced split points, then stream exchange + local k-way merge.

TPU-native design that actually EXPLOITS sortedness (round-1 review:
the old path concatenated and re-ran the full sample sort):

 1. sample:   inputs are already key-sorted, so splitter samples are
              plain quantile *reads* of each worker's sorted columns —
              NO local sort, NO payload movement. The host merges all
              inputs' samples and picks W-1 splitters (the
              single-controller collapse of the reference's pivot
              search).
 2. classify: per input, destination = rank among splitters of
              (key words, input index, position) — monotone along each
              already-sorted input, so items ship through
              ``exchange_presorted`` with an IDENTITY permutation: the
              payload is never gathered before the exchange.
 3. combine:  each worker holds k x W sorted runs (rank-ordered by
              construction); one argsort of the (validity, key words,
              input index, position) words + a single payload gather
              produces the merged output. Equal keys order by input
              index then original position — the reference's tie order.

Total sort-network work: ONE argsort of key words per worker, versus
three full sorts in the naive concat+sort formulation.
"""

from __future__ import annotations

import heapq
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ...core import keys as keymod
from ...data import exchange
from ...data.shards import DeviceShards, HostShards
from ...parallel.mesh import AXIS
from ..dia import DIA
from ..dia_base import DIABase
from ...common.partition import dense_range_bounds
from .sort import (OVERSAMPLE, _lex_greater, choose_splitters,
                   quantile_positions)


class MergeNode(DIABase):
    def __init__(self, ctx, links, key_fn: Optional[Callable]) -> None:
        super().__init__(ctx, "Merge", links)
        self.key_fn = key_fn or (lambda x: x)

    def compute(self):
        pulls = [l.pull() for l in self.parents]
        if any(isinstance(p, HostShards) for p in pulls):
            pulls = [p.to_host_shards("merge-host-path")
                     if isinstance(p, DeviceShards) else p for p in pulls]
            from ...data import multiplexer
            mex = self.context.mesh_exec
            pulls = [multiplexer.ensure_replicated(mex, p, "merge-host")
                     for p in pulls]
            W = pulls[0].num_workers
            seqs = [[it for lst in p.lists for it in lst] for p in pulls]
            merged = list(heapq.merge(*seqs, key=self.key_fn))
            bounds = dense_range_bounds(len(merged), W).tolist()
            return multiplexer.localize(
                mex, HostShards(W, [merged[bounds[w]:bounds[w + 1]]
                                    for w in range(W)]))
        return _device_merge(pulls, self.key_fn, ("merge", self.key_fn))


def _device_merge(inputs: List[DeviceShards], key_fn: Callable,
                  token) -> DeviceShards:
    mex = inputs[0].mesh_exec
    W = mex.num_workers
    k = len(inputs)
    if sum(s.total for s in inputs) == 0:
        return inputs[0]

    # ---- phase 1: quantile samples of the (already sorted) inputs ----
    # A sorted column's quantiles are direct reads — no sort, no gather.
    all_samples = []          # (words..., input_idx, gidx) tuples
    nwords_holder = {}
    samples_per_input = []
    for i, shards in enumerate(inputs):
        cap = shards.cap
        leaves, treedef = jax.tree.flatten(shards.tree)
        offsets = np.concatenate([[0], np.cumsum(shards.counts)])[:-1]
        key1 = ("merge_sample", token, i, cap, treedef,
                tuple((l.dtype, l.shape[2:]) for l in leaves))

        def build1(cap=cap, treedef=treedef):
            holder = {}

            def f(counts_dev, offset_dev, *ls):
                count = counts_dev[0, 0]
                tree = jax.tree.unflatten(treedef, [l[0] for l in ls])
                words = keymod.encode_key_words(key_fn(tree))
                holder["n"] = len(words)
                gidx = offset_dev[0, 0] + jnp.arange(cap, dtype=jnp.int64)
                qpos = quantile_positions(count, cap)
                s_words = jnp.stack([jnp.take(w, qpos) for w in words], 1)
                s_idx = jnp.take(gidx, qpos)
                s_valid = qpos < count
                return (lax.all_gather(s_words, AXIS),
                        lax.all_gather(s_idx, AXIS),
                        lax.all_gather(s_valid, AXIS))

            from jax.sharding import PartitionSpec as P
            # holder is cached WITH the executable: cache hits must not
            # leave it unpopulated
            return (mex.smap(f, 2 + len(leaves),
                             out_specs=(P(), P(), P())), holder)

        f1, h1 = mex.cached(key1, build1)
        sw, si, sv = f1(shards.counts_device(),
                        mex.put_small(offsets.astype(np.int64)[:, None]),
                        *leaves)
        nwords_holder.update(h1)
        samples_per_input.append((mex.fetch(sw), mex.fetch(si),
                                  mex.fetch(sv)))

    nwords = nwords_holder["n"]
    for i, (sw, si, sv) in enumerate(samples_per_input):
        sw = sw.reshape(W * OVERSAMPLE, nwords)
        si = si.reshape(-1)
        sv = sv.reshape(-1)
        for j in range(len(sv)):
            if sv[j]:
                all_samples.append(
                    (tuple(int(x) for x in sw[j]), i, int(si[j])))
    all_samples.sort()
    # W-1 splitters over (words, input_idx, gidx)
    splitters = choose_splitters(
        [s[0] + (s[1], s[2]) for s in all_samples], W, nwords + 2)

    # ---- phase 2: classify (monotone) + ship via presorted exchange --
    carriers = []
    for i, shards in enumerate(inputs):
        cap = shards.cap
        leaves, treedef = jax.tree.flatten(shards.tree)
        offsets = np.concatenate([[0], np.cumsum(shards.counts)])[:-1]
        if W == 1:
            # single worker: nothing to ship; build the carrier directly
            key2 = ("merge_carrier1", token, i, cap, treedef,
                    tuple((l.dtype, l.shape[2:]) for l in leaves))

            def build2a(cap=cap, treedef=treedef):
                def f(counts_dev, offset_dev, *ls):
                    tree = jax.tree.unflatten(treedef,
                                              [l[0] for l in ls])
                    words = keymod.encode_key_words(key_fn(tree))
                    gidx = (offset_dev[0, 0]
                            + jnp.arange(cap, dtype=jnp.int64))
                    return (jnp.stack(words, 1)[None], gidx[None],
                            *[l for l in ls])

                return mex.smap(f, 2 + len(leaves))

            f2 = mex.cached(key2, build2a)
            out2 = f2(shards.counts_device(),
                      mex.put_small(offsets.astype(np.int64)[:, None]),
                      *leaves)
            carrier_tree = {"__words": out2[0], "__gidx": out2[1],
                            "tree": jax.tree.unflatten(treedef,
                                                       list(out2[2:]))}
            carriers.append(DeviceShards(mex, carrier_tree,
                                         shards.counts.copy()))
            continue

        # carrier leaf templates ({__gidx, __words, tree} flatten order)
        # so the phase-B narrowing's range analysis can ride this
        # classify program — encode_key_words always emits uint64 words
        carrier_templates, _ = jax.tree.flatten({
            "__words": jax.ShapeDtypeStruct((W, cap, nwords),
                                            jnp.uint64),
            "__gidx": jax.ShapeDtypeStruct((W, cap), jnp.int64),
            "tree": jax.tree.unflatten(treedef, list(leaves))})
        nidx3 = exchange.presorted_range_leaves(mex, cap,
                                                carrier_templates)
        key2 = ("merge_classify", token, i, W, cap, nwords, treedef,
                nidx3, tuple((l.dtype, l.shape[2:]) for l in leaves))

        def build2(cap=cap, treedef=treedef, i=i, nleaves=len(leaves),
                   nidx3=nidx3):
            def f(spl_a, counts_dev, offset_dev, *ls):
                spl = spl_a[0]                      # [W-1, nwords+2]
                count = counts_dev[0, 0]
                valid = jnp.arange(cap) < count
                tree = jax.tree.unflatten(treedef, [l[0] for l in ls])
                words = keymod.encode_key_words(key_fn(tree))
                wm = jnp.stack(words, 1)
                gidx = (offset_dev[0, 0]
                        + jnp.arange(cap, dtype=jnp.int64))
                # destination = #splitters below (words, input, gidx);
                # monotone because the input is sorted
                iw = jnp.full(cap, i, dtype=jnp.uint64)
                d = jnp.zeros(cap, dtype=jnp.int32)
                for j in range(W - 1):
                    gt = _lex_greater(
                        jnp.concatenate([wm, iw[:, None]], axis=1),
                        gidx.astype(jnp.uint64), spl[j])
                    d = d + gt.astype(jnp.int32)
                dest = jnp.where(valid, d, W)
                all_send = exchange.send_counts(dest, W)
                outs = (dest[None], all_send, wm[None], gidx[None],
                        *[l for l in ls])
                if nidx3:
                    carrier = [gidx, wm] + [l[0] for l in ls]
                    outs = outs + (exchange.leaf_ranges_traced(
                        [carrier[li] for li in nidx3], valid),)
                return outs

            from jax.sharding import PartitionSpec as P
            out_specs = (P(AXIS), P()) + (P(AXIS),) * (2 + nleaves)
            if nidx3:
                out_specs = out_specs + (P(),)
            return mex.smap(f, 3 + nleaves, out_specs=out_specs)

        f2 = mex.cached(key2, build2)
        spl_dev = mex.put_small(np.broadcast_to(
            splitters, (W,) + splitters.shape).copy())
        out2 = f2(spl_dev, shards.counts_device(),
                  mex.put_small(offsets.astype(np.int64)[:, None]), *leaves)
        sorted_dest, send_mat = out2[0], out2[1]
        payload_end = len(out2) - 1 if nidx3 else len(out2)
        range_mat = out2[-1] if nidx3 else None
        carrier_tree = {"__words": out2[2], "__gidx": out2[3],
                        "tree": jax.tree.unflatten(
                            treedef, list(out2[4:payload_end]))}
        carrier_leaves, treedef3 = jax.tree.flatten(carrier_tree)
        S = mex.fetch(send_mat)
        ranges = None if range_mat is None else mex._fetch_raw(range_mat)
        carriers.append(exchange.exchange_presorted(
            mex, treedef3, sorted_dest, carrier_leaves, S,
            ident=("merge_x", token, i), ranges=ranges))

    # ---- phase 3: one local merge sort over all received runs -------
    caps = tuple(c.cap for c in carriers)
    leaves_per, treedefs = zip(*(jax.tree.flatten(c.tree)
                                 for c in carriers))
    nleaves_per = tuple(len(ls) for ls in leaves_per)
    key3 = ("merge_final", token, caps, treedefs,
            tuple(tuple((l.dtype, l.shape[2:]) for l in ls)
                  for ls in leaves_per))
    payload_treedef = jax.tree.structure(inputs[0].tree)

    def build3():
        def f(*args):
            counts = args[:k]
            rest = list(args[k:])
            words_all, iw_all, gidx_all, valid_all, payload_all = \
                [], [], [], [], None
            for i in range(k):
                ls = rest[:nleaves_per[i]]
                rest_i = [l[0] for l in ls]
                del rest[:nleaves_per[i]]
                tree = jax.tree.unflatten(treedefs[i], rest_i)
                wm = tree["__words"]
                gi = tree["__gidx"]
                cap_i = wm.shape[0]
                valid = jnp.arange(cap_i) < counts[i][0, 0]
                words_all.append(wm)
                iw_all.append(jnp.full(cap_i, i, jnp.uint64))
                gidx_all.append(gi.astype(jnp.uint64))
                valid_all.append(valid)
                pl = jax.tree.leaves(tree["tree"])
                payload_all = ([jnp.concatenate([a, b], axis=0)
                                for a, b in zip(payload_all, pl)]
                               if payload_all is not None else pl)
            wm = jnp.concatenate(words_all, axis=0)
            iw = jnp.concatenate(iw_all)
            gi = jnp.concatenate(gidx_all)
            valid = jnp.concatenate(valid_all)
            from ...core.device_sort import argsort_words
            sort_words = ([(~valid).astype(jnp.uint32)]
                          + [wm[:, j] for j in range(nwords)]
                          + [iw, gi])
            perm = argsort_words(sort_words)
            outs = [jnp.take(l, perm, axis=0)[None] for l in payload_all]
            return tuple(outs)

        return mex.smap(f, k + sum(nleaves_per))

    f3 = mex.cached(key3, build3)
    args = [c.counts_device() for c in carriers]
    for ls in leaves_per:
        args.extend(ls)
    out3 = f3(*args)
    tree = jax.tree.unflatten(payload_treedef, list(out3))
    new_counts = sum((c.counts for c in carriers),
                     np.zeros(W, dtype=np.int64))
    return DeviceShards(mex, tree, new_counts)


def Merge(dias: List[DIA], key_fn=None) -> DIA:
    assert dias
    return DIA(MergeNode(dias[0].context, [d._link() for d in dias],
                         key_fn))
