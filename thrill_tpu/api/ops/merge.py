"""Merge of sorted DIAs.

Reference: thrill/api/merge.hpp:76 — distributed multi-sequence
selection (iterative pivot search over the sorted inputs) to find
balanced split points, then stream exchange + local k-way merge.

Device translation: a concatenation that tags items with (input index,
position) followed by the sample-sort machinery keyed on the user key
degenerates to exactly the merge semantics — inputs are already sorted,
so splitter sampling is cheap and the final local sort is a near-sorted
bitonic pass. Equal keys order by input index then original position
(the reference's tie ordering).
"""

from __future__ import annotations

from typing import Callable, List, Optional

import heapq

from ...data.shards import DeviceShards, HostShards
from ..dia import DIA
from ..dia_base import DIABase
from .sort import _device_sample_sort


class MergeNode(DIABase):
    def __init__(self, ctx, links, key_fn: Optional[Callable]) -> None:
        super().__init__(ctx, "Merge", links)
        self.key_fn = key_fn or (lambda x: x)

    def compute(self):
        pulls = [l.pull() for l in self.parents]
        if any(isinstance(p, HostShards) for p in pulls):
            pulls = [p.to_host_shards("merge-host-path") if isinstance(p, DeviceShards)
                     else p for p in pulls]
            W = pulls[0].num_workers
            seqs = [[it for lst in p.lists for it in lst] for p in pulls]
            merged = list(heapq.merge(*seqs, key=self.key_fn))
            bounds = [(w * len(merged)) // W for w in range(W + 1)]
            return HostShards(W, [merged[bounds[w]:bounds[w + 1]]
                                  for w in range(W)])
        # device: order-preserving concat (keeps input-rank global order
        # as the stability tiebreak), then stable sample sort
        from .concat import rebalance_to_even
        combined = rebalance_to_even(pulls[0].mesh_exec, pulls,
                                     ("merge", self.id))
        return _device_sample_sort(combined, self.key_fn,
                                   ("merge", self.key_fn))


def Merge(dias: List[DIA], key_fn=None) -> DIA:
    assert dias
    return DIA(MergeNode(dias[0].context, [d._link() for d in dias],
                         key_fn))
