"""PrefixSum / ExPrefixSum.

Reference: thrill/api/prefix_sum.hpp:28 — local sum, net.ExPrefixSum of
partials, re-emit. Device path: one SPMD program doing a masked local
cumulative sum plus a cross-worker exclusive offset via all_gather of
local totals (the FlowControlChannel step become an XLA collective).
Generic (non-additive) functions run on the host path sequentially.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ...data.shards import DeviceShards, HostShards
from ...parallel.mesh import AXIS
from ..dia import DIA
from ..dia_base import DIABase


class PrefixSumNode(DIABase):
    def __init__(self, ctx, link, fn: Optional[Callable], initial: Any,
                 inclusive: bool) -> None:
        super().__init__(ctx, "PrefixSum" if inclusive else "ExPrefixSum",
                         [link])
        self.fn = fn
        self.initial = initial
        self.inclusive = inclusive

    def _fuse_segment(self):
        """The masked local-cumsum + cross-worker offset trace as a
        fused segment (the all_gather of local totals rides inside the
        stitched program)."""
        from .. import fusion
        inclusive = self.inclusive
        initial = self.initial

        def trace(fctx, tree, mask, _bound):
            def one(x):
                m = mask.reshape(mask.shape + (1,) * (x.ndim - 1))
                xm = jnp.where(m, x, 0)
                incl = jnp.cumsum(xm, axis=0, dtype=x.dtype)
                local_total = incl[-1]
                totals = lax.all_gather(local_total, AXIS)   # [W, ...]
                widx = lax.axis_index(AXIS)
                prev = jnp.where(
                    (jnp.arange(totals.shape[0]) < widx
                     ).reshape((-1,) + (1,) * (totals.ndim - 1)),
                    totals, 0).sum(axis=0)
                scan = incl if inclusive else incl - xm
                return scan + prev + jnp.asarray(initial).astype(x.dtype)

            return jax.tree.map(one, tree), mask

        return fusion.Segment(
            label=self.label,
            token=("prefix_sum_fused", inclusive,
                   np.asarray(initial).tobytes()),
            trace=trace, preserves_counts=True, dia_id=self.id)

    def compute_plan(self):
        from .. import fusion
        if self.fn is not None:
            return None              # generic fold: host path only
        plan = fusion.pull_plan(self.parents[0])
        if not plan.stitchable:
            return fusion.wrap(self._compute_on(plan.finish()))
        plan.append(self._fuse_segment())
        return plan

    def compute(self):
        plan = self.compute_plan()
        if plan is not None:
            return plan.finish()
        return self._compute_on(self.parents[0].pull())

    def _compute_on(self, shards):
        if isinstance(shards, HostShards) or self.fn is not None:
            if isinstance(shards, DeviceShards):
                shards = shards.to_host_shards("prefixsum-nonnumeric-op")
            return self._compute_host(shards)
        return self._compute_device(shards)

    def _compute_host(self, shards: HostShards):
        # generic (possibly non-associative) fold is sequential across
        # the whole stream: replicate across controllers, compute the
        # identical full result, keep the local lists
        from ...data import multiplexer
        mex = self.context.mesh_exec
        replicated = multiplexer.ensure_replicated(mex, shards,
                                                   "prefixsum-host")
        fn = self.fn or (lambda a, b: a + b)
        out = []
        acc = self.initial
        for items in replicated.lists:
            lst = []
            for it in items:
                if self.inclusive:
                    acc = fn(acc, it)
                    lst.append(acc)
                else:
                    lst.append(acc)
                    acc = fn(acc, it)
            out.append(lst)
        return multiplexer.localize(
            mex, HostShards(shards.num_workers, out))

    def _compute_device(self, shards: DeviceShards):
        mex = shards.mesh_exec
        cap = shards.cap
        leaves, treedef = jax.tree.flatten(shards.tree)
        initial = self.initial
        key = ("prefix_sum", self.inclusive, cap, treedef,
               tuple((l.dtype, l.shape[2:]) for l in leaves))

        def build():
            def f(counts_dev, *ls):
                mask = jnp.arange(cap) < counts_dev[0, 0]
                outs = []
                for l in ls:
                    x = l[0]
                    m = mask.reshape(mask.shape + (1,) * (x.ndim - 1))
                    xm = jnp.where(m, x, 0)
                    incl = jnp.cumsum(xm, axis=0, dtype=x.dtype)
                    local_total = incl[-1]
                    totals = lax.all_gather(local_total, AXIS)  # [W, ...]
                    widx = lax.axis_index(AXIS)
                    prev = jnp.where(
                        (jnp.arange(totals.shape[0]) < widx
                         ).reshape((-1,) + (1,) * (totals.ndim - 1)),
                        totals, 0).sum(axis=0)
                    scan = incl if self.inclusive else incl - xm
                    outs.append((scan + prev + jnp.asarray(initial)
                                 .astype(x.dtype))[None])
                return tuple(outs)

            return mex.smap(f, 1 + len(leaves))

        fn = mex.cached(key, build)
        out = fn(shards.counts_device(), *leaves)
        tree = jax.tree.unflatten(treedef, list(out))
        return DeviceShards(mex, tree, shards.counts.copy())


def PrefixSum(dia: DIA, fn=None, initial: Any = 0, inclusive=True) -> DIA:
    return DIA(PrefixSumNode(dia.context, dia._link(), fn, initial,
                             inclusive))
