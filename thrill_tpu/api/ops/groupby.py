"""GroupByKey / GroupToIndex.

Reference: thrill/api/group_by_key.hpp:47 — hash-partition shuffle, local
sort (with spill + multiway merge), then the user function over each
key's iterator. The group function is inherently per-group and arbitrary
(it sees all values of one key), so after a device-side exchange + sort
the per-group application runs on the host — the device handles the
communication-heavy phases, Python the sequential group fold. Vectorized
aggregations should use ReduceByKey, which stays fully on device.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from ...common import hashing
from ...core import keys as keymod
from ...data import exchange
from ...data.shards import DeviceShards, HostShards
from ..dia import DIA
from ..dia_base import DIABase


class GroupByKeyNode(DIABase):
    def __init__(self, ctx, link, key_fn: Callable, group_fn: Callable
                 ) -> None:
        super().__init__(ctx, "GroupByKey", [link])
        self.key_fn = key_fn
        self.group_fn = group_fn

    def compute(self):
        shards = self.parents[0].pull()
        W = self.context.num_workers
        key_fn = self.key_fn
        if isinstance(shards, DeviceShards):
            # device exchange by key hash, then group on host
            if W > 1:
                import jax.numpy as jnp

                def dest(tree, mask, widx):
                    words = keymod.encode_key_words(key_fn(tree))
                    h = hashing.hash_key_words(words)
                    return (h % jnp.uint64(W)).astype(jnp.int32)

                shards = exchange.exchange(
                    shards, dest, ("groupby_dest", key_fn, W))
            shards = shards.to_host_shards()
        else:
            shards = exchange.host_exchange(
                shards, lambda it: hashing.stable_host_hash(key_fn(it)))
        out = []
        for items in shards.lists:
            groups = {}
            for it in items:
                groups.setdefault(_hashable(key_fn(it)), []).append(it)
            out.append([self.group_fn(k, vs) for k, vs in groups.items()])
        return HostShards(W, out)


def _hashable(k: Any):
    if isinstance(k, np.ndarray):
        return tuple(k.tolist())
    if isinstance(k, np.generic):
        return k.item()
    if isinstance(k, tuple):
        return tuple(_hashable(x) for x in k)
    return k


class GroupToIndexNode(DIABase):
    """Index-range variant (reference: api/group_to_index.hpp:42)."""

    def __init__(self, ctx, link, index_fn, group_fn, size, neutral) -> None:
        super().__init__(ctx, "GroupToIndex", [link])
        self.index_fn = index_fn
        self.group_fn = group_fn
        self.size = int(size)
        self.neutral = neutral

    def compute(self):
        shards = self.parents[0].pull()
        if isinstance(shards, DeviceShards):
            shards = shards.to_host_shards()
        W = self.context.num_workers
        n = self.size
        bounds = [(w * n) // W for w in range(W + 1)]
        buckets = [dict() for _ in range(W)]
        for items in shards.lists:
            for it in items:
                i = int(self.index_fn(it))
                if not 0 <= i < n:
                    continue
                w = int(np.searchsorted(bounds[1:], i, side="right"))
                buckets[w].setdefault(i, []).append(it)
        out = []
        for w in range(W):
            lst = []
            for i in range(bounds[w], bounds[w + 1]):
                if i in buckets[w]:
                    lst.append(self.group_fn(i, buckets[w][i]))
                else:
                    lst.append(self.neutral)
            out.append(lst)
        return HostShards(W, out)


def GroupByKey(dia: DIA, key_fn, group_fn) -> DIA:
    return DIA(GroupByKeyNode(dia.context, dia._link(), key_fn, group_fn))


def GroupToIndex(dia: DIA, index_fn, group_fn, size, neutral=None) -> DIA:
    return DIA(GroupToIndexNode(dia.context, dia._link(), index_fn,
                                group_fn, size, neutral))
