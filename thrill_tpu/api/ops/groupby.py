"""GroupByKey / GroupToIndex.

Reference: thrill/api/group_by_key.hpp:47 — hash-partition shuffle, local
sort (with spill + multiway merge), then the user function over each
key's iterator (group_by_key.hpp:188-216).

TPU-native design: the communication-heavy phases (hash exchange, key
sort, run segmentation) always run on device. What happens per group
depends on the group function:

* ``device_fn`` given — FULLY on device: the user receives the sorted
  item tree plus per-item segment ids and folds each group with
  ``jax.ops.segment_*``-family ops; one result row per key, no Python
  per item or per group.
* only ``group_fn`` — the device hands back *sorted* columns; groups
  are delimited with one vectorized boundary scan on the host and
  ``group_fn`` is applied per key run (per-group Python, which an
  arbitrary sequential fold inherently requires — the reference's host
  iterator loop is the same shape). Vectorized aggregations should
  prefer ``device_fn`` or ReduceByKey.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

from ...common import hashing
from ...core import keys as keymod
from ...data import exchange
from ...data.shards import DeviceShards, HostShards
from ..dia import DIA
from ..dia_base import DIABase
from ...common.partition import dense_range_bounds


class GroupByKeyNode(DIABase):
    # grouping wants workspace (reference: GroupByKey registers
    # DIAMemUse::Max for its sort-and-spill buffer,
    # api/group_by_key.hpp); the host path sizes its EM group buffer
    # from the grant, the device paths bound memory by construction
    MEM_USE = "max"

    def __init__(self, ctx, link, key_fn: Callable, group_fn: Callable,
                 device_fn: Optional[Callable] = None) -> None:
        super().__init__(ctx, "GroupByKey", [link])
        self.key_fn = key_fn
        self.group_fn = group_fn
        self.device_fn = device_fn

    def compute(self):
        shards = self.parents[0].pull()
        W = self.context.num_workers
        key_fn = self.key_fn
        if isinstance(shards, DeviceShards):
            if self.group_fn is None and self.device_fn is None:
                raise ValueError(
                    "GroupByKey needs group_fn (host fold) or device_fn "
                    "(vectorized segment fold)")
            shards = self._exchange_by_key_hash(shards)
            if self.device_fn is not None:
                return self._group_device(shards)
            return self._group_sorted_host(shards)
        if self.group_fn is None:
            raise ValueError(
                "GroupByKey over host storage requires group_fn "
                "(device_fn needs columnar device shards)")
        from ...core.em_table import EMGroupBuffer
        from ...data import multiplexer
        from ...data.block_pool import spill_pool
        # hash and hashable key computed ONCE per item and carried
        # through the exchange as (h, k, item) — the shuffle dest and
        # the group buffer reuse them (the reduce path's carry scheme).
        # When this node owns its input, each source list is released
        # as soon as its decorated copy exists, so decoration never
        # doubles peak RAM (Sort's release discipline).
        owns_input = self.parents[0].node.state == "DISPOSED"
        pre_lists = []
        for lst in shards.lists:
            pre_lists.append([(hashing.stable_host_hash(
                kh := _hashable(key_fn(it))), kh, it) for it in lst])
            if owns_input:
                lst.clear()
        pre = HostShards(W, pre_lists)
        del pre_lists
        # hash-partition target (MixStream-eligible): under
        # THRILL_TPU_HOST_MIX=1 a group's items arrive in frame order,
        # so group_fn must be iteration-order-insensitive — the
        # documented contract for opting in (CatStream default keeps
        # source-rank order exactly as before)
        shards = multiplexer.host_exchange(
            self.context.mesh_exec, pre, lambda t: t[0],
            reason="groupby", rank_order=False)
        # grouping phase is memory-bounded: over the negotiated grant,
        # the buffer spills (hash, seq)-sorted runs and the emit merges
        # them so each group streams through RAM (reference:
        # api/group_by_key.hpp:188-216 sorted-run spill + multiway
        # merge); with no spill this is the historical dict path
        pool = spill_pool(self.context.config.spill_dir,
                          self.mem_limit)
        stats: dict = {}
        out = []
        try:
            for items in shards.lists:
                buf = EMGroupBuffer(pool, self.mem_limit,
                                    stats=stats or None)
                stats = buf.stats
                for h, k, it in items:
                    buf.add(k, it, h=h)
                items.clear()    # exchange output is ours: free as we go
                out.append([self.group_fn(k, vs)
                            for k, vs in buf.groups()])
                buf.close()
        finally:
            pool.close()
        self._em_stats = stats
        if stats.get("spills") and self.context.logger.enabled:
            self.context.logger.line(event="groupby_spill",
                                     node=self.label, dia_id=self.id,
                                     **stats)
        return HostShards(W, out)

    # -- device phases --------------------------------------------------
    def _exchange_by_key_hash(self, shards: DeviceShards) -> DeviceShards:
        """Hash exchange (W > 1); grouping sorts afterwards."""
        import jax.numpy as jnp

        W = self.context.num_workers
        key_fn = self.key_fn
        if W == 1:
            return shards

        def dest(tree, mask, widx):
            words = keymod.encode_key_words(key_fn(tree))
            h = hashing.hash_key_words(words)
            return (h % jnp.uint64(W)).astype(jnp.int32)

        return exchange.exchange(shards, dest,
                                 ("groupby_dest", key_fn, W))

    def _group_device(self, shards: DeviceShards) -> DeviceShards:
        """Fully-device grouping: sort by key words, segment ids, then
        the user's vectorized fold (jax.ops.segment_* family).
        The hash-exchange input may be an optimistic (capacity-cached)
        shuffle still owing its overflow check — validated on entry.

        ``device_fn(sorted_tree, segment_ids, num_segments)`` must
        return a pytree of arrays with leading dim ``num_segments``
        (static == shard capacity); row j is group j's result. Invalid
        rows carry segment id num_segments - 1 only when that slot is
        unused (padded capacity), so segment_* ops can ignore them.
        """
        import jax
        import jax.numpy as jnp

        mex = shards.mesh_exec
        shards.validate_pending()
        cap = shards.cap
        key_fn, device_fn = self.key_fn, self.device_fn
        leaves, treedef = jax.tree.flatten(shards.tree)
        key = ("groupby_device", key_fn, device_fn, cap, treedef,
               tuple((l.dtype, l.shape[2:]) for l in leaves))
        holder = {}

        def build():
            def f(counts_dev, *ls):
                count = counts_dev[0, 0]
                valid = jnp.arange(cap) < count
                tree = jax.tree.unflatten(treedef, [l[0] for l in ls])
                _, tree_s, valid_s, starts = _sorted_key_runs(
                    tree, valid, key_fn)
                seg_ids = jnp.cumsum(starts.astype(jnp.int32)) - 1
                nseg = jnp.sum(starts.astype(jnp.int32))
                # park invalid rows in the last (padded, hence unused)
                # segment slot; nseg <= count < cap whenever they exist
                seg_ids = jnp.where(valid_s, seg_ids, cap - 1)
                out_tree = device_fn(tree_s, seg_ids, cap)
                out_leaves, out_td = jax.tree.flatten(out_tree)
                holder["treedef"] = out_td
                return (nseg[None, None].astype(jnp.int32),
                        *[l[None] for l in out_leaves])

            return mex.smap(f, 1 + len(leaves)), holder

        fn, h = mex.cached(key, build)
        out = fn(shards.counts_device(), *leaves)
        tree = jax.tree.unflatten(h["treedef"], list(out[1:]))
        return DeviceShards(mex, tree, out[0])

    def _group_sorted_host(self, shards: DeviceShards) -> HostShards:
        """Arbitrary group_fn: device sort + ONE vectorized boundary
        scan per worker; Python runs once per group, never per item."""
        import jax
        import jax.numpy as jnp

        out = _group_host_radix_impl(shards, self.key_fn, self.group_fn)
        if out is not None:
            return out
        mex = shards.mesh_exec
        cap = shards.cap
        key_fn = self.key_fn
        leaves, treedef = jax.tree.flatten(shards.tree)
        key = ("groupby_sort", key_fn, cap, treedef,
               tuple((l.dtype, l.shape[2:]) for l in leaves))

        def build():
            def f(counts_dev, *ls):
                count = counts_dev[0, 0]
                valid = jnp.arange(cap) < count
                tree = jax.tree.unflatten(treedef, [l[0] for l in ls])
                _, tree_s, _, starts = _sorted_key_runs(
                    tree, valid, key_fn)
                out_leaves = jax.tree.leaves(tree_s)
                return (starts[None], *[l[None] for l in out_leaves])

            return mex.smap(f, 1 + len(leaves))

        fn = mex.cached(key, build)
        out = fn(shards.counts_device(), *leaves)
        starts_all = mex.fetch(out[0])
        sorted_shards = DeviceShards(
            mex, jax.tree.unflatten(treedef, list(out[1:])),
            shards.counts.copy())
        group_fn, key_fn_ = self.group_fn, self.key_fn
        lists = []
        for w, items in enumerate(
                sorted_shards.to_host_shards("groupbykey-group-fn").lists):
            n = len(items)
            bounds = np.flatnonzero(starts_all[w, :n]).tolist() + [n]
            lists.append([
                group_fn(_hashable(key_fn_(items[lo])), items[lo:hi])
                for lo, hi in zip(bounds[:-1], bounds[1:])])
        return HostShards(self.context.num_workers, lists)


def _group_host_radix_impl(shards, key_fn, group_fn):
    """CPU-backend grouping: native hash-group (one probe pass,
    core/host_radix.py), mirroring reduce._host_reduce_shards — the
    XLA single-core sort is the wrong engine when device buffers are
    host memory, and GroupByKey only needs equal keys ADJACENT, not
    key-sorted, so the open-addressing table replaces the 4-pass radix
    argsort (groups come out in first-appearance order, which the
    GroupByKey contract — like the reference's hash-partitioned
    grouping — does not constrain). Returns None when inapplicable."""
    import jax

    from ...core import host_radix

    mex = shards.mesh_exec
    if not host_radix.eligible(mex):
        return None
    # this path itemizes into host lists without going through
    # to_host_shards — log the storage demotion with the same event so
    # the DEVICE_COVERAGE audit sees every device->host transition
    log = getattr(mex, "logger", None)
    if log is not None and log.enabled:
        log.line(event="device_to_host", reason="groupbykey-group-fn",
                 items=int(shards.counts.sum()))
    leaves, treedef = jax.tree.flatten(shards.tree)
    leaves_np = [np.asarray(l) for l in leaves]
    W = mex.num_workers
    # only the sort/encode machinery may fall back (trace-only key_fn);
    # group_fn is an arbitrary, possibly side-effecting host fold and
    # must NOT be silently re-run by the slow path after a mid-loop
    # failure — its exceptions propagate
    per_worker = []
    try:
        for w in range(W):
            cnt = int(shards.counts[w])
            if cnt == 0:
                per_worker.append((0, None, None))
                continue
            tree = jax.tree.unflatten(treedef,
                                      [l[w][:cnt] for l in leaves_np])
            words = keymod.encode_key_words_np(key_fn(tree))
            perm, lens = host_radix.hash_group(words)
            srt = [host_radix.gather_rows(np.ascontiguousarray(a), perm)
                   for a in jax.tree.leaves(tree)]
            bounds = [0] + np.cumsum(lens).tolist()
            per_worker.append((cnt, srt, bounds))
    except Exception:
        return None
    lists = []
    for cnt, srt, bounds in per_worker:
        if cnt == 0:
            lists.append([])
            continue
        from ...data.shards import itemize
        items = itemize(jax.tree.unflatten(treedef, srt))
        lists.append([
            group_fn(_hashable(key_fn(items[lo])), items[lo:hi])
            for lo, hi in zip(bounds[:-1], bounds[1:])])
    return HostShards(W, lists)


def _sorted_key_runs(tree, valid, key_fn):
    """Traced preamble shared by both grouping paths: key-sort the items
    (invalid last) and mark run starts. Returns
    (sorted_words, sorted_tree, sorted_valid, run_starts)."""
    from ...core import segmented

    words = keymod.encode_key_words(key_fn(tree))
    words_s, tree_s, valid_s, _ = segmented.sort_by_key_words(
        words, tree, valid)
    starts = segmented.segment_boundaries(words_s, valid_s)
    return words_s, tree_s, valid_s, starts


def _hashable(k: Any):
    if isinstance(k, np.ndarray):
        return tuple(k.tolist())
    if isinstance(k, np.generic):
        return k.item()
    if isinstance(k, tuple):
        return tuple(_hashable(x) for x in k)
    return k


class GroupToIndexNode(DIABase):
    """Index-range variant (reference: api/group_to_index.hpp:42)."""

    def __init__(self, ctx, link, index_fn, group_fn, size, neutral,
                 device_fn: Optional[Callable] = None) -> None:
        super().__init__(ctx, "GroupToIndex", [link])
        self.index_fn = index_fn
        self.group_fn = group_fn
        self.size = int(size)
        if self.size <= 0:
            raise ValueError("GroupToIndex requires a positive size")
        self.neutral = neutral
        self.device_fn = device_fn

    def compute(self):
        shards = self.parents[0].pull()
        if isinstance(shards, DeviceShards) and self.device_fn is not None:
            return self._compute_device(shards)
        if self.group_fn is None:
            raise ValueError(
                "GroupToIndex over host storage requires group_fn "
                "(device_fn needs columnar device shards)")
        if isinstance(shards, DeviceShards):
            shards = shards.to_host_shards("grouptoindex")
        W = self.context.num_workers
        mex = self.context.mesh_exec
        n = self.size
        index_fn = self.index_fn
        bounds = dense_range_bounds(n, W).tolist()

        from ...data import multiplexer

        # out-of-range indices are dropped AT THE SOURCE — never
        # serialized or shipped cross-process just to be filtered on
        # arrival
        shards = HostShards(W, [[it for it in l
                                 if 0 <= int(index_fn(it)) < n]
                                for l in shards.lists])

        def dest(it):
            i = int(index_fn(it))
            return int(np.searchsorted(bounds[1:], i, side="right"))

        shards = multiplexer.host_exchange(mex, shards, dest,
                                           reason="grouptoindex")
        owned = set(mex.local_workers) if multiplexer.multiprocess(mex) \
            else set(range(W))
        out = []
        for w in range(W):
            if w not in owned:
                out.append([])
                continue
            groups: dict = {}
            for it in shards.lists[w]:
                i = int(index_fn(it))
                if bounds[w] <= i < bounds[w + 1]:
                    groups.setdefault(i, []).append(it)
            out.append([self.group_fn(i, groups[i]) if i in groups
                        else self.neutral
                        for i in range(bounds[w], bounds[w + 1])])
        return HostShards(W, out)


    def _compute_device(self, shards: DeviceShards) -> DeviceShards:
        """Device GroupToIndex: range exchange, then the user's
        ``device_fn(tree, local_index_ids, num_segments)`` folds each
        index's items with segment_* ops (one output row per local
        index, dense). No sort is needed — segment scatters accept
        unsorted ids. Invalid/out-of-range rows carry id num_segments,
        which scatter semantics drop. ``neutral`` (scalar or pytree)
        fills indices that received no items.
        """
        import jax
        import jax.numpy as jnp

        mex = shards.mesh_exec
        W = self.context.num_workers
        n = self.size
        index_fn, device_fn = self.index_fn, self.device_fn
        neutral = self.neutral
        bounds = dense_range_bounds(n, W)

        if W > 1:
            bounds_dev = jnp.asarray(bounds)

            def dest(tree, mask, widx):
                idx = jnp.asarray(index_fn(tree)).astype(jnp.int64)
                return (jnp.searchsorted(bounds_dev[1:], idx,
                                         side="right")).astype(jnp.int32)

            # destination program depends only on index_fn/n/W — never
            # on device_fn, so different folds share one executable
            shards = exchange.exchange(shards, dest,
                                       ("g2i_dest", index_fn, n, W))
            shards.validate_pending()  # optimistic-exchange heal point

        cap = shards.cap
        leaves, treedef = jax.tree.flatten(shards.tree)
        local_sizes = (bounds[1:] - bounds[:-1]).astype(np.int64)
        out_cap = max(1, int(local_sizes.max()))
        import jax as _jax
        neutral_token = (None if neutral is None else
                         (str(_jax.tree.structure(neutral)),
                          tuple(repr(x) for x in _jax.tree.leaves(neutral))))
        key = ("g2i_device", index_fn, device_fn, n, neutral_token, cap,
               out_cap, treedef,
               tuple((l.dtype, l.shape[2:]) for l in leaves))
        holder = {}

        def build():
            def f(counts_dev, range_start, *ls):
                valid = jnp.arange(cap) < counts_dev[0, 0]
                tree = jax.tree.unflatten(treedef, [l[0] for l in ls])
                idx = jnp.asarray(index_fn(tree)).astype(jnp.int64)
                local_idx = idx - range_start[0, 0]
                in_range = valid & (local_idx >= 0) & (local_idx
                                                       < out_cap)
                ids = jnp.where(in_range, local_idx, out_cap
                                ).astype(jnp.int32)
                out_tree = device_fn(tree, ids, out_cap)
                if neutral is not None:
                    cnt = jnp.zeros(out_cap + 1, jnp.int32
                                    ).at[ids].add(1)[:out_cap]

                    def fill(leaf, nval):
                        m = (cnt > 0).reshape(
                            (out_cap,) + (1,) * (leaf.ndim - 1))
                        return jnp.where(m, leaf,
                                         jnp.asarray(nval, leaf.dtype))

                    if jax.tree.structure(out_tree) == \
                            jax.tree.structure(neutral):
                        out_tree = jax.tree.map(fill, out_tree, neutral)
                    else:
                        out_tree = jax.tree.map(
                            lambda l: fill(l, neutral), out_tree)
                out_leaves, out_td = jax.tree.flatten(out_tree)
                holder["treedef"] = out_td
                return tuple(l[None] for l in out_leaves)

            return mex.smap(f, 2 + len(leaves)), holder

        fn, h = mex.cached(key, build)
        out = fn(shards.counts_device(),
                 mex.put_small(bounds[:-1].astype(np.int64)[:, None]), *leaves)
        tree = jax.tree.unflatten(h["treedef"], list(out))
        # per-worker result counts are the host-known range sizes — no
        # device round trip needed
        return DeviceShards(mex, tree, local_sizes.copy())


def GroupByKey(dia: DIA, key_fn, group_fn, device_fn=None) -> DIA:
    return DIA(GroupByKeyNode(dia.context, dia._link(), key_fn, group_fn,
                              device_fn=device_fn))


def GroupToIndex(dia: DIA, index_fn, group_fn, size, neutral=None,
                 device_fn=None) -> DIA:
    return DIA(GroupToIndexNode(dia.context, dia._link(), index_fn,
                                group_fn, size, neutral,
                                device_fn=device_fn))
