"""Zip / ZipWithIndex / ZipWindow.

Reference: thrill/api/zip.hpp:77 (size prefix sums per partition,
Stream::Scatter realignment of misaligned partitions, Cut/Pad variants),
zip_with_index.hpp:38, zip_window.hpp:175.

Device path: realignment is an index-range exchange — every item's
destination is the worker owning its global index under the target
partition (the first DIA's partition, like the reference which scatters
the other DIAs to align with the first), then a fused local zip.
"""

from __future__ import annotations

from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...data import exchange
from ...data.shards import DeviceShards, HostShards
from ..dia import DIA
from ..dia_base import DIABase
from ...common.partition import dense_range_bounds


def _realign_device(shards: DeviceShards, target_bounds: np.ndarray,
                    n_out: int, token, min_cap: int = 1) -> DeviceShards:
    """Move items so worker w holds global indices
    [target_bounds[w], target_bounds[w+1]) of this DIA (items beyond
    n_out are dropped). Order within workers is preserved because the
    exchange is stable and sources arrive rank-ordered."""
    mex = shards.mesh_exec
    W = mex.num_workers
    offsets = np.concatenate([[0], np.cumsum(shards.counts)])[:-1]
    bounds_dev = jnp.asarray(target_bounds[1:])  # upper edges [W]

    def dest(tree, mask, widx):
        leaves = jax.tree.leaves(tree)
        cap = leaves[0].shape[0]
        off = jnp.asarray(offsets)[widx]
        g = off + jnp.arange(cap, dtype=jnp.int64)
        d = jnp.searchsorted(bounds_dev, g, side="right").astype(jnp.int32)
        # drop items past n_out by sending them nowhere (mask them out)
        d = jnp.where(g < n_out, d, W)
        return d

    # dest == W marks dropped items; exchange clips dest, so pre-mask:
    out = exchange.exchange(_mask_tail(shards, n_out), dest,
                            ("realign", token, W), min_cap=min_cap)
    # heal an optimistic capacity miss HERE: the zip path re-wraps the
    # tree into fresh DeviceShards (pad counts), which would drop the
    # deferred check
    out.validate_pending()
    return out


def _mask_tail(shards: DeviceShards, n_out: int) -> DeviceShards:
    """Trim counts so only the first n_out global items stay valid."""
    offsets = np.concatenate([[0], np.cumsum(shards.counts)])[:-1]
    new_counts = np.clip(n_out - offsets, 0, shards.counts)
    return DeviceShards(shards.mesh_exec, shards.tree,
                        new_counts.astype(np.int64))


def _realign_or_keep(p: DeviceShards, tb: np.ndarray, n_out: int, token,
                     min_cap: int = 1):
    """Realign to target bounds, or keep in place when the partition
    already matches (the no-exchange fast path). Returns
    (shards, moved)."""
    off = np.concatenate([[0], np.cumsum(p.counts)])
    same = (len(off) == len(tb) and
            np.array_equal(np.clip(off, 0, n_out), tb))
    if same:
        return _mask_tail(p, n_out), False
    return _realign_device(p, tb, n_out, token, min_cap=min_cap), True


class ZipNode(DIABase):
    def __init__(self, ctx, links, zip_fn: Optional[Callable],
                 mode: str) -> None:
        super().__init__(ctx, "Zip", links)
        self.zip_fn = zip_fn
        self.mode = mode

    def compute_plan(self):
        from .. import fusion
        res = self._compute_any()
        if isinstance(res, fusion.FusionPlan):
            return res
        return fusion.wrap(res)

    def compute(self):
        from .. import fusion
        res = self._compute_any()
        if isinstance(res, fusion.FusionPlan):
            return res.finish()
        return res

    def _compute_any(self):
        pulls = [l.pull() for l in self.parents]
        if any(isinstance(p, HostShards) for p in pulls):
            # only MIXED storage demotes; unequal sizes (cut/pad) stay
            # device-resident via the realign exchange below
            pulls = [p.to_host_shards("zip-mixed-storage")
                     if isinstance(p, DeviceShards) else p
                     for p in pulls]
            return self._compute_host(pulls)
        return self._compute_device(pulls)

    def _out_size(self, totals: List[int]) -> int:
        if self.mode == "cut":
            return min(totals)
        if self.mode == "pad":
            return max(totals)
        if len(set(totals)) != 1:
            raise ValueError(
                f"Zip: unequal sizes {totals}; use mode='cut' or 'pad'")
        return totals[0]

    def _compute_device(self, pulls: List[DeviceShards]):
        mex = pulls[0].mesh_exec
        W = mex.num_workers
        totals = [p.total for p in pulls]
        n_out = self._out_size(totals)
        if self.mode == "pad" and max(totals) != min(totals):
            # pad stays on the device: realign EVERY input to an even
            # n_out partition; the exchange's receive buffers are
            # zero-initialized, so the short inputs' missing tail slots
            # are already default-constructed (zero) items — exactly the
            # reference's ZipPad semantics (api/zip.hpp Pad variant)
            tb = dense_range_bounds(n_out, W)
            counts = (tb[1:] - tb[:-1]).astype(np.int64)
            aligned = []
            for i, p in enumerate(pulls):
                a, moved = _realign_or_keep(
                    p, tb, n_out, (self.id, i, "pad"),
                    min_cap=int(counts.max()))
                if W == 1 and np.any(a.counts < counts):
                    # slots beyond the received prefix become the pad
                    # items; the W>1 exchange zero-fills them already,
                    # but the W==1 no-movement shortcut does not (a
                    # kept W>1 input always has exactly target counts)
                    a = _zero_beyond_count(a)
                # explicit zero-extension keeps the counts<=cap invariant
                # (pads past a short input's cap must be zeros)
                a = _repad(a, max(int(counts.max()), a.cap))
                aligned.append(DeviceShards(mex, a.tree, counts.copy()))
            return self._fused_zip(mex, aligned, counts)
        # target partition = first DIA's distribution truncated to n_out
        c0 = np.clip(pulls[0].counts,
                     0, None)
        tb = np.concatenate([[0], np.cumsum(c0)])
        tb = np.clip(tb, 0, n_out)
        aligned = [_realign_or_keep(p, tb, n_out, (self.id, i))[0]
                   for i, p in enumerate(pulls)]
        counts = (tb[1:] - tb[:-1]).astype(np.int64)
        return self._fused_zip(mex, aligned, counts)

    def _fused_zip(self, mex, aligned: List[DeviceShards],
                   counts: np.ndarray):
        # fused local zip
        cap = max(a.cap for a in aligned)
        aligned = [_repad(a, cap) for a in aligned]
        from .. import fusion
        if fusion.enabled():
            # multi-source head plan: the local zip traces into the
            # consumer's stitched program (downstream ops ride along)
            zip_fn = self.zip_fn

            def trace(fctx, states, _bound):
                trees = [t for t, _m in states]
                out = zip_fn(*trees) if zip_fn else tuple(trees)
                return out, states[0][1]

            head = fusion.Segment(label="Zip",
                                  token=("zip_fuse_head", zip_fn,
                                         self.mode),
                                  trace=trace, already_compact=True,
                                  dia_id=self.id)
            for a in aligned:
                a.validate_pending()
            return fusion.FusionPlan(mex, aligned, head=head,
                                     known_counts=counts)
        tree = _fused_map_trees(mex, [a.tree for a in aligned],
                                self.zip_fn, "zip_fuse")
        return DeviceShards(mex, tree, counts)

    def _compute_host(self, pulls: List[HostShards]):
        W = pulls[0].num_workers
        from ...data import multiplexer
        mex = self.context.mesh_exec
        pulls = [multiplexer.ensure_replicated(mex, p, "zip-host")
                 for p in pulls]
        lists = [[it for l in p.lists for it in l] for p in pulls]
        totals = [len(l) for l in lists]
        n_out = self._out_size(totals)
        if self.mode == "pad":
            # pad with default-constructed items (reference ZipPad uses
            # default-constructed T), derived from each side's schema
            pads = [_default_item(l, pulls) for l in lists]
            lists = [l + [pads[i]] * (n_out - len(l))
                     for i, l in enumerate(lists)]
        zf = self.zip_fn or (lambda *xs: tuple(xs))
        zipped = [zf(*vals) for vals in zip(*[l[:n_out] for l in lists])]
        bounds = dense_range_bounds(n_out, W).tolist()
        return multiplexer.localize(
            mex, HostShards(W, [zipped[bounds[w]:bounds[w + 1]]
                                for w in range(W)]))


def _default_item(items, _pulls):
    """Zero/default-constructed item matching this side's schema."""
    import jax
    if not items:
        return None   # fully empty side: nothing to zip anyway
    probe = items[0]
    return jax.tree.map(
        lambda l: (np.zeros_like(np.asarray(l))
                   if isinstance(l, (np.ndarray, np.generic))
                   else type(l)()), probe)


def _fused_map_trees(mex, trees: List, fn: Optional[Callable],
                     key_prefix: str):
    """One jitted program applying ``fn(*trees)`` (or tuple-of-trees
    when fn is None) per worker over several same-cap shard trees —
    the shared fusion driver for Zip and ZipWindow device paths."""
    all_leaves, treedefs = [], []
    for t in trees:
        ls, td = jax.tree.flatten(t)
        all_leaves.append(ls)
        treedefs.append(td)
    nums = [len(ls) for ls in all_leaves]
    key = (key_prefix, fn, tuple(treedefs),
           tuple(tuple((l.dtype, l.shape[1:]) for l in ls)
                 for ls in all_leaves))
    holder = {}

    def build():
        def f(*flat):
            trees_in = []
            i = 0
            for td, k in zip(treedefs, nums):
                trees_in.append(jax.tree.unflatten(
                    td, [x[0] for x in flat[i:i + k]]))
                i += k
            out = fn(*trees_in) if fn else tuple(trees_in)
            out_leaves, out_td = jax.tree.flatten(out)
            holder["treedef"] = out_td
            return tuple(l[None] for l in out_leaves)

        return mex.smap(f, sum(nums)), holder

    g, h = mex.cached(key, build)
    out = g(*[l for ls in all_leaves for l in ls])
    return jax.tree.unflatten(h["treedef"], list(out))


def _zero_beyond_count(shards: DeviceShards) -> DeviceShards:
    """Zero every slot at or past this worker's valid count (default-
    constructed pad items for ZipPad semantics)."""
    mex = shards.mesh_exec
    cap = shards.cap
    leaves, treedef = jax.tree.flatten(shards.tree)
    key = ("zero_beyond", cap, treedef,
           tuple((l.dtype, l.shape[2:]) for l in leaves))

    def build():
        def f(counts_dev, *ls):
            count = counts_dev[0, 0]
            valid = jnp.arange(cap) < count
            outs = []
            for l in ls:
                x = l[0]
                m = valid.reshape((cap,) + (1,) * (x.ndim - 1))
                outs.append(jnp.where(m, x, jnp.zeros_like(x))[None])
            return tuple(outs)

        return mex.smap(f, 1 + len(leaves))

    fn = mex.cached(key, build)
    out = fn(shards.counts_device(), *leaves)
    return DeviceShards(mex, jax.tree.unflatten(treedef, list(out)),
                        shards.counts.copy())


def _repad(shards: DeviceShards, cap: int) -> DeviceShards:
    if shards.cap == cap:
        return shards
    pad = cap - shards.cap
    tree = jax.tree.map(
        lambda l: jnp.pad(l, [(0, 0), (0, pad)] + [(0, 0)] * (l.ndim - 2)),
        shards.tree)
    return DeviceShards(shards.mesh_exec, tree, shards.counts)


def _zwi_default(it, i):
    return (it, i)


class ZipWithIndexNode(DIABase):
    """zip_fn(item, global_index) (reference: api/zip_with_index.hpp:38)."""

    def __init__(self, ctx, link, zip_fn: Optional[Callable]) -> None:
        super().__init__(ctx, "ZipWithIndex", [link])
        self.zip_fn = zip_fn

    def _fuse_segment(self):
        """Global indices computed IN-TRACE: position within the valid
        mask plus the cross-worker exclusive offset (an all_gather of
        counts inside the stitched program) — no host counts, no
        offsets upload."""
        from .. import fusion
        zf = self.zip_fn or _zwi_default

        def trace(fctx, tree, mask, _bound):
            pos = jnp.cumsum(mask.astype(jnp.int64)) - 1
            g = fctx.exclusive_offset(mask) + pos
            return zf(tree, g), mask

        return fusion.Segment(label="ZipWithIndex",
                              token=("zip_index_fused", self.zip_fn),
                              trace=trace, preserves_counts=True,
                              dia_id=self.id)

    def compute_plan(self):
        from .. import fusion
        plan = fusion.pull_plan(self.parents[0])
        if not plan.stitchable:
            return fusion.wrap(self._compute_on(plan.finish()))
        plan.append(self._fuse_segment())
        return plan

    def compute(self):
        return self.compute_plan().finish()

    def _compute_on(self, shards):
        zf = self.zip_fn or _zwi_default
        if isinstance(shards, HostShards):
            from ...data import multiplexer
            counts = multiplexer.global_counts(
                self.context.mesh_exec, shards)
            offsets = np.concatenate([[0], np.cumsum(counts)])
            out = []
            for w, items in enumerate(shards.lists):
                out.append([zf(it, int(offsets[w]) + i)
                            for i, it in enumerate(items)])
            return HostShards(shards.num_workers, out)

        mex = shards.mesh_exec
        cap = shards.cap
        offsets = np.concatenate([[0], np.cumsum(shards.counts)])[:-1]
        leaves, treedef = jax.tree.flatten(shards.tree)
        key = ("zip_index", self.zip_fn,
               cap, treedef, tuple((l.dtype, l.shape[2:]) for l in leaves))
        holder = {}

        def build():
            def f(off, *ls):
                tree = jax.tree.unflatten(treedef, [l[0] for l in ls])
                g = off[0, 0] + jnp.arange(cap, dtype=jnp.int64)
                out = zf(tree, g)
                out_leaves, out_td = jax.tree.flatten(out)
                holder["treedef"] = out_td
                return tuple(l[None] for l in out_leaves)

            return mex.smap(f, 1 + len(leaves)), holder

        fn, h = mex.cached(key, build)
        out = fn(mex.put_small(offsets.astype(np.int64)[:, None]), *leaves)
        tree = jax.tree.unflatten(h["treedef"], list(out))
        return DeviceShards(mex, tree, shards.counts.copy())


def Zip(dias: List[DIA], zip_fn=None, mode: str = "strict") -> DIA:
    assert len(dias) >= 2
    return DIA(ZipNode(dias[0].context, [d._link() for d in dias],
                       zip_fn, mode))


def ZipWithIndex(dia: DIA, zip_fn=None) -> DIA:
    return DIA(ZipWithIndexNode(dia.context, dia._link(), zip_fn))


class ZipWindowNode(DIABase):
    """Zip fixed-size windows across DIAs
    (reference: api/zip_window.hpp:175): DIA i is consumed in chunks of
    window[i] items; output item j is the tuple of chunk j from each.

    Device path (``device_fn``): each input is realigned so worker w
    holds exactly output chunks [b_w, b_{w+1}) — an index-range exchange
    to chunk-aligned bounds — then reshaped to [chunk_cap, window_i,
    ...] window batches; ``device_fn(*chunk_trees)`` maps them to output
    items like the Window/FlatWindow device contract."""

    def __init__(self, ctx, links, window, zip_fn,
                 device_fn: Optional[Callable] = None) -> None:
        super().__init__(ctx, "ZipWindow", links)
        self.window = tuple(int(w) for w in window)
        self.zip_fn = zip_fn
        self.device_fn = device_fn

    def compute(self):
        pulls = [l.pull() for l in self.parents]
        if all(isinstance(p, DeviceShards) for p in pulls):
            if self.device_fn is not None:
                return self._compute_device(pulls, self.device_fn)
            if self.zip_fn is None:
                # reference default schema (zip_window.hpp:175): output
                # item j is the tuple of chunk j from each input —
                # batched on device as leaves [cap, window_i, ...]
                return self._compute_device(
                    pulls, lambda *chunks: tuple(chunks))
        if self.device_fn is not None and self.zip_fn is None:
            # mirror Window's contract: never silently emit the default
            # tuple-of-chunks schema where device_fn output was expected
            raise ValueError(
                "ZipWindow: inputs are host-resident but only device_fn "
                "was given — pass zip_fn alongside device_fn")
        pulls = [p.to_host_shards("zipwindow") if isinstance(p, DeviceShards) else p
                 for p in pulls]
        from ...data import multiplexer
        mex = self.context.mesh_exec
        pulls = [multiplexer.ensure_replicated(mex, p, "zipwindow-host")
                 for p in pulls]
        W = pulls[0].num_workers
        flats = [[it for l in p.lists for it in l] for p in pulls]
        n_out = min(len(f) // w for f, w in zip(flats, self.window))
        zf = self.zip_fn or (lambda *chunks: tuple(chunks))
        out = [zf(*[flats[i][j * w:(j + 1) * w]
                    for i, w in enumerate(self.window)])
               for j in range(n_out)]
        bounds = dense_range_bounds(n_out, W).tolist()
        return multiplexer.localize(
            mex, HostShards(W, [out[bounds[w]:bounds[w + 1]]
                                for w in range(W)]))

    def _compute_device(self, pulls: List[DeviceShards], device_fn):
        mex = pulls[0].mesh_exec
        W = mex.num_workers
        n_out = min(p.total // w for p, w in zip(pulls, self.window))
        cb = dense_range_bounds(n_out, W)                    # chunk bounds
        chunk_counts = (cb[1:] - cb[:-1]).astype(np.int64)
        chunk_cap = int(chunk_counts.max()) if n_out else 1

        batched = []                                     # per input
        for i, (p, wsz) in enumerate(zip(pulls, self.window)):
            tb = cb * wsz                                # item bounds
            a, _ = _realign_or_keep(p, tb, n_out * wsz,
                                    (self.id, i, "zw"),
                                    min_cap=chunk_cap * wsz)
            a = _repad(a, chunk_cap * wsz) if a.cap < chunk_cap * wsz \
                else a
            # [1, chunk_cap * wsz, ...] -> [1, chunk_cap, wsz, ...]
            tree = jax.tree.map(
                lambda l: l[:, :chunk_cap * wsz].reshape(
                    (l.shape[0], chunk_cap, wsz) + l.shape[2:]),
                a.tree)
            batched.append(tree)

        tree = _fused_map_trees(mex, batched, device_fn, "zip_window")
        return DeviceShards(mex, tree, chunk_counts)


def ZipWindowOp(dias: List[DIA], window, zip_fn=None, device_fn=None) -> DIA:
    return DIA(ZipWindowNode(dias[0].context, [d._link() for d in dias],
                             window, zip_fn, device_fn))
