"""Device-side execution helpers shared by operator implementations."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..data.shards import DeviceShards, compact_valid
from ..parallel.mesh import AXIS
from .stack import (Stack, apply_stack_traced, stack_bound_operands,
                    stack_cache_token)


def apply_stack_device(shards: DeviceShards, stack: Stack) -> DeviceShards:
    """Apply an LOp stack to device shards as one fused jitted program.

    Compacts valid items to the front; the refreshed per-worker counts
    stay device-resident (DeviceShards fetches them lazily only where a
    plan step needs host values). Bind ops' operands enter as
    REPLICATED program arguments — the executable is shape-cached, so
    iterative re-binds (k-means centroids) skip recompilation.
    """
    if not stack:
        return shards
    from jax.sharding import PartitionSpec as P

    mex = shards.mesh_exec
    cap = shards.cap
    leaves, treedef = jax.tree.flatten(shards.tree)
    bound = stack_bound_operands(stack)
    b_leaves, b_treedef = jax.tree.flatten(bound)
    b_leaves = mex.asarray_blessed(b_leaves)
    key = ("stack", stack_cache_token(stack), cap, treedef,
           tuple((l.dtype, l.shape[2:]) for l in leaves))
    holder = {}

    def build():
        nd = 1 + len(leaves)

        def f(counts_dev, *args):
            ls, bls = args[:len(leaves)], args[len(leaves):]
            count = counts_dev[0, 0]
            mask = jnp.arange(cap) < count
            tree = jax.tree.unflatten(treedef, [l[0] for l in ls])
            bound_t = jax.tree.unflatten(b_treedef, list(bls))
            tree, mask = apply_stack_traced(tree, mask, stack,
                                            bound=bound_t)
            tree, new_count = compact_valid(tree, mask)
            out_leaves, out_treedef = jax.tree.flatten(tree)
            holder["treedef"] = out_treedef
            return (new_count[None, None].astype(jnp.int32),
                    *[l[None] for l in out_leaves])

        in_specs = (P(AXIS),) * nd + (P(),) * len(b_leaves)
        return mex.smap(f, nd + len(b_leaves),
                        in_specs=in_specs), holder

    fn, h = mex.cached(key, build)
    pres = mex.pressure
    if pres is not None and pres.enabled \
            and not any(op.kind == "flat_map" for op in stack):
        # admission cost model (mem/pressure.py): a non-expanding LOp
        # stack's output shares the input capacity, so the input leaf
        # bytes bound the program's output — hand the hint to the
        # dispatch choke point (flat_map stacks may emit more rows
        # than they consume; they use the learned/factor estimate)
        pres.hint_output_bytes(sum(int(getattr(l, "nbytes", 0) or 0)
                                   for l in leaves))
    out = fn(shards.counts_device(), *leaves, *b_leaves)
    tree = jax.tree.unflatten(h["treedef"], list(out[1:]))
    # counts stay on device: no host sync between chained programs.
    # All-'map' stacks preserve counts exactly — when the input counts
    # are already host-known, hand them through so a downstream plan
    # step (ZipWithIndex offsets, exchange sizing) doesn't owe a
    # device->host sync for numbers the host never lost
    if shards._counts_host is not None and \
            all(op.kind == "map" for op in stack):
        return DeviceShards(mex, tree, shards._counts_host.copy())
    return DeviceShards(mex, tree, out[0])
