"""Device-side execution helpers shared by operator implementations."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..data.shards import DeviceShards, compact_valid
from .stack import Stack, apply_stack_traced, stack_cache_token


def apply_stack_device(shards: DeviceShards, stack: Stack) -> DeviceShards:
    """Apply an LOp stack to device shards as one fused jitted program.

    Compacts valid items to the front; the refreshed per-worker counts
    stay device-resident (DeviceShards fetches them lazily only where a
    plan step needs host values).
    """
    if not stack:
        return shards
    mex = shards.mesh_exec
    cap = shards.cap
    leaves, treedef = jax.tree.flatten(shards.tree)
    key = ("stack", stack_cache_token(stack), cap, treedef,
           tuple((l.dtype, l.shape[2:]) for l in leaves))
    holder = {}

    def build():
        def f(counts_dev, *ls):
            count = counts_dev[0, 0]
            mask = jnp.arange(cap) < count
            tree = jax.tree.unflatten(treedef, [l[0] for l in ls])
            tree, mask = apply_stack_traced(tree, mask, stack)
            tree, new_count = compact_valid(tree, mask)
            out_leaves, out_treedef = jax.tree.flatten(tree)
            holder["treedef"] = out_treedef
            return (new_count[None, None].astype(jnp.int32),
                    *[l[None] for l in out_leaves])

        return mex.smap(f, 1 + len(leaves)), holder

    fn, h = mex.cached(key, build)
    out = fn(shards.counts_device(), *leaves)
    tree = jax.tree.unflatten(h["treedef"], list(out[1:]))
    # counts stay on device: no host sync between chained programs
    return DeviceShards(mex, tree, out[0])
