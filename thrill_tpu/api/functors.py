"""Declarative reduce functors for ReduceByKey / ReducePair /
ReduceToIndex.

Reference: thrill/common/functional.hpp + core/reduce_functional.hpp —
the reference passes plain functors (std::plus, common::minimum, ...)
and the C++ templates inline them into the probing-table insert loop
at compile time. Python cannot inline a black-box callable, so the
equivalent contract is a DECLARATIVE functor: :class:`FieldReduce`
names the per-field combine op, remains an ordinary associative
callable for the generic engines (the device segmented scan and the
host strided fold both just call it), and lets the CPU local phase
fuse the entire reduction into the native single-pass hash-probe
(native/hostsort.cpp ``hash_group_acc_u64``) — the runtime analog of
the reference's template inlining.

Example (WordCount)::

    counts = words.ReduceByKey(lambda t: t["w"],
                               FieldReduce({"w": "first", "c": "sum"}))

Ops per field: ``"first"`` (keep the first-seen row's value — the
usual choice for the carried key field), ``"sum"``, ``"min"``,
``"max"``.
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np

_OPS = ("first", "sum", "min", "max")


def _is_traced(x) -> bool:
    return isinstance(x, (jax.Array, jax.core.Tracer))


class FieldReduce:
    """Associative combine described per item-tree field.

    The spec is a pytree with the SAME structure as the items and a
    string op at every leaf. Calling the functor combines two item
    trees field by field, working identically on numpy arrays (host
    engines) and jax arrays/tracers (jitted device engines).
    """

    def __init__(self, spec: Any) -> None:
        for s in jax.tree.leaves(spec):
            if s not in _OPS:
                raise ValueError(
                    f"FieldReduce: unknown op {s!r} (expected one of {_OPS})")
        self.spec = spec

    def __call__(self, a, b):
        def comb(op, x, y):
            if op == "first":
                return x
            if op == "sum":
                return x + y
            if _is_traced(x) or _is_traced(y):
                import jax.numpy as jnp
                return jnp.minimum(x, y) if op == "min" else jnp.maximum(x, y)
            return np.minimum(x, y) if op == "min" else np.maximum(x, y)

        try:
            return jax.tree.map(comb, self.spec, a, b)
        except (ValueError, TypeError) as e:
            # A spec/item structure mismatch surfaces either as
            # tree.map's ValueError or — because the spec is the
            # structure argument and item subtrees then reach comb
            # whole — as a TypeError from `dict + dict` deep inside a
            # jitted engine, with no hint of which functor. Translate
            # to an actionable API error (ReducePair("sum") on pytree
            # values is the common way here); errors with MATCHING
            # structures are real and re-raise unchanged.
            spec_td = jax.tree.structure(self.spec)
            td_a, td_b = jax.tree.structure(a), jax.tree.structure(b)
            if td_a == spec_td and td_b == spec_td:
                raise
            raise TypeError(
                f"FieldReduce spec structure {spec_td} does not match "
                f"the item structure "
                f"{td_a if td_a != spec_td else td_b}; for "
                f"ReducePair with a string op the value must be a single "
                f"leaf — pass an explicit FieldReduce spec mirroring the "
                f"item tree instead") from e

    def flat_spec(self, treedef):
        """Per-leaf op strings in ``treedef``'s leaf order, or None if
        the spec's structure does not match the item tree."""
        if jax.tree.structure(self.spec) != treedef:
            return None
        return jax.tree.leaves(self.spec)

    def _key(self):
        return (jax.tree.structure(self.spec),
                tuple(jax.tree.leaves(self.spec)))

    # content equality: ReduceNode caches compiled executables keyed by
    # (key_fn, reduce_fn), and the documented inline style constructs a
    # fresh FieldReduce per pipeline — identity hashing would recompile
    # the jitted reduce program (~20-40s on TPU) for equal specs
    def __eq__(self, other) -> bool:
        return (isinstance(other, FieldReduce)
                and self._key() == other._key())

    def __hash__(self) -> int:
        return hash(self._key())

    def __repr__(self) -> str:
        return f"FieldReduce({self.spec!r})"


def acc_plan(op: str, dtype: np.dtype, ndim: int):
    """Map (op, leaf dtype, leaf ndim) to the native accumulator:
    returns ``(opcode, conv_dtype)`` for ``hash_group_acc_u64`` or
    None when the leaf must go through the generic fold instead.

    conv_dtype is the 8-byte working dtype the column is converted to
    before the pass; the result converts back to the leaf dtype, which
    for integer sums is exact mod 2**bits (matching numpy wraparound)
    and for float32 sums means f64 accumulation (documented to be AT
    LEAST as accurate as the generic f32 fold, not bit-identical)."""
    if op == "first":
        return (-1, None)
    if ndim != 1:
        return None
    if op == "sum":
        if dtype == np.uint64:
            return (0, np.uint64)
        if np.issubdtype(dtype, np.integer):
            return (0, np.int64)
        if np.issubdtype(dtype, np.floating):
            return (3, np.float64)
        return None
    if op in ("min", "max"):
        lo = op == "min"
        if dtype == np.uint64:
            return (6 if lo else 7, np.uint64)
        if np.issubdtype(dtype, np.signedinteger):
            return (1 if lo else 2, np.int64)
        if np.issubdtype(dtype, np.unsignedinteger):
            return (6 if lo else 7, np.uint64)
        if np.issubdtype(dtype, np.floating):
            return (4 if lo else 5, np.float64)
        return None
    return None
