"""DIA graph nodes and the stage driver.

Equivalent of the reference's DIABase / DIANode / StageBuilder
(reference: thrill/api/dia_base.hpp:87 states NEW/EXECUTED/DISPOSED,
dia_base.cpp:302-442 FindStages + toposort + Execute/PushData per stage,
dia_node.hpp:123-177 RunPushData / consume counters).

Single-controller translation: an action triggers ``materialize()`` on
its parents, which recursively executes ancestor nodes in deterministic
node-id order (the recursion *is* the reference's BFS-up + toposort,
since ids increase in construction order and parents always precede
children). Results cache on the node (state EXECUTED) until disposed;
``Keep()`` raises the consume budget exactly like the reference's
consume counters, so memory can be reclaimed mid-pipeline.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple, Union

from ..data.shards import DeviceShards, HostShards
from .stack import Stack, apply_stack_host_list, stack_cache_token

Shards = Union[DeviceShards, HostShards]

NEW = "NEW"
EXECUTED = "EXECUTED"
DISPOSED = "DISPOSED"
# the node's program was traced into its sole consumer's stitched
# dispatch (api/fusion.py) — consumed without ever materializing
FUSED = "FUSED"


@dataclasses.dataclass
class ParentLink:
    """A DOp's link to a parent node plus the LOp stack fused on the edge."""
    node: "DIABase"
    stack: Stack

    def pull(self, consume: bool = True) -> Shards:
        from . import fusion
        if fusion.enabled():
            # fused pull: upstream chains deferred into one stitched
            # dispatch execute here; the edge stack rides along instead
            # of paying its own dispatch
            return fusion.pull_plan(self, consume=consume).finish()
        return self._pull_unfused(consume)

    def _pull_unfused(self, consume: bool = True) -> Shards:
        shards = self.node.materialize(consume=consume)
        if isinstance(shards, DeviceShards):
            # deferred producer validations (hinted-join overflow) run
            # BEFORE any consumer — downstream op or action — reads the
            # columns: a recovering check heals shards.tree in place,
            # so truncation can neither propagate nor be consumed
            shards.validate_pending()
        if not self.stack:
            return shards
        if isinstance(shards, HostShards):
            return HostShards(shards.num_workers,
                              [apply_stack_host_list(l, self.stack)
                               for l in shards.lists])
        from .device_exec import apply_stack_device
        return apply_stack_device(shards, self.stack)

    def cache_token(self) -> Tuple:
        return (self.node.id, stack_cache_token(self.stack))


class DIABase:
    """A node of the DIA dataflow DAG."""

    def __init__(self, ctx, label: str,
                 parents: Sequence[ParentLink] = ()) -> None:
        self.context = ctx
        self.label = label
        self.parents: List[ParentLink] = list(parents)
        self.id = ctx._register_node(self)
        self.state = NEW
        self._shards: Optional[Shards] = None
        # number of remaining consuming pulls before data is freed; every
        # node's data may be used once, .Keep(n) allows n more uses
        # (reference: consume counters, api/dia_base.hpp:226-250)
        self.consume_budget = 1
        # host-RAM grant for this node's compute, set by the stage
        # driver from mem_use() before compute() runs (reference:
        # DIAMemUse negotiation, api/dia_base.cpp:121-270). None =
        # nothing requested/granted.
        self.mem_limit: Optional[int] = None

    # -- overridables ---------------------------------------------------
    # memory appetite of compute(): None = negligible, "max" = wants as
    # much as available (EM operators: Sort runs, GroupBy tables), an
    # int = fixed bytes (reference: DIAMemUse, api/dia_base.hpp:51)
    MEM_USE = None

    def mem_use(self):
        return self.MEM_USE

    def compute(self) -> Shards:
        """Produce this node's output shards (the DOp main op + push)."""
        raise NotImplementedError

    def compute_plan(self):
        """Fusible DOps override: return a :class:`fusion.FusionPlan`
        whose tail carries this node's traced segment (so a consumer
        can stitch it into one dispatch), or None when statically
        ineligible. Implementations that pull parents must ALWAYS
        return a plan afterwards (wrapping an eagerly computed result
        when the input turned out host-resident) — the pull consumed
        the parent."""
        return None

    # -- driver ---------------------------------------------------------
    def _barrier_decision(self, reason: str) -> None:
        """Ledger entry for a declined fusion deferral: WHY this node
        ends the stitched chain (common/decisions.py; explain() shows
        the barrier reason on the node)."""
        from ..common import decisions as _decisions
        led = _decisions.ledger_of(self.context.mesh_exec)
        if led is not None:
            led.record("fusion_barrier",
                       f"node:{self.label}#{self.id}", "materialize",
                       rejected=[("defer", None)], reason=reason,
                       dia=self.id, node=self.label)

    def _bind_ledger_node(self):
        """The mesh ledger with this node pushed as the current
        decision site, or None — decisions recorded inside compute()
        (exchange strategy, prune verdicts, admission) then attach to
        this node in explain()."""
        led = getattr(self.context.mesh_exec, "decisions", None)
        if led is not None and led.enabled:
            led.push_node(self.id, self.label)
            return led
        return None

    def materialize_plan(self, consume: bool = False):
        """Fused-stage entry: defer this node's program into its sole
        consumer's stitched dispatch when safe (sole consumer, nothing
        cached, fusion on), else materialize normally. Returns a
        FusionPlan (deferred) or Shards."""
        from . import fusion
        mgr = getattr(self.context, "checkpoint", None)
        if mgr is not None and self.state == NEW and (
                mgr.restorable(self) or (mgr.auto and self.parents)):
            # resume: this node's state is on disk — restoring beats
            # deferring into a fused dispatch that would recompute the
            # whole upstream subgraph. Auto-checkpoint mode likewise
            # forces materialization: an epoch can only seal
            # MATERIALIZED shards, so every DOp becomes a durable
            # stage barrier (the documented fusion tradeoff of
            # THRILL_TPU_CKPT_AUTO).
            self._barrier_decision("checkpoint restore/auto-epoch "
                                   "needs materialized shards")
            return self.materialize(consume=consume)
        if (fusion.enabled() and consume and self._shards is None
                and self.state == NEW and self.consume_budget <= 1
                and type(self).compute_plan is not DIABase.compute_plan):
            # the legacy path would negotiate around compute(); plans
            # may fall back to mem-hungry host bodies, so grant here too
            negotiated = self.context.negotiate_mem(self)
            led = self._bind_ledger_node()
            try:
                plan = self.compute_plan()
            finally:
                if led is not None:
                    led.pop_node()
                if negotiated:
                    self.context.release_mem(self)
            if plan is not None:
                self.consume_budget = 0
                self.state = FUSED
                log = self.context.logger
                if log.enabled:
                    log.line(event="node_fused", node=self.label,
                             dia_id=self.id,
                             parents=[p.node.id for p in self.parents])
                return plan
            self._barrier_decision("plan ineligible (host storage or "
                                   "untraceable input)")
        elif fusion.enabled() and consume \
                and type(self).compute_plan is not DIABase.compute_plan:
            # statically fusible op that cannot defer THIS pull: name
            # the reason (the explain() barrier taxonomy). Reaching
            # this branch with consume=True means exactly one of these
            # two defer conditions failed.
            self._barrier_decision(
                "cached result" if self._shards is not None
                or self.state != NEW else "multi-consumer (Keep)")
        return self.materialize(consume=consume)

    def materialize(self, consume: bool = False) -> Shards:
        if self.state in (DISPOSED, FUSED):
            raise RuntimeError(
                f"DIA node {self.label}#{self.id} was consumed/disposed "
                f"(consume budget exhausted); call .Keep() before reusing "
                f"a DIA in more than one operation")
        hbm = self.context.hbm
        if self._shards is None:
            log = self.context.logger
            if log.enabled:
                log.line(event="node_execute_start", node=self.label,
                         dia_id=self.id,
                         parents=[p.node.id for p in self.parents])
            # resume path (api/checkpoint.py): a committed epoch holds
            # this node's shards — rebuild them instead of computing,
            # and the pull recursion never touches the upstream graph
            mgr = getattr(self.context, "checkpoint", None)
            restored = mgr.try_restore(self) if mgr is not None else None
            if restored is not None:
                self._shards = restored
            else:
                # stage-level HBM admission (mem/pressure.py): before
                # a new stage computes, bring the cached-results
                # ledger back under the watermark — the pull-model
                # analog of the reference's per-stage RAM distribution
                pres = getattr(self.context, "pressure", None)
                if pres is not None and pres.enabled:
                    pres.admit_stage(self)
                # stage memory negotiation: EM operators get a host-RAM
                # grant split among concurrently computing
                # max-requesters (nested pulls, e.g. recursive DC3
                # sorts, shrink the inner grants exactly like the
                # reference's per-stage split)
                negotiated = self.context.negotiate_mem(self)
                led = self._bind_ledger_node()
                try:
                    self._shards = self.compute()
                finally:
                    if led is not None:
                        led.pop_node()
                    if negotiated:
                        self.context.release_mem(self)
                if mgr is not None:
                    # stage-barrier auto-checkpoint (opt-in)
                    mgr.maybe_autosave(self, self._shards)
            self.state = EXECUTED
            if not (consume and self.consume_budget <= 1):
                # a result released by this very pull is never worth
                # spilling a kept sibling for — skip the LRU entirely
                hbm.on_cache(self)
            if log.enabled:
                # never FORCE a counts fetch for the log line: it would
                # reintroduce a per-op host sync, and (multi-controller)
                # a fetch conditional on local logger settings would
                # issue asymmetric collectives across processes
                host_counts = getattr(self._shards, "_counts_host",
                                      self._shards.counts
                                      if isinstance(self._shards,
                                                    HostShards) else None)
                log.line(event="node_execute_done", node=self.label,
                         dia_id=self.id,
                         items=(int(host_counts.sum())
                                if host_counts is not None else None),
                         per_worker=(host_counts.tolist()
                                     if host_counts is not None
                                     else None))
        else:
            # LRU bump; transparently re-uploads a spilled result
            hbm.touch(self)
        result = self._shards
        if consume:
            self.consume_budget -= 1
            if self.consume_budget <= 0:
                self._shards = None
                self.state = DISPOSED
                hbm.on_release(self, None)  # caller now owns `result`
        return result

    def keep(self, n: int = 1) -> None:
        self.consume_budget += n

    def dispose(self) -> None:
        dropped = self._shards
        self._shards = None
        self.state = DISPOSED
        self.context.hbm.on_release(self, dropped)

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.label}#{self.id} {self.state}>"
