"""Adaptive cost-based planner: one model that chooses, learns and
re-optimizes every plan decision.

The port's data-driven plan choices used to live as ~ten per-site
heuristics, each consulting its own local slice of learned state:
exchange strategy and chunk count in data/exchange.py, fusion split
points under memory pressure in api/fusion.py + mem/pressure.py,
pre-shuffle prune verdicts in core/preshuffle.py, optimistic-dispatch
eligibility in the capacity-plan cache. The plan observatory (PR 11,
common/decisions.py) made every one of those choices auditable —
predicted cost joined against the measured actual — but nothing ACTED
on the accuracy signal: a plan a stale learned stat lied about rode
the sticky lie until a periodic resync happened to revisit it.

This module closes that loop. One :class:`Planner` per Context
(attached as ``mesh_exec.planner``, the pressure/tracer/decisions
pattern: one attribute read plus one predicate on the off path) owns:

* **The cost model.** Three terms, shared by every choice:
  ``fabric_bytes`` (padded rows / serialized frames a candidate plan
  ships), ``dispatches * bytes_eq`` (the measured per-launch overhead
  expressed in equivalent bytes — benchmarks/exchange_crossover.py,
  the same calibration ``_skewed`` always used) and an HBM-admission
  term (a candidate whose estimate cannot fit under the watermark even
  with every cold shard spilled is inadmissible). Inputs come from the
  plan store's learned state: sticky capacities, narrow specs, prune
  fractions, per-program output sizes, host-known counts.
* **The choices.** ``exchange_strategy`` (bulk-dense vs 1-factor vs
  ragged — exactly the ``_strategy_costs`` math, now owned here),
  ``chunk_count`` (bulk vs chunked phase B and K),
  ``optimistic_verdict`` (dispatch on the cached capacity plan vs
  re-sync — including the pre-dispatch *guaranteed-miss* check: when
  host-known input counts prove the cached capacities cannot hold,
  the planner re-chooses the synced plan instead of dispatching into
  a certain overflow heal), pre-shuffle prune verdicts
  (core/preshuffle.py delegates its cost inequality here), and the
  proactive fusion split (a row-local chain whose admission estimate
  exceeds the HBM watermark splits into row-range sub-dispatches
  BEFORE the OOM, api/fusion.py).
* **Re-optimization.** The decision ledger calls :meth:`on_audit` for
  every joined actual. A prediction off by more than the threshold
  (``THRILL_TPU_REPLAN_ERR``, default 1.0 — the PR-11
  ``|log2(pred/actual)|`` signal) on a store-seeded capacity, or an
  observed prune fraction that contradicts the verdict's predicted
  fraction, marks the site: the next dispatch INVALIDATES the learned
  entry and re-chooses from current data instead of riding the lie.
  The deferred capacity check feeds the same path: a hit whose
  observed send matrix now prefers the 1-factor schedule re-syncs the
  site on the next exchange instead of waiting out the periodic
  resync window. Every re-choice lands in the ledger as a ``replan``
  record carrying both plans' costs, so ``ctx.explain()`` names what
  switched and why, and the ``cost_model_mae`` bench lane doubles as
  the planner's own accuracy gauge.

``THRILL_TPU_PLANNER=0`` restores today's per-site heuristics exactly:
no Planner is constructed, every guarded call site takes its legacy
branch, and no replan can ever fire.

Values here are CORRECTNESS-NEUTRAL by the same construction as the
plan store: a wrong choice costs performance (an avoidable heal, a
padded plan, a recompile), never results — which is what makes letting
a learned model choose safe at all.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np


def planner_enabled() -> bool:
    """THRILL_TPU_PLANNER=0 restores the per-site heuristics exactly
    (read once, at Context construction)."""
    from ..common.config import _env_flag
    return _env_flag("THRILL_TPU_PLANNER", True)


def replan_threshold() -> float:
    """THRILL_TPU_REPLAN_ERR: |log2(predicted/actual)| beyond which an
    audited store-seeded prediction invalidates its site's plan
    (default 1.0 — off by more than 2x reads as a lie worth
    re-choosing over; in-process-learned capacities are pow2-ratcheted
    from measured data and cannot exceed 2x by construction, so only
    imported state can trip this)."""
    try:
        v = float(os.environ.get("THRILL_TPU_REPLAN_ERR", "") or 1.0)
    except ValueError:
        return 1.0
    return v if v > 0 else 1.0


def planner_of(mex) -> Optional["Planner"]:
    """The mesh's planner when adaptive planning is live, else None —
    one attribute read plus one predicate on the disabled path (the
    ledger_of/span_of pattern)."""
    pl = getattr(mex, "planner", None)
    if pl is not None and pl.enabled:
        return pl
    return None


class Planner:
    """Per-Context adaptive planner over the mesh's learned plan state.

    Thread-safe where it must be (replan marks arrive from deferred
    checks and audit joins, which may run on the service dispatcher
    thread while a client thread renders explain())."""

    def __init__(self, mex, enabled: Optional[bool] = None) -> None:
        self.mex = mex
        self.enabled = planner_enabled() if enabled is None else enabled
        self.err_threshold = replan_threshold()
        self._lock = threading.Lock()
        # sites marked for re-optimization: consumed (one-shot) by the
        # next plan choice at that site
        self._replan: Dict[str, str] = {}      # site -> reason
        # sites whose capacity plan came from the plan store: the only
        # sites an overprovision audit may invalidate (fresh-learned
        # capacities are pow2-ratcheted from measured data and cannot
        # lie past 2x by construction)
        self._seeded: set = set()
        # counters (ctx.overall_stats: planner_replans / _switches)
        self.replans = 0        # sites invalidated and re-chosen
        self.switches = 0       # re-choices that changed the plan
        # learned per-site readahead depths (ISSUE 15 / ROADMAP edge
        # (b)): grown from the audited io_prefetch hit rate, replacing
        # the single THRILL_TPU_PREFETCH default per site
        self._io_depth: Dict[str, int] = {}
        self._io_rate: Dict[str, float] = {}
        # shrink side of the loop: consecutive runs a site's audited
        # hit rate held >= IO_HIT_SHRINK, and the pending one-shot
        # shrink marks (site -> reason) the streak produced
        self._io_hi_streak: Dict[str, int] = {}
        self._io_shrink: Dict[str, str] = {}

    # -- cost model -----------------------------------------------------
    def bytes_eq(self) -> int:
        """Per-launch overhead in equivalent fabric bytes (the measured
        crossover constant, data/exchange.py)."""
        from ..data.exchange import _bytes_eq
        return _bytes_eq(self.mex)

    def plan_cost(self, fabric_bytes: float, dispatches: int = 0,
                  hbm_bytes: Optional[int] = None) -> float:
        """One candidate plan's scalar cost: bytes shipped plus launch
        overhead in byte-equivalents; an inadmissible HBM estimate
        (cannot fit under the watermark even after spilling everything
        cold) is infinite."""
        c = float(fabric_bytes) + dispatches * self.bytes_eq()
        if hbm_bytes is not None and self.hbm_inadmissible(hbm_bytes):
            return math.inf
        return c

    def sort_engine(self, n: int, total_bits: int, radix_ok: bool,
                    site: Optional[str] = None):
        """Device sort engine choice (edge (e)): delegates to the one
        shared cost model in core/device_sort.py so the planner and the
        legacy auto path can never disagree; a pending replan mark on
        the sort site is consumed here (the decision is re-recorded by
        the caller either way)."""
        from ..core.device_sort import sort_engine_policy
        if site is not None:
            self.take_replan(site)
        return sort_engine_policy(n, total_bits, radix_ok)

    def hbm_inadmissible(self, est_bytes: int) -> bool:
        """True when ``est_bytes`` cannot be admitted at any spill
        level: it exceeds the watermark fraction of the whole HBM
        budget (mem/pressure.py rung-1 inputs). False when admission
        is off (no budget known)."""
        pres = getattr(self.mex, "pressure", None)
        if pres is None or not pres.enabled:
            return False
        return pres.inadmissible(est_bytes)

    # -- choice: exchange strategy --------------------------------------
    def exchange_strategy(self, S: np.ndarray, row_bytes: int,
                          mode: str) -> Tuple[str, float, float, str]:
        """(chosen, dense_cost, onefactor_cost, reason) for one send
        matrix. ``mode`` is the configured exchange mode; only
        ``dense`` lets the cost model arbitrate (the legacy contract:
        forced modes pass through). Costs are total plan costs — padded
        fabric bytes plus per-round launch overhead — so
        ``dense_cost > onefactor_cost`` is EXACTLY the legacy
        ``_skewed`` inequality."""
        from ..data.exchange import _strategy_costs
        dense_b, of_b, n_rounds = _strategy_costs(self.mex, S, row_bytes)
        dense_cost = self.plan_cost(dense_b)
        of_cost = self.plan_cost(of_b, dispatches=n_rounds)
        if mode != "dense":
            return mode, dense_cost, of_cost, "configured mode"
        if dense_cost > of_cost:
            return ("onefactor", dense_cost, of_cost,
                    "skewed send matrix: 1-factor padding beats the "
                    "dense launch savings")
        return ("dense", dense_cost, of_cost, "balanced send matrix")

    def skew_developed(self, S: np.ndarray, row_bytes: int) -> bool:
        """Deferred-check probe: would the strategy choice flip to the
        1-factor schedule on this OBSERVED send matrix? Used by the
        optimistic exchange's capacity check, where the host S is
        fetched anyway — a True verdict marks the site so the next
        dispatch re-syncs immediately instead of waiting out the
        periodic resync window."""
        from ..data.exchange import resolve_mode
        if resolve_mode(self.mex) != "dense":
            return False
        chosen, _, _, _ = self.exchange_strategy(S, row_bytes, "dense")
        return chosen == "onefactor"

    # -- choice: phase-B chunk count ------------------------------------
    def chunk_count(self, W: int, M_pad: int, item_bytes: int) -> int:
        """Bulk vs chunked phase B and K. The planner owns the CHOICE;
        the policy (overlap kill switch, env pin, measured break-even
        volume) is the exchange's :func:`chunk_policy` — one
        implementation, so the planner-on and planner-off paths are
        numerically identical on every platform by construction."""
        from ..data.exchange import chunk_policy
        return chunk_policy(W, M_pad, item_bytes)

    # -- choice: optimistic dispatch vs re-sync -------------------------
    def optimistic_verdict(self, site: str, caps: Tuple[int, int],
                           counts: Optional[np.ndarray],
                           W: int) -> Tuple[bool, Optional[str]]:
        """May this site dispatch phase B on its cached capacity plan?

        (True, None) = dispatch optimistically (the steady-state hit
        path). (False, reason) = the planner re-chooses: either the
        site is marked for re-optimization (an audit or deferred check
        revealed the learned state lied) or host-known input counts
        PROVE the cached capacities cannot hold — a guaranteed miss,
        where dispatching optimistically would buy one wasted dispatch
        plus the heal's re-run. The caller takes the synced plan and
        drops the site's learned capacities so they re-ratchet from
        the current data. Either way the site leaves the seeded set:
        its state is in-process-learned from here (pow2-ratcheted from
        measured data), so the overprovision audit cannot re-fire on a
        capacity that min_cap legitimately dominates."""
        reason = self.take_replan(site)
        if reason is not None:
            with self._lock:
                self._seeded.discard(site)
            return False, reason
        if counts is not None and W > 1:
            M_pad, out_cap = caps
            total = int(np.asarray(counts).sum())
            per_worker_max = int(np.asarray(counts).max())
            # max receive column >= ceil(total/W); max cell >=
            # ceil(row_max/W): if either already exceeds the cached
            # capacity, SOME worker must overflow — no data
            # distribution can avoid it
            if -(-total // W) > out_cap \
                    or -(-per_worker_max // W) > M_pad:
                self.note_replan()
                with self._lock:
                    self._seeded.discard(site)
                return False, ("known row counts exceed the cached "
                               "capacity plan (guaranteed miss)")
        return True, None

    # -- choice: pre-shuffle pruning ------------------------------------
    def prune_verdict(self, rows: int, item_bytes: int, W: int,
                      sides: int, M: int, frac: float) -> bool:
        """The pre-shuffle cost inequality (core/preshuffle.py): prune
        when the expected pruned row bytes clear the fingerprint
        register traffic by the margin. The filter's own launch
        overhead is folded into the margin (the legacy ``_pays``
        calibration), so the verdict is numerically IDENTICAL to the
        per-site heuristic — the planner's value here is the replan
        path (a lying fraction re-evaluates immediately), not a
        different inequality."""
        from ..core.preshuffle import _MARGIN, _pays_est
        if W <= 1 or rows <= 0:
            return False
        pruned, fingerprint = _pays_est(rows, item_bytes, W, sides, M,
                                        frac)
        return pruned > _MARGIN * fingerprint

    # -- choice: proactive fusion split ---------------------------------
    def fusion_split_k(self, est_bytes: int, cap: int) -> Optional[int]:
        """K when a row-local fused chain should execute as K row-range
        sub-dispatches BEFORE dispatching whole (its admission estimate
        cannot fit under the HBM watermark at any spill level), else
        None. Uses the OOM ladder's own rung-3 K (mem/pressure.py
        ``split_k``) so the proactive and the reactive split produce
        identical sub-plans."""
        if cap <= 1 or not self.hbm_inadmissible(est_bytes):
            return None
        from ..mem.pressure import split_k
        return split_k(cap)

    # -- choice: out-of-core readahead depth ----------------------------

    #: grow the depth when a site's audited hit rate falls under this
    #: (log2(1/0.75) ~ 0.415 on the pred=1.0 io_prefetch records)
    IO_HIT_TARGET = 0.75
    #: never grow past this — beyond it the readahead pool itself (not
    #: depth) is the bound, and RAM cost scales with depth blocks
    IO_DEPTH_CAP = 32
    #: shrink a LEARNED depth back toward the default when the audited
    #: hit rate holds at least this for two consecutive runs — the
    #: readahead is comfortably ahead of the consumer, so half the
    #: depth (and half the pinned host RAM) likely still hits; an
    #: overshoot re-grows on the very next sub-target audit
    IO_HIT_SHRINK = 0.95

    def io_prefetch_depth(self, site: str, default: int) -> int:
        """LEARNED per-site readahead depth for an out-of-core site
        (the em_sort merge, spill/checkpoint restore).

        Seeding: the env-pinned depth (vfs/file_io.prefetch_depth,
        passed in as ``default``) the first time a site runs. Learning:
        every run records an ``io_prefetch`` decision predicting a
        perfect hit rate; the audit join (:meth:`on_audit`) marks the
        site when the MEASURED rate lands under ``IO_HIT_TARGET`` —
        the consumer outran the readahead — and the next run at that
        site doubles its depth (capped) instead of riding the one env
        default forever. Each re-choice lands as a ``kind=replan``
        ledger record carrying both depths and the measured rate, so
        ``ctx.explain()`` names the switch like any other plan
        re-optimization. ``default <= 0`` means prefetch is DISABLED
        (THRILL_TPU_PREFETCH=0 / OVERLAP=0) — the learned depth never
        overrides an explicit off switch (the synchronous-ladder
        restoration contract).

        Shrinking: a site whose audited hit rate held at least
        ``IO_HIT_SHRINK`` for two consecutive runs HALVES its learned
        depth back toward ``default`` (floor at ``default`` — the
        explicit/env setting is never undercut), reclaiming the pinned
        readahead RAM a transient burst grew. The re-choice lands as
        the same ``kind=replan`` record, carrying both depths."""
        if default <= 0:
            return default
        with self._lock:
            depth = self._io_depth.get(site, default)
            shrink_why = self._io_shrink.pop(site, None)
            shrink_rate = self._io_rate.get(site)
        if shrink_why is not None and depth > default:
            new = max(default, depth // 2)
            with self._lock:
                self._io_depth[site] = new
                self._io_hi_streak[site] = 0
                # a stale grow mark cannot coexist with a sustained
                # >= IO_HIT_SHRINK streak — drop it without counting
                self._replan.pop(site, None)
            self.note_replan()
            self.note_switch()
            from ..common.decisions import ledger_of
            self.record_replan(
                ledger_of(self.mex), site, f"depth={new}",
                predicted=float(new),
                rejected=[(f"depth={depth}", shrink_rate)],
                reason=shrink_why, depth=new, prev_depth=depth,
                measured_hit_rate=shrink_rate)
            return new
        with self._lock:
            if depth >= self.IO_DEPTH_CAP:
                # at the cap there is nothing to re-choose: drop any
                # pending mark WITHOUT counting a replan (the counter
                # counts performed re-optimizations, and none happens)
                self._replan.pop(site, None)
                return depth
        why = self.take_replan(site)
        if why is None:
            return depth
        new = min(max(depth * 2, default), self.IO_DEPTH_CAP)
        with self._lock:
            self._io_depth[site] = new
            rate = self._io_rate.get(site)
        if new != depth:
            self.note_switch()
        from ..common.decisions import ledger_of
        self.record_replan(
            ledger_of(self.mex), site, f"depth={new}",
            predicted=float(new),
            rejected=[(f"depth={depth}", rate)], reason=why,
            depth=new, prev_depth=depth,
            measured_hit_rate=rate)
        return new

    # -- re-optimization ------------------------------------------------
    def note_seeded(self, site: str) -> None:
        """The site's capacity plan came from the plan store — the one
        class of learned state an overprovision audit may invalidate."""
        with self._lock:
            self._seeded.add(site)

    def mark_replan(self, site: str, reason: str) -> None:
        """Flag ``site`` for re-optimization: its next plan choice
        invalidates the learned entry and re-chooses from current
        data. Idempotent; consumed by :meth:`take_replan`."""
        with self._lock:
            self._replan.setdefault(site, reason)

    def take_replan(self, site: str) -> Optional[str]:
        """Consume a pending re-optimization mark for ``site``. The
        consumer performs the re-choice, so consumption is what the
        ``planner_replans`` counter counts (a mark that never reaches
        a plan choice again re-optimized nothing)."""
        with self._lock:
            why = self._replan.pop(site, None)
            if why is not None:
                self.replans += 1
            return why

    def note_replan(self) -> None:
        """A re-optimization performed WITHOUT a prior mark (the
        pre-dispatch guaranteed-miss re-choice)."""
        with self._lock:
            self.replans += 1

    def note_switch(self) -> None:
        """A re-choice actually changed the plan (different strategy,
        re-ratcheted capacities, flipped verdict, proactive split)."""
        with self._lock:
            self.switches += 1

    def on_audit(self, rec) -> None:
        """Decision-ledger audit hook (common/decisions.py resolve):
        joined actuals whose error exceeds the threshold mark their
        site for re-optimization. Deliberately narrow per kind:

        * ``xchg_optimistic`` — a "hit" whose cached output capacity
          overshoots the measured need by more than the threshold, on
          a STORE-SEEDED site (in-process capacities are pow2-ratcheted
          from measured data and cannot lie), re-ratchets from scratch.
          Misses need no mark: the heal already re-chose.
        * ``prune`` — an observed prune fraction off the predicted one
          by more than the threshold re-evaluates the verdict on the
          next use instead of waiting out the periodic resync window.

        Everything else (admission estimates self-correct on first
        measure, strategy records are informational padding ratios) is
        audited but never triggers a replan."""
        err = rec.err_log2
        if err is None:
            return
        if rec.kind == "xchg_optimistic":
            if rec.verdict == "hit" and err > self.err_threshold \
                    and rec.site in self._seeded:
                self.mark_replan(
                    rec.site,
                    f"seeded capacity overshoots measured need "
                    f"{2 ** err:.1f}x")
        elif rec.kind == "prune":
            if abs(err) > self.err_threshold:
                self.mark_replan(
                    rec.site,
                    f"observed prune fraction off the prediction "
                    f"{2 ** abs(err):.1f}x")
        elif rec.kind == "io_prefetch":
            # predicted = 1.0 (perfect hit rate); a measured rate
            # under the target means the consumer outran the
            # readahead — grow that SITE's depth on its next run. A
            # rate holding >= IO_HIT_SHRINK two runs straight means
            # the depth overshoots — shrink it back toward default.
            rate = rec.actual
            if rate is None:
                return
            with self._lock:
                self._io_rate[rec.site] = float(rate)
                if rate >= self.IO_HIT_SHRINK:
                    streak = self._io_hi_streak.get(rec.site, 0) + 1
                    self._io_hi_streak[rec.site] = streak
                    if streak >= 2:
                        self._io_shrink.setdefault(
                            rec.site,
                            f"prefetch hit rate held >= "
                            f"{self.IO_HIT_SHRINK:.2f} for {streak} "
                            f"consecutive runs: learned depth "
                            f"overshoots")
                else:
                    self._io_hi_streak[rec.site] = 0
                    self._io_shrink.pop(rec.site, None)
            if rate < self.IO_HIT_TARGET:
                self.mark_replan(
                    rec.site,
                    f"prefetch hit rate {rate:.2f} under the "
                    f"{self.IO_HIT_TARGET:.2f} target")

    def record_replan(self, led, site: str, chosen: str, predicted,
                      rejected, reason: str, **inputs: Any) -> None:
        """The switched decision, with both plans' costs, in the
        ledger — what ``ctx.explain()`` shows for a re-optimization."""
        if led is not None:
            led.record("replan", site, chosen, predicted=predicted,
                       rejected=rejected, reason=reason, **inputs)

    def stats(self) -> dict:
        with self._lock:
            return {"planner_replans": self.replans,
                    "planner_switches": self.switches}
