"""Context, runtime bootstrap and the local test harness.

Equivalent of the reference's Context/HostContext/Run machinery
(reference: thrill/api/context.hpp:90-448, context.cpp:336-341,947-1013):
``Run`` bootstraps a runtime and hands the user job a Context; the job
builds and executes DIA pipelines against it.

Single-controller translation: one Context drives all W logical workers
(one per mesh device). ``RunLocalTests`` replicates the reference's
in-process virtual-cluster sweep — the same job body runs on meshes of
several sizes over XLA host-platform devices, no cluster needed.

Multi-host: call ``thrill_tpu.api.Run`` after ``jax.distributed``
initialization and the mesh spans all hosts' devices; each host runs the
same single-controller program (standard JAX multi-controller SPMD).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

import jax

from ..common.config import Config
from ..common.logger import JsonLogger, default_log_path
from ..mem.manager import MemoryManager
from ..net.flow import FlowControlChannel, LocalFlowControl
from ..parallel.mesh import MeshExec


def _wire_ratio(raw: int, actual: int) -> float:
    """bytes_on_wire_raw / bytes_on_wire, 1.0 when nothing shipped."""
    return round(raw / actual, 3) if actual else 1.0


def _em_adopted() -> int:
    """Process-wide count of EM runs adopted from departed ranks
    (core/em_runs.py). Adoption only ever happens in a rank that
    joined/relaunched into an elastic group, so this is exactly zero
    for every non-elastic workload — the perf sentinel pins it."""
    try:
        from ..core.em_runs import adopted_total
        return adopted_total()
    except Exception:
        return 0


class PipelineError(RuntimeError):
    """One pipeline run on a Context failed — and ONLY that pipeline:
    the Context healed (generation-scoped failure domain) and stays
    usable for the next run. Carries the ROOT CAUSE of the abort
    (``origin`` rank, ``cause`` text, ``generation`` of the failed
    run, ``root`` original exception). Deliberately NOT a
    ConnectionError/ClusterAbort subclass: retry policies classify it
    permanent, RunSupervised does not relaunch for it (the caller
    opted into handling scoped failures by using ``ctx.pipeline()``),
    and ``Context.close()`` runs the healthy collective shutdown."""

    def __init__(self, origin: int, cause: str, generation: int,
                 root: Optional[BaseException] = None) -> None:
        super().__init__(
            f"pipeline generation {generation} aborted "
            f"(origin rank {origin}): {cause}")
        self.origin = origin
        self.cause = cause
        self.generation = generation
        self.root = root


# process-level elasticity: the exit code a supervised worker exits
# with once a resize move is COMMITTED (marker on disk). EX_TEMPFAIL —
# "try again", which is literally the contract: the supervisor reads
# the RESIZE marker and relaunches at the target W with resume.
RESIZE_EXIT_CODE = 75


class ResizeRelaunch(SystemExit):
    """Raised by :meth:`Context.resize_processes` once the move is
    committed: this process must exit so the supervisor
    (run-scripts/supervise.sh) can relaunch the job at the target W
    with ``THRILL_TPU_RESUME=1``. A SystemExit subclass with code
    ``RESIZE_EXIT_CODE`` — left uncaught it exits the worker with
    exactly the code the supervisor's resize branch watches for, and
    no retry policy classifies it transient. Raise it only on the MAIN
    thread (a SystemExit in a helper thread kills just that thread);
    autoscaler deployments signal the main loop from ``apply_fn`` and
    let it call resize_processes."""

    def __init__(self, target_w: int, epoch: Optional[int] = None,
                 generation: Optional[int] = None) -> None:
        super().__init__(RESIZE_EXIT_CODE)
        self.target_w = int(target_w)
        self.epoch = epoch
        self.generation = generation

    def __str__(self) -> str:
        return (f"resize move to W={self.target_w} committed: exiting "
                f"{RESIZE_EXIT_CODE} for supervised relaunch")


class Context:
    """Runtime handle passed to user jobs; owns the mesh and services."""

    def __init__(self, mesh_exec: Optional[MeshExec] = None,
                 config: Optional[Config] = None, seed: int = 0,
                 host_rank: Optional[int] = None,
                 resume: bool = False) -> None:
        self.config = config or Config.from_env()
        from ..common.config import DEFAULT_COMPILE_CACHE
        cc = self.config.compile_cache
        # auto-enable only off-CPU (XLA:CPU AOT cache entries reload
        # with machine-feature warning spam) — but ALWAYS honor an
        # explicitly configured non-default directory
        if cc not in ("", "0", "off", "none") and (
                cc != DEFAULT_COMPILE_CACHE
                or jax.default_backend() != "cpu"):
            # best-effort: jax without the feature or a read-only home
            # degrades to in-memory caching
            try:
                jax.config.update("jax_compilation_cache_dir",
                                  os.path.expanduser(cc))
            except Exception:
                pass
        self.mesh_exec = mesh_exec or MeshExec(
            num_workers=self.config.num_workers)
        self.mesh_exec.exchange_mode = self.config.exchange
        if host_rank is None:
            host_rank = jax.process_index()
        self.host_rank = host_rank
        # worker-level collectives, single-controller flavor (host ops)
        self.flow = LocalFlowControl(self.num_workers)
        # host-level control plane: FlowControlChannel over a real group
        # (reference: ctx.net, api/context.hpp:446-448). Single-process
        # runs get a trivial 1-host group; multi-process deployments
        # bootstrap the authenticated TCP full mesh from THRILL_TPU_*
        # env so host-side scalar agreement crosses machines.
        self.net = FlowControlChannel(self._construct_host_group())
        # the host-storage data plane (data/multiplexer.py) reaches the
        # other controllers through the mesh handle every shard carries
        self.mesh_exec.host_net = self.net
        self.logger = JsonLogger(
            default_log_path(self.config.log_path, host_rank=host_rank),
            program="thrill_tpu", workers=self.num_workers,
            host=host_rank)
        # storage-layer events (device->host demotions) log through the
        # mesh the shards carry a reference to
        self.mesh_exec.logger = self.logger
        # tracing spine (common/trace.py): one Tracer per Context,
        # attached to the mesh (dispatch/fusion/exchange/mem/loop
        # spans) and the net group (collective/heal spans); spans are
        # tagged with the generation and tenant CURRENT at span start.
        # THRILL_TPU_TRACE=0 pins the disabled fast path (no span
        # objects anywhere); the ring doubles as the flight recorder.
        from ..common.trace import Tracer
        self.tracer = Tracer(rank=host_rank, logger=self.logger)
        # getattr, not plain attribute reads: generation/current_tenant
        # are assigned further down __init__, and a span started during
        # construction must not crash on the not-yet-bound names
        self.tracer.gen_fn = lambda: getattr(self, "generation", None)
        self.tracer.tenant_fn = \
            lambda: getattr(self, "current_tenant", None)
        self.mesh_exec.tracer = self.tracer
        self.net.group.tracer = self.tracer
        # performance doctor (common/doctor.py): per-peer collective
        # wait attribution + partition-skew detection + the critical-
        # path pass over the span ring. THRILL_TPU_DOCTOR=0 pins the
        # disabled fast path (no Doctor anywhere: every choke point
        # pays one attribute read, allocates nothing).
        from ..common.doctor import Doctor, doctor_enabled
        self.doctor = Doctor(rank=host_rank) if doctor_enabled() \
            else None
        self.mesh_exec.doctor = self.doctor
        self.net.group.doctor = self.doctor
        # plan observatory (common/decisions.py): one DecisionLedger
        # per Context, attached to the mesh so every plan-choice choke
        # point (fusion, exchange, preshuffle, admission, plan store)
        # reaches it in one attribute read. THRILL_TPU_DECISIONS=0
        # pins the disabled fast path (no record objects anywhere);
        # records ride the JSON log (event=decision) and the trace's
        # "plan" lane, and ctx.explain() renders them on the DIA tree.
        from ..common.decisions import DecisionLedger
        self.decisions = DecisionLedger(logger=self.logger,
                                        tracer=self.tracer)
        self.mesh_exec.decisions = self.decisions
        # adaptive cost-based planner (api/planner.py): one model over
        # the learned plan state that CHOOSES — exchange strategy and
        # chunk count, optimistic-vs-synced dispatch, pre-shuffle
        # prune verdicts, proactive fusion splits under the HBM
        # admission estimate — and RE-OPTIMIZES when the decision
        # ledger's audit joins reveal a learned stat lied.
        # THRILL_TPU_PLANNER=0 restores the per-site heuristics
        # exactly (no Planner constructed, every call site takes its
        # legacy branch).
        from .planner import Planner, planner_enabled
        self.planner = None
        if planner_enabled():
            self.planner = Planner(self.mesh_exec)
            self.mesh_exec.planner = self.planner
            self.decisions.audit_hook = self.planner.on_audit
        # live metrics endpoint (common/metrics.py): Prometheus text on
        # THRILL_TPU_METRICS_PORT from a daemon thread; unset = off
        from ..common.metrics import maybe_start as _metrics_start
        self._metrics = _metrics_start(self)
        # fault-injection / retry / abort events from every layer ride
        # the same JSON stream (tools/json2profile.py renders them);
        # counters are process-lifetime, so snapshot a baseline and
        # report per-job deltas (sequential Run()s must not inherit a
        # previous job's retries)
        from ..common import faults
        if self.logger.enabled:
            faults.REGISTRY.set_logger(self.logger.line)
        self._faults_base = faults.REGISTRY.stats()
        # out-of-core I/O overlap ledger (common/iostats.py): same
        # process-lifetime baseline pattern as the fault counters
        from ..common.iostats import IO as _iostats
        self._io_base = _iostats.snapshot()
        self.mem = MemoryManager(name="context")
        from ..mem.hbm import HbmGovernor
        self.hbm = HbmGovernor(self, limit=self.config.hbm_limit)
        # memory-pressure resilience (mem/pressure.py): HBM admission
        # control + the OOM escalation ladder. Enabled only when a
        # budget is known (device memory_stats or THRILL_TPU_HBM_LIMIT)
        # — otherwise every dispatch pays one attribute read.
        from ..mem.pressure import PressureMonitor
        self.pressure = PressureMonitor(self.mesh_exec,
                                        governor=self.hbm)
        self.mesh_exec.pressure = self.pressure
        # stage memory negotiation state: bytes currently reserved by
        # active grants (reference: per-stage RAM distribution among
        # max-RAM requesters, api/dia_base.cpp:121-270)
        self._mem_reserved = 0
        self._mem_lock = threading.Lock()
        self.rng = np.random.default_rng(seed)
        self._nodes: List[Any] = []
        # coordinated-abort latch: set by abort() (and by close() when
        # an abort-class exception is in flight) so cleanup never runs
        # collectives against dead peers and leaked run files get swept
        self._aborted = False
        # generation-scoped failure domains: every pipeline run carries
        # the CURRENT generation id; an abort tears down only that
        # generation (ctx.pipeline() heals and bumps it) instead of
        # poisoning the whole Context. The net group shares the id so
        # poison frames / barriers are tagged consistently. The
        # counter is MONOTONIC and never reused (nested/sequential
        # blocks each get a fresh id; clean exits restore the parent
        # domain without ever re-issuing an id a node is stamped with).
        self.generation = 1
        self._gen_counter = 1
        self.net.group.generation = self.generation
        self.stats_pipeline_aborts = 0
        self.stats_heal_time_s = 0.0
        # elastic mesh (Context.resize): resizes completed on this
        # Context and the wall seconds they cost — the serve lane
        # reports both (a resize-free run must show 0 / 0.0)
        self.stats_resizes = 0
        self.stats_resize_time_s = 0.0
        # process-level elasticity (resize_processes): moves this
        # Context committed, and the exiting-for-relaunch latch —
        # once the marker is on disk the shutdown is LOCAL (the group
        # membership already drained; a shrink's survivors and its
        # departing ranks no longer share collective membership)
        self.stats_resizes_proc = 0
        self._resize_exiting = False
        # service plane (thrill_tpu/service/): the scheduler is
        # constructed lazily by the first submit(); current_tenant is
        # the tenant nodes created right now are stamped with (the
        # scheduler sets it around each job, service/tenancy.py's
        # activate() is the direct-use form)
        self.service = None
        self._service_lock = threading.Lock()
        self._closed = False
        self.current_tenant: Optional[str] = None
        # network front door (service/front_door.py): set when a
        # FrontDoor binds to this Context — closed before the
        # scheduler so no reader thread submits into a draining queue.
        # THRILL_TPU_SERVE_PORT auto-starts one (mirror of the metrics
        # endpoint above); loud degrade on bind failure, never fatal.
        self.front_door = None
        from ..service.front_door import maybe_start as _fd_start
        _fd_start(self)
        # autoscaler (service/autoscale.py): the policy thread that
        # watches queue depth / rejects / serve p99 and drives resize.
        # Off (None, zero overhead) unless THRILL_TPU_AUTOSCALE_S > 0;
        # stopped in close() before the front door so no decision
        # fires into a draining service plane.
        self.autoscaler = None
        from ..service.autoscale import maybe_start as _as_start
        self.autoscaler = _as_start(self)
        # persistent plan store (service/plan_store.py): learned
        # exchange capacities / narrow specs / plan kinds / pre-shuffle
        # verdicts seed the fresh mesh, so a warm restart re-runs a
        # known pipeline with zero data-driven plan builds. Off (zero
        # overhead) unless THRILL_TPU_PLAN_STORE is set.
        self.plan_store = None
        if self.config.plan_store and self.mesh_exec.num_processes > 1:
            # multi-controller meshes: RANK 0 reads the store and
            # BROADCASTS the entries over the host control plane, so
            # every rank installs the IDENTICAL seeds — the
            # asymmetric-read hazard (one rank cold, one seeded; a
            # corrupt file on one host) that used to force the loud
            # skip cannot arise, because only one read ever happens.
            # Rank 0 keeps the store handle (it is the single writer
            # at close; the learned state derives from replicated plan
            # inputs, so one rank's copy is the cluster's copy).
            # Without a spanning host control plane there is still no
            # agreement channel — keep the loud skip.
            if self.net.num_workers == self.mesh_exec.num_processes:
                from ..service.plan_store import (PlanStore,
                                                  install_entries)
                entries = None
                if self.host_rank == 0:
                    self.plan_store = PlanStore(self.config.plan_store,
                                                logger=self.logger)
                    entries = self.plan_store.load()
                entries = self.net.broadcast(entries, origin=0)
                seeded = install_entries(self.mesh_exec, entries or {},
                                         symmetric=True)
                # every rank now provably holds identical seeds, and
                # state learned from here derives from the replicated
                # send matrix: the optimistic exchange path stays open
                # on this mesh (data/exchange.py _optimistic_ok —
                # symmetric=True is the attestation; a storeless mesh
                # is symmetric by default, planner edge (a))
                self.mesh_exec._plan_seed_symmetric = True
                if self.logger.enabled:
                    self.logger.line(event="plan_store_load",
                                     path=self.config.plan_store,
                                     entries=seeded, broadcast=True)
                if self.decisions.enabled:
                    # the store_skip decision of old is now a
                    # store_broadcast one: explain() shows the warm
                    # start happened and how it stayed symmetric
                    self.decisions.record(
                        "store_broadcast", "plan_store",
                        "warm-start" if seeded else "cold",
                        rejected=[("per-rank-read", None)],
                        reason="rank-0 load broadcast over ctx.net "
                               "keeps SPMD plan seeds symmetric",
                        entries=seeded, path=self.config.plan_store)
            else:
                import sys
                print("thrill_tpu.service: THRILL_TPU_PLAN_STORE "
                      "ignored on a multi-process mesh without a "
                      "spanning host control plane (no channel to "
                      "broadcast rank 0's entries); recompiling cold",
                      file=sys.stderr)
                if self.decisions.enabled:
                    self.decisions.record(
                        "store_skip", "plan_store", "cold",
                        rejected=[("warm-start", None)],
                        reason="multi-process mesh without a host "
                               "control plane: rank-0 entries cannot "
                               "be broadcast",
                        path=self.config.plan_store)
        elif self.config.plan_store:
            from ..service.plan_store import PlanStore
            self.plan_store = PlanStore(self.config.plan_store,
                                        logger=self.logger)
            seeded = self.plan_store.attach(self.mesh_exec)
            if self.logger.enabled:
                self.logger.line(event="plan_store_load",
                                 path=self.config.plan_store,
                                 entries=seeded)
            if self.decisions.enabled \
                    and self.plan_store._last_corrupt is not None:
                # the corrupt-degrade is a plan decision too: the
                # service chose cold recompile over a torn store
                self.decisions.record(
                    "store_skip", "plan_store", "cold",
                    rejected=[("warm-start", None)],
                    reason="store corrupt: "
                           + self.plan_store._last_corrupt[:120],
                    path=self.config.plan_store)
        # checkpoint/resume subsystem (api/checkpoint.py): fully off —
        # ctx.checkpoint stays None, the stage driver pays one
        # attribute read — unless THRILL_TPU_CKPT_DIR is set
        self.checkpoint = None
        if self.config.ckpt_dir:
            from .checkpoint import CheckpointManager
            self.checkpoint = CheckpointManager(
                self, self.config.ckpt_dir,
                resume=resume or self.config.resume,
                auto=self.config.ckpt_auto)
        self._profiler = None
        if self.config.profile and self.logger.enabled:
            from ..common.profile import ProfileThread
            self._profiler = ProfileThread(self.logger).start()

    def _construct_host_group(self):
        from ..net import tcp
        import os
        if jax.process_count() > 1:
            # THRILL_TPU_NET selects the control-plane transport like
            # the reference's THRILL_NET (api/context.cpp:822-847):
            # tcp (default, authenticated full mesh) or mpi (mpi4py,
            # tag-namespace groups over COMM_WORLD)
            if os.environ.get("THRILL_TPU_NET") == "mpi":
                from ..net import mpi as mpi_net
                grp = mpi_net.construct(1)[0]
                if grp.num_hosts != jax.process_count():
                    raise ValueError(
                        f"MPI world has {grp.num_hosts} ranks but "
                        f"jax.process_count() is {jax.process_count()}")
                if grp.my_rank != jax.process_index():
                    raise ValueError(
                        f"MPI rank {grp.my_rank} disagrees with "
                        f"jax.process_index()={jax.process_index()} — "
                        f"the host control plane and the device mesh "
                        f"must use the same rank order")
                return grp
            grp = tcp.construct_from_env()
            if grp is not None:
                if grp.num_hosts != jax.process_count():
                    raise ValueError(
                        f"THRILL_TPU_HOSTLIST has {grp.num_hosts} hosts "
                        f"but jax.process_count() is "
                        f"{jax.process_count()}")
                if grp.my_rank != jax.process_index():
                    raise ValueError(
                        f"THRILL_TPU_RANK={grp.my_rank} disagrees with "
                        f"jax.process_index()={jax.process_index()} — "
                        f"the host control plane and the device mesh "
                        f"must use the same rank order")
                return grp
            import sys
            print("thrill_tpu: multi-process run without "
                  "THRILL_TPU_HOSTLIST — host-side control plane is "
                  "process-local only (cross-host scalar agreement "
                  "rides device collectives exclusively)",
                  file=sys.stderr)
        return tcp.TcpGroup(0, 1, {})

    # -- identity -------------------------------------------------------
    @property
    def num_workers(self) -> int:
        return self.mesh_exec.num_workers

    def _register_node(self, node) -> int:
        # stamp the failure domain: a heal disposes exactly the nodes
        # of the aborted generation (their shards may be partial) and
        # leaves earlier generations' cached results untouched. The
        # tenant stamp routes the node's HBM bytes to the per-tenant
        # ledger (mem/hbm.py, service/tenancy.py).
        node._generation = self.generation
        node._tenant = self.current_tenant
        self._nodes.append(node)
        return len(self._nodes) - 1

    # -- service plane (thrill_tpu/service/) ----------------------------
    def submit(self, pipeline_fn: Callable[["Context"], Any],
               tenant: str = "default", name: str = "",
               weight: Optional[float] = None):
        """Queue ``pipeline_fn(ctx) -> result`` for execution on this
        Context and return a :class:`~thrill_tpu.service.JobFuture`.

        Thread-safe: any number of client threads may submit; jobs
        serialize onto the SPMD mesh in weighted-fair order across
        tenants (service/scheduler.py). Each job runs in its own
        ``ctx.pipeline()`` failure domain — a failing job raises its
        :class:`PipelineError` from ``future.result()`` while the
        Context heals and later jobs run normally. Once a Context
        serves, run ALL its pipelines through submit(): the Context is
        not re-entrant, and a main-thread pipeline racing the
        dispatcher would interleave device programs."""
        svc = self.service
        if svc is None:
            # first submit may race across client threads: exactly ONE
            # scheduler (and dispatcher thread) may ever own the mesh
            with self._service_lock:
                if self._closed:
                    # a first submit AFTER close() must not construct
                    # a live scheduler over the torn-down mesh — it
                    # resolves failed, like a submit on a closed
                    # scheduler does
                    from ..service.scheduler import JobFuture
                    return JobFuture.failed(
                        0, tenant, name or "job-0",
                        RuntimeError("Context is closed"))
                svc = self.service
                if svc is None:
                    from ..service.scheduler import Scheduler
                    svc = self.service = Scheduler(self)
        return svc.submit(pipeline_fn, tenant=tenant, name=name,
                          weight=weight)

    # -- elastic mesh: W is a per-generation property --------------------
    def resize(self, num_workers: int) -> float:
        """Resize the mesh to ``num_workers`` logical workers at a
        generation boundary; returns the wall seconds it took.

        Every LIVE cached result (node shards held by ``.Keep`` or a
        pending consumer) is re-partitioned across the new W by the
        checkpoint serializer — the same dense-range split a fresh
        ``W'``-wide run lays data out with, so post-resize pipelines
        compute bit-identical to a fixed-``W'`` Context. Learned plan
        state is W-SHAPED and swaps atomically: the old W's sticky
        exchange capacities, cached programs and loop tapes are parked
        in a per-W archive (a later resize BACK restores them warm),
        while the HBM governor's tenant ledger, the scheduler and its
        WFQ queue carry across unchanged.

        On a SERVING Context the swap runs fenced on the dispatcher
        thread at the next job boundary: the in-flight job finishes on
        the old mesh, the swap runs exclusively (ahead of the queue —
        under sustained traffic the queue may never drain), and every
        queued future then runs on the new mesh and resolves normally.
        A job observes exactly one W for its whole run, never a
        half-swapped mesh.

        Single-process only: a JAX device mesh cannot change its
        process set, so on multi-controller deployments membership
        changes happen in the host control plane instead
        (``net.Group.resize`` / ``net.tcp.join_tcp_group``) and each
        process keeps its local devices. ``THRILL_TPU_RESIZE=0`` pins
        W entirely (this method raises)."""
        from ..net.group import resize_enabled
        if self._closed:
            raise RuntimeError("Context is closed")
        if not resize_enabled():
            raise RuntimeError(
                "THRILL_TPU_RESIZE=0 pins the worker count for this "
                "process; unset it to allow Context.resize")
        new_w = int(num_workers)
        if new_w < 1:
            raise ValueError("cannot resize to an empty mesh")
        if self.mesh_exec.num_processes > 1 \
                or self.net.num_workers > 1 or jax.process_count() > 1:
            raise RuntimeError(
                "Context.resize is single-process only: a JAX device "
                "mesh cannot add or drop processes at runtime. On a "
                "multi-controller deployment, change membership in "
                "the host control plane (net.Group.resize for "
                "survivors/leavers, net.tcp.join_tcp_group for a "
                "joining rank) and relaunch the job at the new W — "
                "see ARCHITECTURE.md \"Elastic mesh\"")
        if new_w == self.num_workers:
            return 0.0
        svc = self.service
        if svc is not None and svc.alive:
            # fenced: the dispatcher runs the swap between jobs, so no
            # pipeline ever traces against a half-swapped mesh. The
            # front door's verdict gate closes FIRST: a socket submit
            # that reaches its admission verdict while this fence is
            # pending must not be told "accept" with the generation
            # (and W) the swap is about to invalidate — its verdict
            # waits out the swap and names the post-resize generation.
            fd = self.front_door
            if fd is not None:
                fd.begin_resize_fence()
            try:
                return svc.fence(lambda: self._resize_now(new_w))
            finally:
                if fd is not None:
                    fd.end_resize_fence()
        return self._resize_now(new_w)

    def _resize_now(self, new_w: int) -> float:
        from ..mem.hbm import SpilledShards
        from .checkpoint import (commit_repartition, stage_repartition)
        t0 = time.monotonic()
        mex = self.mesh_exec
        old_w = mex.num_workers
        plat = mex.devices[0].platform
        devs = [d for d in jax.devices() if d.platform == plat]
        if new_w > len(devs):
            raise ValueError(
                f"resize to {new_w} needs {new_w} {plat} devices, "
                f"have {len(devs)}; set XLA_FLAGS="
                f"--xla_force_host_platform_device_count={new_w} "
                f"for CPU meshes")
        # 1) STAGE: serialize every live result to host bytes through
        # the checkpoint serializer. Pure reads — the repartition
        # fault site fires here, BEFORE anything mutated, so an
        # injected failure leaves the Context exactly as it was and
        # the next resize attempt starts clean.
        live = []
        for node in self._nodes:
            if getattr(node, "_shards", None) is None:
                continue
            if isinstance(node._shards, SpilledShards):
                # re-split works on materialized shards; touch()
                # transparently restores the spilled result first
                self.hbm.touch(node)
            live.append((node, stage_repartition(node._shards)))
        # 2) SWAP: the mesh itself (per-W plan state parks in the
        # archive inside), then the worker-level flow channel, which
        # is W-wide by construction
        mex.resize(devs[:new_w])
        self.flow = LocalFlowControl(new_w)
        # 3) COMMIT: rebuild every staged result on the new mesh and
        # re-admit it to the HBM ledger at its new true size (tenant
        # budgets and spill counters carry across untouched)
        for node, blob in live:
            self.hbm.on_release(node, None)
            node._shards = commit_repartition(mex, blob)
            self.hbm.on_cache(node)
        # 4) a fresh generation: results computed from here belong to
        # the new W's failure domain (the host group is trivial in a
        # single-process Context, so the barrier is local bookkeeping)
        self._gen_counter += 1
        self.generation = self._gen_counter
        self.net.group.begin_generation(self.generation)
        dt = time.monotonic() - t0
        self.stats_resizes += 1
        self.stats_resize_time_s += dt
        if self.logger.enabled:
            self.logger.line(event="resize", workers_old=old_w,
                             workers_new=new_w, nodes_moved=len(live),
                             generation=self.generation,
                             resize_time_s=round(dt, 4))
        return dt

    # -- process-level elasticity: drain → seal → relaunch as one move --
    def resize_processes(self, num_workers: int, state=None,
                         drain_timeout_s: Optional[float] = None):
        """Orchestrated process-level resize: drain the service plane,
        seal a RESIZE checkpoint epoch re-partitioned to ``W'``,
        agree the relaunch over the host group, commit the RESIZE
        marker, and exit every process with :data:`RESIZE_EXIT_CODE`
        so the supervisor (run-scripts/supervise.sh) relaunches the
        job at ``W'`` with ``THRILL_TPU_RESUME=1``. Never returns:
        raises :class:`ResizeRelaunch` (a SystemExit) on success.

        ``state`` is the DIA whose materialized shards carry across
        the move (``Execute()``/``.Keep`` it first); ``None`` commits
        a data-free move — the relaunch starts the job body from
        scratch at ``W'``. Call it on the MAIN thread only; an
        autoscaler ``apply_fn`` should signal the main loop rather
        than call this from the policy thread (a SystemExit raised on
        a helper thread kills just that thread).

        Crash-safety, step by step (the fault-matrix contract):

        1. DRAIN — front door stops admitting (typed ``draining``
           rejects, clients redial post-relaunch), local queue runs
           dry. Nothing durable changed; failure aborts clean.
        2. SEAL (``ckpt.resize_manifest``) — the W'-worker epoch.
           SIGKILL mid-seal leaves an uncommitted dir swept at next
           resume; a COMMITTED epoch with no marker is inert (the
           old-W resume's workers gate rejects it).
        3. GATE (``net.group.relaunch``) — mutation-free agreement
           every rank reached the move (shrink settles through the
           lenient departing-peer barrier). Failure aborts clean.
        4. MARKER (``ckpt.resize_manifest``, stage=marker) — the
           point of no return. Before it lands: relaunch heals at the
           old W. After: any relaunch — including the supervisor's
           retry after a SIGKILL right here — reads the marker and
           completes the move at ``W'``.
        5. EXIT — every rank raises :class:`ResizeRelaunch`; close()
           runs collective-free (``_resize_exiting``) since ranks exit
           at their own pace from here.
        """
        from ..common import faults
        from ..net.group import resize_enabled, resize_timeout_s
        if self._closed:
            raise RuntimeError("Context is closed")
        if not resize_enabled():
            raise RuntimeError(
                "THRILL_TPU_RESIZE=0 pins the worker count for this "
                "job; unset it to allow Context.resize_processes")
        if self.checkpoint is None:
            raise ValueError(
                "resize_processes needs THRILL_TPU_CKPT_DIR: the "
                "RESIZE epoch and the relaunch marker live in the "
                "checkpoint directory")
        new_w = int(num_workers)
        if new_w < 1:
            raise ValueError("cannot resize to an empty mesh")
        old_w = self.num_workers
        if new_w == old_w:
            raise ValueError(
                f"already running W={old_w}: resize_processes is a "
                f"whole-process relaunch, a same-W move would restart "
                f"the job for nothing")
        procs = max(1, self.mesh_exec.num_processes)
        local = max(1, old_w // procs)
        if procs > 1 and new_w % local:
            raise ValueError(
                f"W'={new_w} is not a multiple of the {local} "
                f"workers each process contributes; the supervisor "
                f"relaunches whole processes")
        target_procs = (new_w // local) if procs > 1 else 1
        timeout = (drain_timeout_s if drain_timeout_s is not None
                   else resize_timeout_s())
        t0 = time.monotonic()
        # 1) DRAIN
        if self.front_door is not None:
            self.front_door.drain()
        self._quiesce_service(timeout)
        # 2) SEAL
        epoch = None
        if state is not None:
            node = getattr(state, "node", state)
            shards = getattr(node, "_shards", None)
            if shards is None:
                raise ValueError(
                    f"resize_processes state {node.label!r} has no "
                    f"materialized shards; Execute()/Keep() it before "
                    f"the move")
            epoch = self.checkpoint.seal_resize(node, shards, new_w)
        # 3) GATE — settle the move's generation over the old group
        gen = self._gen_counter + 1
        self.net.group.prepare_relaunch(target_procs, gen)
        self._gen_counter = gen
        self.generation = gen
        # 4) MARKER — the point of no return
        self.checkpoint.commit_resize_marker(
            new_w, epoch=epoch, generation=gen, procs=target_procs)
        # 5) EXIT
        self._resize_exiting = True
        self.stats_resizes_proc += 1
        dt = time.monotonic() - t0
        self.stats_resize_time_s += dt
        faults.note("recovery", what="ctx.resize_processes",
                    old_w=old_w, new_w=new_w, epoch=epoch,
                    generation=gen, _quiet=True)
        if self.logger.enabled:
            self.logger.line(event="resize_processes",
                             workers_old=old_w, workers_new=new_w,
                             procs_old=procs, procs_new=target_procs,
                             epoch=epoch, generation=gen,
                             seconds=round(dt, 4))
        raise ResizeRelaunch(new_w, epoch=epoch, generation=gen)

    def _quiesce_service(self, timeout: float) -> None:
        """Wait until the local scheduler has no queued or in-flight
        job (``jobs_done`` catches up to ``jobs_submitted``). The
        front door is already draining, so no NEW work arrives over
        the socket edge; direct ``ctx.submit`` callers are expected to
        stop submitting around a resize — under sustained direct
        traffic this times out and the move aborts clean."""
        svc = self.service
        if svc is None or not svc.alive:
            return
        deadline = time.monotonic() + max(0.1, float(timeout))
        while True:
            with svc._cv:
                idle = (svc.queue.depth == 0
                        and svc.jobs_done >= svc.jobs_submitted)
            if idle:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"resize_processes: service did not drain within "
                    f"{timeout:.1f}s (queued={svc.queue.depth}, "
                    f"in_flight="
                    f"{svc.jobs_submitted - svc.jobs_done}); the move "
                    f"aborted with nothing mutated")
            time.sleep(0.02)
        if self.net.num_workers > 1:
            # multi-controller: the follower dispatchers park in a net
            # recv waiting for rank 0's next ordering frame, so the
            # move's seal/gate collectives below would race that recv
            # for frames. Stop the scheduler collectively instead —
            # rank 0's close broadcasts the drain sentinel and every
            # rank's dispatcher exits at the same control-plane point
            # (TCP ordering puts the sentinel after the last job's
            # frames). Every drained future has already resolved; a
            # submit after an aborted move lazily builds a fresh
            # scheduler, so the abort still leaves a serving Context.
            svc.close(timeout=timeout)
            self.service = None

    # -- stage memory negotiation ---------------------------------------
    # Reference: the StageBuilder distributes worker RAM per stage —
    # fixed DIAMemUse requests are subtracted, the remainder is split
    # evenly among ops requesting DIAMemUse::Max
    # (api/dia_base.cpp:121-270). Pull-model translation: requesters
    # negotiate on entry to compute() and RESERVE their grant until
    # release; a "max" requester gets half of the remaining pool, so
    # nested concurrent requesters (recursive Sorts) get geometrically
    # smaller shares and the pool is never over-committed (the
    # reference can split exactly because a stage's requesters are
    # known up front; here they arrive dynamically).
    @property
    def ram_workers(self) -> int:
        """Host-RAM pool for operator workspace (one third of the
        configured or detected RAM, reference MemoryConfig split,
        api/context.cpp:1082-1093)."""
        ram = getattr(self, "_ram_workers", None)
        if ram is None:
            total = self.config.ram or self.config.host_ram
            if not total:
                try:
                    total = (os.sysconf("SC_PAGE_SIZE")
                             * os.sysconf("SC_PHYS_PAGES"))
                except (ValueError, OSError):
                    total = 8 << 30
            from ..mem.manager import MemoryConfig
            ram = self._ram_workers = MemoryConfig.split(total).ram_workers
        return ram

    def negotiate_mem(self, node) -> bool:
        """Grant ``node.mem_limit`` per its ``mem_use()`` request.
        Returns True when something was granted (caller must
        release_mem after compute)."""
        req = node.mem_use()
        if req is None:
            node.mem_limit = None
            return False
        with self._mem_lock:   # net layer is multi-threaded; stay safe
            remaining = max(self.ram_workers - self._mem_reserved, 4096)
            if req == "max":
                grant = max(remaining // 2, 4096)
            else:
                grant = min(int(req), remaining)
            self._mem_reserved += grant
            reserved = self._mem_reserved
        node.mem_limit = grant
        node._mem_grant = grant
        short = req != "max" and grant < int(req)
        if self.logger.enabled:
            self.logger.line(event="mem_negotiate", node=node.label,
                             dia_id=node.id, grant=grant,
                             reserved=reserved,
                             short=short or None)
        if short:
            # fixed-size requesters must see they got less than asked —
            # they read node.mem_limit (the granted amount) to adapt
            import sys
            print(f"thrill_tpu: mem_negotiate short grant for "
                  f"{node.label}: requested {req}, granted {grant}",
                  file=sys.stderr)
        return True

    def release_mem(self, node) -> None:
        grant = getattr(node, "_mem_grant", 0)
        if grant:
            with self._mem_lock:
                self._mem_reserved -= grant
        node._mem_grant = 0

    # -- sources (created lazily like every DIA op) ---------------------
    def Generate(self, size: int, fn: Optional[Callable] = None,
                 storage: Optional[str] = None):
        from .ops import sources
        return sources.Generate(self, size, fn, storage)

    def Distribute(self, items, storage: Optional[str] = None):
        from .ops import sources
        return sources.Distribute(self, items, storage)

    def EqualToDIA(self, items, storage: Optional[str] = None):
        """Every-worker-identical local data -> DIA (reference:
        api/equal_to_dia.hpp:30; here identical by construction)."""
        from .ops import sources
        return sources.Distribute(self, items, storage)

    def ConcatToDIA(self, per_worker_items, storage: Optional[str] = None):
        from .ops import sources
        return sources.ConcatToDIA(self, per_worker_items, storage)

    def ReadLines(self, path_or_glob: str):
        from .ops import read_write
        return read_write.ReadLines(self, path_or_glob)

    def ReadWordsPacked(self, path_or_glob: str, max_word: int = 16):
        """Text -> device DIA of {"w": [max_word] uint8} packed words
        (vectorized tokenization; device-native WordCount input)."""
        from .ops import read_write
        return read_write.ReadWordsPacked(self, path_or_glob, max_word)

    def ReadBinary(self, path_or_glob: str, dtype, record_shape=()):
        from .ops import read_write
        return read_write.ReadBinary(self, path_or_glob, dtype, record_shape)

    # -- plan observatory (common/decisions.py) -------------------------
    def explain(self, pipeline_fn: Optional[Callable] = None,
                name: str = "") -> str:
        """Render the physical plan as an annotated tree: ops, fused
        segments, the exchange strategy per shuffle edge, and every
        recorded decision with its reason and (post-run) its audit
        verdict.

        ``ctx.explain(pipeline_fn)`` runs ``pipeline_fn(ctx)`` and
        renders exactly the nodes that run created; ``ctx.explain()``
        renders everything this Context has built so far. Purely
        observational: reads the decision ledger, changes no plan."""
        from ..common.decisions import render_plan
        lo = 0
        if pipeline_fn is not None:
            lo = len(self._nodes)
            pipeline_fn(self)
        nodes = self._nodes[lo:]
        return render_plan(
            [{"id": n.id, "label": n.label, "state": n.state,
              "parents": [p.node.id for p in n.parents]}
             for n in nodes],
            self.decisions.snapshot(), W=self.num_workers,
            title=name or (getattr(pipeline_fn, "__name__", "")
                           if pipeline_fn is not None else ""))

    def doctor_report(self, k: int = 5) -> dict:
        """The performance doctor's full diagnosis for this Context:
        wait attribution + straggler scores, per-site skew table, and
        the critical path computed over the tracer's span ring (the
        post-run pass; tools/doctor_report.py is the offline twin over
        merged logs). Returns {} with THRILL_TPU_DOCTOR=0. Purely
        observational — local state only, never a collective."""
        if self.doctor is None:
            return {}
        ring = self.tracer.ring if self.tracer.enabled else None
        return self.doctor.report(ring=ring or (), k=k)

    def overall_stats(self, local_only: bool = False) -> dict:
        """End-of-job summary (reference: OverallStats AllReduce,
        api/context.cpp:1235-1341). In multi-process runs the per-host
        stats are aggregated over the host control plane (``ctx.net``):
        counters sum, peaks take the max.

        ``local_only=True`` NEVER enters the cross-host collective —
        the metrics endpoint's scrape thread (common/metrics.py) uses
        it so a scrape can run while the service dispatcher owns the
        control plane (the PR-9 local-view stats rule)."""
        mex = self.mesh_exec
        # fold real process RSS into the reported peak (reference:
        # malloc_tracker feeds OverallStats the true allocation peak)
        self.mem.sample_rss()
        stats = {
            "workers": self.num_workers,
            "nodes_created": len(self._nodes),
            "nodes_executed": sum(1 for n in self._nodes
                                  if n.state != "NEW"),
            "exchanges": mex.stats_exchanges,
            "items_moved": mex.stats_items_moved,
            "bytes_moved": mex.stats_bytes_moved,
            # overlapped exchange data plane (data/exchange.py):
            # exchanges dispatched with NO mid-shuffle host sync, the
            # capacity-plan cache's hit/miss record, and the bytes that
            # actually cross the fabric (padded device rows) / the TCP
            # wire (serialized host frames) — bytes_on_wire is the
            # pinned baseline for ROADMAP's shrink-the-wire item
            "exchanges_overlapped": mex.stats_exchanges_overlapped,
            "cap_cache_hits": mex.stats_cap_cache_hits,
            "cap_cache_misses": mex.stats_cap_cache_misses,
            "bytes_wire_device": mex.stats_bytes_wire_device,
            "bytes_wire_host": mex.stats_bytes_wire_host,
            "bytes_on_wire": (mex.stats_bytes_wire_device
                              + mex.stats_bytes_wire_host),
            # shrink-the-wire layer (ISSUE 7): the raw-equivalent
            # volume (full-width device rows + host frame bytes before
            # the column codec) and the resulting compression ratio —
            # >= 1.0, exactly 1.0 with THRILL_TPU_WIRE_COMPRESS=0
            "bytes_wire_device_raw": mex.stats_bytes_wire_device_raw,
            "bytes_wire_host_saved": mex.stats_bytes_wire_host_saved,
            "bytes_on_wire_raw": (mex.stats_bytes_wire_device_raw
                                  + mex.stats_bytes_wire_host
                                  + mex.stats_bytes_wire_host_saved),
            "wire_compress_ratio": _wire_ratio(
                mex.stats_bytes_wire_device_raw
                + mex.stats_bytes_wire_host
                + mex.stats_bytes_wire_host_saved,
                mex.stats_bytes_wire_device
                + mex.stats_bytes_wire_host),
            # on a tunneled chip each dispatch/upload costs one link
            # RTT (140.7 ms measured, BASELINE.md r5) — the governing
            # pipeline cost; see tests/api/test_dispatch_budget.py
            "device_dispatches": mex.stats_dispatches,
            "device_uploads": mex.stats_uploads,
            "device_fetches": mex.stats_fetches,
            # program stitching (api/fusion.py): how many dispatches
            # the fused runner launched, how many DOp segments they
            # carried (ops/dispatch > 1 means chains actually fused),
            # and the per-stage composition table
            "fused_dispatches": mex.stats_fused_dispatches,
            "fused_ops": mex.stats_fused_ops,
            # dict() snapshot: the metrics scrape thread calls this
            # with local_only=True while the dispatcher inserts new
            # stage compositions — iterating the live dict would die
            # mid-scrape on "changed size during iteration"
            "fused_stages": {" + ".join(ops): n for ops, n in
                             dict(mex.fused_stage_counts).items()},
            # iteration execution layer (api/loop.py): captures vs
            # replayed iterations (zero graph build / planning), whole-
            # loop fori_loop iterations, loud replay fallbacks, and
            # HBM bytes donated back to XLA on replayed dispatches
            "loop_plan_builds": mex.stats_loop_plan_builds,
            "loop_replays": mex.stats_loop_replays,
            "loop_fori_iters": mex.stats_loop_fori_iters,
            "loop_replay_fallbacks": mex.stats_loop_fallbacks,
            "loop_donated_bytes": mex.stats_loop_donated_bytes,
            "host_mem_peak": self.mem.peak,
            "hbm_peak": self.hbm.mem.peak,
            "hbm_spills": self.hbm.spill_count,
            "hbm_restores": self.hbm.restore_count,
            # memory-pressure ladder (mem/pressure.py): the admission
            # cost model's high watermark, OOM-retry dispatches,
            # segment splits and bytes spilled under pressure
            **self.pressure.stats(),
            # robustness layer: lineage retries of hinted joins plus
            # the process-wide fault/retry/abort counters
            # (common/faults.py)
            "join_overflow_retries": mex.stats_join_overflow_retries,
            # generation-scoped failure domains: pipelines aborted on
            # this Context (each healed, not fatal), time spent
            # healing, links repaired by the tcp reconnect, and stale
            # prior-generation frames the filter dropped — the seed
            # metrics for the sustained-traffic harness
            "generation": self.generation,
            "pipeline_aborts": self.stats_pipeline_aborts,
            "heal_time_s": round(self.stats_heal_time_s, 4),
            # elastic mesh: W changes this Context performed and their
            # wall cost (0 / 0.0 proves the machinery idle when unused)
            "resizes": self.stats_resizes,
            "resize_time_s": round(self.stats_resize_time_s, 4),
            # process-level elasticity (resize_processes) and the
            # autoscaler that drives it: orchestrated moves committed
            # by this Context, policy decisions/ticks, and EM runs
            # adopted from departed ranks — all pinned EXACTLY zero on
            # non-elastic workloads by the perf sentinel
            "resizes_proc": self.stats_resizes_proc,
            **(self.autoscaler.stats()
               if getattr(self, "autoscaler", None) is not None
               else {"autoscale_decisions": 0, "autoscale_ticks": 0}),
            "runs_adopted": _em_adopted(),
            "conn_reconnects": getattr(self.net.group,
                                       "stats_reconnects", 0),
            "stale_frames_dropped": getattr(self.net.group,
                                            "stats_stale_dropped", 0),
            # service plane (thrill_tpu/service/): admission counters
            # from the scheduler, per-tenant HBM peaks from the
            # governor ledger, and the plan-store counters — a warm
            # restart of a known pipeline reports plan_builds == 0
            **(self.service.stats() if self.service is not None else
               {"jobs_submitted": 0, "jobs_failed": 0,
                "jobs_rejected": 0, "jobs_rate_limited": 0,
                "queue_depth_peak": 0}),
            # front door (service/front_door.py): socket-edge counters
            # when this Context serves external clients — all zero (and
            # absent machinery) otherwise
            **(self.front_door.stats()
               if getattr(self, "front_door", None) is not None
               else {"fd_conns_accepted": 0, "fd_conns_dropped": 0,
                     "fd_jobs_submitted": 0, "fd_jobs_rejected": 0,
                     "fd_chunks_sent": 0, "fd_slow_clients": 0,
                     "fd_deadline_expired": 0}),
            "tenant_hbm_peaks": dict(self.hbm.tenant_peaks),
            "tenant_spills": self.hbm.tenant_spill_count,
            "plan_builds": mex.stats_plan_builds,
            "plan_store_hits": mex.stats_plan_store_hits,
            # adaptive planner (api/planner.py): sites whose learned
            # plan was invalidated and re-chosen after an audit/
            # deferred-check lie, and re-choices that actually changed
            # the plan — 0/0 on a run whose learned stats held
            **(self.planner.stats() if self.planner is not None else
               {"planner_replans": 0, "planner_switches": 0}),
            # plan observatory (common/decisions.py): how many plan
            # choices were recorded, how many have joined actuals, and
            # the per-kind accuracy ledger (mean |log2 pred/actual|) —
            # the number the ROADMAP adaptive planner will be judged by
            "decisions_recorded": sum(
                self.decisions.kind_counts.values()),
            "decisions_joined": sum(
                self.decisions.joined_counts.values()),
            "decision_accuracy": {
                k: v["mae_log2"]
                for k, v in self.decisions.accuracy().items()
                if v.get("mae_log2") is not None},
            # performance doctor (common/doctor.py): seconds blocked
            # at collectives/exchange barriers with the per-peer
            # arrival deltas and the net/exchange/io/skew
            # decomposition, plus the worst partition-skew ratio any
            # exchange site observed
            **(self.doctor.stats() if self.doctor is not None else
               {"collective_wait_s": 0.0, "wait_net_s": 0.0,
                "wait_exchange_s": 0.0, "wait_io_s": 0.0,
                "wait_skew_s": 0.0, "straggler_waits": {},
                "skew_ratio": 0.0}),
            # service-plane latency histograms (service/scheduler.py):
            # deterministic log2-bucket accept-to-result quantiles per
            # tenant, {} until a job completed
            **({"serve_p50_ms": {}, "serve_p99_ms": {}}
               if self.service is None
               else self.service.latency_quantiles()),
        }
        # durability layer (api/checkpoint.py): epochs committed, bytes
        # sealed, ops skipped by resume, time spent restoring
        if self.checkpoint is not None:
            stats.update(self.checkpoint.stats())
        from ..common import faults
        stats.update({k: v - self._faults_base.get(k, 0)
                      for k, v in faults.REGISTRY.stats().items()})
        # out-of-core storage tier (vfs prefetch readers, write-behind
        # spill, double-buffered restore): hit/miss record, foreground
        # seconds lost to I/O, background busy seconds, write-behind
        # volume and queue high-water mark, restores that overlapped
        from ..common.iostats import IO as _iostats
        stats.update(_iostats.delta(_iostats.snapshot(),
                                    self._io_base))
        if self.net.num_workers > 1 and not local_only \
                and not self._aborted and self.service is None \
                and not self._resize_exiting:
            # once a rank has EVER served, degrade to the local view
            # permanently: while dispatchers live, the non-root ranks'
            # park in a recv on this same untagged control plane
            # waiting for ordering frames — an application-thread
            # all_gather here would race them for frames — and the
            # skip decision must be CROSS-RANK DETERMINISTIC, which
            # `service.alive` is not (a one-rank poison kills one
            # dispatcher while its peers' survive; scheduler
            # CONSTRUCTION is lockstep under the submission contract,
            # so gating on it keeps every rank on the same branch).
            per_host = self.net.all_gather(stats)
            # almost every counter is a per-controller view of one
            # global value (exchange stats derive from the replicated
            # send matrix, the mesh spans all hosts, the DAG is one
            # logical graph) — take host 0's copy, don't sum. Only the
            # host-process-local peaks (and the per-process fault/
            # retry/abort counters) genuinely differ across hosts.
            local_peaks = {"host_mem_peak", "recovery_time_s",
                           "hbm_high_watermark", "heal_time_s"}
            local_peaks |= {"writeback_queue_peak"}
            # the worst skew any rank observed is the cluster's skew
            local_peaks |= {"skew_ratio"}
            local_sums = {"faults_injected", "faults_delayed",
                          "retries", "recoveries",
                          "aborts", "ckpt_bytes_written", "oom_retries",
                          "segment_splits", "host_fallbacks",
                          "admission_spills", "pressure_spilled_bytes",
                          # out-of-core tier: per-process background
                          # I/O flows sum; the queue peak maxes
                          "prefetch_hits", "prefetch_misses",
                          "io_wait_s", "io_busy_s", "writeback_bytes",
                          "restore_overlaps", "spill_runs",
                          "prefetch_submits", "records_blocks",
                          # link repairs and stale-frame drops are
                          # per-process transport events; the abort/
                          # generation counters are coordinated (host
                          # 0's copy, the default, is the global view)
                          "conn_reconnects", "stale_frames_dropped",
                          # adopted EM runs are per-process transport-
                          # local events too (each adopting rank
                          # rewrote its own OWNER records)
                          "runs_adopted",
                          # host frames (and their codec savings) are
                          # per-process partials; the device wire
                          # bytes — actual and raw — derive from the
                          # replicated send matrix (host 0's copy)
                          "bytes_wire_host", "bytes_wire_host_saved",
                          # per-process tenant spills sum; the service
                          # admission counters and plan-build/store
                          # counters are coordinated (lockstep
                          # submission / replicated plan decisions —
                          # host 0's copy, the default). The
                          # tenant_hbm_peaks DICT also stays host 0's
                          # view: per-process governor ledgers.
                          "tenant_spills",
                          # doctor wait ledgers are per-process blocked
                          # seconds: cluster view sums them (the
                          # straggler_waits DICT merges per-key below)
                          "collective_wait_s", "wait_net_s",
                          "wait_exchange_s", "wait_io_s",
                          "wait_skew_s"}
            stats = {
                k: (max(h[k] for h in per_host) if k in local_peaks
                    else sum(h.get(k, 0) for h in per_host)
                    if k in local_sums else per_host[0][k])
                for k in stats}
            stats["bytes_on_wire"] = (stats["bytes_wire_device"]
                                      + stats["bytes_wire_host"])
            stats["bytes_on_wire_raw"] = (
                stats["bytes_wire_device_raw"]
                + stats["bytes_wire_host"]
                + stats["bytes_wire_host_saved"])
            stats["wire_compress_ratio"] = _wire_ratio(
                stats["bytes_on_wire_raw"], stats["bytes_on_wire"])
            # global straggler blame: rank r's score is the sum over
            # EVERY rank of the seconds that rank spent waiting on r
            merged_waits: dict = {}
            for h in per_host:
                for p, w in (h.get("straggler_waits") or {}).items():
                    merged_waits[p] = merged_waits.get(p, 0.0) + w
            stats["straggler_waits"] = {
                p: round(w, 4) for p, w in sorted(merged_waits.items())}
            stats["hosts"] = len(per_host)
        return stats

    # -- generation-scoped failure domains ------------------------------

    @contextlib.contextmanager
    def pipeline(self, name: str = ""):
        """Scoped failure domain for one pipeline run.

        Any error escaping the block aborts ONLY this pipeline: the
        Context heals (stale in-flight frames drained by generation
        tag, the failed run's HBM reservations and cached-shard pins
        released, deferred checks cancelled, dropped TCP links
        reconnected, watchdog + heartbeat re-armed) and surfaces a
        catchable :class:`PipelineError` carrying the root cause and
        generation — the next pipeline on this same Context runs
        bit-identical to a fresh-Context run.

        Unrecoverable verdicts (heartbeat-confirmed dead peer, or a
        heal that itself fails) re-raise the ORIGINAL abort so the
        supervised relaunch + resume path still engages. Yields the
        generation id of this run.

        Entering the block starts a FRESH generation (a never-reused
        id off a monotonic counter), so nodes cached by earlier
        successful pipelines (or created between blocks) belong to
        other generations and survive this block's abort — only THIS
        run's nodes are disposed by the heal. A nested block's clean
        exit restores the ENCLOSING failure domain, so an outer abort
        heals the outer run's nodes, not the nested survivor's. In
        multi-controller runs every controller must enter/exit
        pipeline() at the same program points (the same lockstep
        contract every collective already has)."""
        parent = self.generation
        self._gen_counter += 1
        self.generation = self._gen_counter
        self.net.group.generation = self.generation
        gen = self.generation
        try:
            yield gen
            # a deferred check crossing the boundary belongs to THIS
            # pipeline: surface it here, inside the failure domain
            self.mesh_exec.drain_checks()
        except PipelineError:
            # a nested pipeline() already aborted, healed and wrapped
            # this failure — pass it through, never double-heal (a
            # second barrier would waste a collective round and the
            # re-wrap would misreport the failed generation). Node
            # stamping resumes in the enclosing domain.
            self.generation = parent
            raise
        except Exception as e:
            replacement = self._pipeline_failed(e, name)
            if replacement is e:
                raise
            # healed: execution resumes in the ENCLOSING domain — a
            # caller catching this PipelineError continues the outer
            # block with its own generation, so the outer run's nodes
            # (stamped before AND after this failed block) share one
            # id and a later outer abort heals all of them. The WIRE
            # epoch (group.generation) stays at the heal's advanced
            # value so the failed generation's frames read as stale.
            self.generation = parent
            raise replacement from e
        else:
            # clean exit: pop back to the enclosing failure domain
            # (frames tagged with this block's id stay >= the restored
            # group generation, so nothing of a LIVE outer run ever
            # reads as stale)
            self.generation = parent
            self.net.group.generation = parent

    def _pipeline_failed(self, exc: BaseException,
                         name: str = "") -> BaseException:
        """Abort bookkeeping + heal; returns the exception the caller
        should raise (a PipelineError after a successful heal, the
        original otherwise)."""
        from ..common import faults
        from ..net.group import ClusterAbort
        failed_gen = self.generation
        unrecoverable = (isinstance(exc, ClusterAbort)
                         and not getattr(exc, "recoverable", True))
        origin = int(getattr(exc, "origin", self.host_rank))
        cause = str(getattr(exc, "cause", "") or
                    f"{type(exc).__name__}: {exc}")
        self.stats_pipeline_aborts += 1
        if self.logger.enabled:
            self.logger.line(event="pipeline_abort", origin=origin,
                             generation=failed_gen,
                             pipeline=name or None,
                             recoverable=not unrecoverable,
                             cause=cause[:300])
        # flight recorder: every abort leaves a self-contained
        # post-mortem — the ring's final spans name the failing site
        # (error attrs) and the generation; the decision ledger lands
        # beside it (the chaos sweep archives both: what the planner
        # chose on the road to this abort). Best-effort by contract.
        try:
            self.decisions.dump_beside(
                self.tracer.dump_flight(cause, generation=failed_gen))
        except Exception:
            pass
        if (self.net.num_workers > 1
                and not isinstance(exc, ClusterAbort)):
            # a RANK-LOCAL failure (user logic, per-rank I/O): the
            # peers never saw it and would not enter their own heal —
            # the generation barrier would then wait on ranks that
            # never aborted. Poison them first so every controller
            # aborts this generation and meets us at the barrier.
            try:
                self.net.group.poison_peers(cause)
            except Exception:
                pass
        if not unrecoverable:
            try:
                self._heal(failed_gen)
            except Exception as he:
                unrecoverable = True
                faults.note("recovery", what="heal_failed",
                            gen=failed_gen, error=repr(he))
        if unrecoverable:
            self._aborted = True
            return exc
        return PipelineError(origin, cause, failed_gen, root=exc)

    def _heal(self, failed_gen: int) -> None:
        """Tear down generation ``failed_gen`` and make the Context as
        good as fresh: dispose the failed run's nodes (releasing the
        HbmGovernor ledger entries, cached-shard pins, spilled blocks
        and host-RAM grants), cancel its deferred checks and any live
        loop capture, then run the fresh-generation barrier over the
        host group (reconnecting dropped TCP links, draining stale
        in-flight frames by generation tag) and re-arm the heartbeat
        monitor. Raises when the mesh cannot be healed (dead peer,
        reconnect failure, barrier timeout)."""
        from .dia_base import DISPOSED
        t0 = time.monotonic()
        mex = self.mesh_exec
        # the healed domain gets a FRESH never-reused id: past
        # failed_gen (stale-frame ordering) AND past every id nested
        # blocks already consumed (never collide with a surviving
        # node's stamp)
        self._gen_counter = max(self._gen_counter, failed_gen) + 1
        self.generation = self._gen_counter
        checks_dropped = mex.reset_run_state()
        released = 0
        for node in self._nodes:
            if getattr(node, "_generation", 0) != failed_gen:
                continue
            self.release_mem(node)
            if node.state == DISPOSED:
                continue
            try:
                node.dispose()
                released += 1
            except Exception:
                pass           # best effort: the ledger entry is gone
        # the transport heal + barrier is the COLLECTIVE part: every
        # controller that aborted this generation enters it. A rank
        # that MISSED the cluster's abort adopts the newer generation
        # its peers' barrier markers announced — re-sync local ids to
        # whatever the barrier settled on.
        stale = self.net.group.begin_generation(self.generation)
        self.generation = max(self.generation,
                              self.net.group.generation)
        self._gen_counter = max(self._gen_counter, self.generation)
        # re-arm liveness probing if the monitor thread has exited
        # (it stops itself only on a dead-peer verdict, which is
        # unrecoverable — this covers monitors stopped by tests or a
        # future recoverable-stop path)
        hb = getattr(self.net.group, "_heartbeat", None)
        if hb is not None and (hb._thread is None
                               or not hb._thread.is_alive()):
            from ..net import heartbeat
            self.net.group._heartbeat = heartbeat.maybe_start(
                self.net.group)
        self._aborted = False
        dt = time.monotonic() - t0
        self.stats_heal_time_s += dt
        if self.logger.enabled:
            self.logger.line(event="heal", generation=self.generation,
                             heal_time_s=round(dt, 4),
                             nodes_released=released,
                             checks_dropped=checks_dropped,
                             stale_frames=stale)

    def abort(self, cause: Any) -> None:
        """Coordinated abort: broadcast ``cause`` as a poison control
        frame to every controller (each peer surfaces it as a
        ClusterAbort carrying this ROOT CAUSE within its own recv
        deadline — no cascade of secondary timeouts), then raise it
        locally. The ``event=abort`` line is emitted BEFORE the raise
        (with origin + generation), so single-rank aborts — where no
        poison frame is ever sent — are visible in json2profile
        exactly like poisoned ones."""
        from ..net.group import ClusterAbort
        self._aborted = True
        if self.logger.enabled:
            cause_s = (f"{type(cause).__name__}: {cause}"
                       if isinstance(cause, BaseException)
                       else str(cause))
            self.logger.line(event="abort", origin=self.host_rank,
                             generation=self.generation,
                             cause=cause_s[:300])
        try:
            self.decisions.dump_beside(self.tracer.dump_flight(
                cause, generation=self.generation))
        except Exception:
            pass
        if self.net.num_workers > 1:
            self.net.group.poison_peers(cause)
        if isinstance(cause, BaseException):
            raise cause
        raise ClusterAbort(self.host_rank, str(cause),
                           generation=self.generation)

    def collective_mean_stdev(self, value: float):
        """(mean, stdev) of a per-controller scalar across the cluster
        — a COLLECTIVE; every controller must call it (reference:
        PrintCollectiveMeanStdev, api/context.hpp:352-375)."""
        vals = [float(v) for v in self.net.all_gather(float(value))]
        mean = sum(vals) / len(vals)
        var = sum((v - mean) ** 2 for v in vals) / len(vals)
        return mean, var ** 0.5

    def print_collective_mean_stdev(self, label: str,
                                    value: float) -> None:
        """Rank-0 prints mean/stdev of a per-controller scalar."""
        mean, stdev = self.collective_mean_stdev(value)
        if self.host_rank == 0:
            print(f"{label}: mean {mean:.6g} stdev {stdev:.6g} over "
                  f"{self.net.num_workers} hosts", flush=True)

    def note_failure(self, exc: BaseException) -> None:
        """Called by the run wrappers with an exception PROPAGATING out
        of the job (not sniffed from sys.exc_info(), which would also
        see exceptions merely being handled further up the stack — a
        successful nested retry Run inside an ``except ClusterAbort``
        must not shut down as aborted). A framework-owned abort
        (poisoned group, hung collective) switches close() to the
        aborted shutdown: no collectives against dead peers, sweep the
        run's leaked artifacts. Deliberately narrow: a user job's own
        ConnectionError/TimeoutError must NOT skip the collective
        shutdown the other ranks are entering (the detectors —
        watchdog, heartbeat, poison frames — convert real worker loss
        into ClusterAbort)."""
        from ..net.group import ClusterAbort, CollectiveHangTimeout
        if isinstance(exc, (ClusterAbort, CollectiveHangTimeout)):
            self._aborted = True
            # an abort escaping the whole job (no ctx.pipeline() heal
            # caught it) still leaves its post-mortem
            try:
                self.decisions.dump_beside(self.tracer.dump_flight(
                    exc, generation=getattr(exc, "generation",
                                            self.generation)))
            except Exception:
                pass

    def close(self) -> None:
        from ..net.group import ClusterAbort
        # an abort DISCOVERED during close itself (heartbeat latch, or
        # a peer's poison frame surfacing in the stats collective) must
        # complete the cleanup AND still surface: a surviving rank
        # whose job body already finished would otherwise exit 0 and a
        # supervisor would relaunch only the dead rank — stranding it
        # in bootstrap against a rank that never comes back
        discovered: Optional[BaseException] = None
        # metrics endpoint first: no scrape may observe (or race) the
        # teardown below
        if getattr(self, "_metrics", None) is not None:
            self._metrics.close()
            self._metrics = None
        # service plane first: drain queued jobs and stop the
        # dispatcher BEFORE the stats collective (the dispatcher owns
        # the mesh while serving), then persist the learned plan state
        # (rank 0 writes; all ranks read — the state derives from
        # replicated plan inputs, so one copy is the cluster's copy)
        with self._service_lock:
            self._closed = True
        # autoscaler before everything in the service plane: no policy
        # decision may fire a resize into the teardown below
        if getattr(self, "autoscaler", None) is not None:
            try:
                self.autoscaler.stop()
            except Exception as e:
                from ..common import faults as _faults
                _faults.note("recovery", what="autoscale.stop_failed",
                             error=repr(e)[:200])
            self.autoscaler = None
        # front door before the scheduler: stop accepting sockets and
        # flush streamed results while the dispatcher can still run
        # the in-flight jobs those streams are waiting on
        if self.front_door is not None:
            try:
                self.front_door.close()
            except Exception as e:
                from ..common import faults as _faults
                _faults.note("recovery",
                             what="front_door.close_failed",
                             error=repr(e)[:200])
            self.front_door = None
        if self.service is not None:
            try:
                self.service.close()
            except Exception as e:
                from ..common import faults as _faults
                _faults.note("recovery", what="service.close_failed",
                             error=repr(e)[:200])
        # single-writer by construction: on multi-process meshes only
        # rank 0 holds a store handle (it loaded and broadcast the
        # entries at __init__), so this save needs no rank guard —
        # and rank 0's learned state derives from replicated plan
        # inputs, so its copy is the cluster's copy
        if self.plan_store is not None:
            try:
                self.plan_store.save(self.mesh_exec)
            except Exception as e:
                # a failing store must never take down a clean close
                from ..common import faults as _faults
                _faults.note("recovery", what="plan_store.save_failed",
                             error=repr(e)[:200])
            # the audited accuracy ledger persists NEXT TO the plan
            # state it judges: plans.json says what the model learned,
            # decisions.json says how right it was (best-effort too)
            try:
                if self.decisions.enabled \
                        and self.decisions.kind_counts:
                    self.plan_store.save_ledger(
                        self.decisions.summary())
            except Exception as e:
                from ..common import faults as _faults
                _faults.note("recovery",
                             what="decision_ledger.save_failed",
                             error=repr(e)[:200])
        # a dead-peer verdict latched by the background heartbeat
        # monitor (net/heartbeat.py mark_dead) may arrive with NO
        # exception in flight (the job finished between collectives):
        # entering the stats all_gather would raise it mid-close and
        # skip all cleanup — honor the latch up front instead
        pending = getattr(self.net.group, "_pending_abort", None)
        if pending is not None:
            if not self._aborted:
                discovered = pending
            self._aborted = True
        if self._profiler is not None:
            self._profiler.stop()
        # overall_stats() is a COLLECTIVE in multi-host runs: every host
        # must enter it regardless of its local logger setting, or
        # all_gather and barrier traffic would interleave across hosts
        # (after an abort it degrades to the local view — see the
        # _aborted guard inside). A PEER's abort can surface right
        # here (its poison frame arrives in our stats all_gather even
        # though our own job succeeded) — degrade to the local view
        # instead of letting the abort skip the rest of the cleanup.
        try:
            stats = self.overall_stats()
        except (ClusterAbort, ConnectionError, TimeoutError) as e:
            if not self._aborted:
                discovered = e
            self._aborted = True
            stats = self.overall_stats()      # local, collective-free
        if self.logger.enabled:
            self.logger.line(event="overall_stats", **stats)
        from ..common import faults
        if faults.REGISTRY._log == self.logger.line:
            faults.REGISTRY.set_logger(None)
        self.logger.close()
        self.hbm.close()
        if self._aborted:
            # leaked-artifact hygiene: uncommitted epoch of THIS run,
            # plus spill files whose owning process is gone (a
            # kill -9'd worker cannot clean up after itself)
            if self.checkpoint is not None:
                self.checkpoint.abort_cleanup()
            from ..data.block_pool import purge_stale_spills
            purge_stale_spills(self.config.spill_dir)
        if self.net.num_workers > 1:
            # an exiting-for-relaunch rank closes collective-free too:
            # after the marker barrier every rank exits at its own
            # pace (the supervisor is the next synchronization point)
            if not self._aborted and not self._resize_exiting:
                try:
                    self.net.barrier()
                except (ClusterAbort, ConnectionError,
                        TimeoutError) as e:
                    # a dying peer must not block shutdown, but the
                    # loss must still surface (see ``discovered``)
                    if discovered is None:
                        discovered = e
            self.net.group.close()
        if discovered is not None:
            # re-raise ONLY when no other exception is propagating
            # (close() runs in a finally: raising over an in-flight
            # error would mask the real root cause)
            import sys
            if sys.exc_info()[1] is None:
                raise discovered


# ----------------------------------------------------------------------
# runtime bootstrap
# ----------------------------------------------------------------------

def Run(job: Callable[[Context], Any], config: Optional[Config] = None,
        devices: Optional[Sequence[Any]] = None, seed: int = 0,
        resume: bool = False) -> Any:
    """Run a job on all (or the configured number of) local devices.

    ``resume=True`` (or ``THRILL_TPU_RESUME=1``) restores the newest
    complete checkpoint epoch from ``THRILL_TPU_CKPT_DIR`` and replays
    only post-checkpoint work (api/checkpoint.py)."""
    mex = MeshExec(devices=devices,
                   num_workers=(config or Config.from_env()).num_workers)
    ctx = Context(mex, config, seed, resume=resume)
    try:
        return job(ctx)
    except BaseException as e:
        ctx.note_failure(e)
        raise
    finally:
        ctx.close()


def RunSupervised(job: Callable[[Context], Any],
                  config: Optional[Config] = None,
                  devices: Optional[Sequence[Any]] = None, seed: int = 0,
                  max_restarts: int = 2) -> Any:
    """Run with supervised re-execution: an abort-class failure
    (ClusterAbort from a poisoned/hung group, transport loss, timeout)
    tears the run down and relaunches the SAME job with resume enabled,
    so a committed checkpoint epoch bounds the recomputation. The
    multi-process analog lives in run-scripts/supervise.sh (process
    relaunch); this is the in-process form for single-controller jobs
    and tests."""
    from ..common import faults
    from ..net.group import ClusterAbort
    attempt = 0
    while True:
        try:
            return Run(job, config, devices, seed,
                       resume=attempt > 0)
        except (ClusterAbort, ConnectionError, TimeoutError) as e:
            if attempt >= max_restarts:
                raise
            attempt += 1
            faults.note("recovery", what="supervised_restart",
                        attempt=attempt, error=repr(e))
            import sys
            print(f"thrill_tpu: supervised restart {attempt}/"
                  f"{max_restarts} after {e!r} (resume=True)",
                  file=sys.stderr)


def RunLocalMock(job: Callable[[Context], Any], workers: int,
                 config: Optional[Config] = None, seed: int = 0) -> Any:
    """Run on a fixed-size virtual CPU mesh (reference: RunLocalMock)."""
    cpus = jax.devices("cpu")
    if workers > len(cpus):
        raise ValueError(
            f"need {workers} CPU devices; set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={workers}")
    mex = MeshExec(devices=cpus[:workers])
    ctx = Context(mex, config, seed)
    try:
        return job(ctx)
    except BaseException as e:
        ctx.note_failure(e)
        raise
    finally:
        ctx.close()


def RunDistributed(job: Callable[[Context], Any],
                   coordinator_address: Optional[str] = None,
                   num_processes: Optional[int] = None,
                   process_id: Optional[int] = None,
                   config: Optional[Config] = None,
                   resume: bool = False) -> Any:
    """Multi-host entry point: the mesh spans every host's devices.

    The reference reaches multiple hosts through its tcp/mpi backends
    (api/context.cpp:496,651); here the data plane rides
    ``jax.distributed`` — XLA routes collectives over ICI within a
    slice and DCN across slices, and the jitted operator programs are
    unchanged. Each host runs this same function (standard JAX
    multi-controller SPMD). Sources that take global host data
    (Distribute) expect identical input on every host; per-host data
    should enter via ConcatToDIA of the local portion.

    Host fetches of device results are multi-controller safe: plan
    matrices and samples are replicated inside the jitted programs, and
    every remaining device->host read goes through ``MeshExec.fetch``,
    which process-allgathers arrays spanning non-addressable devices.
    Host-side scalar agreement between controllers rides ``ctx.net``
    (FlowControlChannel over the authenticated TCP group from
    THRILL_TPU_HOSTLIST/RANK/SECRET). Validated by the 2-process
    WordCount test (tests/net/test_distributed.py).
    """
    if num_processes is not None and num_processes > 1:
        # the coordinator handshake is a distress deadline like the
        # net bootstraps: on a contended host a peer controller can
        # take minutes of imports/compiles to reach it (see
        # common/timeouts.py)
        import inspect
        from ..common.platform import enable_cpu_multiprocess_collectives
        from ..common.timeouts import scaled
        # a CPU mesh spanning processes needs an explicit collectives
        # backend (gloo) or every cross-process program fails at runtime
        enable_cpu_multiprocess_collectives()
        kw = {}
        try:
            if "initialization_timeout" in inspect.signature(
                    jax.distributed.initialize).parameters:
                kw["initialization_timeout"] = int(scaled(300.0))
        except (TypeError, ValueError):
            pass            # builtins without introspectable signature
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id, **kw)
    mex = MeshExec(devices=jax.devices())
    ctx = Context(mex, config, host_rank=process_id or 0,
                  resume=resume)
    try:
        return job(ctx)
    except BaseException as e:
        ctx.note_failure(e)
        raise
    finally:
        ctx.close()


def RunLocalTests(job: Callable[[Context], Any],
                  worker_counts: Sequence[int] = (1, 2, 5, 8),
                  config: Optional[Config] = None) -> List[Any]:
    """Sweep the job over several virtual cluster sizes in-process.

    The single most valuable testing harness of the reference
    (api::RunLocalTests, thrill/api/context.cpp:336-341, sweeping mock
    clusters of {1,2,5,8} hosts x {1,3} workers).
    """
    cpus = jax.devices("cpu")
    max_w = int(os.environ.get("THRILL_TPU_MAX_MOCK_WORKERS", "64"))
    results = []
    for w in worker_counts:
        if w > len(cpus) or w > max_w:
            continue
        results.append(RunLocalMock(job, w, config))
    return results
