"""Checkpoint/resume: durable epoch snapshots of materialized DIAs.

The reference framework has NO fault tolerance — a lost worker kills
the whole SPMD job (reference: thrill/api/context.cpp:849-878 is
die-with-parent hygiene, nothing more). PR 1 made *transient* faults
survivable; this module makes **process loss** survivable, following
the RDD lineage+checkpoint model (Zaharia et al., NSDI'12): at stage
barriers (explicitly via ``dia.Checkpoint()``, or every barrier with
``THRILL_TPU_CKPT_AUTO=1``) a materialized DIA's per-worker shard state
is serialized through data/serializer.py and the vfs writers into an
epoch-stamped directory under ``THRILL_TPU_CKPT_DIR``::

    $THRILL_TPU_CKPT_DIR/
      epoch_000000/
        n<dia_id>.w<worker>.bin     per-worker shard payload
        MANIFEST.json               atomic commit record (tmp+rename)
      epoch_000001/ ...

An epoch is COMMITTED iff its manifest exists — the manifest is
written via ``vfs.write_file_atomic`` (write-temp + fsync + rename),
carries dtype/treedef/count metadata plus a CRC32 per shard file, and
is the unit of resume. A relaunched job (``Run(..., resume=True)`` or
``THRILL_TPU_RESUME=1``) loads the newest *complete* epoch, marks the
matching DIA node as already materialized (host Files rebuilt in
place, device shards re-uploaded through ``MeshExec``), and the pull
recursion then skips the node's entire upstream subgraph — only
post-checkpoint work replays, deterministically.

Node identity across runs is ``"<dia_id>:<label>"``: DIA ids are
assigned in construction order, so the same job code constructs the
same ids — the same determinism contract the fused plan cache and the
multi-controller SPMD model already rely on.

Multi-controller: every process writes shard files for its OWN workers
(the ckpt dir must be a shared filesystem across hosts), per-worker
CRCs are agreed over the host control plane, and rank 0 commits the
manifest after all hosts report their files written.

With ``THRILL_TPU_CKPT_DIR`` unset nothing here runs: ``Context``
leaves ``ctx.checkpoint`` as ``None`` and the stage driver's hooks are
a single attribute read (asserted by tests/api/test_checkpoint.py and
the dispatch-budget/fusion parity suites).
"""

from __future__ import annotations

import base64
import glob
import json
import os
import pickle
import shutil
import time
import zlib
from typing import Any, Dict, List, Optional

import numpy as np

from ..common import faults
from ..common.retry import default_policy
from ..data.serializer import deserialize_leaves, serialize_leaves
from ..data.shards import DeviceShards, HostShards
from ..vfs import file_io

MANIFEST = "MANIFEST.json"
_EPOCH_FMT = "epoch_{:06d}"
#: the commit record of an orchestrated process-level resize
#: (Context.resize_processes): written atomically AFTER the RESIZE
#: epoch seals and the net layer agreed to relaunch, consumed by the
#: supervisor (run-scripts/supervise.sh reads target_w) and cleared by
#: the relaunched run once it is actually running at the new W
RESIZE_MARKER = "RESIZE.json"

# checkpoint I/O is idempotent (files are rewritten whole, manifests
# commit atomically), so transient storage faults retry under the
# shared backoff policy before surfacing
_F_WRITE = faults.declare("ckpt.write")
_F_READ = faults.declare("ckpt.read")
_F_MANIFEST = faults.declare("ckpt.manifest")

# elastic re-partition (Context.resize): fired at STAGE time, before
# any shard or mesh state mutates — an injected failure aborts the
# resize with every old-W shard intact, so the generation heals and
# the next resize attempt runs from exactly the same state
_F_REPART = faults.declare("ckpt.repartition")

# process-level resize (Context.resize_processes): fired at RESIZE-
# epoch seal entry and again at marker commit, both BEFORE their
# writes — an injected failure leaves either nothing (seal) or a
# sealed-but-unannounced epoch an old-W resume rejects by the workers
# gate (marker), so the caller aborts with the old mesh fully intact
# and a clean retry runs the identical move
_F_RESIZE_MANIFEST = faults.declare("ckpt.resize_manifest")


def node_key(node) -> str:
    return f"{node.id}:{node.label}"


def resize_marker_path(directory: str) -> str:
    return os.path.join(directory, RESIZE_MARKER)


def pending_resize_target(directory: str) -> Optional[dict]:
    """The committed-but-unconsumed resize marker under ``directory``,
    or None. Module-level (no Context needed): the supervisor parses
    ``target_w`` from it before relaunching, and a relaunched child
    reads it to size its mesh before the Context even exists. A
    corrupt marker is LOUD and treated as absent — the relaunch then
    proceeds at the old W, whose epochs are still committed."""
    path = resize_marker_path(directory)
    try:
        if _is_remote(directory):
            with file_io.OpenReadStream(path) as f:
                raw = f.read()
        else:
            if not os.path.isfile(path):
                return None
            with open(path, "rb") as f:
                raw = f.read()
        m = json.loads(raw.decode())
        if int(m.get("target_w", 0)) < 1:
            raise ValueError(f"bad target_w {m.get('target_w')!r}")
        return m
    except FileNotFoundError:
        return None
    except (ValueError, KeyError, OSError) as e:
        import sys
        print(f"thrill_tpu.checkpoint: ignoring corrupt resize "
              f"marker {path}: {e}", file=sys.stderr)
        return None


def clear_resize_marker(directory: str) -> bool:
    """Consume the resize marker (the move completed: the relaunched
    run is up at the target W). Remote stores have no delete verb on
    the vfs seam — the relaunched run's workers gate makes a stale
    remote marker harmless, so this degrades to False."""
    path = resize_marker_path(directory)
    if _is_remote(directory):
        return False
    try:
        os.remove(path)
        return True
    except FileNotFoundError:
        return False
    except OSError:
        return False


def _epoch_num(path: str) -> Optional[int]:
    name = os.path.basename(path.rstrip("/"))
    if not name.startswith("epoch_"):
        return None
    try:
        return int(name[len("epoch_"):])
    except ValueError:
        return None


def _is_remote(path: str) -> bool:
    """Object-store (s3/hdfs/http) checkpoint directory: no mkdir, no
    rmtree, no posix stat — discovery goes through the vfs Glob and
    a missing manifest is detected by the read itself. Everything else
    (shard writes, manifest commit, restores) already rides the
    scheme-agnostic vfs seam."""
    return "://" in path and not path.startswith("file://")


class CheckpointManager:
    """Owned by :class:`api.context.Context`; saves materialized shard
    state at stage barriers and restores it on resume."""

    def __init__(self, ctx, directory: str, resume: bool = False,
                 auto: bool = False) -> None:
        self.ctx = ctx
        self.dir = directory
        self.auto = auto
        self.resume = resume
        # observability (surfaced by ctx.overall_stats())
        self.epochs_written = 0
        self.bytes_written = 0
        self.resume_skipped_ops = 0
        # EM-sort runs reloaded from the run store instead of re-formed
        # (core/em_runs.py bumps this on every successful try_load)
        self.resume_skipped_runs = 0
        self.restored_nodes = 0
        self.recovery_time_s = 0.0
        self.resume_epoch: Optional[int] = None
        self._inflight_dir: Optional[str] = None
        self._manifest: Optional[dict] = None
        if not _is_remote(self.dir):
            os.makedirs(self.dir, exist_ok=True)
        self._next_epoch = 1 + max(
            (e for e in (_epoch_num(p) for p in self._epoch_dirs())
             if e is not None), default=-1)
        if self._multihost():
            # controllers must agree on epoch numbering: a rank whose
            # directory scan raced another rank's incomplete-epoch
            # cleanup would otherwise write into a different epoch dir
            self._next_epoch = max(
                self.ctx.net.all_gather(self._next_epoch))
        if resume:
            if self._host_rank() == 0:
                self.cleanup_incomplete()
            self._manifest = self._load_newest_manifest()
            if self._multihost():
                # controllers must resume from ONE agreed epoch (or
                # none at all): a rank whose manifest scan raced, hit a
                # transient read error, or found nothing would
                # otherwise replay a different subgraph than its peers
                # — a silent deadlock or mixed-epoch corruption. Agree
                # on the MINIMUM visible epoch (every rank can load
                # it), -1 anywhere = nobody resumes; then agree that
                # every rank actually holds that manifest.
                mine = (int(self._manifest["epoch"])
                        if self._manifest is not None else -1)
                agreed = min(self.ctx.net.all_gather(mine))
                if agreed < 0:
                    self._manifest = None
                elif agreed != mine:
                    self._manifest = self._load_manifest_for(agreed)
                ok = self._manifest is not None
                if not all(self.ctx.net.all_gather(ok)):
                    self._manifest = None
            if self._manifest is not None:
                self.resume_epoch = int(self._manifest["epoch"])
                log = self.ctx.logger
                if log.enabled:
                    log.line(event="resume", epoch=self.resume_epoch,
                             node=self._manifest["node"]["key"])
            # consume a committed resize marker once the relaunch is
            # actually UP at the target W: from here the move is
            # complete and the supervisor must not relaunch again. A
            # marker for a DIFFERENT W stays (this run is not the
            # relaunch the move asked for — its epochs are still
            # gated per-W, so nothing wrong can restore).
            marker = pending_resize_target(self.dir)
            if marker is not None and self._host_rank() == 0 \
                    and int(marker["target_w"]) \
                    == self.ctx.mesh_exec.num_workers:
                clear_resize_marker(self.dir)
                faults.note("recovery", what="ckpt.resize_complete",
                            target_w=int(marker["target_w"]),
                            from_w=marker.get("from_w"),
                            epoch=marker.get("epoch"))

    # -- topology helpers ----------------------------------------------
    def _host_rank(self) -> int:
        return self.ctx.net.my_rank if self.ctx.net.num_workers > 1 else 0

    def _multihost(self) -> bool:
        return self.ctx.net.num_workers > 1

    def _local_workers(self) -> List[int]:
        mex = self.ctx.mesh_exec
        if getattr(mex, "num_processes", 1) > 1:
            return list(mex.local_workers)
        return list(range(mex.num_workers))

    def _epoch_dirs(self) -> List[str]:
        if _is_remote(self.dir):
            # object stores have no directories: list the epoch_*
            # object prefix and fold keys back into epoch "dirs"
            base = self.dir.rstrip("/")
            seen: Dict[str, None] = {}
            try:
                listing = file_io.Glob(base + "/epoch_*")
            except (OSError, NotImplementedError):
                return []
            for fi in listing:
                rest = fi.path[len(base) + 1:]
                if "/" in rest:
                    seen.setdefault(rest.split("/", 1)[0], None)
            return [f"{base}/{d}" for d in seen]
        return [p for p in glob.glob(os.path.join(self.dir, "epoch_*"))
                if os.path.isdir(p)]

    # ------------------------------------------------------------------
    # save
    # ------------------------------------------------------------------
    def maybe_autosave(self, node, shards) -> None:
        """Stage-barrier hook (``THRILL_TPU_CKPT_AUTO=1``): checkpoint
        every freshly materialized DOp result. Sources (no parents) and
        explicit Checkpoint nodes (they save themselves) are skipped."""
        if not self.auto or not node.parents:
            return
        if node.label.startswith("Checkpoint"):
            return
        if isinstance(shards, (DeviceShards, HostShards)):
            self.save(node, shards)

    def save(self, node, shards) -> int:
        """Write one epoch holding ``shards`` for ``node``; returns the
        epoch number. The epoch is durable once the manifest lands.

        Multihost: the whole body runs under the abort protocol
        (poison_on_error) — a rank whose shard write fails past the
        retry budget poisons its peers BEFORE they block in the
        file-table all_gather, so the group gets the root cause
        instead of stranding in a collective."""
        from ..net.group import poison_on_error
        grp = self.ctx.net.group if self._multihost() else None
        with poison_on_error(grp, "ckpt.save"):
            return self._save_guarded(node, shards)

    def _save_guarded(self, node, shards) -> int:
        t0 = time.perf_counter()
        epoch = self._next_epoch
        self._next_epoch += 1
        edir = os.path.join(self.dir, _EPOCH_FMT.format(epoch))
        if not _is_remote(self.dir):
            os.makedirs(edir, exist_ok=True)
        self._inflight_dir = edir
        if isinstance(shards, DeviceShards):
            rec, nbytes = self._save_device(node, shards, edir)
        elif isinstance(shards, HostShards):
            rec, nbytes = self._save_host(node, shards, edir)
        else:
            raise TypeError(f"cannot checkpoint {type(shards).__name__}")
        if self._multihost():
            # agree the full per-worker file table (names/CRCs/counts)
            # across controllers, then rank 0 commits for everyone
            tables = self.ctx.net.all_gather(
                (rec["files"], rec.get("counts"), nbytes))
            files: Dict[str, Any] = {}
            for tab, cnts, _ in tables:
                files.update(tab)
            rec["files"] = files
            if rec.get("counts") is None or rec["kind"] == "host":
                # host-storage counts are per-process partials: merge
                merged = [0] * self.ctx.mesh_exec.num_workers
                for tab, cnts, _ in tables:
                    for w, c in (cnts or {}).items():
                        merged[int(w)] = int(c)
                rec["counts"] = merged
        manifest = {"format": 1, "epoch": epoch,
                    "workers": self.ctx.mesh_exec.num_workers,
                    "node": rec}
        if self._host_rank() == 0:
            payload = json.dumps(manifest, sort_keys=True).encode()

            def commit():
                faults.check(_F_MANIFEST, epoch=epoch)
                file_io.write_file_atomic(
                    os.path.join(edir, MANIFEST), payload)

            default_policy().run(commit, what="ckpt.manifest")
        if self._multihost():
            # nobody proceeds past the barrier until the epoch is
            # committed — a straggler must not build on an epoch a
            # crashed rank 0 never sealed
            self.ctx.net.barrier()
        self._inflight_dir = None
        self.epochs_written += 1
        self.bytes_written += nbytes
        log = self.ctx.logger
        if log.enabled:
            log.line(event="checkpoint", epoch=epoch, node=node.label,
                     dia_id=node.id, bytes=nbytes,
                     seconds=round(time.perf_counter() - t0, 4))
        return epoch

    def _write_file(self, edir: str, name: str, payload: bytes) -> dict:
        path = os.path.join(edir, name)

        def write():
            faults.check(_F_WRITE, file=name)
            with file_io.OpenWriteStream(path) as f:
                f.write(payload)

        default_policy().run(write, what="ckpt.write")
        return {"name": name, "crc": zlib.crc32(payload),
                "bytes": len(payload)}

    def _save_device(self, node, shards: DeviceShards, edir: str):
        import jax
        # drains any deferred producer validation first (to_worker_
        # arrays calls validate_pending), so a hinted-join overflow can
        # never be sealed into an epoch
        per_worker = shards.to_worker_arrays(local_only=True)
        _, treedef = jax.tree.flatten(shards.tree)
        skeleton = jax.tree.unflatten(
            treedef, list(range(treedef.num_leaves)))
        files: Dict[str, Any] = {}
        nbytes = 0
        for w in self._local_workers():
            tree = per_worker[w]
            if tree is None:
                continue
            payload = serialize_leaves(
                [np.asarray(l) for l in jax.tree.leaves(tree)])
            files[str(w)] = self._write_file(
                edir, f"n{node.id}.w{w}.bin", payload)
            nbytes += len(payload)
        rec = {"key": node_key(node), "dia_id": node.id,
               "label": node.label, "kind": "device",
               "counts": [int(c) for c in shards.counts],
               "cap": int(shards.cap),
               "skeleton": base64.b64encode(
                   pickle.dumps(skeleton)).decode("ascii"),
               "files": files}
        return rec, nbytes

    def _save_host(self, node, shards: HostShards, edir: str):
        from ..data.serializer import serialize_batch
        files: Dict[str, Any] = {}
        counts: Dict[str, int] = {}
        nbytes = 0
        for w in self._local_workers():
            items = shards.lists[w]
            payload = serialize_batch(list(items))
            files[str(w)] = self._write_file(
                edir, f"n{node.id}.w{w}.bin", payload)
            counts[str(w)] = len(items)
            nbytes += len(payload)
        rec = {"key": node_key(node), "dia_id": node.id,
               "label": node.label, "kind": "host",
               "counts": counts, "files": files}
        return rec, nbytes

    # ------------------------------------------------------------------
    # orchestrated process-level resize (Context.resize_processes)
    # ------------------------------------------------------------------
    def seal_resize(self, node, shards, target_w: int) -> int:
        """Seal a RESIZE epoch: ``shards`` re-partitioned to
        ``target_w`` AT SEAL TIME and written as a ``target_w``-worker
        epoch. The relaunched W'-wide run then restores through the
        completely standard resume path — its workers gate
        (``_try_load_manifest``) matches, and the shard layout is the
        ``dense_range_bounds`` split a fixed-W' run of the same
        pipeline would have produced, so every post-resume result is
        bit-identical to a fixed-W' reference.

        Crash-safety: the ``ckpt.resize_manifest`` site fires at entry
        before any byte lands; an uncommitted epoch (SIGKILL mid-seal)
        is swept by ``cleanup_incomplete`` at the next resume; a
        COMMITTED W' epoch with no marker is rejected by an old-W
        resume's workers gate — in every case either the old state or
        the sealed move survives, never a mix."""
        from ..net.group import poison_on_error
        grp = self.ctx.net.group if self._multihost() else None
        with poison_on_error(grp, "ckpt.seal_resize"):
            return self._seal_resize_guarded(node, shards, target_w)

    def _seal_resize_guarded(self, node, shards, target_w: int) -> int:
        import jax
        from ..data.serializer import (deserialize_batch,
                                       serialize_batch)
        from ..data.shards import resplit_leaves
        t0 = time.perf_counter()
        target_w = int(target_w)
        old_w = self.ctx.mesh_exec.num_workers
        faults.check(_F_RESIZE_MANIFEST, stage="seal",
                     target=target_w, old=old_w)
        # gather the FULL per-worker view over the host control plane
        # (each process serializes only its local workers; rank 0 ends
        # up holding everything and writes every W' shard file — the
        # joiners of a grow do not exist yet, so nobody else can)
        if isinstance(shards, DeviceShards):
            per_worker = shards.to_worker_arrays(local_only=True)
            _, treedef = jax.tree.flatten(shards.tree)
            skeleton = jax.tree.unflatten(
                treedef, list(range(treedef.num_leaves)))
            local_tab = {
                w: serialize_leaves([np.asarray(l) for l in
                                     jax.tree.leaves(per_worker[w])])
                for w in self._local_workers()
                if per_worker[w] is not None}
            kind = "device"
        elif isinstance(shards, HostShards):
            skeleton = None
            local_tab = {w: serialize_batch(list(shards.lists[w]))
                         for w in self._local_workers()}
            kind = "host"
        else:
            raise TypeError(
                f"cannot seal {type(shards).__name__} for a resize")
        if self._multihost():
            full: Dict[int, bytes] = {}
            for tab in self.ctx.net.all_gather(local_tab):
                full.update({int(w): p for w, p in tab.items()})
        else:
            full = dict(local_tab)
        epoch = self._next_epoch
        self._next_epoch += 1
        edir = os.path.join(self.dir, _EPOCH_FMT.format(epoch))
        nbytes = 0
        if self._host_rank() == 0:
            if not _is_remote(self.dir):
                os.makedirs(edir, exist_ok=True)
            self._inflight_dir = edir
            if kind == "device":
                per_worker_leaves = [
                    deserialize_leaves(full[w]) for w in range(old_w)]
                new_leaves = resplit_leaves(per_worker_leaves,
                                            target_w)
                counts = [int(l[0].shape[0]) if l else 0
                          for l in new_leaves]
                payloads = [serialize_leaves(l) for l in new_leaves]
                rec: Dict[str, Any] = {
                    "key": node_key(node), "dia_id": node.id,
                    "label": node.label, "kind": "device",
                    "counts": counts, "cap": max([1] + counts),
                    "skeleton": base64.b64encode(
                        pickle.dumps(skeleton)).decode("ascii")}
            else:
                lists = [deserialize_batch(full[w])
                         for w in range(old_w)]
                new = HostShards(old_w, lists).repartition(target_w)
                counts = [len(l) for l in new.lists]
                payloads = [serialize_batch(l) for l in new.lists]
                rec = {"key": node_key(node), "dia_id": node.id,
                       "label": node.label, "kind": "host",
                       "counts": counts}
            files: Dict[str, Any] = {}
            for w in range(target_w):
                files[str(w)] = self._write_file(
                    edir, f"n{node.id}.w{w}.bin", payloads[w])
                nbytes += len(payloads[w])
            rec["files"] = files
            manifest = {"format": 1, "epoch": epoch,
                        "workers": target_w,
                        "resize": {"from": old_w, "to": target_w},
                        "node": rec}
            payload = json.dumps(manifest, sort_keys=True).encode()

            def commit():
                faults.check(_F_MANIFEST, epoch=epoch)
                file_io.write_file_atomic(
                    os.path.join(edir, MANIFEST), payload)

            default_policy().run(commit, what="ckpt.manifest")
            self._inflight_dir = None
        if self._multihost():
            self.ctx.net.barrier()
        self.epochs_written += 1
        self.bytes_written += nbytes
        log = self.ctx.logger
        if log.enabled:
            log.line(event="resize_seal", epoch=epoch,
                     node=node.label, dia_id=node.id,
                     workers_old=old_w, workers_new=target_w,
                     bytes=nbytes,
                     seconds=round(time.perf_counter() - t0, 4))
        return epoch

    def commit_resize_marker(self, target_w: int,
                             epoch: Optional[int] = None,
                             generation: Optional[int] = None,
                             procs: Optional[int] = None) -> str:
        """Commit the resize move: the marker's existence tells the
        supervisor (and any relaunch, however it died) that the move
        is ON and what W to relaunch at (``target_procs`` is the
        process count the supervisor's multi-worker mode spawns; the
        single-child mode re-sizes the one child's mesh to
        ``target_w`` instead). Atomic (tmp+rename); the fault site
        fires first, so an injected failure commits nothing and the
        caller aborts with the old W intact."""
        faults.check(_F_RESIZE_MANIFEST, stage="marker",
                     target=int(target_w))
        payload = json.dumps(
            {"format": 1, "target_w": int(target_w),
             "from_w": self.ctx.mesh_exec.num_workers,
             "target_procs": int(procs) if procs else 1,
             "epoch": epoch, "generation": generation},
            sort_keys=True).encode()
        path = resize_marker_path(self.dir)
        if self._host_rank() == 0:
            default_policy().run(
                lambda: file_io.write_file_atomic(path, payload),
                what="ckpt.resize_marker")
        if self._multihost():
            self.ctx.net.barrier()
        return path

    # ------------------------------------------------------------------
    # resume / restore
    # ------------------------------------------------------------------
    def _load_manifest_for(self, epoch: int) -> Optional[dict]:
        """Load one specific epoch's manifest (cross-rank agreement
        picked an epoch older than this rank's newest)."""
        edir = os.path.join(self.dir, _EPOCH_FMT.format(epoch))
        return self._try_load_manifest(edir)

    def _try_load_manifest(self, edir: str) -> Optional[dict]:
        mpath = os.path.join(edir, MANIFEST)
        if not _is_remote(self.dir) and not os.path.isfile(mpath):
            return None
        try:
            if _is_remote(self.dir):
                try:
                    with file_io.OpenReadStream(mpath) as f:
                        raw = f.read()
                except FileNotFoundError:
                    # no manifest object = uncommitted epoch, exactly
                    # the missing-file case the posix isfile probe hits
                    return None
            else:
                with open(mpath, "rb") as f:
                    raw = f.read()
            m = json.loads(raw.decode())
            if m.get("format") != 1:
                raise ValueError(f"unknown format {m.get('format')}")
            if m.get("workers") != self.ctx.mesh_exec.num_workers:
                raise ValueError(
                    f"epoch was written by a {m.get('workers')}-worker "
                    f"mesh; this run has "
                    f"{self.ctx.mesh_exec.num_workers}")
            m["_dir"] = edir
            return m
        except (ValueError, KeyError, OSError) as e:
            import sys
            print(f"thrill_tpu.checkpoint: skipping epoch "
                  f"{os.path.basename(edir)}: {e}", file=sys.stderr)
            return None

    def _load_newest_manifest(self) -> Optional[dict]:
        # foreign/renamed epoch_* dirs (non-numeric suffix) are not
        # resumable epochs — skip them instead of crashing the scan
        dirs = sorted((p for p in self._epoch_dirs()
                       if _epoch_num(p) is not None),
                      key=_epoch_num, reverse=True)
        for edir in dirs:
            m = self._try_load_manifest(edir)
            if m is not None:
                return m
        return None

    def restorable(self, node) -> bool:
        """Does the resume manifest hold this node's state? (Cheap:
        one dict probe; used by the stage driver to route a fused pull
        into the restore path instead of re-deferring upstream.)"""
        m = self._manifest
        return (m is not None and node._shards is None
                and m["node"]["key"] == node_key(node))

    def try_restore(self, node):
        """Rebuild the node's shards from the resume epoch, or None.

        A corrupt epoch (CRC mismatch, missing file) logs loudly and
        returns None — recomputing from lineage is always correct,
        dying on a half-written checkpoint never is."""
        if not self.restorable(node):
            return None
        m = self._manifest
        res = self._restore_agreed(node.label, "recomputing from "
                                               "lineage")
        if res is None:
            self._manifest = None        # every rank recomputes
            return None
        shards, dt = res
        skipped = _count_upstream_new(node)
        self.resume_skipped_ops += skipped
        # one restore per manifest: downstream re-executions of the
        # same key (a later Checkpoint call reusing the id after a
        # Dispose) must recompute, not replay a stale epoch
        self._manifest = None
        faults.note("recovery", what="ckpt.restore", node=node.label,
                    epoch=m["epoch"], skipped_ops=skipped,
                    seconds=round(dt, 4))
        return shards

    # ------------------------------------------------------------------
    # loop-carry epochs (api/loop.py Iterate(..., checkpoint_every=k))
    # ------------------------------------------------------------------
    def save_loop_state(self, name: str, iteration: int, shards) -> int:
        """Seal a loop-carried state into a durable epoch. The label
        encodes (loop name, iteration) so a resumed run can re-enter
        the loop mid-flight without rebuilding the body graph."""
        import types
        shim = types.SimpleNamespace(
            id=0, label=f"LoopState[{name}@{iteration}]", parents=())
        return self.save(shim, shards)

    def try_restore_loop(self, name: str):
        """(shards, iteration) from the resume manifest when it holds a
        loop epoch for ``name``, else None. Same all-or-nothing
        multihost agreement and corrupt-epoch degradation as
        :meth:`try_restore`."""
        m = self._manifest
        if m is None:
            return None
        rec = m["node"]
        label = rec["key"].split(":", 1)[1]
        prefix = f"LoopState[{name}@"
        if not label.startswith(prefix) or not label.endswith("]"):
            return None
        try:
            iteration = int(label[len(prefix):-1])
        except ValueError:
            return None
        res = self._restore_agreed(label, "re-running the loop from "
                                          "its start")
        self._manifest = None
        if res is None:
            return None
        shards, dt = res
        faults.note("recovery", what="ckpt.restore", node=label,
                    epoch=m["epoch"], loop=name, iteration=iteration,
                    seconds=round(dt, 4))
        return shards, iteration

    def _restore_agreed(self, label: str, fallback: str):
        """The shared restore core of :meth:`try_restore` /
        :meth:`try_restore_loop`: rebuild the manifest node's shards
        (corrupt epoch -> loud stderr + recovery note + None) and run
        the all-or-nothing cross-rank agreement. Restore is
        all-or-nothing ACROSS RANKS: one rank falling back to
        recompute while the others restore would re-enter upstream
        exchange collectives alone (deadlock) or finish on mixed-epoch
        data (wrong results). The agreement runs in lockstep:
        restorable() is deterministic after the startup epoch
        agreement, so every controller reaches this all_gather for the
        same node. Returns (shards, seconds) or None; the caller owns
        clearing ``_manifest``."""
        m = self._manifest
        rec = m["node"]
        t0 = time.perf_counter()
        try:
            if rec["kind"] == "device":
                shards = self._restore_device(rec, m["_dir"])
            else:
                shards = self._restore_host(rec, m["_dir"])
        except Exception as e:
            import sys
            print(f"thrill_tpu.checkpoint: restore of {rec['key']} "
                  f"from epoch {m['epoch']} failed ({e!r}); {fallback}",
                  file=sys.stderr)
            faults.note("recovery", what="ckpt.restore_failed",
                        node=label, epoch=m["epoch"], error=repr(e))
            shards = None
        if self._multihost():
            oks = self.ctx.net.all_gather(shards is not None)
            if not all(oks) and shards is not None:
                faults.note("recovery", what="ckpt.restore_abandoned",
                            node=label, epoch=m["epoch"],
                            peers_failed=oks.count(False))
                shards = None
        if shards is None:
            return None
        dt = time.perf_counter() - t0
        self.restored_nodes += 1
        self.recovery_time_s += dt
        return shards, dt

    def _read_file(self, edir: str, finfo: dict) -> bytes:
        path = os.path.join(edir, finfo["name"])

        def read():
            faults.check(_F_READ, file=finfo["name"])
            with file_io.OpenReadStream(
                    path, tracer=getattr(self.ctx.mesh_exec, "tracer",
                                         None)) as f:
                return f.read()

        data = default_policy().run(read, what="ckpt.read")
        if zlib.crc32(data) != finfo["crc"]:
            raise IOError(f"CRC mismatch in {finfo['name']}")
        return data

    def _overlapped_reads(self, edir: str, rec: dict, workers):
        """Yield ``(worker, shard file bytes)`` with the NEXT worker's
        file read already in flight behind the current worker's
        decode+upload — the checkpoint-restore face of the out-of-core
        overlap tier. Each read is the full retry+CRC path
        (:meth:`_read_file`, itself streaming through the prefetching
        vfs reader); a background failure degrades to the demand read
        on this thread, so corruption/fault semantics are unchanged.
        ``THRILL_TPU_PREFETCH=0`` restores strictly sequential reads."""
        from ..data.writeback import make_readahead, overlapped_fetch
        from ..vfs.file_io import prefetch_depth
        from ..common.decisions import record_of, resolve_io_prefetch
        from ..common.iostats import IO as _IOSTATS
        from .planner import planner_of
        workers = list(workers)
        mex = self.ctx.mesh_exec
        ra = None
        drec = None
        st: dict = {}
        io0 = _IOSTATS.snapshot()
        if len(workers) > 1:
            # planner consult + decision record only when a readahead
            # pool actually runs — a 1-file restore must not consume a
            # replan mark or ledger a re-optimization it never
            # exercised
            depth = prefetch_depth()
            pl = planner_of(mex)
            if pl is not None:
                # per-site learned depth (seeded from this site's
                # audited hit rate, not just the one env default)
                depth = pl.io_prefetch_depth("ckpt.restore", depth)
            ra = make_readahead(depth)
            if ra is not None:
                drec = record_of(
                    mex, "io_prefetch", "ckpt.restore",
                    f"depth={depth}", predicted=1.0,
                    reason="overlap next shard's read with the "
                           "current decode+upload",
                    files=len(workers), depth=depth)
        try:
            yield from overlapped_fetch(
                workers,
                lambda w: self._read_file(edir, rec["files"][str(w)]),
                "ckpt.restore", ra, stats=st)
            if st.get("prefetched"):
                _IOSTATS.add(restore_overlaps=1)
                log = self.ctx.logger
                if log.enabled:
                    log.line(event="restore_overlap", kind="ckpt",
                             files=len(workers),
                             prefetched=st["prefetched"])
        finally:
            if ra is not None:
                ra.shutdown(wait=True, cancel_futures=True)
            resolve_io_prefetch(
                mex, drec, _IOSTATS.delta(_IOSTATS.snapshot(), io0))

    def _restore_device(self, rec: dict, edir: str) -> DeviceShards:
        import jax
        mex = self.ctx.mesh_exec
        W = mex.num_workers
        counts = np.asarray([int(c) for c in rec["counts"]],
                            dtype=np.int64)
        cap = int(rec["cap"])
        skeleton = pickle.loads(base64.b64decode(rec["skeleton"]))
        treedef = jax.tree.structure(skeleton)
        local = self._local_workers()
        per_worker_leaves: Dict[int, List[np.ndarray]] = {}
        for w, data in self._overlapped_reads(edir, rec, local):
            leaves = deserialize_leaves(data)
            if len(leaves) != treedef.num_leaves:
                raise IOError(
                    f"worker {w}: {len(leaves)} leaves, treedef wants "
                    f"{treedef.num_leaves}")
            if leaves and leaves[0].shape[0] != counts[w]:
                raise IOError(
                    f"worker {w}: {leaves[0].shape[0]} rows, manifest "
                    f"says {counts[w]}")
            per_worker_leaves[w] = leaves
        out_leaves = []
        for i in range(treedef.num_leaves):
            singles = []
            tail = per_worker_leaves[local[0]][i].shape[1:]
            dtype = per_worker_leaves[local[0]][i].dtype
            for w in local:
                arr = per_worker_leaves[w][i]
                if arr.dtype != dtype or arr.shape[1:] != tail:
                    raise IOError(
                        f"worker {w} leaf {i}: {arr.dtype}{arr.shape} "
                        f"does not match worker {local[0]}'s "
                        f"{dtype}(*, {tail}) — corrupt epoch")
                pad = [(0, cap - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
                padded = np.pad(arr, pad)[None]        # [1, cap, ...]
                singles.append(jax.device_put(padded, mex.devices[w]))
            out_leaves.append(jax.make_array_from_single_device_arrays(
                (W, cap) + tail, mex.sharded, singles))
        tree = jax.tree.unflatten(treedef, out_leaves)
        shards = DeviceShards(mex, tree, counts)
        log = self.ctx.logger
        if log.enabled:
            log.line(event="ckpt_restore", kind="device",
                     epoch=self._manifest["epoch"],
                     items=int(counts.sum()))
        return shards

    def _restore_host(self, rec: dict, edir: str) -> HostShards:
        from ..data.serializer import deserialize_batch
        mex = self.ctx.mesh_exec
        W = mex.num_workers
        lists: List[List[Any]] = [[] for _ in range(W)]
        local = self._local_workers()
        for w in local:
            if rec["files"].get(str(w)) is None:
                raise IOError(f"worker {w}: shard file missing from "
                              f"manifest")
        for w, data in self._overlapped_reads(edir, rec, local):
            lists[w] = deserialize_batch(data)
            want = int(rec["counts"].get(str(w), len(lists[w]))) \
                if isinstance(rec["counts"], dict) \
                else int(rec["counts"][w])
            if len(lists[w]) != want:
                raise IOError(f"worker {w}: {len(lists[w])} items, "
                              f"manifest says {want}")
        shards = HostShards(W, lists)
        log = self.ctx.logger
        if log.enabled:
            log.line(event="ckpt_restore", kind="host",
                     epoch=self._manifest["epoch"], items=shards.total)
        return shards

    # ------------------------------------------------------------------
    # hygiene
    # ------------------------------------------------------------------
    def cleanup_incomplete(self) -> int:
        """Remove epoch directories without a committed manifest (a
        crashed run's half-written epoch). Safe only when no live
        writer shares the directory: called at resume startup (the
        previous run is dead by definition) and from the abort path
        (only this run's own in-flight epoch is fresh)."""
        removed = 0
        if _is_remote(self.dir):
            # no delete verb on the vfs seam — harmless: an epoch
            # without a manifest is invisible to resume discovery
            return 0
        for edir in self._epoch_dirs():
            if os.path.isfile(os.path.join(edir, MANIFEST)):
                continue
            try:
                shutil.rmtree(edir)
                removed += 1
            except OSError:
                pass
        if removed:
            faults.note("recovery", what="ckpt.cleanup_incomplete",
                        removed=removed)
        return removed

    def abort_cleanup(self) -> None:
        """Drop this run's uncommitted in-flight epoch (if any)."""
        edir, self._inflight_dir = self._inflight_dir, None
        if edir and _is_remote(self.dir):
            return
        if edir and not os.path.isfile(os.path.join(edir, MANIFEST)):
            try:
                shutil.rmtree(edir)
            except OSError:
                pass

    def stats(self) -> dict:
        return {"checkpoint_epochs": self.epochs_written,
                "ckpt_bytes_written": self.bytes_written,
                "resume_skipped_ops": self.resume_skipped_ops,
                "resume_skipped_runs": self.resume_skipped_runs,
                "recovery_time_s": round(self.recovery_time_s, 4)}


# ----------------------------------------------------------------------
# elastic re-partition (api/context.py Context.resize)
# ----------------------------------------------------------------------
#
# Live shards move across a W change in two phases so a mid-resize
# failure can never strand half-moved data:
#
# * stage_repartition — runs BEFORE anything mutates: every live
#   shard's valid rows serialize through the checkpoint serializer
#   (the same columnar records an epoch file holds, data/serializer.py)
#   into an in-memory staging blob. Any failure here (including the
#   injected ``ckpt.repartition`` site) aborts the resize with the old
#   mesh, membership and shards untouched.
# * commit_repartition — runs AFTER ``MeshExec.resize``: the staged
#   records deserialize behind the PR-13/15 prefetching reader
#   (writeback.overlapped_fetch — the next worker's decode is in
#   flight behind the current upload), re-split across
#   ``dense_range_bounds(total, W')`` and upload to the new mesh.
#   The split is exactly the layout a fresh W'-wide run would build,
#   which is what keeps post-resize results bit-identical to a
#   fixed-W' run.


def stage_repartition(shards) -> dict:
    """Serialize one live shard store for a W change; returns the
    staging blob ``commit_repartition`` consumes. Pure read: the
    shards stay valid and untouched."""
    import jax as _jax
    faults.check(_F_REPART, kind=type(shards).__name__,
                 workers=shards.num_workers)
    if isinstance(shards, DeviceShards):
        per_worker = shards.to_worker_arrays()
        _, treedef = _jax.tree.flatten(shards.tree)
        skeleton = _jax.tree.unflatten(
            treedef, list(range(treedef.num_leaves)))
        payloads = [serialize_leaves(
            [np.asarray(l) for l in _jax.tree.leaves(t)])
            for t in per_worker]
        return {"kind": "device", "skeleton": skeleton,
                "payloads": payloads}
    if isinstance(shards, HostShards):
        from ..data.serializer import serialize_batch
        return {"kind": "host",
                "payloads": [serialize_batch(list(items))
                             for items in shards.lists]}
    raise TypeError(f"cannot repartition {type(shards).__name__}")


def _overlapped_staged(mex, payloads):
    """Yield ``(worker, payload)`` with the next worker's record fetch
    in flight behind the current decode — the same planner-consulted
    readahead the checkpoint restore path runs, at its own
    ``ckpt.repartition`` site."""
    from ..data.writeback import make_readahead, overlapped_fetch
    from ..vfs.file_io import prefetch_depth
    from .planner import planner_of
    workers = list(range(len(payloads)))
    ra = None
    if len(workers) > 1:
        depth = prefetch_depth()
        pl = planner_of(mex)
        if pl is not None:
            depth = pl.io_prefetch_depth("ckpt.repartition", depth)
        ra = make_readahead(depth)
    try:
        yield from overlapped_fetch(
            workers, lambda w: payloads[w], "ckpt.repartition", ra)
    finally:
        if ra is not None:
            ra.shutdown(wait=True, cancel_futures=True)


def commit_repartition(mex, staged: dict):
    """Rebuild one staged shard store against the RESIZED mesh (device
    kind) or the new worker count (host kind)."""
    import jax as _jax
    if staged["kind"] == "host":
        from ..data.serializer import deserialize_batch
        lists: List[List[Any]] = []
        for _, payload in _overlapped_staged(mex, staged["payloads"]):
            lists.append(deserialize_batch(payload))
        return HostShards(len(lists), lists).repartition(
            mex.num_workers)
    from ..data.shards import resplit_leaves
    treedef = _jax.tree.structure(staged["skeleton"])
    per_worker_leaves: List[List[np.ndarray]] = [
        deserialize_leaves(payload)
        for _, payload in _overlapped_staged(mex, staged["payloads"])]
    new_leaves = resplit_leaves(per_worker_leaves, mex.num_workers)
    per_worker = [_jax.tree.unflatten(treedef, leaves)
                  for leaves in new_leaves]
    return DeviceShards.from_worker_arrays(mex, per_worker)


def _count_upstream_new(node) -> int:
    """How many transitive ancestors the restore just short-circuited
    (they stay NEW: the pull recursion never reaches them)."""
    seen = set()
    stack = [p.node for p in node.parents]
    n = 0
    while stack:
        x = stack.pop()
        if x.id in seen:
            continue
        seen.add(x.id)
        if x.state == "NEW":
            n += 1
            stack.extend(p.node for p in x.parents)
    return n


# ----------------------------------------------------------------------
# the explicit barrier node (dia.Checkpoint())
# ----------------------------------------------------------------------

def make_checkpoint_node(dia, name: Optional[str] = None):
    from .dia import DIA
    from .dia_base import DIABase

    class CheckpointNode(DIABase):
        """Materializes its parent and seals the result into an epoch.
        A fusion/stage barrier by construction (no compute_plan): a
        downstream fused chain starts from the checkpointed shards."""

        def compute(self):
            shards = self.parents[0].pull()
            mgr = getattr(self.context, "checkpoint", None)
            if mgr is not None:
                mgr.save(self, shards)
            return shards

    label = "Checkpoint" if name is None else f"Checkpoint[{name}]"
    node = CheckpointNode(dia.context, label, [dia._link()])
    return DIA(node)
