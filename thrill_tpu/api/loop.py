"""Device-resident iteration: LoopPlan capture, replay and donation.

Thrill's iterative examples drive a Collapse'd loop DIA per iteration
(reference: examples/page_rank/page_rank.hpp:71-131) — and so did this
port: every iteration re-built the Python DIA graph, re-ran the pull
recursion, re-planned fusion and re-entered the dispatch path. For a
body whose compiled programs are cheap (the dense-gather join + the
scatter ReduceToIndex engine), that host-side work IS the iteration
cost. This module is the Pathways move for data-flow loops
(arXiv:2203.12533): run the body ONCE through the existing pull
recursion + fusion planner, record the resulting sequence of compiled
dispatches as a :class:`LoopPlan` tape, and replay the tape for the
remaining iterations with the loop-carried buffers threaded through —
zero graph construction, zero re-planning, zero host round trips for
iterations 2..N.

How the tape stays correct:

* Recording happens at the ONE choke point every device program passes
  through (``parallel.mesh._CountedJit.__call__``). Each recorded call
  classifies its arguments: a loop-carry leaf, the output of an
  earlier recorded call, or a CONSTANT (anything else — materialized
  upstream shards, ``put_small``-cached plan arrays, Bind operands).
  Classification is by buffer identity, so the capture first copies
  every carry leaf into a fresh buffer: an initial carry that aliases
  a closure constant of the body (or another carry slot) must not get
  the constant misclassified as loop-varying.
* Dataflow pruning: calls whose outputs never reach the loop carry are
  dropped; calls that are needed but do NOT depend on the carry are
  iteration-invariant — their captured outputs become constants and
  the calls are never re-run (this is what makes in-body pulls of
  Keep'd upstream tables free on replay).
* A carry-out leaf that is neither a recorded output nor a carry
  passthrough means the body computed state OUTSIDE the recorded
  dispatch stream (eager host math) — the capture is rejected loudly
  and the loop falls back to plain per-iteration execution.
* The tape assumes per-iteration plan values (exchange send matrices,
  ZipWithIndex offsets, join capacities) are ITERATION-INVARIANT —
  true for the fixed-shape loops this layer targets (PageRank,
  k-means, SGD) where every such value derives from counts that do not
  change across iterations. Invariance of a fetched plan value is
  verified per output LEAF: when host plan logic reads an output of a
  carry-dependent dispatch, the call's jaxpr input→output reachability
  (:class:`_LeafTaint`) decides whether THAT output depends on the
  carry — a constant-topology W>1 shuffle's send matrix (fixed key
  column riding next to the changing ranks) captures, a genuinely
  data-dependent plan still rejects, and every analysis gap falls back
  to the conservative per-call verdict. ``THRILL_TPU_LOOP_REPLAY=0``
  restores the exact per-iteration planning behavior.
* KNOWN BLIND SPOT — carry-dependent Python control flow: a body that
  branches on a scalar it computes with EAGER jnp math and converts
  directly (``if float(jnp.sum(x)) < eps``, ``bool()``, ``.item()``,
  ``np.asarray()`` on an eager result) freezes the iteration-1 branch
  into the tape. The eager value never feeds a recorded dispatch (so
  the constant-provenance guard never sees it) and bypasses
  ``mex.fetch`` (so the fetch taint never fires) — scalar conversion
  on a raw ``jax.Array`` is the one host read this layer cannot
  intercept. Convergence checks belong OUTSIDE ``Iterate`` (run a
  fixed block of iterations, test, repeat — the recipe in
  examples/k_means.py), or read loop data through DIA actions /
  ``mex.fetch``, both of which reject the capture loudly.

Buffer donation: on replayed dispatches the previous iteration's
carry and intermediates are owned by the loop, so their HBM is donated
back to XLA (``donate_argnums`` twins of the compiled programs) instead
of copied — disabled automatically on backends without donation
support (XLA:CPU no-ops with a warning), while fault injection is
armed (a retried dispatch must not have consumed its inputs), for the
first replay (whose carry the capture graph still references), and for
a carry that was just sealed into a checkpoint epoch.

Whole-loop lowering: a body that collapses to ONE fused dispatch — no
exchange, no host fallback, every argument a carry leaf or a constant
— is lowered into a single ``jax.jit(lax.fori_loop)`` program over the
remaining iterations: one dispatch for the whole loop.

Failure semantics: every replayed iteration passes the
``api.loop.replay`` fault site; an injected or real dispatch failure
logs ``event=loop_replay_fallback``, counts in
``ctx.overall_stats()['loop_replay_fallbacks']`` and degrades to full
re-planning (the body runs again through the pull recursion, which
re-captures), so a broken tape can slow the loop down but never
corrupt it. ``Iterate(..., checkpoint_every=k)`` seals the carry into
a durable epoch every k iterations via api/checkpoint.py; a resumed
run restores the newest loop epoch and continues from the next
iteration.
"""

from __future__ import annotations

import dataclasses
import os
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..common import faults
from ..data.shards import DeviceShards, HostShards
from .dia import DIA
from .dia_base import DIABase

_F_REPLAY = faults.declare("api.loop.replay")


# ----------------------------------------------------------------------
# plan-state persistence: loop-capture tape metadata
# ----------------------------------------------------------------------
# The capture iteration's expensive parts are the ANALYSIS — the
# per-output-leaf taint verification re-traces call programs as jaxprs
# — and, for loops that can never capture, the futile capture attempts
# themselves (a full carry copy plus a recorder pass each, twice,
# before the miss streak gives up). Both outcomes are pure functions
# of the tape: which compiled programs ran (their MeshExec cache keys)
# and how their arguments/outputs were wired. Persisting that
# metadata in the plan store lets a warm restart skip the work:
#
# * a loop whose tape previously analyzed clean re-validates by digest
#   (same program keys, same wiring, same fetched plan reads) and
#   skips the taint re-traces — the tape is trusted because the
#   analysis inputs are provably identical;
# * a loop that previously REJECTED capture runs plain from iteration
#   1, skipping the capture probes entirely.
#
# Stale metadata degrades LOUDLY: a digest mismatch logs
# ``event=loop_seed_stale`` and runs the full fresh analysis — the
# seed can cost nothing but the log line. Correctness-neutral like
# every plan-store value: a trusted tape still re-records THIS run's
# calls; only the verification that the recorded wiring is replayable
# is reused, never the wiring itself.


def export_plan_state(mex) -> dict:
    """Per-loop tape metadata (plan keys + wiring + donation twins) as
    digest maps — the plan store's on-disk form (service/plan_store.py
    ``loop_tape`` kind)."""
    from ..data.exchange import _ident_digest, merge_unconsumed_seeds
    return merge_unconsumed_seeds(mex, {
        "loop_tape": {_ident_digest(k): v for k, v in
                      getattr(mex, "_loop_tapes", {}).items()},
    })


def import_plan_state(mex, state: dict, *,
                      symmetric: bool = False) -> int:
    from ..data.exchange import install_plan_seeds
    return install_plan_seeds(mex, state, ("loop_tape",),
                              symmetric=symmetric)


def _note_tape(mex, token, meta: Optional[dict]) -> None:
    """Remember this loop's capture outcome for export."""
    if meta is None:
        return
    tapes = getattr(mex, "_loop_tapes", None)
    if tapes is None:
        tapes = mex._loop_tapes = {}
    tapes[token] = meta


def replay_enabled() -> bool:
    """THRILL_TPU_LOOP_REPLAY=0 restores plain per-iteration planning."""
    return os.environ.get("THRILL_TPU_LOOP_REPLAY", "1") not in (
        "0", "off", "false")


def donation_enabled() -> bool:
    """THRILL_TPU_LOOP_DONATE overrides; default: on where XLA supports
    input-output aliasing (donation on XLA:CPU is a no-op + warning)."""
    v = os.environ.get("THRILL_TPU_LOOP_DONATE")
    if v is not None:
        return v not in ("0", "off", "false")
    return jax.default_backend() != "cpu"


def fori_enabled() -> bool:
    """THRILL_TPU_LOOP_FORI=0 keeps replay per-iteration (tape calls
    dispatched one by one) instead of lowering the remaining
    iterations into one whole-loop ``lax.fori_loop`` program."""
    return os.environ.get("THRILL_TPU_LOOP_FORI", "1") not in (
        "0", "off", "false")


# ----------------------------------------------------------------------
# tape capture
# ----------------------------------------------------------------------

@dataclasses.dataclass
class _Call:
    """One recorded dispatch: the counted-jit callable plus classified
    argument references.  ``arg_refs``: ("carry", slot) | ("val",
    (call_idx, out_idx)) | ("const", buffer) | ("tree", treedef,
    [leaf refs]) for pytree arguments that MIX loop-owned leaves with
    constants (a jit_cached body called on the carry dict).  Filled
    during analysis: ``donate_pos`` — argument positions whose buffers
    are loop-owned and dead after this call.  ``leaf_kinds`` (flatten
    order across all arguments = jaxpr invar order) and ``avals``
    support the per-output-LEAF taint refinement: a fetched output
    that provably depends only on constant/invariant input leaves
    does not poison the tape even when ANOTHER output of the same
    call is carry-dependent."""
    fn: Any
    arg_refs: List[Tuple]
    out_buffers: List[Any]
    donate_pos: Tuple[int, ...] = ()
    leaf_kinds: Optional[List[Tuple]] = None
    avals: Optional[Tuple] = None


def _leaf_refs(refs):
    """Iterate the leaf-level refs of an arg_refs list (trees
    flattened)."""
    for ref in refs:
        if ref[0] == "tree":
            for s in ref[2]:
                yield s
        else:
            yield ref


class _Recorder:
    """Installed as ``mex.loop_recorder`` around the capture iteration's
    body run; sees every ``_CountedJit`` dispatch."""

    def __init__(self, carry_ids: Dict[int, int],
                 known: Optional[list] = None) -> None:
        self.carry_ids = carry_ids
        self.calls: List[_Call] = []
        self.produced: Dict[int, Tuple[int, int]] = {}
        self.plan_reads: set = set()   # (call, out) leaves fetched to host
        self.dispatch_s = 0.0            # issue time inside dispatches
        self.dirty: Optional[str] = None
        # constant provenance: device arrays live BEFORE the capture
        # iteration (upstream tables, plan caches, Bind operands) and
        # host uploads made during it (mesh.put blesses) are legitimate
        # tape constants; any OTHER array created during the body is
        # eager device math whose value could depend on the carry — a
        # tape would freeze it at iteration-1 values, so reject. The
        # snapshot holds WEAK refs so it cannot pin the process's HBM
        # through the capture iteration; lookups verify identity, so a
        # pre-live array that dies and hands its id to a fresh eager
        # result reads as unknown (reject — slow but correct).
        self._known: Dict[int, Any] = {}
        for a in (known or []):
            try:
                self._known[id(a)] = weakref.ref(a)
            except TypeError:
                self._known[id(a)] = (lambda a=a: a)

    def bless(self, buf) -> None:
        """mesh.put uploaded ``buf`` during this capture. Blessed
        buffers are held strongly: the tape's bound args reference
        them anyway, and a blessing must not silently expire."""
        self._known[id(buf)] = (lambda buf=buf: buf)

    def _is_known(self, a) -> bool:
        r = self._known.get(id(a))
        return r is not None and r() is a

    def on_fetch(self, arr) -> None:
        """Host plan logic fetched ``arr`` during the capture run. If a
        recorded dispatch produced it, the body's between-dispatch
        host code READ loop data — remember the producing (call, out)
        LEAF so analysis can reject the tape when that specific output
        is carry-dependent (its fetched value would vary per
        iteration: a data-dependent exchange send matrix, a join size
        agreement). A fetched CARRY leaf is carry-dependent by
        definition (e.g. the carry's device counts sizing an exchange)
        — reject outright."""
        if id(arr) in self.carry_ids:
            self.dirty = ("host plan logic fetched a carry leaf "
                          "during capture (carry-dependent plan)")
            return
        src = self.produced.get(id(arr))
        if src is not None:
            self.plan_reads.add(src)

    def _leaf_ref(self, a) -> Optional[Tuple]:
        slot = self.carry_ids.get(id(a))
        if slot is not None:
            return ("carry", slot)
        if id(a) in self.produced:
            return ("val", self.produced[id(a)])
        if isinstance(a, np.ndarray):
            # a host array feeding a dispatch may be a fetched copy
            # of loop-VARIANT data (multi-controller egress); a
            # tape would freeze it — reject the capture instead
            self.dirty = ("numpy argument entered a recorded "
                          "dispatch (host round trip in the body)")
            return None
        if isinstance(a, jax.Array) and self._known \
                and not self._is_known(a):
            # created during the body but not by a recorded dispatch
            # or a host upload: eager device math, possibly over the
            # carry — its frozen value would corrupt every replay
            self.dirty = ("eager device math fed a recorded dispatch "
                          "during capture (unrecorded jax op in the "
                          "body?)")
            return None
        return ("const", a)

    def on_call(self, fn, args, kwargs, out) -> None:
        if self.dirty is not None:
            return
        if kwargs:
            self.dirty = "dispatch with keyword arguments"
            return
        refs: List[Tuple] = []
        leaf_kinds: List[Tuple] = []     # flatten order = jaxpr invars
        for a in args:
            leaves, td = jax.tree.flatten(a)
            if len(leaves) == 1 and leaves[0] is a:
                ref = self._leaf_ref(a)
                if ref is None:
                    return
                refs.append(ref)
                leaf_kinds.append(ref)
                continue
            subs = []
            for l in leaves:
                s = self._leaf_ref(l)
                if s is None:
                    return
                subs.append(s)
            leaf_kinds.extend(subs)
            if all(s[0] == "const" for s in subs):
                refs.append(("const", a))     # wholly-constant pytree
            else:
                refs.append(("tree", td, subs))
        try:
            # abstract argument shapes for the per-output-leaf taint
            # refinement (re-tracing with ShapeDtypeStructs is cheap
            # and happens only for fetched, carry-dependent calls)
            avals = tuple(
                jax.tree.map(lambda l: jax.ShapeDtypeStruct(
                    jnp.shape(l), jnp.result_type(l)), a)
                for a in args)
        except Exception:
            avals = None                  # conservative: no refinement
        out_leaves = jax.tree.leaves(out)
        idx = len(self.calls)
        for j, o in enumerate(out_leaves):
            self.produced[id(o)] = (idx, j)
        self.calls.append(_Call(fn, refs, out_leaves,
                                leaf_kinds=leaf_kinds, avals=avals))


# ----------------------------------------------------------------------
# per-output-leaf taint refinement (jaxpr input->output reachability)
# ----------------------------------------------------------------------

# call-like primitives whose sub-jaxpr maps eqn invars to outvars
# one-to-one, so reachability may recurse instead of union-ing all
# inputs into all outputs. Loops/conds (scan, while, cond) are NOT
# here on purpose: their iteration semantics mix operands across
# rounds, so they keep the conservative union.
_CALL_PRIMS = frozenset({"pjit", "closed_call", "core_call", "xla_call",
                         "custom_jvp_call", "custom_vjp_call",
                         "remat", "checkpoint", "shard_map"})


def _jaxpr_output_deps(jaxpr) -> List[frozenset]:
    """For each jaxpr output, the set of INVAR indices it may depend
    on — a conservative over-approximation (per-equation union, with
    recursion into call-like sub-jaxprs so a ``pjit``/``shard_map``
    wrapper does not collapse the whole program into one equation)."""
    deps = {v: frozenset([i]) for i, v in enumerate(jaxpr.invars)}

    def get(atom):
        if hasattr(atom, "val"):           # Literal
            return frozenset()
        return deps.get(atom, frozenset())  # constvars -> empty

    for eqn in jaxpr.eqns:
        sub = None
        if eqn.primitive.name in _CALL_PRIMS:
            p = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
            if p is not None:
                inner = getattr(p, "jaxpr", p)   # ClosedJaxpr -> Jaxpr
                if len(inner.invars) == len(eqn.invars):
                    sub = inner
        if sub is not None:
            inner_out = _jaxpr_output_deps(sub)
            in_sets = [get(a) for a in eqn.invars]
            for ov, od in zip(eqn.outvars, inner_out):
                s = frozenset()
                for k in od:
                    s |= in_sets[k]
                deps[ov] = s
            continue
        u = frozenset()
        for a in eqn.invars:
            u |= get(a)
        for ov in eqn.outvars:
            deps[ov] = u
    return [get(o) for o in jaxpr.outvars]


def _call_output_deps(c: "_Call") -> Optional[List[frozenset]]:
    """Per-output-leaf invar dependence of one recorded call, from a
    fresh abstract trace of its program; None (refinement unavailable)
    on any failure — the caller then falls back to call-level taint."""
    if c.leaf_kinds is None or c.avals is None:
        return None
    target = getattr(c.fn, "raw", None) or getattr(c.fn, "_jitted",
                                                   None)
    if target is None:
        return None
    try:
        closed = jax.make_jaxpr(target)(*c.avals)
        return _jaxpr_output_deps(closed.jaxpr)
    except Exception:
        return None


class _LeafTaint:
    """Transitive per-output-LEAF carry dependence over a recorded
    tape: output (i, j) is carry-dependent iff the jaxpr-level
    reachability of call ``i`` connects it to a carry input leaf or to
    a carry-dependent output of an earlier call (judged recursively at
    leaf level). Conservative at every gap: a call whose program
    cannot be re-traced falls back to its call-level verdict. Traces
    are computed lazily and memoized — only calls actually reachable
    from a fetched output pay one abstract trace."""

    def __init__(self, calls: List["_Call"], dep: List[bool]) -> None:
        self.calls = calls
        self.dep = dep
        self._out_deps: Dict[int, Optional[List[frozenset]]] = {}
        self._pair: Dict[Tuple[int, int], bool] = {}

    def pair_dep(self, i: int, j: int) -> bool:
        key = (i, j)
        hit = self._pair.get(key)
        if hit is not None:
            return hit
        if not self.dep[i]:
            self._pair[key] = False
            return False
        od = self._out_deps.get(i, ...)
        if od is ...:
            od = self._out_deps[i] = _call_output_deps(self.calls[i])
        kinds = self.calls[i].leaf_kinds
        r = True                        # conservative default
        if od is not None and kinds is not None and j < len(od):
            r = False
            for k in od[j]:
                if k >= len(kinds):
                    r = True
                    break
                ref = kinds[k]
                if ref[0] == "carry" or (
                        ref[0] == "val" and self.pair_dep(*ref[1])):
                    r = True
                    break
        self._pair[key] = r
        return r


# ----------------------------------------------------------------------
# the plan
# ----------------------------------------------------------------------

def _exact_ident(x) -> bool:
    """Does ``_canon(x)`` carry full content identity? Mirrors
    _canon's branches: tuples recurse; callables are exact when their
    token embeds a bytecode hash (i.e. they have ``__code__``);
    everything else is exact unless its repr is address-bearing (which
    _canon degrades to a bare class name two distinct objects would
    share)."""
    if isinstance(x, tuple):
        return all(_exact_ident(e) for e in x)
    if callable(x) and not isinstance(x, type):
        if getattr(x, "__qualname__", None):
            return getattr(x, "__code__", None) is not None
        # falls through to _canon's repr branch below
    return " at 0x" not in repr(x)


def _tape_meta(calls: List[_Call], plan_reads, carry_out,
               n_carry: int) -> Optional[dict]:
    """The tape's persistable identity: per-call compiled-program keys
    (MeshExec cache-key digests) plus a wiring digest over argument
    refs, fetched plan reads and the carry mapping — exactly the
    inputs the capture analysis is a pure function of, so two tapes
    with equal metadata provably analyze the same. None when any call
    lacks a stable cache key (uncached program: no cross-process
    identity)."""
    import hashlib

    from ..data.exchange import _canon
    keys = []
    exact = True
    for c in calls:
        key = getattr(c.fn, "cache_key", None)
        if key is None:
            return None
        # _canon degrades some reprs to identities WITHOUT content
        # hashes (address-bearing objects -> bare class, callables
        # without __code__ -> bare qualname) — correctness-neutral for
        # capacities (they ratchet and heal) but NOT for trusting a
        # taint verdict: two distinct programs could digest equal.
        # _exact_ident walks the key structurally (mirroring _canon's
        # branches), so ordinary keys — strings, ints, dtypes,
        # treedefs, user functions incl. lambdas/locals (their tokens
        # carry bytecode+consts+closure hashes) — stay exact.
        if not _exact_ident(key):
            exact = False
        keys.append(hashlib.sha1(_canon(key).encode()).hexdigest())

    def rsig(ref):
        if ref[0] == "const":
            return "c"
        if ref[0] == "tree":
            return ("t", _canon(ref[1]),
                    tuple(rsig(s) for s in ref[2]))
        return ref                     # ("carry", s) / ("val", (i, j))

    wiring = repr((
        tuple(tuple(rsig(r) for r in c.arg_refs) for c in calls),
        tuple(sorted(plan_reads)),
        tuple("c" if r[0] == "const" else r for r in carry_out),
        n_carry))
    return {"capture": True, "calls": keys, "exact": exact,
            "wiring": hashlib.sha1(wiring.encode()).hexdigest()}


class LoopPlan:
    """A replayable tape over one loop iteration.

    ``carry_out``: per carry-leaf reference — ("val", (i, j)) into the
    live tape or ("carry", s) passthrough. ``counts`` (shards mode):
    the iteration-invariant host counts of the carry, or None when the
    counts thread through the tape as a device leaf. ``seed``: the
    plan store's remembered tape metadata for this loop — a digest
    match skips the taint re-traces (trusted tape), a mismatch is
    STALE and runs the full fresh analysis."""

    def __init__(self, mex, calls: List[_Call], carry_out: List[Tuple],
                 n_carry: int, plan_reads: Optional[set] = None,
                 name: Optional[str] = None,
                 seed: Optional[dict] = None) -> None:
        self.mex = mex
        self.calls = calls
        self.carry_out = carry_out
        self.n_carry = n_carry
        self.name = name
        self.plan_reads = plan_reads or set()
        self.seed = seed if isinstance(seed, dict) else None
        self.seeded = False            # trusted warm-restart metadata
        self.seed_stale = False        # seed present but mismatched
        self.meta: Optional[dict] = None
        # set by _analyze when the tape cannot be replayed safely
        self.invalid: Optional[str] = None
        # shards-mode carry counts: the iteration-invariant host counts
        # replayed carries inherit (None = counts thread through the
        # tape as the last carry leaf)
        self.counts: Optional[np.ndarray] = None
        self.pruned_invariant = 0
        self.pruned_dead = 0
        self._fori: Any = None           # lazily built whole-loop program
        self._fori_failed = False
        self._analyze()

    # -- dataflow analysis ---------------------------------------------
    def _analyze(self) -> None:
        calls = self.calls
        n = len(calls)
        # carry dependence (forward)
        dep = [False] * n
        for i, c in enumerate(calls):
            for ref in _leaf_refs(c.arg_refs):
                if ref[0] == "carry" or (ref[0] == "val"
                                         and dep[ref[1][0]]):
                    dep[i] = True
                    break
        # tape identity (plan-store loop_tape metadata): computed over
        # the ORIGINAL calls/wiring/plan-reads — exactly the inputs
        # the taint verification below is a pure function of
        self.meta = _tape_meta(calls, self.plan_reads, self.carry_out,
                               self.n_carry)
        trusted = False
        if self.seed is not None:
            if self.meta is not None and self.seed.get("capture") \
                    and self.meta["exact"] and self.seed.get("exact") \
                    and self.seed.get("calls") == self.meta["calls"] \
                    and self.seed.get("wiring") == self.meta["wiring"]:
                # warm restart: this exact tape (same compiled-program
                # keys, same wiring, same fetched plan reads) analyzed
                # clean before — skip the per-output-leaf taint
                # re-traces, the capture iteration's expensive half
                trusted = self.seeded = True
            else:
                self.seed_stale = True
        # host plan logic that read a CARRY-DEPENDENT value during
        # capture (data-dependent exchange send matrix, a size
        # agreement) would be frozen by the tape at iteration-1 values
        # — reject. Dependence is judged per output LEAF: when the
        # producing call is carry-dependent overall, its jaxpr's
        # input->output reachability decides whether THIS output
        # depends on a carry leaf or only on constants/invariant
        # values (a constant-topology shuffle's send matrix derives
        # from a fixed key column riding next to the changing ranks —
        # per-CALL taint would reject it, per-leaf taint captures it).
        # Refinement failures fall back to the per-call verdict.
        if not trusted:
            taint = _LeafTaint(calls, dep)
            for i, j in self.plan_reads:
                if dep[i] and taint.pair_dep(i, j):
                    self.invalid = ("host plan logic read a "
                                    "carry-dependent value during "
                                    "capture (data-dependent exchange "
                                    "plan?)")
                    break
        # liveness (backward from the carry outputs)
        needed = [False] * n
        stack = [ref[1][0] for ref in self.carry_out if ref[0] == "val"]
        while stack:
            i = stack.pop()
            if needed[i]:
                continue
            needed[i] = True
            for ref in _leaf_refs(calls[i].arg_refs):
                if ref[0] == "val":
                    stack.append(ref[1][0])
        live_idx = [i for i in range(n) if needed[i] and dep[i]]
        self.pruned_invariant = sum(1 for i in range(n)
                                    if needed[i] and not dep[i])
        self.pruned_dead = n - sum(needed)
        remap = {old: new for new, old in enumerate(live_idx)}

        def rewrite(ref):
            if ref[0] == "val":
                src, j = ref[1]
                if src in remap:
                    return ("val", (remap[src], j))
                # invariant producer: its captured output IS the
                # value for every future iteration
                return ("const", calls[src].out_buffers[j])
            if ref[0] == "tree":
                return ("tree", ref[1], [rewrite(s) for s in ref[2]])
            return ref

        live: List[_Call] = []
        for i in live_idx:
            c = calls[i]
            live.append(_Call(c.fn, [rewrite(r) for r in c.arg_refs],
                              c.out_buffers))
        out: List[Tuple] = []
        for ref in self.carry_out:
            if ref[0] == "val":
                src, j = ref[1]
                if src in remap:
                    out.append(("val", (remap[src], j)))
                else:
                    # invariant producer: this carry leaf is the SAME
                    # value every iteration — fold it, like rewrite()
                    out.append(("const", calls[src].out_buffers[j]))
            else:
                out.append(ref)
        self.calls = live
        self.carry_out = out
        # donation positions are recomputed per capture (cheap, pure
        # python over the refs) — the wiring digest in the metadata
        # fully determines them, so a trusted seed's donation twins
        # provably match what this analysis just derived
        self._mark_donations()
        # live calls must not pin the capture iteration's HBM: their
        # recorded outputs are never read again (invariant producers'
        # outputs were just folded into ("const", ...) refs above)
        for c in self.calls:
            c.out_buffers = None
        # which (call, out) pairs later steps / the carry actually read
        used: set = set()
        for c in self.calls:
            for ref in _leaf_refs(c.arg_refs):
                if ref[0] == "val":
                    used.add(ref[1])
        for ref in self.carry_out:
            if ref[0] == "val":
                used.add(ref[1])
        self.used_outputs = used

    def _mark_donations(self) -> None:
        """Static donation plan: an argument buffer is donatable when
        it is loop-owned (a carry leaf or a live call's output), this
        is its LAST use in the iteration, and it does not survive into
        the next carry. Pytree arguments stay pinned (jax donates whole
        arguments; a mixed tree would donate its constants too)."""
        survivors = set()
        for slot, ref in enumerate(self.carry_out):
            if ref[0] in ("carry", "val"):
                survivors.add((ref[0], ref[1]))
            else:
                # folded-const carry-out: slot hands back the SAME
                # buffer every iteration (and holds it on entry from
                # the previous iteration's carry) — donating it would
                # free a buffer the loop still owns
                survivors.add(("carry", slot))
        by_ref: Dict[Tuple, List[int]] = {}
        for slot, ref in enumerate(self.carry_out):
            if ref[0] in ("carry", "val"):
                by_ref.setdefault((ref[0], ref[1]), []).append(slot)
        for slots in by_ref.values():
            if len(slots) > 1:
                # aliased carry-out: these slots hand back ONE buffer,
                # so the next iteration's incoming carry leaves alias —
                # donating any one view would free the buffer another
                # slot still reads mid-iteration
                for s in slots:
                    survivors.add(("carry", s))
        last_use: Dict[Tuple, Tuple[int, int]] = {}
        for i, c in enumerate(self.calls):
            seen_here: Dict[Tuple, int] = {}
            for p, ref in enumerate(c.arg_refs):
                if ref[0] not in ("carry", "val"):
                    continue
                key = (ref[0], ref[1])
                seen_here[key] = seen_here.get(key, 0) + 1
                last_use[key] = (i, p)
            # a buffer passed twice to one call cannot be donated;
            # neither can one this call ALSO reads through a pytree
            # argument (donating would free a buffer the same dispatch
            # reads) — position -1 never matches a donatable slot
            for key, k in seen_here.items():
                if k > 1:
                    last_use.pop(key, None)
            for ref in c.arg_refs:
                if ref[0] == "tree":
                    for s in ref[2]:
                        if s[0] != "const":
                            last_use[(s[0], s[1])] = (i, -1)
        for i, c in enumerate(self.calls):
            pos = tuple(sorted(
                p for p, ref in enumerate(c.arg_refs)
                if ref[0] in ("carry", "val")
                and (ref[0], ref[1]) not in survivors
                and last_use.get((ref[0], ref[1])) == (i, p)))
            c.donate_pos = pos

    # -- execution ------------------------------------------------------
    def replay(self, carry: List[Any], donate: bool,
               donate_carry: bool = True) -> List[Any]:
        """Run one tape iteration over ``carry`` leaves; returns the
        next carry leaves. ``donate_carry=False`` pins the incoming
        carry buffers (first replay; the iteration after a checkpoint
        seal)."""
        mex = self.mex
        vals: Dict[Tuple[int, int], Any] = {}

        def resolve(ref):
            kind = ref[0]
            if kind == "const":
                return ref[1]
            if kind == "carry":
                return carry[ref[1]]
            if kind == "val":
                return vals[ref[1]]
            return jax.tree.unflatten(ref[1],
                                      [resolve(s) for s in ref[2]])

        for i, call in enumerate(self.calls):
            args = [resolve(ref) for ref in call.arg_refs]
            fn = call.fn
            if donate and call.donate_pos:
                pos = call.donate_pos
                if not donate_carry:
                    pos = tuple(p for p in pos
                                if call.arg_refs[p][0] != "carry")
                if pos:
                    fn = call.fn.donating(pos)
                    mex.stats_loop_donated_bytes += sum(
                        getattr(args[p], "nbytes", 0) for p in pos)
            out = fn(*args)
            for j, o in enumerate(jax.tree.leaves(out)):
                if (i, j) in self.used_outputs:
                    vals[(i, j)] = o
        return [carry[ref[1]] if ref[0] == "carry"
                else ref[1] if ref[0] == "const"
                else vals[ref[1]] for ref in self.carry_out]

    # -- whole-loop fori_loop lowering ---------------------------------
    def fori_eligible(self) -> bool:
        """Every recorded call retains its raw (pre-jit) program, so
        the whole tape can be re-traced inside ONE ``lax.fori_loop``
        body — exchanges and host fallbacks never record, so any
        all-device tape qualifies."""
        return bool(self.calls) and all(
            getattr(c.fn, "raw", None) is not None for c in self.calls)

    def _fori_consts(self) -> Tuple:
        """Constant operands in tape order (tree args contribute their
        const LEAVES, in flatten order — the fori body consumes them
        from the same traversal)."""
        out = []
        for c in self.calls:
            for ref in c.arg_refs:
                if ref[0] == "const":
                    out.append(ref[1])
                elif ref[0] == "tree":
                    out.extend(s[1] for s in ref[2] if s[0] == "const")
        return tuple(out)

    def run_fori(self, carry: List[Any], k: int) -> Optional[List[Any]]:
        """Lower the remaining ``k`` iterations into ONE jitted
        ``lax.fori_loop`` dispatch over the whole tape, or return None
        when the body cannot be lowered (version/topology limits).

        The incoming carry is never donated here: fori only ever runs
        as the FIRST replay after a (re)capture, whose carry buffers
        the capture graph still references."""
        if self._fori_failed or not self.fori_eligible():
            return None
        calls = self.calls
        out_slots: List[Tuple] = list(self.carry_out)
        used = self.used_outputs
        cached = self._fori
        if cached is None or cached[1] != k:
            # two plans with the same per-call programs and wiring are
            # the SAME loop — share one compiled fori program through
            # the mesh cache (a fresh capture per driver call must not
            # recompile the whole-loop dispatch)
            def ref_sig(r):
                if r[0] == "const":
                    return ("const",)
                if r[0] == "tree":
                    return ("tree", r[1],
                            tuple(ref_sig(s) for s in r[2]))
                return r

            # a const carry-out leaf is CLOSED OVER by the traced body
            # (folded invariant producer), so the compiled program is
            # keyed on that buffer's identity — never shared across
            # captures holding different values
            out_sig = tuple(("const", id(r[1])) if r[0] == "const"
                            else r for r in out_slots)
            key = ("loop_fori",
                   tuple(getattr(c.fn, "cache_key", None)
                         or ("rawid", id(c.fn.raw)) for c in calls),
                   tuple(tuple(ref_sig(r) for r in c.arg_refs)
                         for c in calls),
                   tuple(sorted(used)), out_sig, k)

            built = []

            def build():
                built.append(True)
                # the compiled closure lives in the mesh cache for the
                # MESH's lifetime — it must not pin this plan's const
                # ARGUMENT buffers (they arrive through the runtime
                # ``consts`` operand; only const carry-OUT leaves are
                # intentionally closed over, that's what the id-keying
                # above is for)
                def strip(r):
                    if r[0] == "const":
                        return ("const", None)
                    if r[0] == "tree":
                        return ("tree", r[1], [strip(s) for s in r[2]])
                    return r
                call_plan = [(c.fn.raw, [strip(r) for r in c.arg_refs])
                             for c in calls]

                def loop_fn(carry_t, consts):
                    def body(_, c):
                        ci = iter(consts)
                        vals: Dict[Tuple[int, int], Any] = {}

                        def resolve(ref):
                            if ref[0] == "carry":
                                return c[ref[1]]
                            if ref[0] == "val":
                                return vals[ref[1]]
                            if ref[0] == "const":
                                return next(ci)
                            return jax.tree.unflatten(
                                ref[1], [resolve(s) for s in ref[2]])

                        for i, (raw, refs) in enumerate(call_plan):
                            args = [resolve(r) for r in refs]
                            leaves = jax.tree.leaves(raw(*args))
                            for j, o in enumerate(leaves):
                                if (i, j) in used:
                                    vals[(i, j)] = o
                        return tuple(
                            c[ref[1]] if ref[0] == "carry"
                            else ref[1] if ref[0] == "const"
                            else vals[ref[1]] for ref in out_slots)

                    return lax.fori_loop(0, k, body, tuple(carry_t))

                # the whole-loop program dispatches through the
                # _CountedJit choke point like every other device
                # entry: HBM admission control, the OOM-retry ladder
                # and the dispatch counters cover it (an OOM here used
                # to bypass rung 1/2 entirely and only degrade via
                # Iterate's re-plan fallback). counted_jit keeps
                # parallel/mesh.py the single module constructing jits
                # (the choke-point source audit in test_tracing.py)
                return self.mex.counted_jit(loop_fn)

            try:
                fn = self.mex.cached(key, build)
                if built:                        # fresh program: probe
                    fn.lower(tuple(carry), self._fori_consts())
            except Exception as e:               # version/topology limits
                self._fori_failed = True
                log = getattr(self.mex, "logger", None)
                if log is not None and log.enabled:
                    log.line(event="loop_fori_unavailable",
                             loop=self.name, error=repr(e)[:200])
                return None
            self._fori = (fn, k)
        fn = self._fori[0]
        # the dispatch counter ticks inside _CountedJit.__call__ now
        out = fn(tuple(carry), self._fori_consts())
        return list(out)


# ----------------------------------------------------------------------
# carry plumbing
# ----------------------------------------------------------------------

class _LoopCarryNode(DIABase):
    """Source node wrapping the loop-carried shards of one iteration."""

    def __init__(self, ctx, shards) -> None:
        super().__init__(ctx, "LoopCarry")
        self._carry = shards

    def compute(self):
        return self._carry


def _carry_dia(ctx, shards) -> DIA:
    return DIA(_LoopCarryNode(ctx, shards))


def _shards_carry_ids(shards: DeviceShards) -> Tuple[Dict[int, int], int]:
    leaves = jax.tree.leaves(shards.tree)
    ids = {id(l): s for s, l in enumerate(leaves)}
    n = len(leaves)
    if shards._counts_dev is not None and shards._counts_host is None:
        ids[id(shards._counts_dev)] = n
        n += 1
    return ids, n


def _leaf_sig(leaves: Sequence[Any]) -> Tuple:
    return tuple((jnp.dtype(l.dtype), tuple(l.shape)) for l in leaves)


# ----------------------------------------------------------------------
# Iterate
# ----------------------------------------------------------------------

def Iterate(ctx, body: Callable, carry, n: int, *, name: str = "loop",
            checkpoint_every: Optional[int] = None):
    """Run ``body`` ``n`` times with ``carry`` threaded through,
    replaying a captured LoopPlan for iterations 2..N.

    ``carry`` is either a DIA / DeviceShards (``body(dia) -> dia``, the
    Collapse-loop idiom) or a pytree of device arrays (``body(tree) ->
    tree``, the k-means centroid idiom). The body must be
    iteration-index-independent: same graph, same shapes every
    iteration (the capture contract; violations reject the capture and
    fall back to plain per-iteration planning, they cannot corrupt —
    with ONE exception the recorder cannot see: Python control flow on
    a directly-converted eager scalar (``if float(jnp.sum(x)) < eps``)
    bakes the iteration-1 branch into the tape; see the module
    docstring's "known blind spot" and keep convergence checks outside
    ``Iterate``).

    ``checkpoint_every=k`` (DIA/DeviceShards carries only — a pytree
    carry raises) seals the carry into a durable epoch every k
    iterations when the Context has a CheckpointManager
    (THRILL_TPU_CKPT_DIR); a resumed run restores the newest loop epoch
    for ``name`` and continues after it. Returns the final carry in
    the same form it was given (DIA in, DIA out)."""
    if n <= 0:
        return carry
    mex = ctx.mesh_exec
    log = ctx.logger
    mgr = getattr(ctx, "checkpoint", None)

    # -- normalize the carry -------------------------------------------
    dia_mode = isinstance(carry, (DIA, DIABase))
    if dia_mode:
        if isinstance(carry, DIABase):
            carry = DIA(carry)
        state = carry._link().pull(consume=True)
    elif isinstance(carry, (DeviceShards, HostShards)):
        dia_mode = True
        state = carry
    else:
        state = jax.tree.map(jnp.asarray, carry)

    if checkpoint_every and not dia_mode:
        # sealing requires the shard-file epoch path (DIA/DeviceShards
        # carries); silently skipping would deliver NO durability the
        # caller asked for — refuse up front instead
        raise ValueError(
            "Iterate(checkpoint_every=...) requires a DIA/DeviceShards "
            "carry; pytree carries cannot be sealed into checkpoint "
            "epochs (wrap the state in a DIA, or drop checkpoint_every)")

    start = 0
    if mgr is not None and checkpoint_every and dia_mode:
        restored = mgr.try_restore_loop(name)
        if restored is not None:
            state, start = restored
            start += 1                       # resume AFTER the epoch

    can_replay = (replay_enabled()
                  and not (mgr is not None and mgr.auto)
                  and (not dia_mode or isinstance(state, DeviceShards)))

    def run_body(st):
        """One plain iteration: st -> next st, through the full pull
        recursion + fusion planner."""
        if dia_mode:
            out = body(_carry_dia(ctx, st))
            if isinstance(out, DIABase):
                out = DIA(out)
            return out._link().pull(consume=True)
        return body(st)

    def seal(st, i):
        if mgr is not None and checkpoint_every and dia_mode \
                and (i + 1) % checkpoint_every == 0 and i + 1 < n:
            mgr.save_loop_state(name, i, st)
            return True
        return False

    plan: Optional[LoopPlan] = None
    donate = donation_enabled()
    miss_streak = 0          # consecutive capture misses: a miss is
    # almost always deterministic (eager body math, data-dependent
    # plan, W>1 shuffle) — re-attempting burns a full carry copy +
    # recorder pass per iteration; two strikes and the rest of the
    # loop runs plain (one retry tolerates a first iteration whose
    # carry shape was still stabilizing)
    # plan-store loop-tape metadata: the remembered capture outcome
    # for this (name, carry-signature) loop — a clean tape's digests
    # let the capture skip its taint re-traces, a known-uncapturable
    # loop skips the capture probes entirely
    tape_token = _tape_token(name, dia_mode, state, body) \
        if can_replay else None
    tape_seed = None
    seed_mode: Optional[str] = None
    last_miss: Dict[str, str] = {}
    if tape_token is not None:
        from ..data.exchange import plan_seed as _plan_seed
        tape_seed = _plan_seed(mex, "loop_tape", tape_token)
        if isinstance(tape_seed, dict) \
                and tape_seed.get("capture") is False:
            # warm restart: this loop previously rejected capture for
            # a deterministic reason — run plain from iteration 1,
            # skipping the probes (each a full carry copy + recorder
            # pass). LOUD: logged with the remembered reason; if the
            # body changed enough to capture now, its carry signature
            # almost always changed too (fresh token, no seed).
            miss_streak = 2
            seed_mode = "nocapture"
            _note_tape(mex, tape_token, tape_seed)
            if log.enabled:
                log.line(event="loop_seed_nocapture", loop=name,
                         reason=str(tape_seed.get("reason", "?"))[:200])
            tape_seed = None
    report = {"name": name, "iters": n - start, "captures": 0, "replays": 0,
              "fori_iters": 0, "fallbacks": 0, "capture_s": 0.0,
              "replay_s": 0.0, "calls": 0, "pruned": 0,
              "donated_bytes0": mex.stats_loop_donated_bytes}
    tracer = getattr(ctx, "tracer", None)
    tr_on = tracer is not None and tracer.enabled
    i = start
    while i < n:
        if plan is None:
            # ---- capture (or plain) iteration ------------------------
            t0 = time.perf_counter()
            d0 = mex.stats_dispatches
            sp = (tracer.begin("loop", "capture", loop=name, iter=i)
                  if tr_on else None)
            try:
                if can_replay and miss_streak < 2:
                    state, plan = _capture(ctx, run_body, state,
                                           name=name, it=i,
                                           seed=tape_seed,
                                           info=last_miss)
                    if plan is not None:
                        miss_streak = 0
                        mex.stats_loop_plan_builds += 1
                        report["captures"] += 1
                        report["calls"] = len(plan.calls)
                        report["pruned"] = (plan.pruned_invariant
                                            + plan.pruned_dead)
                        if plan.seeded:
                            seed_mode = "tape"
                        elif plan.seed_stale:
                            seed_mode = "stale"
                        if tape_token is not None:
                            _note_tape(mex, tape_token, plan.meta)
                    else:
                        miss_streak += 1
                        if miss_streak >= 2 and tape_token is not None:
                            # deterministic reject: remember it so a
                            # warm restart skips the capture probes
                            _note_tape(mex, tape_token, {
                                "capture": False,
                                "reason": last_miss.get("reason",
                                                        "?")[:200]})
                else:
                    state = run_body(state)
            finally:
                if sp is not None:
                    tracer.end(sp, mode=("capture" if plan is not None
                                         else "plain"))
            dt = time.perf_counter() - t0
            report["capture_s"] += dt
            if log.enabled:
                log.line(event="iteration", loop=name, iter=i,
                         mode="capture" if plan is not None else "plain",
                         seconds=round(dt, 6),
                         dispatches=mex.stats_dispatches - d0,
                         plan_calls=(len(plan.calls)
                                     if plan is not None else None))
            ckpt = seal(state, i)
            i += 1
            fresh_plan = True
            continue

        # ---- replayed iterations -------------------------------------
        leaves, treedef = _carry_leaves(state, dia_mode, plan)
        if leaves is None:
            plan = None                      # carry shape drifted
            continue
        remaining = n - i
        # whole-loop lowering: only when no checkpoint epoch is due
        # inside the window (an epoch needs the carry on the host) —
        # checkpoint_every without a CheckpointManager seals nothing,
        # so it must not cost the fori lowering either
        fori_ok = fori_enabled() \
            and not (checkpoint_every and mgr is not None) \
            and plan.fori_eligible() and remaining > 1
        t0 = time.perf_counter()
        d0 = mex.stats_dispatches
        sp = (tracer.begin("loop", "replay", loop=name, iter=i)
              if tr_on else None)
        try:
            try:
                if faults.REGISTRY.active():
                    faults.check(_F_REPLAY, loop=name, iter=i)
                if fori_ok:
                    out = plan.run_fori(leaves, remaining)
                    if out is not None:
                        mex.stats_loop_fori_iters += remaining
                        report["fori_iters"] += remaining
                        state = _rebuild_carry(out, treedef, dia_mode,
                                               mex, plan)
                        dt = time.perf_counter() - t0
                        report["replay_s"] += dt
                        if sp is not None:
                            sp.attrs["fori_iters"] = remaining
                        if log.enabled:
                            log.line(event="loop_replay", loop=name,
                                     iter=i, iters=remaining, fori=True,
                                     seconds=round(dt, 6))
                        i = n
                        continue
                out = plan.replay(
                    leaves,
                    donate and not faults.REGISTRY.active(),
                    donate_carry=not fresh_plan and not ckpt)
            except Exception as e:
                # LOUD degradation: a failed replayed dispatch falls
                # back to full re-planning for this iteration (the body
                # path, which re-captures); the loop slows down, it
                # never lies. Unless donation already consumed part of
                # the carry mid-iteration — then there is nothing to
                # re-plan FROM, and the only honest outcome is a clear
                # error, not a deleted-array crash deep inside the pull
                # recursion.
                if sp is not None:
                    sp.attrs["error"] = repr(e)[:200]
                if any(getattr(l, "is_deleted", lambda: False)()
                       for l in leaves):
                    raise RuntimeError(
                        f"loop '{name}' iteration {i}: a replayed "
                        f"dispatch failed after part of the loop carry "
                        f"was donated; cannot degrade to re-planning. "
                        f"Re-run with THRILL_TPU_LOOP_DONATE=0 (or "
                        f"from the last checkpoint epoch).") from e
                mex.stats_loop_fallbacks += 1
                report["fallbacks"] += 1
                faults.note("recovery", what="loop_replay", loop=name,
                            iter=i, error=repr(e)[:200])
                if log.enabled:
                    log.line(event="loop_replay_fallback", loop=name,
                             iter=i, error=repr(e)[:200])
                plan = None
                continue
            mex.stats_loop_replays += 1
            report["replays"] += 1
            state = _rebuild_carry(out, treedef, dia_mode, mex, plan)
            dt = time.perf_counter() - t0
            report["replay_s"] += dt
            if log.enabled:
                log.line(event="loop_replay", loop=name, iter=i,
                         dispatches=mex.stats_dispatches - d0,
                         seconds=round(dt, 6))
            ckpt = seal(state, i)
            fresh_plan = False
            i += 1
        finally:
            if sp is not None:
                tracer.end(sp)

    report["donated_bytes"] = (mex.stats_loop_donated_bytes
                               - report.pop("donated_bytes0"))
    if seed_mode is not None:
        # plan-store tape-metadata outcome: "tape" (trusted, analysis
        # skipped), "stale" (digest mismatch, fresh analysis),
        # "nocapture" (known-uncapturable, probes skipped)
        report["seed"] = seed_mode
    mex.loop_reports.append(report)
    if log.enabled:
        log.line(event="loop_done", **{k: (round(v, 6)
                                           if isinstance(v, float) else v)
                                       for k, v in report.items()})
    if dia_mode:
        return _carry_dia(ctx, state)
    return state


def _tape_token(name: str, dia_mode: bool, state,
                body) -> Optional[Tuple]:
    """Plan-store identity of one loop's tape: name + the BODY's
    canonical identity (module.qualname + bytecode hash — two loops
    sharing the default name must not share a tape record, or an
    uncapturable sibling's ``capture: False`` would force a capturable
    one to run plain forever) + carry signature (leaf dtypes/shapes,
    capacity, counts mode). None when the carry cannot be signed
    (host storage, conversion failure)."""
    from ..data.exchange import _canon
    try:
        body_id = _canon(body)
        if dia_mode:
            if not isinstance(state, DeviceShards):
                return None
            sig = (_leaf_sig(jax.tree.leaves(state.tree)), state.cap,
                   state._counts_host is not None)
        else:
            sig = (_leaf_sig(jax.tree.leaves(state)),)
    except Exception:
        return None
    return ("loop_tape", name, bool(dia_mode), body_id, sig)


def _capture(ctx, run_body, state, name="loop", it=0, seed=None,
             info=None):
    """Run one body iteration with the tape recorder installed.
    Returns (next_state, LoopPlan or None). ``seed`` is the plan
    store's remembered tape metadata (LoopPlan trusts a digest match);
    ``info`` (dict) receives the miss reason for the caller's own
    metadata bookkeeping."""
    mex = ctx.mesh_exec
    log = ctx.logger

    def miss(reason, out_state):
        if info is not None:
            info["reason"] = reason
        if log.enabled:
            log.line(event="loop_capture_miss", loop=name, iter=it,
                     reason=reason)
        return out_state, None

    # De-alias the carry before recording: classification is by buffer
    # IDENTITY, so a carry leaf sharing its buffer with a closure
    # constant of the body (or with another carry slot) would record a
    # lying ("carry", s) ref for the constant — every leaf gets a
    # fresh buffer only the carry can be holding. One eager copy per
    # capture, nothing per replay.
    try:
        if isinstance(state, DeviceShards):
            state.tree = jax.tree.map(jnp.copy, state.tree)
            if state._counts_dev is not None \
                    and state._counts_host is None:
                state._counts_dev = jnp.copy(state._counts_dev)
        else:
            leaves = jax.tree.leaves(state)
            if not all(isinstance(l, jax.Array) for l in leaves):
                return miss("carry is not device-resident",
                            run_body(state))
            state = jax.tree.map(jnp.copy, state)
    except Exception as e:                 # non-addressable shards
        return miss(f"carry copy failed ({e!r})", run_body(state))
    if isinstance(state, DeviceShards):
        carry_ids, n_carry = _shards_carry_ids(state)
    else:
        leaves = jax.tree.leaves(state)
        carry_ids = {id(l): s for s, l in enumerate(leaves)}
        n_carry = len(leaves)
    rec = _Recorder(carry_ids, known=list(jax.live_arrays()))
    prev = mex.loop_recorder
    if prev is not None:
        # nested Iterate inside a capturing body: the inner loop's
        # dispatches bypass the OUTER recorder (this capture replaces
        # it), so the outer tape would silently skip the whole inner
        # loop on replay — dirty the outer capture so it rejects
        # loudly; the inner loop may still capture for itself
        prev.dirty = "nested Iterate inside a capturing body"
    mex.loop_recorder = rec
    try:
        out_state = run_body(state)
    finally:
        mex.loop_recorder = prev
    if rec.dirty is not None:
        return miss(rec.dirty, out_state)
    if mex._pending_checks:
        # an unresolved deferred validation (un-drained hinted-join
        # overflow check) cannot be replayed — it would never run
        return miss("pending deferred validations", out_state)

    # map the produced carry back onto the tape
    host_counts = None
    if isinstance(out_state, DeviceShards):
        if not isinstance(state, DeviceShards):
            return miss("carry storage changed", out_state)
        out_leaves = jax.tree.leaves(out_state.tree)
        in_leaves = jax.tree.leaves(state.tree)
        if _leaf_sig(out_leaves) != _leaf_sig(in_leaves) \
                or out_state.cap != state.cap \
                or (jax.tree.structure(out_state.tree)
                    != jax.tree.structure(state.tree)):
            return miss("carry schema/shape drifted", out_state)
        if state._counts_host is not None:
            # host-known input counts were baked into the tape's
            # dispatches as blessed constants — they must provably hold
            # for EVERY iteration's input, i.e. the body must hand the
            # same host counts back (then by induction every replay's
            # input matches the baked values); a count-changing body
            # with stable leaf shapes/cap would otherwise replay a
            # silently wrong valid mask
            if out_state._counts_host is None:
                return miss("carry counts went device-resident across "
                            "the iteration (baked host count constants "
                            "cannot be checked)", out_state)
            if not np.array_equal(np.asarray(state._counts_host),
                                  np.asarray(out_state._counts_host)):
                return miss("carry counts changed across the iteration "
                            "(baked count constants would lie on "
                            "replay)", out_state)
        if out_state._counts_host is not None:
            host_counts = out_state._counts_host
        else:
            out_leaves = out_leaves + [out_state._counts_dev]
    elif isinstance(out_state, HostShards):
        return miss("body produced host storage", out_state)
    else:
        out_leaves = jax.tree.leaves(out_state)
        if _leaf_sig(out_leaves) != _leaf_sig(jax.tree.leaves(state)) \
                or (jax.tree.structure(out_state)
                    != jax.tree.structure(state)):
            return miss("carry schema/shape drifted", out_state)
    carry_out = []
    for leaf in out_leaves:
        if id(leaf) in rec.produced:
            carry_out.append(("val", rec.produced[id(leaf)]))
        elif id(leaf) in carry_ids:
            carry_out.append(("carry", carry_ids[id(leaf)]))
        else:
            return miss("carry leaf produced outside the recorded "
                        "dispatch stream (eager host math in the "
                        "body?)", out_state)
    plan = LoopPlan(mex, rec.calls, carry_out, n_carry, name=name,
                    plan_reads=rec.plan_reads, seed=seed)
    if plan.seed_stale and log.enabled:
        # stale plan-store metadata: LOUD, and the full fresh
        # analysis just ran — the seed cost nothing but this line
        log.line(event="loop_seed_stale", loop=name, iter=it)
    if plan.invalid is not None:
        return miss(plan.invalid, out_state)
    if host_counts is not None:
        plan.counts = host_counts.copy()
    if log.enabled:
        log.line(event="loop_plan", loop=name, calls=len(plan.calls),
                 pruned_invariant=plan.pruned_invariant,
                 pruned_dead=plan.pruned_dead,
                 fori=plan.fori_eligible(),
                 seeded=plan.seeded or None,
                 donatable=sum(len(c.donate_pos) for c in plan.calls))
    return out_state, plan


def _carry_leaves(state, dia_mode, plan):
    """Current carry as tape-slot-ordered leaves (the capture's input
    convention); (None, None) when the state no longer matches."""
    if dia_mode:
        leaves = list(jax.tree.leaves(state.tree))
        treedef = jax.tree.structure(state.tree)
        if plan.n_carry == len(leaves) + 1:
            # the tape threads device-resident counts as a carry slot
            leaves.append(state.counts_device())
        elif plan.n_carry != len(leaves):
            return None, None
        return leaves, treedef
    leaves = jax.tree.leaves(state)
    if len(leaves) != plan.n_carry:
        return None, None
    return leaves, jax.tree.structure(state)


def _rebuild_carry(out_leaves, treedef, dia_mode, mex, plan):
    if not dia_mode:
        return jax.tree.unflatten(treedef, out_leaves)
    if plan.counts is not None:
        tree = jax.tree.unflatten(treedef, out_leaves)
        return DeviceShards(mex, tree, plan.counts.copy())
    tree = jax.tree.unflatten(treedef, out_leaves[:-1])
    return DeviceShards(mex, tree, out_leaves[-1])
