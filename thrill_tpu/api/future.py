"""Deferred action results.

Equivalent of the reference's ActionResultNode / Future<T>
(reference: thrill/api/action_node.hpp:65,83,126): *Future action
variants defer evaluation; ``get()`` (or calling the future) runs the
pipeline. Issuing a future reserves one consume-budget unit on its DIA
(DIA._future), so actions executed between issue and get cannot starve
it — issue order governs consumption like the reference, where the
action node is built at creation time.
"""

from __future__ import annotations

from typing import Any, Callable, Generic, TypeVar

T = TypeVar("T")

_UNSET = object()


class ActionFuture(Generic[T]):
    def __init__(self, thunk: Callable[[], T]) -> None:
        self._thunk = thunk
        self._result: Any = _UNSET

    def get(self) -> T:
        if self._result is _UNSET:
            self._result = self._thunk()
            self._thunk = None  # free captured pipeline references
        return self._result

    __call__ = get

    @property
    def done(self) -> bool:
        return self._result is not _UNSET
