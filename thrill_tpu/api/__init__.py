from .context import Context, Run, RunLocalMock, RunLocalTests  # noqa: F401
from .dia import DIA, Concat, InnerJoin, Merge, Union, Zip, ZipWindow  # noqa: F401
