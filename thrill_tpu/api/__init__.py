from .context import (Context, PipelineError, Run,  # noqa: F401
                      RunDistributed, RunLocalMock, RunLocalTests,
                      RunSupervised)
from .dia import DIA, Concat, InnerJoin, Merge, Union, Zip, ZipWindow  # noqa: F401
from .functors import FieldReduce  # noqa: F401
from .loop import Iterate  # noqa: F401
from .planner import Planner  # noqa: F401
from .stack import Bind  # noqa: F401
