"""The DIA handle: a lazily evaluated distributed immutable array.

Equivalent of the reference's ``DIA<ValueType, Stack>``
(reference: thrill/api/dia.hpp:141): a cheap handle = node pointer +
stack of fused local operations. Chaining ``Map``/``Filter``/``FlatMap``
never touches data — it extends the stack; distributed operations cut
the stack by constructing a new DAG node; actions trigger execution.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from .dia_base import DIABase, ParentLink
from .stack import Stack, StackOp


class DIA:
    def __init__(self, node: DIABase, stack: Stack = ()) -> None:
        self.node = node
        self.stack = stack

    @property
    def context(self):
        return self.node.context

    def _link(self) -> ParentLink:
        return ParentLink(self.node, self.stack)

    # ------------------------------------------------------------------
    # local ops (stack pushes; reference api/dia.hpp:358,405,458)
    # ------------------------------------------------------------------
    def Map(self, fn: Callable) -> "DIA":
        return DIA(self.node, self.stack + (StackOp("map", fn),))

    def Filter(self, fn: Callable) -> "DIA":
        return DIA(self.node, self.stack + (StackOp("filter", fn),))

    def FlatMap(self, fn: Callable, device_fn: Optional[Callable] = None,
                factor: int = 1) -> "DIA":
        """Host: ``fn(item) -> iterable``. Device storage additionally
        needs the batched form ``device_fn(tree) -> (tree[n,k,...],
        valid[n,k])`` with static ``factor`` k; without it the pipeline
        falls back to host storage at this point."""
        from .ops import lop_nodes
        if device_fn is not None:
            return DIA(self.node, self.stack +
                       (StackOp("flat_map", device_fn, factor),))
        return lop_nodes.flat_map_host(self, fn)

    def BernoulliSample(self, p: float, seed: int = 0) -> "DIA":
        from .ops import sample
        return sample.BernoulliSample(self, p, seed)

    # ------------------------------------------------------------------
    # distributed ops
    # ------------------------------------------------------------------
    def ReduceByKey(self, key_fn: Callable, reduce_fn: Callable,
                    dup_detection=None) -> "DIA":
        """``dup_detection`` (reference: DuplicateDetectionTag) skips
        shuffling globally-unique keys: the device path folds a
        presence-register psum into the destination program, the host
        path exchanges Golomb fingerprints. None — the default —
        defers to the plan-time cost model (core/preshuffle.py,
        forced either way with THRILL_TPU_DUP_DETECT=0/1); True/False
        force it per call.

        Output order is UNSPECIFIED (as in the reference's
        hash-partitioned tables): the device engine emits key-sorted
        order, the CPU-backend native hash-group emits
        first-appearance order — sort before comparing across
        backends. Dup detection additionally changes which worker
        holds a unique key's result (it stays local instead of
        travelling to its hash home) — the result SET is identical."""
        from .ops import reduce as _r
        return _r.ReduceByKey(self, key_fn, reduce_fn, dup_detection)

    def ReducePair(self, reduce_fn: Callable) -> "DIA":
        """Items are (key, value) pairs; reduce_fn combines values."""
        from .ops import reduce as _r
        return _r.ReducePair(self, reduce_fn)

    def ReduceToIndex(self, index_fn: Callable, reduce_fn: Callable,
                      size: int, neutral: Any = None) -> "DIA":
        from .ops import reduce as _r
        return _r.ReduceToIndex(self, index_fn, reduce_fn, size, neutral)

    def GroupByKey(self, key_fn: Callable, group_fn: Callable = None,
                   device_fn: Callable = None) -> "DIA":
        """Group order is UNSPECIFIED (reference: hash-partitioned
        grouping): the device engine yields key-sorted groups, the
        CPU-backend hash-group yields first-appearance order — sort
        before comparing across backends."""
        from .ops import groupby
        return groupby.GroupByKey(self, key_fn, group_fn,
                                  device_fn=device_fn)

    def GroupToIndex(self, index_fn: Callable, group_fn: Callable = None,
                     size: int = 0, neutral: Any = None,
                     device_fn: Callable = None) -> "DIA":
        from .ops import groupby
        return groupby.GroupToIndex(self, index_fn, group_fn, size, neutral,
                                    device_fn=device_fn)

    def Sort(self, key_fn: Optional[Callable] = None,
             compare_fn: Optional[Callable] = None) -> "DIA":
        from .ops import sort as _s
        return _s.Sort(self, key_fn, compare_fn, stable=False)

    def SortStable(self, key_fn: Optional[Callable] = None,
                   compare_fn: Optional[Callable] = None) -> "DIA":
        from .ops import sort as _s
        return _s.Sort(self, key_fn, compare_fn, stable=True)

    def PrefixSum(self, fn: Callable = None, initial: Any = 0) -> "DIA":
        from .ops import prefix_sum as _p
        return _p.PrefixSum(self, fn, initial, inclusive=True)

    def ExPrefixSum(self, fn: Callable = None, initial: Any = 0) -> "DIA":
        from .ops import prefix_sum as _p
        return _p.PrefixSum(self, fn, initial, inclusive=False)

    def ZipWithIndex(self, zip_fn: Callable = None) -> "DIA":
        from .ops import zip_ as _z
        return _z.ZipWithIndex(self, zip_fn)

    def Window(self, k: int, fn: Callable,
               device_fn: Optional[Callable] = None) -> "DIA":
        from .ops import window as _w
        return _w.Window(self, k, fn, device_fn, disjoint=False)

    def FlatWindow(self, k: int, fn: Callable = None,
                   device_fn: Optional[Callable] = None,
                   factor: int = 0) -> "DIA":
        from .ops import window as _w
        return _w.FlatWindow(self, k, fn, device_fn=device_fn,
                             factor=factor)

    def DisjointWindow(self, k: int, fn: Callable,
                       device_fn: Optional[Callable] = None,
                       partial_fn: Optional[Callable] = None) -> "DIA":
        """``partial_fn(start, items)`` additionally receives the
        trailing block of fewer than k items (reference:
        partial_window_function, api/window.hpp:389); passing it keeps
        the op on the host path (dynamic-length tail)."""
        from .ops import window as _w
        return _w.Window(self, k, fn, device_fn, disjoint=True,
                         partial_fn=partial_fn)

    def Concat(self, other: "DIA") -> "DIA":
        from .ops import concat as _c
        return _c.Concat(self, other)

    def Union(self, *others: "DIA") -> "DIA":
        from .ops import union as _u
        return _u.Union(self, *others)

    def Rebalance(self) -> "DIA":
        from .ops import rebalance as _rb
        return _rb.Rebalance(self)

    def Sample(self, k: int, seed: int = 0) -> "DIA":
        from .ops import sample as _sm
        return _sm.Sample(self, k, seed)

    # ------------------------------------------------------------------
    # consume control / materialization nodes
    # ------------------------------------------------------------------
    def ToHost(self) -> "DIA":
        """Explicitly demote to host item-list storage (logged)."""
        from .ops import lop_nodes
        return lop_nodes.to_host(self)

    def ToDevice(self) -> "DIA":
        """Explicitly promote host items to columnar device storage."""
        from .ops import lop_nodes
        return lop_nodes.to_device(self)

    def Keep(self, n: int = 1) -> "DIA":
        self.node.keep(n)
        return self

    def Cache(self) -> "DIA":
        from .ops import cache as _ca
        return _ca.Cache(self)

    def Collapse(self) -> "DIA":
        from .ops import cache as _ca
        return _ca.Collapse(self)

    def Checkpoint(self, name: Optional[str] = None) -> "DIA":
        """Materialize here and seal the result into a durable epoch
        (api/checkpoint.py) when ``THRILL_TPU_CKPT_DIR`` is set; a
        resumed run (``resume=True`` / ``THRILL_TPU_RESUME=1``) reloads
        the newest committed epoch and skips this node's entire
        upstream subgraph. Without a checkpoint dir this is a plain
        materialization barrier (Cache-like)."""
        from .checkpoint import make_checkpoint_node
        return make_checkpoint_node(self, name)

    def Execute(self) -> "DIA":
        self.node.materialize()
        return self

    def Dispose(self) -> None:
        self.node.dispose()

    def explain(self) -> str:
        """Annotated physical plan of THIS DIA's upstream subgraph:
        ops, fused segments, exchange strategy per shuffle edge, and
        every recorded decision with its reason and (post-run) audit
        verdict (common/decisions.py; ``ctx.explain()`` renders the
        whole Context). Purely observational — reads the decision
        ledger, changes no plan or state."""
        from ..common.decisions import render_plan
        nodes, stack = [], [self.node]
        seen = set()
        while stack:
            n = stack.pop()
            if n.id in seen:
                continue
            seen.add(n.id)
            nodes.append(n)
            stack.extend(p.node for p in n.parents)
        return render_plan(
            [{"id": n.id, "label": n.label, "state": n.state,
              "parents": [p.node.id for p in n.parents]}
             for n in nodes],
            self.context.decisions.snapshot(),
            W=self.context.num_workers,
            title=f"{self.node.label}#{self.node.id}")

    # ------------------------------------------------------------------
    # actions
    # ------------------------------------------------------------------
    def Size(self) -> int:
        from .ops import actions
        return actions.Size(self)

    # Future variants defer execution until .get() — reference:
    # api/action_node.hpp Future<T>. Creation reserves one consume-budget
    # unit so issue order (not get order) governs consumption: actions
    # run between issue and get cannot starve the future.
    def _future(self, thunk) -> "ActionFuture":
        from .future import ActionFuture
        self.node.keep(1)
        return ActionFuture(thunk)

    def SizeFuture(self):
        from .ops import actions
        return self._future(lambda: actions.Size(self))

    def AllGatherFuture(self):
        from .ops import actions
        return self._future(lambda: actions.AllGather(self))

    def SumFuture(self, fn: Callable = None, initial: Any = 0):
        from .ops import actions
        if fn is not None:
            return self._future(lambda: actions.AllReduce(self, fn, initial))
        return self._future(lambda: actions.Sum(self, initial))

    def AllGather(self) -> list:
        from .ops import actions
        return actions.AllGather(self)

    def AllGatherArrays(self):
        """Columnar AllGather: one pytree of stacked leaves [total, ...]
        — device arrays on the device path (no host sync; feed them to
        the next iteration's Bind directly)."""
        from .ops import actions
        return actions.AllGatherArrays(self)

    def Gather(self, root: int = 0) -> list:
        from .ops import actions
        return actions.Gather(self, root)

    def Print(self, label: str = "", limit: int = 100) -> "DIA":
        from .ops import actions
        actions.Print(self, label, limit)
        return self

    def AllReduce(self, fn: Callable, initial: Any = None) -> Any:
        from .ops import actions
        return actions.AllReduce(self, fn, initial)

    def Sum(self, fn: Callable = None, initial: Any = 0,
            device: bool = False) -> Any:
        """``device=True`` (device storage, no custom fn): the summed
        pytree stays on device — feed it back into a Bind without a
        host sync (zero-sync iterative loops)."""
        from .ops import actions
        if fn is not None:
            return actions.AllReduce(self, fn, initial)
        return actions.Sum(self, initial, device=device)

    def Min(self) -> Any:
        from .ops import actions
        return actions.MinMax(self, is_min=True)

    def Max(self) -> Any:
        from .ops import actions
        return actions.MinMax(self, is_min=False)

    def HyperLogLog(self, precision: int = 14) -> float:
        from .ops import hll
        return hll.HyperLogLog(self, precision)

    def WriteLines(self, path_pattern: str) -> None:
        from .ops import read_write
        read_write.WriteLines(self, path_pattern)

    def WriteLinesOne(self, path: str) -> None:
        from .ops import read_write
        read_write.WriteLinesOne(self, path)

    def WriteBinary(self, path_pattern: str) -> None:
        from .ops import read_write
        read_write.WriteBinary(self, path_pattern)


# ----------------------------------------------------------------------
# free functions over multiple DIAs
# ----------------------------------------------------------------------

def Zip(*dias: DIA, zip_fn: Callable = None, mode: str = "strict") -> DIA:
    from .ops import zip_ as _z
    return _z.Zip(list(dias), zip_fn, mode)

def ZipWindow(window: tuple, *dias: DIA, zip_fn: Callable = None,
              device_fn: Callable = None) -> DIA:
    from .ops import zip_ as _z
    return _z.ZipWindowOp(list(dias), window, zip_fn, device_fn)


def Merge(*dias: DIA, key_fn: Callable = None) -> DIA:
    from .ops import merge as _m
    return _m.Merge(list(dias), key_fn)


def Concat(*dias: DIA) -> DIA:
    from .ops import concat as _c
    return _c.ConcatMany(list(dias))


def Union(*dias: DIA) -> DIA:
    from .ops import union as _u
    return _u.UnionMany(list(dias))


def InnerJoin(left: DIA, right: DIA, left_key_fn: Callable,
              right_key_fn: Callable, join_fn: Callable,
              location_detection=None,
              out_size_hint=None, dense_right_index=None) -> DIA:
    """``location_detection`` (reference: LocationDetectionTag) prunes
    items whose key exists on only one side before the shuffle, on
    both the device path (presence-register filter) and the host path
    (Golomb fingerprint exchange). None — the default — defers to the
    plan-time cost model (core/preshuffle.py, forced either way with
    THRILL_TPU_LOCATION_DETECT=0/1); True/False force it per call.
    ``out_size_hint``: optional per-worker match-count upper bound —
    the device path then skips its blocking size sync (overflow raises
    at the next host fetch, never silently truncates).
    ``dense_right_index=n``: the right side is a dense index table
    (row at global position g has key g, n rows total) — the join runs
    as a pure device gather, no sort/exchange/sync at any W."""
    from .ops import join as _j
    return _j.InnerJoin(left, right, left_key_fn, right_key_fn, join_fn,
                        location_detection=location_detection,
                        out_size_hint=out_size_hint,
                        dense_right_index=dense_right_index)
