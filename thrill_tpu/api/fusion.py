"""Cross-op program stitching: fuse device DOp chains into one dispatch.

The function-stack machinery (api/stack.py) already fuses chained
Map/Filter/FlatMap lambdas into one traced program, but every device
DOp still issued its OWN jitted dispatch — and on a tunneled chip each
dispatch pays the link round trip (140.7 ms measured, BASELINE.md r5),
so a six-op pipeline paid six RTTs where one would do. This module is
the cross-op generalization of the stack: at stage-build time the pull
recursion assembles a :class:`FusionPlan` — a chain of traced
:class:`Segment`s over one (or, for Zip/Join heads, several) input
``DeviceShards`` — and the whole chain compiles ONCE via
``MeshExec.cached()`` under a composite plan key and dispatches through
ONE ``smap`` call.

Mechanics, mirroring the reference's template function stacks
(thrill/api/dia.hpp:358-387) one level up the operator hierarchy:

* A fusible DOp implements ``compute_plan()`` (api/dia_base.py): pull
  the parent as a plan, append its own traced segment, hand the plan
  on. A sole-consumer parent in state NEW *defers* — its program is
  traced into the consumer's dispatch instead of running on its own
  (``materialize_plan``); anything else materializes normally and
  becomes a plan *source*.
* Fusion barriers: all-to-all exchanges, host fallbacks, spills,
  actions, multi-consumer results (``Keep``), and any op without a
  traced segment. A barrier simply ends the chain — the plan executes
  and its output shards seed the next chain.
* State inside a stitched program is ``(tree, mask)`` exactly like the
  stack contract; the final program compacts valid rows once and
  returns device-resident counts. Cross-worker plan values that the
  legacy per-op programs fetched via host counts (ZipWithIndex offsets,
  Window halos) are computed IN-TRACE from collectives over the mask,
  so fused chains need no mid-chain host syncs at all.
* PR-1 failure semantics are preserved: the dispatch retries transient
  faults under the shared policy (the program is pure), every fused
  segment keeps a per-op fault site (``api.fuse.<OpLabel>``), and
  deferred validations (hinted-join overflow) attach to the fused
  program's OUTPUT — checks drain at the fused boundary, recovery
  re-dispatches the plan at the true capacity (lineage = the plan's
  immutable sources).

``THRILL_TPU_FUSE=0`` restores the exact per-op dispatch behavior
(every code path falls back to the pre-fusion implementations).
Observability: ``stats_fused_dispatches`` / ``stats_fused_ops`` on the
mesh, per-stage fused-op lists as ``event=fused_dispatch`` JSON lines,
both surfaced by ``ctx.overall_stats()`` and tools/json2profile.py.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..common import decisions as _decisions
from ..common import faults
from ..common import trace as _trace
from ..common.retry import default_policy
from ..data.shards import DeviceShards, HostShards, compact_valid
from ..parallel.mesh import AXIS
from .stack import (Stack, apply_stack_host_list, apply_stack_traced,
                    stack_bound_operands, stack_cache_token)


def enabled() -> bool:
    """THRILL_TPU_FUSE=0 restores per-op dispatches exactly."""
    return os.environ.get("THRILL_TPU_FUSE", "1") not in ("0", "off",
                                                          "false")


class TraceCtx:
    """Per-trace context handed to segment trace functions."""

    def __init__(self, W: int) -> None:
        self.W = W
        self.aux: dict = {}          # name -> per-worker scalar output

    @staticmethod
    def count(mask: jnp.ndarray) -> jnp.ndarray:
        return jnp.sum(mask.astype(jnp.int32))

    def exclusive_offset(self, mask: jnp.ndarray) -> jnp.ndarray:
        """Global item offset of this worker's valid items, computed
        in-trace (an all_gather of local counts — the fused analog of
        the host-counts prefix the legacy per-op programs uploaded)."""
        cnt = jnp.sum(mask.astype(jnp.int64))
        if self.W == 1:
            return jnp.int64(0)
        totals = lax.all_gather(cnt, AXIS)              # [W]
        widx = lax.axis_index(AXIS)
        return jnp.where(jnp.arange(self.W) < widx, totals, 0).sum()

    def emit_aux(self, name: str, value: jnp.ndarray) -> None:
        """Expose a per-worker SCALAR as an extra program output (e.g.
        a hinted join's true match totals for the deferred check)."""
        self.aux[name] = value


@dataclasses.dataclass
class Segment:
    """One fusible device-DOp phase, traceable into a stitched program.

    ``trace(fctx, tree, mask, bound)`` runs per worker inside shard_map
    and returns the new ``(tree, mask)``; collectives over AXIS are
    allowed. Head segments (multi-input ops) instead receive the list
    of source ``(tree, mask)`` states. ``bound`` carries the traced
    form of :attr:`bound` (runtime pytrees entering the program as
    replicated arguments — the Bind contract, so iterative re-binds
    never recompile).
    """

    label: str
    token: Tuple
    trace: Callable
    bound: Tuple = ()
    # output counts == input counts (all-map stacks, ZipWithIndex...):
    # lets the plan hand host-known counts through, like the legacy
    # apply_stack_device counts passthrough
    preserves_counts: bool = False
    # output already has all valid rows in a prefix (sorts): the final
    # compaction scatter is skipped
    already_compact: bool = False
    # host-known output counts this segment imposes (ReduceToIndex's
    # dense range sizes); replaces the plan's known counts
    sets_counts: Optional[np.ndarray] = None
    # multi-input head refit hook: rebuild this segment with a new
    # static output capacity (hinted-join overflow recovery)
    refit: Optional[Callable[[int], "Segment"]] = None
    # called by execute() with (plan, out_shards): attaches deferred
    # checks (hinted-join overflow) to the fused boundary
    finalize: Optional[Callable[["FusionPlan", DeviceShards], None]] = None
    dia_id: Optional[int] = None
    # every output row derives from exactly one input row (LOp stacks:
    # map/filter/flatmap — no collectives, no cross-row state), so the
    # memory-pressure ladder may re-plan the chain as row-range
    # sub-dispatches (mem/pressure.py rung 3) without changing results
    row_local: bool = False
    # may emit MORE rows than it consumes (flat_map): the admission
    # cost model must not bound this chain's output by its input bytes
    expands: bool = False
    # host-engine form of this segment (items list -> items list); the
    # ladder's LAST rung runs the chain through these when even split
    # chunks exhaust HBM
    host_apply: Optional[Callable] = None


def _src_sig(shards: DeviceShards, flat) -> Tuple:
    leaves, treedef = flat
    return (shards.cap, treedef,
            tuple((jnp.dtype(l.dtype), l.shape[2:]) for l in leaves))


class FusionPlan:
    """A pending chain of traced segments over source DeviceShards.

    ``head`` (optional) consumes ALL sources (Zip/Join); the tail
    segments are linear. ``stitchable=False`` marks a plain wrapper
    around already-computed shards (host storage, or fusion disabled)
    — ``finish()`` then just unwraps.
    """

    def __init__(self, mesh_exec, sources: List[Any],
                 head: Optional[Segment] = None,
                 stitchable: bool = True,
                 known_counts: Optional[np.ndarray] = None) -> None:
        self.mex = mesh_exec
        self.sources = sources
        self.head = head
        self.segments: List[Segment] = []
        # the THRILL_TPU_FUSE=0 escape hatch gates stitchability at the
        # root: every wrapped plan then refuses segments and each op
        # falls back to its per-op dispatch path exactly
        self.stitchable = stitchable and enabled() and all(
            isinstance(s, DeviceShards) for s in sources)
        if head is not None:
            known_counts = head.sets_counts if head.sets_counts is not None \
                else known_counts
        elif known_counts is None and self.stitchable \
                and len(sources) == 1:
            known_counts = sources[0]._counts_host
        self.known_counts = known_counts
        self.aux: dict = {}          # last execute()'s aux outputs
        self._no_finalize = False    # recovery re-runs skip finalizers
        self._no_split = False       # split-rung chunks must not re-split

    # -- building -------------------------------------------------------
    def append(self, seg: Segment) -> None:
        assert self.stitchable, "cannot extend a non-stitchable plan"
        self.segments.append(seg)
        if seg.sets_counts is not None:
            self.known_counts = seg.sets_counts
        elif not seg.preserves_counts:
            self.known_counts = None

    @property
    def all_segments(self) -> List[Segment]:
        return ([self.head] if self.head is not None else []) \
            + self.segments

    def counts_preserved(self) -> bool:
        """Every pending segment keeps per-worker counts unchanged."""
        return self.head is None and all(s.preserves_counts
                                         for s in self.segments)

    # -- execution ------------------------------------------------------
    def finish(self):
        """Produce this plan's shards (host or device) for NON-TRACED
        consumption. This is the fused boundary: deferred checks a
        segment attached (hinted-join overflow) drain HERE, before any
        consumer — exchange plan step, action egress, host fallback —
        can read the columns (the unfused pull's validate_pending
        invariant, dia_base.ParentLink._pull_unfused)."""
        if not self.stitchable:
            return self.sources[0]
        shards = self.execute()
        shards.validate_pending()
        return shards

    def execute(self) -> DeviceShards:
        mex = self.mex
        segs = self.all_segments
        if not segs:
            return self.sources[0]
        tr = getattr(mex, "tracer", None)
        if tr is None or not tr.enabled:
            return self._execute_inner()
        # one span per stitched launch: the chunk/dispatch spans nest
        # under it, so a Perfetto lane shows which ops each dispatch
        # carried (trace taxonomy: cat "fusion")
        with tr.span("fusion",
                     "+".join(s.label for s in segs)[:120],
                     ops=len(segs)):
            return self._execute_inner()

    def _execute_inner(self) -> DeviceShards:
        mex = self.mex
        srcs = self.sources
        segs = self.all_segments
        # exchange-boundary scheduling: a source produced by an
        # OPTIMISTIC exchange (data/exchange.py capacity-plan cache)
        # still owes its deferred capacity check — run it before this
        # program bakes the source columns. The check blocks only until
        # the exchange's FIRST chunk lands (the overflow flag rides
        # chunk 0), so the stitched program here is enqueued while the
        # remaining chunks' collectives are still in flight — that is
        # the chunk-pipeline overlap, with none of the wrong-data risk
        for s in srcs:
            s.validate_pending()
        src_flat = [jax.tree.flatten(s.tree) for s in srcs]
        sigs = tuple(_src_sig(s, f) for s, f in zip(srcs, src_flat))
        bound_flat = []
        bound_sig = []
        for seg in segs:
            bl, bt = jax.tree.flatten(seg.bound)
            bl = mex.asarray_blessed(bl)
            bound_flat.append((bl, bt))
            bound_sig.append((bt, tuple((jnp.dtype(l.dtype),
                                         tuple(l.shape)) for l in bl)))
        key = ("fused", sigs, tuple(s.token for s in segs),
               tuple(bound_sig))
        holder: dict = {}
        W = mex.num_workers
        caps = [s[0] for s in sigs]
        head, tail, last = self.head, self.segments, segs[-1]

        def build():
            def f(*args):
                nsrc = len(srcs)
                counts = args[:nsrc]
                pos = nsrc
                states = []
                for k, (leaves_, td_) in enumerate(src_flat):
                    ls = args[pos:pos + len(leaves_)]
                    pos += len(leaves_)
                    tree = jax.tree.unflatten(td_, [l[0] for l in ls])
                    mask = jnp.arange(caps[k]) < counts[k][0, 0]
                    states.append((tree, mask))
                bounds_t = []
                for bl, bt in bound_flat:
                    bs = args[pos:pos + len(bl)]
                    pos += len(bl)
                    bounds_t.append(jax.tree.unflatten(bt, list(bs)))
                fctx = TraceCtx(W)
                si = 0
                if head is not None:
                    tree, mask = head.trace(fctx, states, bounds_t[0])
                    si = 1
                else:
                    tree, mask = states[0]
                for seg, bound_t in zip(tail, bounds_t[si:]):
                    tree, mask = seg.trace(fctx, tree, mask, bound_t)
                if last.already_compact:
                    out_tree = tree
                    new_count = jnp.sum(mask.astype(jnp.int32))
                else:
                    out_tree, new_count = compact_valid(tree, mask)
                out_leaves, out_td = jax.tree.flatten(out_tree)
                holder["treedef"] = out_td
                holder["n_out"] = len(out_leaves)
                holder["aux_names"] = tuple(sorted(fctx.aux))
                return (new_count[None, None].astype(jnp.int32),
                        *[l[None] for l in out_leaves],
                        *[fctx.aux[n][None, None]
                          for n in holder["aux_names"]])

            nd = len(srcs) + sum(len(f_[0]) for f_ in src_flat)
            nb = sum(len(bf[0]) for bf in bound_flat)
            in_specs = (P(AXIS),) * nd + (P(),) * nb
            return mex.smap(f, nd + nb, in_specs=in_specs), holder

        fn, h = mex.cached(key, build)
        split = self._proactive_split(fn, srcs, segs)
        if split is not None:
            return split
        args = ([s.counts_device() for s in srcs]
                + [l for f_ in src_flat for l in f_[0]]
                + [l for bf in bound_flat for l in bf[0]])
        if faults.REGISTRY.active():
            # per-op fault sites survive fusion: each constituent op
            # keeps a named site, and a transient fire at the stage
            # boundary retries under the shared policy. The dispatch
            # itself stays OUTSIDE this policy — _CountedJit already
            # retries api.mesh.dispatch under its own run, and nesting
            # the two would multiply the documented attempt budget
            # (4 -> 16) for dispatch faults inside stitched programs,
            # silently diverging from the THRILL_TPU_FUSE=0 path
            def site_checks():
                for seg in segs:
                    faults.check("api.fuse." + seg.label,
                                 dia_id=seg.dia_id, fused_ops=len(segs))

            default_policy().run(site_checks, what="fuse.dispatch")
        pres = mex.pressure
        if pres is not None and pres.enabled \
                and not any(s.expands for s in segs) \
                and getattr(fn, "_out_bytes", None) is None:
            # cost-model hint from the plan's shapes: a non-expanding
            # chain produces at most its sources' rows, so the sources'
            # leaf bytes bound the stitched program's output. Expanding
            # chains (flat_map) skip the hint — the learned per-program
            # size / factor guess handles them instead of a systematic
            # underestimate on exactly the chains most likely to OOM.
            # Once the program LEARNED its measured output size (this
            # process, or imported from the plan store on a warm
            # restart), that exact number governs instead of this
            # upper bound — a fused ReduceByKey's output is usually
            # far smaller than its sources
            pres.hint_output_bytes(sum(
                int(getattr(l, "nbytes", 0) or 0)
                for s in srcs for l in jax.tree.leaves(s.tree)))
        # decision ledger: the fusion split point — which ops ride this
        # one dispatch, and what the cost model predicts its output
        # weighs (audited below against the measured output leaves)
        led = _decisions.ledger_of(mex)
        dec = None
        if led is not None:
            ops_label = "+".join(s.label for s in segs)[:80]
            pred = getattr(fn, "_out_bytes", None)
            why = "learned output size"
            if pred is None and not any(s.expands for s in segs):
                pred = sum(int(getattr(l, "nbytes", 0) or 0)
                           for s in srcs
                           for l in jax.tree.leaves(s.tree))
                why = "non-expanding chain: bounded by source bytes"
            elif pred is None:
                why = "expanding chain: no bound"
            dec = led.record("fusion", "fuse:" + ops_label, "fuse",
                             predicted=pred, reason=why,
                             ops=ops_label, n_ops=len(segs),
                             dia_ids=[s.dia_id for s in segs])
        try:
            out = fn(*args)
        except Exception as e:
            # rungs 3-4 of the memory-pressure ladder (mem/pressure.py):
            # the dispatch choke point already spilled and retried —
            # an OOM surfacing here means the segment chain itself does
            # not fit, so re-plan it as row-range sub-dispatches (or,
            # last, run the chain's host-engine form)
            from ..mem import pressure as _pressure
            if self._no_split or not (_pressure.retry_enabled()
                                      and _pressure.is_oom_error(e)):
                raise
            return self._execute_degraded(e)
        mex.stats_fused_dispatches += 1
        mex.stats_fused_ops += len(segs)
        ops = tuple(s.label for s in segs)
        counts_map = getattr(mex, "fused_stage_counts", None)
        if counts_map is not None:
            counts_map[ops] = counts_map.get(ops, 0) + 1
        log = getattr(mex, "logger", None)
        if log is not None and log.enabled:
            log.line(event="fused_dispatch", ops=list(ops),
                     dia_ids=[s.dia_id for s in segs])
        n_out = h["n_out"]
        if dec is not None:
            led.resolve(dec, sum(int(getattr(l, "nbytes", 0) or 0)
                                 for l in out[1:1 + n_out]))
        tree = jax.tree.unflatten(h["treedef"], list(out[1:1 + n_out]))
        self.aux = dict(zip(h["aux_names"], out[1 + n_out:]))
        if self.known_counts is not None:
            shards = DeviceShards(mex, tree, self.known_counts.copy())
        else:
            shards = DeviceShards(mex, tree, out[0])
        if not self._no_finalize:
            for seg in segs:
                if seg.finalize is not None:
                    seg.finalize(self, shards)
        return shards

    def reexecute(self, new_cap: int) -> DeviceShards:
        """Recovery re-dispatch with the head refit to ``new_cap``
        (hinted-join overflow): same sources, same tail, finalizers
        suppressed so checks are not re-attached."""
        assert self.head is not None and self.head.refit is not None
        plan = FusionPlan(self.mex, self.sources,
                          head=self.head.refit(new_cap))
        plan.segments = list(self.segments)
        plan.known_counts = None
        plan._no_finalize = True
        return plan.execute()

    def _proactive_split(self, fn, srcs, segs):
        """Planner-chosen fusion split point under the HBM admission
        estimate (api/planner.py): a row-local single-source chain
        whose estimated input+output bytes cannot fit under the
        watermark at ANY spill level executes as K row-range
        sub-dispatches up front — the same sub-plan the OOM ladder's
        rung 3 would reach, chosen BEFORE the dispatch instead of
        after a retry budget's worth of failed allocations. Returns
        the split result, or None (dispatch whole — the normal path).
        Eligibility mirrors ``_execute_degraded`` exactly: what the
        reactive rung could not split, the planner must not either."""
        from .planner import planner_of
        mex = self.mex
        pl = planner_of(mex)
        pres = mex.pressure
        if pl is None or pres is None or not pres.enabled \
                or self._no_split or self.head is not None \
                or len(srcs) != 1 \
                or getattr(mex, "num_processes", 1) > 1 \
                or not all(s.row_local and s.finalize is None
                           for s in segs):
            return None
        from ..mem import pressure as _pressure
        if not _pressure.retry_enabled():
            return None
        src = srcs[0]
        src_bytes = sum(int(getattr(l, "nbytes", 0) or 0)
                        for l in jax.tree.leaves(src.tree))
        out_est = getattr(fn, "_out_bytes", None)
        if out_est is None:
            out_est = (src_bytes if not any(s.expands for s in segs)
                       else int(src_bytes * pres.est_factor))
        est = src_bytes + int(out_est)
        k = pl.fusion_split_k(est, src.cap)
        if k is None:
            return None
        try:
            out = self._execute_split(src, k)
        except Exception as e:
            if not _pressure.is_oom_error(e):
                raise
            # even the split chunks exhausted HBM: dispatch whole and
            # let the reactive ladder (rungs 2-4) own the escalation —
            # the planner's choice is advisory, never the last word
            faults.note("recovery", what="mem.split_oom",
                        ops=[s.label for s in segs],
                        error=repr(e)[:200])
            return None
        # recorded AFTER the split succeeded: a fallback-to-whole must
        # not leave a ledger record claiming split:K for a dispatch
        # that actually ran whole (the whole path records its own
        # `fusion` decision). Deliberately NOT a planner_switches tick:
        # a chain that stays inadmissible re-splits on every execute —
        # that is a standing choice, not a re-optimization.
        ops_label = "+".join(s.label for s in segs)[:80]
        led = _decisions.ledger_of(mex)
        if led is not None:
            led.record("fusion_split", "fuse:" + ops_label,
                       f"split:{k}", predicted=est // k,
                       rejected=[("whole", est)],
                       reason="admission estimate exceeds the HBM "
                              "watermark at any spill level",
                       ops=ops_label, k=k,
                       dia_ids=[s.dia_id for s in segs])
        pres.segment_splits += 1
        faults.note("segment_split", k=k,
                    ops=[s.label for s in segs], cap=src.cap,
                    proactive=True)
        faults.note("recovery", what="mem.segment_split_proactive",
                    _quiet=True)
        _trace.instant_of(getattr(mex, "tracer", None), "mem",
                          "segment_split", k=k, proactive=True)
        return out

    # -- memory-pressure degradation (mem/pressure.py rungs 3-4) --------
    def _execute_degraded(self, exc: BaseException):
        """The stitched dispatch exhausted the OOM-retry budget:
        escalate. Rung 3 re-plans a row-local single-source chain as K
        row-range sub-dispatches (``event=segment_split`` — lineage-
        level like the hinted-join overflow re-run, never wrong data);
        rung 4 runs the chain's host-engine form. Multi-controller
        meshes re-raise: degradation is a per-process decision, and an
        asymmetric re-plan would desynchronize the collective
        schedule across controllers (same reasoning as the governor's
        multi-process spill guard)."""
        from ..mem import pressure as _pressure
        mex = self.mex
        segs = self.all_segments
        labels = [s.label for s in segs]
        if getattr(mex, "num_processes", 1) > 1 or self.head is not None \
                or len(self.sources) != 1:
            raise exc
        pres = _pressure._monitor_for(mex)
        src = self.sources[0]
        if all(s.row_local and s.finalize is None for s in segs):
            k = _pressure.split_k(src.cap)
            if src.cap > 1:
                try:
                    out = self._execute_split(src, k)
                except Exception as e2:
                    if not _pressure.is_oom_error(e2):
                        raise
                    faults.note("recovery", what="mem.split_oom",
                                ops=labels, error=repr(e2)[:200])
                else:
                    pres.segment_splits += 1
                    faults.note("segment_split", k=k, ops=labels,
                                cap=src.cap)
                    faults.note("recovery", what="mem.segment_split",
                                _quiet=True)
                    _trace.instant_of(getattr(mex, "tracer", None),
                                      "mem", "segment_split", k=k)
                    return out
        if all(s.host_apply is not None for s in segs):
            # last rung: the host engine (the reference's EM
            # degradation — slower, unbounded by HBM, bit-identical)
            pres.host_fallbacks += 1
            faults.note("recovery", what="mem.host_fallback",
                        ops=labels)
            _trace.instant_of(getattr(mex, "tracer", None), "mem",
                              "host_fallback", ops=len(labels))
            shards = src.to_host_shards(reason="memory_pressure")
            lists = shards.lists
            for seg in segs:
                lists = [seg.host_apply(items) for items in lists]
            return HostShards(shards.num_workers, lists)
        raise exc

    def _execute_split(self, src: DeviceShards, k: int) -> DeviceShards:
        """Run the (row-local) segment chain as ``k`` row-range
        sub-dispatches over ``common/partition.py`` bounds and
        reassemble per-worker results in chunk order — identical to
        the unsplit program because every output row derives from
        exactly one input row and chunk-then-compact preserves input
        order."""
        from ..common.partition import dense_range_bounds
        mex = self.mex
        bounds = dense_range_bounds(src.cap, k)
        counts = src.counts                 # host sync: degraded path
        parts: List[List[Any]] = [[] for _ in range(mex.num_workers)]
        for i in range(k):
            lo, hi = int(bounds[i]), int(bounds[i + 1])
            if hi <= lo:
                continue
            chunk_tree = jax.tree.map(lambda l: l[:, lo:hi], src.tree)
            chunk = DeviceShards(
                mex, chunk_tree,
                np.clip(counts - lo, 0, hi - lo).astype(np.int64))
            sub = FusionPlan(mex, [chunk])
            sub.segments = list(self.segments)
            sub.known_counts = None
            sub._no_finalize = True
            sub._no_split = True
            out_k = sub.execute()
            for w, t in enumerate(out_k.to_worker_arrays()):
                parts[w].append(t)
        per_worker = [jax.tree.map(
            lambda *ls: np.concatenate([np.asarray(l) for l in ls],
                                       axis=0), *p) for p in parts]
        return DeviceShards.from_worker_arrays(mex, per_worker)


def wrap(shards) -> FusionPlan:
    """Plan-shaped wrapper around computed shards (host or device)."""
    mex = getattr(shards, "mesh_exec", None)
    return FusionPlan(mex, [shards],
                      stitchable=isinstance(shards, DeviceShards))


def stack_segment(stack: Stack, dia_id: Optional[int] = None) -> Segment:
    """The LOp function stack as a fused segment (same traced math as
    api/device_exec.apply_stack_device, minus its own dispatch)."""
    bound = tuple(stack_bound_operands(stack))

    def trace(fctx, tree, mask, bound_t):
        return apply_stack_traced(tree, mask, stack,
                                  bound=list(bound_t) if bound_t
                                  else None)

    return Segment(label="Stack",
                   token=("stack", stack_cache_token(stack)),
                   trace=trace, bound=bound,
                   preserves_counts=all(op.kind == "map" for op in stack),
                   dia_id=dia_id, row_local=True,
                   expands=any(op.kind == "flat_map" for op in stack),
                   host_apply=lambda items, _s=stack:
                       apply_stack_host_list(items, _s))


def pull_plan(link, consume: bool = True) -> FusionPlan:
    """Pull a parent edge as a fusion plan.

    The fused counterpart of ``ParentLink.pull``: the parent either
    defers (its segments arrive pending in the plan) or materializes
    (its shards become the plan source, deferred validations drained at
    this boundary); the edge's LOp stack joins the chain as a segment.
    With fusion disabled this is exactly ``wrap(link.pull())``.
    """
    if not enabled():
        return wrap(link.pull(consume))
    res = link.node.materialize_plan(consume=consume)
    if isinstance(res, FusionPlan):
        plan = res
    elif isinstance(res, DeviceShards):
        # overflow checks drain at the fused boundary (the legacy
        # pull's validate_pending contract)
        res.validate_pending()
        plan = FusionPlan(res.mesh_exec, [res])
    else:
        plan = wrap(res)
    if link.stack:
        if plan.stitchable:
            plan.append(stack_segment(link.stack, dia_id=link.node.id))
        else:
            shards = plan.finish()
            if isinstance(shards, HostShards):
                shards = HostShards(shards.num_workers,
                                    [apply_stack_host_list(l, link.stack)
                                     for l in shards.lists])
            else:                      # pragma: no cover — defensive
                from .device_exec import apply_stack_device
                shards = apply_stack_device(shards, link.stack)
            plan = wrap(shards)
    return plan
