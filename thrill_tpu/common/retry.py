"""Bounded exponential backoff with full jitter — THE retry policy.

Like common/timeouts.py is the one distress-deadline policy, this is
the one transient-fault retry policy: tcp bootstrap dials, s3/hdfs/
posix ranged reads, multiplexer frame I/O and device dispatch all
retry through :class:`RetryPolicy` instead of hand-rolling loops, so
attempt budgets and backoff shape can never silently diverge between
layers.

Shape: attempt k sleeps ``uniform(0, min(max_delay, base * 2**k))`` —
"full jitter" (the AWS Architecture Blog analysis: equal-jitter and
no-jitter herd retries into synchronized spikes; full jitter spreads
them). Deterministic under test via an explicit ``seed``.

Classification is explicit and *permanent wins*: an exception listed
(or derived from a class listed) in ``permanent`` never retries even
if it also matches ``transient`` — a bad MAC is a ConnectionError, but
retrying authentication failures would turn a key mismatch into a
slow, noisy mystery. Injected faults (common/faults.py) carry their
class in ``.kind`` and are classified by it, whatever they subclass.

Env overrides (cluster-wide tuning without code changes):
``THRILL_TPU_RETRY_ATTEMPTS``, ``THRILL_TPU_RETRY_BASE_S``,
``THRILL_TPU_RETRY_MAX_S``; ``THRILL_TPU_RETRY=0`` disables retries
globally (every fault surfaces on first hit — chaos runs use it to
assert the *detection* half of the story).
"""

from __future__ import annotations

import dataclasses
import os
import random
import time
from typing import Any, Callable, Optional, Tuple

from . import faults


def _env_float(name: str, default: float) -> float:
    try:
        v = os.environ.get(name)
        return float(v) if v not in (None, "") else default
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        v = os.environ.get(name)
        return int(v) if v not in (None, "") else default
    except ValueError:
        return default


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Immutable retry policy; ``run()`` executes a callable under it."""

    max_attempts: int = 4           # total tries (1 = no retry)
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    transient: Tuple[type, ...] = (ConnectionError, TimeoutError,
                                   OSError)
    permanent: Tuple[type, ...] = ()

    def classify(self, exc: BaseException) -> str:
        """'transient' | 'permanent' — permanent wins ties.

        Deterministic OSError subclasses (missing file, permissions,
        wrong node type) are permanent even though OSError is in the
        default transient set: retrying them could never succeed and
        only delays + mislabels the real error."""
        from ..net import wire
        from ..net.group import ClusterAbort
        if isinstance(exc, (wire.AuthError, ClusterAbort,
                            FileNotFoundError, PermissionError,
                            IsADirectoryError, NotADirectoryError)
                      + tuple(self.permanent)):
            return faults.PERMANENT
        if isinstance(exc, faults.InjectedFault):
            return exc.kind          # injection declares its own class
        # object-store responses carry their status (vfs/object_store):
        # server-side failures and throttles are worth retrying, any
        # other 4xx is a deterministic request error
        status = getattr(exc, "http_status", None)
        if status is not None:
            return (faults.TRANSIENT
                    if status >= 500 or status in (408, 429)
                    else faults.PERMANENT)
        if isinstance(exc, tuple(self.transient)):
            return faults.TRANSIENT
        return faults.PERMANENT

    def delay(self, attempt: int, rng: random.Random) -> float:
        """Full-jitter backoff for ``attempt`` (0-based)."""
        cap = min(self.max_delay_s, self.base_delay_s * (2 ** attempt))
        return rng.uniform(0.0, cap)

    def run(self, fn: Callable[[], Any], *, what: str,
            seed: Optional[int] = None,
            sleep: Callable[[float], None] = time.sleep) -> Any:
        """Call ``fn()`` retrying transient failures with backoff.

        ``what`` names the operation in retry logs; ``seed`` pins the
        jitter stream (tests); ``sleep`` is injectable for zero-delay
        unit tests. The last failure re-raises unchanged, so callers'
        except clauses see the real error type.
        """
        attempts = self.max_attempts
        if os.environ.get("THRILL_TPU_RETRY", "1") == "0":
            attempts = 1
        rng = None                   # lazy: the happy path never pays
        for attempt in range(attempts):
            try:
                return fn()
            except BaseException as e:
                if (attempt + 1 >= attempts
                        or self.classify(e) != faults.TRANSIENT):
                    raise
                if rng is None:
                    rng = random.Random(seed if seed is not None
                                        else random.getrandbits(32))
                d = self.delay(attempt, rng)
                faults.note("retry", what=what, attempt=attempt + 1,
                            delay_s=round(d, 4), error=repr(e))
                sleep(d)
        raise AssertionError("unreachable")     # pragma: no cover


def default_policy(**overrides: Any) -> RetryPolicy:
    """Policy with env-tuned knobs; keyword args override per site."""
    kw = dict(
        max_attempts=_env_int("THRILL_TPU_RETRY_ATTEMPTS", 4),
        base_delay_s=_env_float("THRILL_TPU_RETRY_BASE_S", 0.05),
        max_delay_s=_env_float("THRILL_TPU_RETRY_MAX_S", 2.0),
    )
    kw.update(overrides)
    return RetryPolicy(**kw)
