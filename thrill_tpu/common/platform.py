"""Platform forcing helper for this image's axon-plugin quirks.

The image exports ``JAX_PLATFORMS=axon`` globally and the axon plugin
both ignores the env var for CPU selection and can hang PJRT client
init when its tunnel is unhealthy. ``force_cpu_platform()`` makes an
explicit CPU request robust; the private-API pieces are best-effort so
a jax upgrade degrades to the plain config update instead of crashing.
"""

from __future__ import annotations


def force_cpu_platform() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb
        _xb._backend_factories.pop("axon", None)
        # discovery at first backends() would re-register the plugin and
        # re-force jax_platforms
        _xb.discover_pjrt_plugins = lambda: None
    except Exception as e:  # private API drifted: warn, don't crash
        import sys
        print(f"thrill_tpu: CPU forcing is partial ({e!r}); if jax hangs "
              f"at device init, the accelerator plugin is the cause",
              file=sys.stderr)


def enable_cpu_multiprocess_collectives() -> bool:
    """Select the gloo CPU collectives backend, if this jax has it.

    Without an explicit CPU collectives implementation, a multi-process
    CPU mesh fails every cross-process program with "Multiprocess
    computations aren't implemented on the CPU backend" — jax does not
    pick gloo by itself.  Must run BEFORE the backend initializes (the
    multi-process entry point calls it ahead of
    ``jax.distributed.initialize``); only applies when the platform is
    (or is forced to) CPU, so TPU meshes are untouched.  Returns
    whether the option took, so callers can decide to skip rather than
    fail on jax builds that predate it."""
    import os

    import jax

    platforms = getattr(jax.config, "jax_platforms", None) \
        or os.environ.get("JAX_PLATFORMS", "")
    if "cpu" not in str(platforms):
        return False
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        return True
    except Exception:
        return False        # option or gloo absent: caller degrades


def has_ragged_all_to_all() -> bool:
    """Does this jax build export ``lax.ragged_all_to_all``?

    The single source of truth for the capability probe: the exchange
    planner's ragged path, the driver dryrun and every test skipif gate
    on THIS instead of hand-rolled ``hasattr`` copies (this container's
    jax/jaxlib predates the op entirely; execution is TPU-only even
    where the symbol exists)."""
    import jax
    return hasattr(jax.lax, "ragged_all_to_all")


def maybe_force_cpu_from_env() -> bool:
    """Apply force_cpu_platform iff the user explicitly asked for CPU.
    Returns whether it applied."""
    import os
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        force_cpu_platform()
        return True
    return False


def accelerator_healthy(timeout_s: float = 75.0) -> bool:
    """Probe the accelerator in a THROWAWAY subprocess with a timeout.

    The axon plugin can hang (not raise) at PJRT client init when its
    tunnel is wedged, so the probe must never run in the calling
    process. Shared by bench.py and the benchmarks/ scripts."""
    import subprocess
    import sys
    code = "import jax; assert jax.devices()[0].platform != 'cpu'"
    try:
        return subprocess.run([sys.executable, "-c", code],
                              capture_output=True,
                              timeout=timeout_s).returncode == 0
    except subprocess.SubprocessError:
        return False


def force_cpu_unless_accelerator(timeout_s: float = 75.0) -> None:
    """Benchmark-script platform policy: use the accelerator iff it
    answers the subprocess probe; otherwise force CPU so the run never
    wedges on the plugin."""
    import os
    if os.environ.get("AB_FORCE_TPU") == "1":
        return
    if maybe_force_cpu_from_env():     # explicit request: skip the probe
        return
    if not accelerator_healthy(timeout_s):
        force_cpu_platform()
