"""Live service metrics: a Prometheus text endpoint on a daemon thread.

``THRILL_TPU_METRICS_PORT=<port>`` makes every Context serve
``GET /metrics`` (any path, in fact) with the ``overall_stats()``
counters plus live service-plane gauges — queue depth, jobs in flight,
per-tenant HBM bytes — in Prometheus text exposition format, so an
always-on service (PR 9) can be scraped while it runs.

Scrape safety is the PR-9 local-view stats rule: the handler calls
``overall_stats(local_only=True)``, which NEVER enters the cross-host
all_gather — while the service dispatcher owns the mesh the non-root
ranks park in a recv on the same control plane, and a scrape-thread
collective would race them for frames. Each rank therefore serves its
own local view (scrape every rank and aggregate in the collector, the
standard Prometheus posture). Counter reads are plain attribute reads
under the GIL: a scrape never blocks or perturbs a running job.

Unset/invalid/0 port = completely off (zero threads, zero overhead).
Multi-process runs on one machine need distinct ports per rank.
"""

from __future__ import annotations

import http.server
import os
import re
import threading
import weakref
from typing import Optional

_BAD = re.compile(r"[^a-zA-Z0-9_]")


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _label(v) -> str:
    return str(v).replace("\\", "").replace('"', "").replace("\n", " ")


def render_prometheus(ctx) -> str:
    """One scrape's worth of metrics text for ``ctx`` (local view)."""
    lines = []

    def gauge(name: str, value, labels: str = "") -> None:
        lines.append(f"{name}{labels} {value}")

    try:
        stats = ctx.overall_stats(local_only=True)
    except Exception as e:  # a scrape must answer, never raise
        return f"# thrill_tpu stats unavailable: {e!r}\n"
    for k in sorted(stats):
        v = stats[k]
        name = "thrill_tpu_" + _BAD.sub("_", str(k))
        if _num(v):
            lines.append(f"# TYPE {name} gauge")
            gauge(name, v)
        elif isinstance(v, dict):
            sub = [(t, b) for t, b in sorted(v.items()) if _num(b)]
            if sub:
                lines.append(f"# TYPE {name} gauge")
                for t, b in sub:
                    gauge(name, b, f'{{key="{_label(t)}"}}')
    # live gauges beyond the end-of-job counters: what is queued /
    # running RIGHT NOW, and each tenant's current HBM footprint
    svc = getattr(ctx, "service", None)
    if svc is not None:
        depth = getattr(getattr(svc, "queue", None), "depth", 0)
        done = getattr(svc, "jobs_done", 0)
        sub = getattr(svc, "jobs_submitted", 0)
        lines.append("# TYPE thrill_tpu_queue_depth gauge")
        gauge("thrill_tpu_queue_depth", depth)
        lines.append("# TYPE thrill_tpu_jobs_in_flight gauge")
        gauge("thrill_tpu_jobs_in_flight", max(sub - done, 0))
        # per-tenant accept-to-result latency: a real Prometheus
        # histogram (cumulative le buckets at the fixed log2
        # boundaries the scheduler records into) — what the front-door
        # scrape will alert on
        hist = getattr(svc, "latency_histogram", None)
        hist = hist() if callable(hist) else {}
        if hist:
            name = "thrill_tpu_serve_latency_ms"
            lines.append(f"# TYPE {name} histogram")
            for tenant, (counts, count, sum_ms) in hist.items():
                t = _label(tenant)
                cum = 0
                for i, c in enumerate(counts[:-1]):
                    # the last bucket is the CLAMP bucket (latencies
                    # past every boundary): no finite le may claim to
                    # bound it — it folds into +Inf only
                    if not c:
                        continue
                    cum += c
                    gauge(f"{name}_bucket", cum,
                          f'{{tenant="{t}",le="{1 << i}"}}')
                gauge(f"{name}_bucket", count,
                      f'{{tenant="{t}",le="+Inf"}}')
                gauge(f"{name}_count", count, f'{{tenant="{t}"}}')
                gauge(f"{name}_sum", round(sum_ms, 3),
                      f'{{tenant="{t}"}}')
    # live dicts are snapshotted (dict(...)) before iterating: job
    # threads insert keys concurrently, and a scrape must answer, not
    # die on "dictionary changed size during iteration"
    hbm = getattr(ctx, "hbm", None)
    if hbm is not None:
        lines.append("# TYPE thrill_tpu_hbm_live_bytes gauge")
        gauge("thrill_tpu_hbm_live_bytes", hbm.mem.total)
        tb = dict(getattr(hbm, "tenant_bytes", None) or {})
        if tb:
            lines.append("# TYPE thrill_tpu_tenant_hbm_bytes gauge")
            for t, b in sorted(tb.items()):
                gauge("thrill_tpu_tenant_hbm_bytes", b,
                      f'{{tenant="{_label(t)}"}}')
    tr = getattr(ctx, "tracer", None)
    lanes = dict(tr.lane_counts) if tr is not None else {}
    if lanes:
        lines.append("# TYPE thrill_tpu_trace_spans gauge")
        for lane, n in sorted(lanes.items()):
            gauge("thrill_tpu_trace_spans", n,
                  f'{{lane="{_label(lane)}"}}')
    return "\n".join(lines) + "\n"


class MetricsServer:
    """ThreadingHTTPServer on a daemon thread, bound to the Context by
    weakref (a leaked server can outlive its Context without pinning
    the mesh)."""

    def __init__(self, ctx, port: int,
                 addr: Optional[str] = None) -> None:
        ctx_ref = weakref.ref(ctx)

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                c = ctx_ref()
                if c is None:
                    self.send_response(503)
                    self.end_headers()
                    return
                try:
                    body = render_prometheus(c).encode()
                except Exception as e:  # answer, never drop the conn
                    body = f"# thrill_tpu scrape failed: {e!r}\n" \
                        .encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; "
                                 "charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:
                pass                    # scrapes must not spam stderr

        if addr is None:
            # loopback by default: the endpoint exposes tenant names,
            # job counters and HBM footprints — a network-reachable
            # scrape target must be an EXPLICIT operator decision
            # (THRILL_TPU_METRICS_ADDR=0.0.0.0)
            addr = os.environ.get("THRILL_TPU_METRICS_ADDR",
                                  "127.0.0.1")
        self.httpd = http.server.ThreadingHTTPServer((addr, port),
                                                     Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever,
            name="thrill-tpu-metrics", daemon=True)
        self._thread.start()

    def close(self) -> None:
        try:
            self.httpd.shutdown()
            self.httpd.server_close()
        except Exception:
            pass


def maybe_start(ctx) -> Optional[MetricsServer]:
    """Start the endpoint when THRILL_TPU_METRICS_PORT names a port.
    A bind failure (port taken) is reported loudly and degrades to no
    endpoint — observability must never take down the job."""
    raw = os.environ.get("THRILL_TPU_METRICS_PORT", "")
    if not raw:
        return None
    try:
        port = int(raw)
    except ValueError:
        import sys
        print(f"thrill_tpu: bad THRILL_TPU_METRICS_PORT={raw!r}; "
              f"metrics endpoint disabled", file=sys.stderr)
        return None
    if port <= 0:
        return None
    try:
        return MetricsServer(ctx, port)
    except OSError as e:
        import sys
        print(f"thrill_tpu: metrics endpoint failed to bind port "
              f"{port}: {e}; disabled", file=sys.stderr)
        return None
