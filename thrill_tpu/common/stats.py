"""Timers, counters and scalar aggregates.

Equivalents of the reference's StatsTimer / StatsCounter
(reference: thrill/common/stats_timer.hpp, stats_counter.hpp) and
Aggregate (reference: thrill/common/aggregate.hpp): cheap instrumentation
that can be compiled out; here a module-level ``STATS_ENABLED`` flag makes
the instances no-ops when disabled.
"""

from __future__ import annotations

import math
import time

STATS_ENABLED = True


class StatsTimer:
    """Accumulating wall-clock timer, usable as a context manager."""

    __slots__ = ("seconds", "_start", "_running")

    def __init__(self, start: bool = False) -> None:
        self.seconds = 0.0
        self._start = 0.0
        self._running = False
        if start and STATS_ENABLED:
            self.start()

    def start(self) -> "StatsTimer":
        if STATS_ENABLED and not self._running:
            self._start = time.perf_counter()
            self._running = True
        return self

    def stop(self) -> "StatsTimer":
        if self._running:
            self.seconds += time.perf_counter() - self._start
            self._running = False
        return self

    def __enter__(self) -> "StatsTimer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def ms(self) -> float:
        return self.seconds * 1e3

    @property
    def us(self) -> float:
        return self.seconds * 1e6


class StatsCounter:
    __slots__ = ("value",)

    def __init__(self, value: int = 0) -> None:
        self.value = value

    def incr(self, delta: int = 1) -> None:
        if STATS_ENABLED:
            self.value += delta

    def __int__(self) -> int:
        return self.value


class Aggregate:
    """Running min/max/mean/stdev over added values.

    Reference: thrill/common/aggregate.hpp (used e.g. for per-worker
    balance statistics in SortNode, api/sort.hpp:656-662).
    """

    __slots__ = ("count", "total", "min", "max", "_mean", "_m2")

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._mean = 0.0
        self._m2 = 0.0

    def add(self, x: float) -> "Aggregate":
        x = float(x)
        self.count += 1
        self.total += x
        self.min = min(self.min, x)
        self.max = max(self.max, x)
        d = x - self._mean
        self._mean += d / self.count
        self._m2 += d * (x - self._mean)
        return self

    @property
    def mean(self) -> float:
        return self._mean

    @property
    def stdev(self) -> float:
        if self.count < 2:
            return 0.0
        return math.sqrt(self._m2 / (self.count - 1))

    def __iadd__(self, other: "Aggregate") -> "Aggregate":
        if other.count:
            new_count = self.count + other.count
            delta = other._mean - self._mean
            self._m2 += other._m2 + delta * delta * self.count * other.count / new_count
            self._mean = (self._mean * self.count + other._mean * other.count) / new_count
            self.count = new_count
            self.total += other.total
            self.min = min(self.min, other.min)
            self.max = max(self.max, other.max)
        return self
