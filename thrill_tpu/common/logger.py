"""Structured JSON event logging.

Equivalent of the reference's JsonLogger/JsonLine
(reference: thrill/common/json_logger.hpp:69,119): every Context and DIA
node can emit timestamped JSON events (node creation, stage execution,
push-data timing, profile samples) into a per-host JSON-lines file, which
``tools/json2profile.py`` renders into an HTML timeline report.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Optional


class JsonLogger:
    """Append-only JSON-lines event log.

    Thread-safe; each `line()` call emits one JSON object with a
    microsecond timestamp ``ts`` and any caller-supplied fields. Loggers
    can be chained: child loggers inherit common fields from the parent
    (like the reference's JsonLogger(parent, key, value) constructor).
    """

    def __init__(self, path: Optional[str] = None,
                 parent: Optional["JsonLogger"] = None,
                 **common: Any) -> None:
        self.parent = parent
        self.common = dict(parent.common) if parent else {}
        self.common.update(common)
        if parent is not None:
            self._file = parent._file
            self._lock = parent._lock
            self._wall0 = parent._wall0
            self._perf0 = parent._perf0
        else:
            self._lock = threading.Lock()
            self._file = open(path, "a", buffering=1) if path else None
            # (wall, monotonic) anchor pair: event timestamps derive
            # from perf_counter deltas off this one wall-clock read, so
            # NTP steps / wall-clock drift mid-run cannot skew a
            # multi-host merge in json2profile (events within one log
            # are strictly ordered by real elapsed time). Field name
            # and units ("ts", microseconds) are unchanged, so old
            # logs still render.
            self._wall0 = time.time()
            self._perf0 = time.perf_counter()

    @property
    def enabled(self) -> bool:
        return self._file is not None and not self._file.closed

    def now_us(self) -> int:
        """Current event timestamp: the construction-time wall anchor
        plus the monotonic delta since (shared by child loggers and
        the tracing spine, common/trace.py)."""
        return int((self._wall0
                    + (time.perf_counter() - self._perf0)) * 1e6)

    def line(self, **fields: Any) -> None:
        if self._file is None or self._file.closed:
            return
        rec = {"ts": self.now_us()}
        rec.update(self.common)
        rec.update(fields)
        with self._lock:
            self._file.write(json.dumps(rec, default=_json_default) + "\n")

    def close(self) -> None:
        if self._file is not None and self.parent is None:
            self._file.close()
        self._file = None


def _json_default(o: Any) -> Any:
    try:
        import numpy as np
        if isinstance(o, np.integer):
            return int(o)
        if isinstance(o, np.floating):
            return float(o)
        if isinstance(o, np.ndarray):
            return o.tolist()
    except ImportError:
        pass
    return str(o)


def default_log_path(pattern: Optional[str], host_rank: int) -> Optional[str]:
    """Expand a THRILL_TPU_LOG pattern to a per-host path.

    Mirrors the reference's per-host log naming
    (reference: thrill/api/context.cpp:1154-1174).
    """
    if not pattern:
        return None
    if "{}" in pattern:
        return pattern.format(host_rank)
    base, ext = os.path.splitext(pattern)
    return f"{base}-host{host_rank}{ext or '.json'}"
