"""Periodic runtime profiling into the JSON event log.

Equivalent of the reference's ProfileThread + LinuxProcStatsProfiler
(reference: thrill/common/profile_thread.hpp:32,
linux_proc_stats.cpp — CPU/mem/net sampled every 500ms into the
JsonLogger) plus TPU-specific device memory stats from PJRT.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from .logger import JsonLogger


def _read_proc_stat():
    try:
        with open("/proc/stat") as f:
            parts = f.readline().split()
        vals = [int(x) for x in parts[1:8]]
        idle = vals[3] + vals[4]
        total = sum(vals)
        return total, idle
    except (OSError, ValueError, IndexError):
        return None


def _read_meminfo():
    try:
        out = {}
        with open("/proc/meminfo") as f:
            for line in f:
                k, _, rest = line.partition(":")
                if k in ("MemTotal", "MemAvailable"):
                    out[k] = int(rest.split()[0]) * 1024
        return out
    except (OSError, ValueError):
        return {}


def _device_memory_stats():
    try:
        import jax
        dev = jax.devices()[0]
        stats = dev.memory_stats()
        if stats:
            return {"bytes_in_use": stats.get("bytes_in_use"),
                    "bytes_limit": stats.get("bytes_limit")}
    except Exception:
        pass
    return {}


class ProfileThread:
    """Samples host CPU/RAM and device HBM every ``interval`` seconds."""

    def __init__(self, logger: JsonLogger, interval: float = 0.5) -> None:
        self.logger = logger
        self.interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_cpu = None

    def start(self) -> "ProfileThread":
        if self._thread is None and self.logger.enabled:
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self._sample()

    def _sample(self) -> None:
        fields = {"event": "profile"}
        cpu = _read_proc_stat()
        if cpu and self._last_cpu:
            dt = cpu[0] - self._last_cpu[0]
            didle = cpu[1] - self._last_cpu[1]
            if dt > 0:
                fields["cpu_util"] = round(1.0 - didle / dt, 4)
        self._last_cpu = cpu
        mem = _read_meminfo()
        if mem:
            fields["host_mem_total"] = mem.get("MemTotal")
            fields["host_mem_available"] = mem.get("MemAvailable")
        fields.update(_device_memory_stats())
        self.logger.line(**fields)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
