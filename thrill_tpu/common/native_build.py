"""Shared build-from-source loader for the native C++ components.

One artifact lifecycle for every native library (block store,
dispatcher, ...): the output path embeds the SHA256 of the source file,
so a stale or foreign binary (wrong hash name) is never loaded — it is
rebuilt from the reviewed source instead. No prebuilt binaries ship in
the repo (native/build/ is gitignored). Builds land through a
tmp+rename so concurrent builders race safely, and stale hash-named
artifacts from earlier source versions are garbage-collected.

Callers attach their own ctypes signatures to the returned CDLL.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

NATIVE_DIR = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", "native"))


def build_and_load(source_name: str,
                   extra_flags: tuple = ()) -> Optional[ctypes.CDLL]:
    """Compile ``native/<source_name>`` (if needed) and dlopen it.

    Returns None when the toolchain is unavailable or the build fails;
    callers fall back to their pure-Python engines.
    """
    src = os.path.join(NATIVE_DIR, source_name)
    stem = os.path.splitext(source_name)[0]
    try:
        import hashlib
        with open(src, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
        build_dir = os.path.join(NATIVE_DIR, "build")
        out = os.path.join(build_dir, f"lib{stem}-{digest}.so")
        if not os.path.exists(out):
            os.makedirs(build_dir, exist_ok=True)
            tmp = out + f".tmp.{os.getpid()}"
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                 "-pthread", *extra_flags, src, "-o", tmp],
                check=True, capture_output=True, timeout=120)
            os.replace(tmp, out)  # atomic vs concurrent builders
            for name in os.listdir(build_dir):
                if (name.startswith(f"lib{stem}-") and name.endswith(".so")
                        and os.path.join(build_dir, name) != out):
                    try:
                        os.unlink(os.path.join(build_dir, name))
                    except OSError:
                        pass
        return ctypes.CDLL(out)
    except (OSError, subprocess.SubprocessError):
        return None
