"""Process-wide out-of-core I/O counters: the overlap ledger.

The storage tier (vfs prefetching readers, write-behind spill, the
double-buffered HBM restore) runs its I/O on background threads so the
device/compute thread never idles on disk. This module is the single
accounting point those threads share, so ``ctx.overall_stats()`` and
the bench em lane can report the STRUCTURE of the overlap — how much
background I/O ran, and how much of it the foreground actually had to
wait for — instead of inferring it from noisy totals:

* ``prefetch_hits`` / ``prefetch_misses`` — a consumer needing the
  next block found it already resident (hit) or had to block on the
  background reader (miss).
* ``io_wait_s``  — foreground seconds spent blocked on background I/O
  (readahead queue empty, write-behind queue full, flush barriers).
* ``io_busy_s``  — seconds background threads spent inside read/write
  calls. ``overlap_frac() = 1 - io_wait_s / io_busy_s`` is the
  fraction of I/O time hidden behind compute (1.0 = fully overlapped,
  0.0 = the blocking ladder this tier replaced).
* ``writeback_bytes`` / ``writeback_queue_peak`` — bytes flushed
  through write-behind writers and the deepest their bounded queues
  ever got.
* ``restore_overlaps`` — spill/checkpoint restores that ran with the
  next block's read in flight behind the current upload.

Counters are process-global (the threads have no Context handle);
``Context`` snapshots them at construction and reports deltas, the
same baseline pattern the fault registry uses.
"""

from __future__ import annotations

import threading

#: ``spill_runs`` (sorted runs handed to the write-behind spiller),
#: ``prefetch_submits`` (block-readahead jobs actually submitted to a
#: pool) and ``records_blocks`` (columnar blocks the native record
#: format encoded) are DETERMINISTIC for a fixed program — the perf
#: sentinel's em_sort contract compares them exactly, so a silent
#: fallback to the pickle spill path fails a counter diff instead of
#: hiding in wall-clock noise (ISSUE 15).
#: ``remote_gets`` / ``remote_puts`` (object-store requests issued by
#: vfs/object_store) and ``runs_reused`` (spilled runs rebuilt from
#: committed manifests instead of re-sorted, core/em_runs) are likewise
#: exact for a fixed program — a silent fallback to whole-file reads or
#: a broken run manifest fails a sentinel counter diff (ISSUE 17).
_COUNTERS = ("prefetch_hits", "prefetch_misses", "io_wait_s",
             "io_busy_s", "writeback_bytes", "restore_overlaps",
             "spill_runs", "prefetch_submits", "records_blocks",
             "remote_gets", "remote_puts", "runs_reused")


class IoStats:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.prefetch_hits = 0
        self.prefetch_misses = 0
        self.io_wait_s = 0.0
        self.io_busy_s = 0.0
        self.writeback_bytes = 0
        self.writeback_queue_peak = 0
        self.restore_overlaps = 0
        self.spill_runs = 0
        self.prefetch_submits = 0
        self.records_blocks = 0
        self.remote_gets = 0
        self.remote_puts = 0
        self.runs_reused = 0

    def add(self, **kv) -> None:
        with self._lock:
            for k, v in kv.items():
                setattr(self, k, getattr(self, k) + v)

    def note_queue_depth(self, depth: int) -> None:
        with self._lock:
            if depth > self.writeback_queue_peak:
                self.writeback_queue_peak = depth

    def snapshot(self) -> dict:
        with self._lock:
            out = {k: getattr(self, k) for k in _COUNTERS}
            out["writeback_queue_peak"] = self.writeback_queue_peak
            return out

    @staticmethod
    def delta(now: dict, base: dict) -> dict:
        """Per-Context view: counters since ``base``; the queue peak is
        a high-water mark, not a flow, so it reports raw."""
        out = {k: now[k] - base.get(k, 0) for k in _COUNTERS}
        out["io_wait_s"] = round(out["io_wait_s"], 4)
        out["io_busy_s"] = round(out["io_busy_s"], 4)
        out["writeback_queue_peak"] = now["writeback_queue_peak"]
        return out

    def reset(self) -> None:
        """Forget everything (tests)."""
        with self._lock:
            self.prefetch_hits = self.prefetch_misses = 0
            self.io_wait_s = self.io_busy_s = 0.0
            self.writeback_bytes = self.writeback_queue_peak = 0
            self.restore_overlaps = 0
            self.spill_runs = self.prefetch_submits = 0
            self.records_blocks = 0
            self.remote_gets = self.remote_puts = 0
            self.runs_reused = 0


def overlap_frac(stats: dict) -> float:
    """Fraction of background-I/O busy time the foreground did NOT
    wait for, clamped to [0, 1]; 0.0 when no background I/O ran."""
    busy = stats.get("io_busy_s", 0.0)
    if busy <= 0:
        return 0.0
    return max(0.0, min(1.0, 1.0 - stats.get("io_wait_s", 0.0) / busy))


def hit_rate(stats: dict) -> float:
    """Prefetch hit fraction; 0.0 with no prefetch consumption."""
    n = stats.get("prefetch_hits", 0) + stats.get("prefetch_misses", 0)
    return (stats.get("prefetch_hits", 0) / n) if n else 0.0


#: process-wide ledger: background reader/writer threads add here,
#: Context.overall_stats() reads deltas against its construction base
IO = IoStats()
