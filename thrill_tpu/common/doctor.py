"""Performance doctor: where did the time go, and who is to blame?

The trace spine (PR 10) records WHAT happened and the decision ledger
(PR 11) records WHAT WAS CHOSEN; this module is the diagnosis layer on
top — three deterministic-ish attributions every serving stack needs
before it can be tuned:

* **Collective wait attribution** — every blocking receive at a host
  collective (net/group.py) and every exchange barrier (the host plan
  sync in data/exchange.py, the per-peer frame receives in
  data/multiplexer.py) records how long the caller was BLOCKED and on
  WHOM. Per-peer totals are per-peer *arrival deltas*: the rank the
  cluster keeps waiting on is the straggler, and ``straggler_scores``
  ranks it by seconds of other ranks' time it burned. The total
  decomposes in ``overall_stats()``:

  - ``wait_net_s``      — blocked in host-group collectives,
  - ``wait_exchange_s`` — blocked at exchange barriers (plan syncs,
    deferred capacity checks, host frame receives),
  - ``wait_io_s``       — the portion that coincided with background
    I/O being busy locally (common/iostats.py ``io_busy_s`` sampled
    around each blocked window): time the storage tier, not a peer,
    is to blame for,
  - ``wait_skew_s``     — the unexplained remainder: the late peer's
    compute skew (or net transit — locally indistinguishable, and
    stated so).

* **Partition-skew attribution** — every exchange already computes the
  [W, W] send matrix; the doctor folds each site's per-worker receive
  rows into a running histogram and a hot-slot verdict
  (``max/mean >= THRILL_TPU_SKEW_HOT``, default 3.0). Surfaced as
  ``skew_ratio`` in ``overall_stats()``, a skew lane in json2profile,
  ``kind=skew`` instants on the trace's plan lane, and a ``skew``
  decision record so ``ctx.explain()`` can say "this join is 6x hot
  on worker 2".

* **Cross-rank critical path** — a post-run pass over the span ring
  (or offline over merged ``event=span`` logs,
  tools/doctor_report.py) rebuilds the span forest from parent ids,
  computes per-span EXCLUSIVE time (duration not covered by child
  spans), walks the latest-finishing child chain from the
  longest-running root, and names the top-K edges by exclusive time —
  the ``job -> exchange -> dispatch`` chain that actually bounded the
  run.

Overhead contract: ``THRILL_TPU_DOCTOR=0`` constructs NO Doctor — the
collective choke points pay one attribute read plus one predicate and
allocate nothing (pinned via :data:`RECORDS` in
tests/common/test_doctor.py). Wait records are plain float adds under
one lock; skew records run only where a send matrix was already
fetched to the host.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

#: wait records ever taken in this process — the THRILL_TPU_DOCTOR=0
#: no-op pin asserts this stays flat across a full pipeline
RECORDS = 0


def doctor_enabled() -> bool:
    """THRILL_TPU_DOCTOR=0 disables the doctor everywhere (read once
    per Context, at construction)."""
    from .config import _env_flag
    return _env_flag("THRILL_TPU_DOCTOR", True)


def skew_hot_ratio() -> float:
    """Hot-slot verdict threshold (max/mean receive rows per exchange
    site): THRILL_TPU_SKEW_HOT, default 3.0."""
    import os
    try:
        v = float(os.environ.get("THRILL_TPU_SKEW_HOT", "3.0"))
    except ValueError:
        return 3.0
    return v if v > 1.0 else 3.0


class Doctor:
    """Per-Context wait/skew ledger.

    Attached as ``ctx.doctor`` / ``mesh_exec.doctor`` /
    ``net.group.doctor`` so every choke point reaches it in one
    attribute read; a None attribute (THRILL_TPU_DOCTOR=0) makes every
    guarded site skip recording entirely."""

    def __init__(self, rank: int = 0) -> None:
        self.rank = rank
        self._lock = threading.Lock()
        # seconds this rank spent blocked waiting for each peer's frame
        self.wait_by_peer: Dict[int, float] = {}
        # seconds blocked per site ("all_reduce", "xchg.plan_sync"...)
        self.wait_by_site: Dict[str, float] = {}
        self.wait_net_s = 0.0        # host-group collective lane
        self.wait_exchange_s = 0.0   # exchange-barrier lane
        self.wait_io_s = 0.0         # overlapped with local bg I/O
        # per-exchange-site skew state:
        # site -> {"ratio": max seen, "worker": hot worker at max,
        #          "rows": recv rows at max, "exchanges": count,
        #          "hot": verdict, "reported": ratio last put in the
        #          decision ledger}
        self.skew_by_site: Dict[str, dict] = {}
        self._hot_thresh = skew_hot_ratio()

    # -- collective wait attribution ------------------------------------

    def record_wait(self, site: str, peer: Optional[int],
                    wait_s: float, lane: Optional[str] = None,
                    io_s: float = 0.0) -> None:
        """One blocked window: ``wait_s`` seconds at ``site`` waiting
        on ``peer`` (None when the wait has no single peer — a device
        plan sync). ``io_s`` is the background-I/O busy time that
        elapsed DURING the window (callers sample iostats around the
        block); it caps the I/O attribution. ``lane`` defaults by
        site name: exchange-barrier sites (``xchg.*``,
        ``host_exchange``) land on the exchange lane, everything else
        on the net lane."""
        global RECORDS
        RECORDS += 1
        if wait_s <= 0:
            return
        if lane is None:
            lane = ("exchange"
                    if site.startswith(("xchg", "host_exchange"))
                    else "net")
        io = min(max(io_s, 0.0), wait_s)
        with self._lock:
            if peer is not None:
                self.wait_by_peer[peer] = \
                    self.wait_by_peer.get(peer, 0.0) + wait_s
            self.wait_by_site[site] = \
                self.wait_by_site.get(site, 0.0) + wait_s
            if lane == "exchange":
                self.wait_exchange_s += wait_s
            else:
                self.wait_net_s += wait_s
            self.wait_io_s += io

    @property
    def collective_wait_s(self) -> float:
        return self.wait_net_s + self.wait_exchange_s

    def straggler_scores(self) -> Dict[int, float]:
        """Per-peer arrival deltas: seconds of blocked time beyond the
        FASTEST peer's — the peer everyone arrives after scores 0, the
        straggler scores what it cost. With one peer the delta is the
        raw wait (nothing to subtract against)."""
        with self._lock:
            waits = dict(self.wait_by_peer)
        if not waits:
            return {}
        if len(waits) == 1:
            return waits
        floor = min(waits.values())
        return {p: w - floor for p, w in waits.items()}

    def straggler_rank(self) -> Optional[int]:
        scores = self.straggler_scores()
        if not scores or max(scores.values()) <= 0:
            return None
        return max(sorted(scores), key=lambda p: scores[p])

    # -- partition-skew attribution -------------------------------------

    def record_exchange(self, site: str, recv_rows: np.ndarray,
                        item_bytes: int, tracer=None,
                        ledger=None) -> Optional[tuple]:
        """Fold one exchange's per-worker receive rows into the site's
        skew state; returns THIS exchange's ``(ratio, hot_worker,
        hot_rows)`` (the caller's log-line fields — one computation,
        here). Emits the ``kind=skew`` plan-lane instant + the
        ``skew`` decision record on the FIRST hot verdict per site
        (and again when the ratio doubles past the last report — a
        loop must not spam one record per iteration)."""
        rows = np.asarray(recv_rows, dtype=np.int64)
        total = int(rows.sum())
        if rows.size == 0 or total <= 0:
            return None
        mean = total / rows.size
        worker = int(rows.argmax())
        ratio = float(rows[worker] / mean) if mean > 0 else 1.0
        with self._lock:
            st = self.skew_by_site.get(site)
            if st is None:
                st = self.skew_by_site[site] = {
                    "ratio": 0.0, "worker": worker, "rows": 0,
                    "bytes": 0, "exchanges": 0, "hot": False,
                    "reported": 0.0}
            st["exchanges"] += 1
            st["bytes"] += total * max(item_bytes, 0)
            if ratio > st["ratio"]:
                st["ratio"] = ratio
                st["worker"] = worker
                st["rows"] = int(rows[worker])
            hot = st["ratio"] >= self._hot_thresh
            st["hot"] = hot
            report = hot and (st["reported"] == 0.0
                              or st["ratio"] >= 2 * st["reported"])
            if report:
                st["reported"] = st["ratio"]
            snap = dict(st)
        if report:
            if tracer is not None and tracer.enabled:
                # kind=skew instant on the plan lane: the trace shows
                # WHERE in the timeline the hot slot was detected
                tracer.instant("plan", "skew", kind="skew", site=site,
                               ratio=round(snap["ratio"], 2),
                               worker=snap["worker"])
            if ledger is not None and getattr(ledger, "enabled", False):
                ledger.record(
                    "skew", site, f"worker {snap['worker']}",
                    predicted=snap["rows"],
                    reason=(f"hot slot: {snap['ratio']:.1f}x the mean "
                            f"receive volume lands on worker "
                            f"{snap['worker']}"),
                    ratio=round(snap["ratio"], 2))
        return (ratio, worker, int(rows[worker]))

    def max_skew_ratio(self) -> float:
        with self._lock:
            if not self.skew_by_site:
                return 0.0
            return max(st["ratio"] for st in self.skew_by_site.values())

    def hot_sites(self) -> List[dict]:
        with self._lock:
            return sorted(
                ({"site": s, **st}
                 for s, st in self.skew_by_site.items() if st["hot"]),
                key=lambda d: -d["ratio"])

    # -- reporting -------------------------------------------------------

    def stats(self) -> dict:
        """The overall_stats() contribution (always present; zeros on
        an idle doctor). ``wait_skew_s`` is the unexplained remainder:
        peer compute skew or net transit, attributed to the peer."""
        with self._lock:
            total = self.wait_net_s + self.wait_exchange_s
            out = {
                "collective_wait_s": round(total, 4),
                "wait_net_s": round(self.wait_net_s, 4),
                "wait_exchange_s": round(self.wait_exchange_s, 4),
                "wait_io_s": round(self.wait_io_s, 4),
                "wait_skew_s": round(max(total - self.wait_io_s, 0.0),
                                     4),
                "straggler_waits": {
                    str(p): round(w, 4)
                    for p, w in sorted(self.wait_by_peer.items())},
            }
        out["skew_ratio"] = round(self.max_skew_ratio(), 3)
        return out

    def report(self, ring=None, k: int = 5) -> dict:
        """The full diagnosis: stats + per-site tables + the critical
        path over ``ring`` (an iterable of span record dicts — the
        tracer's flight-recorder ring, or records loaded from logs)."""
        out = self.stats()
        out["straggler_rank"] = self.straggler_rank()
        out["straggler_scores"] = {
            str(p): round(s, 4)
            for p, s in sorted(self.straggler_scores().items())}
        with self._lock:
            out["wait_by_site"] = {
                s: round(w, 4)
                for s, w in sorted(self.wait_by_site.items(),
                                   key=lambda kv: -kv[1])}
            out["skew_sites"] = sorted(
                ({"site": s, **{k2: (round(v, 3)
                                     if isinstance(v, float) else v)
                                for k2, v in st.items()}}
                 for s, st in self.skew_by_site.items()),
                key=lambda d: -d["ratio"])
        if ring is not None:
            out["critical_path"] = critical_path(list(ring), k=k)
        return out


def fold_skew_sites(events) -> Dict[str, dict]:
    """Per-site skew state folded from ``event=exchange`` log lines —
    the offline twin of :meth:`Doctor.record_exchange`'s live fold,
    shared by tools/doctor_report.py and tools/json2profile.py so the
    two renderers cannot drift. Only lines carrying ``skew_ratio``
    participate; ``rows`` is the hot worker's diagonal-included
    receive total (``hot_rows`` — the figure the ratio was computed
    from)."""
    hot = skew_hot_ratio()
    sites: Dict[str, dict] = {}
    for e in events:
        if e.get("event") != "exchange" \
                or e.get("skew_ratio") is None:
            continue
        site = str(e.get("site") or "xchg:?")
        st = sites.setdefault(site, {"ratio": 0.0, "worker": 0,
                                     "rows": 0, "bytes": 0,
                                     "items": 0, "exchanges": 0,
                                     "hot": False})
        st["exchanges"] += 1
        st["bytes"] += int(e.get("bytes", 0) or 0)
        st["items"] += int(e.get("items", 0) or 0)
        try:
            ratio = float(e["skew_ratio"])
        except (TypeError, ValueError):
            continue
        if ratio > st["ratio"]:
            st["ratio"] = ratio
            st["worker"] = int(e.get("hot_worker", 0) or 0)
            st["rows"] = int(e.get("hot_rows", 0) or 0)
        st["hot"] = st["ratio"] >= hot
    return sites


# ----------------------------------------------------------------------
# cross-rank critical path over span records
# ----------------------------------------------------------------------

def _span_key(rec: dict) -> tuple:
    """Spans are unique per (rank, trace, span id) — merged multi-rank
    logs reuse span ids across ranks."""
    return (rec.get("rank", 0), rec.get("trace"), rec.get("span"))


def critical_path(records: List[dict], k: int = 5) -> List[dict]:
    """Top-``k`` edges by exclusive time along the critical path.

    ``records`` are span record dicts (``event=span`` — the tracer's
    ring entries or log lines; non-span records are ignored). The
    forest is rebuilt from parent ids per rank; exclusive time is a
    span's duration minus its children's (clamped at 0 — async
    children can outlive the parent window). The critical path starts
    at the longest root span across ALL ranks (multi-rank logs merged
    by the caller: whichever rank's chain ran longest bounds the
    cluster) and at each level follows the child that FINISHES last.
    Every span on that path becomes an edge record ``{name, cat,
    rank, excl_us, dur_us, path}`` where ``path`` is the ancestor
    chain (``job:x > exchange:phase_b > dispatch``); edges rank by
    exclusive time."""
    spans = {}
    for rec in records:
        if rec.get("event") != "span" or rec.get("kind") == "instant":
            continue
        if rec.get("ts") is None or not rec.get("dur_us"):
            continue
        spans[_span_key(rec)] = rec
    if not spans:
        return []
    children: Dict[tuple, List[tuple]] = {}
    roots: List[tuple] = []
    for key, rec in spans.items():
        parent = rec.get("parent")
        pkey = (key[0], key[1], parent) if parent is not None else None
        if pkey is not None and pkey in spans:
            children.setdefault(pkey, []).append(key)
        else:
            roots.append(key)
    if not roots:
        return []

    def end_us(key: tuple) -> int:
        r = spans[key]
        return int(r["ts"]) + int(r["dur_us"])

    def excl_us(key: tuple) -> int:
        r = spans[key]
        kids = children.get(key, ())
        covered = sum(int(spans[c]["dur_us"]) for c in kids)
        return max(int(r["dur_us"]) - covered, 0)

    # deterministic tie-breaks: duration desc, then ts, then span id
    root = max(roots, key=lambda c: (int(spans[c]["dur_us"]),
                                     -int(spans[c]["ts"] or 0),
                                     c[2] if c[2] is not None else 0))
    path: List[tuple] = [root]
    cur = root
    while True:
        kids = children.get(cur)
        if not kids:
            break
        cur = max(kids, key=lambda c: (end_us(c),
                                       int(spans[c]["dur_us"]),
                                       c[2] if c[2] is not None else 0))
        path.append(cur)

    def label(key: tuple) -> str:
        r = spans[key]
        return f"{r.get('cat', '?')}:{r.get('name', '?')}"

    edges = []
    for i, key in enumerate(path):
        r = spans[key]
        edges.append({
            "name": str(r.get("name", "?")),
            "cat": str(r.get("cat", "?")),
            "rank": int(r.get("rank", 0) or 0),
            "dur_us": int(r.get("dur_us", 0)),
            "excl_us": excl_us(key),
            "job": r.get("job"),
            "path": " > ".join(label(p) for p in path[:i + 1]),
        })
    edges.sort(key=lambda e: -e["excl_us"])
    return edges[:k]


def render_report(report: dict) -> str:
    """Human-readable rendering of :meth:`Doctor.report` (shared by
    tools/doctor_report.py and tests)."""
    lines = ["== performance doctor =="]
    lines.append(
        f"collective wait {report.get('collective_wait_s', 0.0):.4f}s "
        f"(net {report.get('wait_net_s', 0.0):.4f}s, exchange "
        f"{report.get('wait_exchange_s', 0.0):.4f}s, io "
        f"{report.get('wait_io_s', 0.0):.4f}s, skew "
        f"{report.get('wait_skew_s', 0.0):.4f}s)")
    sr = report.get("straggler_rank")
    scores = report.get("straggler_scores") or {}
    if sr is not None:
        lines.append(f"straggler: rank {sr} "
                     f"(+{scores.get(str(sr), 0.0):.4f}s vs fastest "
                     f"peer)")
    elif scores:
        lines.append("straggler: none (peers balanced)")
    for site, w in (report.get("wait_by_site") or {}).items():
        lines.append(f"  wait {w:8.4f}s  at {site}")
    skews = report.get("skew_sites") or []
    if skews:
        lines.append("-- partition skew --")
        for st in skews:
            verdict = "HOT" if st.get("hot") else "ok"
            lines.append(
                f"  {verdict:3s} {st['ratio']:6.2f}x on worker "
                f"{st['worker']} ({st['rows']} rows peak, "
                f"{st['exchanges']} exchanges) at {st['site']}")
    cp = report.get("critical_path") or []
    if cp:
        lines.append("-- critical path (top edges by exclusive "
                     "time) --")
        for e in cp:
            lines.append(
                f"  {e['excl_us']:>10d}us excl ({e['dur_us']}us "
                f"total) rank {e['rank']}  {e['path']}")
    return "\n".join(lines) + "\n"
