"""Runtime configuration.

The reference framework is configured exclusively through environment
variables parsed at startup (reference: thrill/api/context.cpp:204-272,
1023-1093 — THRILL_NET, THRILL_RAM, THRILL_BLOCK_SIZE, THRILL_LOG, ...).
We keep the same model under the ``THRILL_TPU_`` namespace, plus
TPU-specific knobs (exchange mode, device platform).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return int(v) if v not in (None, "") else default


def _env_str(name: str, default: Optional[str]) -> Optional[str]:
    v = os.environ.get(name)
    return v if v not in (None, "") else default


def _env_flag(name: str, default: bool = True) -> bool:
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    return v not in ("0", "off", "false")


def overlap_enabled() -> bool:
    """THRILL_TPU_OVERLAP=0 restores the bulk-synchronous data plane
    exactly: single-dispatch phase-B exchanges, a host sync on every
    send-count matrix, and the serial per-peer host-frame sender.
    Master switch over the per-feature knobs (XCHG_CHUNKS,
    XCHG_CAP_CACHE, ASYNC_SEND)."""
    return _env_flag("THRILL_TPU_OVERLAP", True)


def cap_cache_enabled() -> bool:
    """THRILL_TPU_XCHG_CAP_CACHE=0 disables optimistic capacity-plan
    reuse: every exchange then syncs its [W, W] send-count matrix to
    the host before phase B, as before this knob existed."""
    return overlap_enabled() and _env_flag("THRILL_TPU_XCHG_CAP_CACHE",
                                           True)


def wire_compress_enabled() -> bool:
    """THRILL_TPU_WIRE_COMPRESS=0 restores the uncompressed wire on
    BOTH planes bit-identically: host frames ship the raw column codec
    (net/wire.py emits no compressed tags) and device exchanges ship
    rows at their declared dtypes (no phase-B narrowing). Master
    switch of the shrink-the-wire layer."""
    return _env_flag("THRILL_TPU_WIRE_COMPRESS", True)


def xchg_narrow_enabled() -> bool:
    """THRILL_TPU_XCHG_NARROW=0 disables just the device plane's
    phase-B row narrowing (data/exchange.py) while the host-frame
    codec stays on; results are bit-identical either way — narrowing
    is an exact integer cast chosen from observed ranges."""
    return wire_compress_enabled() and _env_flag(
        "THRILL_TPU_XCHG_NARROW", True)


def parse_si_iec_units(s: str) -> int:
    """Parse '100', '64K', '1Gi', '2GB' style size strings to bytes.

    Mirrors the semantics of tlx's parse_si_iec_units used by THRILL_RAM
    (reference: thrill/api/context.cpp:1027).
    """
    s = s.strip()
    mult = 1
    low = s.lower()
    for suffix, m in (
        ("kib", 1024), ("mib", 1024 ** 2), ("gib", 1024 ** 3), ("tib", 1024 ** 4),
        ("kb", 1000), ("mb", 1000 ** 2), ("gb", 1000 ** 3), ("tb", 1000 ** 4),
        ("ki", 1024), ("mi", 1024 ** 2), ("gi", 1024 ** 3), ("ti", 1024 ** 4),
        ("k", 1024), ("m", 1024 ** 2), ("g", 1024 ** 3), ("t", 1024 ** 4),
        ("b", 1),
    ):
        if low.endswith(suffix):
            mult = m
            s = s[: -len(suffix)]
            break
    return int(float(s.strip()) * mult)


def parse_kv_spec(spec: str, parse_value, what: str) -> dict:
    """Parse a "name=value,name=value" env spec, skipping malformed
    entries LOUDLY (a typo must not silently drop a tenant's weight or
    budget). ``parse_value`` converts and validates one value (raise
    ValueError to reject); shared by the service plane's weight and
    budget knobs (service/scheduler.py, service/tenancy.py)."""
    out: dict = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, _, v = entry.partition("=")
        try:
            out[name.strip()] = parse_value(v)
        except (ValueError, IndexError):
            import sys
            print(f"thrill_tpu: malformed {what} entry {entry!r} "
                  f"ignored", file=sys.stderr)
    return out


DEFAULT_COMPILE_CACHE = "~/.cache/thrill_tpu_xla"


@dataclasses.dataclass
class Config:
    """Host-level runtime configuration (one per HostContext)."""

    # Number of logical workers. 0 = one per local accelerator device.
    num_workers: int = 0
    # Preferred storage for ambiguous sources: 'device' or 'host'.
    default_storage: str = "device"
    # Exchange implementation: 'dense' (padded all_to_all; works on all
    # platforms; auto-switches to 1-factor rounds when the send matrix
    # is skewed), 'onefactor' (always W-1 ppermute rounds, each padded
    # to its own pair maximum — skew-proof), or 'ragged'
    # (lax.ragged_all_to_all; TPU-only fast path).
    exchange: str = "dense"
    # Item-capacity granularity for device block padding (power of two).
    block_items: int = 1024
    # Bytes of device memory the block pool may use (0 = autodetect).
    ram: int = 0
    # HBM budget for cached DIA node results (0 = unlimited). When the
    # budget is exceeded, cold EXECUTED node shards spill to the host
    # block store and are re-uploaded on their next pull.
    hbm_limit: int = 0
    # Host-DRAM budget for the spill block store (0 = autodetect: one
    # third of physical RAM, the reference's MemoryConfig split); past
    # this soft limit the store evicts blocks to disk.
    host_ram: int = 0
    # JSON event-log path pattern (None = disabled).
    log_path: Optional[str] = None
    # Directory for host-side spill files.
    spill_dir: str = "/tmp"
    # Enable periodic profiling.
    profile: bool = False
    # Persistent XLA compilation cache directory ("" or "0"/"off"
    # disables — env vars can't carry an empty string distinctly). On
    # the tunneled TPU a cold compile costs 20-200 s per program; the
    # on-disk cache buries repeat costs across processes and sessions.
    # The DEFAULT auto-enables off-CPU only; an explicit non-default
    # value is honored on every backend (api/context.py).
    compile_cache: str = DEFAULT_COMPILE_CACHE
    # Durable checkpoint directory (api/checkpoint.py). Empty = the
    # whole checkpoint/resume subsystem is OFF (zero overhead, zero
    # behavior change — asserted by tests/api/test_checkpoint.py).
    ckpt_dir: str = ""
    # Resume from the newest complete checkpoint epoch on startup
    # (THRILL_TPU_RESUME=1; Run()/RunDistributed(resume=True) override).
    resume: bool = False
    # Auto-checkpoint every materialized DOp stage barrier, not just
    # explicit dia.Checkpoint() calls (THRILL_TPU_CKPT_AUTO=1).
    ckpt_auto: bool = False
    # Persistent plan store directory (service/plan_store.py): learned
    # exchange capacities, narrow specs, plan kinds and pre-shuffle
    # verdicts survive process restarts — a warm restart re-runs a
    # known pipeline with zero data-driven plan builds. Any vfs scheme
    # (file://, s3://, hdfs://). Empty = off (zero overhead).
    plan_store: str = ""

    @staticmethod
    def from_env() -> "Config":
        ram = os.environ.get("THRILL_TPU_RAM")
        hbm = os.environ.get("THRILL_TPU_HBM_LIMIT")
        return Config(
            num_workers=_env_int("THRILL_TPU_WORKERS", 0),
            default_storage=_env_str("THRILL_TPU_STORAGE", "device"),
            exchange=_env_str("THRILL_TPU_EXCHANGE", "dense"),
            block_items=_env_int("THRILL_TPU_BLOCK_ITEMS", 1024),
            ram=parse_si_iec_units(ram) if ram else 0,
            hbm_limit=parse_si_iec_units(hbm) if hbm else 0,
            host_ram=parse_si_iec_units(
                os.environ.get("THRILL_TPU_HOST_RAM") or "0"),
            log_path=_env_str("THRILL_TPU_LOG", None),
            spill_dir=_env_str("THRILL_TPU_SPILL_DIR", "/tmp"),
            profile=bool(_env_int("THRILL_TPU_PROFILE", 0)),
            compile_cache=_env_str("THRILL_TPU_COMPILE_CACHE",
                                   DEFAULT_COMPILE_CACHE),
            ckpt_dir=_env_str("THRILL_TPU_CKPT_DIR", "") or "",
            resume=bool(_env_int("THRILL_TPU_RESUME", 0)),
            ckpt_auto=bool(_env_int("THRILL_TPU_CKPT_AUTO", 0)),
            plan_store=_env_str("THRILL_TPU_PLAN_STORE", "") or "",
        )


def round_up_pow2(n: int) -> int:
    """Smallest power of two >= max(n, 1)."""
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


def round_up(n: int, granularity: int) -> int:
    return ((n + granularity - 1) // granularity) * granularity
