"""The dense range split shared by every op that lays out (or
addresses) a size-``n`` dense index space across ``W`` workers.

``Generate`` (sources.py) materializes rows ``bounds[w]:bounds[w+1]``
on worker ``w``, ``ReduceToIndex`` (reduce.py) scatters into exactly
that layout, every re-laying-out op (concat, merge, groupby, sort's
host path, window, zip, read_write, ``DeviceShards.from_host``) slices
its output by the same split, and the dense-index gather join
(join.py) computes ``gidx = w*rcap + (key - bounds[w])`` assuming the
right table was laid out by exactly this split. The formula is
load-bearing across ALL of them: if one site ever switched (say to
ceil-div balancing) while the others kept this split, the dense join
would silently address garbage rows whenever the right counts are
device-resident (host-known counts are validated in
``InnerJoinNode._check_dense``). One definition keeps the coupling
explicit — do not inline the formula at new layout sites.
"""

from __future__ import annotations

import numpy as np


def dense_range_bounds(n: int, W: int) -> np.ndarray:
    """``W+1`` split points of ``range(n)`` over ``W`` workers:
    worker ``w`` owns ``[bounds[w], bounds[w+1])``."""
    return np.array([(w * n) // W for w in range(W + 1)],
                    dtype=np.int64)


def dense_range_sizes(n: int, W: int) -> np.ndarray:
    """Per-worker row counts of the dense split — ``diff`` of
    :func:`dense_range_bounds`. The elastic re-partition step
    (api/checkpoint.py) re-splits live shards by exactly this layout
    so a resized mesh addresses rows the same way a fresh ``W'``-wide
    run would (the dense join's gidx formula above depends on it)."""
    b = dense_range_bounds(n, W)
    return (b[1:] - b[:-1]).astype(np.int64)
